"""Validator client + slashing protection tests.

The headline test (VERDICT r1 item 4): a beacon node served over real TCP and
a validator client holding the keys — not harness shortcuts — keep the chain
justifying/finalizing; a double-sign attempt is refused by the EIP-3076 DB.
"""

import os

import pytest

from lighthouse_tpu.chain import BeaconChainHarness
from lighthouse_tpu.consensus.genesis import interop_secret_key
from lighthouse_tpu.http_api import BeaconNodeHttpClient, HttpApiServer
from lighthouse_tpu.validator_client import (
    NoViableBeaconNode,
    SlashingProtectionDB,
    SlashingProtectionError,
    ValidatorClient,
)

PK_A = b"\xaa" * 48
PK_B = b"\xbb" * 48
ROOT_1 = b"\x11" * 32
ROOT_2 = b"\x22" * 32


# ------------------------------------------------------ slashing DB unit


class TestSlashingProtectionDB:
    def test_block_double_propose_refused(self):
        db = SlashingProtectionDB()
        db.check_and_insert_block_proposal(PK_A, 10, ROOT_1)
        with pytest.raises(SlashingProtectionError):
            db.check_and_insert_block_proposal(PK_A, 10, ROOT_2)
        # identical re-sign is idempotent
        db.check_and_insert_block_proposal(PK_A, 10, ROOT_1)
        # lower slot refused even with fresh root
        with pytest.raises(SlashingProtectionError):
            db.check_and_insert_block_proposal(PK_A, 9, ROOT_2)
        db.check_and_insert_block_proposal(PK_A, 11, ROOT_2)
        # per-pubkey isolation
        db.check_and_insert_block_proposal(PK_B, 10, ROOT_1)

    def test_attestation_double_vote_refused(self):
        db = SlashingProtectionDB()
        db.check_and_insert_attestation(PK_A, 2, 3, ROOT_1)
        with pytest.raises(SlashingProtectionError):
            db.check_and_insert_attestation(PK_A, 2, 3, ROOT_2)
        db.check_and_insert_attestation(PK_A, 2, 3, ROOT_1)  # idempotent

    def test_attestation_surround_refused(self):
        db = SlashingProtectionDB()
        db.check_and_insert_attestation(PK_A, 3, 4, ROOT_1)
        with pytest.raises(SlashingProtectionError):  # (2,5) surrounds (3,4)
            db.check_and_insert_attestation(PK_A, 2, 5, ROOT_2)
        db2 = SlashingProtectionDB()
        db2.check_and_insert_attestation(PK_A, 2, 5, ROOT_1)
        with pytest.raises(SlashingProtectionError):  # (3,4) surrounded by (2,5)
            db2.check_and_insert_attestation(PK_A, 3, 4, ROOT_2)

    def test_attestation_monotonic_bounds(self):
        db = SlashingProtectionDB()
        db.check_and_insert_attestation(PK_A, 4, 5, ROOT_1)
        with pytest.raises(SlashingProtectionError):  # source moves backwards
            db.check_and_insert_attestation(PK_A, 3, 6, ROOT_2)
        with pytest.raises(SlashingProtectionError):  # target not increasing
            db.check_and_insert_attestation(PK_A, 4, 5, ROOT_2)
        db.check_and_insert_attestation(PK_A, 4, 6, ROOT_2)

    def test_interchange_roundtrip(self):
        gvr = b"\x42" * 32
        db = SlashingProtectionDB()
        db.check_and_insert_block_proposal(PK_A, 7, ROOT_1)
        db.check_and_insert_attestation(PK_A, 1, 2, ROOT_2)
        text = db.export_json(gvr)
        db2 = SlashingProtectionDB()
        assert db2.import_json(text, gvr) == 1
        # imported protections are enforced
        with pytest.raises(SlashingProtectionError):
            db2.check_and_insert_block_proposal(PK_A, 7, ROOT_2)
        with pytest.raises(SlashingProtectionError):
            db2.check_and_insert_attestation(PK_A, 1, 2, ROOT_1)
        # wrong chain refused
        with pytest.raises(SlashingProtectionError):
            db2.import_json(text, b"\x43" * 32)

    def test_lockbox_persistence(self, tmp_path):
        from lighthouse_tpu.store.lockbox_store import LockboxStore

        path = str(tmp_path / "slashing.db")
        store = LockboxStore(path)
        db = SlashingProtectionDB(store=store)
        db.check_and_insert_block_proposal(PK_A, 5, ROOT_1)
        db.check_and_insert_attestation(PK_A, 0, 1, ROOT_2)
        store.close()

        store2 = LockboxStore(path)
        db2 = SlashingProtectionDB(store=store2)
        with pytest.raises(SlashingProtectionError):
            db2.check_and_insert_block_proposal(PK_A, 5, ROOT_2)
        with pytest.raises(SlashingProtectionError):
            db2.check_and_insert_attestation(PK_A, 0, 1, ROOT_1)
        db2.check_and_insert_block_proposal(PK_A, 6, ROOT_2)
        store2.close()


# ----------------------------------------------------------- full VC loop


@pytest.fixture(scope="module")
def vc_setup():
    from lighthouse_tpu.crypto.bls.backends import set_backend

    set_backend("fake")
    harness = BeaconChainHarness(validator_count=16, fake_crypto=True)
    server = HttpApiServer(harness.chain).start()
    client = BeaconNodeHttpClient(server.url)
    vc = ValidatorClient(
        keys=[interop_secret_key(i) for i in range(16)],
        beacon_nodes=[client],
        spec=harness.spec,
        types=harness.types,
        genesis_validators_root=harness.chain.genesis_validators_root,
        fake_signatures=True,
    )
    yield harness, server, vc
    server.stop()
    set_backend("host")


def test_vc_keeps_chain_finalizing(vc_setup):
    """Drive 4+ epochs purely through the VC over TCP: the chain must
    justify and finalize with no harness signing at all."""
    harness, server, vc = vc_setup
    chain = harness.chain
    spec = harness.spec
    slots = spec.slots_per_epoch * 5
    proposals = 0
    attestations = 0
    for _ in range(slots):
        slot = harness.advance_slot()
        summary = vc.run_slot(slot)
        proposals += 1 if summary["proposed"] else 0
        attestations += summary["attestations"]
    assert proposals == slots, "every slot should have been proposed by the VC"
    # one attester duty per validator per epoch
    assert attestations == 16 * 5, f"expected one attestation per validator per epoch, got {attestations}"
    assert chain.finalized_checkpoint()[0] >= 2, (
        f"chain must finalize under pure-VC operation "
        f"(finalized={chain.finalized_checkpoint()[0]})"
    )


def test_vc_double_sign_refused(vc_setup):
    """A second proposal at an already-signed slot is vetoed by the DB."""
    harness, server, vc = vc_setup
    slot = harness.advance_slot()
    summary = vc.run_slot(slot)  # VC signs + publishes the slot's block
    assert summary["proposed"] is not None
    pubkey = vc.duties.proposer_at_slot(slot, harness.spec)
    # hand-build a conflicting block at the same slot and try to sign it
    parent = bytes(harness.chain.get_block(bytes.fromhex(summary["proposed"])).message.parent_root)
    state, _ = harness.chain.state_at_slot(slot, parent)
    block, _ = harness.chain.produce_block(
        slot,
        vc.store.randao_reveal(pubkey, slot // harness.spec.slots_per_epoch),
        graffiti=b"\xde\xad" * 16,  # different block => different signing root
        parent_root=parent,
        pre_state=state.copy(),
    )
    with pytest.raises(SlashingProtectionError):
        vc.store.sign_block(pubkey, block)


def test_vc_aggregates_published(vc_setup):
    """At least some slots elect one of our validators as aggregator, and the
    signed aggregate reaches the BN pool."""
    harness, server, vc = vc_setup
    total_aggregates = 0
    for _ in range(4):
        slot = harness.advance_slot()
        summary = vc.run_slot(slot)
        total_aggregates += summary["aggregates"]
    assert total_aggregates > 0, "no aggregates published over 4 slots"


def test_vc_real_crypto_slot():
    """One slot of real-BLS validator work over TCP: the produced block and
    attestations carry genuine signatures the chain's bulk verifier accepts."""
    harness = BeaconChainHarness(validator_count=16, fake_crypto=False)
    server = HttpApiServer(harness.chain).start()
    try:
        vc = ValidatorClient(
            keys=[interop_secret_key(i) for i in range(16)],
            beacon_nodes=[BeaconNodeHttpClient(server.url)],
            spec=harness.spec,
            types=harness.types,
            genesis_validators_root=harness.chain.genesis_validators_root,
            fake_signatures=False,
        )
        slot = harness.advance_slot()
        summary = vc.run_slot(slot)
        assert summary["proposed"] is not None
        assert harness.chain.head_root.hex() == summary["proposed"]
        assert summary["attestations"] >= 1
    finally:
        server.stop()


def test_vc_multi_bn_fallback(vc_setup):
    """First BN dead → second serves (beacon_node_fallback.rs semantics)."""
    harness, server, vc = vc_setup
    dead = BeaconNodeHttpClient("http://127.0.0.1:9", timeout=0.3)  # discard port
    live = BeaconNodeHttpClient(server.url)
    vc2 = ValidatorClient(
        keys=[interop_secret_key(i) for i in range(4)],
        beacon_nodes=[dead, live],
        spec=harness.spec,
        types=harness.types,
        genesis_validators_root=harness.chain.genesis_validators_root,
        fake_signatures=True,
    )
    epoch = harness.chain.current_slot() // harness.spec.slots_per_epoch
    vc2.update_duties(epoch)  # succeeds via the second BN
    assert vc2.duties.resolve_indices(), "duties must resolve through fallback"

    all_dead = ValidatorClient(
        keys=[interop_secret_key(0)],
        beacon_nodes=[dead],
        spec=harness.spec,
        types=harness.types,
        genesis_validators_root=harness.chain.genesis_validators_root,
        fake_signatures=True,
    )
    with pytest.raises(NoViableBeaconNode):
        all_dead.update_duties(epoch)


# ---------------------------------------------- sync committee + doppelganger


def test_vc_sync_committee_duties(vc_setup):
    """VERDICT r2 item 6: the VC produces sync-committee messages at +1/3 and
    signed contributions at +2/3; pooled contributions end up in the next
    block's sync aggregate."""
    harness, server, vc = vc_setup
    slot = harness.advance_slot()
    summary = vc.run_slot(slot)
    assert summary["sync_messages"] > 0, "sync duties produced no messages"
    assert summary["sync_contributions"] > 0, "no contributions published"
    # the pool now holds contributions over the head root at `slot`
    head_root = harness.chain.head_root
    pool = harness.chain.sync_contribution_pool
    assert any(k[0] == slot and k[1] == head_root for k in pool._pool), (
        "contribution pool is empty for the signed head root"
    )
    # next block picks the aggregate up from the pool
    next_slot = harness.advance_slot()
    block, _ = harness.chain.produce_block(
        next_slot, randao_reveal=harness.randao_reveal(
            harness.chain.head_state, next_slot,
            __import__("lighthouse_tpu.consensus.helpers", fromlist=["h"]).get_beacon_proposer_index(
                harness.chain.state_at_slot(next_slot)[0], harness.spec),
        ),
    )
    agg = block.body.sync_aggregate
    assert any(agg.sync_committee_bits), "block sync aggregate is empty"


def test_doppelganger_blocks_until_clean_epochs():
    """Doppelganger: no signing until 2 clean epochs; a live sighting of our
    key latches the block permanently."""
    from lighthouse_tpu.crypto.bls.backends import set_backend

    set_backend("fake")
    try:
        harness = BeaconChainHarness(validator_count=16, fake_crypto=True)
        server = HttpApiServer(harness.chain).start()
        client = BeaconNodeHttpClient(server.url)
        try:
            vc = ValidatorClient(
                keys=[interop_secret_key(i) for i in range(4)],
                beacon_nodes=[client],
                spec=harness.spec,
                types=harness.types,
                genesis_validators_root=harness.chain.genesis_validators_root,
                fake_signatures=True,
            )
            spe = harness.spec.slots_per_epoch
            start_epoch = 0
            vc.enable_doppelganger_protection(start_epoch)
            assert not vc.store.signing_enabled

            # epoch 0: nothing signed (gate down), duties still polled
            for _ in range(spe):
                slot = harness.advance_slot()
                s = vc.run_slot(slot)
                assert s["proposed"] is None and s["attestations"] == 0
            # epoch boundary 1: previous epoch (0) can't count (start epoch)
            slot = harness.advance_slot()
            vc.run_slot(slot)
            assert not vc.store.signing_enabled
            for _ in range(spe - 1):
                harness.advance_slot()
            # epoch 2 check: epoch 1 was clean -> 1 clean epoch
            slot = harness.advance_slot()
            vc.run_slot(slot)
            assert not vc.store.signing_enabled
            for _ in range(spe - 1):
                harness.advance_slot()
            # epoch 3 check: epochs 1+2 clean -> signing enabled
            slot = harness.advance_slot()
            vc.run_slot(slot)
            assert vc.store.signing_enabled
            # epoch 4: our OWN duties from epoch 3 show up as liveness — the
            # completed service must NOT re-latch the gate (review finding)
            for _ in range(spe - 1):
                slot = harness.advance_slot()
                vc.run_slot(slot)
            slot = harness.advance_slot()
            vc.run_slot(slot)
            assert vc.store.signing_enabled, "gate re-latched on own liveness"
            assert not vc.doppelganger.detected
        finally:
            server.stop()
    finally:
        set_backend("host")


def test_doppelganger_detects_live_validator():
    from lighthouse_tpu.crypto.bls.backends import set_backend
    from lighthouse_tpu.validator_client.validator_store import DoppelgangerBlocked

    set_backend("fake")
    try:
        harness = BeaconChainHarness(validator_count=16, fake_crypto=True)
        server = HttpApiServer(harness.chain).start()
        client = BeaconNodeHttpClient(server.url)
        try:
            vc = ValidatorClient(
                keys=[interop_secret_key(i) for i in range(4)],
                beacon_nodes=[client],
                spec=harness.spec,
                types=harness.types,
                genesis_validators_root=harness.chain.genesis_validators_root,
                fake_signatures=True,
            )
            spe = harness.spec.slots_per_epoch
            vc.enable_doppelganger_protection(0)
            # skip epoch 0, then "another instance" runs ALL validators
            # through epoch 1 (committees partition the epoch, so every
            # validator attests once)
            for _ in range(spe):
                harness.advance_slot()
            harness.extend_chain(spe, attest=True)
            # epoch-2 check sees epoch 1 liveness -> latched
            slot = harness.advance_slot()
            assert slot // spe == 2
            vc.run_slot(slot)
            assert vc.doppelganger.detected, "live duplicate was not detected"
            assert not vc.store.signing_enabled
            with pytest.raises(DoppelgangerBlocked):
                vc.store.randao_reveal(interop_secret_key(2).public_key().to_bytes(), 2)
        finally:
            server.stop()
    finally:
        set_backend("host")


def test_preparation_service_routes_fee_recipient(vc_setup):
    """PreparationService POSTs per-validator fee recipients each epoch and
    the produced payload pays the prepared recipient (preparation_service.rs
    -> proposer_prep_service -> payload attributes)."""
    from lighthouse_tpu.crypto.bls.backends import set_backend

    set_backend("fake")  # earlier tests in this module restore "host"
    harness, server, vc = vc_setup
    chain = harness.chain
    recipient = b"\x42" * 20
    vc.preparation.fee_recipient = recipient
    n = vc.preparation.prepare()
    assert n == 16
    assert chain.proposer_preparations  # BN recorded them
    assert all(r == recipient for r in chain.proposer_preparations.values())

    slot = harness.advance_slot()
    summary = vc.run_slot(slot)
    assert summary["proposed"] is not None
    head = chain.get_block(chain.head_root)
    assert bytes(head.message.body.execution_payload.fee_recipient) == recipient


# ------------------------------------------------- graffiti file + latency


def test_graffiti_file_precedence(tmp_path):
    """Per-validator entry > file default > VC graffiti (graffiti_file.rs)."""
    from lighthouse_tpu.validator_client.graffiti_file import (
        GraffitiFile,
        GraffitiFileError,
    )

    pk = b"\xab" * 48
    path = tmp_path / "graffiti.txt"
    path.write_text(
        "# comment\n"
        "default: team default\n"
        f"0x{pk.hex()}: my very own\n"
    )
    gf = GraffitiFile(str(path))
    assert gf.graffiti_for(pk) == b"my very own".ljust(32, b"\x00")
    assert gf.graffiti_for(b"\xcd" * 48) == b"team default".ljust(32, b"\x00")
    # live reload: edits apply without restarting anything
    path.write_text("default: changed\n")
    assert gf.graffiti_for(pk) == b"changed".ljust(32, b"\x00")
    # malformed lines are loud
    path.write_text("not a mapping\n")
    with pytest.raises(GraffitiFileError):
        gf.graffiti_for(pk)
    path.write_text("0x1234: short pubkey\n")
    with pytest.raises(GraffitiFileError):
        gf.graffiti_for(pk)
    path.write_text("default: " + "x" * 33 + "\n")
    with pytest.raises(GraffitiFileError):
        gf.graffiti_for(pk)


def test_graffiti_file_flows_into_block(vc_setup, tmp_path):
    """A produced block carries the file graffiti for the proposer."""
    from lighthouse_tpu.crypto.bls.backends import set_backend
    from lighthouse_tpu.validator_client.graffiti_file import GraffitiFile

    set_backend("fake")
    harness, server, vc = vc_setup
    chain = harness.chain
    path = tmp_path / "graffiti.txt"
    path.write_text("default: from-the-file\n")
    vc.blocks.graffiti_file = GraffitiFile(str(path))
    try:
        slot = harness.advance_slot()
        summary = vc.run_slot(slot)
        assert summary["proposed"] is not None
        head = chain.get_block(chain.head_root)
        assert bytes(head.message.body.graffiti).rstrip(b"\x00") == b"from-the-file"
    finally:
        vc.blocks.graffiti_file = None


def test_latency_measurement(vc_setup):
    """measure_latency reports an RTT per configured BN and None for dead
    endpoints (latency.rs measure_latency)."""
    from lighthouse_tpu.http_api import BeaconNodeHttpClient
    from lighthouse_tpu.validator_client.services import BeaconNodeFallback

    harness, server, vc = vc_setup
    dead = BeaconNodeHttpClient("http://127.0.0.1:1")
    dead.timeout = 0.3
    fb = BeaconNodeFallback([vc.fallback.clients[0], dead])
    out = fb.measure_latency()
    assert len(out) == 2
    assert out[0]["latency"] is not None and out[0]["latency"] < 5
    assert out[1]["latency"] is None


# ------------------------------------------------- EIP-3076 veto regression
#
# ISSUE 11 satellite: the HONEST signing path (sign_block/sign_attestation)
# must refuse every slashable message, and the explicit unsafe seam
# (sign_*_unsafe — the byzantine actor layer's signer, adversary.py) must be
# the only way around the veto, without poisoning the honest history.


class TestValidatorStoreVeto:
    @pytest.fixture()
    def store_setup(self):
        from lighthouse_tpu.crypto.bls.backends import set_backend
        from lighthouse_tpu.validator_client.validator_store import ValidatorStore

        set_backend("fake")
        harness = BeaconChainHarness(validator_count=4, fake_crypto=True)
        sk = interop_secret_key(0)
        store = ValidatorStore(
            keys=[sk],
            spec=harness.spec,
            genesis_validators_root=bytes(
                harness.chain.genesis_state.genesis_validators_root
            ),
            fake_signatures=True,
        )
        yield harness.types, store, sk.public_key().to_bytes()
        set_backend("host")

    @staticmethod
    def _att_data(types, source, target, beacon_root=b"\x01" * 32):
        return types.AttestationData(
            slot=target * 8,
            index=0,
            beacon_block_root=beacon_root,
            source=types.Checkpoint(epoch=source, root=b"\x0a" * 32),
            target=types.Checkpoint(epoch=target, root=b"\x0b" * 32),
        )

    @staticmethod
    def _header(types, slot, graffiti_byte=0):
        return types.BeaconBlockHeader(
            slot=slot,
            proposer_index=0,
            parent_root=b"\x0c" * 32,
            state_root=bytes([graffiti_byte]) * 32,
            body_root=b"\x0d" * 32,
        )

    def test_double_vote_refused_unsafe_signs(self, store_setup):
        types, store, pk = store_setup
        store.sign_attestation(pk, self._att_data(types, 2, 3, b"\xaa" * 32))
        double = self._att_data(types, 2, 3, b"\xbb" * 32)
        with pytest.raises(SlashingProtectionError):
            store.sign_attestation(pk, double)
        # the byzantine seam is the only bypass
        assert store.sign_attestation_unsafe(pk, double)

    def test_surround_refused_unsafe_signs(self, store_setup):
        types, store, pk = store_setup
        store.sign_attestation(pk, self._att_data(types, 3, 4))
        surround = self._att_data(types, 2, 5, b"\xcc" * 32)
        with pytest.raises(SlashingProtectionError):
            store.sign_attestation(pk, surround)
        assert store.sign_attestation_unsafe(pk, surround)

    def test_double_propose_refused_unsafe_signs(self, store_setup):
        types, store, pk = store_setup
        store.sign_block(pk, self._header(types, 5, 1))
        double = self._header(types, 5, 2)
        with pytest.raises(SlashingProtectionError):
            store.sign_block(pk, double)
        assert store.sign_block_unsafe(pk, double)

    def test_unsafe_does_not_poison_honest_history(self, store_setup):
        """The unsafe seam neither checks NOR records: after a byzantine
        double-sign the validator's honest future stays exactly as wide as
        the honest history allows."""
        types, store, pk = store_setup
        store.sign_attestation(pk, self._att_data(types, 2, 3, b"\xaa" * 32))
        store.sign_attestation_unsafe(pk, self._att_data(types, 0, 9, b"\xbb" * 32))
        # (0,9) was never recorded, so the honest (3,4) still signs; had the
        # unsafe sign been recorded, (3,4) would be a surrounded-by veto
        store.sign_attestation(pk, self._att_data(types, 3, 4))
        store.sign_block(pk, self._header(types, 7, 1))
        store.sign_block_unsafe(pk, self._header(types, 7, 2))
        store.sign_block(pk, self._header(types, 8, 3))
