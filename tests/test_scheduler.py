"""Scheduler tests: strict priority drain order, attestation batch
coalescing, bounded-queue drops, reprocessing delay queue (modeled on the
reference's beacon_processor unit tests + work_reprocessing_queue docs)."""

import threading
import time

import pytest

from lighthouse_tpu.scheduler import BeaconProcessor, ReprocessQueue, W, WorkEvent


@pytest.fixture()
def processor():
    p = BeaconProcessor(max_workers=1)
    yield p
    p.shutdown()


def gate_event(work_type, gate, started=None):
    def run(_):
        if started is not None:
            started.set()
        gate.wait(5.0)

    return WorkEvent(work_type=work_type, process=run)


class TestPriority:
    def test_blocks_before_attestations(self, processor):
        order = []
        gate = threading.Event()
        started = threading.Event()
        # Occupy the single worker so subsequent sends pile up in queues.
        processor.send(gate_event(W.STATUS, gate, started))
        assert started.wait(2.0)
        done = threading.Event()

        def make(wt):
            return WorkEvent(work_type=wt, process=lambda _: order.append(wt))

        # Enqueue in "wrong" order: attestation first, block last.
        processor.send(make(W.GOSSIP_ATTESTATION))
        processor.send(make(W.BACKFILL_SYNC))
        processor.send(make(W.GOSSIP_AGGREGATE))
        processor.send(make(W.GOSSIP_BLOCK))
        processor.send(
            WorkEvent(work_type=W.API_REQUEST_P1, process=lambda _: done.set())
        )
        gate.set()
        assert done.wait(5.0)
        assert order == [
            W.GOSSIP_BLOCK,
            W.GOSSIP_AGGREGATE,
            W.GOSSIP_ATTESTATION,
            W.BACKFILL_SYNC,
        ]

    def test_metrics_counted(self, processor):
        processor.send(WorkEvent(work_type=W.GOSSIP_BLOCK, process=lambda _: None))
        assert processor.wait_idle(5.0)
        assert processor.metrics.received[W.GOSSIP_BLOCK] == 1
        assert processor.metrics.processed[W.GOSSIP_BLOCK] == 1


class TestBatching:
    def test_attestations_coalesce(self, processor, monkeypatch):
        # Pin the coalescing cap to 64 for this test: the production cap is
        # the 4096-set standard device bucket (asserted in
        # test_verify_buckets), far above what a unit test should enqueue —
        # the drain logic is what's under test, not the cap value.
        from lighthouse_tpu.scheduler import work

        monkeypatch.setitem(
            work.BATCH_RULES, W.GOSSIP_ATTESTATION,
            (W.GOSSIP_ATTESTATION_BATCH, 64))
        gate = threading.Event()
        started = threading.Event()
        processor.send(gate_event(W.STATUS, gate, started))
        assert started.wait(2.0)

        batches = []
        singles = []

        def single(item):
            singles.append(item)

        def batch(items):
            batches.append(list(items))

        for i in range(70):
            processor.send(
                WorkEvent(
                    work_type=W.GOSSIP_ATTESTATION,
                    process=single,
                    process_batch=batch,
                    item=i,
                )
            )
        gate.set()
        assert processor.wait_idle(5.0)
        total = sum(len(b) for b in batches) + len(singles)
        assert total == 70
        # with the worker gated, the first drain takes a full 64-batch
        assert any(len(b) == 64 for b in batches)
        assert processor.metrics.batch_items[W.GOSSIP_ATTESTATION_BATCH] >= 64

    def test_single_event_takes_batch_path(self, processor):
        """A batchable class with exactly ONE queued event still routes
        through the batch handler (the device-pipeline seam) — the old
        ``len(q) > 1`` guard sent lone events down the per-item path, so
        they could never coalesce with anything (ISSUE 8 satellite)."""
        batches = []
        singles = []
        done = threading.Event()

        def batch(items):
            batches.append(list(items))
            done.set()

        processor.send(
            WorkEvent(
                work_type=W.GOSSIP_ATTESTATION,
                process=lambda it: singles.append(it),
                process_batch=batch,
                item="lone",
            )
        )
        assert done.wait(5.0)
        assert batches == [["lone"]]
        assert singles == []
        assert processor.metrics.batch_items[W.GOSSIP_ATTESTATION_BATCH] == 1

    def test_mixed_batch_shapeless_events_run_per_item(self, processor):
        """A drained batch mixing full-shape events (process_batch + item)
        with shapeless ones (process only, item=None — the shape the
        reprocess queue's released parks used to carry) must run BOTH: the
        shaped events through one batch call, the shapeless per-item.  The
        old code fed every ``ev.item`` to the batch handler, so one
        item=None poisoned the whole batch with an unpack TypeError that
        the worker-panic handler swallowed — silently losing every
        attestation in the batch (caught by the ISSUE 20 128-epoch soak as
        nondeterministic block content)."""
        gate = threading.Event()
        started = threading.Event()
        processor.send(gate_event(W.STATUS, gate, started))
        assert started.wait(2.0)

        batches = []
        loose = []
        for i in range(3):
            processor.send(
                WorkEvent(
                    work_type=W.GOSSIP_ATTESTATION,
                    process=lambda it: loose.append(("single", it)),
                    process_batch=lambda items: batches.append(list(items)),
                    item=i,
                )
            )
        # the shapeless event, sandwiched into the same queue
        processor.send(
            WorkEvent(
                work_type=W.GOSSIP_ATTESTATION,
                process=lambda _=None: loose.append(("shapeless", None)),
            )
        )
        gate.set()
        assert processor.wait_idle(5.0)
        assert batches == [[0, 1, 2]]
        assert loose == [("shapeless", None)]
        # nothing was dropped: every event completed through its own path
        assert processor.metrics.processed[W.GOSSIP_ATTESTATION] == 4
        assert W.GOSSIP_ATTESTATION not in processor.metrics.dropped

    def test_queue_depth_gauge_sampled(self, processor):
        """The manager mirrors queue lengths onto
        beacon_processor_queue_depth{work} (throttled sampling)."""
        from lighthouse_tpu import metrics as gm

        gate = threading.Event()
        started = threading.Event()
        processor.send(gate_event(W.STATUS, gate, started))
        assert started.wait(2.0)
        for _ in range(5):
            processor.send(
                WorkEvent(work_type=W.BACKFILL_SYNC, process=lambda _: None)
            )
        deadline = time.monotonic() + 3.0
        while time.monotonic() < deadline:
            if gm.BEACON_PROCESSOR_QUEUE_DEPTH.get(work=W.BACKFILL_SYNC) >= 5:
                break
            time.sleep(0.05)
        assert gm.BEACON_PROCESSOR_QUEUE_DEPTH.get(work=W.BACKFILL_SYNC) >= 5
        gate.set()
        assert processor.wait_idle(5.0)

    def test_worker_error_does_not_kill_processor(self, processor):
        def boom(_):
            raise RuntimeError("injected")

        processor.send(WorkEvent(work_type=W.GOSSIP_BLOCK, process=boom))
        assert processor.wait_idle(5.0)
        done = threading.Event()
        processor.send(WorkEvent(work_type=W.GOSSIP_BLOCK, process=lambda _: done.set()))
        assert done.wait(5.0)


class TestBackpressure:
    def test_full_queue_drops(self):
        p = BeaconProcessor(max_workers=1, queue_lengths={W.GOSSIP_ATTESTATION: 4})
        try:
            gate = threading.Event()
            started = threading.Event()
            p.send(gate_event(W.STATUS, gate, started))
            assert started.wait(2.0)
            accepted = sum(
                p.send(
                    WorkEvent(work_type=W.GOSSIP_ATTESTATION, process=lambda _: None)
                )
                for _ in range(10)
            )
            assert accepted == 4
            assert p.metrics.dropped[W.GOSSIP_ATTESTATION] == 6
            gate.set()
        finally:
            p.shutdown()


class TestReprocess:
    def test_delayed_event_fires(self, processor):
        rq = ReprocessQueue(processor)
        try:
            done = threading.Event()
            rq.schedule_at(
                time.monotonic() + 0.15,
                WorkEvent(work_type=W.DELAYED_IMPORT_BLOCK, process=lambda _: done.set()),
            )
            assert not done.wait(0.05)  # not yet
            assert done.wait(2.0)
        finally:
            rq.shutdown()

    def test_await_block_release(self, processor):
        rq = ReprocessQueue(processor)
        try:
            done = threading.Event()
            root = b"\xaa" * 32
            rq.await_block(
                root,
                WorkEvent(
                    work_type=W.UNKNOWN_BLOCK_ATTESTATION, process=lambda _: done.set()
                ),
            )
            assert not done.wait(0.05)
            assert rq.block_imported(root) == 1
            assert done.wait(2.0)
            assert rq.block_imported(root) == 0
        finally:
            rq.shutdown()


class TestDropDuringSync:
    """drop_during_sync enforcement (reference beacon_processor: stale gossip
    is discarded while the node is syncing, with a per-class drop metric)."""

    def test_flagged_work_dropped_while_syncing(self):
        syncing = [True]
        p = BeaconProcessor(max_workers=1, is_syncing=lambda: syncing[0])
        try:
            ran = threading.Event()
            ev = WorkEvent(
                work_type=W.GOSSIP_ATTESTATION,
                process=lambda _: ran.set(),
                drop_during_sync=True,
            )
            assert p.send(ev) is False
            assert not ran.wait(0.2)
            assert p.metrics.dropped_during_sync[W.GOSSIP_ATTESTATION] == 1
            # never even counted as received — it was discarded at ingress
            assert W.GOSSIP_ATTESTATION not in p.metrics.received

            # unflagged work (a block) still flows while syncing
            done = threading.Event()
            assert p.send(
                WorkEvent(work_type=W.GOSSIP_BLOCK, process=lambda _: done.set())
            )
            assert done.wait(5.0)

            # once synced, the same flagged work is processed again
            syncing[0] = False
            done2 = threading.Event()
            assert p.send(
                WorkEvent(
                    work_type=W.GOSSIP_ATTESTATION,
                    process=lambda _: done2.set(),
                    drop_during_sync=True,
                )
            )
            assert done2.wait(5.0)
            assert p.metrics.dropped_during_sync[W.GOSSIP_ATTESTATION] == 1
        finally:
            p.shutdown()

    def test_prometheus_counter_bumped(self):
        from lighthouse_tpu.scheduler import processor as proc_mod

        p = BeaconProcessor(max_workers=1, is_syncing=lambda: True)
        try:
            before = proc_mod.DROPPED_DURING_SYNC.get(work=W.GOSSIP_AGGREGATE)
            p.send(
                WorkEvent(
                    work_type=W.GOSSIP_AGGREGATE,
                    process=lambda _: None,
                    drop_during_sync=True,
                )
            )
            after = proc_mod.DROPPED_DURING_SYNC.get(work=W.GOSSIP_AGGREGATE)
            assert after == before + 1
        finally:
            p.shutdown()
