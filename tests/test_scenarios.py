"""Scenario soak engine (ISSUE 7): the tier-1 smoke scenario plus the full
slow-marked matrix with the determinism gate (two runs, same seed =>
identical final head roots and SOAK artifacts that agree)."""

import json
import os

import pytest

from lighthouse_tpu import blackbox, fault_injection
from lighthouse_tpu.crypto.bls.backends import set_backend
from lighthouse_tpu.scenarios import (
    SCENARIOS,
    ScenarioRunner,
    run_scenario,
    smoke_partition,
)


@pytest.fixture(autouse=True)
def _fake(tmp_path):
    set_backend("fake")
    fault_injection.reset_for_tests()
    blackbox.reset_for_tests()
    blackbox.configure(directory=str(tmp_path / "postmortems"))
    yield
    fault_injection.reset_for_tests()
    blackbox.reset_for_tests()
    set_backend("host")


def test_smoke_partition_scenario(tmp_path):
    """Tier-1 gate: the smoke scenario (partition -> fork -> heal -> reorg
    -> finality resumes) passes and writes a complete SOAK artifact."""
    artifact = run_scenario(smoke_partition(seed=0), out_dir=str(tmp_path))
    assert artifact["passed"]
    result = artifact["result"]
    assert result["converged"]
    # every live node converged to ONE head and finality advanced past the
    # fault window
    heads = {n["head_root"] for n in result["per_node"] if n["alive"]}
    assert len(heads) == 1
    assert result["final_finalized_epoch"] > result["finalized_at_window_end"]
    # the partition really forked the fleet mid-run
    assert artifact["extra"]["max_distinct_heads"] >= 2
    assert artifact["net"]["counters"]["dropped_partition"] > 0
    # slot-relative delay metrics from the tracing layer made it in
    assert artifact["delay_metrics"]["block_imported"]["count"] > 0
    assert artifact["delay_metrics"]["block_imported"]["mean_s"] is not None
    # the artifact landed on disk and round-trips as JSON
    path = os.path.join(str(tmp_path), "SOAK_smoke_partition_seed0.json")
    with open(path) as f:
        on_disk = json.load(f)
    assert on_disk["scenario"]["name"] == "smoke_partition"
    assert on_disk["passed"]
    assert "schedule_digest" in on_disk["net"]
    assert "timeline" in on_disk
    # the black box journaled the run: every timeline event landed in the
    # incident journal keyed on the fleet's VIRTUAL slot (the runner
    # installs its sim clock as the fault-injection slot provider)
    window = blackbox.JOURNAL.window(source="scenario")
    assert any(r["event"] == "run_start"
               and r.get("scenario") == "smoke_partition" for r in window)
    timeline_events = [r for r in window
                       if r.get("scenario") == "smoke_partition"
                       and r["event"] != "run_start"]
    assert timeline_events, "scenario timeline events never hit the journal"
    assert all(isinstance(r["slot"], int) for r in timeline_events), (
        "journal records in a virtual-time soak must key on the sim slot")


def test_failed_gate_still_writes_artifact(tmp_path):
    """A scenario whose gates fail must still leave its evidence on disk
    (the whole point of a soak artifact is triaging the failure)."""
    from lighthouse_tpu.scenarios import Scenario, ScenarioFailure

    # recovery far too short for finality to advance => the gate trips
    doomed = Scenario(name="doomed", seed=0, node_count=3,
                      validator_count=16, warmup_slots=2, fault_slots=1,
                      recovery_slots=1)
    with pytest.raises(ScenarioFailure):
        ScenarioRunner(doomed, out_dir=str(tmp_path)).run()
    with open(os.path.join(str(tmp_path), "SOAK_doomed_seed0.json")) as f:
        artifact = json.load(f)
    assert not artifact["passed"]
    assert "failure" in artifact
    # ISSUE 17: the gate failure froze a postmortem bundle and the SOAK
    # artifact names it — an unattended soak failure triages from one file
    bundle_path = artifact.get("postmortem_bundle")
    assert bundle_path and os.path.exists(bundle_path)
    with open(bundle_path) as f:
        bundle = json.load(f)
    assert bundle["reason"] == "scenario_gate:doomed"
    assert bundle["extra"]["failure"] == artifact["failure"]
    assert any(r["source"] == "scenario" and r["event"] == "run_start"
               and r.get("scenario") == "doomed"
               for r in bundle["journal"])


@pytest.mark.slow
@pytest.mark.parametrize("name", sorted(SCENARIOS))
def test_scenario_matrix_deterministic(name, tmp_path):
    """The full matrix, each scenario twice with one seed: both runs pass
    their gates, produce identical final head roots, AND identical merged
    fleet timelines (the ISSUE 20 acceptance criterion: seeded faults +
    virtual time => bit-for-bit reproducible chains at any horizon —
    ``long_horizon_soak`` makes this a 128-epoch byte-identity gate)."""
    results, timelines = [], []
    for run_index in range(2):
        out = tmp_path / f"run{run_index}"
        artifact = run_scenario(name, seed=7, out_dir=str(out))
        assert artifact["passed"], f"{name} run {run_index} failed its gates"
        results.append(artifact["result"])
        timelines.append(json.dumps(
            artifact.get("fleet", {}).get("timeline", []), sort_keys=True))
    assert results[0]["head_root"] == results[1]["head_root"], (
        f"{name}: nondeterministic final head"
    )
    assert (results[0]["final_finalized_epoch"]
            == results[1]["final_finalized_epoch"])
    # byte-identity on the cross-node event stream, not just the final
    # head: any thread-scheduling leak into block content or delivery
    # order shows up here first (volatile fields are already stripped by
    # the fleet merge)
    assert timelines[0] == timelines[1], (
        f"{name}: fleet timelines diverged between identically-seeded runs"
    )


def test_byzantine_smoke_slashing_pipeline(tmp_path):
    """Tier-1 byzantine gate (ISSUE 11): one double-voting validator, and
    the SOAK artifact proves the complete pipeline — offense emitted →
    slasher detection → gossiped slashing → op-pool pack → block inclusion
    → ``validators[idx].slashed`` → zero fork-choice weight — while the
    honest majority's convergence/finality gates still pass."""
    from lighthouse_tpu.scenarios import byz_double_vote_smoke

    artifact = run_scenario(byz_double_vote_smoke(seed=0), out_dir=str(tmp_path))
    assert artifact["passed"]
    # honest-majority gates held
    result = artifact["result"]
    assert result["final_finalized_epoch"] > result["finalized_at_window_end"]
    # adversarial coverage is a tracked artifact
    adv = artifact["adversary"]
    assert adv["offenses_emitted"] == 1
    assert adv["offenses_detected"] == 1
    assert adv["offenses_included"] == 1
    assert adv["veto_asserted"] == 1, "EIP-3076 veto was not asserted"
    (offense,) = adv["offenses"]
    assert offense["strategy"] == "double_vote"
    assert offense["detection_latency_slots"] <= 8
    assert offense["inclusion_latency_slots"] <= 8
    # the pipeline gate's own evidence made it into the artifact
    (conviction,) = artifact["extra"]["slashing_pipeline"]
    assert conviction["slashing_kind"] == "attester"
    assert conviction["validator"] == offense["validator"]
    # and round-trips from disk with the adversary section attached
    path = os.path.join(str(tmp_path), "SOAK_byz_double_vote_smoke_seed0.json")
    with open(path) as f:
        on_disk = json.load(f)
    assert on_disk["adversary"]["offenders"] == [offense["validator"]]
