"""StableHLO budget gate (CI guard, CPU-jax).

The ad-hoc dot-count lock this file used to carry is promoted to
``scripts/analysis/hlo_budget.py`` (ISSUE 10): committed per-(op, backend,
bucket) budgets — contraction dots, the s8-operand lock, convert/transpose
counts, and collective-op counts so sharded lowerings are auditable from
day one — with an ``--update-baseline`` churn workflow.  Tier-1 gates the
small buckets (tower/group-law primitives at the probe shape, the full
bls_verify/kzg_batch entry points at their smallest buckets, sha256/epoch
kernels); the full bucket set runs behind the ``slow`` marker.

One compiled-HLO canary stays here: budgets count the LOWERED StableHLO
(trace only), and the canary keeps the "XLA does not rematerialize the
pipeline" claim honest at the optimized-HLO level.
"""

import os
import re
import sys

import jax
import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO_ROOT, "scripts"))

from analysis import hlo_budget  # noqa: E402

from lighthouse_tpu.ops import fq, tower  # noqa: E402


def test_auditor_self_test_fires():
    """The budget auditor must prove it can still see: count a known
    program, detect the s8 lock, detect a seeded budget perturbation."""
    assert hlo_budget.self_test() == []


def test_small_tier_budgets_within_baseline():
    mismatches, measured = hlo_budget.audit("small")
    assert measured, "hlo_budget audited no targets — the gate has gone blind"
    assert not mismatches, "\n".join(mismatches)
    # The committed baseline must cover every small-tier target (no silent
    # audit shrinkage) and lock s8 operands on every int8-backend program.
    baseline = hlo_budget.load_baseline()
    sharded_seen = 0
    for key, counts in measured.items():
        assert key in baseline, f"missing committed budget for {key}"
        if "|int8|" in key:
            assert counts["s8_dot"] > 0, (
                f"{key}: int8 backend lowered with no s8-operand dots — "
                "the MXU path lost its s8 lock"
            )
        elif "|int32|" in key:
            # baseline-independent: the int32 backend must never pick up
            # s8 operands (an --update-baseline cannot silence this)
            assert counts["s8_dot"] == 0, (
                f"{key}: int32 backend lowered with s8-operand dots"
            )
        if key.endswith("|-"):
            assert counts["collective"] == 0, (
                f"{key}: unsharded lowering contains collective ops"
            )
        else:
            # The mesh keys: the bls batch-wide MSM and the kzg blob-axis
            # lincombs must complete through psums — baseline-independent
            # (an --update-baseline cannot silence a lost collective).
            sharded_seen += 1
            assert counts["collective"] > 0, (
                f"{key}: mesh-sharded lowering contains NO collective — "
                "the batch reduction is not crossing the mesh"
            )
    # the 8-device conftest mesh must actually audit the tier-1 psum lock
    # (the int8 twin + kzg mesh keys audit in the slow tier)
    assert sharded_seen >= 1, "no sharded key audited on the conftest mesh"


@pytest.mark.slow
def test_full_tier_budgets_within_baseline():
    mismatches, measured = hlo_budget.audit("all")
    assert measured
    assert not mismatches, "\n".join(mismatches)


def test_baseline_roundtrips_byte_identically():
    """--update-baseline must be churn-free: serializing the loaded
    baseline reproduces the committed bytes exactly."""
    with open(hlo_budget.BASELINE_PATH, "rb") as f:
        raw = f.read()
    assert hlo_budget.serialize_budgets(hlo_budget.load_baseline()).encode() == raw


def test_seeded_budget_mismatch_is_detected():
    got = {"dot_general": 2, "s8_dot": 0, "convert": 3, "transpose": 0,
           "collective": 0}
    want = dict(got, dot_general=4)
    assert hlo_budget.compare("op|int32|probe", want, got)
    assert hlo_budget.compare("op|int32|probe", None, got)  # missing budget
    assert not hlo_budget.compare("op|int32|probe", dict(got), got)


def test_compiled_hlo_does_not_rematerialize_fq2_mul():
    """Compiled-HLO canary: XLA keeps the fq2_mul pipeline at exactly 2
    dots (optimization could in principle duplicate the contraction; the
    lowered-text budgets would not see that)."""
    import numpy as np
    import jax.numpy as jnp

    a2 = jnp.asarray(np.ones((4, 2, 25), np.int32))
    prev = fq.set_fq_backend("int32")
    try:
        txt = jax.jit(lambda a, b: tower.fq2_mul(a, b)).lower(
            a2, a2).compile().as_text()
    finally:
        fq.set_fq_backend(prev)
    dots = len(re.findall(r"\bdot\(", txt)) + len(re.findall(r"\bdot-general\b", txt))
    assert dots == 2
