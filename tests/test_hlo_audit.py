"""HLO lowering audit for the hot-path kernels (CI guard, CPU-jax).

Locks in the contraction structure the MXU work depends on, so a refactor
cannot silently rematerialize a convolution or de-widen the fused group-law
rounds:

- every tower multiply is ONE fq_mul pipeline = 2 dot_generals (conv +
  reduction), regardless of tower level;
- the widened schedules fuse each round of independent products:
  point_add 2 pipelines (4 dots), point_double / _proj_dbl 3 (6 dots),
  _proj_add_mixed 4 (8 dots);
- under the int8 backend every pipeline's convolution dot carries s8
  operands (the MXU's native integer path).

Counts are taken on the LOWERED StableHLO (trace only — no XLA compile, so
the whole audit costs seconds); one compiled-HLO canary keeps the
"XLA does not rematerialize" claim honest.  All targets are jitted through
fresh closures: jax's trace cache keys on callable identity, and a direct
``jax.jit(module_fn)`` could replay a trace made under the other backend.
"""

import re

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from lighthouse_tpu.ops import ec, fq, pairing, tower

A2 = jnp.asarray(np.ones((4, 2, 25), np.int32))
A12 = jnp.asarray(np.ones((4, 2, 3, 2, 25), np.int32))
G1 = tuple(jnp.asarray(np.stack([c] * 4)) for c in ec.G1_GEN_LIMBS)
G2 = tuple(jnp.asarray(np.stack([c] * 4)) for c in ec.G2_GEN_LIMBS)

#: (name, fresh-closure factory, args, expected dot_general count)
TARGETS = (
    ("fq2_mul", lambda: (lambda a, b: tower.fq2_mul(a, b)), (A2, A2), 2),
    ("fq12_mul", lambda: (lambda a, b: tower.fq12_mul(a, b)), (A12, A12), 2),
    ("fq12_square", lambda: (lambda a: tower.fq12_square(a)), (A12,), 2),
    ("g1_point_add", lambda: (lambda p, q: ec.point_add(ec.G1_OPS, p, q)),
     (G1, G1), 4),
    ("g1_point_double", lambda: (lambda p: ec.point_double(ec.G1_OPS, p)),
     (G1,), 6),
    ("g2_proj_dbl", lambda: (lambda t: pairing._proj_dbl(t)), (G2,), 6),
    ("g2_proj_add_mixed", lambda: (lambda t, q: pairing._proj_add_mixed(t, q)),
     (G2, (G2[0], G2[1])), 8),
)


def _lowered_text(factory, args, backend):
    prev = fq.set_fq_backend(backend)
    try:
        return jax.jit(factory()).lower(*args).as_text()
    finally:
        fq.set_fq_backend(prev)


def _dot_lines(txt):
    """Contraction dot_generals in lowered StableHLO.  The int32 einsum
    lowers its elementwise outer product as a degenerate dot_general with
    ``contracting_dims = [] x []`` that XLA fuses into a multiply — only
    dots that actually contract count."""
    return [
        l for l in txt.splitlines()
        if "dot_general" in l and "contracting_dims = [] x []" not in l
    ]


@pytest.mark.parametrize("name,factory,args,want", TARGETS,
                         ids=[t[0] for t in TARGETS])
def test_dot_count_int32(name, factory, args, want):
    assert len(_dot_lines(_lowered_text(factory, args, "int32"))) == want


@pytest.mark.parametrize("name,factory,args,want", TARGETS,
                         ids=[t[0] for t in TARGETS])
def test_dot_count_and_s8_operands_int8(name, factory, args, want):
    lines = _dot_lines(_lowered_text(factory, args, "int8"))
    assert len(lines) == want
    # Every pipeline = one s8-operand conv dot + one s32 reduction dot.
    s8 = [l for l in lines if l.count("xi8>") >= 2]
    assert len(s8) == want // 2, f"{name}: conv dots lost their s8 operands"


def test_int32_dots_carry_no_s8_operands():
    lines = _dot_lines(_lowered_text(*TARGETS[0][1:3], backend="int32"))
    assert all(l.count("xi8>") < 2 for l in lines)


def test_compiled_hlo_does_not_rematerialize_fq2_mul():
    """Compiled-HLO canary: XLA keeps the fq2_mul pipeline at exactly 2
    dots (optimization could in principle duplicate the contraction; the
    lowered-text counts above would not see that)."""
    prev = fq.set_fq_backend("int32")
    try:
        txt = jax.jit(lambda a, b: tower.fq2_mul(a, b)).lower(A2, A2).compile().as_text()
    finally:
        fq.set_fq_backend(prev)
    dots = len(re.findall(r"\bdot\(", txt)) + len(re.findall(r"\bdot-general\b", txt))
    assert dots == 2
