"""Multi-node simulator (reference ``testing/simulator`` basic-sim): N
in-process nodes with partitioned validators keep one chain finalizing over
gossip alone, and survive a node dropping out (fallback-sim's killed-BN
liveness property)."""

import pytest

from lighthouse_tpu.crypto.bls.backends import set_backend
from lighthouse_tpu.simulator import Simulator


@pytest.fixture(autouse=True)
def _fake():
    set_backend("fake")
    yield
    set_backend("host")


def test_basic_sim_three_nodes_finalize():
    from lighthouse_tpu.logs import RING, setup_logging

    setup_logging()
    tail = RING.tail(1)
    seq_before = tail[-1]["seq"] if tail else 0
    sim = Simulator(node_count=3, validator_count=16)
    try:
        sim.run_epochs(5)
        sim.check_heads_agree()
        sim.check_finalization(min_epoch=2)

        # VERDICT r4 item 7: a multi-node run must leave structured records
        # in the log ring — block imports with fields, peer lifecycle, and
        # the finalization advance (the node must not run silent).
        records = [r for r in RING.tail(RING.capacity) if r["seq"] > seq_before]
        by_msg = {}
        for r in records:
            by_msg.setdefault(r["message"], []).append(r)
        imports = by_msg.get("block imported", [])
        assert len(imports) >= 10, "an epoch of imports must be logged"
        assert {"slot", "root", "delay_s", "import_s"} <= set(imports[0]["fields"])
        assert by_msg.get("peer connected"), "peer lifecycle must be logged"
        assert by_msg.get("finalized checkpoint advanced"), \
            "finalization must be logged"
        # every node contributed blocks (validators are partitioned)
        proposers = set()
        chain = sim.nodes[0].chain
        spe = sim.nodes[0].harness.spec.slots_per_epoch
        for slot in range(1, spe * 5):
            root = chain.block_root_at_slot(slot)
            blk = chain.get_block(root) if root else None
            if blk is not None and int(blk.message.slot) == slot:
                proposers.add(int(blk.message.proposer_index) % 3)
        assert proposers == {0, 1, 2}
    finally:
        sim.shutdown()


def test_sim_finalizes_over_secured_tcp_with_discv5():
    """The capstone topology: three nodes DISCOVER each other through a
    discv5 boot node, connect over the secured fabric (multistream ->
    noise -> yamux on real sockets), and keep one chain finalizing —
    the reference simulator's liveness property on the reference's own
    wire formats."""
    pytest.importorskip(
        "cryptography",
        reason="secured TCP + discv5 needs the `cryptography` package",
    )
    sim = Simulator(node_count=3, validator_count=16,
                    transport="tcp_secured", discovery="discv5")
    try:
        # discovery actually connected the mesh
        for n in sim.nodes:
            assert len(n.node.endpoint.connected_peers()) >= 2, (
                n.index, n.node.endpoint.connected_peers())
        sim.run_epochs(5)
        sim.check_heads_agree()
        sim.check_finalization(min_epoch=2)
    finally:
        sim.shutdown()


def test_sim_survives_node_loss():
    """fallback-sim's liveness core: with one of three nodes gone, the
    remaining 2/3 of validators keep the chain advancing and justifying."""
    sim = Simulator(node_count=3, validator_count=16)
    try:
        sim.run_epochs(2)
        lost = sim.nodes.pop()
        lost.shutdown()
        before = sim.nodes[0].chain.head_slot()
        sim.run_epochs(3)
        sim.check_heads_agree()
        assert sim.nodes[0].chain.head_slot() > before
        j_epoch, _ = sim.nodes[0].chain.justified_checkpoint()
        assert j_epoch >= 2, f"chain stopped justifying after node loss ({j_epoch})"
    finally:
        sim.shutdown()
