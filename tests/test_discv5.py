"""discv5 wire protocol (VERDICT r4 item 5; reference
``lighthouse_network/src/discovery/mod.rs`` + the discv5 crate).

Layers: keccak/secp256k1/RLP primitives against public vectors, the
EIP-778 ENR spec record, masked packet codec round trips, and two live
UDP nodes doing WHOAREYOU handshake -> PING/PONG -> FINDNODE/NODES ->
multi-node bootstrap discovery."""

import pytest

# discv5 packet crypto (AES-GCM/AES-CTR) needs the `cryptography` package,
# absent from this container (pre-existing env failure, CHANGES.md PR 7/8
# notes) — skip the whole module so tier-1 stays signal-clean.
pytest.importorskip(
    "cryptography",
    reason="discv5 packet crypto needs the `cryptography` package",
)

from lighthouse_tpu.network.discv5 import ENR, Discv5Service, KeyPair  # noqa: E402
from lighthouse_tpu.network.discv5 import packets, rlp, secp256k1, session  # noqa: E402
from lighthouse_tpu.network.discv5.enr import EnrError  # noqa: E402
from lighthouse_tpu.network.discv5.keccak import keccak256  # noqa: E402
from lighthouse_tpu.network.discv5.service import log2_distance  # noqa: E402


class TestPrimitives:
    def test_keccak256_vectors(self):
        assert keccak256(b"").hex() == (
            "c5d2460186f7233c927e7db2dcc703c0e500b653ca82273b7bfad8045d85a470")
        assert keccak256(b"abc").hex() == (
            "4e03657aea45a94fc7d47ba826c8d667c0d1e6e33a64a036ec44f58fa12d6c45")

    def test_secp256k1_sign_verify_roundtrip(self):
        kp = KeyPair(0x1234)
        h = keccak256(b"message")
        sig = secp256k1.sign(kp.priv, h)
        assert secp256k1.verify(kp.pub, h, sig)
        assert not secp256k1.verify(kp.pub, keccak256(b"other"), sig)
        # determinism (RFC 6979)
        assert sig == secp256k1.sign(kp.priv, h)
        # compress/decompress round trip
        assert secp256k1.decompress(secp256k1.compress(kp.pub)) == kp.pub

    def test_ecdh_agreement(self):
        a, b = KeyPair(7), KeyPair(11)
        assert secp256k1.ecdh(a.priv, b.pub) == secp256k1.ecdh(b.priv, a.pub)

    def test_rlp_roundtrip(self):
        items = [b"cat", [b"dog", b""], b"\x01", b"x" * 60]
        assert rlp.decode(rlp.encode(items)) == items
        assert rlp.encode(b"\x01") == b"\x01"  # single-byte literal
        with pytest.raises(rlp.RlpError):
            rlp.decode(rlp.encode(items) + b"\x00")  # trailing garbage


class TestEnr:
    # The EIP-778 specification example record.
    SPEC_TEXT = (
        "enr:-IS4QHCYrYZbAKWCBRlAy5zzaDZXJBGkcnh4MHcBFZntXNFrdvJjX04jRzjzCBOo"
        "nrkTfj499SZuOh8R33Ls8RRcy5wBgmlkgnY0gmlwhH8AAAGJc2VjcDI1NmsxoQPKY0yu"
        "DUmstAHYpMa2_oxVtw0RW_QAdpzBQA8yWM0xOIN1ZHCCdl8"
    )
    SPEC_NODE_ID = "a448f24c6d18e575453db13171562b71999873db5b286df957af199ec94617f7"
    SPEC_PRIV = 0xB71C71A67E1177AD4E901695E1B4B9EE17AE16C6668D313EAC2F96DBCDA3F291

    def test_spec_vector_decodes_and_verifies(self):
        r = ENR.from_text(self.SPEC_TEXT)
        assert r.seq == 1
        assert r.ip() == "127.0.0.1"
        assert r.udp_port() == 30303
        assert r.node_id.hex() == self.SPEC_NODE_ID
        assert r.to_text() == self.SPEC_TEXT  # byte-exact re-encode

    def test_own_signing_matches_spec_identity(self):
        kp = KeyPair(self.SPEC_PRIV)
        mine = ENR.build(kp, seq=1, ip="127.0.0.1", udp=30303)
        assert mine.node_id.hex() == self.SPEC_NODE_ID
        assert mine.verify()

    def test_tampered_record_rejected(self):
        r = ENR.from_text(self.SPEC_TEXT)
        r.pairs[b"udp"] = rlp.encode_uint(9)
        assert not r.verify()
        with pytest.raises(EnrError):
            ENR.from_rlp(r.to_rlp())


class TestPackets:
    def test_masked_header_roundtrip(self):
        dest = keccak256(b"dest-node")
        header = packets.Header(packets.FLAG_ORDINARY, b"\x01" * 12,
                                packets.ordinary_authdata(b"\x02" * 32))
        datagram = packets.encode_packet(dest, header, b"ciphertext")
        pkt = packets.decode_packet(dest, datagram)
        assert pkt.header.flag == packets.FLAG_ORDINARY
        assert pkt.header.nonce == b"\x01" * 12
        assert pkt.header.authdata == b"\x02" * 32
        assert pkt.message_ct == b"ciphertext"
        # the wrong recipient cannot even parse the header
        with pytest.raises(packets.PacketError):
            packets.decode_packet(keccak256(b"other"), datagram)

    def test_session_keys_agree(self):
        a, b = KeyPair(3), KeyPair(5)
        eph = KeyPair(9)
        challenge = b"\xaa" * 63
        ik1, rk1 = session.derive_keys(
            eph.priv, b.pub, a.node_id, b.node_id, challenge)
        ik2, rk2 = session.derive_keys_from_pubkey(
            b.priv, eph.pub, a.node_id, b.node_id, challenge)
        assert (ik1, rk1) == (ik2, rk2)
        sig = session.id_sign(a.priv, challenge, eph.compressed_pub, b.node_id)
        assert session.id_verify(a.pub, sig, challenge,
                                 eph.compressed_pub, b.node_id)
        assert not session.id_verify(a.pub, sig, challenge,
                                     eph.compressed_pub, a.node_id)


class TestLiveNodes:
    def test_handshake_ping_findnode(self):
        a = Discv5Service(KeyPair()).start()
        b = Discv5Service(KeyPair()).start()
        c_kp = KeyPair()
        c_enr = ENR.build(c_kp, seq=1, ip="127.0.0.1", udp=9)
        try:
            b.add_enr(c_enr)  # something for FINDNODE to return
            a.add_enr(b.enr)
            # first request runs the full WHOAREYOU handshake under the hood
            seq = a.ping(b.enr)
            assert seq == b.enr.seq
            assert b.node_id in a._sessions and a.node_id in b._sessions
            # second request reuses the session (no pending handshakes left)
            assert a.ping(b.enr) == b.enr.seq
            assert not a._pending and not b._challenges

            dist = log2_distance(b.node_id, c_enr.node_id)
            found = a.find_node(b.enr, [dist])
            assert any(e.node_id == c_enr.node_id for e in found)
            # distance 0 returns b's own record
            me = a.find_node(b.enr, [0])
            assert any(e.node_id == b.node_id for e in me)
        finally:
            a.stop(); b.stop()

    def test_session_recovers_after_peer_restart(self):
        """A peer that lost its session (restart) answers WHOAREYOU to our
        sessioned packet; the request must replay through a fresh handshake
        instead of timing out forever on stale keys."""
        a = Discv5Service(KeyPair()).start()
        b = Discv5Service(KeyPair()).start()
        try:
            assert a.ping(b.enr) == 1
            b._sessions.clear()  # simulate b restarting
            assert a.ping(b.enr) == 1
            # both sides ended on fresh working keys
            assert a.ping(b.enr) == 1
        finally:
            a.stop(); b.stop()

    def test_ping_without_prior_add_enr(self):
        """The public request APIs must not hide an add_enr precondition."""
        a = Discv5Service(KeyPair()).start()
        b = Discv5Service(KeyPair()).start()
        try:
            assert a.ping(b.enr) == 1  # no add_enr first
        finally:
            a.stop(); b.stop()

    def test_node_discovers_and_dials_over_fabric(self):
        """The discovery/transport split end to end: nodes advertise their
        TCP fabric port in ENRs, a newcomer learns peers via discv5
        FINDNODE sweeps against a boot node, DIALS them over TCP, and
        gossip flows (reference: discv5 finds, libp2p connects)."""
        from lighthouse_tpu.chain import BeaconChainHarness
        from lighthouse_tpu.network.node import LocalNode
        from lighthouse_tpu.network.tcp_transport import TcpEndpoint
        from lighthouse_tpu.crypto.bls.backends import set_backend
        import time

        set_backend("fake")
        boot = Discv5Service(KeyPair()).start()
        nodes = []
        try:
            for name in ("a", "b", "c"):
                h = BeaconChainHarness(validator_count=16, fake_crypto=True,
                                       genesis_time=1_600_000_000)
                n = LocalNode(peer_id=name, harness=h,
                              endpoint=TcpEndpoint(name))
                n.enable_discv5()
                nodes.append(n)
            na, nb, nc = nodes
            # a and b register with the boot node (handshake carries their
            # ENRs, incl. tcp ports)
            assert na.discv5.ping(boot.enr) == 1
            assert nb.discv5.ping(boot.enr) == 1
            assert len(boot.table) >= 2
            # the newcomer discovers and dials them over the TCP fabric
            dialed = nc.discover_peers_discv5([boot.enr], max_new=8)
            assert dialed >= 2, f"only dialed {dialed}"
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline and len(
                    nc.endpoint.connected_peers()) < 2:
                time.sleep(0.05)
            assert {"a", "b"} <= nc.endpoint.connected_peers()
            # and the fabric is live: gossip a block from a, c imports it
            na.harness.advance_slot(); nb.harness.advance_slot()
            nc.harness.advance_slot()
            blk = na.harness.produce_signed_block()
            root = na.chain.process_block(blk, block_delay_seconds=1.0)
            na.publish_block(blk)
            deadline = time.monotonic() + 15
            while time.monotonic() < deadline and nc.chain.head_root != root:
                time.sleep(0.05)
            assert nc.chain.head_root == root
        finally:
            for n in nodes:
                n.shutdown()
            boot.stop()
            set_backend("host")

    def test_bootstrap_discovers_peers(self):
        boot = Discv5Service(KeyPair()).start()
        others = [Discv5Service(KeyPair()).start() for _ in range(3)]
        newcomer = Discv5Service(KeyPair()).start()
        try:
            for o in others:
                boot.add_enr(o.enr)
            found = newcomer.bootstrap(boot.enr, rounds=32)
            # all three peers live at some distance from the boot node; the
            # newcomer must have learned at least one beyond the boot node
            assert found >= 2, f"table only reached {found}"
            assert boot.node_id in newcomer.table
        finally:
            boot.stop(); newcomer.stop()
            for o in others:
                o.stop()


def test_persisted_dht_roundtrip():
    """ENRs survive the store round-trip and a 'restarted' node seeds its
    table from them (persisted_dht.rs load/persist/clear)."""
    from lighthouse_tpu.network.discv5 import KeyPair
    from lighthouse_tpu.network.discv5.enr import ENR
    from lighthouse_tpu.network.persisted_dht import (
        clear_dht,
        load_dht,
        persist_dht,
    )
    from lighthouse_tpu.store.kv import MemoryStore

    store = MemoryStore()
    enrs = [
        ENR.build(KeyPair(), seq=i + 1, ip="10.0.0.%d" % (i + 1),
                  udp=9000 + i, tcp=9100 + i)
        for i in range(3)
    ]
    assert load_dht(store) == []
    assert persist_dht(store, enrs) == 3
    back = load_dht(store)
    assert [e.node_id for e in back] == [e.node_id for e in enrs]
    assert [e.seq for e in back] == [1, 2, 3]
    # corrupt tail: keep the records that decode cleanly
    from lighthouse_tpu.store.kv import DBColumn
    from lighthouse_tpu.network.persisted_dht import DHT_DB_KEY
    raw = store.get(DBColumn.DHT, DHT_DB_KEY)
    store.put(DBColumn.DHT, DHT_DB_KEY, raw + b"\x00\x09garbage")
    assert len(load_dht(store)) == 3
    clear_dht(store)
    assert load_dht(store) == []
