"""Child process for the two-OS-process TCP sync test: builds a chain,
serves it on a TcpEndpoint, prints its port + head root as JSON, then waits
until stdin closes."""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from lighthouse_tpu.chain import BeaconChainHarness  # noqa: E402
from lighthouse_tpu.crypto.bls.backends import set_backend  # noqa: E402
from lighthouse_tpu.network.node import LocalNode  # noqa: E402
from lighthouse_tpu.network.tcp_transport import TcpEndpoint  # noqa: E402


def main() -> int:
    genesis_time = int(sys.argv[1])
    n_blocks = int(sys.argv[2])
    set_backend("fake")
    harness = BeaconChainHarness(
        validator_count=16, fake_crypto=True, genesis_time=genesis_time
    )
    harness.extend_chain(n_blocks)
    endpoint = TcpEndpoint("server")
    node = LocalNode(peer_id="server", harness=harness, endpoint=endpoint)
    print(json.dumps({
        "port": endpoint.listen_addr[1],
        "head": harness.chain.head_root.hex(),
        "head_slot": harness.chain._blocks_slot(harness.chain.head_root),
    }), flush=True)
    sys.stdin.read()  # parent closes stdin to stop us
    node.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
