"""Incremental BeaconState Merkleization (types/tree_cache.py): cached roots
must be bit-identical to the uncached recursive computation through arbitrary
mutations, and a re-hash after one small change must touch O(log n) nodes
(VERDICT r2 item 3; reference consensus/cached_tree_hash/src/lib.rs:1-45)."""

import numpy as np
import pytest

from lighthouse_tpu.consensus.genesis import interop_genesis_state
from lighthouse_tpu.types import ssz as ssz_mod
from lighthouse_tpu.types.containers import build_types
from lighthouse_tpu.types.spec import minimal_spec


def uncached_root(state) -> bytes:
    """The plain recursive merkleization (cache bypassed)."""
    t = state.ssz_type
    return ssz_mod.merkleize(
        [ft.hash_tree_root(getattr(state, name)) for name, ft in t.field_types.items()]
    )


@pytest.fixture(scope="module")
def setup():
    spec = minimal_spec(altair_fork_epoch=0, bellatrix_fork_epoch=0,
                        capella_fork_epoch=0, deneb_fork_epoch=None)
    types = build_types(spec.preset)
    state = interop_genesis_state(32, types, spec, genesis_time=1_600_000_000)
    return spec, types, state


def test_cached_equals_uncached_fresh(setup):
    _, _, state = setup
    st = state.copy()
    assert st.hash_tree_root() == uncached_root(st)


def test_cached_tracks_mutations(setup):
    spec, types, state = setup
    st = state.copy()
    st.hash_tree_root()  # prime the cache
    # balances
    st.balances[3] += 17
    assert st.hash_tree_root() == uncached_root(st)
    # validator field mutation
    st.validators[5].slashed = True
    st.validators[5].exit_epoch = 9
    assert st.hash_tree_root() == uncached_root(st)
    # root vectors
    st.block_roots[7] = b"\x42" * 32
    st.state_roots[2] = b"\x43" * 32
    st.randao_mixes[1] = b"\x44" * 32
    assert st.hash_tree_root() == uncached_root(st)
    # participation (list of uint8)
    st.current_epoch_participation[4] = 7
    assert st.hash_tree_root() == uncached_root(st)
    # scalars / small fields
    st.slot = int(st.slot) + 1
    st.latest_block_header.state_root = b"\x55" * 32
    assert st.hash_tree_root() == uncached_root(st)
    # slashings vector
    st.slashings[0] = 123456
    assert st.hash_tree_root() == uncached_root(st)


def test_cached_tracks_appends(setup):
    spec, types, state = setup
    st = state.copy()
    st.hash_tree_root()
    v = st.validators[0].copy()
    v.pubkey = b"\x09" * 48
    st.validators.append(v)
    st.balances.append(32_000_000_000)
    st.current_epoch_participation.append(0)
    st.previous_epoch_participation.append(0)
    st.inactivity_scores.append(0)
    assert st.hash_tree_root() == uncached_root(st)


def test_copy_isolates_cache(setup):
    _, _, state = setup
    st = state.copy()
    r0 = st.hash_tree_root()
    st2 = st.copy()
    st2.balances[0] += 1
    r2 = st2.hash_tree_root()
    assert r2 != r0
    assert st.hash_tree_root() == r0, "mutating the copy must not disturb the parent"
    assert st2.hash_tree_root() == uncached_root(st2)


def test_single_balance_change_is_olog_n(setup):
    """After priming, one balance change re-hashes O(log n) nodes, not O(n)."""
    _, _, state = setup
    st = state.copy()
    st.hash_tree_root()

    calls = {"blocks": 0}
    real = ssz_mod._hash_pairs

    def counting(buf):
        calls["blocks"] += len(buf) // 64
        return real(buf)

    ssz_mod.set_hash_pairs_impl(counting)
    try:
        st.balances[1] += 1
        st.hash_tree_root()
    finally:
        ssz_mod.set_hash_pairs_impl(real)
    # Balances subtree: ~38 nodes to the 2^38-chunk limit cap; plus the
    # constant small-field recompute (header/eth1/checkpoints/payload) and
    # the container top — a constant ~110 regardless of validator count.
    # O(n) at 32 validators is ~600+ (and grows linearly).
    assert calls["blocks"] <= 150, f"{calls['blocks']} hashes for one balance change"


def test_larger_state_randomized_equivalence(setup):
    spec, types, _ = setup
    import random

    rng = random.Random(7)
    st = interop_genesis_state(64, types, spec, genesis_time=1_600_000_000)
    st.hash_tree_root()
    for round_ in range(12):
        op = rng.randrange(5)
        if op == 0:
            st.balances[rng.randrange(len(st.balances))] = rng.randrange(1 << 40)
        elif op == 1:
            v = st.validators[rng.randrange(len(st.validators))]
            v.effective_balance = rng.randrange(1 << 40)
            v.activation_epoch = rng.randrange(1 << 20)
        elif op == 2:
            st.block_roots[rng.randrange(len(st.block_roots))] = bytes(
                rng.randrange(256) for _ in range(32)
            )
        elif op == 3:
            st.inactivity_scores[rng.randrange(len(st.inactivity_scores))] = rng.randrange(100)
        else:
            st.current_epoch_participation[
                rng.randrange(len(st.current_epoch_participation))
            ] = rng.randrange(8)
        assert st.hash_tree_root() == uncached_root(st), f"divergence at round {round_}"


def test_native_hash_pairs_matches_hashlib():
    import os

    buf = os.urandom(64 * 33)
    assert ssz_mod._hash_pairs(buf) == ssz_mod._hash_pairs_hashlib(buf)


def test_composite_list_caches_track_changes(setup):
    """eth1_data_votes (identity-memo composite cache): append, reset, and
    replacement all re-root correctly."""
    spec, types, state = setup
    st = state.copy()
    st.hash_tree_root()
    st.eth1_data_votes.append(types.Eth1Data(
        deposit_root=b"\x01" * 32, deposit_count=5, block_hash=b"\x02" * 32))
    assert st.hash_tree_root() == uncached_root(st)
    st.eth1_data_votes.append(types.Eth1Data(
        deposit_root=b"\x03" * 32, deposit_count=6, block_hash=b"\x04" * 32))
    assert st.hash_tree_root() == uncached_root(st)
    st.eth1_data_votes[0] = types.Eth1Data(
        deposit_root=b"\x05" * 32, deposit_count=7, block_hash=b"\x06" * 32)
    assert st.hash_tree_root() == uncached_root(st)
    st.eth1_data_votes = []  # period reset
    assert st.hash_tree_root() == uncached_root(st)


def test_composite_list_cache_detects_in_place_mutation(setup):
    """The element memo must key on field VALUES, not object identity: an
    in-place mutation of a cached element served a stale root before r4
    (ADVICE r3 tree_cache.py:256 — a wrong state root is a consensus split)."""
    spec, types, state = setup
    st = state.copy()
    st.eth1_data_votes.append(types.Eth1Data(
        deposit_root=b"\x01" * 32, deposit_count=5, block_hash=b"\x02" * 32))
    st.hash_tree_root()  # prime the memo with the element cached
    st.eth1_data_votes[0].deposit_count = 99  # same object, new value
    assert st.hash_tree_root() == uncached_root(st)
