"""Consensus-layer basics: shuffling invariants, interop genesis, empty-slot
advancement through epoch processing on every fork (the sanity_slots tier of
the reference's test ladder, SURVEY.md §4)."""

import numpy as np
import pytest

from lighthouse_tpu.consensus import compute_shuffled_index, shuffle_list
from lighthouse_tpu.consensus import helpers as h
from lighthouse_tpu.consensus.genesis import interop_genesis_state, interop_keypair
from lighthouse_tpu.consensus.per_slot import process_slots
from lighthouse_tpu.types.containers import build_types
from lighthouse_tpu.types.spec import minimal_spec

SEED = bytes(range(32))


class TestShuffling:
    def test_list_matches_single_index(self):
        for n in (2, 7, 100, 333):
            vals = np.arange(1000, 1000 + n)
            shuffled = shuffle_list(vals, SEED, rounds=10)
            expect = [vals[compute_shuffled_index(i, n, SEED, 10)] for i in range(n)]
            assert shuffled.tolist() == expect

    def test_permutation(self):
        vals = np.arange(257)
        out = shuffle_list(vals, SEED, rounds=90)
        assert sorted(out.tolist()) == list(range(257))
        assert out.tolist() != list(range(257))  # astronomically unlikely identity

    def test_seed_sensitivity(self):
        vals = np.arange(64)
        a = shuffle_list(vals, SEED, rounds=10)
        b = shuffle_list(vals, bytes(32), rounds=10)
        assert a.tolist() != b.tolist()

    def test_single_element(self):
        assert shuffle_list(np.array([5]), SEED, 10).tolist() == [5]
        assert compute_shuffled_index(0, 1, SEED, 10) == 0


@pytest.fixture(scope="module")
def spec():
    return minimal_spec(altair_fork_epoch=0, bellatrix_fork_epoch=0, capella_fork_epoch=0,
                        deneb_fork_epoch=None, electra_fork_epoch=None)


@pytest.fixture(scope="module")
def types(spec):
    return build_types(spec.preset)


class TestInteropGenesis:
    def test_phase0_genesis(self, types):
        spec0 = minimal_spec(
            altair_fork_epoch=None, bellatrix_fork_epoch=None, capella_fork_epoch=None,
            deneb_fork_epoch=None, electra_fork_epoch=None,
        )
        state = interop_genesis_state(16, types, spec0)
        assert type(state).fork_name == "phase0"
        assert len(state.validators) == 16
        assert all(v.activation_epoch == 0 for v in state.validators)
        assert state.genesis_validators_root != bytes(32)
        # deterministic
        state2 = interop_genesis_state(16, types, spec0)
        assert state.hash_tree_root() == state2.hash_tree_root()

    def test_capella_genesis(self, types, spec):
        state = interop_genesis_state(24, types, spec)
        assert type(state).fork_name == "capella"
        assert state.fork.current_version == spec.capella_fork_version
        assert state.fork.previous_version == spec.bellatrix_fork_version
        assert len(state.current_sync_committee.pubkeys) == spec.preset.sync_committee_size
        assert len(state.inactivity_scores) == 24

    def test_keypairs_deterministic(self):
        sk, pk = interop_keypair(3)
        sk2, pk2 = interop_keypair(3)
        assert sk.to_bytes() == sk2.to_bytes() and pk == pk2
        assert interop_keypair(4)[1] != pk


class TestSlotProcessing:
    def test_advance_one_epoch_capella(self, types, spec):
        state = interop_genesis_state(24, types, spec)
        state = process_slots(state, spec.slots_per_epoch + 1, types, spec)
        assert state.slot == spec.slots_per_epoch + 1
        assert h.get_current_epoch(state, spec) == 1
        # block roots chained: every past slot has a root
        for s in range(state.slot):
            assert bytes(state.block_roots[s % spec.preset.slots_per_historical_root]) != bytes(32)

    def test_advance_through_fork_upgrade(self, types):
        spec = minimal_spec(
            altair_fork_epoch=0, bellatrix_fork_epoch=0, capella_fork_epoch=0,
            deneb_fork_epoch=2, electra_fork_epoch=None,
        )
        state = interop_genesis_state(24, types, spec)
        assert type(state).fork_name == "capella"
        state = process_slots(state, 2 * spec.slots_per_epoch, types, spec)
        assert type(state).fork_name == "deneb"
        assert state.fork.current_version == spec.deneb_fork_version
        assert state.fork.epoch == 2

    def test_effective_balance_hysteresis(self, types, spec):
        state = interop_genesis_state(16, types, spec)
        # drain a validator's balance below the downward hysteresis bound
        state.balances[0] = 31 * 10**9 - 1
        state = process_slots(state, spec.slots_per_epoch, types, spec)
        assert state.validators[0].effective_balance == 30 * 10**9

    def test_proposer_index_in_active_set(self, types, spec):
        state = interop_genesis_state(24, types, spec)
        p = h.get_beacon_proposer_index(state, spec)
        assert 0 <= p < 24


class TestCommittees:
    def test_committees_partition_active_set(self, types, spec):
        state = interop_genesis_state(24, types, spec)
        epoch = 0
        seen = []
        count = h.get_committee_count_per_slot(state, epoch, spec)
        for slot in range(spec.slots_per_epoch):
            for index in range(count):
                seen.extend(int(x) for x in h.get_beacon_committee(state, slot, index, spec))
        assert sorted(seen) == list(range(24))


def test_device_epoch_backend_matches_numpy():
    """The jnp epoch-deltas kernel (ops/epoch_device.py) must drive a full
    ``process_epoch`` to the IDENTICAL post-state as the numpy path —
    same balances, inactivity scores, and state root (VERDICT r3 item 8:
    the §2.3 intra-op-parallel epoch path, reference single_pass.rs)."""
    from lighthouse_tpu.consensus import per_epoch as pe
    from lighthouse_tpu.consensus.genesis import interop_genesis_state
    from lighthouse_tpu.consensus.per_slot import process_slots
    from lighthouse_tpu.types.containers import build_types
    from lighthouse_tpu.types.spec import minimal_spec

    spec = minimal_spec(altair_fork_epoch=0, bellatrix_fork_epoch=0,
                        capella_fork_epoch=0)
    types = build_types(spec.preset)
    state = interop_genesis_state(64, types, spec, genesis_time=1_600_000_000)
    # two epochs of slots with synthetic participation so rewards fire
    import random

    rng = random.Random(11)
    state = process_slots(state, spec.slots_per_epoch * 2 - 1, types, spec)
    state.previous_epoch_participation = [
        rng.randrange(0, 8) for _ in range(64)
    ]
    state.current_epoch_participation = [
        rng.randrange(0, 8) for _ in range(64)
    ]
    state.inactivity_scores = [rng.randrange(0, 50) for _ in range(64)]

    a = state.copy()
    b = state.copy()
    pe.process_epoch(a, types, spec)
    pe.set_epoch_backend("device")
    try:
        pe.process_epoch(b, types, spec)
    finally:
        pe.set_epoch_backend("numpy")
    assert list(a.balances) == list(b.balances)
    assert list(a.inactivity_scores) == list(b.inactivity_scores)
    assert a.hash_tree_root() == b.hash_tree_root()


def test_compare_fields_names_divergent_leaves():
    """compare_fields (reference common/compare_fields): a state mismatch
    names the exact differing fields instead of a bare root mismatch."""
    from lighthouse_tpu.chain import BeaconChainHarness
    from lighthouse_tpu.crypto.bls.backends import set_backend
    from lighthouse_tpu.types.compare_fields import (
        assert_states_equal,
        compare_fields,
    )

    set_backend("fake")
    try:
        h_ = BeaconChainHarness(validator_count=8, fake_crypto=True)
        a = h_.chain.head_state
        assert compare_fields(a, a.copy()) == []
        assert_states_equal(a, a.copy())

        b = a.copy()
        b.slot = int(a.slot) + 5
        b.balances[3] = int(a.balances[3]) - 7
        diffs = compare_fields(a, b)
        assert any(d.startswith("slot:") for d in diffs), diffs
        assert any(d.startswith("balances[3]:") for d in diffs), diffs
        try:
            assert_states_equal(a, b)
        except AssertionError as e:
            assert "slot" in str(e) and "balances[3]" in str(e)
        else:
            raise AssertionError("expected a named-field mismatch")
    finally:
        set_backend("host")
