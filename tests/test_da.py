"""Deneb data-availability pipeline tests: inclusion proofs, the DA checker
gating import, device-vs-host KZG batch agreement, and blob gossip completing
a pending block (VERDICT r1 item 6)."""

import dataclasses

import pytest

from lighthouse_tpu.chain import BeaconChainHarness
from lighthouse_tpu.chain.beacon_chain import BlockError
from lighthouse_tpu.chain.da import (
    BlobError,
    compute_blob_inclusion_proof,
    verify_blob_inclusion_proof,
)
from lighthouse_tpu.crypto.bls.backends import set_backend
from lighthouse_tpu.crypto.kzg.kzg import Kzg, TrustedSetup
from lighthouse_tpu.types.spec import MINIMAL_PRESET, minimal_spec

WIDTH = 64  # small blobs: 64 field elements = 2 KiB, fast host math
PRESET = dataclasses.replace(MINIMAL_PRESET, field_elements_per_blob=WIDTH)


def _blob(i: int) -> bytes:
    return b"".join(((i * WIDTH + j) % 251).to_bytes(32, "big") for j in range(WIDTH))


@pytest.fixture(scope="module")
def setup():
    return TrustedSetup.insecure_dev_setup(width=WIDTH)


@pytest.fixture()
def harness(setup):
    set_backend("fake")
    spec = minimal_spec(
        preset=PRESET,
        altair_fork_epoch=0, bellatrix_fork_epoch=0,
        capella_fork_epoch=0, deneb_fork_epoch=0,
    )
    hs = BeaconChainHarness(
        validator_count=16, spec=spec, fake_crypto=True, kzg=Kzg(setup)
    )
    yield hs
    set_backend("host")


def test_inclusion_proof_roundtrip(harness):
    harness.advance_slot()
    signed, sidecars = harness.produce_signed_block_with_blobs([_blob(0), _blob(1)])
    body_cls = harness.types.block_body["deneb"]
    maxc = harness.spec.preset.max_blob_commitments_per_block
    for sc in sidecars:
        assert verify_blob_inclusion_proof(sc, body_cls, maxc)
    # tampered commitment fails the proof
    bad = harness.types.BlobSidecar(
        index=sidecars[0].index,
        blob=sidecars[0].blob,
        kzg_commitment=b"\xaa" * 48,
        kzg_proof=sidecars[0].kzg_proof,
        signed_block_header=sidecars[0].signed_block_header,
        kzg_commitment_inclusion_proof=sidecars[0].kzg_commitment_inclusion_proof,
    )
    assert not verify_blob_inclusion_proof(bad, body_cls, maxc)


def test_import_gated_on_availability(harness):
    harness.advance_slot()
    signed, sidecars = harness.produce_signed_block_with_blobs([_blob(2), _blob(3)])
    chain = harness.chain
    # without blobs: import refuses and stashes the block
    with pytest.raises(BlockError, match="pending availability"):
        chain.process_block(signed)
    # with blobs: imports, stores sidecars
    root = chain.process_block_with_blobs(signed, sidecars)
    assert chain.head_root == root
    stored = chain.get_blobs(root)
    assert [int(s.index) for s in stored] == [0, 1]


def test_bad_kzg_proof_rejected(harness):
    harness.advance_slot()
    signed, sidecars = harness.produce_signed_block_with_blobs([_blob(4), _blob(5)])
    tampered = harness.types.BlobSidecar(
        index=sidecars[1].index,
        blob=sidecars[1].blob,
        kzg_commitment=sidecars[1].kzg_commitment,
        kzg_proof=sidecars[0].kzg_proof,  # wrong proof
        signed_block_header=sidecars[1].signed_block_header,
        kzg_commitment_inclusion_proof=sidecars[1].kzg_commitment_inclusion_proof,
    )
    with pytest.raises(BlockError, match="blob verification failed"):
        harness.chain.process_block_with_blobs(signed, [sidecars[0], tampered])


def test_commitment_mismatch_rejected(harness):
    """Sidecars from a different block must not satisfy availability."""
    harness.advance_slot()
    signed, sidecars = harness.produce_signed_block_with_blobs([_blob(6)])
    other = harness.chain.types.BlobSidecar(
        index=0,
        blob=_blob(7),
        kzg_commitment=harness.chain.kzg.blob_to_kzg_commitment(_blob(7)),
        kzg_proof=sidecars[0].kzg_proof,
        signed_block_header=sidecars[0].signed_block_header,
        kzg_commitment_inclusion_proof=sidecars[0].kzg_commitment_inclusion_proof,
    )
    with pytest.raises(BlockError):
        harness.chain.process_block_with_blobs(signed, [other])


def test_blob_gossip_completes_pending_block(setup):
    """Two nodes on the hub: the block arrives before its blobs; the blob
    sidecars complete availability and trigger the deferred import."""
    from lighthouse_tpu.network.node import LocalNode
    from lighthouse_tpu.network.transport import Hub

    set_backend("fake")
    try:
        spec = minimal_spec(
            preset=PRESET,
            altair_fork_epoch=0, bellatrix_fork_epoch=0,
            capella_fork_epoch=0, deneb_fork_epoch=0,
        )
        mk = lambda: BeaconChainHarness(
            validator_count=16, spec=spec, fake_crypto=True, kzg=Kzg(setup)
        )
        ha, hb = mk(), mk()
        hub = Hub()
        na = LocalNode(hub=hub, peer_id="a", harness=ha)
        nb = LocalNode(hub=hub, peer_id="b", harness=hb)
        try:
            hub.connect("a", "b")
            ha.advance_slot()
            hb.advance_slot()
            signed, sidecars = ha.produce_signed_block_with_blobs([_blob(8), _blob(9)])
            ha.chain.process_block_with_blobs(signed, sidecars)
            root = signed.message.hash_tree_root()

            import time

            def wait_until(cond, timeout=15.0):
                deadline = time.monotonic() + timeout
                while time.monotonic() < deadline:
                    if cond():
                        return True
                    time.sleep(0.05)
                return cond()

            na.publish_block(signed)  # b: pending availability
            na.wait_idle()
            nb.wait_idle()
            for sc in sidecars:
                na.publish_blob_sidecar(sc)
            # the service loop may be mid-sync-handshake; poll for the import
            assert wait_until(lambda: hb.chain.get_block(root) is not None), (
                "blobs must complete the deferred import"
            )
            # sidecar storage lands a hair after block visibility: poll
            assert wait_until(
                lambda: [int(s.index) for s in hb.chain.get_blobs(root)] == [0, 1]
            ), "imported blob block must expose its sidecars"
        finally:
            na.shutdown()
            nb.shutdown()
    finally:
        set_backend("host")


def test_forged_header_sidecar_rejected(setup):
    """A sidecar whose header carries a forged proposer signature must be
    refused before it enters the DA cache (real BLS; review finding)."""
    from lighthouse_tpu.chain.da import BlobError

    spec = minimal_spec(
        preset=PRESET,
        altair_fork_epoch=0, bellatrix_fork_epoch=0,
        capella_fork_epoch=0, deneb_fork_epoch=0,
    )
    hs = BeaconChainHarness(
        validator_count=16, spec=spec, fake_crypto=False, kzg=Kzg(setup)
    )
    hs.advance_slot()
    signed, sidecars = hs.produce_signed_block_with_blobs([_blob(10)])
    # legit sidecar verifies
    hs.chain.da_checker.put_blob(sidecars[0])
    # forge: same header content, signature swapped for a valid-but-wrong one
    other_sig = hs.keys[0].sign(b"not the header").to_bytes()
    forged = hs.types.BlobSidecar(
        index=0,
        blob=sidecars[0].blob,
        kzg_commitment=sidecars[0].kzg_commitment,
        kzg_proof=sidecars[0].kzg_proof,
        signed_block_header=hs.types.SignedBeaconBlockHeader(
            message=sidecars[0].signed_block_header.message.copy(),
            signature=other_sig,
        ),
        kzg_commitment_inclusion_proof=sidecars[0].kzg_commitment_inclusion_proof,
    )
    with pytest.raises(BlobError, match="proposer signature"):
        hs.chain.da_checker.put_blob(forged)


def test_device_kzg_batch_matches_host(setup):
    """The fused device MSM+pairing program agrees with the host golden model
    on valid and tampered batches."""
    host = Kzg(setup)
    dev = Kzg(setup, device=True)
    blobs = [_blob(i) for i in range(3)]
    comms = [host.blob_to_kzg_commitment(b) for b in blobs]
    proofs = [host.compute_blob_kzg_proof(b, c) for b, c in zip(blobs, comms)]
    assert host.verify_blob_kzg_proof_batch(blobs, comms, proofs)
    assert dev.verify_blob_kzg_proof_batch(blobs, comms, proofs)
    bad = [proofs[1], proofs[0], proofs[2]]
    assert not host.verify_blob_kzg_proof_batch(blobs, comms, bad)
    assert not dev.verify_blob_kzg_proof_batch(blobs, comms, bad)


def test_device_kzg_batch_is_supervised(setup):
    """ISSUE 10 host-sync fix: the kzg device leg runs under the device
    supervisor — a faulted dispatch resolves through the host golden model
    (correct verdicts, one fallback counter), and a tripped breaker routes
    subsequent batches straight to the host."""
    from lighthouse_tpu import device_supervisor as ds
    from lighthouse_tpu import fault_injection as fi

    fi.reset_for_tests()
    ds.reset_for_tests()
    try:
        dev = Kzg(setup, device=True)
        blobs = [_blob(i) for i in range(2)]
        comms = [dev.blob_to_kzg_commitment(b) for b in blobs]
        proofs = [dev.compute_blob_kzg_proof(b, c) for b, c in zip(blobs, comms)]

        fi.install("device.dispatch", "error", op="kzg_batch")
        # valid and tampered batches both decide CORRECTLY on the host path
        assert dev.verify_blob_kzg_proof_batch(blobs, comms, proofs)
        bad = [proofs[1], proofs[0]]
        assert not dev.verify_blob_kzg_proof_batch(blobs, comms, bad)
        # third failure trips the breaker (default threshold 3) — batches
        # now route to host without touching the device
        assert dev.verify_blob_kzg_proof_batch(blobs, comms, proofs)
        assert ds.breaker_state("kzg_batch") == "open"
        assert dev.verify_blob_kzg_proof_batch(blobs, comms, proofs)
        # device recovers once faults clear and the cooldown elapses
        fi.reset_for_tests()
        ds.SUPERVISOR.breaker("kzg_batch")._opened_at = 0.0
        assert dev.verify_blob_kzg_proof_batch(blobs, comms, proofs)
        assert ds.breaker_state("kzg_batch") in ("half_open", "closed")
    finally:
        fi.reset_for_tests()
        ds.reset_for_tests()


def test_range_sync_fetches_blobs(setup):
    """A fresh node range-syncing a chain that CONTAINS blob blocks pulls
    sidecars over BlobsByRoot and imports with availability intact
    (reference network_context.rs block+blob coupling)."""
    from lighthouse_tpu.network.node import LocalNode
    from lighthouse_tpu.network.transport import Hub

    set_backend("fake")
    try:
        spec = minimal_spec(
            preset=PRESET,
            altair_fork_epoch=0, bellatrix_fork_epoch=0,
            capella_fork_epoch=0, deneb_fork_epoch=0,
        )
        mk = lambda: BeaconChainHarness(
            validator_count=16, spec=spec, fake_crypto=True, kzg=Kzg(setup)
        )
        ha, hb = mk(), mk()
        # chain with a blob block in the middle
        ha.extend_chain(2)
        ha.advance_slot()
        signed, sidecars = ha.produce_signed_block_with_blobs([_blob(3), _blob(4)])
        ha.chain.process_block_with_blobs(signed, sidecars)
        blob_root = signed.message.hash_tree_root()
        ha.extend_chain(2)
        for _ in range(5):
            hb.advance_slot()  # same wall clock on the fresh side

        hub = Hub()
        na = LocalNode(hub=hub, peer_id="a2", harness=ha)
        nb = LocalNode(hub=hub, peer_id="b2", harness=hb)
        try:
            hub.connect("a2", "b2")  # status exchange triggers range sync
            import time

            deadline = time.monotonic() + 15
            while time.monotonic() < deadline:
                if hb.chain.head_root == ha.chain.head_root:
                    break
                time.sleep(0.1)
            assert hb.chain.head_root == ha.chain.head_root, "sync did not complete"
            assert hb.chain.get_block(blob_root) is not None
            assert [int(s.index) for s in hb.chain.get_blobs(blob_root)] == [0, 1], (
                "synced node must hold the blob sidecars it fetched"
            )
        finally:
            na.shutdown()
            nb.shutdown()
    finally:
        set_backend("host")


def test_backfill_fetches_blobs_in_retention_window(setup):
    """Checkpoint-synced node backfills a blob block: sidecars come over
    BlobsByRoot, authenticated by commitment equality against the
    hash-chain-verified block."""
    from lighthouse_tpu.chain.beacon_chain import BeaconChain, genesis_block_root_of
    from lighthouse_tpu.network.backfill import BackfillSync
    from lighthouse_tpu.network.node import LocalNode
    from lighthouse_tpu.network.transport import Hub

    set_backend("fake")
    try:
        spec = minimal_spec(
            preset=PRESET,
            altair_fork_epoch=0, bellatrix_fork_epoch=0,
            capella_fork_epoch=0, deneb_fork_epoch=0,
        )
        ha = BeaconChainHarness(
            validator_count=16, spec=spec, fake_crypto=True, kzg=Kzg(setup)
        )
        # history: 2 plain blocks, then a blob block, then 2 more
        ha.extend_chain(2)
        ha.advance_slot()
        signed, sidecars = ha.produce_signed_block_with_blobs([_blob(5)])
        ha.chain.process_block_with_blobs(signed, sidecars)
        blob_root = signed.message.hash_tree_root()
        ha.extend_chain(2)

        # checkpoint-boot a fresh node from the current head
        anchor_root = ha.chain.head_root
        anchor_block = ha.chain.get_block(anchor_root)
        anchor_state = ha.chain.get_state(anchor_root).copy()
        from lighthouse_tpu.chain.slot_clock import ManualSlotClock

        chain_b = BeaconChain(
            genesis_state=anchor_state,
            types=ha.types, spec=spec,
            slot_clock=ManualSlotClock(
                int(anchor_state.genesis_time), spec.seconds_per_slot
            ),
            kzg=Kzg(setup),
            anchor_block=anchor_block,
        )
        chain_b.slot_clock.set_slot(int(anchor_state.slot))
        hub = Hub()
        na = LocalNode(hub=hub, peer_id="bf-a", harness=ha)
        nb = LocalNode(hub=hub, peer_id="bf-b", chain=chain_b)
        try:
            hub.connect("bf-a", "bf-b")
            backfill = BackfillSync(chain=chain_b, service=nb.service)
            filled = backfill.backfill_from("bf-a")
            assert filled == 4  # blocks 1..4 behind the anchor at slot 5
            assert chain_b.db.get_block(blob_root) is not None
            got = chain_b.get_blobs(blob_root)
            assert [int(s.index) for s in got] == [0], (
                "backfill must fetch the blob sidecars in the retention window"
            )
        finally:
            na.shutdown()
            nb.shutdown()
    finally:
        set_backend("host")
