"""Optimistic transition block verification (reference
``otb_verification_service.rs``): the merge-transition block imported
optimistically is persisted, TTD-checked once the EL answers, and
invalidated in fork choice when the check fails."""

import pytest

from lighthouse_tpu.chain import BeaconChainHarness
from lighthouse_tpu.chain.beacon_chain import BeaconChain
from lighthouse_tpu.chain.mock_el import MockExecutionEngine
from lighthouse_tpu.chain.otb_verification import verify_otbs
from lighthouse_tpu.chain.slot_clock import ManualSlotClock
from lighthouse_tpu.chain.harness import interop_genesis_state
from lighthouse_tpu.crypto.bls.backends import set_backend


@pytest.fixture()
def premerge_harness():
    """A harness whose genesis predates the merge (empty payload header), so
    the first produced block IS the transition block."""
    set_backend("fake")
    h = BeaconChainHarness(validator_count=16, fake_crypto=True)
    genesis = interop_genesis_state(
        16, h.types, h.spec, genesis_time=h.chain.genesis_time
    )
    genesis.latest_execution_payload_header = type(
        genesis.latest_execution_payload_header
    )()
    h.chain = BeaconChain(
        genesis_state=genesis,
        types=h.types,
        spec=h.spec,
        slot_clock=ManualSlotClock(h.chain.genesis_time, h.spec.seconds_per_slot),
        execution_engine=MockExecutionEngine(),
    )
    yield h
    set_backend("host")


def _import_transition_block_optimistically(h):
    chain = h.chain
    slot = h.advance_slot()
    block = h.produce_signed_block(slot=slot)
    payload_hash = bytes(block.message.body.execution_payload.block_hash)
    assert any(payload_hash), "first block must carry the transition payload"
    chain.execution_engine.optimistic_hashes = {payload_hash}
    root = chain.process_block(block, block_delay_seconds=1.0)
    return root, block


def test_transition_block_registered_and_verified(premerge_harness):
    h = premerge_harness
    chain = h.chain
    root, block = _import_transition_block_optimistically(h)
    assert [r for r, _ in chain.otb_store.all()] == [root]

    engine = chain.execution_engine
    pow_parent = bytes(block.message.body.execution_payload.parent_hash)

    # An EL WITHOUT the PoW lookup capability at all: undecidable, persists
    chain.execution_engine = object()
    assert verify_otbs(chain) == 0
    assert chain.otb_store.all(), "capability-less EL must leave the OTB"
    chain.execution_engine = engine

    # EL reachable but erroring: also undecidable, record survives
    engine_get = engine.get_pow_block
    engine.get_pow_block = lambda h_: (_ for _ in ()).throw(ConnectionError())
    assert verify_otbs(chain) == 0
    assert chain.otb_store.all(), "unanswerable OTB must persist"
    engine.get_pow_block = engine_get

    # EL learns the PoW parent met TTD: record resolves, block stays viable
    engine.pow_blocks[pow_parent] = {
        "total_difficulty": chain.spec.terminal_total_difficulty,
        "parent_total_difficulty": 0,
    }
    assert verify_otbs(chain) == 1
    assert chain.otb_store.all() == []
    assert chain.head_root == root


def test_pow_parent_not_found_is_undecidable(premerge_harness):
    """A missing PoW parent retries forever (reference
    TerminalPoWBlockNotFound) — the EL may be syncing; it proves nothing."""
    h = premerge_harness
    chain = h.chain
    root, _ = _import_transition_block_optimistically(h)
    assert verify_otbs(chain) == 0
    assert chain.otb_store.all(), "not-found must keep the record"
    assert chain.head_root == root


def test_invalid_transition_block_is_invalidated(premerge_harness):
    h = premerge_harness
    chain = h.chain
    root, block = _import_transition_block_optimistically(h)
    assert chain.head_root == root

    # The PoW parent EXISTS but fails the TTD check -> provably invalid:
    # fork choice must drop the block as head.
    parent = bytes(block.message.body.execution_payload.parent_hash)
    chain.execution_engine.pow_blocks[parent] = {
        "total_difficulty": chain.spec.terminal_total_difficulty - 1,
        "parent_total_difficulty": 0,
    }
    assert verify_otbs(chain) == 1
    assert chain.otb_store.all() == []
    assert chain.head_root != root, "invalid transition block kept as head"


def test_partial_el_response_is_undecidable(premerge_harness):
    h = premerge_harness
    chain = h.chain
    root, block = _import_transition_block_optimistically(h)
    parent = bytes(block.message.body.execution_payload.parent_hash)
    chain.execution_engine.pow_blocks[parent] = {
        "total_difficulty": chain.spec.terminal_total_difficulty,
        # parent_total_difficulty missing: incomplete response
    }
    assert verify_otbs(chain) == 0
    assert chain.otb_store.all(), "partial data must not resolve the OTB"
    assert chain.head_root == root