"""Eth1 deposit follower (reference ``beacon_node/eth1`` + ``genesis``):
deposit cache proofs verify under the spec check, blocks carry required
deposits that actually activate validators, eth1-data voting follows the
period rules, and deposit-triggered genesis assembles a valid state."""

import pytest

from lighthouse_tpu.chain import BeaconChainHarness
from lighthouse_tpu.consensus import helpers as h
from lighthouse_tpu.consensus.genesis import (
    interop_secret_key,
    interop_withdrawal_credentials,
)
from lighthouse_tpu.consensus.per_block import is_valid_merkle_branch
from lighthouse_tpu.consensus.signature_sets import deposit_signature_message
from lighthouse_tpu.crypto.bls.backends import set_backend
from lighthouse_tpu.eth1 import DepositCache, Eth1GenesisService, Eth1Service
from lighthouse_tpu.types.containers import build_types
from lighthouse_tpu.types.spec import minimal_spec

DEPOSIT_DEPTH = 32


def _deposit_data(types, spec, index: int, amount=32_000_000_000):
    sk = interop_secret_key(index)
    pk = sk.public_key().to_bytes()
    data = types.DepositData(
        pubkey=pk,
        withdrawal_credentials=interop_withdrawal_credentials(pk),
        amount=amount,
    )
    root = deposit_signature_message(data, types, spec)
    data.signature = sk.sign(root).to_bytes()
    return data


class MockEth1Provider:
    """In-process provider: one eth1 block per deposit batch."""

    def __init__(self, types, spec):
        self.types = types
        self.spec = spec
        self._cache = DepositCache(types)
        self.blocks = []

    def add_deposits(self, datas, timestamp: int):
        for d in datas:
            self._cache.insert_log(len(self._cache), d)
        self.blocks.append({
            "number": len(self.blocks),
            "hash": bytes([len(self.blocks) + 1]) * 32,
            "timestamp": timestamp,
            "deposit_count": len(self._cache),
            "deposit_root": self._cache.deposit_root(),
        })

    def eth1_blocks(self):
        return list(self.blocks)

    def deposit_logs(self, start, end):
        return self._cache._deposit_data[start:end]


@pytest.fixture()
def rig():
    set_backend("host")
    harness = BeaconChainHarness(validator_count=8, fake_crypto=False)
    provider = MockEth1Provider(harness.types, harness.spec)
    service = Eth1Service(provider=provider, types=harness.types, spec=harness.spec)
    harness.chain.eth1_service = service
    yield harness, provider, service
    harness.chain.eth1_service = None
    set_backend("host")


def test_deposit_proofs_verify(rig):
    harness, provider, service = rig
    types, spec = harness.types, harness.spec
    cache = DepositCache(types)
    datas = [_deposit_data(types, spec, i) for i in range(5)]
    for d in datas:
        cache.insert_log(len(cache), d)
    root = cache.deposit_root()
    for i, dep in enumerate(cache.get_deposits(0, 5, 5)):
        assert is_valid_merkle_branch(
            dep.data.hash_tree_root(), dep.proof, DEPOSIT_DEPTH + 1, i, root
        ), f"deposit {i} proof invalid under the spec check"


def test_block_carries_deposits_and_activates_validator(rig):
    """A new on-chain deposit flows: provider -> cache -> block -> state
    (the validator registry grows)."""
    harness, provider, service = rig
    chain = harness.chain
    types, spec = harness.types, harness.spec
    n0 = len(chain.head_state.validators)

    # the provider's deposit tree mirrors the chain: the 8 genesis deposits
    # first (state.eth1_deposit_index is already past them), then a NEW 9th
    # depositor appears on eth1
    old_ts = int(chain.head_state.genesis_time) - \
        spec.seconds_per_eth1_block * spec.eth1_follow_distance - 1000
    provider.add_deposits(
        [_deposit_data(types, spec, i) for i in range(n0)], timestamp=old_ts - 10
    )
    new_deposit = _deposit_data(types, spec, 100)
    provider.add_deposits([new_deposit], timestamp=old_ts)
    service.update()

    # force the state's eth1_data to the provider's tip so the deposit
    # becomes REQUIRED (the voting path is exercised separately below)
    b = provider.blocks[-1]
    slot = harness.advance_slot()
    state, parent_root = chain.state_at_slot(slot)
    state.eth1_data = types.Eth1Data(
        deposit_root=b["deposit_root"], deposit_count=b["deposit_count"],
        block_hash=b["hash"],
    )
    deposits = service.deposits_for_block(state)
    assert len(deposits) == 1

    from lighthouse_tpu.consensus.per_block import apply_deposit

    apply_deposit(state, deposits[0], types, spec)
    assert len(state.validators) == n0 + 1
    assert bytes(state.validators[-1].pubkey) == bytes(new_deposit.pubkey)


def test_eth1_vote_prefers_majority_then_latest(rig):
    harness, provider, service = rig
    types, spec = harness.types, harness.spec
    state = harness.chain.head_state.copy()
    period_start = service._voting_period_start_time(state)
    in_window = period_start - spec.seconds_per_eth1_block * spec.eth1_follow_distance - 10
    # candidates must carry at least the state's deposit_count (8 at genesis)
    provider.add_deposits(
        [_deposit_data(types, spec, i) for i in range(8)], timestamp=in_window
    )
    provider.add_deposits([], timestamp=in_window + 1)
    service.update()

    # no ballots yet: newest in-window candidate wins
    vote = service.eth1_vote(state)
    assert bytes(vote.block_hash) == provider.blocks[-1]["hash"]

    # ballots for the OLDER candidate dominate: majority wins
    older = provider.blocks[-2]
    state.eth1_data_votes = [
        types.Eth1Data(deposit_root=older["deposit_root"],
                       deposit_count=older["deposit_count"],
                       block_hash=older["hash"])
    ] * 3
    vote = service.eth1_vote(state)
    assert bytes(vote.block_hash) == older["hash"]

    # out-of-window junk ballots are ignored
    state.eth1_data_votes = [
        types.Eth1Data(deposit_root=b"\x77" * 32, deposit_count=99,
                       block_hash=b"\x88" * 32)
    ] * 5
    vote = service.eth1_vote(state)
    assert bytes(vote.block_hash) == provider.blocks[-1]["hash"]


def test_deposit_triggered_genesis():
    set_backend("host")
    spec = minimal_spec(altair_fork_epoch=0, bellatrix_fork_epoch=0,
                        capella_fork_epoch=0, deneb_fork_epoch=None)
    spec.min_genesis_active_validator_count = 4
    spec.min_genesis_time = 1_500_000_000
    types = build_types(spec.preset)
    provider = MockEth1Provider(types, spec)
    svc = Eth1GenesisService(provider=provider, types=types, spec=spec)

    assert svc.try_genesis() is None  # no deposits yet
    provider.add_deposits(
        [_deposit_data(types, spec, i) for i in range(3)],
        timestamp=1_500_000_100,
    )
    assert svc.try_genesis() is None  # below the minimum count
    provider.add_deposits(
        [_deposit_data(types, spec, 3)], timestamp=1_500_000_200
    )
    state = svc.try_genesis()
    assert state is not None
    assert len(state.validators) == 4
    assert int(state.genesis_time) == 1_500_000_200 + spec.genesis_delay
    # deposit root in the genesis eth1_data matches the cache
    assert bytes(state.eth1_data.deposit_root) == provider._cache.deposit_root()
