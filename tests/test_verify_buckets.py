"""The 4096-set standard bucket in the production dispatch path.

Fast structural coverage for tier-1 (bucket selection, oversized-batch
chunking, scheduler coalescing aligned with the top bucket, padded uneven
verdict parity) plus the full-size 4096-bucket execution as an opt-in slow
test — on this 1-core CPU host the real 4096x32 program takes ~40 min/rep
(PERF.md big-bucket table), which no routine suite should pay.
"""

import os

import numpy as np
import pytest

from lighthouse_tpu.crypto.bls import api
from lighthouse_tpu.crypto.bls.backends import host
from lighthouse_tpu.ops import verify as v


def make_set(msg: bytes, n_keys: int = 1):
    sks = [api.SecretKey.random() for _ in range(n_keys)]
    agg = api.AggregateSignature.infinity()
    for sk in sks:
        agg.add_assign(sk.sign(msg))
    return api.SignatureSet.multiple_pubkeys(
        agg, [sk.public_key() for sk in sks], msg)


def test_bucket_selection_promotes_4096_top_bucket():
    assert v.N_BUCKETS[-1] == 4096
    assert v.MAX_SETS_PER_DISPATCH == 4096
    assert v._bucket(2049, v.N_BUCKETS) == 4096
    assert v._bucket(4096, v.N_BUCKETS) == 4096
    with pytest.raises(ValueError):
        v._bucket(4097, v.N_BUCKETS)


def test_scheduler_coalescing_matches_standard_bucket():
    """One drained scheduler batch feeds one device program: the gossip
    coalescing cap must equal the production top bucket, or the big buckets
    never fill under real traffic."""
    from lighthouse_tpu.scheduler import work

    assert work.STANDARD_DEVICE_BATCH == v.N_BUCKETS[-1]
    for _, max_batch in work.BATCH_RULES.values():
        assert max_batch == work.STANDARD_DEVICE_BATCH


def test_oversized_batch_chunks_through_top_bucket(monkeypatch):
    """Batches beyond the top bucket chunk through MAX_SETS_PER_DISPATCH-
    set dispatches (verdicts AND) instead of raising — exercised with a
    shrunk cap so the test stays at small compiled shapes."""
    monkeypatch.setattr(v, "MAX_SETS_PER_DISPATCH", 2)
    sets = [make_set(b"chunk-%d" % i) for i in range(5)]
    assert v.verify_signature_sets_device(sets, seed=b"t") is True

    sk = api.SecretKey.random()
    bad = api.SignatureSet.single_pubkey(
        sk.sign(b"other"), sk.public_key(), b"chunk-bad")
    # the bad set lands in the LAST chunk: every chunk still gets a verdict
    assert v.verify_signature_sets_device(sets + [bad], seed=b"t") is False


def test_padded_uneven_batch_matches_host_golden():
    """Uneven live count inside a bucket (3 live sets padded to the 4
    bucket, mixed key counts) — device verdict is bit-identical to the host
    golden model, for both the passing and failing batch."""
    sets = [make_set(b"pad-a"), make_set(b"pad-b", n_keys=2), make_set(b"pad-c")]
    assert v.verify_signature_sets_device(sets, seed=b"s") is True
    assert host.verify_signature_sets(sets, seed=b"s") is True

    sk = api.SecretKey.random()
    bad = api.SignatureSet.single_pubkey(
        sk.sign(b"x"), sk.public_key(), b"pad-bad")
    batch = sets[:2] + [bad]
    assert (v.verify_signature_sets_device(batch, seed=b"s")
            == host.verify_signature_sets(batch, seed=b"s") is False)


@pytest.mark.slow
@pytest.mark.skipif(
    os.environ.get("LIGHTHOUSE_TPU_RUN_HUGE_BUCKETS") != "1",
    reason="~40 min/rep on a 1-core CPU host; set "
           "LIGHTHOUSE_TPU_RUN_HUGE_BUCKETS=1 (or run on a TPU) to execute",
)
def test_4096_bucket_full_dispatch_matches_host():
    """The real thing: 3000 live sets (128 distinct, tiled — the device
    dataflow is value-independent) pad into the 4096 bucket and dispatch
    through the production supervised path; the verdict matches the host
    golden model bit-for-bit."""
    distinct = [make_set(b"scale-%d" % i) for i in range(128)]
    reps = -(-3000 // len(distinct))
    sets = (distinct * reps)[:3000]
    got = v.verify_signature_sets_device(sets, seed=b"scale")
    want = host.verify_signature_sets(sets, seed=b"scale")
    assert got is True and want is True
