"""Storage tests: native lockbox engine (persistence, crash recovery,
compaction), hot/cold split DB (freezing, restore points, replay
reconstruction), and chain-integrated finalization migration (modeled on the
reference's ``store_tests.rs``)."""

import os

import pytest

from lighthouse_tpu.chain import BeaconChainHarness
from lighthouse_tpu.crypto.bls.backends import set_backend
from lighthouse_tpu.store import DBColumn, HotColdDB, MemoryStore
from lighthouse_tpu.store.lockbox_store import LockboxStore


@pytest.fixture(autouse=True)
def _restore_backend():
    yield
    set_backend("host")


class TestLockbox:
    def test_roundtrip(self, tmp_path):
        db = LockboxStore(str(tmp_path / "db.log"))
        db.put(b"blk", b"key1", b"value1")
        db.put(b"blk", b"key2", b"v" * 100_000)  # > initial 4k read buffer
        assert db.get(b"blk", b"key1") == b"value1"
        assert db.get(b"blk", b"key2") == b"v" * 100_000
        assert db.get(b"ste", b"key1") is None  # column isolation
        db.delete(b"blk", b"key1")
        assert db.get(b"blk", b"key1") is None
        db.close()

    def test_persistence_across_reopen(self, tmp_path):
        path = str(tmp_path / "db.log")
        db = LockboxStore(path)
        for i in range(50):
            db.put(b"blk", f"k{i}".encode(), f"val{i}".encode() * 10)
        db.delete(b"blk", b"k7")
        db.close()
        db2 = LockboxStore(path)
        assert db2.get(b"blk", b"k3") == b"val3" * 10
        assert db2.get(b"blk", b"k7") is None
        db2.close()

    def test_torn_tail_recovered(self, tmp_path):
        path = str(tmp_path / "db.log")
        db = LockboxStore(path)
        db.put(b"blk", b"good", b"data")
        db.flush()
        db.close()
        with open(path, "ab") as f:  # simulate crash mid-append
            f.write(b"\x01\xff\xff")
        db2 = LockboxStore(path)
        assert db2.get(b"blk", b"good") == b"data"
        db2.put(b"blk", b"after", b"crash")
        db2.close()
        db3 = LockboxStore(path)
        assert db3.get(b"blk", b"after") == b"crash"
        db3.close()

    def test_iter_column_sorted(self, tmp_path):
        db = LockboxStore(str(tmp_path / "db.log"))
        for k in [b"c", b"a", b"b"]:
            db.put(b"blk", k, k.upper())
        db.put(b"ste", b"x", b"other-column")
        items = list(db.iter_column(b"blk"))
        assert items == [(b"a", b"A"), (b"b", b"B"), (b"c", b"C")]
        db.close()

    def test_compaction_preserves_data_and_shrinks(self, tmp_path):
        path = str(tmp_path / "db.log")
        db = LockboxStore(path)
        for i in range(100):
            db.put(b"blk", b"hot-key", f"version{i}".encode() * 50)
        db.put(b"blk", b"keep", b"kept")
        db.flush()
        before = os.path.getsize(path)
        db.compact()
        after = os.path.getsize(path)
        assert after < before / 10
        assert db.get(b"blk", b"hot-key") == b"version99" * 50
        assert db.get(b"blk", b"keep") == b"kept"
        db.close()
        db2 = LockboxStore(path)
        assert db2.get(b"blk", b"keep") == b"kept"
        db2.close()


class TestHotColdMigration:
    def test_chain_finalization_freezes_history(self):
        h = BeaconChainHarness(validator_count=16, fake_crypto=True)
        h.extend_chain(5 * 8)  # finalizes epoch 3 (slot 24)
        chain = h.chain
        assert h.finalized_epoch() >= 3
        db = chain.db
        split = db.get_split_slot()
        assert split >= 24
        # Frozen roots are queryable from the freezer
        for slot in range(1, split):
            assert db.cold_block_root_at_slot(slot) is not None
        # Restore point at slot 16 (2 epochs default spacing) exists
        state16 = db.load_cold_state_by_slot(16)
        assert state16 is not None and int(state16.slot) == 16
        # Replay reconstruction: a non-restore-point slot
        state19 = db.load_cold_state_by_slot(19)
        assert state19 is not None and int(state19.slot) == 19
        assert (
            state19.hash_tree_root()
            == db.cold_state_root_at_slot(19)
        )
        # Hot object cache pruned below the split (head-side retained)
        assert all(chain._blocks_slot(r) >= split or r == chain.fork_choice.finalized_checkpoint[1]
                   for r in chain._states)

    def test_blocks_survive_migration(self):
        h = BeaconChainHarness(validator_count=16, fake_crypto=True)
        roots = h.extend_chain(5 * 8)
        db = h.chain.db
        # All blocks (frozen or not) remain fetchable by root
        for root in roots:
            blk = db.get_block(root)
            assert blk is not None

    def test_hot_state_roundtrip(self):
        h = BeaconChainHarness(validator_count=16, fake_crypto=True)
        h.extend_chain(2)
        chain = h.chain
        state = chain.head_state
        loaded = chain.db.get_hot_state(state.hash_tree_root())
        assert loaded is not None
        assert loaded.hash_tree_root() == state.hash_tree_root()
        summary = chain.db.get_state_summary(state.hash_tree_root())
        assert summary.slot == int(state.slot)
        assert summary.latest_block_root == chain.head_root

    def test_chain_on_lockbox_store(self, tmp_path):
        """Full chain writing through the native engine."""
        store = LockboxStore(str(tmp_path / "chain.db"))
        h = BeaconChainHarness(validator_count=16, fake_crypto=True)
        h.chain.store = store
        h.chain.db = HotColdDB(hot=store, types=h.types, spec=h.spec)
        roots = h.extend_chain(8)
        assert h.chain.db.get_block(roots[-1]) is not None
        store.close()

    def test_skip_slots_migrate_correctly(self):
        """Skip slots must not corrupt frozen roots or lose restore points
        (regression: restore-point slots landing on skips made whole spans
        unloadable, and state roots for skips were the previous block's)."""
        h = BeaconChainHarness(validator_count=16, fake_crypto=True)
        # Block at every slot except 15,16,17 — slot 16 is a restore point.
        for _ in range(14):
            h.extend_chain(1)
        for _ in range(3):
            h.advance_slot()  # skip 15,16,17
        for _ in range(5 * 8 - 17):
            h.extend_chain(1)
        chain = h.chain
        assert h.finalized_epoch() >= 3
        db = chain.db
        split = db.get_split_slot()
        assert split > 17
        # Skip-slot state root equals the slot-advanced state's root.
        st16 = db.load_cold_state_by_slot(16)
        assert st16 is not None and int(st16.slot) == 16
        assert st16.hash_tree_root() == db.cold_state_root_at_slot(16)
        # Block root at the skip repeats the last block before it.
        assert db.cold_block_root_at_slot(16) == db.cold_block_root_at_slot(14)

    def test_frozen_history_survives_reopen(self, tmp_path):
        """Hot + cold both persistent: the full checkpoint/resume story."""
        hot_p, cold_p = str(tmp_path / "chain.db"), str(tmp_path / "freezer.db")
        hot, cold = LockboxStore(hot_p), LockboxStore(cold_p)
        h = BeaconChainHarness(validator_count=16, fake_crypto=True)
        h.chain.store = hot
        h.chain.db = HotColdDB(hot=hot, cold=cold, types=h.types, spec=h.spec)
        roots = h.extend_chain(5 * 8)
        split = h.chain.db.get_split_slot()
        assert split >= 24
        hot.close()
        cold.close()

        hot2, cold2 = LockboxStore(hot_p), LockboxStore(cold_p)
        db2 = HotColdDB(hot=hot2, cold=cold2, types=h.types, spec=h.spec)
        assert db2.get_split_slot() == split
        assert db2.get_block(roots[-1]) is not None
        assert db2.cold_block_root_at_slot(10) is not None
        state = db2.load_cold_state_by_slot(19)
        assert state is not None and int(state.slot) == 19
        hot2.close()
        cold2.close()
