"""Multi-device sharding tests on the 8-CPU virtual mesh (conftest.py).

Proves inside the suite what the driver's ``dryrun_multichip`` checks
externally: the fused batch-verification program compiles and runs correctly
when the signature-set batch axis is sharded over a ``jax.sharding.Mesh``
(the data-parallel analog of the reference's rayon chunking,
block_signature_verifier.rs:396-404), with XLA inserting the cross-device
collectives for the G2 tree-sum and Miller-product reductions.
"""

import functools
import os
import subprocess
import sys

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

N_DEVICES = 8
N_SETS = 16


@functools.lru_cache(maxsize=None)
def _sharded_fn():
    from lighthouse_tpu.ops.verify import _device_verify

    devices = jax.devices()
    assert len(devices) >= N_DEVICES, "conftest must provision 8 virtual CPU devices"
    mesh = Mesh(np.array(devices[:N_DEVICES]), ("dp",))
    dp = NamedSharding(mesh, P("dp"))
    repl = NamedSharding(mesh, P())
    fn = jax.jit(_device_verify.__wrapped__, out_shardings=(repl, repl))
    return fn, dp


def _shard_args(batch, dp):
    pk, sig, msg, wbits, live = batch
    shard = lambda x: jax.device_put(x, dp)
    return (
        tuple(shard(c) for c in pk),
        tuple(shard(c) for c in sig),
        tuple(shard(c) for c in msg),
        shard(wbits),
        shard(live),
    )


def test_sharded_verify_on_mesh():
    from __graft_entry__ import _build_example
    from lighthouse_tpu.ops.pairing import fe_is_one

    fn, dp = _sharded_fn()
    batch = _build_example(n_sets=N_SETS, n_keys=2)
    fe, w_z = fn(*_shard_args(batch, dp))
    jax.block_until_ready((fe, w_z))
    assert fe_is_one(fe)


def test_sharded_verify_rejects_bad_signature():
    """Sharded path must reject a corrupted batch (same shape → same program)."""
    from __graft_entry__ import _build_example
    from lighthouse_tpu.ops.pairing import fe_is_one

    fn, dp = _sharded_fn()
    pk, sig, msg, wbits, live = _build_example(n_sets=N_SETS, n_keys=2)
    # Corrupt the hash points: swap x and y limb blocks.
    batch = (pk, sig, (msg[1], msg[0]), wbits, live)
    fe, _ = fn(*_shard_args(batch, dp))
    assert not fe_is_one(fe)


def test_dryrun_multichip_subprocess():
    """The driver-facing entry point must succeed from an arbitrary parent env.

    Simulates the round-1 failure mode: dryrun_multichip must pass regardless
    of the parent's JAX platform config, because it re-execs a CPU-forced
    child with the device count fixed before interpreter start.
    """
    code = (
        "import os, sys; sys.path.insert(0, %r); "
        "import __graft_entry__ as g; g.dryrun_multichip(4); print('PARENT-OK')" % REPO
    )
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"  # parent needs a working jax only for import
    env.setdefault("JAX_COMPILATION_CACHE_DIR", os.path.join(REPO, ".jax_cache"))
    proc = subprocess.run(
        [sys.executable, "-c", code],
        env=env,
        cwd=REPO,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        timeout=600,
    )
    out = proc.stdout.decode(errors="replace")
    assert proc.returncode == 0, out
    assert "PARENT-OK" in out
