"""Multi-device sharding tests on the 8-CPU virtual mesh (conftest.py).

Proves inside the suite what the driver's ``dryrun_multichip`` checks
externally: the fused batch-verification program compiles and runs correctly
when the signature-set batch axis is sharded over a ``jax.sharding.Mesh``
(the data-parallel analog of the reference's rayon chunking,
block_signature_verifier.rs:396-404), with XLA inserting the cross-device
collectives for the G2 tree-sum and Miller-product reductions.
"""

import functools
import os
import subprocess
import sys

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

N_DEVICES = 8
N_SETS = 16


@functools.lru_cache(maxsize=None)
def _sharded_fn():
    from lighthouse_tpu.ops.verify import _device_verify

    devices = jax.devices()
    assert len(devices) >= N_DEVICES, "conftest must provision 8 virtual CPU devices"
    mesh = Mesh(np.array(devices[:N_DEVICES]), ("dp",))
    dp = NamedSharding(mesh, P("dp"))
    repl = NamedSharding(mesh, P())
    fn = jax.jit(_device_verify.__wrapped__, out_shardings=(repl, repl))
    return fn, dp


def _shard_args(batch, dp):
    pk, sig, msg, wbits, live = batch
    shard = lambda x: jax.device_put(x, dp)
    return (
        tuple(shard(c) for c in pk),
        tuple(shard(c) for c in sig),
        tuple(shard(c) for c in msg),
        shard(wbits),
        shard(live),
    )


def test_sharded_verify_on_mesh():
    from __graft_entry__ import _build_example
    from lighthouse_tpu.ops.pairing import fe_is_one

    fn, dp = _sharded_fn()
    batch = _build_example(n_sets=N_SETS, n_keys=2)
    fe, w_z = fn(*_shard_args(batch, dp))
    jax.block_until_ready((fe, w_z))
    assert fe_is_one(fe)


def test_sharded_verify_rejects_bad_signature():
    """Sharded path must reject a corrupted batch (same shape → same program)."""
    from __graft_entry__ import _build_example
    from lighthouse_tpu.ops.pairing import fe_is_one

    fn, dp = _sharded_fn()
    pk, sig, msg, wbits, live = _build_example(n_sets=N_SETS, n_keys=2)
    # Corrupt the hash points: swap x and y limb blocks.
    batch = (pk, sig, (msg[1], msg[0]), wbits, live)
    fe, _ = fn(*_shard_args(batch, dp))
    assert not fe_is_one(fe)


def test_sharded_128_sets_bit_parity_vs_unsharded():
    """VERDICT r4 item 8: the headline 128-set batch on the 8-device mesh.

    Per-device sharding is ASSERTED on the inputs (8 addressable shards on
    the batch axis), and the mesh program's FE output limbs must be
    BIT-IDENTICAL to the single-device program on the same arrays — the
    cross-device collective structure (G2 tree-sum, Miller line-product
    reductions) must not perturb a single limb."""
    from __graft_entry__ import _build_example
    from lighthouse_tpu.ops.pairing import fe_is_one
    from lighthouse_tpu.ops.verify import _device_verify

    fn, dp = _sharded_fn()
    batch = _build_example(n_sets=128, n_keys=4, seed=21)
    sharded_args = _shard_args(batch, dp)
    # sharding asserted: the batch axis is split across all 8 devices
    pk0 = sharded_args[0][0]
    assert len(pk0.sharding.device_set) == N_DEVICES
    shard_rows = sorted(s.data.shape[0] for s in pk0.addressable_shards)
    assert shard_rows == [16] * N_DEVICES, shard_rows

    fe_mesh, wz_mesh = fn(*sharded_args)
    jax.block_until_ready((fe_mesh, wz_mesh))
    assert fe_is_one(fe_mesh)

    fe_one, wz_one = _device_verify(*batch)
    jax.block_until_ready((fe_one, wz_one))
    assert np.array_equal(np.asarray(fe_mesh), np.asarray(fe_one)), (
        "mesh FE limbs diverge from the single-device program")
    assert np.array_equal(np.asarray(wz_mesh), np.asarray(wz_one))


def test_sharded_uneven_live_batch_100_over_8():
    """An UNEVEN 100-set batch over 8 devices.

    XLA rejects non-divisible jit input shardings by design (static
    shapes), so raw 100-over-8 sharding is impossible; the framework's
    uneven-batch mechanism is the BUCKET layer: ``build_batch(100 sets)``
    pads to the 128 bucket with identity points + dead ``live`` rows.  This
    test proves that path end to end on the mesh: the padded batch shards
    16 rows/device (the last two devices holding mostly padding), the
    padding flows through every cross-device collective as exact neutral
    elements, the result verifies, and the FE limbs are bit-identical to
    the single-device program."""
    from __graft_entry__ import _build_example
    from lighthouse_tpu.ops.pairing import fe_is_one
    from lighthouse_tpu.ops.verify import _device_verify

    fn, dp = _sharded_fn()
    batch = _build_example(n_sets=100, n_keys=2, seed=33)
    live = np.asarray(batch[4])
    assert live.shape[0] == 128 and live.sum() == 100  # bucket-padded
    sharded_args = _shard_args(batch, dp)
    pk0 = sharded_args[0][0]
    assert len(pk0.sharding.device_set) == N_DEVICES
    shard_rows = [s.data.shape[0] for s in pk0.addressable_shards]
    assert shard_rows == [16] * N_DEVICES, shard_rows

    fe_mesh, wz_mesh = fn(*sharded_args)
    jax.block_until_ready((fe_mesh, wz_mesh))
    assert fe_is_one(fe_mesh)

    fe_one, _ = _device_verify(*batch)
    jax.block_until_ready(fe_one)
    assert np.array_equal(np.asarray(fe_mesh), np.asarray(fe_one))

    # and a corrupted LIVE row still fails while dead rows stay inert
    pk, sig, msg, wbits, live_arr = batch
    bad = (pk, sig, (msg[1], msg[0]), wbits, live_arr)
    fe_bad, _ = fn(*_shard_args(bad, dp))
    assert not fe_is_one(fe_bad)


def test_sharded_bit_parity_vs_host_golden():
    """The mesh program's FE equals the HOST golden model's final
    exponentiation value exactly (not just is_one agreement): the full
    limb-decode of the mesh output is compared against the host-integer
    pairing product for the same sets and weights."""
    from __graft_entry__ import _build_example
    from lighthouse_tpu.crypto.bls import host_projective as hpp
    from lighthouse_tpu.crypto.bls.backends.host import _rand_scalars
    from lighthouse_tpu.crypto.bls.hash_to_curve import hash_to_g2
    from lighthouse_tpu.crypto.bls.pairing import final_exponentiation
    from lighthouse_tpu.crypto.bls.params import DST
    from lighthouse_tpu.crypto.bls import api, curve
    from lighthouse_tpu.ops import tower
    import random as _random

    fn, dp = _sharded_fn()
    n_sets, n_keys = N_SETS, 2

    # Rebuild the same sets _build_example makes, to drive the host model.
    rng = _random.Random(7)
    from lighthouse_tpu.crypto.bls.params import R
    sks = [api.SecretKey(rng.randrange(1, R)) for _ in range(n_keys)]
    pks = [sk.public_key() for sk in sks]
    agg_sk = api.SecretKey(sum(sk.scalar for sk in sks) % R)
    sets = []
    for i in range(n_sets):
        msg = (i.to_bytes(2, "big") + bytes([7])) * 10 + b"\x00\x00"
        sets.append(api.SignatureSet.multiple_pubkeys(agg_sk.sign(msg), pks, msg))
    rands = _rand_scalars(len(sets), seed=b"graft-entry")

    from lighthouse_tpu.ops.verify import build_batch
    batch = build_batch(sets, rands)
    fe_mesh, _ = fn(*_shard_args(batch, dp))
    jax.block_until_ready(fe_mesh)

    # Host golden: f = prod_i miller([r_i]aggpk_i, H(m_i)) * miller(-g1, W)
    f = None
    w = None
    for s, r in zip(sets, rands):
        h = hash_to_g2(s.message, DST)
        aggpk = None
        for key in s.signing_keys:
            aggpk = curve.add(aggpk, key.point)
        p = curve.mul(aggpk, r)
        fi = hpp.miller_loop_projective(p, h)
        f = fi if f is None else f * fi
        w = curve.add(w, curve.mul(s.signature.point, r))
    neg_g1 = (curve.G1[0], -curve.G1[1])
    f = f * hpp.miller_loop_projective(neg_g1, w)
    expected = final_exponentiation(f)
    assert tower.fq12_from_limbs(np.asarray(fe_mesh)) == expected, (
        "mesh FE value diverges from the host golden model")


# ------------------------------------------------- the SUPERVISED mesh path
#
# Everything above drives the raw jitted program by hand.  These tests run
# the PRODUCTION dispatch stack — device_mesh.ShardedEntry derives the
# specs from ops/batch_axes.py, the supervisor wraps the dispatch, the
# flight recorder carries the per-shard occupancy view — and assert the
# sharded verdicts/bytes match the single-device path exactly.


import contextlib

import pytest


@contextlib.contextmanager
def _mesh(spec="auto"):
    from lighthouse_tpu import device_mesh

    size = device_mesh.configure(spec)
    assert size == N_DEVICES, "conftest must provision 8 virtual CPU devices"
    try:
        yield size
    finally:
        device_mesh.reset_for_tests()


def _example_sets(n_sets, n_keys=2, seed=7):
    import random

    from lighthouse_tpu.crypto.bls import api
    from lighthouse_tpu.crypto.bls.params import R

    rng = random.Random(seed)
    sks = [api.SecretKey(rng.randrange(1, R)) for _ in range(n_keys)]
    pks = [sk.public_key() for sk in sks]
    agg = api.SecretKey(sum(sk.scalar for sk in sks) % R)
    sets = []
    for i in range(n_sets):
        msg = (i.to_bytes(2, "big") + bytes([seed & 0xFF])) * 10 + b"\x00\x00"
        sets.append(api.SignatureSet.multiple_pubkeys(agg.sign(msg), pks, msg))
    return sets


def test_supervised_sharded_bls_verify_matches_single_device():
    """The production entry (`verify_signature_sets_device` — supervisor,
    telemetry, the registry-derived placer) on the mesh: same verdict as
    unsharded, per-shard live counts recorded, padding on the last shards
    (12 live sets in the 16-bucket over 8 devices)."""
    from lighthouse_tpu import device_telemetry
    from lighthouse_tpu.ops.verify import verify_signature_sets_device

    sets = _example_sets(12)
    assert verify_signature_sets_device(sets, seed=b"mesh-par") is True
    with _mesh():
        assert verify_signature_sets_device(sets, seed=b"mesh-par") is True
        rec = device_telemetry.FLIGHT_RECORDER.recent(1)[0]
    assert rec["shape"] == "16x2@dp8"
    assert rec["mesh"] == N_DEVICES
    assert rec["shard_live"] == [2, 2, 2, 2, 2, 2, 0, 0]
    assert not rec["host_fallback"]
    assert rec["occupancy_per_shard"][-1] == 0.0  # padding lands last


def test_supervised_sharded_bls_rejects_bad_set():
    """A corrupted set fails on the mesh exactly as it fails unsharded
    (same program shape -> same cached executables as the test above)."""
    from lighthouse_tpu.crypto.bls import api
    from lighthouse_tpu.ops.verify import verify_signature_sets_device

    sets = _example_sets(12)
    bad = _example_sets(1, seed=9)[0]
    sets[5] = api.SignatureSet.multiple_pubkeys(
        bad.signature, bad.signing_keys, b"a different message entirely")
    assert verify_signature_sets_device(sets, seed=b"mesh-par") is False
    with _mesh():
        assert verify_signature_sets_device(sets, seed=b"mesh-par") is False


def test_sharded_sha256_pairs_bit_identical_uneven():
    """The supervised pair-hash on the mesh returns byte-identical digests
    for a NON-divisible live count (100 blocks -> 256 bucket over 8), with
    the padding accounted on the last shards."""
    from lighthouse_tpu import device_telemetry
    from lighthouse_tpu.ops import sha256_device

    data = bytes(range(256)) * 25  # 100 64-byte blocks
    host = sha256_device.hash_pairs_device(data)
    with _mesh():
        meshed = sha256_device.hash_pairs_device(data)
        rec = device_telemetry.FLIGHT_RECORDER.recent(1)[0]
    assert meshed == host
    assert rec["shape"] == "256@dp8"
    assert rec["shard_live"] == [32, 32, 32, 4, 0, 0, 0, 0]


def test_sharded_epoch_deltas_bit_identical_uneven():
    """The epoch kernel on the mesh — registry-wide participating sums
    completing through psums — returns bit-identical int64 arrays for a
    100-validator registry (buckets to 256, never-active pad rows)."""
    from lighthouse_tpu.ops import epoch_device

    rng = np.random.default_rng(5)
    n = 100

    class _Arrays:
        effective_balance = rng.integers(1, 32_000_000_000, n)
        activation_epoch = rng.integers(0, 5, n)
        exit_epoch = rng.integers(6, 100, n)
        withdrawable_epoch = rng.integers(6, 200, n)
        slashed = rng.random(n) < 0.1

    class _Spec:
        effective_balance_increment = 1_000_000_000
        inactivity_score_bias = 4
        inactivity_score_recovery_rate = 16

    kw = dict(
        previous_epoch=4, in_leak=False, base_reward_per_increment=512,
        total_active_balance=int(_Arrays.effective_balance.sum()),
        quotient=67_108_864, spec=_Spec(),
    )
    prev_part = rng.integers(0, 8, n)
    inact = rng.integers(0, 10, n)
    host = epoch_device.epoch_deltas_device(_Arrays, prev_part, inact, **kw)
    with _mesh():
        meshed = epoch_device.epoch_deltas_device(
            _Arrays, prev_part, inact, **kw)
    for h, m in zip(host, meshed):
        assert np.array_equal(h, m)
        assert m.shape == (n,)  # the mesh pad is sliced back off


@pytest.mark.slow
def test_sharded_kzg_batch_verdict_and_fe_identical():
    """kzg_batch on the mesh: the blob-axis lincombs psum across devices
    and the supervised verdict matches single-device (fabricated points —
    verdict equality is the contract, the host golden model decides)."""
    from lighthouse_tpu import device_telemetry
    from lighthouse_tpu.crypto.bls import curve
    from lighthouse_tpu.crypto.bls.params import R
    from lighthouse_tpu.ops import kzg_device

    npts = 5
    c_pts = [curve.mul(curve.G1, i + 2) for i in range(npts)]
    p_pts = [curve.mul(curve.G1, 3 * i + 1) for i in range(npts)]
    r_powers = [pow(7, i, R) for i in range(npts)]
    zs = [11 + i for i in range(npts)]
    ys = [5 + 2 * i for i in range(npts)]
    g2_tau = curve.mul(curve.G2, 1234567)
    host = kzg_device.verify_kzg_proof_batch_device(
        c_pts, p_pts, r_powers, zs, ys, g2_tau)
    with _mesh():
        meshed = kzg_device.verify_kzg_proof_batch_device(
            c_pts, p_pts, r_powers, zs, ys, g2_tau)
        rec = device_telemetry.FLIGHT_RECORDER.recent(1)[0]
    assert meshed == host
    assert rec["shape"] == "8@dp8"
    assert rec["shard_live"] == [1, 1, 1, 1, 1, 0, 0, 0]
    assert not rec["host_fallback"]


@pytest.mark.slow
def test_per_device_breaker_trip_reshards_mid_op(monkeypatch):
    """The acceptance path: a device failure mid-op trips that device's
    breaker, the mesh re-shards to 7 survivors, the SAME batch retries on
    the shrunk topology (re-padded 16 -> 21 rows) and the verdict is
    identical to single-device — no host fallback, no op-breaker trip."""
    from lighthouse_tpu import device_mesh, device_supervisor, device_telemetry, fault_injection
    from lighthouse_tpu.ops.verify import verify_signature_sets_device

    monkeypatch.setenv(device_mesh.DEVICE_FAILURE_THRESHOLD_ENV, "1")
    sets = _example_sets(12)
    assert verify_signature_sets_device(sets, seed=b"trip") is True
    device_supervisor.reset_for_tests()
    with _mesh():
        # exactly ONE dispatch fault: the charge trips the suspect device
        # (threshold 1), the mesh re-shards, and the retry must succeed
        for plan in fault_injection.parse_spec(
                "device.dispatch[op=bls_verify]=error:first_n=1"):
            fault_injection.REGISTRY.install(plan)
        try:
            assert verify_signature_sets_device(sets, seed=b"trip") is True
        finally:
            fault_injection.clear()
        rec = device_telemetry.FLIGHT_RECORDER.recent(1)[0]
        snap = device_mesh.summary()
    assert snap["size"] == N_DEVICES - 1
    assert snap["reshards_total"] == 1
    assert rec["shape"] == "21x2@dp7"
    assert rec["shard_live"] == [3, 3, 3, 3, 0, 0, 0]
    assert not rec["host_fallback"]
    # the op-level breaker never engaged: the device layer absorbed it
    assert device_supervisor.breaker_state("bls_verify") == "closed"


def test_dryrun_multichip_subprocess():
    """The driver-facing entry point must succeed from an arbitrary parent env.

    Simulates the round-1 failure mode: dryrun_multichip must pass regardless
    of the parent's JAX platform config, because it re-execs a CPU-forced
    child with the device count fixed before interpreter start.
    """
    code = (
        "import os, sys; sys.path.insert(0, %r); "
        "import __graft_entry__ as g; g.dryrun_multichip(4); print('PARENT-OK')" % REPO
    )
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"  # parent needs a working jax only for import
    env.setdefault("JAX_COMPILATION_CACHE_DIR", os.path.join(REPO, ".jax_cache"))
    proc = subprocess.run(
        [sys.executable, "-c", code],
        env=env,
        cwd=REPO,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        timeout=600,
    )
    out = proc.stdout.decode(errors="replace")
    assert proc.returncode == 0, out
    assert "PARENT-OK" in out
