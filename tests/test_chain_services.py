"""Small chain-core services (VERDICT r4 missing #4): attestation
simulator, graffiti calculator, fork-readiness watchers (reference
``attestation_simulator.rs``, ``graffiti_calculator.rs``,
``*_readiness.rs`` + notifier)."""

import pytest

from lighthouse_tpu.chain import BeaconChainHarness
from lighthouse_tpu.chain.fork_readiness import fork_readiness, next_scheduled_fork
from lighthouse_tpu.chain.graffiti_calculator import (
    GraffitiCalculator,
    GraffitiOrigin,
)
from lighthouse_tpu.crypto.bls.backends import set_backend
from lighthouse_tpu.types.spec import minimal_spec


@pytest.fixture()
def harness():
    set_backend("fake")
    yield BeaconChainHarness(validator_count=16, fake_crypto=True)
    set_backend("host")


class TestAttestationSimulator:
    def test_simulated_votes_scored_against_chain(self, harness):
        chain = harness.chain
        harness.extend_chain(2)
        chain.validator_monitor._simulated.clear()  # extend_chain pre-seeded
        for _ in range(4):
            slot = harness.advance_slot()
            block = harness.produce_signed_block(slot=slot)
            chain.process_block(block, block_delay_seconds=1.0)
            # the simulator fires at +1/3 into the slot — AFTER the block
            chain.simulate_attestation()
        stats = chain.validator_monitor.simulator_stats
        # every simulated head vote matched (the chain never re-orged)
        assert stats["head_hits"] >= 3, stats
        assert stats["head_misses"] == 0, stats

    def test_simulator_skips_while_syncing(self, harness):
        chain = harness.chain
        harness.extend_chain(1)
        spe = harness.spec.slots_per_epoch
        for _ in range(spe * 3):  # wall clock runs 3 epochs ahead of the head
            harness.advance_slot()
        chain.validator_monitor._simulated.clear()  # entries from the climb
        chain.simulate_attestation()
        assert not chain.validator_monitor._simulated, (
            "a node 2+ epochs behind must not burn old-state committees")


class TestGraffitiCalculator:
    def test_precedence_vc_then_user_then_calculated(self, harness):
        chain = harness.chain
        calc = chain.graffiti_calculator
        vc = b"from-the-vc".ljust(32, b"\x00")
        assert calc.get_graffiti(vc) == vc
        # calculated: mock EL identity + our version
        auto = calc.get_graffiti(b"\x00" * 32)
        assert b"MK" in auto and b"LH" in auto
        # operator-pinned beats calculated
        calc.beacon_graffiti = GraffitiOrigin.user(b"operator flag")
        pinned = calc.get_graffiti(None)
        assert pinned.startswith(b"operator flag")

    def test_produced_blocks_carry_calculated_graffiti(self, harness):
        chain = harness.chain
        harness.extend_chain(1)
        slot = harness.advance_slot()
        block, _ = chain.produce_block(slot, harness.randao_reveal(
            chain.state_at_slot(slot)[0], slot,
            __import__("lighthouse_tpu.consensus.helpers",
                       fromlist=["h"]).get_beacon_proposer_index(
                chain.state_at_slot(slot)[0], harness.spec)))
        g = bytes(block.body.graffiti)
        assert any(g) and b"LH" in g


class TestForkReadiness:
    def test_upcoming_fork_reports_ready(self):
        set_backend("fake")
        try:
            spec = minimal_spec(
                altair_fork_epoch=0, bellatrix_fork_epoch=0,
                capella_fork_epoch=2, deneb_fork_epoch=None,
            )
            h = BeaconChainHarness(validator_count=16, fake_crypto=True,
                                   spec=spec)
            assert next_scheduled_fork(spec, 0) == ("capella", 2)
            report = fork_readiness(h.chain)
            assert report is not None and report["fork"] == "capella"
            assert report["ready"] is True  # in-proc engine is fork-complete
        finally:
            set_backend("host")

    def test_missing_kzg_flags_not_ready_for_deneb(self):
        set_backend("fake")
        try:
            spec = minimal_spec(
                altair_fork_epoch=0, bellatrix_fork_epoch=0,
                capella_fork_epoch=0, deneb_fork_epoch=2,
            )
            h = BeaconChainHarness(validator_count=16, fake_crypto=True,
                                   spec=spec)
            h.chain.kzg = None
            report = fork_readiness(h.chain)
            assert report is not None and report["ready"] is False
            assert any("KZG" in p for p in report["problems"])
        finally:
            set_backend("host")

    def test_no_report_outside_window(self, harness):
        # default harness spec schedules no future fork
        assert fork_readiness(harness.chain) is None

class TestValidatorMonitorDepth:
    """Sync-committee + missed-proposal tracking (validator_monitor.rs
    register_sync_aggregate_in_block / missed-block tracking)."""

    def test_sync_aggregate_tracking(self, harness):
        chain = harness.chain
        chain.validator_monitor.register(range(16))
        slot = harness.advance_slot()
        signed = harness.produce_signed_block(slot=slot)
        chain.process_block(signed)
        counters = chain.validator_monitor.validator_metrics(range(16))
        hits = sum(c.get("sync_committee_hits", 0)
                   for c in counters["validators"].values())
        misses = sum(c.get("sync_committee_misses", 0)
                     for c in counters["validators"].values())
        # a harness block carries a full sync aggregate: every DISTINCT
        # committee member scores one hit (members repeat in a 32-slot
        # committee drawn from 16 validators; participation is judged
        # per validator per block), zero misses
        distinct = len(set(
            chain._sync_committee_member_indices(chain.head_state)))
        assert hits == distinct > 0
        assert misses == 0

    def test_missed_proposal_counted_once(self, harness):
        chain = harness.chain
        chain.validator_monitor.register(range(16))
        harness.extend_chain(2)
        # skip a slot entirely; the miss is judged at a FULL slot's lag
        # (a late block landing seconds into the next slot is not a miss),
        # so advance two slots before ticking — twice, for idempotence
        skipped = harness.advance_slot()
        harness.advance_slot()
        harness.advance_slot()
        chain.per_slot_task()
        chain.per_slot_task()  # idempotent: the tick may re-fire
        from lighthouse_tpu.consensus import helpers as h
        expected = h.get_beacon_proposer_index(
            chain.head_state, chain.spec, slot=skipped)
        c = chain.validator_monitor.validator_metrics([expected])
        assert c["validators"][str(expected)]["proposal_misses"] == 1

    def test_proposal_hit_counted(self, harness):
        chain = harness.chain
        chain.validator_monitor.register(range(16))
        slot = harness.advance_slot()
        signed = harness.produce_signed_block(slot=slot)
        chain.process_block(signed)
        proposer = int(signed.message.proposer_index)
        c = chain.validator_monitor.validator_metrics([proposer])
        assert c["validators"][str(proposer)]["proposal_hits"] == 1
