"""Decoder-robustness smoke (ISSUE 3 satellite; first slice of VERDICT
Missing #7): a deterministic seeded randomized-bytes loop over every wire
decoder — SSZ containers, the gossipsub protobuf codec, both snappy
formats, and discv5 packet parsing — asserting that hostile input produces
CLEAN TYPED ERRORS (the decoder's declared error class), never a raw
traceback (IndexError/KeyError/struct.error/RecursionError/...).

Two input families per target:
- pure random bytes at assorted lengths (shallow paths, framing);
- structure-aware mutations of a VALID encoding — bit flips, truncations,
  extensions — which reach the deep field-decode paths.

Bounded iterations; runs in a few seconds on CPU.
"""

import random

import pytest

from lighthouse_tpu.network import pb, snappy_codec
from lighthouse_tpu.types.containers import build_types
from lighthouse_tpu.types.spec import minimal_spec

SEED = 0xC0FFEE
N_RANDOM = 150  # random inputs per target
N_MUTATE = 150  # mutated-valid inputs per target
LENGTHS = (0, 1, 2, 3, 4, 7, 8, 15, 16, 31, 64, 100, 257, 1000)


@pytest.fixture(scope="module")
def spec():
    return minimal_spec(altair_fork_epoch=0, bellatrix_fork_epoch=0,
                        capella_fork_epoch=0)


@pytest.fixture(scope="module")
def types(spec):
    return build_types(spec.preset)


def _random_inputs(rng):
    for _ in range(N_RANDOM):
        yield bytes(rng.getrandbits(8) for _ in range(rng.choice(LENGTHS)))


def _mutations(rng, valid: bytes):
    for _ in range(N_MUTATE):
        data = bytearray(valid)
        kind = rng.randrange(4)
        if kind == 0 and data:  # flip bytes
            for _ in range(rng.randrange(1, 4)):
                data[rng.randrange(len(data))] ^= 1 << rng.randrange(8)
        elif kind == 1:  # truncate
            data = data[: rng.randrange(len(data) + 1)]
        elif kind == 2:  # extend with noise
            data += bytes(rng.getrandbits(8) for _ in range(rng.randrange(1, 40)))
        else:  # splice a random window
            if data:
                i = rng.randrange(len(data))
                j = min(len(data), i + rng.randrange(1, 16))
                data[i:j] = bytes(rng.getrandbits(8) for _ in range(j - i))
        yield bytes(data)


def _assert_clean(decode, inputs, allowed):
    """Decoding must either succeed or raise exactly an allowed error."""
    for data in inputs:
        try:
            decode(data)
        except allowed:
            pass
        # anything else (IndexError, KeyError, struct.error, ...) propagates
        # and fails the test with the offending input visible in the repr


class TestSszDecoders:
    def test_attestation_random_and_mutated(self, types):
        rng = random.Random(SEED)
        decode = types.Attestation.from_ssz_bytes
        _assert_clean(decode, _random_inputs(rng), (ValueError,))
        valid = types.Attestation().as_ssz_bytes()
        _assert_clean(decode, _mutations(rng, valid), (ValueError,))

    def test_signed_block_random_and_mutated(self, types):
        rng = random.Random(SEED + 1)
        decode = types.signed_block["capella"].from_ssz_bytes
        _assert_clean(decode, _random_inputs(rng), (ValueError,))
        valid = types.signed_block["capella"]().as_ssz_bytes()
        _assert_clean(decode, _mutations(rng, valid), (ValueError,))

    def test_state_random(self, types):
        rng = random.Random(SEED + 2)
        decode = types.state["capella"].from_ssz_bytes
        _assert_clean(decode, _random_inputs(rng), (ValueError,))


class TestGossipPbDecoder:
    def test_rpc_random_and_mutated(self):
        rng = random.Random(SEED + 3)
        _assert_clean(pb.RPC.decode, _random_inputs(rng), (pb.PbError,))
        valid = pb.RPC(
            publish=[pb.Message(data=b"payload", topic="topic/x")]
        ).encode()
        _assert_clean(pb.RPC.decode, _mutations(rng, valid), (pb.PbError,))


class TestSnappyDecoders:
    def test_raw_random_and_mutated(self):
        rng = random.Random(SEED + 4)
        _assert_clean(
            snappy_codec.decompress, _random_inputs(rng), (snappy_codec.SnappyError,)
        )
        valid = snappy_codec.compress(bytes(range(256)) * 8)
        _assert_clean(
            snappy_codec.decompress, _mutations(rng, valid), (snappy_codec.SnappyError,)
        )

    def test_frames_random_and_mutated(self):
        rng = random.Random(SEED + 5)
        _assert_clean(
            snappy_codec.frame_decompress,
            _random_inputs(rng),
            (snappy_codec.SnappyError,),
        )
        valid = snappy_codec.frame_compress(b"block body bytes " * 64)
        _assert_clean(
            snappy_codec.frame_decompress,
            _mutations(rng, valid),
            (snappy_codec.SnappyError,),
        )


class TestDiscv5PacketDecoder:
    def test_decode_packet_random_and_mutated(self):
        packets = pytest.importorskip(
            "lighthouse_tpu.network.discv5.packets",
            reason="discv5 needs the `cryptography` package",
        )
        rng = random.Random(SEED + 6)
        node_id = bytes(rng.getrandbits(8) for _ in range(32))
        decode = lambda d: packets.decode_packet(node_id, d)  # noqa: E731
        _assert_clean(decode, _random_inputs(rng), (packets.PacketError,))
        # a well-formed masked header with mutated tails
        for data in _random_inputs(rng):
            _assert_clean(decode, [b"\x00" * 16 + data], (packets.PacketError,))
