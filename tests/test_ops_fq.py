"""Exact validation of the JAX limb arithmetic against Python integers."""

import random

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lighthouse_tpu.crypto.bls.params import P
from lighthouse_tpu.ops import fq

rng = random.Random(0xF00D)


def rand_elt():
    return rng.randrange(P)


def test_roundtrip():
    for _ in range(10):
        v = rand_elt()
        assert fq.from_limbs16(fq.to_limbs16(v)) == v


def test_mul_exact():
    mul = jax.jit(fq.fq_mul)
    for _ in range(20):
        a, b = rand_elt(), rand_elt()
        r = mul(jnp.asarray(fq.to_limbs16(a)), jnp.asarray(fq.to_limbs16(b)))
        assert fq.from_limbs16(np.asarray(r)) == a * b % P
        assert int(np.abs(np.asarray(r)).max()) < 1 << 17


def test_mul_batched():
    n = 64
    av = [rand_elt() for _ in range(n)]
    bv = [rand_elt() for _ in range(n)]
    a = jnp.asarray(np.stack([fq.to_limbs16(x) for x in av]))
    b = jnp.asarray(np.stack([fq.to_limbs16(x) for x in bv]))
    r = np.asarray(jax.jit(fq.fq_mul)(a, b))
    for i in range(n):
        assert fq.from_limbs16(r[i]) == av[i] * bv[i] % P


def test_deep_expression_chains():
    """Adversarial chains of add/sub/mul keep exactness and limb bounds."""

    @jax.jit
    def chain(a, b, c):
        t = fq.fq_mul(a, b)
        acc = t
        for _ in range(100):          # long additive chain between muls
            acc = fq.fq_add(acc, t)
        u = fq.fq_sub(acc, fq.fq_mul_small(c, 37))
        v = fq.fq_mul(u, fq.fq_neg(acc))
        return fq.fq_mul(v, v)

    a, b, c = rand_elt(), rand_elt(), rand_elt()
    r = chain(*(jnp.asarray(fq.to_limbs16(x)) for x in (a, b, c)))
    t = a * b % P
    acc = t * 101 % P
    u = (acc - 37 * c) % P
    v = u * (-acc) % P
    assert fq.from_limbs16(np.asarray(r)) == v * v % P


def test_zero_and_edge_values():
    mul = jax.jit(fq.fq_mul)
    for a, b in [(0, 0), (0, 1), (1, 1), (P - 1, P - 1), (P - 1, 1), (2**380, P - 2)]:
        r = mul(jnp.asarray(fq.to_limbs16(a)), jnp.asarray(fq.to_limbs16(b)))
        assert fq.from_limbs16(np.asarray(r)) == a * b % P


def test_negative_redundant_inputs():
    """Subtraction results (negative values / signed limbs) multiply correctly."""

    @jax.jit
    def f(a, b):
        d = fq.fq_sub(a, b)          # negative value when a < b
        return fq.fq_mul(d, d)

    a, b = 5, P - 3
    r = f(jnp.asarray(fq.to_limbs16(a)), jnp.asarray(fq.to_limbs16(b)))
    assert fq.from_limbs16(np.asarray(r)) == (a - b) ** 2 % P


def test_pow_and_inv():
    x = rand_elt()
    xi = np.asarray(jax.jit(fq.fq_inv)(jnp.asarray(fq.to_limbs16(x))))
    assert fq.from_limbs16(xi) == pow(x, P - 2, P)
    assert fq.from_limbs16(xi) * x % P == 1


def test_reduce_tightens():
    x = jnp.asarray(fq.to_limbs16(rand_elt())) * jnp.int32(400)  # limbs ~2^24.6
    r = np.asarray(jax.jit(fq.fq_reduce)(x))
    assert fq.from_limbs16(r) == fq.from_limbs16(np.asarray(x))
    assert int(np.abs(r).max()) < 1 << 17
