"""Exact validation of the JAX limb arithmetic against Python integers."""

import random

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lighthouse_tpu.crypto.bls.params import P
from lighthouse_tpu.ops import fq

rng = random.Random(0xF00D)


def rand_elt():
    return rng.randrange(P)


def test_roundtrip():
    for _ in range(10):
        v = rand_elt()
        assert fq.from_limbs16(fq.to_limbs16(v)) == v


def test_mul_exact():
    mul = jax.jit(fq.fq_mul)
    for _ in range(20):
        a, b = rand_elt(), rand_elt()
        r = mul(jnp.asarray(fq.to_limbs16(a)), jnp.asarray(fq.to_limbs16(b)))
        assert fq.from_limbs16(np.asarray(r)) == a * b % P
        assert int(np.abs(np.asarray(r)).max()) < 1 << 17


def test_mul_batched():
    n = 64
    av = [rand_elt() for _ in range(n)]
    bv = [rand_elt() for _ in range(n)]
    a = jnp.asarray(np.stack([fq.to_limbs16(x) for x in av]))
    b = jnp.asarray(np.stack([fq.to_limbs16(x) for x in bv]))
    r = np.asarray(jax.jit(fq.fq_mul)(a, b))
    for i in range(n):
        assert fq.from_limbs16(r[i]) == av[i] * bv[i] % P


def test_deep_expression_chains():
    """Adversarial chains of add/sub/mul keep exactness and limb bounds."""

    @jax.jit
    def chain(a, b, c):
        t = fq.fq_mul(a, b)
        acc = t
        for _ in range(100):          # long additive chain between muls
            acc = fq.fq_add(acc, t)
        u = fq.fq_sub(acc, fq.fq_mul_small(c, 37))
        v = fq.fq_mul(u, fq.fq_neg(acc))
        return fq.fq_mul(v, v)

    a, b, c = rand_elt(), rand_elt(), rand_elt()
    r = chain(*(jnp.asarray(fq.to_limbs16(x)) for x in (a, b, c)))
    t = a * b % P
    acc = t * 101 % P
    u = (acc - 37 * c) % P
    v = u * (-acc) % P
    assert fq.from_limbs16(np.asarray(r)) == v * v % P


def test_zero_and_edge_values():
    mul = jax.jit(fq.fq_mul)
    for a, b in [(0, 0), (0, 1), (1, 1), (P - 1, P - 1), (P - 1, 1), (2**380, P - 2)]:
        r = mul(jnp.asarray(fq.to_limbs16(a)), jnp.asarray(fq.to_limbs16(b)))
        assert fq.from_limbs16(np.asarray(r)) == a * b % P


def test_negative_redundant_inputs():
    """Subtraction results (negative values / signed limbs) multiply correctly."""

    @jax.jit
    def f(a, b):
        d = fq.fq_sub(a, b)          # negative value when a < b
        return fq.fq_mul(d, d)

    a, b = 5, P - 3
    r = f(jnp.asarray(fq.to_limbs16(a)), jnp.asarray(fq.to_limbs16(b)))
    assert fq.from_limbs16(np.asarray(r)) == (a - b) ** 2 % P


def test_pow_and_inv():
    x = rand_elt()
    xi = np.asarray(jax.jit(fq.fq_inv)(jnp.asarray(fq.to_limbs16(x))))
    assert fq.from_limbs16(xi) == pow(x, P - 2, P)
    assert fq.from_limbs16(xi) * x % P == 1


def test_reduce_tightens():
    x = jnp.asarray(fq.to_limbs16(rand_elt())) * jnp.int32(400)  # limbs ~2^24.6
    r = np.asarray(jax.jit(fq.fq_reduce)(x))
    assert fq.from_limbs16(r) == fq.from_limbs16(np.asarray(x))
    assert int(np.abs(r).max()) < 1 << 17


# ------------------------------------------------------------ int8 backend


def _limbs(v: int) -> jnp.ndarray:
    return jnp.asarray(fq.to_limbs16(v))


def test_int8_backend_selection_and_env(monkeypatch):
    monkeypatch.setenv(fq.FQ_BACKEND_ENV, "int8")
    prev = fq.set_fq_backend(None)  # force re-resolution from env
    try:
        assert fq.active_fq_backend() == "int8"
        monkeypatch.setenv(fq.FQ_BACKEND_ENV, "bogus")
        fq.set_fq_backend(None)
        with pytest.raises(ValueError):
            fq.active_fq_backend()
    finally:
        monkeypatch.delenv(fq.FQ_BACKEND_ENV, raising=False)
        fq.set_fq_backend(prev)


def test_int8_mul_exact_canonical():
    """int8 lowering is exact (and value-identical to int32) on canonical
    inputs; both meet the shared output-bound contract."""
    m8 = jax.jit(fq._fq_mul_int8)
    m32 = jax.jit(fq._fq_mul_int32)
    for _ in range(20):
        a, b = rand_elt(), rand_elt()
        r8 = np.asarray(m8(_limbs(a), _limbs(b)))
        r32 = np.asarray(m32(_limbs(a), _limbs(b)))
        assert fq.from_limbs16(r8) == a * b % P
        assert fq.from_limbs16(r8) == fq.from_limbs16(r32)
        assert int(np.abs(r8).max()) < 1 << 17


def test_int8_mul_exact_at_documented_magnitude_limit():
    """The bound discipline's edge: EVERY limb at +-2^25 (the documented
    input ceiling) still multiplies exactly — the balanced-nibble digits
    stay in [-8, 8] and nothing overflows int8/int32 anywhere."""
    m8 = jax.jit(fq._fq_mul_int8)
    hi = np.full((fq.L16,), 1 << 25, np.int32)
    lo = -hi
    mixed = np.asarray([(1 << 25) * (-1) ** i for i in range(fq.L16)], np.int32)
    for x, y in [(hi, hi), (hi, lo), (lo, lo), (mixed, hi), (mixed, mixed)]:
        r = np.asarray(m8(jnp.asarray(x), jnp.asarray(y)))
        want = fq.from_limbs16(x) * fq.from_limbs16(y) % P
        assert fq.from_limbs16(r) == want
        assert int(np.abs(r).max()) < 1 << 17


def test_int8_mul_chained_add_worst_case():
    """Chained-add worst case: ~500 summed fresh elements (limbs ~2^25)
    multiplied under the int8 lowering match exact integers."""

    @jax.jit
    def chain(a, b):
        acc = a
        for _ in range(499):
            acc = fq.fq_add(acc, a)  # 500 * a, limbs up to ~500 * 2^16
        return fq._fq_mul_int8(acc, b)

    a, b = rand_elt(), rand_elt()
    r = np.asarray(chain(_limbs(a), _limbs(b)))
    assert fq.from_limbs16(r) == (500 * a % P) * b % P


def test_int8_mul_redundant_and_negative_inputs():
    """Redundant signed limbs (subtraction results, scaled elements) are
    value-identical between the two lowerings."""
    m8 = jax.jit(fq._fq_mul_int8)
    m32 = jax.jit(fq._fq_mul_int32)
    rs = np.random.RandomState(0xBEEF)
    for _ in range(10):
        x = rs.randint(-(1 << 25), 1 << 25, size=(4, fq.L16)).astype(np.int32)
        y = rs.randint(-(1 << 25), 1 << 25, size=(4, fq.L16)).astype(np.int32)
        r8 = np.asarray(m8(jnp.asarray(x), jnp.asarray(y)))
        r32 = np.asarray(m32(jnp.asarray(x), jnp.asarray(y)))
        for i in range(4):
            want = fq.from_limbs16(x[i]) * fq.from_limbs16(y[i]) % P
            assert fq.from_limbs16(r8[i]) == want
            assert fq.from_limbs16(r8[i]) == fq.from_limbs16(r32[i])


def test_balanced_nibbles_bounds_and_value():
    """The digitisation invariants the s8 dot depends on: |digit| <= 8 and
    exact value preservation, across the whole documented input range."""
    rs = np.random.RandomState(7)
    x = rs.randint(-(1 << 25), 1 << 25, size=(32, fq.L16)).astype(np.int32)
    folded = jax.jit(fq.fold16_2)(jnp.asarray(x))
    digits = np.asarray(jax.jit(fq._balanced_nibbles)(folded))
    assert digits.dtype == np.int8
    assert int(np.abs(digits).max()) <= 8
    for row in range(x.shape[0]):
        val = sum(int(d) << (4 * k) for k, d in enumerate(digits[row]))
        assert val == sum(int(l) << (16 * i) for i, l in enumerate(x[row]))


def test_fq_mul_many_matches_per_call_fuzz():
    """Seeded fuzz: heterogeneous batch shapes through fq_mul_many are
    bit-identical to per-call fq_mul."""
    rs = np.random.RandomState(0x51EED)
    for _ in range(3):
        pairs = []
        for shape in [(), (3,), (2, 2), (5,)]:
            a = rs.randint(-(1 << 24), 1 << 24, size=shape + (fq.L16,))
            b = rs.randint(-(1 << 24), 1 << 24, size=shape + (fq.L16,))
            pairs.append((jnp.asarray(a, jnp.int32), jnp.asarray(b, jnp.int32)))
        outs = jax.jit(fq.fq_mul_many)(pairs)
        assert len(outs) == len(pairs)
        for (a, b), o in zip(pairs, outs):
            assert np.array_equal(np.asarray(o), np.asarray(fq.fq_mul(a, b)))


def test_fq_mul_many_broadcasts_like_fq_mul():
    a = jnp.asarray(np.stack([fq.to_limbs16(rand_elt()) for _ in range(3)]))
    s = _limbs(rand_elt())
    (o,) = fq.fq_mul_many([(a, s)])  # (3, 25) x (25,) broadcast
    assert np.array_equal(np.asarray(o), np.asarray(fq.fq_mul(a, s)))
