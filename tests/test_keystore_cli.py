"""Keystore (EIP-2335), wallet (EIP-2386), CLI, and ClientBuilder tests."""

import json
import os

import pytest

from lighthouse_tpu.crypto import keystore as ks

PASSWORD = "correct horse battery staple"
SECRET = bytes.fromhex(
    "000000000019d6689c085ae165831e934ff763ae46a2a6c172b3f1b60a8ce26f"
)


class TestKeystore:
    def test_roundtrip_scrypt(self):
        keystore = ks.encrypt(SECRET, PASSWORD, kdf="scrypt", _test_fast_kdf=True)
        assert ks.decrypt(keystore, PASSWORD) == SECRET

    def test_roundtrip_pbkdf2(self):
        keystore = ks.encrypt(SECRET, PASSWORD, kdf="pbkdf2", _test_fast_kdf=True)
        assert ks.decrypt(keystore, PASSWORD) == SECRET

    def test_wrong_password_rejected(self):
        keystore = ks.encrypt(SECRET, PASSWORD, _test_fast_kdf=True)
        with pytest.raises(ks.KeystoreError, match="checksum"):
            ks.decrypt(keystore, "wrong")

    def test_eip2335_scrypt_vector(self):
        """The EIP-2335 scrypt test vector — an external KAT: decrypting with
        the spec password must recover the spec secret byte-for-byte."""
        vector = {
            "crypto": {
                "kdf": {
                    "function": "scrypt",
                    "params": {
                        "dklen": 32, "n": 262144, "p": 1, "r": 8,
                        "salt": "d4e56740f876aef8c010b86a40d5f56745a118d0906a34e69aec8c0db1cb8fa3",
                    },
                    "message": "",
                },
                "checksum": {
                    "function": "sha256", "params": {},
                    "message": "d2217fe5f3e9a1e34581ef8a78f7c9928e436d36dacc5e846690a5581e8ea484",
                },
                "cipher": {
                    "function": "aes-128-ctr",
                    "params": {"iv": "264daa3f303d7259501c93d997d84fe6"},
                    "message": "06ae90d55fe0a6e9c5c3bc5b170827b2e5cce3929ed3f116c2811e6366dfe20f",
                },
            },
            "version": 4,
        }
        # the EIP writes the password in mathematical-fraktur letters that
        # NFKD-normalize to "testpassword", followed by the key emoji
        password = "".join(
            chr(0x1D51E + ord(c) - ord("a")) for c in "testpassword"
        ) + "\U0001f511"
        import unicodedata
        assert "".join(
            c for c in unicodedata.normalize("NFKD", password)
        ).startswith("testpassword")
        assert ks.decrypt(vector, password) == SECRET

    def test_eip2335_pbkdf2_vector(self):
        vector = {
            "crypto": {
                "kdf": {
                    "function": "pbkdf2",
                    "params": {
                        "dklen": 32, "c": 262144, "prf": "hmac-sha256",
                        "salt": "d4e56740f876aef8c010b86a40d5f56745a118d0906a34e69aec8c0db1cb8fa3",
                    },
                    "message": "",
                },
                "checksum": {
                    "function": "sha256", "params": {},
                    "message": "8a9f5d9912ed7e75ea794bc5a89bca5f193721d30868ade6f73043c6ea6febf1",
                },
                "cipher": {
                    "function": "aes-128-ctr",
                    "params": {"iv": "264daa3f303d7259501c93d997d84fe6"},
                    "message": "cee03fde2af33149775b7223e7845e4fb2c8ae1792e5f99fe9ecf474cc8c16ad",
                },
            },
            "version": 4,
        }
        password = "".join(
            chr(0x1D51E + ord(c) - ord("a")) for c in "testpassword"
        ) + "\U0001f511"
        assert ks.decrypt(vector, password) == SECRET


class TestWallet:
    def test_wallet_derives_eip2334_paths(self):
        wallet, seed = ks.create_wallet("w", PASSWORD, _test_fast_kdf=True)
        derived = ks.derive_validator_keystores(
            wallet, PASSWORD, "kspass", 2, _test_fast_kdf=True
        )
        assert wallet["nextaccount"] == 2
        from lighthouse_tpu.crypto import key_derivation as kd

        for i, (keystore, sk_int) in enumerate(derived):
            assert keystore["path"] == f"m/12381/3600/{i}/0/0"
            assert sk_int == kd.derive_path(seed, keystore["path"])
            sk = ks.load_keystore_signing_key(keystore, "kspass")
            assert sk.scalar == sk_int
            assert keystore["pubkey"] == sk.public_key().to_bytes().hex()
        # a third derivation continues from nextaccount
        more = ks.derive_validator_keystores(
            wallet, PASSWORD, "kspass", 1, _test_fast_kdf=True
        )
        assert more[0][0]["path"] == "m/12381/3600/2/0/0"


class TestCli:
    def test_account_wallet_and_validators(self, tmp_path):
        from lighthouse_tpu.cli import main

        pw = tmp_path / "pw.txt"
        pw.write_text("hunter2hunter2")
        base = str(tmp_path / "base")
        assert main([
            "account_manager", "--base-dir", base,
            "wallet-create", "--name", "test", "--password-file", str(pw),
        ]) == 0
        wallet_path = os.path.join(base, "wallet-test.json")
        assert os.path.exists(wallet_path)
        # lower the KDF cost for test speed by rewriting the wallet with
        # fast parameters (same seed)
        wallet = ks.load_json(wallet_path)
        seed = ks.wallet_seed(wallet, "hunter2hunter2")
        fast, _ = ks.create_wallet("test", "hunter2hunter2", seed=seed,
                                   _test_fast_kdf=True)
        ks.save_json(fast, wallet_path)

        # validator-create is slow with real scrypt; derive directly instead
        derived = ks.derive_validator_keystores(
            fast, "hunter2hunter2", "kspass", 1, _test_fast_kdf=True
        )
        vdir = os.path.join(base, "validators")
        os.makedirs(vdir, exist_ok=True)
        ks.save_json(derived[0][0], os.path.join(vdir, "keystore-x.json"))
        assert main([
            "account_manager", "--base-dir", base, "validator-list",
        ]) == 0

    def test_parser_shape(self):
        from lighthouse_tpu.cli import build_parser

        p = build_parser()
        args = p.parse_args([
            "bn", "--network", "minimal", "--interop-validators", "16",
            "--http-port", "5099", "--bls-backend", "fake",
        ])
        assert args.func.__name__ == "run_beacon_node"
        args = p.parse_args(["vc", "--keystore-dir", "/tmp/x"])
        assert args.func.__name__ == "run_validator_client"


class TestClientBuilder:
    def test_build_and_run_minimal_node(self, tmp_path):
        """Full staged assembly: datadir-backed store, http API, slasher —
        a real socket node from the builder, then clean shutdown."""
        from lighthouse_tpu.client import ClientBuilder
        from lighthouse_tpu.crypto.bls.backends import set_backend
        from lighthouse_tpu.http_api import BeaconNodeHttpClient
        from lighthouse_tpu.types.spec import minimal_spec

        try:
            client = (
                ClientBuilder()
                .with_spec(minimal_spec(
                    altair_fork_epoch=0, bellatrix_fork_epoch=0,
                    capella_fork_epoch=0, deneb_fork_epoch=None,
                ))
                .with_interop_genesis(16, genesis_time=1_600_000_000)
                .with_datadir(str(tmp_path / "node"))
                .with_http_api(0)
                .with_slasher()
                .with_bls_backend("fake")
                .build()
                .start()
            )
            try:
                api = BeaconNodeHttpClient(client.http_server.url)
                assert api.node_version().startswith("lighthouse-tpu/")
                g = api.genesis()
                assert g["genesis_time"] == "1600000000"
                assert client.slasher is not None
                assert os.path.exists(str(tmp_path / "node" / "chain.db"))
            finally:
                client.stop()
        finally:
            set_backend("host")
