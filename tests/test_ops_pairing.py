"""JAX multi-pairing vs host oracles (golden model + projective mirror)."""

import random

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lighthouse_tpu.crypto.bls import curve, pairing as hp
from lighthouse_tpu.crypto.bls import host_projective as hpp
from lighthouse_tpu.ops import ec, pairing as jp, tower as tw

rng = random.Random(0x9A1)


def rand_g1():
    return curve.mul(curve.G1, rng.randrange(1, curve.R))


def rand_g2():
    return curve.mul(curve.G2, rng.randrange(1, curve.R))


def stack_g1(pts):
    return tuple(
        jnp.stack([jnp.asarray(ec.g1_to_limbs(pt)[i]) for pt in pts]) for i in range(3)
    )


def stack_g2_affine(pts):
    xs = jnp.stack([jnp.asarray(tw.fq2_to_limbs(pt[0])) for pt in pts])
    ys = jnp.stack([jnp.asarray(tw.fq2_to_limbs(pt[1])) for pt in pts])
    return (xs, ys)


def test_miller_matches_host_mirror():
    p, q = rand_g1(), rand_g2()
    f = jax.jit(jp.miller_loop)(
        tuple(jnp.asarray(c) for c in ec.g1_to_limbs(p)),
        (jnp.asarray(tw.fq2_to_limbs(q[0])), jnp.asarray(tw.fq2_to_limbs(q[1]))),
    )
    assert tw.fq12_from_limbs(f) == hpp.miller_loop_projective(p, q)


def test_final_exponentiation_matches_golden():
    p, q = rand_g1(), rand_g2()
    f_host = hpp.miller_loop_projective(p, q)
    fe = jax.jit(jp.final_exponentiation)(jnp.asarray(tw.fq12_to_limbs(f_host)))
    assert tw.fq12_from_limbs(fe) == hp.final_exponentiation(f_host)


def test_multi_pairing_valid_and_invalid():
    p, q = rand_g1(), rand_g2()
    a = rng.randrange(2, 2**40)
    pairs_good = [(curve.mul(p, a), q), (curve.neg(p), curve.mul(q, a))]
    pairs_bad = [(curve.mul(p, a), q), (curve.neg(p), curve.mul(q, a + 1))]
    fn = jax.jit(jp.multi_pairing_fe)
    for pairs, expect in [(pairs_good, True), (pairs_bad, False)]:
        p1 = stack_g1([pr[0] for pr in pairs])
        q2 = stack_g2_affine([pr[1] for pr in pairs])
        fe = fn(p1, q2, jnp.asarray([True, True]))
        assert jp.fe_is_one(fe) == expect


def test_g1_infinity_auto_killed():
    """A (projective-infinity G1, Q) pair contributes subfield junk only."""
    q = rand_g2()
    p1 = stack_g1([None, rand_g1()])
    g = curve.mul(curve.G2, 7)
    q2 = stack_g2_affine([q, g])
    # pair 2 = (P, 7*G2') chosen invalid alone; combined with masked-in inf pair
    fe = jax.jit(jp.multi_pairing_fe)(p1, q2, jnp.asarray([True, False]))
    assert jp.fe_is_one(fe)  # inf pair -> 1, other masked -> 1


def test_mask_and_padding():
    p, q = rand_g1(), rand_g2()
    a = rng.randrange(2, 2**40)
    # 3 pairs (non-power-of-two): the valid two + one garbage pair masked out.
    pairs = [(curve.mul(p, a), q), (curve.neg(p), curve.mul(q, a)), (rand_g1(), rand_g2())]
    p1 = stack_g1([pr[0] for pr in pairs])
    q2 = stack_g2_affine([pr[1] for pr in pairs])
    fe = jax.jit(jp.multi_pairing_fe)(p1, q2, jnp.asarray([True, True, False]))
    assert jp.fe_is_one(fe)
