"""Fault-injection layer tests (ISSUE 5): plan-spec parsing, scoping
(op filter / fail-first-N / seeded probability), the disabled-is-a-noop
contract, the admin endpoint (POST/GET/DELETE /lighthouse/faults), and the
non-device injection points — store.write into block import, engine.request
through the EL state machine, signer.request through the web3signer
retry satellite."""

import http.client
import json

import pytest

from lighthouse_tpu import fault_injection as fi
from lighthouse_tpu import metrics


@pytest.fixture(autouse=True)
def _clean_faults():
    fi.reset_for_tests()
    yield
    fi.reset_for_tests()


# ----------------------------------------------------------------- parsing


class TestPlanParsing:
    def test_bare_point(self):
        p = fi.parse_plan("device.dispatch=error")
        assert (p.point, p.mode, p.op) == ("device.dispatch", "error", None)

    def test_op_selector(self):
        p = fi.parse_plan("device.dispatch[op=bls_verify]=error")
        assert p.op == "bls_verify"

    def test_args(self):
        p = fi.parse_plan("store.write=error:first_n=2")
        assert p.first_n == 2
        p = fi.parse_plan("device.dispatch=hang:sleep_s=1.5")
        assert p.mode == "hang" and p.sleep_s == 1.5
        p = fi.parse_plan("device.result=corrupt:probability=0.25,seed=7")
        assert p.probability == 0.25 and p.seed == 7

    def test_multi_plan_spec(self):
        plans = fi.parse_spec(
            "device.dispatch[op=bls_verify]=error; store.write=error:first_n=1"
        )
        assert [p.point for p in plans] == ["device.dispatch", "store.write"]

    @pytest.mark.parametrize("bad", [
        "nonsense",
        "unknown.point=error",
        "device.dispatch=explode",
        "device.dispatch=error:first_n=2,probability=0.5",
        "device.dispatch=error:probability=1.5",
        "device.dispatch[shape=4]=error",
        "device.dispatch=error:wat=1",
    ])
    def test_rejects_malformed(self, bad):
        with pytest.raises(ValueError):
            fi.parse_plan(bad)

    def test_env_configuration(self, monkeypatch):
        monkeypatch.setenv(
            "LIGHTHOUSE_TPU_FAULTS",
            "device.dispatch[op=bls_verify]=error;device.result=corrupt",
        )
        assert fi.configure_from_env() == 2
        points = {p["point"] for p in fi.plans()}
        assert points == {"device.dispatch", "device.result"}


# ----------------------------------------------------------------- firing


class TestFiring:
    def test_disabled_is_noop(self):
        assert fi.ACTIVE is False
        assert fi.fire("device.dispatch", op="bls_verify") is None
        fi.check("store.write")  # must not raise

    def test_error_mode_raises_and_counts(self):
        before = fi.FAULT_INJECTIONS_FIRED.get(
            point="device.dispatch", mode="error")
        fi.install("device.dispatch", "error")
        assert fi.ACTIVE is True
        with pytest.raises(fi.InjectedFault):
            fi.check("device.dispatch", op="anything")
        assert fi.FAULT_INJECTIONS_FIRED.get(
            point="device.dispatch", mode="error") == before + 1

    def test_op_filter(self):
        fi.install("device.dispatch", "error", op="bls_verify")
        fi.check("device.dispatch", op="sha256_pairs")  # no fire
        with pytest.raises(fi.InjectedFault):
            fi.check("device.dispatch", op="bls_verify")
        plan = fi.plans()[0]
        assert plan["hits"] == 1 and plan["fired"] == 1

    def test_fail_first_n_then_passes(self):
        fi.install("store.write", "error", first_n=2)
        for _ in range(2):
            with pytest.raises(fi.InjectedFault):
                fi.check("store.write")
        fi.check("store.write")  # 3rd call passes
        fi.check("store.write")
        plan = fi.plans()[0]
        assert plan["hits"] == 4 and plan["fired"] == 2

    def test_seeded_probability_is_deterministic(self):
        def firing_pattern():
            plan = fi.install("device.dispatch", "corrupt",
                              probability=0.5, seed=1234)
            pattern = [
                fi.fire("device.dispatch") == "corrupt" for _ in range(32)
            ]
            fi.clear(plan_id=plan.plan_id)
            return pattern

        a, b = firing_pattern(), firing_pattern()
        assert a == b
        assert 0 < sum(a) < 32  # actually probabilistic, not constant

    def test_corrupt_action_returned(self):
        fi.install("device.result", "corrupt")
        assert fi.fire("device.result") == "corrupt"
        # check() swallows the action (for sites with nothing to corrupt)
        fi.check("device.result")

    def test_clear_by_point_and_id(self):
        a = fi.install("device.dispatch", "error")
        fi.install("store.write", "error")
        assert fi.clear(plan_id=a.plan_id) == 1
        assert fi.clear(point="store.write") == 1
        assert fi.ACTIVE is False


# ---------------------------------------------------------- admin endpoint


@pytest.fixture(scope="module")
def faults_api():
    from lighthouse_tpu.chain import BeaconChainHarness
    from lighthouse_tpu.crypto.bls.backends import set_backend
    from lighthouse_tpu.http_api import HttpApiServer

    set_backend("fake")
    harness = BeaconChainHarness(validator_count=8, fake_crypto=True)
    server = HttpApiServer(harness.chain).start()
    yield harness, server
    server.stop()
    set_backend("host")


def _request(port, method, path, body=None):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    try:
        payload = None if body is None else json.dumps(body)
        headers = {} if body is None else {"Content-Type": "application/json"}
        conn.request(method, path, body=payload, headers=headers)
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read())
    finally:
        conn.close()


class TestAdminEndpoint:
    def test_install_list_clear_roundtrip(self, faults_api):
        _, server = faults_api
        status, out = _request(
            server.port, "POST", "/lighthouse/faults",
            body={"spec": "device.dispatch[op=bls_verify]=error:first_n=3"},
        )
        assert status == 200
        (plan,) = out["data"]
        assert plan["point"] == "device.dispatch"
        assert plan["op"] == "bls_verify" and plan["first_n"] == 3

        status, out = _request(server.port, "GET", "/lighthouse/faults")
        assert status == 200
        assert out["data"]["active"] is True
        assert len(out["data"]["plans"]) == 1
        assert "device.dispatch" in out["data"]["points"]

        status, out = _request(
            server.port, "DELETE", f"/lighthouse/faults?id={plan['id']}")
        assert status == 200
        assert out["data"]["cleared"] == 1
        assert fi.ACTIVE is False

    def test_install_structured_plan(self, faults_api):
        _, server = faults_api
        status, out = _request(
            server.port, "POST", "/lighthouse/faults",
            body={"point": "device.result", "mode": "corrupt",
                  "probability": 0.5, "seed": 9},
        )
        assert status == 200
        assert out["data"][0]["mode"] == "corrupt"
        assert out["data"][0]["seed"] == 9
        status, out = _request(server.port, "DELETE", "/lighthouse/faults")
        assert status == 200 and out["data"]["cleared"] == 1

    def test_bad_plans_are_400(self, faults_api):
        _, server = faults_api
        for body in (
            {"spec": "unknown.point=error"},
            {"point": "device.dispatch", "mode": "explode"},
            {},
        ):
            status, _ = _request(
                server.port, "POST", "/lighthouse/faults", body=body)
            assert status == 400, body

    def test_delete_with_non_numeric_id_is_400(self, faults_api):
        _, server = faults_api
        status, _ = _request(server.port, "DELETE", "/lighthouse/faults?id=abc")
        assert status == 400


# -------------------------------------------------- non-device fault points


class TestStoreWriteFault:
    def test_block_import_fails_then_recovers(self):
        from lighthouse_tpu.chain import BeaconChainHarness

        harness = BeaconChainHarness(validator_count=8, fake_crypto=True)
        harness.extend_chain(1, attest=False)
        fi.install("store.write", "error", first_n=1)
        harness.advance_slot()
        signed = harness.produce_signed_block()
        with pytest.raises(fi.InjectedFault):
            harness.chain.process_block(signed)
        # The fault plan is exhausted; the block was never marked observed
        # (that happens after the store write), so re-importing it lands it
        # in the store and the chain keeps extending.
        harness.chain.process_block(signed)
        roots = harness.extend_chain(1, attest=False)
        assert harness.chain.head_root == roots[-1]


class TestEngineRequestFault:
    def test_engine_flips_offline_and_recovers(self):
        from lighthouse_tpu.execution_layer.engines import (
            STATE_OFFLINE, STATE_ONLINE, Engine, EngineOffline,
        )

        class FakeApi:
            url = "http://fake:8551"

            def exchange_capabilities(self):
                return ["engine_newPayloadV3"]

        eng = Engine(FakeApi(), upcheck_cooldown=0.0)
        assert eng.request(lambda api: "ok") == "ok"
        assert eng.state == STATE_ONLINE

        fi.install("engine.request", "error", first_n=1)
        with pytest.raises(EngineOffline):
            eng.request(lambda api: "ok")
        assert eng.state == STATE_OFFLINE
        # recovery through the normal upcheck machinery (cooldown=0)
        assert eng.request(lambda api: "ok") == "ok"
        assert eng.state == STATE_ONLINE


class TestSignerRequestFault:
    def test_sign_retries_once_on_connection_error(self):
        from lighthouse_tpu.crypto.bls import api as bls
        from lighthouse_tpu.validator_client.web3signer import (
            MockWeb3Signer, Web3SignerClient,
        )

        sk = bls.SecretKey.random()
        signer = MockWeb3Signer([sk]).start()
        try:
            client = Web3SignerClient(signer.url, backoff_s=0.01)
            before = metrics.WEB3SIGNER_RETRIES.get(kind="sign")
            fi.install("signer.request", "error", first_n=1)
            root = b"\x22" * 32
            sig = client.sign(sk.public_key().to_bytes(), root)
            assert sig == sk.sign(root).to_bytes()
            assert metrics.WEB3SIGNER_RETRIES.get(kind="sign") == before + 1
        finally:
            signer.stop()

    def test_sign_fails_after_retries_exhausted(self):
        from lighthouse_tpu.validator_client.web3signer import (
            Web3SignerClient, Web3SignerError,
        )

        client = Web3SignerClient("http://127.0.0.1:9", timeout=0.2,
                                  backoff_s=0.01)
        fi.install("signer.request", "error")  # every attempt
        with pytest.raises(Web3SignerError, match="unreachable"):
            client.sign(b"\x01" * 48, b"\x02" * 32)
