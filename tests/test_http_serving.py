"""The serving performance layer (ISSUE 14): checkpoint-keyed response
caching with event-driven invalidation, prioritized admission/shedding in
front of the scheduler, SSE backpressure, and the arbiter contention of
cache-miss API state work.

Correctness contract under test: a cached server must be *bit-identical*
to an uncached one at every point in chain history — including across a
reorg — and a head/finalization event must invalidate exactly the affected
``(head, finalized)`` keys.
"""

import http.client
import json
import threading
import time

import pytest

from lighthouse_tpu import device_pipeline, metrics
from lighthouse_tpu.chain import BeaconChainHarness
from lighthouse_tpu.chain.events import EventBus
from lighthouse_tpu.crypto.bls.backends import set_backend
from lighthouse_tpu.http_api import HttpApiServer
from lighthouse_tpu.http_api.server import CACHED_ROUTES
from lighthouse_tpu.http_api.response_cache import VALID_INVALIDATION_TOPICS
from lighthouse_tpu.scheduler import (
    AdmissionController,
    BeaconProcessor,
    ClassPolicy,
    ShedError,
)
from lighthouse_tpu.scheduler.admission import (
    CLASS_BULK,
    CLASS_CRITICAL,
    CLASS_DUTIES,
)


def _get(port: int, path: str, method: str = "GET", body=None):
    """Raw request -> (status, headers, body bytes) — byte-exact compares
    need the wire bytes, not the client's parsed view."""
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    payload = None if body is None else json.dumps(body)
    headers = {"Content-Type": "application/json"} if payload else {}
    conn.request(method, path, body=payload, headers=headers)
    resp = conn.getresponse()
    data = resp.read()
    out = (resp.status, dict(resp.getheaders()), data)
    conn.close()
    return out


#: Deterministic hot-route probe: duties, state queries, rewards, headers.
def _probe_requests(epoch: int):
    return [
        ("GET", f"/eth/v1/validator/duties/proposer/{epoch}", None),
        ("POST", f"/eth/v1/validator/duties/attester/{epoch}",
         [str(i) for i in range(16)]),
        ("GET", "/eth/v1/beacon/states/head/validators", None),
        ("GET", "/eth/v1/beacon/states/head/validator_balances?id=0,1,2", None),
        ("GET", "/eth/v1/beacon/states/head/finality_checkpoints", None),
        ("GET", "/eth/v1/beacon/states/head/root", None),
        ("GET", "/eth/v1/beacon/headers", None),
        ("GET", "/eth/v1/beacon/headers/head", None),
        ("GET", "/eth/v1/debug/beacon/heads", None),
        ("GET", "/eth/v1/beacon/rewards/blocks/head", None),
    ]


@pytest.fixture()
def harness():
    set_backend("fake")
    h = BeaconChainHarness(validator_count=16, fake_crypto=True)
    h.extend_chain(4)
    yield h
    set_backend("host")


@pytest.fixture()
def served_pair(harness):
    """One chain, two servers: cached and uncached — the bit-identity
    oracle."""
    processor = BeaconProcessor(max_workers=2)
    cached = HttpApiServer(harness.chain, processor=processor).start()
    uncached = HttpApiServer(harness.chain, response_cache=False).start()
    yield harness, cached, uncached
    cached.stop()
    uncached.stop()
    processor.shutdown()


class TestAutotuneSurface:
    def test_lighthouse_autotune_route(self, served_pair):
        """GET /lighthouse/autotune: the self-tuning snapshot plus the
        live admission state — the operator's one-read triage surface
        (ISSUE 15)."""
        # registration mirrors module imports — pull the ops in so every
        # tunable vocabulary is visible on the surface
        from lighthouse_tpu.ops import epoch_device  # noqa: F401
        from lighthouse_tpu.ops import sha256_device  # noqa: F401
        from lighthouse_tpu.ops import verify  # noqa: F401

        _, cached, _ = served_pair
        status, _, body = _get(cached.port, "/lighthouse/autotune")
        assert status == 200
        data = json.loads(body)["data"]
        assert data["mode"] in ("0", "pinned", "live")
        # the registered ops' vocabularies are all visible, static+overlay
        for vocab in ("bls_verify", "sha256_pairs", "epoch_deltas"):
            v = data["vocabularies"][vocab]
            assert v["static"] and set(v["effective"]) >= set(v["static"])
        adm = data["admission"]
        assert set(adm["effective"]) == {CLASS_CRITICAL, CLASS_DUTIES,
                                         CLASS_BULK}
        for klass, eff in adm["effective"].items():
            assert eff["max_inflight"] <= adm["bounds"][klass]
            assert eff["deadline_s"] <= adm["deadlines_s"][klass]


class TestResponseCache:
    def test_hit_is_bit_identical_and_counted(self, served_pair):
        harness, cached, uncached = served_pair
        epoch = harness.chain.current_slot() // harness.spec.slots_per_epoch
        for method, path, body in _probe_requests(epoch):
            s1, _, b1 = _get(cached.port, path, method, body)   # miss
            s2, _, b2 = _get(cached.port, path, method, body)   # hit
            s3, _, b3 = _get(uncached.port, path, method, body)  # oracle
            assert s1 == s2 == s3 == 200, path
            assert b1 == b2, f"cached replay differs: {path}"
            assert b1 == b3, f"cached vs uncached differ: {path}"
        snap = cached.response_cache.snapshot()
        assert snap["hits"] >= len(_probe_requests(epoch))
        assert snap["misses"] >= len(_probe_requests(epoch))
        assert uncached.response_cache is None

    def test_head_event_invalidates_exactly_stale_keys(self, served_pair):
        harness, cached, _ = served_pair
        cache = cached.response_cache
        epoch = harness.chain.current_slot() // harness.spec.slots_per_epoch
        for method, path, body in _probe_requests(epoch):
            _get(cached.port, path, method, body)
        old_fp = cache.fingerprint()
        assert len(cache) > 0
        assert all(k[0] == old_fp for k in cache.keys_snapshot())

        # Seed one entry under the CURRENT fingerprint *after* the head
        # moves, then fire another head event: only dead-fingerprint keys
        # may be dropped.
        harness.extend_chain(1)  # publishes a head event
        new_fp = cache.fingerprint()
        assert new_fp != old_fp
        # every old-head key is gone (exact invalidation)
        assert all(k[0] != old_fp for k in cache.keys_snapshot())
        inval_after_first = cache.invalidated
        assert inval_after_first > 0

        _get(cached.port, "/eth/v1/beacon/states/head/root")  # repopulate
        fresh_keys = [k for k in cache.keys_snapshot() if k[0] == new_fp]
        assert fresh_keys
        # a head event that does NOT change the fingerprint must keep them
        harness.chain.events.publish("head", {"slot": "0"})
        kept = [k for k in cache.keys_snapshot() if k[0] == new_fp]
        assert kept == fresh_keys, "same-fingerprint keys must survive"

    def test_stale_read_across_reorg(self, served_pair):
        """Bit-identical vs the uncached oracle before AND after a reorg —
        the cached server must never serve the abandoned branch."""
        harness, cached, uncached = served_pair
        chain = harness.chain
        roots = harness.extend_chain(2, attest=False)
        harness.advance_slot()
        slot = chain.current_slot()
        canonical = harness.produce_signed_block(slot=slot)
        fork_block = harness.produce_signed_block(
            slot=slot, parent_root=roots[0], graffiti=b"\x42" * 32)

        c_root = chain.process_block(canonical, block_delay_seconds=1.0)
        assert chain.head_root == c_root
        probe = [
            ("GET", "/eth/v1/beacon/states/head/root", None),
            ("GET", "/eth/v1/beacon/headers/head", None),
            ("GET", "/eth/v1/debug/beacon/heads", None),
        ]
        before = [_get(cached.port, p, m, b)[2] for m, p, b in probe]
        assert before == [_get(uncached.port, p, m, b)[2] for m, p, b in probe]

        # competing import; whether or not the head flips, the cached
        # server must track the uncached one exactly
        inval_before = cached.response_cache.invalidated
        chain.process_block(fork_block, block_delay_seconds=20.0)
        after_cached = [_get(cached.port, p, m, b)[2] for m, p, b in probe]
        after_uncached = [_get(uncached.port, p, m, b)[2] for m, p, b in probe]
        assert after_cached == after_uncached
        # the import's block event fired invalidation (at minimum the
        # block-sensitive debug-heads entry is re-derived, not replayed)
        assert cached.response_cache.invalidated > inval_before

    def test_put_refused_after_invalidation_event(self, served_pair):
        """The mid-handler reorg guard: an entry computed while ANY
        invalidation event fired must not be stored (an A->B->A reorg
        passes the fingerprint equality check but not the generation
        check)."""
        from lighthouse_tpu.http_api.response_cache import CacheEntry

        _, cached, _ = served_pair
        cache = cached.response_cache
        key = cache.make_key("GET", "/probe", {}, {}, None, False)
        entry = lambda: CacheEntry("json", b"{}", None, (), key[0], ("head",))  # noqa: E731
        gen = cache.generation
        cache.on_event("head", {})  # same fingerprint, but an event fired
        assert not cache.put(key, "/probe", entry(), generation=gen)
        assert cache.put(key, "/probe", entry(), generation=cache.generation)

    def test_cache_miss_contends_at_device_arbiter(self, served_pair):
        harness, cached, _ = served_pair
        grants_before = device_pipeline.ARBITER.snapshot()["grants"].get(
            "http_api", 0)
        _get(cached.port, "/eth/v1/beacon/states/head/validators")
        grants_after = device_pipeline.ARBITER.snapshot()["grants"].get(
            "http_api", 0)
        assert grants_after > grants_before

    def test_every_cached_route_declares_valid_topics(self):
        assert CACHED_ROUTES, "cache registry must not be empty"
        for (method, pattern), topics in CACHED_ROUTES.items():
            assert topics, f"{method} {pattern}: empty invalidation topics"
            bad = set(topics) - set(VALID_INVALIDATION_TOPICS)
            assert not bad, f"{method} {pattern}: unknown topics {bad}"
            # every cached route must prune on head movement at minimum
            assert "head" in topics, f"{method} {pattern}: missing 'head'"

    def test_duties_ride_their_own_queue(self, served_pair):
        harness, cached, _ = served_pair
        processor = cached.spawner.processor
        epoch = harness.chain.current_slot() // harness.spec.slots_per_epoch
        cached.response_cache.clear()
        _get(cached.port, f"/eth/v1/validator/duties/proposer/{epoch}")
        assert processor.metrics.received.get("api_request_duties", 0) >= 1


class TestAdmission:
    def test_admission_full_sheds_503_with_retry_after(self, harness):
        admission = AdmissionController([
            ClassPolicy(CLASS_CRITICAL, 64, 8.0, 1),
            ClassPolicy(CLASS_DUTIES, 64, 4.0, 2),
            ClassPolicy(CLASS_BULK, 0, 2.0, 5),  # shed every bulk request
        ])
        server = HttpApiServer(harness.chain, admission=admission,
                               response_cache=False).start()
        try:
            status, headers, body = _get(server.port, "/lighthouse/health")
            assert status == 503
            assert headers.get("Retry-After") == "5"
            assert b"overloaded" in body
            # critical traffic is untouched by the bulk bound
            status, _, _ = _get(
                server.port,
                "/eth/v1/validator/attestation_data?slot=1&committee_index=0")
            assert status != 503
        finally:
            server.stop()
        snap = admission.snapshot()
        assert snap["shed_total"] >= 1
        from lighthouse_tpu.scheduler.admission import HTTP_REQUESTS_SHED

        assert HTTP_REQUESTS_SHED.get(**{"class": CLASS_BULK,
                                         "reason": "admission_full"}) >= 1

    def test_deadline_shed_at_dequeue(self):
        admission = AdmissionController([ClassPolicy(CLASS_BULK, 8, 0.0, 5)])
        ticket = admission.try_admit(CLASS_BULK)
        time.sleep(0.01)
        with pytest.raises(ShedError) as e:
            ticket.check_deadline()
        assert e.value.reason == "deadline"
        ticket.release()
        snap = admission.snapshot()
        assert snap["inflight"][CLASS_BULK] == 0
        assert snap["shed_total"] == 1  # deadline sheds count too

    def test_inflight_accounting_releases(self):
        admission = AdmissionController([ClassPolicy(CLASS_BULK, 2, 5.0, 5)])
        t1 = admission.try_admit(CLASS_BULK)
        t2 = admission.try_admit(CLASS_BULK)
        with pytest.raises(ShedError):
            admission.try_admit(CLASS_BULK)
        t1.release()
        t3 = admission.try_admit(CLASS_BULK)
        t2.release()
        t3.release()
        assert admission.snapshot()["inflight"][CLASS_BULK] == 0

    def test_drop_policy_generalizes_drop_during_sync(self):
        from lighthouse_tpu.scheduler import DropPolicy, W, WorkEvent

        class DropEverything(DropPolicy):
            def should_drop(self, event):
                return "test"

        processor = BeaconProcessor(max_workers=1, drop_policy=DropEverything())
        try:
            ran = threading.Event()
            accepted = processor.send(WorkEvent(
                work_type=W.GOSSIP_ATTESTATION, process=lambda _i: ran.set()))
            assert not accepted
            # a custom policy's drop lands on the GENERIC dropped counter —
            # dropped_during_sync stays reserved for the "syncing" reason
            assert processor.metrics.dropped.get(W.GOSSIP_ATTESTATION) == 1
            assert processor.metrics.dropped_during_sync.get(
                W.GOSSIP_ATTESTATION, 0) == 0
            assert not ran.wait(0.1)
        finally:
            processor.shutdown()


class TestSseBackpressure:
    def test_slow_subscriber_drops_without_blocking(self):
        bus = EventBus()
        sub = bus.subscribe(["head"])
        before = metrics.SSE_EVENTS_DROPPED.get(topic="head")
        t0 = time.perf_counter()
        for i in range(sub.q.maxsize + 50):
            bus.publish("head", {"slot": str(i)})
        elapsed = time.perf_counter() - t0
        # non-blocking: hundreds of publishes against a wedged subscriber
        # finish in well under a second
        assert elapsed < 1.0
        assert sub.q.qsize() == sub.q.maxsize  # bounded, not unbounded
        assert sub.dropped == 50
        assert metrics.SSE_EVENTS_DROPPED.get(topic="head") == before + 50
        bus.unsubscribe(sub)

    def test_drop_counter_is_the_required_serving_metric(self):
        assert metrics.SSE_EVENTS_DROPPED.name == "http_sse_events_dropped_total"
        assert metrics.SSE_EVENTS_SENT.name == "http_sse_events_sent_total"
