"""Electra fork tests: deneb→electra boundary, EIP-7549 attestations through
the full chain, EIP-7251 consolidations/maxEB, EIP-7002 withdrawal requests,
EIP-6110 pending deposits (VERDICT r1 item 7)."""

import pytest

from lighthouse_tpu.chain import BeaconChainHarness
from lighthouse_tpu.consensus import electra as el
from lighthouse_tpu.consensus import helpers as h
from lighthouse_tpu.crypto.bls.backends import set_backend
from lighthouse_tpu.types.spec import FAR_FUTURE_EPOCH, minimal_spec


@pytest.fixture(autouse=True)
def _fake_backend():
    set_backend("fake")
    yield
    set_backend("host")


def electra_harness(**spec_overrides):
    spec = minimal_spec(
        altair_fork_epoch=0, bellatrix_fork_epoch=0, capella_fork_epoch=0,
        deneb_fork_epoch=0, electra_fork_epoch=0, **spec_overrides,
    )
    return BeaconChainHarness(validator_count=16, spec=spec, fake_crypto=True)


def test_genesis_on_electra_and_finalization():
    """A chain born on electra finalizes under EIP-7549 attestations."""
    harness = electra_harness()
    state = harness.chain.head_state
    assert type(state).fork_name == "electra"
    assert int(state.deposit_requests_start_index) > 0  # UNSET sentinel
    harness.extend_chain(harness.spec.slots_per_epoch * 5)
    assert harness.finalized_epoch() >= 2, "electra chain must finalize"


def test_deneb_to_electra_boundary():
    """Cross the fork mid-chain: state upgrades, blocks switch container,
    finalization continues."""
    spec = minimal_spec(
        altair_fork_epoch=0, bellatrix_fork_epoch=0, capella_fork_epoch=0,
        deneb_fork_epoch=0, electra_fork_epoch=2,
    )
    harness = BeaconChainHarness(validator_count=16, spec=spec, fake_crypto=True)
    harness.extend_chain(spec.slots_per_epoch)  # epoch 0->1, still deneb
    assert type(harness.head_state).fork_name == "deneb"
    harness.extend_chain(spec.slots_per_epoch * 3)
    assert type(harness.head_state).fork_name == "electra"
    st = harness.head_state
    assert hasattr(st, "pending_deposits")
    assert int(st.earliest_exit_epoch) >= 2
    harness.extend_chain(spec.slots_per_epoch * 2)
    assert harness.finalized_epoch() >= 2, "finalization must survive the fork"


def test_electra_attestation_indexing():
    """get_indexed_attestation resolves committee_bits spans correctly."""
    harness = electra_harness()
    harness.extend_chain(2)
    chain = harness.chain
    slot = chain.current_slot()
    state, _ = chain.state_at_slot(slot)
    spec = harness.spec
    committees = h.get_committee_count_per_slot(
        state, h.compute_epoch_at_slot(slot, spec), spec
    )
    data = chain.produce_attestation_data(slot, 0)
    assert int(data.index) == 0
    # attestation spanning ALL committees of the slot
    bits = []
    expected = []
    for ci in range(committees):
        committee = h.get_beacon_committee(state, slot, ci, spec)
        bits.extend([True] * len(committee))
        expected.extend(int(v) for v in committee)
    committee_bits = [i < committees for i in range(spec.preset.max_committees_per_slot)]
    att = harness.types.AttestationElectra(
        aggregation_bits=bits, data=data, signature=b"\xc0" + b"\x00" * 95,
        committee_bits=committee_bits,
    )
    indexed = h.get_indexed_attestation(state, att, harness.types, spec)
    assert list(indexed.attesting_indices) == sorted(set(expected))


def test_withdrawal_request_full_exit():
    harness = electra_harness(shard_committee_period=0)
    harness.extend_chain(1)
    chain = harness.chain
    state = chain.head_state.copy()
    v = state.validators[3]
    # give it execution (0x01) credentials so the EL can direct an exit
    addr = bytes(range(20))
    v.withdrawal_credentials = b"\x01" + b"\x00" * 11 + addr
    req = harness.types.WithdrawalRequest(
        source_address=addr, validator_pubkey=bytes(v.pubkey),
        amount=harness.spec.full_exit_request_amount,
    )
    assert v.exit_epoch == FAR_FUTURE_EPOCH
    el.process_withdrawal_request(state, req, harness.types, harness.spec)
    assert state.validators[3].exit_epoch != FAR_FUTURE_EPOCH

    # wrong source address is silently dropped
    state2 = chain.head_state.copy()
    v2 = state2.validators[4]
    v2.withdrawal_credentials = b"\x01" + b"\x00" * 11 + addr
    bad = harness.types.WithdrawalRequest(
        source_address=b"\xff" * 20, validator_pubkey=bytes(v2.pubkey), amount=0
    )
    el.process_withdrawal_request(state2, bad, harness.types, harness.spec)
    assert state2.validators[4].exit_epoch == FAR_FUTURE_EPOCH


def test_withdrawal_request_partial():
    harness = electra_harness(shard_committee_period=0)
    harness.extend_chain(1)
    state = harness.chain.head_state.copy()
    spec = harness.spec
    addr = bytes(range(20))
    v = state.validators[5]
    v.withdrawal_credentials = spec.compounding_withdrawal_prefix + b"\x00" * 11 + addr
    state.balances[5] = spec.min_activation_balance + 7 * 10**9
    req = harness.types.WithdrawalRequest(
        source_address=addr, validator_pubkey=bytes(v.pubkey), amount=5 * 10**9
    )
    el.process_withdrawal_request(state, req, harness.types, spec)
    assert len(state.pending_partial_withdrawals) == 1
    w = state.pending_partial_withdrawals[0]
    assert int(w.validator_index) == 5 and int(w.amount) == 5 * 10**9
    # validator keeps FAR_FUTURE exit (partial, not full)
    assert state.validators[5].exit_epoch == FAR_FUTURE_EPOCH


def test_consolidation_switch_to_compounding():
    harness = electra_harness(shard_committee_period=0)
    harness.extend_chain(1)
    state = harness.chain.head_state.copy()
    spec = harness.spec
    addr = bytes(range(20))
    v = state.validators[6]
    v.withdrawal_credentials = b"\x01" + b"\x00" * 11 + addr
    state.balances[6] = spec.min_activation_balance + 3 * 10**9
    req = harness.types.ConsolidationRequest(
        source_address=addr,
        source_pubkey=bytes(v.pubkey),
        target_pubkey=bytes(v.pubkey),  # self => switch to compounding
    )
    el.process_consolidation_request(state, req, harness.types, spec)
    assert h.has_compounding_withdrawal_credential(state.validators[6], spec)
    # excess above 32 ETH banked as a pending deposit
    assert int(state.balances[6]) == spec.min_activation_balance
    assert len(state.pending_deposits) == 1
    assert int(state.pending_deposits[0].amount) == 3 * 10**9


def test_pending_deposit_flow():
    """A deposit request parks in the queue and is applied at the epoch
    boundary once its slot is finalized."""
    harness = electra_harness()
    harness.extend_chain(1)
    state = harness.chain.head_state.copy()
    spec, types = harness.spec, harness.types
    # top-up for an EXISTING validator skips signature checks entirely
    pk0 = bytes(state.validators[0].pubkey)
    req = types.DepositRequest(
        pubkey=pk0, withdrawal_credentials=bytes(state.validators[0].withdrawal_credentials),
        amount=10**9, signature=b"\x00" * 96, index=0,
    )
    el.process_deposit_request(state, req, types, spec)
    assert int(state.deposit_requests_start_index) == 0  # first request pins it
    assert len(state.pending_deposits) == 1

    bal_before = int(state.balances[0])
    # eth1 bridge drained + deposit's slot finalized -> processed this epoch
    state.eth1_deposit_index = state.deposit_requests_start_index
    state.finalized_checkpoint = types.Checkpoint(
        epoch=h.get_current_epoch(state, spec) + 1,  # deposit's slot finalized
        root=b"\x00" * 32,
    )
    el.process_pending_deposits(state, types, spec)
    assert len(state.pending_deposits) == 0
    assert int(state.balances[0]) == bal_before + 10**9


def test_effective_balance_cap_compounding():
    """Compounding validators' effective balance rises past 32 ETH at the
    epoch update; eth1-credential validators stay capped."""
    harness = electra_harness()
    harness.extend_chain(1)
    state = harness.chain.head_state.copy()
    spec, types = harness.spec, harness.types
    state.validators[0].withdrawal_credentials = (
        spec.compounding_withdrawal_prefix + bytes(state.validators[0].withdrawal_credentials)[1:]
    )
    state.balances[0] = 100 * 10**9
    state.balances[1] = 100 * 10**9  # bls-credential validator
    from lighthouse_tpu.consensus.per_epoch import (
        EpochArrays,
        _process_effective_balance_updates,
    )

    _process_effective_balance_updates(state, EpochArrays(state, spec), spec)
    assert int(state.validators[0].effective_balance) == 100 * 10**9
    assert int(state.validators[1].effective_balance) == spec.min_activation_balance


def test_exit_churn_is_balance_weighted():
    """A 2048-ETH exit consumes many epochs of churn (EIP-7251)."""
    harness = electra_harness(shard_committee_period=0)
    harness.extend_chain(1)
    state = harness.chain.head_state.copy()
    spec = harness.spec
    state.validators[2].effective_balance = 2048 * 10**9
    h.initiate_validator_exit(state, 2, spec)
    whale_exit = int(state.validators[2].exit_epoch)
    state.validators[3].effective_balance = 32 * 10**9
    h.initiate_validator_exit(state, 3, spec)
    assert int(state.validators[3].exit_epoch) >= whale_exit
    assert whale_exit > h.compute_activation_exit_epoch(
        h.get_current_epoch(state, spec), spec
    ), "a whale exit must push past the base activation-exit epoch"
