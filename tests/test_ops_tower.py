"""JAX tower arithmetic vs the host golden model (exact)."""

import random

import jax
import jax.numpy as jnp
import numpy as np

from lighthouse_tpu.crypto.bls import fields as hf
from lighthouse_tpu.crypto.bls.params import P
from lighthouse_tpu.ops import tower as tw

rng = random.Random(0xA11CE)


def rand_fq2():
    return hf.Fq2(rng.randrange(P), rng.randrange(P))


def rand_fq6():
    return hf.Fq6(rand_fq2(), rand_fq2(), rand_fq2())


def rand_fq12():
    return hf.Fq12(rand_fq6(), rand_fq6())


def j2(x):
    return jnp.asarray(tw.fq2_to_limbs(x))


def j12(x):
    return jnp.asarray(tw.fq12_to_limbs(x))


def test_fq2_ops():
    a, b = rand_fq2(), rand_fq2()
    assert tw.fq2_from_limbs(jax.jit(tw.fq2_mul)(j2(a), j2(b))) == a * b
    assert tw.fq2_from_limbs(jax.jit(tw.fq2_square)(j2(a))) == a.square()
    assert tw.fq2_from_limbs(jax.jit(tw.fq2_mul_by_xi)(j2(a))) == a.mul_by_xi()
    assert tw.fq2_from_limbs(jax.jit(tw.fq2_inv)(j2(a))) == a.inv()
    assert tw.fq2_from_limbs(j2(a) - j2(b)) == a - b


def test_fq6_ops():
    a, b = rand_fq6(), rand_fq6()
    ja = jnp.asarray(tw.fq6_to_limbs(a))
    jb = jnp.asarray(tw.fq6_to_limbs(b))
    assert tw.fq6_from_limbs(jax.jit(tw.fq6_mul)(ja, jb)) == a * b
    assert tw.fq6_from_limbs(jax.jit(tw.fq6_mul_by_v)(ja)) == a.mul_by_v()
    assert tw.fq6_from_limbs(jax.jit(tw.fq6_inv)(ja)) == a.inv()


def test_fq12_ops():
    a, b = rand_fq12(), rand_fq12()
    assert tw.fq12_from_limbs(jax.jit(tw.fq12_mul)(j12(a), j12(b))) == a * b
    assert tw.fq12_from_limbs(jax.jit(tw.fq12_square)(j12(a))) == a.square()
    assert tw.fq12_from_limbs(jax.jit(tw.fq12_conj)(j12(a))) == a.conj()
    assert tw.fq12_from_limbs(jax.jit(tw.fq12_inv)(j12(a))) == a.inv()


def test_fq12_frobenius():
    a = rand_fq12()
    fr = jax.jit(tw.fq12_frobenius)
    assert tw.fq12_from_limbs(fr(j12(a))) == a.frobenius()
    assert tw.fq12_from_limbs(fr(fr(j12(a)))) == a.frobenius_n(2)


def test_batched_mul():
    avs = [rand_fq12() for _ in range(4)]
    bvs = [rand_fq12() for _ in range(4)]
    a = jnp.stack([j12(x) for x in avs])
    b = jnp.stack([j12(x) for x in bvs])
    r = np.asarray(jax.jit(tw.fq12_mul)(a, b))
    for i in range(4):
        assert tw.fq12_from_limbs(r[i]) == avs[i] * bvs[i]
