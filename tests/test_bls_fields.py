"""Field tower + curve-parameter sanity (the trust anchor for everything else).

Mirrors what the reference gets for free from blst's own test suite plus the EF
BLS vectors (testing/ef_tests/src/cases/bls_*.rs): since the spec tarballs are
unavailable offline, these tests establish correctness from mathematical
invariants (group laws, bilinearity, characteristic equations) instead.
"""

import random

import pytest

from lighthouse_tpu.crypto.bls import curve, params
from lighthouse_tpu.crypto.bls.fields import GAMMA, Fq, Fq2, Fq6, Fq12
from lighthouse_tpu.crypto.bls.pairing import (
    final_exponentiation,
    miller_loop,
    multi_pairing_is_one,
    pairing,
)
from lighthouse_tpu.crypto.bls.params import P, R, X

rng = random.Random(0xB15)


def rand_fq():
    return Fq(rng.randrange(P))

def rand_fq2():
    return Fq2(rng.randrange(P), rng.randrange(P))

def rand_fq6():
    return Fq6(rand_fq2(), rand_fq2(), rand_fq2())

def rand_fq12():
    return Fq12(rand_fq6(), rand_fq6())


def test_params_consistency():
    t = X + 1  # trace of Frobenius
    n1 = P + 1 - t
    assert n1 == params.H1 * R, "G1 cofactor relation"
    # Twist order: #E'(Fp2) must equal h2 * r.  Verify by annihilating a random
    # twist point (found by x-coordinate search, so not constructed inside G2).
    pt = _random_twist_point()
    assert curve.mul(pt, params.H2 * R) is None, "G2 cofactor relation h2*r kills the twist group"


def _random_twist_point():
    while True:
        x = Fq2(rng.randrange(P), rng.randrange(P))
        y = (x * x * x + curve.B2_FQ2).sqrt()
        if y is not None:
            return (x, y)


def test_field_axioms_fq2():
    for _ in range(20):
        a, b, c = rand_fq2(), rand_fq2(), rand_fq2()
        assert (a + b) * c == a * c + b * c
        assert a * b == b * a
        assert (a * b) * c == a * (b * c)
        assert a.square() == a * a
        if not a.is_zero():
            assert a * a.inv() == Fq2.one()


def test_fq2_sqrt():
    for _ in range(30):
        a = rand_fq2()
        sq = a.square()
        r = sq.sqrt()
        assert r is not None
        assert r.square() == sq
    # non-residue: xi has known QR status; count roots
    found_nonsquare = False
    for _ in range(30):
        a = rand_fq2()
        if not a.is_square():
            assert a.sqrt() is None
            found_nonsquare = True
    assert found_nonsquare


def test_field_axioms_fq6_fq12():
    for _ in range(10):
        a, b, c = rand_fq6(), rand_fq6(), rand_fq6()
        assert (a + b) * c == a * c + b * c
        assert (a * b) * c == a * (b * c)
        if not a.is_zero():
            assert a * a.inv() == Fq6.one()
    for _ in range(5):
        a, b = rand_fq12(), rand_fq12()
        assert a * b == b * a
        assert a * a.inv() == Fq12.one()
        # frobenius is the p-power map
        assert a.frobenius() == a.pow(P)


def test_fq12_tower_structure():
    w = Fq12.w()
    v6 = w * w  # should be v in Fq6 embedding
    assert v6 == Fq12(Fq6(Fq2.zero(), Fq2.one(), Fq2.zero()), Fq6.zero())
    # w^6 = xi
    w6 = w.pow(6)
    assert w6 == Fq12.from_fq2(Fq2(1, 1))


def test_generators_on_curve_and_in_subgroup():
    assert curve.is_on_curve(curve.G1, curve.B1_FQ)
    assert curve.is_on_curve(curve.G2, curve.B2_FQ2)
    assert curve.mul(curve.G1, R) is None
    assert curve.mul(curve.G2, R) is None
    # full-group orders
    assert curve.mul(curve.G1, params.H1 * R) is None


def test_group_laws():
    g = curve.G1
    for _ in range(5):
        a, b = rng.randrange(R), rng.randrange(R)
        pa, pb = curve.mul(g, a), curve.mul(g, b)
        assert curve.add(pa, pb) == curve.mul(g, (a + b) % R)
    h = curve.G2
    a, b = rng.randrange(R), rng.randrange(R)
    assert curve.add(curve.mul(h, a), curve.mul(h, b)) == curve.mul(h, (a + b) % R)
    # untwisted generator lies on E(Fp12)
    uq = curve.untwist(curve.G2)
    assert curve.is_on_curve(uq, curve.B12_FQ12)
    assert curve.is_on_curve(curve.embed_g1(curve.G1), curve.B12_FQ12)


def test_psi_endomorphism():
    # psi maps the twist to itself and satisfies the eigenvalue relation on G2.
    q = curve.mul(curve.G2, rng.randrange(1, R))
    pq = curve.psi(q)
    assert curve.is_on_curve(pq, curve.B2_FQ2)
    assert pq == curve.mul_by_x(q), "psi acts as [x] on G2"
    assert curve.in_g2(q)
    # characteristic polynomial psi^2 - [t]psi + [p] = 0 must hold on the WHOLE
    # twist group, so check it on a random twist point not constructed in G2.
    w = _random_twist_point()
    t = X + 1
    lhs = curve.add(curve.psi2(w), curve.neg(curve.mul(curve.psi(w), t)))
    lhs = curve.add(lhs, curve.mul(w, P))
    assert lhs is None
    # in_g2 (psi-eigenvalue check) must agree with the naive [r]P == O check on
    # twist points outside the subgroup (cofactor ~ 2^508, so w is outside whp).
    assert curve.in_g2(w) == (curve.mul(w, R) is None)
    assert not curve.in_g2(w)


def test_clear_cofactor_lands_in_g2():
    # take an arbitrary point on the twist (not in G2), clear cofactor, check G2.
    x = Fq2(rng.randrange(P), rng.randrange(P))
    while True:
        rhs = x * x * x + curve.B2_FQ2
        y = rhs.sqrt()
        if y is not None:
            break
        x = Fq2(rng.randrange(P), rng.randrange(P))
    pt = (x, y)
    assert curve.is_on_curve(pt, curve.B2_FQ2)
    cleared = curve.clear_cofactor_g2(pt)
    assert cleared is not None
    assert curve.in_g2(cleared)
    assert curve.mul(cleared, R) is None


def test_final_exp_identity():
    # 3*(p^4 - p^2 + 1)/r == (x-1)^2 (x+p) (x^2+p^2-1) + 3
    assert (P**4 - P**2 + 1) % R == 0
    hard = (P**4 - P**2 + 1) // R
    assert (X - 1) ** 2 * (X + P) * (X * X + P * P - 1) + 3 == 3 * hard


def test_final_exp_output_in_gt():
    f = rand_fq12()
    e = final_exponentiation(f)
    assert e.pow(R).is_one()
    # matches naive exponent (p^12-1)/r * 3
    naive = f.pow((P**12 - 1) // R * 3)
    assert e == naive


def test_pairing_bilinearity():
    g1, g2 = curve.G1, curve.G2
    e = pairing(g1, g2)
    assert not e.is_one()
    assert e.pow(R).is_one()
    a, b = rng.randrange(2, 2**30), rng.randrange(2, 2**30)
    e_ab = pairing(curve.mul(g1, a), curve.mul(g2, b))
    assert e_ab == e.pow(a * b)
    # e(P, Q1+Q2) = e(P,Q1) e(P,Q2)
    q1 = curve.mul(g2, 7)
    q2 = curve.mul(g2, 11)
    assert pairing(g1, curve.add(q1, q2)) == pairing(g1, q1) * pairing(g1, q2)


def test_multi_pairing_check():
    g1, g2 = curve.G1, curve.G2
    s = rng.randrange(2, R)
    # e(-g1, [s]g2) * e([s]g1, g2) == 1
    assert multi_pairing_is_one([
        (curve.neg(g1), curve.mul(g2, s)),
        (curve.mul(g1, s), g2),
    ])
    assert not multi_pairing_is_one([
        (curve.neg(g1), curve.mul(g2, s + 1)),
        (curve.mul(g1, s), g2),
    ])
    # infinity pairs contribute the identity
    assert multi_pairing_is_one([(None, g2), (g1, None)])
