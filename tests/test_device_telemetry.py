"""Device-telemetry tests (ISSUE 4): compile-cache counting (once per
shape, flat on re-invocation), occupancy math against hand-computed
batches, flight-recorder ring bounds + trace-id linkage, the
``/lighthouse/device*`` endpoint shapes, the profiler 501 path on CPU,
and the SSE sent/dropped satellite."""

import http.client
import json
import queue
import random

import numpy as np
import pytest

from lighthouse_tpu import device_telemetry, metrics, tracing
from lighthouse_tpu.crypto.bls import api

rng = random.Random(0xD37)


def make_set(msg: bytes, n_keys: int = 1):
    sks = [api.SecretKey.random() for _ in range(n_keys)]
    pks = [sk.public_key() for sk in sks]
    agg = api.AggregateSignature.infinity()
    for sk in sks:
        agg.add_assign(sk.sign(msg))
    return api.SignatureSet.multiple_pubkeys(agg, pks, msg)


# --------------------------------------------------------------- unit layer


class TestCompileCache:
    def test_counter_fires_once_per_shape_then_stays_flat(self):
        cache = device_telemetry.CompileCache()
        before = metrics.DEVICE_PROGRAM_COMPILES.get(op="test_cc", shape="4x2")
        assert cache.note_dispatch("test_cc", (4, 2), 1.5) is True
        assert cache.note_dispatch("test_cc", (4, 2), 0.001) is False
        assert cache.note_dispatch("test_cc", (4, 2), 0.001) is False
        assert metrics.DEVICE_PROGRAM_COMPILES.get(
            op="test_cc", shape="4x2") == before + 1
        # a different shape of the same op is its own program
        assert cache.note_dispatch("test_cc", (8, 2), 0.7) is True
        inv = {e["shape"]: e for e in cache.inventory()}
        assert inv["4x2"]["invocations"] == 3
        assert inv["4x2"]["compile_seconds"] == 1.5
        assert inv["8x2"]["invocations"] == 1

    def test_compile_seconds_histogram_fed_on_first_dispatch_only(self):
        cache = device_telemetry.CompileCache()
        n0 = metrics.DEVICE_PROGRAM_COMPILE_SECONDS.stats(op="test_hist")[0]
        cache.note_dispatch("test_hist", (1,), 2.0)
        cache.note_dispatch("test_hist", (1,), 2.0)
        n1, total = metrics.DEVICE_PROGRAM_COMPILE_SECONDS.stats(op="test_hist")
        assert n1 == n0 + 1 and total >= 2.0


class TestOccupancy:
    def test_hand_computed_batch(self):
        rec = device_telemetry.FlightRecorder(capacity=8)
        old_ring = device_telemetry.FLIGHT_RECORDER
        device_telemetry.FLIGHT_RECORDER = rec
        try:
            sets0 = metrics.DEVICE_BATCH_WASTED_LANES.get(op="test_occ", axis="sets")
            keys0 = metrics.DEVICE_BATCH_WASTED_LANES.get(op="test_occ", axis="keys")
            entry = device_telemetry.record_batch(
                op="test_occ", shape=(8, 4), n_live=5, live_keys=13,
            )
            # 5 live sets in an 8-bucket; 13 live keys across 8*4 lanes
            assert entry["occupancy_sets"] == pytest.approx(5 / 8)
            assert entry["occupancy_keys"] == pytest.approx(13 / 32, abs=1e-4)
            assert metrics.DEVICE_BATCH_WASTED_LANES.get(
                op="test_occ", axis="sets") == sets0 + 3
            assert metrics.DEVICE_BATCH_WASTED_LANES.get(
                op="test_occ", axis="keys") == keys0 + 19
        finally:
            device_telemetry.FLIGHT_RECORDER = old_ring

    def test_full_batch_is_unit_occupancy(self):
        entry = device_telemetry.record_batch(
            op="test_occ_full", shape=(4, 2), n_live=4, live_keys=8)
        assert entry["occupancy_sets"] == 1.0
        assert entry["occupancy_keys"] == 1.0


class TestFlightRecorder:
    def test_ring_is_bounded_and_newest_first(self):
        ring = device_telemetry.FlightRecorder(capacity=4)
        for i in range(6):
            ring.record({"op": "x", "i": i})
        assert len(ring) == 4
        assert ring.recorded_total == 6
        recent = ring.recent(limit=10)
        assert [r["i"] for r in recent] == [5, 4, 3, 2]
        assert [r["seq"] for r in recent] == [6, 5, 4, 3]

    def test_ring_size_env_configurable(self):
        """ISSUE 17 satellite: ``LIGHTHOUSE_TPU_FLIGHT_RING`` sizes the
        ring (long soaks grow it so pre-incident records survive to the
        postmortem bundle), with the legacy capacity name as fallback.
        The constant is read at import, so the probe runs in a child."""
        import os
        import subprocess
        import sys

        probe = (
            "from lighthouse_tpu import device_telemetry as dt\n"
            "assert dt.FLIGHT_RECORDER_CAPACITY == 32\n"
            "assert dt.FLIGHT_RECORDER.capacity == 32\n"
            "for i in range(100):\n"
            "    dt.record_batch(op='ring_probe', shape=(4,), n_live=2)\n"
            "assert len(dt.FLIGHT_RECORDER) == 32\n"
            "assert dt.FLIGHT_RECORDER.recorded_total == 100\n"
            "print('RING_OK')\n"
        )
        repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        res = subprocess.run(
            [sys.executable, "-c", probe], capture_output=True, text=True,
            cwd=repo_root, timeout=120,
            env={**os.environ, "LIGHTHOUSE_TPU_FLIGHT_RING": "32"})
        assert res.returncode == 0, res.stderr
        assert "RING_OK" in res.stdout
        # the legacy env name still works when the new one is absent
        env = {k: v for k, v in os.environ.items()
               if k != "LIGHTHOUSE_TPU_FLIGHT_RING"}
        env["LIGHTHOUSE_TPU_FLIGHT_RECORDER_CAPACITY"] = "16"
        res = subprocess.run(
            [sys.executable, "-c",
             "from lighthouse_tpu import device_telemetry as dt\n"
             "assert dt.FLIGHT_RECORDER.capacity == 16\n"],
            capture_output=True, text=True, cwd=repo_root, timeout=120,
            env=env)
        assert res.returncode == 0, res.stderr

    def test_filters(self):
        ring = device_telemetry.FlightRecorder(capacity=8)
        ring.record({"op": "a", "trace_id": "t1"})
        ring.record({"op": "b", "trace_id": "t2"})
        ring.record({"op": "a", "trace_id": "t2"})
        assert [r["op"] for r in ring.recent(op="a")] == ["a", "a"]
        assert [r["op"] for r in ring.recent(trace_id="t2")] == ["a", "b"]

    def test_summary_percentiles(self):
        device_telemetry.reset_for_tests()
        for live in (2, 4, 8):
            device_telemetry.record_batch(op="test_pct", shape=(8,), n_live=live)
        s = device_telemetry.summary()
        occ = s["occupancy"]["test_pct"]["sets"]
        assert occ["n"] == 3
        assert occ["min"] == pytest.approx(0.25)
        assert occ["max"] == pytest.approx(1.0)
        assert s["flight_recorder"]["stored"] == 3
        assert isinstance(s["memory"], list)  # cpu devices listed, no stats

    def test_summary_percentiles_grouped_per_op(self):
        """An unpadded op at occupancy 1.0 must not dilute the padding-waste
        percentiles of a bucketed op."""
        device_telemetry.reset_for_tests()
        for _ in range(10):
            device_telemetry.record_batch(op="test_unpadded", shape=(4,), n_live=4)
        device_telemetry.record_batch(op="test_padded", shape=(8,), n_live=4)
        occ = device_telemetry.summary()["occupancy"]
        assert occ["test_unpadded"]["sets"]["p50"] == pytest.approx(1.0)
        assert occ["test_padded"]["sets"]["p50"] == pytest.approx(0.5)


# ---------------------------------------------------- device verify (real)


class TestVerifyIntegration:
    def test_compile_counted_once_per_bucket_shape(self):
        """Acceptance: a fresh bucket shape increments
        device_program_compiles_total exactly once; repeat calls do not."""
        from lighthouse_tpu.ops.verify import verify_signature_sets_device

        device_telemetry.reset_for_tests()
        before = metrics.DEVICE_PROGRAM_COMPILES.get(op="bls_verify", shape="1x1")
        s = make_set(b"telemetry-1")
        assert verify_signature_sets_device([s], seed=b"t") is True
        assert metrics.DEVICE_PROGRAM_COMPILES.get(
            op="bls_verify", shape="1x1") == before + 1
        assert verify_signature_sets_device([s], seed=b"t") is True
        assert metrics.DEVICE_PROGRAM_COMPILES.get(
            op="bls_verify", shape="1x1") == before + 1  # flat on re-invoke

        records = device_telemetry.FLIGHT_RECORDER.recent(op="bls_verify")
        assert len(records) >= 2
        newest, second = records[0], records[1]
        assert second["compiled"] is True and newest["compiled"] is False
        assert newest["verdict"] is True and newest["host_fallback"] is False
        assert newest["shape"] == "1x1" and newest["n_live"] == 1
        assert newest["occupancy_sets"] == 1.0 and newest["occupancy_keys"] == 1.0
        assert {"setup", "dispatch", "wait", "verdict"} <= set(newest["stages_s"])

    def test_trace_id_links_flight_record_to_trace_tree(self):
        """Acceptance: the flight-recorder entry carries the same trace id
        as the enclosing trace, and the device_verify span carries the
        record's seq (cross-reference in both directions)."""
        from lighthouse_tpu.crypto.bls.backends import jax_backend

        s = make_set(b"telemetry-linkage")
        with tracing.span("block_import", slot=77) as root:
            assert jax_backend.verify_signature_sets([s], seed=b"t") is True
        trace_id = root.trace.trace_id
        records = device_telemetry.FLIGHT_RECORDER.recent(trace_id=trace_id)
        assert len(records) == 1
        dv = next(c for c in root.children if c.name == "device_verify")
        assert dv.fields["flight_seq"] == records[0]["seq"]
        # and the trace is retrievable from the ring by that id
        assert tracing.TRACES.get(trace_id) is root.trace

    def test_w_at_infinity_host_fallback_is_counted_and_stamped(self, monkeypatch):
        from lighthouse_tpu.ops import verify as verify_mod

        # Force the W-at-infinity path: zero Z limbs out of the "device".
        fake_w_z = np.zeros((2, 25), np.int32)
        monkeypatch.setattr(
            verify_mod, "_device_verify",
            lambda *batch: (np.zeros((12, 25), np.int32), fake_w_z),
        )
        before = metrics.DEVICE_HOST_FALLBACK.get(reason="w_at_infinity")
        s = make_set(b"fallback")
        with tracing.span("fallback_root") as root:
            assert verify_mod.verify_signature_sets_device([s], seed=b"t") is True
        assert metrics.DEVICE_HOST_FALLBACK.get(
            reason="w_at_infinity") == before + 1
        rec = device_telemetry.FLIGHT_RECORDER.recent(op="bls_verify")[0]
        assert rec["host_fallback"] is True
        assert rec["fallback_reason"] == "w_at_infinity"
        assert rec["verdict"] is True  # the host re-verify decided
        assert device_telemetry.host_fallback_counts()["w_at_infinity"] >= 1
        # stamped on the trace: the verdict span carries the fallback flag
        verdicts = [sp for sp in _walk(root) if sp.name == "device_batch_verdict"]
        assert any(sp.fields.get("host_fallback") for sp in verdicts)
        assert root.fields.get("host_fallback") is True


def _walk(sp):
    yield sp
    for c in sp.children:
        yield from _walk(c)


# ------------------------------------------------------------------ HTTP API


@pytest.fixture(scope="module")
def device_api():
    from lighthouse_tpu.chain import BeaconChainHarness
    from lighthouse_tpu.crypto.bls.backends import set_backend
    from lighthouse_tpu.http_api import HttpApiServer

    set_backend("fake")
    harness = BeaconChainHarness(validator_count=8, fake_crypto=True)
    server = HttpApiServer(harness.chain).start()
    yield harness, server
    server.stop()
    set_backend("host")


def _request(port, method, path):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    try:
        conn.request(method, path)
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read())
    finally:
        conn.close()


class TestEndpoints:
    def test_device_summary_shape(self, device_api):
        device_telemetry.reset_for_tests()
        device_telemetry.note_dispatch("bls_verify", (8, 4), 1.25)
        device_telemetry.record_batch(
            op="bls_verify", shape=(8, 4), n_live=6, live_keys=20,
            trace_id="abc", compiled=True)
        _, server = device_api
        status, out = _request(server.port, "GET", "/lighthouse/device")
        assert status == 200
        data = out["data"]
        assert {"programs", "occupancy", "host_fallbacks",
                "flight_recorder", "memory"} <= set(data)
        prog = next(p for p in data["programs"] if p["shape"] == "8x4")
        assert prog["op"] == "bls_verify"
        assert prog["compile_seconds"] == 1.25
        assert data["occupancy"]["bls_verify"]["sets"]["max"] == pytest.approx(0.75)
        assert data["flight_recorder"]["capacity"] >= 1
        # cpu run: devices are listed, memory stats simply absent
        for dev in data["memory"]:
            assert {"id", "platform"} <= set(dev)

    def test_device_batches_listing_and_filters(self, device_api):
        _, server = device_api
        status, out = _request(
            server.port, "GET", "/lighthouse/device/batches?op=bls_verify&limit=5")
        assert status == 200
        assert out["data"], "flight recorder should have records"
        for rec in out["data"]:
            assert rec["op"] == "bls_verify"
            assert {"seq", "t_ms", "shape", "n_live"} <= set(rec)
        status, filtered = _request(
            server.port, "GET", "/lighthouse/device/batches?trace_id=abc")
        assert status == 200
        assert all(r["trace_id"] == "abc" for r in filtered["data"])
        status, _ = _request(
            server.port, "GET", "/lighthouse/device/batches?limit=junk")
        assert status == 400

    def test_profiler_501_on_cpu(self, device_api, monkeypatch):
        monkeypatch.delenv("LIGHTHOUSE_TPU_FORCE_PROFILER", raising=False)
        _, server = device_api
        status, out = _request(
            server.port, "POST", "/lighthouse/device/profile?seconds=1")
        assert status == 501
        assert "cpu" in out["message"]

    def test_profiler_bad_seconds(self, device_api):
        _, server = device_api
        status, _ = _request(
            server.port, "POST", "/lighthouse/device/profile?seconds=zero")
        assert status == 400
        status, _ = _request(
            server.port, "POST", "/lighthouse/device/profile?seconds=-3")
        assert status == 400

    def test_events_subscribers_summary(self, device_api):
        harness, server = device_api
        sub = harness.chain.events.subscribe(["head"])
        try:
            harness.chain.events.publish("head", {"slot": "1"})
            status, out = _request(
                server.port, "GET", "/lighthouse/events/subscribers")
            assert status == 200
            entry = next(e for e in out["data"] if e["topics"] == ["head"])
            assert entry["queue_depth"] == 1
            assert entry["dropped"] == 0
            assert {"sent", "queue_capacity", "dropped_by_topic"} <= set(entry)
        finally:
            harness.chain.events.unsubscribe(sub)


# ------------------------------------------------------------- SSE satellite


class TestSseDropAccounting:
    def test_publish_counts_drops_per_topic(self):
        from lighthouse_tpu.chain import events as ev

        bus = ev.EventBus()
        sub = bus.subscribe([ev.TOPIC_HEAD, ev.TOPIC_BLOCK])
        sub.q = queue.Queue(maxsize=1)  # shrink to force drops
        before = metrics.SSE_EVENTS_DROPPED.get(topic=ev.TOPIC_HEAD)
        bus.publish(ev.TOPIC_HEAD, {"slot": "1"})   # fills the queue
        bus.publish(ev.TOPIC_HEAD, {"slot": "2"})   # dropped
        bus.publish(ev.TOPIC_BLOCK, {"slot": "2"})  # dropped (shared queue)
        assert sub.dropped == 2
        assert sub.dropped_by_topic == {ev.TOPIC_HEAD: 1, ev.TOPIC_BLOCK: 1}
        assert metrics.SSE_EVENTS_DROPPED.get(topic=ev.TOPIC_HEAD) == before + 1
        summary = bus.summary()
        assert summary[0]["dropped"] == 2
        assert summary[0]["queue_depth"] == 1

    def test_sse_stream_counts_sent(self):
        """End to end: events written to a live /eth/v1/events stream bump
        http_sse_events_sent_total{topic} and the subscriber's sent
        figure."""
        from lighthouse_tpu.chain import BeaconChainHarness
        from lighthouse_tpu.crypto.bls.backends import set_backend
        from lighthouse_tpu.http_api import HttpApiServer

        set_backend("fake")
        harness = BeaconChainHarness(validator_count=8, fake_crypto=True)
        server = HttpApiServer(harness.chain).start()
        try:
            import time as _t

            before = metrics.SSE_EVENTS_SENT.get(topic="head")
            conn = http.client.HTTPConnection(
                "127.0.0.1", server.port, timeout=10)
            conn.request("GET", "/eth/v1/events?topics=head")
            resp = conn.getresponse()  # returns once headers are out, i.e.
            # after _serve_events subscribed — publishing now is safe
            harness.chain.events.publish("head", {"slot": "3", "block": "0x00"})
            buf = b""
            deadline = _t.time() + 5
            while b"\n\n" not in buf and _t.time() < deadline:
                chunk = resp.read1(4096)
                if not chunk:
                    break
                buf += chunk
            conn.close()
            assert b"event: head" in buf
            # the writer counted the delivery
            deadline = _t.time() + 3
            while (metrics.SSE_EVENTS_SENT.get(topic="head") == before
                   and _t.time() < deadline):
                _t.sleep(0.05)
            assert metrics.SSE_EVENTS_SENT.get(topic="head") == before + 1
        finally:
            server.stop()
            set_backend("host")
