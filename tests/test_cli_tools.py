"""Operator tooling CLI (reference ``database_manager/`` + ``lcli/``):
db version/inspect/compact and lcli root/ssz/skip-slot tools."""

import json
import os
import sys

import pytest

from lighthouse_tpu import cli
from lighthouse_tpu.chain import BeaconChainHarness
from lighthouse_tpu.crypto.bls.backends import set_backend


@pytest.fixture()
def state_file(tmp_path):
    set_backend("fake")
    harness = BeaconChainHarness(validator_count=8, fake_crypto=True)
    path = tmp_path / "state.ssz"
    state = harness.chain.head_state
    path.write_bytes(state.as_ssz_bytes())
    yield str(path), state, harness
    set_backend("host")


def test_lcli_state_root(state_file, capsys):
    path, state, harness = state_file
    fork = type(state).fork_name
    rc = cli.main(["lcli", "state-root", "--network", "minimal",
                   "--fork", fork, path])
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip())
    assert out["root"] == "0x" + state.hash_tree_root().hex()


def test_lcli_skip_slots(state_file, tmp_path, capsys):
    path, state, harness = state_file
    fork = type(state).fork_name
    out_path = str(tmp_path / "post.ssz")
    rc = cli.main(["lcli", "skip-slots", "--network", "minimal",
                   "--fork", fork, "--pre-state", path,
                   "--slots", "2", "--output", out_path])
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip())
    assert out["slots"] == 2
    from lighthouse_tpu.types.containers import build_types

    types = build_types(harness.spec.preset)
    post = types.state[fork].from_ssz_bytes(open(out_path, "rb").read())
    assert int(post.slot) == int(state.slot) + 2
    assert "0x" + post.hash_tree_root().hex() == out["state_root"]


def test_lcli_parse_ssz(state_file, capsys):
    path, state, harness = state_file
    block = harness.produce_signed_block(slot=harness.advance_slot())
    import tempfile

    with tempfile.NamedTemporaryFile(suffix=".ssz", delete=False) as f:
        f.write(block.message.body.eth1_data.as_ssz_bytes())
        p = f.name
    rc = cli.main(["lcli", "parse-ssz", "--network", "minimal", "Eth1Data", p])
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip())
    assert "deposit_root" in out


def test_db_manager_roundtrip(tmp_path, capsys):
    from lighthouse_tpu.store.kv import DBColumn
    from lighthouse_tpu.store.lockbox_store import LockboxStore

    datadir = tmp_path / "node"
    datadir.mkdir()
    store = LockboxStore(str(datadir / "chain.db"))
    import struct

    store.put(DBColumn.BEACON_META, b"schema", struct.pack(">Q", 1))
    store.put(DBColumn.BEACON_BLOCK, b"k" * 32, b"v")
    store.close()

    rc = cli.main(["db", "version", "--datadir", str(datadir)])
    assert rc == 0
    assert json.loads(capsys.readouterr().out.strip())["schema_version"] == 1

    rc = cli.main(["db", "inspect", "--datadir", str(datadir)])
    assert rc == 0
    counts = json.loads(capsys.readouterr().out.strip())["keys_per_column"]
    assert counts.get("BEACON_BLOCK") == 1

    rc = cli.main(["db", "compact", "--datadir", str(datadir)])
    assert rc == 0
    assert json.loads(capsys.readouterr().out.strip())["compacted"] is True


def test_db_prune_payloads_and_blobs(tmp_path, capsys):
    """`db prune-payloads` rewrites stored full blocks as blinded (payload
    reconstructible via the EL); `db prune-blobs` drops sidecars below the
    horizon.  Reference `lighthouse db prune-payloads` / `prune-blobs`."""
    from lighthouse_tpu.chain import BeaconChainHarness
    from lighthouse_tpu.crypto.bls.backends import set_backend
    from lighthouse_tpu.store.kv import DBColumn
    from lighthouse_tpu.store.lockbox_store import LockboxStore

    set_backend("fake")
    try:
        harness = BeaconChainHarness(validator_count=8, fake_crypto=True)
        harness.extend_chain(2)
        types = harness.chain.types
        datadir = tmp_path / "node"
        datadir.mkdir()
        store = LockboxStore(str(datadir / "chain.db"))
        # copy BOTH chain blocks into the on-disk db — multi-entry
        # prune/skip accounting must be exercised with more than one row
        head = harness.chain.get_block(harness.chain.head_root)
        parent = harness.chain.get_block(bytes(head.message.parent_root))
        n_blocks = 0
        for signed in (head, parent):
            fork = type(signed).fork_name
            store.put(DBColumn.BEACON_BLOCK, signed.message.hash_tree_root(),
                      fork.encode() + b"\x00" + signed.as_ssz_bytes())
            n_blocks += 1
        assert n_blocks == 2
        store.close()

        rc = cli.main(["db", "prune-payloads", "--datadir", str(datadir),
                       "--network", "minimal"])
        assert rc == 0
        out = json.loads(capsys.readouterr().out.strip())
        assert out["payloads_pruned"] == n_blocks

        # blinded on disk now; a second run skips them all
        store = LockboxStore(str(datadir / "chain.db"))
        raw = store.get(DBColumn.BEACON_BLOCK, harness.chain.head_root)
        assert raw.startswith(b"blinded:")
        fork = raw.split(b"\x00", 1)[0][len(b"blinded:"):].decode()
        blinded = types.signed_blinded_block[fork].from_ssz_bytes(
            raw.split(b"\x00", 1)[1])
        assert hasattr(blinded.message.body, "execution_payload_header")
        # a default sidecar (slot 0) sits below any positive horizon
        sc = types.BlobSidecar()
        store.put(DBColumn.BLOB_SIDECAR, b"r" * 32,
                  len(sc.as_ssz_bytes()).to_bytes(4, "big") + sc.as_ssz_bytes())
        store.close()
        rc = cli.main(["db", "prune-payloads", "--datadir", str(datadir),
                       "--network", "minimal"])
        assert rc == 0
        out = json.loads(capsys.readouterr().out.strip())
        assert out["payloads_pruned"] == 0 and out["skipped"] == n_blocks

        rc = cli.main(["db", "prune-blobs", "--datadir", str(datadir),
                       "--network", "minimal", "--before-slot", "100"])
        assert rc == 0
        out = json.loads(capsys.readouterr().out.strip())
        assert out["blob_sets_pruned"] == 1
    finally:
        set_backend("host")


def test_lcli_mock_el_serves_engine_api(tmp_path):
    """`lcli mock-el` runs a standalone fake EL a BN can connect to
    (reference `lcli mock-el`)."""
    import subprocess

    jwt_path = tmp_path / "jwt.hex"
    proc = subprocess.Popen(
        [sys.executable, "-m", "lighthouse_tpu", "lcli", "mock-el",
         "--jwt-output", str(jwt_path)],
        stdout=subprocess.PIPE, cwd=os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))),
    )
    try:
        line = proc.stdout.readline().decode()
        info = json.loads(line)
        assert info["endpoint"].startswith("http://127.0.0.1:")
        secret = bytes.fromhex(jwt_path.read_text().removeprefix("0x"))
        assert len(secret) == 32
        # a real engine-API exchange through the spawned process
        from lighthouse_tpu.execution_layer.engine_api import EngineApiClient
        client = EngineApiClient(info["endpoint"], secret)
        caps = client.exchange_capabilities()
        assert any("engine_newPayload" in c for c in caps)
    finally:
        proc.terminate()
        proc.wait(timeout=5)


def test_lcli_generate_bootnode_enr(tmp_path, capsys):
    """`lcli generate-bootnode-enr` mints a decodable signed ENR + key
    (reference lcli generate_bootnode_enr.rs)."""
    # ENR signing needs secp256k1 via the `cryptography` package, which some
    # CI containers don't ship — skip rather than fail on the environment.
    pytest.importorskip("cryptography",
                        reason="discv5 ENR signing needs the cryptography package")
    from lighthouse_tpu.network.discv5.enr import ENR

    out_dir = tmp_path / "bootnode"
    rc = cli.main(["lcli", "generate-bootnode-enr", "--ip", "10.1.2.3",
                   "--udp-port", "9000", "--tcp-port", "9001",
                   "--output-dir", str(out_dir)])
    assert rc == 0
    info = json.loads(capsys.readouterr().out.strip())
    enr = ENR.from_text((out_dir / "enr.dat").read_text())
    assert info["enr"] == enr.to_text()
    assert enr.ip() == "10.1.2.3" and enr.udp_port() == 9000
    key = (out_dir / "key").read_text()
    assert key.startswith("0x") and int(key, 16) > 0
    # refuses to clobber
    with pytest.raises(SystemExit):
        cli.main(["lcli", "generate-bootnode-enr", "--ip", "10.1.2.3",
                  "--udp-port", "9000", "--tcp-port", "9001",
                  "--output-dir", str(out_dir)])
