"""Deterministic multi-node scenario soak engine.

The adversarial half of the in-process simulator (``simulator.py`` mirrors
the reference's ``basic-sim``/``fallback-sim`` happy path; this module is
the reference's fault matrix grown past it): a declarative
:class:`Scenario` spec — seed, node/validator counts, a timeline of
:class:`Event`\\ s (partition/heal, kill/restart, checkpoint-sync join
under lossy links, spam/slow peers, device fault plans, byzantine actor
strategies via ``adversary.py``) — executed by
:class:`ScenarioRunner` on top of the :class:`~.network.transport.Hub`
fault fabric and the ``fault_injection`` registry, with **convergence
gates** at the end: every live node must agree on one head and finality
must advance strictly past its value at the end of the fault window.

Everything is seeded and deterministic: link-level fault decisions are a
pure function of ``(seed, directed link, message index)``
(``transport.LinkPlan``), timeline events fire at fixed window-relative
slots, and a node that restarts or joins is pumped to the fleet head
*before* slots resume so thread scheduling cannot change which blocks get
built.  Two runs with the same seed produce identical final head roots —
the slow test matrix asserts exactly that.

Every run writes a **SOAK JSON** artifact (analogous to BENCH JSON):
per-node convergence/finality evidence, slot-relative delay metric deltas
from the tracing layer's histograms, fabric fault counters plus the
per-link schedule digest, fault-injection plan hit counts, and device
circuit-breaker states.

Run the full matrix::

    python -m lighthouse_tpu.scenarios --seed 1

or one scenario with two determinism runs::

    python -m lighthouse_tpu.scenarios --scenario nonfinality_spell --runs 2
"""

from __future__ import annotations

import json
import os
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from . import blackbox, fault_injection, metrics, telemetry_scope, tracing
from .logs import get_logger
from .network.transport import LinkPlan
from .simulator import SimNode, Simulator
from .virtual_clock import VirtualClock, telemetry_stamp

log = get_logger("scenarios")

SCENARIO_RUNS = metrics.counter(
    "scenario_runs_total",
    "scenario soak runs, by scenario and outcome (passed|failed|error)",
)
SCENARIO_EVENTS = metrics.counter(
    "scenario_events_applied_total",
    "timeline events applied by the scenario runner, by action",
)
SOAK_LEAK_CHECKS = metrics.counter(
    "soak_leak_checks_total",
    "production-soak leak-gate evaluations, by gate and outcome "
    "(passed|failed)",
)

#: Envelope kinds that carry gossipsub traffic (vs the rpc_* stream) — the
#: usual target of lossy-link plans, so sync RPC stays merely slow.
GOSSIP_KINDS = frozenset(
    {"gossip", "ihave", "iwant", "graft", "prune", "subscribe", "unsubscribe"}
)

#: The slot-relative delay histograms (tracing layer, PR 2) sampled into
#: every SOAK artifact as before/after deltas.
DELAY_HISTOGRAMS = {
    "block_arrival": metrics.BLOCK_ARRIVAL_DELAY_SECONDS,
    "block_imported": metrics.BLOCK_IMPORTED_DELAY_SECONDS,
    "attestation_arrival": metrics.ATTESTATION_ARRIVAL_DELAY_SECONDS,
}


class ScenarioFailure(AssertionError):
    """A convergence gate (or a scenario's extra check) did not hold."""


@dataclass
class Event:
    """One timeline entry: ``action`` applied at ``at_slot`` (0-based,
    relative to the start of the fault window)."""

    at_slot: int
    action: str
    args: Dict[str, Any] = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {"at_slot": self.at_slot, "action": self.action,
                "args": self.args}


@dataclass
class Scenario:
    """Declarative scenario spec.  ``warmup_slots`` run with the happy-path
    convergence assert (the fabric is clean), ``fault_slots`` run the event
    timeline without it, ``recovery_slots`` run after every fault is
    cleared; then the gates fire."""

    name: str
    description: str = ""
    seed: int = 0
    node_count: int = 3
    validator_count: int = 16
    warmup_slots: int = 8
    fault_slots: int = 8
    recovery_slots: int = 24
    #: run a per-node slasher (required by byzantine scenarios — the
    #: detect→slash pipeline must be live on every node).  Off by default:
    #: the per-attestation detection work adds real per-slot CPU that the
    #: purely-lossy scenarios don't need.
    slasher: bool = False
    events: Tuple[Event, ...] = ()
    #: optional callable(runner) -> dict of extra evidence; raises
    #: AssertionError to fail the scenario (kept out of the artifact spec)
    extra_checks: Optional[Callable[["ScenarioRunner"], dict]] = None

    def to_dict(self) -> dict:
        return {
            "name": self.name, "description": self.description,
            "seed": self.seed, "node_count": self.node_count,
            "validator_count": self.validator_count,
            "warmup_slots": self.warmup_slots,
            "fault_slots": self.fault_slots,
            "recovery_slots": self.recovery_slots,
            "slasher": self.slasher,
            "events": [e.to_dict() for e in self.events],
        }


def _plan_from(spec: Dict[str, Any]) -> LinkPlan:
    kwargs = dict(spec)
    if "kinds" in kwargs and kwargs["kinds"] is not None:
        kinds = kwargs["kinds"]
        kwargs["kinds"] = GOSSIP_KINDS if kinds == "gossip" else frozenset(kinds)
    return LinkPlan(**kwargs)


class ScenarioRunner:
    """Executes one :class:`Scenario` and writes its SOAK JSON artifact."""

    #: pump cadence while waiting on sync/backfill — each iteration drains
    #: one fabric tick, so plan latency resolves in milliseconds of wall
    #: time instead of one simulated slot
    PUMP_SLEEP_S = 0.02
    SYNC_DEADLINE_S = 60.0
    CONVERGE_DEADLINE_S = 30.0
    #: rekick cadence in VIRTUAL seconds.  The old wall-clock 1.0 s compare
    #: meant a loaded box rekicked at a different virtual point than an
    #: idle one — the cadence is now a property of the run, not the host.
    REKICK_VIRTUAL_S = 1.0
    #: per-step quiescence budget.  Settle returns False on timeout and the
    #: slot proceeds un-quiesced — silent nondeterminism.  The busiest slots
    #: (a byzantine burst: votes + slashing gossip + packing) can exceed
    #: Simulator.settle's 10 s default on a cold first run, so the runner's
    #: own steps get triple the room; a quiet fabric still exits instantly.
    SETTLE_TIMEOUT_S = 30.0

    def __init__(self, scenario: Scenario, out_dir: Optional[str] = None):
        self.scenario = scenario
        self.out_dir = out_dir or os.environ.get("LIGHTHOUSE_TPU_SOAK_DIR", ".")
        self.sim: Optional[Simulator] = None
        self.byz = None  # ByzantineController, created by the first byz event
        self.ctx: Dict[str, Any] = {}  # cross-event state for extra checks
        self.timeline: List[dict] = []
        # The run's virtual clock: owned here, injected into the Simulator
        # and installed into every control-path seam (breaker cooldowns,
        # pipeline linger, fault hang sleeps) for the run's duration.
        self.clock = VirtualClock()
        self._saved_hash_impl = None
        self._saved_host_impl = None
        self._state_hashing_on = False
        self._breakers_touched = False
        self._epoch_device_touched = False
        self._pipeline_enabled = False
        self._mesh_touched = False
        self._autotune_touched = False
        self._spam_endpoints: List[str] = []
        self._api_servers: List[Any] = []  # (cached, uncached) HTTP pairs
        self._offense_seen = 0  # byz.offenses already journaled (fleet)

    # ------------------------------------------------------------ helpers

    def _node(self, index: int) -> SimNode:
        return self.sim.nodes[index]

    def _current_slot(self) -> Optional[int]:
        """The fleet's logical slot — the fault registry's slot provider.
        Fault plans key their fire decisions on this instead of arrival
        order, so thread interleaving across slots cannot move which
        dispatch faults (the ``device_breaker_mid_sync`` flake)."""
        sim = self.sim
        if sim is None:
            return None
        for n in sim.live_nodes:
            try:
                return int(n.chain.current_slot())
            except Exception:
                continue
        return None

    def _settle(self) -> None:
        """Quiesce the fabric or fail LOUDLY.  A silent settle timeout
        let the slot proceed un-quiesced — the nondeterminism it exists
        to prevent, reported only as a downstream head mismatch."""
        if not self.sim.settle(timeout=self.SETTLE_TIMEOUT_S):
            raise ScenarioFailure(
                f"fabric failed to quiesce within {self.SETTLE_TIMEOUT_S}s "
                f"at slot {self._current_slot()} — un-quiesced slots race "
                "thread scheduling into block content")

    def _pump_until(self, cond: Callable[[], bool], timeout: float,
                    rekick: Optional[Callable[[], None]] = None) -> bool:
        """Advance fabric ticks (so delayed envelopes drain) until ``cond``
        holds; ``rekick`` fires about once a virtual second (re-triggering
        sync for a node whose first attempt lost a race).

        Runs on the scenario's virtual clock: the deadline is a budget of
        virtual seconds and the rekick cadence is keyed on virtual ticks,
        so a loaded box pumps/rekicks at the same virtual points as an
        idle one (the determinism gate's structural guarantee)."""
        clock = self.clock
        deadline = clock.now() + timeout
        next_kick = clock.now()
        while clock.now() < deadline:
            if cond():
                return True
            if self.sim.hub.pending_delayed():
                self.sim.hub.advance_tick()
            if rekick is not None and clock.now() >= next_kick:
                next_kick = clock.now() + self.REKICK_VIRTUAL_S
                rekick()
            clock.lull(self.PUMP_SLEEP_S)
        return cond()

    def _pump_node_to_head(self, node: SimNode, donor: SimNode,
                           deadline: Optional[float] = None) -> None:
        """Block until ``node`` reaches ``donor``'s head, re-kicking range
        sync via a fresh status push — a restarted/joined node resumes
        duties only once synced, so thread scheduling cannot change which
        blocks the fleet builds."""

        def rekick() -> None:
            try:
                node.node.sync.on_peer_status(
                    donor.peer_id, donor.node.router.local_status())
            except Exception:
                pass  # donor churning mid-kick: the next kick retries

        ok = self._pump_until(
            lambda: node.chain.head_root == donor.chain.head_root,
            deadline or self.SYNC_DEADLINE_S, rekick=rekick)
        if not ok:
            raise ScenarioFailure(
                f"node {node.peer_id} failed to sync to {donor.peer_id} "
                f"within {deadline or self.SYNC_DEADLINE_S}s")

    def _donor(self) -> SimNode:
        """A live full node to sync against (lowest index, the convention
        every built-in scenario follows for its anchor)."""
        for n in self.sim.live_nodes:
            if n.harness is not None:
                return n
        raise ScenarioFailure("no live full node left to sync against")

    def _step_slot(self) -> int:
        """One fault-window/recovery slot: advance clocks, run duties on
        every live node, drain one fabric tick, then ``Simulator.settle``
        until the fabric is quiescent — each slot's gossip lands before
        the next slot proposes, keeping block content deterministic (but
        no convergence assert: fault windows diverge by design).

        The byzantine controller (adversary.py) hooks in three places:
        BEFORE duties (forged-content strategies need the slot's honest
        block to not exist yet), INTO duties (suppressing validators whose
        honest messages a strategy replaces), and AFTER duties settle
        (equivocations ride on top of the honest message); its per-slot
        evidence probe runs at the end of every step, recovery included."""
        sim = self.sim
        settle = self._settle
        slot = None
        for n in sim.live_nodes:
            slot = n.advance_slot()
        if self.byz is not None:
            self.byz.pre_duties(slot)
            settle()
        for n in sim.live_nodes:
            n.run_duties(
                slot,
                skip_validators=(self.byz.suppressed_for(n)
                                 if self.byz is not None else None))
            settle()  # per-node: see Simulator.run_slot
        if self.byz is not None:
            self.byz.act(slot)
            settle()
        sim.hub.advance_tick()
        settle()
        if self._autotune_touched:
            # The controller's clock inside a scenario is the per-slot
            # evaluation index — never wall-clock — so a pinned decision
            # list replays at the same slots in both determinism-gate runs.
            from . import autotune

            autotune.CONTROLLER.evaluate()
        if self.byz is not None:
            self.byz.observe_slot(slot)
            self._journal_offenses()
        # quiescent: fold worker-deferred fleet events into the scoped
        # journals on this (runner) thread — see Simulator.drain_fleet_events
        sim.drain_fleet_events()
        heads = {n.chain.head_root for n in sim.live_nodes}
        max_final = max(
            n.chain.finalized_checkpoint()[0] for n in sim.live_nodes)
        self.timeline.append(
            {"slot": slot, "distinct_heads": len(heads),
             "head_root": sim.live_nodes[0].chain.head_root.hex(),
             "max_finalized_epoch": max_final})
        # re-anchor virtual time to the slot boundary: slot-spanning
        # durations (breaker cooldowns, score decay) become deterministic
        # functions of the slot timeline, not of settle-round counts
        self.clock.snap_to_next_slot()
        return slot

    def _finalized(self, agg) -> int:
        return agg(n.chain.finalized_checkpoint()[0]
                   for n in self.sim.live_nodes)

    def _journal_offenses(self) -> None:
        """Journal freshly-recorded byzantine offenses under the OFFENDING
        node's telemetry scope (the node whose validator misbehaved) — the
        head of the cross-node causal chain the fleet-timeline gate asserts
        (offense on A precedes slashing inclusion on B in merge order)."""
        offenses = self.byz.offenses
        fresh, self._offense_seen = (
            offenses[self._offense_seen:], len(offenses))
        for off in fresh:
            node = next((n for n in self.sim.live_nodes
                         if off.validator in n.keys), None)
            scope = getattr(node, "scope", None) if node is not None else None
            # forger strategies (invalid_block, invalid_aggregate, ...) have
            # no offending validator — they journal without one, under the
            # global scope (no node's validator misbehaved)
            fields = {"slot": int(off.slot), "strategy": off.strategy}
            if off.validator is not None:
                fields["validator"] = int(off.validator)
            with telemetry_scope.activate(scope):
                blackbox.emit("adversary", "offense", **fields)

    # ------------------------------------------------------- event actions

    def _apply(self, event: Event) -> None:
        handler = getattr(self, f"_ev_{event.action}", None)
        if handler is None:
            raise ValueError(f"unknown scenario action {event.action!r}")
        log.info("scenario event", scenario=self.scenario.name,
                 action=event.action, at_slot=event.at_slot)
        SCENARIO_EVENTS.inc(action=event.action)
        blackbox.emit("scenario", event.action,
                      scenario=self.scenario.name, at_slot=event.at_slot)
        handler(**event.args)

    def _ev_partition(self, groups: Sequence[Sequence[int]]) -> None:
        for gid, group in enumerate(groups):
            for index in group:
                self.sim.hub.set_partition(self._node(index).peer_id, gid)

    def _ev_heal(self) -> None:
        self.sim.hub.clear_partitions()

    def _ev_kill(self, node: int) -> None:
        self.sim.kill_node(node)

    def _ev_restart(self, node: int) -> None:
        restarted = self.sim.restart_node(node)
        self._pump_node_to_head(restarted, self._donor())

    def _ev_link_plan(self, a: int, b: int, plans: Sequence[dict]) -> None:
        pa, pb = self._node(a).peer_id, self._node(b).peer_id
        for i, spec in enumerate(plans):
            self.sim.hub.set_link_plan(pa, pb, _plan_from(spec), append=i > 0)

    def _ev_clear_link_plans(self) -> None:
        self.sim.hub.clear_link_plans()

    def _ev_install_faults(self, spec: str) -> None:
        for plan in fault_injection.parse_spec(spec):
            fault_injection.REGISTRY.install(plan)

    def _ev_clear_faults(self) -> None:
        fault_injection.clear()

    def _ev_breaker_config(self, **kwargs) -> None:
        from . import device_supervisor

        self._breakers_touched = True
        device_supervisor.SUPERVISOR.configure(
            config=device_supervisor.BreakerConfig(**kwargs))

    def _ev_autotune(self, mode: str = "pinned",
                     pin: Optional[Sequence[dict]] = None) -> None:
        """Enable the self-tuning controller for this scenario.  ``pinned``
        (the only mode a deterministic scenario should run) replays the
        given ``pin`` — a recorded decision list keyed by evaluation
        index; the runner then drives one evaluation per stepped slot, so
        both determinism-gate runs apply identical decisions at identical
        slots."""
        from . import autotune

        self._autotune_touched = True
        autotune.set_mode(mode)
        if pin is not None:
            autotune.CONTROLLER.install_pin(pin)

    def _ev_epoch_device(self, enable: bool, fused: bool = True) -> None:
        """Route every node's epoch-boundary processing through the device
        backend — with ``fused`` the whole boundary (deltas + balances +
        shuffling + proposer selection) runs as ONE supervised dispatch
        (``op=epoch_boundary``), so a fault plan on it exercises the
        breaker/host-golden fallback on the fused program.  Host and device
        produce identical bytes, so enabling it never changes chain
        content — the determinism gate covers exactly that."""
        from .consensus import per_epoch

        if enable:
            self._epoch_device_touched = True
            per_epoch.set_epoch_backend("device")
            per_epoch.set_fused_boundary(fused)
        else:
            per_epoch.set_epoch_backend("numpy")
            per_epoch.set_fused_boundary(False)

    def _ev_device_pipeline(self, enable: bool, linger_s: float = 0.002) -> None:
        """Route every node's ``verify_signature_sets`` through the async
        device pipeline (device_pipeline.py) — coalescing stays active over
        whatever BLS backend the scenario runs, so the determinism gate
        covers batching-composition variance: batch makeup may differ
        between runs, but verdicts (and therefore heads) must not."""
        from . import device_pipeline

        if enable:
            self._pipeline_enabled = True
            # a tight linger keeps scenario wall time sane: the point is the
            # coalescing seam in the path, not big batches
            device_pipeline.get_pipeline().linger_s = float(linger_s)
            device_pipeline.enable()
        else:
            device_pipeline.disable()

    def _ev_device_mesh(self, enable: bool, spec: str = "auto") -> None:
        """Shard the bucketed device ops over the data-parallel mesh
        (device_mesh.py).  Sharded and single-device programs produce
        identical bytes, so enabling the mesh never changes chain content —
        the determinism gate covers exactly that.  Records whether a real
        mesh came up (``ctx["mesh_enabled"]``): on a 1-device interpreter
        the fallback is transparent, and the extra check fails loudly
        rather than passing vacuously."""
        from . import device_mesh

        self._mesh_touched = True
        if enable:
            size = device_mesh.configure(spec)
            self.ctx["mesh_enabled"] = size >= 2
            self.ctx["mesh_size"] = size
        else:
            device_mesh.reset_for_tests()

    def _ev_mesh_trip_device(self, device: int) -> None:
        """Kill one mesh device mid-scenario: its breaker trips, the mesh
        re-shards over the survivors, and every subsequent sharded dispatch
        runs on the shrunk topology.  The full-strength evidence is
        snapshotted HERE — the flight recorder is a bounded ring, and the
        post-trip sync traffic would evict the pre-trip records before the
        end-of-run check reads them."""
        from . import device_mesh, device_telemetry

        self.ctx["meshes_before_trip"] = sorted({
            r["mesh"]
            for r in device_telemetry.FLIGHT_RECORDER.recent(
                limit=device_telemetry.FLIGHT_RECORDER.capacity)
            if r.get("mesh")
        })
        self.ctx["mesh_tripped"] = device_mesh.force_trip(
            int(device), reason="scenario_kill")

    def _ev_device_hashing(self, enable: bool, threshold_blocks: int = 4) -> None:
        """Route Merkle pair-hash layers of ``threshold_blocks``+ through
        the supervised device op (so a ``device.dispatch[op=sha256_pairs]``
        fault plan has a seam to bite mid-sync); host and device produce
        identical bytes, so enabling it never changes chain content.  The
        swap mirrors ``sha256_device.install_device_hash`` but is
        reversible, and ``_HOST_IMPL`` is pointed at the saved kernel so
        the supervisor's fallback cannot recurse into the hybrid."""
        from .ops import sha256_device
        from .types import ssz as ssz_mod

        if enable:
            if self._saved_hash_impl is not None:
                return
            host = self._saved_hash_impl = ssz_mod._hash_pairs
            self._saved_host_impl = sha256_device._HOST_IMPL
            sha256_device._HOST_IMPL = host

            def hybrid(data: bytes) -> bytes:
                n = len(data) // 64
                if threshold_blocks <= n <= sha256_device.N_BUCKETS[-1]:
                    return sha256_device.hash_pairs_device(data)
                return host(data)

            ssz_mod.set_hash_pairs_impl(hybrid)
        elif self._saved_hash_impl is not None:
            ssz_mod.set_hash_pairs_impl(self._saved_hash_impl)
            sha256_device._HOST_IMPL = self._saved_host_impl
            self._saved_hash_impl = None

    def _ev_state_hashing(self, enable: bool, threshold_blocks: int = 4) -> None:
        """Route Merkle pair-hash layers of ``threshold_blocks``+ through
        ``ops/tree_hash.hash_pairs`` — the pipeline-aware hash seam: with
        the device pipeline on, layers coalesce through the
        ``sha256_pairs`` hash pipeline (supervised inside — a
        ``device.dispatch[op=sha256_pairs]`` fault plan bites the exact
        production path, and breaker-open batches resolve through the host
        kernel with identical bytes).  The tree-hash state PR's analog of
        ``device_hashing``; reversible, with ``sha256_device._HOST_IMPL``
        pointed at the saved kernel so the supervisor's fallback cannot
        recurse into the hybrid."""
        from .ops import sha256_device, tree_hash
        from .types import ssz as ssz_mod

        if enable:
            if self._saved_hash_impl is not None:
                return
            host = self._saved_hash_impl = ssz_mod._hash_pairs
            self._saved_host_impl = sha256_device._HOST_IMPL
            sha256_device._HOST_IMPL = host
            self._state_hashing_on = True
            tree_hash.configure(enabled=True,
                                device_min_blocks=threshold_blocks)
            # pin a tight linger (same rationale as _ev_device_pipeline):
            # the adaptive default tracks observed in-flight durations,
            # which on the 1-core gate box would park every per-level
            # Merkle batch far longer than the scenario budget tolerates.
            # Starting the hash pipeline here makes THIS event a pipeline
            # owner too — flag it so teardown shuts the worker down even
            # when the scenario never ran a device_pipeline event
            from . import device_pipeline

            device_pipeline.get_hash_pipeline().linger_s = 0.002
            self._pipeline_enabled = True

            def hybrid(data: bytes) -> bytes:
                n = len(data) // 64
                if threshold_blocks <= n <= sha256_device.N_BUCKETS[-1]:
                    return tree_hash.hash_pairs(data)
                return host(data)

            ssz_mod.set_hash_pairs_impl(hybrid)
        elif self._saved_hash_impl is not None:
            ssz_mod.set_hash_pairs_impl(self._saved_hash_impl)
            sha256_device._HOST_IMPL = self._saved_host_impl
            self._saved_hash_impl = None
            self._state_hashing_on = False
            tree_hash.reset_for_tests()

    def _ev_join_checkpoint(self, anchor_from: int = 0, lossy: bool = False,
                            backfill: bool = False,
                            churn_kill: Optional[int] = None) -> None:
        """A new node joins from ``anchor_from``'s finalized checkpoint.
        ``lossy``: its links get a seeded lossy-gossip + slow-RPC plan
        BEFORE sync starts.  ``backfill``: it then backfills history; with
        ``churn_kill`` the named peer is killed first and listed as the
        preferred backfill server, so the dead-peer timeout/retry path is
        what actually fills history."""
        donor = self._node(anchor_from)
        joined = self.sim.add_checkpoint_node(anchor_from=anchor_from)
        self.ctx["joined"] = joined
        if lossy:
            for other in self.sim.live_nodes:
                if other is joined:
                    continue
                self.sim.hub.set_link_plan(
                    joined.peer_id, other.peer_id,
                    LinkPlan(drop=0.2, delay=1, jitter=1, duplicate=0.1,
                             reorder=0.3, kinds=GOSSIP_KINDS))
                self.sim.hub.set_link_plan(
                    joined.peer_id, other.peer_id,
                    LinkPlan(delay=1, kinds=frozenset(
                        {"rpc_request", "rpc_response"})),
                    append=True)
        self._pump_node_to_head(joined, donor)
        if not backfill:
            return
        from .network.backfill import BackfillSync

        sync = BackfillSync(chain=joined.chain, service=joined.node.service)
        self.ctx["backfill"] = sync
        dead_peer = None
        if churn_kill is not None:
            dead_peer = self._node(churn_kill).peer_id
            self.sim.kill_node(churn_kill)
        serving = dead_peer or donor.peer_id
        fallbacks = [n.peer_id for n in self.sim.live_nodes
                     if n is not joined and n.peer_id != serving]
        done: Dict[str, Any] = {}

        def run_backfill() -> None:
            try:
                done["filled"] = sync.backfill_from(
                    serving, request_timeout=2.0, fallback_peers=fallbacks)
            except Exception as e:  # surfaced by the gate below
                done["error"] = repr(e)

        worker = threading.Thread(target=run_backfill, daemon=True,
                                  name="scenario-backfill")
        worker.start()
        self._pump_until(lambda: not worker.is_alive(), self.SYNC_DEADLINE_S)
        if worker.is_alive() or "error" in done:
            raise ScenarioFailure(
                f"backfill did not finish cleanly: {done.get('error', 'stuck')}")
        self.ctx["backfill_filled"] = done.get("filled", 0)
        if churn_kill is not None:
            restarted = self.sim.restart_node(churn_kill)
            self._pump_node_to_head(restarted, donor)

    def _ev_byzantine(self, strategy: str, node: int, validators=None,
                      max_offenses: int = 1, **kwargs) -> None:
        """Arm a byzantine misbehavior strategy (adversary.py) on a subset
        of ``node``'s validators.  Every decision the controller takes is
        keyed on sha256(seed | strategy | slot | validator), so the 2-run
        determinism gate covers the adversary."""
        from .adversary import ByzantineController

        if self.byz is None:
            self.byz = ByzantineController(self.sim, seed=self.scenario.seed)
            self.ctx["byz"] = self.byz
        self.byz.arm(strategy, node, validators=validators,
                     max_offenses=max_offenses, **kwargs)

    def _ev_spam(self, target: int = 0, count: int = 64) -> None:
        """An ephemeral hub peer floods the target with undecodable gossip
        on a real subscribed topic — the peer-scoring path must absorb and
        penalize it without disturbing the honest mesh."""
        import hashlib

        from .network import topics as topics_mod
        from .network.transport import Envelope

        victim = self._node(target)
        spammer_id = f"spammer{len(self._spam_endpoints)}"
        endpoint = self.sim.hub.register(spammer_id)
        self._spam_endpoints.append(spammer_id)
        self.sim.hub.connect(spammer_id, victim.peer_id)
        topic = str(topics_mod.GossipTopic(
            victim.node.router.fork_digest, topics_mod.BEACON_BLOCK))
        for i in range(count):
            junk = hashlib.sha256(
                f"{self.scenario.seed}:spam:{i}".encode()).digest()
            endpoint.send(victim.peer_id, Envelope(
                kind="gossip", sender=spammer_id, topic=topic, data=junk))
        self.ctx["spammer"] = (spammer_id, victim)

    def _ev_api_serve(self, node: int = 0) -> None:
        """Stand up the serving pair over ``node``'s chain — one server with
        the checkpoint-keyed response cache, one without (the bit-identity
        oracle) — and leave both running across subsequent slots so the
        cache's event-driven invalidation is exercised by real head /
        finalization traffic, not by synthetic events."""
        from .http_api import HttpApiServer

        chain = self._node(node).chain
        cached = HttpApiServer(chain).start()
        uncached = HttpApiServer(chain, response_cache=False).start()
        self._api_servers.extend([cached, uncached])
        self.ctx["api_pair"] = (cached, uncached)
        self.ctx["api_probes"] = []

    def _ev_api_probe(self, label: str = "window") -> None:
        """Replay the deterministic hot-route request list against both
        servers — twice, so the second pass hits the cache — and record
        byte-identity plus a response digest."""
        import hashlib

        cached, uncached = self.ctx["api_pair"]
        chain = cached.chain
        digest = hashlib.sha256()
        mismatches: List[str] = []
        n_requests = 0
        for method, path, body in _api_probe_requests(chain):
            for _pass in (0, 1):
                sc, bc = _api_http(cached.port, method, path, body)
                su, bu = _api_http(uncached.port, method, path, body)
                n_requests += 1
                if (sc, bc) != (su, bu):
                    mismatches.append(f"{method} {path} [pass {_pass}]")
                digest.update(bc)
        self.ctx["api_probes"].append({
            "label": label,
            "n_requests": n_requests,
            "mismatches": mismatches,
            "digest": digest.hexdigest(),
            "cache": cached.response_cache.snapshot(),
        })

    def _ev_leak_baseline(self) -> None:
        """Snapshot every bounded ring and every monotone counter at the
        start of the fault window — the reference point the leak gates
        (``_check_leak_gates``) diff the end-of-run state against.  Only
        ``Counter`` series are snapshotted: gauges may legally fall, and
        histograms ride on counters of their own."""
        from . import device_telemetry

        with metrics._REGISTRY_LOCK:
            counters = {name: m.snapshot()
                        for name, m in metrics._REGISTRY.items()
                        if isinstance(m, metrics.Counter)}
        self.ctx["leak_baseline"] = {
            "counters": counters,
            "journal_emitted": blackbox.JOURNAL.emitted_total,
            "flight_recorded":
                device_telemetry.FLIGHT_RECORDER.recorded_total,
        }

    # ------------------------------------------------------------ the run

    def run(self) -> dict:
        scenario = self.scenario
        started = telemetry_stamp()  # telemetry only: artifact duration_s
        delay_before = {k: h.stats() for k, h in DELAY_HISTOGRAMS.items()}
        # fault-window evidence, captured before recovery clears the plans
        breakers: Optional[dict] = None
        fault_plans: Optional[list] = None
        # byzantine scenarios run a slasher on every node (the detect→slash
        # pipeline under test) — which is also an implicit honest-traffic
        # gate: a false-positive slashing would flip validators[i].slashed
        # and fail the finality gate
        self.sim = Simulator(
            node_count=scenario.node_count,
            validator_count=scenario.validator_count,
            seed=scenario.seed,
            enable_slasher=scenario.slasher,
            clock=self.clock,
        )
        self.sim.hub.record_schedule()
        # Install the virtual clock into every control-path wall-time seam
        # for the run's duration (restored in _cleanup): breaker cooldowns,
        # pipeline linger decisions, and fault-injection hang sleeps all
        # burn virtual time while the scenario owns the process.
        self._install_clock_seams()
        # Fault plans key on the fleet's logical slot for the whole run —
        # see fault_injection's slot-keying section; cleared in _cleanup.
        fault_injection.set_slot_provider(self._current_slot)
        blackbox.emit("scenario", "run_start", scenario=scenario.name,
                      seed=scenario.seed)
        artifact: dict = {"scenario": scenario.to_dict(), "passed": False}
        try:
            for _ in range(scenario.warmup_slots):
                self.sim.run_slot()
                self.clock.snap_to_next_slot()
            finalized_at_window_start = self._finalized(max)

            events = sorted(scenario.events, key=lambda e: e.at_slot)
            queue = list(events)
            for offset in range(scenario.fault_slots):
                while queue and queue[0].at_slot <= offset:
                    self._apply(queue.pop(0))
                self._step_slot()
            for event in queue:  # events past the window still apply once
                self._apply(event)
            finalized_at_window_end = self._finalized(max)
            breakers = self._breaker_summary()
            fault_plans = fault_injection.plans()

            # implicit recovery: every fabric fault heals, injected faults
            # clear; churned nodes must have been restarted by the timeline;
            # byzantine actors stop offending (their evidence probe keeps
            # running so detection latency spans into recovery)
            self.sim.hub.clear_partitions()
            self.sim.hub.clear_link_plans()
            fault_injection.clear()
            if self.byz is not None:
                self.byz.deactivate()
            for _ in range(scenario.recovery_slots):
                self._step_slot()

            converged = self.sim.wait_converged(self.CONVERGE_DEADLINE_S)
            # late imports during the convergence pump defer fleet events
            # too — fold them in before the gates read the merged timeline
            self.sim.drain_fleet_events()
            final_finalized_min = self._finalized(min)
            per_node = [self._node_summary(n) for n in self.sim.nodes]
            if self.byz is not None:
                self._check_fleet_causality()
            extra = {}
            if scenario.extra_checks is not None:
                extra = scenario.extra_checks(self) or {}

            if not converged:
                raise ScenarioFailure(
                    f"live nodes did not converge: "
                    f"{[p['head_root'][:16] for p in per_node if p['alive']]}")
            if final_finalized_min <= finalized_at_window_end:
                raise ScenarioFailure(
                    f"finality did not advance past the fault window "
                    f"({final_finalized_min} <= {finalized_at_window_end})")

            head = self.sim.live_nodes[0].chain.head_root
            artifact.update({
                "passed": True,
                "result": {
                    "converged": True,
                    "head_root": head.hex(),
                    "head_slot": self.sim.live_nodes[0].chain.head_slot(),
                    "finalized_at_window_start": finalized_at_window_start,
                    "finalized_at_window_end": finalized_at_window_end,
                    "final_finalized_epoch": final_finalized_min,
                    "per_node": per_node,
                },
                "extra": extra,
            })
            SCENARIO_RUNS.inc(scenario=scenario.name, outcome="passed")
            return artifact
        except ScenarioFailure as e:
            artifact["failure"] = str(e)
            SCENARIO_RUNS.inc(scenario=scenario.name, outcome="failed")
            self._capture_postmortem(
                artifact, f"scenario_gate:{scenario.name}", str(e))
            raise
        except Exception as e:
            artifact["failure"] = f"{type(e).__name__}: {e}"
            SCENARIO_RUNS.inc(scenario=scenario.name, outcome="error")
            self._capture_postmortem(
                artifact, f"scenario_crash:{scenario.name}",
                artifact["failure"])
            raise
        finally:
            try:
                if breakers is None:  # failed before the window-end snapshot
                    breakers = self._breaker_summary()
                    fault_plans = fault_injection.plans()
                if self.byz is not None:
                    # adversarial coverage is a tracked artifact: offenses
                    # emitted/detected/included + detection latency ride in
                    # every byzantine SOAK JSON alongside the fabric evidence
                    artifact["adversary"] = self.byz.summary()
                artifact.update({
                    "net": {
                        "counters": self.sim.hub.fault_counters(),
                        "schedule_digest": self.sim.hub.schedule_digest(),
                        "pending_delayed": self.sim.hub.pending_delayed(),
                    },
                    "faults": fault_plans,
                    "breakers": breakers,
                    "delay_metrics": self._delay_deltas(delay_before),
                    "timeline": self.timeline,
                    # frozen BEFORE _cleanup unregisters the node scopes
                    "fleet": self._fleet_section(),
                    "duration_s": round(telemetry_stamp() - started, 3),
                })
                self._write_artifact(artifact)
            finally:
                self._cleanup()

    # ---------------------------------------------------------- reporting

    def _capture_postmortem(self, artifact: dict, reason: str,
                            failure: str) -> None:
        """Freeze the black box at a gate failure and attach the bundle
        path to the SOAK artifact — an unattended soak failure triages
        from one file (see OBSERVABILITY.md's playbook)."""
        try:
            cap = blackbox.capture(reason, extra={
                "scenario": self.scenario.name, "failure": failure})
            artifact["postmortem_bundle"] = cap["path"]
        except Exception as e:  # noqa: BLE001 — must not mask the gate
            log.warning("postmortem capture failed",
                        scenario=self.scenario.name,
                        error=f"{type(e).__name__}: {e}")

    def _check_fleet_causality(self) -> None:
        """Gate: the merged fleet timeline must order every cross-node
        slashing pipeline causally — the first journaled offense (on the
        offending node's scope) precedes the first ``slashing_included``
        (journaled under the including proposer's scope) in merge order."""
        included = [o for o in self.byz.offenses if o.included_slot is not None]
        if not included:
            return  # nothing reached inclusion: nothing to order
        timeline = blackbox.fleet_summary()["timeline"]
        first_off = next((i for i, r in enumerate(timeline)
                          if r.get("event") == "offense"), None)
        first_inc = next((i for i, r in enumerate(timeline)
                          if r.get("event") == "slashing_included"), None)
        if first_off is None or first_inc is None:
            raise ScenarioFailure(
                f"fleet timeline is missing the slashing causal chain "
                f"(offense at {first_off}, inclusion at {first_inc})")
        if first_off >= first_inc:
            raise ScenarioFailure(
                f"fleet timeline orders slashing inclusion (index "
                f"{first_inc}) before the offense (index {first_off}) — "
                "cross-node causality broken in the merge")

    def _fleet_section(self) -> dict:
        """The SOAK artifact's fleet-observability evidence: per-node scope
        snapshots, the merged causally-ordered timeline, and cross-node
        trace trees — each joins a ``propose_block`` span on the origin
        node to a ``gossip_block_import`` span on a receiving node via the
        envelope-propagated trace context (``remote_trace_id``)."""
        try:
            summary = blackbox.fleet_summary()
        except Exception as e:  # noqa: BLE001 — evidence must not mask gates
            return {"error": f"{type(e).__name__}: {e}"}
        proposals = {t.trace_id: t for t in tracing.TRACES.recent(
            root="propose_block", limit=512)}
        trees = []
        for t in tracing.TRACES.recent(root="gossip_block_import", limit=1024):
            origin = proposals.get(t.root.fields.get("remote_trace_id"))
            if origin is None:
                continue  # import of a non-traced (pre-scope) publish
            trees.append({
                "proposal": {
                    "trace_id": origin.trace_id,
                    "node": origin.root.fields.get("node"),
                    "slot": origin.root.fields.get("slot"),
                    "root": origin.root.fields.get("root"),
                },
                "import": {
                    "trace_id": t.trace_id,
                    "node": t.root.fields.get("node"),
                    "remote_trace_id": t.root.fields.get("remote_trace_id"),
                    "slot": t.root.fields.get("slot"),
                    "root": t.root.fields.get("root"),
                },
            })
        trees.sort(key=lambda e: (
            e["import"].get("slot") or -1, str(e["import"].get("root")),
            str(e["import"].get("node"))))
        summary["trace_trees"] = trees
        return summary

    def _node_summary(self, n: SimNode) -> dict:
        f_epoch, _ = n.chain.finalized_checkpoint()
        return {
            "peer_id": n.peer_id,
            "alive": n.alive,
            "validators": len(n.keys),
            "head_slot": n.chain.head_slot(),
            "head_root": n.chain.head_root.hex(),
            "finalized_epoch": f_epoch,
        }

    def _breaker_summary(self) -> dict:
        from . import device_supervisor

        summary = device_supervisor.summary()
        return {
            b["op"]: {"state": b["state"], "trips_total": b.get("trips_total", 0)}
            for b in summary.get("breakers", [])
        }

    def _delay_deltas(self, before: Dict[str, Tuple[int, float]]) -> dict:
        """Slot-relative delay deltas over this run (the tracing layer's
        histograms are process-cumulative; a per-scenario artifact wants
        just this scenario's traffic)."""
        out = {}
        for key, hist in DELAY_HISTOGRAMS.items():
            n0, s0 = before[key]
            n1, s1 = hist.stats()
            count = n1 - n0
            out[key] = {
                "count": count,
                "mean_s": round((s1 - s0) / count, 6) if count else None,
            }
        return out

    def _write_artifact(self, artifact: dict) -> Optional[str]:
        try:
            os.makedirs(self.out_dir, exist_ok=True)
            path = os.path.join(
                self.out_dir,
                f"SOAK_{self.scenario.name}_seed{self.scenario.seed}.json")
            with open(path, "w") as f:
                json.dump(artifact, f, indent=2, sort_keys=True)
            artifact["artifact_path"] = path
            log.info("soak artifact written", path=path,
                     passed=artifact.get("passed", False))
            return path
        except OSError:
            log.warning("soak artifact not written", out_dir=self.out_dir)
            return None

    def _install_clock_seams(self) -> None:
        """Point every control-path wall-time seam at the run's virtual
        clock.  _cleanup restores the wall defaults unconditionally, so a
        crashed run cannot leak virtual time into the next test."""
        from . import device_pipeline, device_supervisor

        device_supervisor.set_cooldown_clock(self.clock.now)
        device_pipeline.set_linger_clock(self.clock.now)
        fault_injection.set_sleeper(self.clock.sleep)

    def _restore_clock_seams(self) -> None:
        from . import device_pipeline, device_supervisor

        device_supervisor.set_cooldown_clock(None)
        device_pipeline.set_linger_clock(None)
        fault_injection.set_sleeper(None)

    def _cleanup(self) -> None:
        self._restore_clock_seams()
        fault_injection.set_slot_provider(None)
        fault_injection.clear()
        if self._epoch_device_touched:
            from .consensus import per_epoch

            per_epoch.set_epoch_backend("numpy")
            per_epoch.set_fused_boundary(False)
        if self._mesh_touched:
            from . import device_mesh

            device_mesh.reset_for_tests()
        if self._pipeline_enabled:
            from . import device_pipeline

            device_pipeline.reset_for_tests()
        if self._saved_hash_impl is not None:
            if self._state_hashing_on:
                self._ev_state_hashing(enable=False)
            else:
                self._ev_device_hashing(enable=False)
        if self._breakers_touched:
            from . import device_supervisor

            device_supervisor.reset_for_tests()
        if self._autotune_touched:
            from . import autotune

            autotune.reset_for_tests()
        if self.byz is not None:
            self.byz.cleanup()
        for server in self._api_servers:
            try:
                server.stop()
            except Exception:
                pass
        self._api_servers = []
        if self.sim is not None:
            for spammer in self._spam_endpoints:
                self.sim.hub.unregister(spammer)
            self.sim.shutdown()


# ------------------------------------------------------- api-load helpers


def _api_http(port: int, method: str, path: str, body) -> Tuple[int, bytes]:
    """One raw request -> (status, body bytes); byte-exact comparison needs
    the wire bytes, not a parsed view."""
    import http.client

    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    try:
        payload = None if body is None else json.dumps(body)
        headers = {"Content-Type": "application/json"} if payload else {}
        conn.request(method, path, body=payload, headers=headers)
        resp = conn.getresponse()
        return resp.status, resp.read()
    finally:
        conn.close()


def _api_probe_requests(chain) -> List[Tuple[str, str, Any]]:
    """The deterministic hot-route list: duties, state queries, rewards,
    headers/heads — every family the response cache covers."""
    epoch = chain.current_slot() // chain.spec.slots_per_epoch
    n_validators = len(chain.head_state.validators)
    ids = [str(i) for i in range(n_validators)]
    return [
        ("GET", f"/eth/v1/validator/duties/proposer/{epoch}", None),
        ("POST", f"/eth/v1/validator/duties/attester/{epoch}", ids),
        ("POST", f"/eth/v1/validator/duties/sync/{epoch}", ids),
        ("GET", "/eth/v1/beacon/states/head/validators", None),
        ("GET", "/eth/v1/beacon/states/head/validator_balances", None),
        ("GET", "/eth/v1/beacon/states/head/finality_checkpoints", None),
        ("GET", "/eth/v1/beacon/states/head/root", None),
        ("GET", f"/eth/v1/beacon/states/head/committees?epoch={epoch}", None),
        ("GET", "/eth/v1/beacon/headers", None),
        ("GET", "/eth/v1/beacon/headers/head", None),
        ("GET", "/eth/v1/debug/beacon/heads", None),
        ("GET", "/eth/v1/beacon/rewards/blocks/head", None),
        ("POST", f"/eth/v1/beacon/rewards/attestations/{max(epoch - 1, 0)}",
         None),
    ]


# --------------------------------------------------------------- built-ins


def smoke_partition(seed: int = 0) -> Scenario:
    """Tier-1 smoke: a 3-node fleet partitions {0} | {1, 2} for four slots,
    both sides fork, the heal converges them and finality resumes."""
    return Scenario(
        name="smoke_partition",
        description="partition/heal smoke with a small fork and reorg",
        seed=seed, node_count=3, validator_count=16,
        warmup_slots=8, fault_slots=6, recovery_slots=24,
        events=(
            Event(0, "partition", {"groups": [[0], [1, 2]]}),
            Event(4, "heal"),
        ),
        extra_checks=_check_reorg,
    )


def api_load(seed: int = 0) -> Scenario:
    """The serving-layer scenario (ISSUE 14): the cached beacon API rides a
    partition/heal/reorg cycle and must stay byte-identical to an uncached
    server at every probe point — while its cache is populated, invalidated
    by real head events, and repopulated.  The 2-run determinism gate makes
    the probe digests reproducible evidence."""
    return Scenario(
        name="api_load",
        description="cached vs uncached beacon API bit-identity across "
                    "partition, heal, and reorg",
        seed=seed, node_count=3, validator_count=16,
        warmup_slots=8, fault_slots=6, recovery_slots=24,
        events=(
            Event(0, "api_serve", {"node": 0}),
            Event(0, "partition", {"groups": [[0], [1, 2]]}),
            # mid-partition: node 0's minority fork is what's being served
            Event(2, "api_probe", {"label": "partitioned"}),
            Event(4, "heal"),
            # post-heal: the reorg just invalidated the minority entries
            Event(5, "api_probe", {"label": "healed"}),
        ),
        extra_checks=_check_api_load,
    )


def partition_deep_reorg(seed: int = 0) -> Scenario:
    """A minority node builds alone for a full epoch, then reorgs back to
    the majority fork — the deepest reorg the parent-lookup path must walk."""
    return Scenario(
        name="partition_deep_reorg",
        description="epoch-long minority partition, deep reorg on heal",
        seed=seed, node_count=3, validator_count=16,
        warmup_slots=8, fault_slots=10, recovery_slots=24,
        events=(
            Event(0, "partition", {"groups": [[0], [1, 2]]}),
            Event(8, "heal"),
        ),
        extra_checks=_check_reorg,
    )


def nonfinality_spell(seed: int = 0) -> Scenario:
    """>1/3 of validators go offline: finality stalls for two epochs, the
    nodes come back, sync, and finality resumes (the reference's
    fallback-sim liveness property plus recovery)."""
    return Scenario(
        name="nonfinality_spell",
        description=">1/3 offline non-finality spell with recovery",
        seed=seed, node_count=5, validator_count=20,
        warmup_slots=32, fault_slots=24, recovery_slots=24,
        events=(
            Event(0, "kill", {"node": 3}),
            Event(0, "kill", {"node": 4}),
            Event(16, "restart", {"node": 3}),
            Event(16, "restart", {"node": 4}),
        ),
        extra_checks=_check_stall,
    )


def checkpoint_join_lossy(seed: int = 0) -> Scenario:
    """A node checkpoint-syncs into a lossy fabric (seeded gossip drop /
    delay / duplication / reordering, slow RPC), then backfills history
    through a dead preferred peer — the timeout+retry path fills it."""
    return Scenario(
        name="checkpoint_join_lossy",
        description="checkpoint-sync join under lossy links + backfill churn",
        seed=seed, node_count=3, validator_count=16,
        warmup_slots=40, fault_slots=8, recovery_slots=24,
        events=(
            Event(0, "join_checkpoint",
                  {"anchor_from": 0, "lossy": True, "backfill": True,
                   "churn_kill": 1}),
        ),
        extra_checks=_check_backfill,
    )


def device_breaker_mid_sync(seed: int = 0) -> Scenario:
    """A joining node range-syncs while every ``sha256_pairs`` device
    dispatch faults: the supervisor's breaker trips OPEN, imports resolve
    through the host golden model, and sync still converges."""
    return Scenario(
        name="device_breaker_mid_sync",
        description="device.dispatch fault plan trips the breaker mid-sync",
        seed=seed, node_count=3, validator_count=16,
        warmup_slots=32, fault_slots=8, recovery_slots=24,
        events=(
            Event(0, "breaker_config",
                  {"failure_threshold": 2, "open_cooldown_s": 300.0,
                   "probe_successes": 1}),
            Event(0, "device_hashing", {"enable": True}),
            Event(0, "install_faults",
                  {"spec": "device.dispatch[op=sha256_pairs]=error"}),
            Event(1, "join_checkpoint", {"anchor_from": 0}),
            Event(4, "clear_faults"),
            Event(4, "device_hashing", {"enable": False}),
        ),
        extra_checks=_check_breaker_tripped,
    )


def mesh_degradation(seed: int = 0) -> Scenario:
    """A device dies mid-sync and the mesh re-shards around it: the fleet
    runs Merkle pair-hashing on the 8-device mesh (sha256_pairs sharded
    over ``("dp",)``), a joining node range-syncs through it, and one mesh
    device is killed mid-window — its per-device breaker trips, the mesh
    re-shards to 7 survivors, and every later sharded dispatch runs on the
    shrunk topology with identical bytes.  Gates: the fleet still
    converges + finalizes (standard), the re-shard really happened, and
    sharded work really flowed both before and after it.  Needs >= 2 jax
    devices (the test suite's 8-device virtual CPU mesh; standalone runs
    need ``XLA_FLAGS=--xla_force_host_platform_device_count=8``)."""
    return Scenario(
        name="mesh_degradation",
        description="device killed mid-sync: mesh re-shards, fleet converges",
        seed=seed, node_count=3, validator_count=16,
        warmup_slots=32, fault_slots=8, recovery_slots=24,
        events=(
            Event(0, "device_mesh", {"enable": True}),
            Event(0, "device_hashing", {"enable": True}),
            Event(1, "join_checkpoint", {"anchor_from": 0}),
            Event(2, "mesh_trip_device", {"device": 7}),
            Event(6, "device_hashing", {"enable": False}),
        ),
        extra_checks=_check_mesh_resharded,
    )


def pipeline_mid_sync(seed: int = 0) -> Scenario:
    """``device_breaker_mid_sync`` with the async device pipeline enabled:
    every gossip/import verification rides the coalescing pipeline while a
    joining node range-syncs and the ``sha256_pairs`` breaker trips OPEN.
    The determinism gate (2 identical runs) proves batch COMPOSITION
    variance — which groups coalesce together is timing-dependent — cannot
    leak into chain content, and the breaker interplay proves pipeline
    futures still resolve while device work routes to the host."""
    return Scenario(
        name="pipeline_mid_sync",
        description="async device pipeline on during breaker-tripping sync",
        seed=seed, node_count=3, validator_count=16,
        warmup_slots=32, fault_slots=8, recovery_slots=24,
        events=(
            Event(0, "device_pipeline", {"enable": True}),
            Event(0, "breaker_config",
                  {"failure_threshold": 2, "open_cooldown_s": 300.0,
                   "probe_successes": 1}),
            Event(0, "device_hashing", {"enable": True}),
            Event(0, "install_faults",
                  {"spec": "device.dispatch[op=sha256_pairs]=error"}),
            Event(1, "join_checkpoint", {"anchor_from": 0}),
            Event(4, "clear_faults"),
            Event(4, "device_hashing", {"enable": False}),
        ),
        extra_checks=_check_pipeline_active,
    )


def state_hash_pipeline(seed: int = 0) -> Scenario:
    """Tree-hash traffic through the async pipeline's shared arbiter:
    Merkle pair-hash layers route through ``ops/tree_hash.hash_pairs``
    (coalescing into ``sha256_pairs`` hash-pipeline batches) while every
    bls verification rides the verify pipeline, a joining node range-syncs
    through it all, and a fault plan trips the sha breaker mid-window —
    hash futures must still resolve bit-identically through the host
    kernel.  The 2-run gate proves batch COMPOSITION variance (which hash
    groups coalesce together is timing-dependent) cannot leak into chain
    content."""
    return Scenario(
        name="state_hash_pipeline",
        description="pipelined tree-hash + bls traffic under sha faults",
        seed=seed, node_count=3, validator_count=16,
        warmup_slots=32, fault_slots=8, recovery_slots=24,
        events=(
            Event(0, "device_pipeline", {"enable": True}),
            Event(0, "breaker_config",
                  {"failure_threshold": 2, "open_cooldown_s": 300.0,
                   "probe_successes": 1}),
            Event(0, "state_hashing", {"enable": True}),
            Event(0, "install_faults",
                  {"spec": "device.dispatch[op=sha256_pairs]=error"}),
            Event(1, "join_checkpoint", {"anchor_from": 0}),
            Event(4, "clear_faults"),
            Event(4, "state_hashing", {"enable": False}),
        ),
        extra_checks=_check_hash_pipeline,
    )


def autotune_pinned(seed: int = 0) -> Scenario:
    """The self-tuning controller in its deterministic mode, under device
    faults: the fleet hashes through the supervised sha path while a fault
    plan trips the ``sha256_pairs`` breaker mid-sync, and the autotune
    controller replays a PINNED decision list — adopt the 640 midpoint
    sha bucket at evaluation 2 (through the committed-hlo_budget gate, the
    static-gate honesty check), drop it at evaluation 6.  The 2-run
    determinism gate then proves the controller's whole machinery — mode
    plumbing, per-slot evaluation clock, overlay swap in the live
    ``_bucket`` path — cannot leak wall-clock into chain content: both
    runs must apply the identical adopted-bucket sequence AND finish on
    identical heads."""
    pin = [
        {"after_evaluation": 2, "vocab": "sha256_pairs",
         "action": "adopt", "bucket": 640},
        {"after_evaluation": 6, "vocab": "sha256_pairs",
         "action": "drop", "bucket": 640},
        # a pin must not be able to smuggle an unbudgeted lowering past
        # the static gate: this entry is REFUSED (no committed hlo_budget
        # key for 900) and the refusal is part of the pinned sequence
        {"after_evaluation": 8, "vocab": "sha256_pairs",
         "action": "adopt", "bucket": 900},
    ]
    return Scenario(
        name="autotune_pinned",
        description="pinned autotune decisions replay under device faults",
        seed=seed, node_count=3, validator_count=16,
        warmup_slots=32, fault_slots=8, recovery_slots=24,
        events=(
            Event(0, "autotune", {"mode": "pinned", "pin": pin}),
            Event(0, "breaker_config",
                  {"failure_threshold": 2, "open_cooldown_s": 300.0,
                   "probe_successes": 1}),
            Event(0, "device_hashing", {"enable": True}),
            Event(0, "install_faults",
                  {"spec": "device.dispatch[op=sha256_pairs]=error"}),
            Event(1, "join_checkpoint", {"anchor_from": 0}),
            Event(4, "clear_faults"),
            Event(4, "device_hashing", {"enable": False}),
        ),
        extra_checks=_check_autotune_pinned,
    )


def fused_epoch_boundary(seed: int = 0) -> Scenario:
    """The fused epoch-boundary dispatch (ISSUE 16) under chaos: every
    node's epoch transition runs as ONE supervised device program
    (deltas + balance updates + next-epoch shuffling + proposer selection),
    a fault plan errors the ``epoch_boundary`` dispatch at the first
    boundary inside the window — the breaker trips, transitions resolve
    through the host golden model verdict-identically — and after the
    plan clears the breaker probes shut and later boundaries run on the
    device again.  Warmup of 15 puts the epoch 1 -> 2 transition (the
    first boundary PAST genesis — the genesis transition skips the delta
    pass entirely) at window offset 0, so the faulted dispatch lands
    there deterministically (slot-keyed fault firing makes WHICH dispatch
    faults independent of thread arrival order)."""
    return Scenario(
        name="fused_epoch_boundary",
        description="fused epoch dispatch faults, host fallback, recovery",
        seed=seed, node_count=3, validator_count=16,
        warmup_slots=15, fault_slots=8, recovery_slots=24,
        events=(
            Event(0, "breaker_config",
                  {"failure_threshold": 2, "open_cooldown_s": 0.5,
                   "probe_successes": 1}),
            Event(0, "epoch_device", {"enable": True, "fused": True}),
            Event(0, "install_faults",
                  {"spec": "device.dispatch[op=epoch_boundary]=error"}),
            Event(4, "clear_faults"),
        ),
        extra_checks=_check_fused_boundary,
    )


def spam_slow_peer(seed: int = 0) -> Scenario:
    """A spammer floods undecodable blocks at one node while another pair's
    RPC link turns slow: scoring graylists the spammer, the mesh converges
    anyway."""
    return Scenario(
        name="spam_slow_peer",
        description="gossip spam + slow RPC link, mesh unharmed",
        seed=seed, node_count=3, validator_count=16,
        warmup_slots=8, fault_slots=8, recovery_slots=16,
        events=(
            Event(0, "link_plan",
                  {"a": 1, "b": 2,
                   "plans": [{"delay": 1, "jitter": 1,
                              "kinds": ["rpc_request", "rpc_response"]}]}),
            Event(1, "spam", {"target": 0, "count": 64}),
        ),
        extra_checks=_check_spammer_penalized,
    )


# ------------------------------------------------------- byzantine actors


def byz_double_vote_smoke(seed: int = 0) -> Scenario:
    """Tier-1 byzantine smoke: ONE double-voting validator, the complete
    slashing pipeline asserted — offense → slasher detection → gossiped
    slashing → op-pool pack → block inclusion → ``slashed`` flag → zeroed
    fork-choice weight — while the honest majority still finalizes.
    Warmup of 7 aligns the fault window on an epoch boundary, so the armed
    validator's one duty slot per epoch is guaranteed inside the window."""
    return Scenario(
        name="byz_double_vote_smoke",
        description="single double-voting validator, slashing pipeline gate",
        seed=seed, node_count=3, validator_count=16,
        warmup_slots=7, fault_slots=8, recovery_slots=24,
        slasher=True,
        events=(
            Event(0, "byzantine",
                  {"strategy": "double_vote", "node": 1, "validators": [1]}),
        ),
        extra_checks=_check_slashing_pipeline,
    )


def byz_minority_equivocation(seed: int = 0) -> Scenario:
    """Minority equivocation under partition: while one node is partitioned
    off, a byzantine proposer on node 1 double-proposes — the honest block
    to everyone, a conflicting block to half the mesh.  The observed-
    producer cache flags the equivocation, the slasher builds the
    ProposerSlashing, and the pipeline gate asserts conviction while the
    partitioned node still reorgs back and the fleet finalizes."""
    return Scenario(
        name="byz_minority_equivocation",
        description="double-proposing validator during a partition",
        seed=seed, node_count=4, validator_count=16,
        warmup_slots=8, fault_slots=16, recovery_slots=24,
        slasher=True,
        events=(
            Event(0, "partition", {"groups": [[0, 1, 2], [3]]}),
            Event(0, "byzantine",
                  {"strategy": "double_propose", "node": 1,
                   "max_offenses": 2}),
            Event(12, "heal"),
        ),
        extra_checks=_check_slashing_pipeline,
    )


def byz_surround_nonfinality(seed: int = 0) -> Scenario:
    """Surround voter during a non-finality spell: >1/3 of validators go
    offline (finality stalls), and a byzantine validator on node 0 seeds an
    honest vote in one epoch then signs a surrounding (source-1, target+1)
    vote the next.  Detection, gossip, and inclusion all happen while
    finality is stalled; the gate then proves conviction and that finality
    resumed past the window after the nodes return."""
    return Scenario(
        name="byz_surround_nonfinality",
        description="surround vote emitted during a non-finality spell",
        seed=seed, node_count=5, validator_count=20,
        warmup_slots=32, fault_slots=24, recovery_slots=24,
        slasher=True,
        events=(
            Event(0, "kill", {"node": 3}),
            Event(0, "kill", {"node": 4}),
            Event(0, "byzantine",
                  {"strategy": "surround_vote", "node": 0,
                   "validators": [0]}),
            Event(16, "restart", {"node": 3}),
            Event(16, "restart", {"node": 4}),
        ),
        extra_checks=_check_surround_pipeline,
    )


def byz_invalid_block_spam(seed: int = 0) -> Scenario:
    """Invalid-block spammer vs peer scoring: forged blocks that are
    perfectly decodable but consensus-invalid (bad state root, wrong
    proposer, future slot, unknown parent) plus malformed gossip
    (truncated SSZ, broken snappy) flood one node.  Every REJECT path must
    count (``gossip_rejected_total``), score, and graylist the forger —
    with zero effect on honest convergence or finality."""
    return Scenario(
        name="byz_invalid_block_spam",
        description="forged invalid blocks + malformed gossip vs scoring",
        seed=seed, node_count=3, validator_count=16,
        warmup_slots=8, fault_slots=8, recovery_slots=16,
        slasher=True,
        events=(
            # the three deterministic REJECT modes; unknown_parent (also
            # implemented) triggers the sync parent-chase, whose wall-clock
            # retry cadence is not determinism-gate material
            Event(0, "byzantine",
                  {"strategy": "invalid_block", "node": 1, "target": 0,
                   "modes": ["bad_state_root", "wrong_proposer",
                             "future_slot"],
                   "count": 3, "max_offenses": 4}),
            Event(1, "byzantine",
                  {"strategy": "malformed_gossip", "node": 1, "target": 0,
                   "count": 8, "max_offenses": 4}),
        ),
        extra_checks=_check_forgers_penalized,
    )


def byz_slashing_flood(seed: int = 0) -> Scenario:
    """Slashing flood at the op-pool cap: three validators double-vote in
    one window, producing more attester slashings than one block may carry
    (``max_attester_slashings``).  The pool must pack deterministically
    under the cap, spread conviction over several blocks, slash all three,
    and then prune itself empty (dead slashings are dropped)."""
    return Scenario(
        name="byz_slashing_flood",
        description="more slashings than one block can carry",
        seed=seed, node_count=3, validator_count=16,
        warmup_slots=7, fault_slots=16, recovery_slots=24,
        slasher=True,
        events=(
            Event(0, "byzantine",
                  {"strategy": "double_vote", "node": 1,
                   "validators": [1, 4, 7], "max_offenses": 3,
                   "burst": True}),
        ),
        extra_checks=_check_slashing_flood,
    )


def byz_invalid_aggregate(seed: int = 0) -> Scenario:
    """Forged ``SignedAggregateAndProof`` wraps vs the aggregate gossip
    rules: HONEST inner attestations (real committee data, a real member's
    signature) wrapped by aggregators that are not in the committee, past
    the registry's end, or simply undecodable SSZ.  Every mode must count
    its REJECT reason on the aggregate topic, score its forger below the
    graylist, and leave honest convergence/finality untouched — the
    aggregate half of ROADMAP item 4's adversarial coverage gap."""
    return Scenario(
        name="byz_invalid_aggregate",
        description="forged aggregate-and-proof wraps vs gossip validation",
        seed=seed, node_count=3, validator_count=16,
        warmup_slots=8, fault_slots=8, recovery_slots=16,
        slasher=True,
        events=(
            Event(0, "byzantine",
                  {"strategy": "invalid_aggregate", "node": 1, "target": 0,
                   "max_offenses": 2}),
        ),
        extra_checks=_check_aggregate_rejected,
    )


def byz_malformed_sync_contribution(seed: int = 0) -> Scenario:
    """Forged ``SignedContributionAndProof`` messages vs the sync gossip
    rules: contributions at the CURRENT slot (the ±1-slot window IGNOREs
    anything else, proving nothing) with an out-of-range subcommittee, a
    subcommittee the aggregator holds no seat in, zero participation bits,
    or undecodable SSZ.  Counts, graylisting, and untouched honest
    finality gate it — the sync half of ROADMAP item 4's coverage gap."""
    return Scenario(
        name="byz_malformed_sync_contribution",
        description="malformed sync contributions vs gossip validation",
        seed=seed, node_count=3, validator_count=16,
        warmup_slots=8, fault_slots=8, recovery_slots=16,
        slasher=True,
        events=(
            Event(0, "byzantine",
                  {"strategy": "malformed_sync_contribution", "node": 1,
                   "target": 0, "max_offenses": 2}),
        ),
        extra_checks=_check_sync_contribution_rejected,
    )


# ------------------------------------------------------- production soaks


def long_horizon_soak(seed: int = 0) -> Scenario:
    """The long-horizon production soak: 128+ epochs of continuous fleet
    operation in minutes of wall time (the virtual clock is what makes the
    horizon affordable), with the whole epoch boundary fused on the device
    backend and a brief partition/heal cycle early in the window.  The
    leak gates then assert the run's residue: bounded rings honored their
    bounds over the whole horizon, counters moved monotonically, and the
    evidence is read back off the blackbox journal itself."""
    return Scenario(
        name="long_horizon_soak",
        description="128-epoch virtual-time soak with leak-check gates",
        seed=seed, node_count=3, validator_count=16,
        warmup_slots=8, fault_slots=8, recovery_slots=1008,
        events=(
            Event(0, "leak_baseline"),
            Event(0, "epoch_device", {"enable": True, "fused": True}),
            Event(2, "partition", {"groups": [[0, 1], [2]]}),
            Event(6, "heal"),
        ),
        extra_checks=_check_long_horizon,
    )


def production_fleet_soak(seed: int = 0) -> Scenario:
    """The production-scale fleet soak: 16 SimNodes sharing thousands of
    validators, every node's duty evaluation riding the device epoch ops
    (shuffling + proposer selection at registry scale), a partition/heal
    cycle mid-window, and the same leak gates as the long-horizon soak.
    Short horizon by design — the axis under test is fleet width and
    registry size, not epoch count."""
    return Scenario(
        name="production_fleet_soak",
        description="16-node fleet at registry scale with leak-check gates",
        seed=seed, node_count=16, validator_count=2048,
        warmup_slots=8, fault_slots=4, recovery_slots=12,
        events=(
            Event(0, "leak_baseline"),
            Event(0, "epoch_device", {"enable": True, "fused": True}),
            Event(1, "partition",
                  {"groups": [list(range(12)), [12, 13, 14, 15]]}),
            Event(3, "heal"),
        ),
        extra_checks=_check_fleet_soak,
    )


# ------------------------------------------------------------ extra checks


def _check_reorg(runner: ScenarioRunner) -> dict:
    """The minority side really forked and really reorged back."""
    forked = max(t["distinct_heads"] for t in runner.timeline)
    assert forked >= 2, "partition never produced distinct heads"
    return {"max_distinct_heads": forked}


def _check_api_load(runner: ScenarioRunner) -> dict:
    """Every probe byte-identical, the cache actually used (hits) and
    actually invalidated by chain traffic — plus one final probe on the
    converged chain."""
    runner._ev_api_probe(label="recovered")
    probes = runner.ctx.get("api_probes") or []
    assert len(probes) >= 3, "api probes did not run"
    for p in probes:
        assert not p["mismatches"], (
            f"cached vs uncached responses diverged: {p['mismatches']}")
    final = probes[-1]["cache"]
    assert final["hits"] > 0, "cache never served a hit"
    assert final["invalidated"] > 0, (
        "head/finalization traffic never invalidated a cache entry")
    # the partition really forked the fleet while we were serving it
    forked = max(t["distinct_heads"] for t in runner.timeline)
    assert forked >= 2, "partition never produced distinct heads"
    return {"api_load": {
        "probes": [{k: p[k] for k in ("label", "n_requests", "digest")}
                   for p in probes],
        "cache": final,
        "max_distinct_heads": forked,
    }}


def _check_stall(runner: ScenarioRunner) -> dict:
    """Finality stalled while >1/3 were offline (the timeline's
    max_finalized must be flat across the first half of the window)."""
    window = runner.timeline[: runner.scenario.fault_slots]
    stalled = window[: 16]
    assert stalled, "no fault-window timeline recorded"
    values = {t["max_finalized_epoch"] for t in stalled}
    assert len(values) == 1, f"finality advanced during the spell: {values}"
    return {"stalled_at_epoch": values.pop()}


def _check_backfill(runner: ScenarioRunner) -> dict:
    sync = runner.ctx.get("backfill")
    assert sync is not None and sync.complete, "backfill did not complete"
    retries = metrics.BACKFILL_BATCH_RETRIES.get(outcome="recovered")
    assert retries >= 1, "dead-peer backfill never exercised the retry path"
    return {"backfill_filled": runner.ctx.get("backfill_filled", 0),
            "backfill_retries_recovered": retries}


def _check_breaker_tripped(runner: ScenarioRunner) -> dict:
    joined = runner.ctx.get("joined")
    assert joined is not None, "join event never ran"
    from . import device_supervisor

    br = device_supervisor.SUPERVISOR.breaker("sha256_pairs")
    snapshot = br.snapshot()
    assert snapshot["trips_total"] >= 1, "breaker never tripped mid-sync"
    return {"breaker": snapshot}


def _check_mesh_resharded(runner: ScenarioRunner) -> dict:
    """The mesh really came up, the killed device really left it, and
    sharded dispatches ran on BOTH topologies (8 before the trip, 7
    after) — otherwise the scenario proved nothing about degradation."""
    from . import device_mesh, device_telemetry

    assert runner.ctx.get("mesh_enabled"), (
        "no device mesh came up — run under "
        "XLA_FLAGS=--xla_force_host_platform_device_count=8")
    assert runner.ctx.get("mesh_tripped"), "the kill event never tripped"
    snap = device_mesh.summary()
    assert snap["reshards_total"] >= 1, "mesh never resharded"
    # <= rather than ==: an ORGANIC per-device trip on top of the scripted
    # kill (a watchdog timeout under gate-box load is exactly what this
    # layer exists to absorb) must not flake the gate
    assert snap["size"] <= runner.ctx["mesh_size"] - 1, snap
    assert 7 not in snap["devices"], "the killed device rejoined the mesh"
    before = runner.ctx.get("meshes_before_trip", [])
    after = {
        r.get("mesh") for r in device_telemetry.FLIGHT_RECORDER.recent(
            limit=device_telemetry.FLIGHT_RECORDER.capacity, op="sha256_pairs")
        if r.get("mesh")
    }
    assert runner.ctx["mesh_size"] in before, (
        f"no sharded dispatch ran on the full mesh before the kill "
        f"(saw {before})")
    assert any(m < runner.ctx["mesh_size"] for m in after), (
        f"no sharded dispatch ran on a shrunk mesh after the kill "
        f"(saw {sorted(after)})")
    return {"mesh": {k: snap[k] for k in
                     ("size", "full_size", "reshards_total", "generation")},
            "sharded_topologies_before_trip": before,
            "sharded_topologies_after_trip": sorted(after)}


def _check_pipeline_active(runner: ScenarioRunner) -> dict:
    """The pipeline really carried traffic AND the breaker really tripped —
    otherwise the scenario proved nothing about their interplay."""
    from . import device_pipeline, device_supervisor

    snap = device_pipeline.summary()
    assert snap is not None and snap["batches_total"] >= 1, (
        "no verification rode the pipeline")
    br = device_supervisor.SUPERVISOR.breaker("sha256_pairs").snapshot()
    assert br["trips_total"] >= 1, "breaker never tripped mid-sync"
    assert snap["pending_groups"] == 0 and snap["in_flight_groups"] == 0, (
        "pipeline did not drain")
    return {"pipeline": {k: snap[k] for k in
                         ("batches_total", "groups_total", "sets_total")},
            "breaker": br}


def _check_hash_pipeline(runner: ScenarioRunner) -> dict:
    """Tree-hash traffic really rode the hash pipeline, the sha breaker
    really tripped (so breaker-open host routing with futures resolving is
    what the convergence gate certified), and everything drained."""
    from . import device_pipeline, device_supervisor

    snap = device_pipeline.summary()
    assert snap is not None, "no pipeline ever started"
    hash_snap = snap.get("hash")
    assert hash_snap is not None and hash_snap["batches_total"] >= 1, (
        "no pair-hash batch rode the hash pipeline")
    assert hash_snap["pending_groups"] == 0 and \
        hash_snap["in_flight_groups"] == 0, "hash pipeline did not drain"
    br = device_supervisor.SUPERVISOR.breaker("sha256_pairs").snapshot()
    assert br["trips_total"] >= 1, "sha breaker never tripped mid-window"
    grants = snap["arbiter"]["grants"]
    assert grants.get("sha256_pairs", 0) >= 1, (
        f"no sha256_pairs arbiter grant recorded ({grants})")
    return {
        "hash_pipeline": {k: hash_snap[k] for k in
                          ("batches_total", "groups_total", "blocks_total")},
        "arbiter_grants": grants,
        "breaker": br,
    }


def _check_autotune_pinned(runner: ScenarioRunner) -> dict:
    """The pinned decision list really replayed — same sequence, same
    evaluation indices, guardrails live — and the device fault plan really
    bit (so the replay happened under the degraded conditions the scenario
    advertises).  Identity of this evidence ACROSS the two gate runs is
    what the matrix's head comparison certifies."""
    from . import autotune, device_supervisor

    log_ = autotune.CONTROLLER.decision_log()
    applied = [(d["action"], d["bucket"], d["evaluation"], d["outcome"])
               for d in log_ if d.get("knob") == "bucket"]
    assert applied == [
        ("adopt", 640, 2, "adopted"),
        ("drop", 640, 6, "dropped"),
        ("adopt", 900, 8, "refused_no_budget"),
    ], f"pinned replay diverged: {applied}"
    assert all(d.get("via") == "pin" for d in log_), log_
    assert autotune.overlay() == {}, (
        "overlay not empty after the pinned drop")
    # the scenario ran the controller every stepped slot (8 fault + 24
    # recovery), so the pin's indices were all reachable
    assert autotune.CONTROLLER.evaluations >= 10, (
        f"only {autotune.CONTROLLER.evaluations} evaluations ran")
    br = device_supervisor.SUPERVISOR.breaker("sha256_pairs").snapshot()
    assert br["trips_total"] >= 1, "sha breaker never tripped: the fault "\
        "plan did not bite"
    return {
        "autotune": {"decisions": applied,
                     "evaluations": autotune.CONTROLLER.evaluations},
        "breaker": br,
    }


def _check_fused_boundary(runner: ScenarioRunner) -> dict:
    """The fault really bit the fused dispatch (breaker tripped), the
    breaker really recovered once the plan cleared (closed at run end),
    boundary dispatches really reached the device, and the duty caches
    really got primed from the fused result — convergence + finality
    gates having passed is the verdict-identity evidence."""
    from . import device_supervisor, device_telemetry

    br = device_supervisor.SUPERVISOR.breaker("epoch_boundary").snapshot()
    assert br["trips_total"] >= 1, (
        "epoch_boundary breaker never tripped: the fault plan did not bite")
    assert br["state"] == "closed", (
        f"epoch_boundary breaker did not recover after the plan cleared: "
        f"{br}")
    recs = device_telemetry.FLIGHT_RECORDER.recent(
        limit=device_telemetry.FLIGHT_RECORDER.capacity, op="epoch_boundary")
    assert recs, "no fused boundary dispatch ever completed on the device"
    primes = device_telemetry.boundary_prime_counts()
    seeded = sum(v for k, v in primes.items() if k.startswith("seeded:"))
    assert seeded >= 1, (
        f"the fused boundary never seeded a duty cache ({primes})")

    # The black box (ISSUE 17): the injected fault must have frozen a
    # postmortem bundle at the breaker trip, and the bundle's journal
    # window must show the incident causally — the fault firing BEFORE the
    # breaker transition it caused, with the host-fallback verdict present
    # (the pre-trip fallback is IN the trip-time bundle; the tripping
    # batch's own fallback resolves after the freeze and must appear in
    # the live journal after the transition).
    caps = [c for c in blackbox.captures()
            if c["reason"] == "breaker_open:epoch_boundary"]
    assert caps, "no postmortem bundle captured at the injected fault"
    bundle = None
    for cap in reversed(caps):
        try:
            with open(cap["path"]) as f:
                bundle = json.load(f)
            break
        except (OSError, ValueError):
            continue  # pruned by retention — try the next-newest capture
    assert bundle is not None, "no captured bundle readable from disk"
    window = bundle["journal"]

    def _seqs(pred):
        return [r["seq"] for r in window if pred(r)]

    fault_seqs = _seqs(lambda r: r["source"] == "fault"
                       and r.get("op") == "epoch_boundary")
    open_seqs = _seqs(lambda r: r["source"] == "breaker"
                      and r.get("op") == "epoch_boundary"
                      and r.get("to") == "open")
    fb_seqs = _seqs(lambda r: r["source"] == "supervisor"
                    and r["event"] == "host_fallback"
                    and r.get("op") == "epoch_boundary")
    assert fault_seqs and open_seqs, (
        f"bundle journal missing the incident "
        f"(faults={fault_seqs}, opens={open_seqs})")
    assert min(fault_seqs) < min(open_seqs), (
        "fault firing did not precede the breaker trip in the journal")
    assert fb_seqs, "no host-fallback verdict in the bundle journal"
    # ... and the live journal carries the tripping batch's fallback AFTER
    # the transition: fault -> open -> host_fallback, in seq order.
    live = blackbox.JOURNAL.window()
    live_opens = [r["seq"] for r in live if r["source"] == "breaker"
                  and r.get("op") == "epoch_boundary"
                  and r.get("to") == "open"]
    live_fbs = [r["seq"] for r in live if r["source"] == "supervisor"
                and r["event"] == "host_fallback"
                and r.get("op") == "epoch_boundary"]
    assert live_opens and live_fbs and max(live_fbs) > min(live_opens), (
        "no host-fallback verdict followed the breaker trip in the journal")
    return {"breaker": br,
            "device_boundary_dispatches": len(recs),
            "boundary_primes": primes,
            "postmortem": {
                "captured": True,
                "journal_records": len(window),
                "fault_before_trip": True,
                "host_fallback_records": len(fb_seqs),
            }}


def _check_spammer_penalized(runner: ScenarioRunner) -> dict:
    spammer_id, victim = runner.ctx["spammer"]
    score = victim.node.service.peer_manager._peer(spammer_id).score
    assert score < 0, f"spammer was never penalized (score {score})"
    return {"spammer_score": score}


def _check_slashing_pipeline(runner: ScenarioRunner) -> dict:
    """The end-to-end byzantine gate — see adversary.slashing_pipeline_gate."""
    from .adversary import slashing_pipeline_gate

    return slashing_pipeline_gate(runner)


def _check_surround_pipeline(runner: ScenarioRunner) -> dict:
    """Pipeline gate + the spell really stalled finality (the shared
    ``_check_stall`` assertion) and the conviction really was a surround."""
    gate = _check_slashing_pipeline(runner)
    kinds = {e["strategy"] for e in gate["slashing_pipeline"]}
    assert "surround_vote" in kinds, f"no surround conviction (got {kinds})"
    gate.update(_check_stall(runner))
    return gate


def _check_forgers_penalized(runner: ScenarioRunner) -> dict:
    """Every forger identity scored below the graylist; the REJECT reasons
    all counted; the honest mesh converged regardless (standard gates)."""
    from .network import service as service_mod

    byz = runner.ctx.get("byz")
    assert byz is not None and byz.forger_ids, "no forger ever attacked"
    assert any(o.strategy == "invalid_block" for o in byz.offenses), (
        "no invalid blocks were emitted")
    assert any(o.strategy == "malformed_gossip" for o in byz.offenses), (
        "no malformed gossip was emitted")
    victim = runner._node(0)
    pm = victim.node.service.peer_manager
    forgers = {}
    for forger in byz.forger_ids:
        info = pm.peers.get(forger)
        assert info is not None, f"forger {forger} was never scored"
        forgers[forger] = round(info.score, 1)
        assert info.score < service_mod.GRAYLIST_THRESHOLD, (
            f"forger {forger} not graylisted (score {info.score})")
    # deltas against the controller's creation-time snapshot: the counter is
    # process-cumulative and must not satisfy a later run vacuously
    rejected = {
        "invalid_block": service_mod.GOSSIP_REJECTED.delta(
            byz.rejected_baseline, topic="beacon_block",
            reason="invalid_block"),
        "undecodable": service_mod.GOSSIP_REJECTED.delta(
            byz.rejected_baseline, topic="beacon_block",
            reason="undecodable"),
        "bad_snappy": service_mod.GOSSIP_REJECTED.delta(
            byz.rejected_baseline, topic="attester_slashing",
            reason="bad_snappy"),
    }
    for reason, count in rejected.items():
        assert count >= 1, f"gossip_rejected_total never counted {reason}"
    return {"forger_scores": forgers, "gossip_rejected": rejected}


def _forger_scores_graylisted(runner: ScenarioRunner) -> dict:
    """Every forger identity the controller laundered traffic through must
    have been scored below the graylist on the victim (node 0)."""
    from .network import service as service_mod

    byz = runner.ctx["byz"]
    pm = runner._node(0).node.service.peer_manager
    forgers = {}
    for forger in byz.forger_ids:
        info = pm.peers.get(forger)
        assert info is not None, f"forger {forger} was never scored"
        forgers[forger] = round(info.score, 1)
        assert info.score < service_mod.GRAYLIST_THRESHOLD, (
            f"forger {forger} not graylisted (score {info.score})")
    return forgers


def _check_aggregate_rejected(runner: ScenarioRunner) -> dict:
    """Every forged-aggregate mode REJECTed and counted on the aggregate
    topic, every forger graylisted, honest convergence untouched (the
    runner's standard gates)."""
    from .network import service as service_mod

    byz = runner.ctx.get("byz")
    assert byz is not None and byz.forger_ids, "no forger ever attacked"
    assert any(o.strategy == "invalid_aggregate" for o in byz.offenses), (
        "no forged aggregates were emitted")
    forgers = _forger_scores_graylisted(runner)
    # deltas against the controller's creation-time snapshot — see
    # _check_forgers_penalized.  Both committee-rule modes (outside the
    # committee, index past the registry) land on invalid_attestation;
    # the truncation mode lands on undecodable.
    rejected = {
        "invalid_attestation": service_mod.GOSSIP_REJECTED.delta(
            byz.rejected_baseline, topic="beacon_aggregate_and_proof",
            reason="invalid_attestation"),
        "undecodable": service_mod.GOSSIP_REJECTED.delta(
            byz.rejected_baseline, topic="beacon_aggregate_and_proof",
            reason="undecodable"),
    }
    for reason, count in rejected.items():
        assert count >= 1, f"gossip_rejected_total never counted {reason}"
    return {"forger_scores": forgers, "gossip_rejected": rejected}


def _check_sync_contribution_rejected(runner: ScenarioRunner) -> dict:
    """Every malformed-contribution mode REJECTed and counted on the sync
    contribution topic, every forger graylisted, honest convergence
    untouched (the runner's standard gates)."""
    from .network import service as service_mod

    byz = runner.ctx.get("byz")
    assert byz is not None and byz.forger_ids, "no forger ever attacked"
    assert any(o.strategy == "malformed_sync_contribution"
               for o in byz.offenses), "no forged contributions were emitted"
    forgers = _forger_scores_graylisted(runner)
    # the three contribution-rule modes (bad subcommittee, no seat in the
    # subcommittee, zero participation) all land on invalid_op; the
    # truncation mode lands on undecodable
    rejected = {
        "invalid_op": service_mod.GOSSIP_REJECTED.delta(
            byz.rejected_baseline,
            topic="sync_committee_contribution_and_proof",
            reason="invalid_op"),
        "undecodable": service_mod.GOSSIP_REJECTED.delta(
            byz.rejected_baseline,
            topic="sync_committee_contribution_and_proof",
            reason="undecodable"),
    }
    for reason, count in rejected.items():
        assert count >= 1, f"gossip_rejected_total never counted {reason}"
    return {"forger_scores": forgers, "gossip_rejected": rejected}


def _check_leak_gates(runner: ScenarioRunner) -> dict:
    """The production-soak leak gates.  Each gate reads the same surface
    an operator triages from (``blackbox.summary()``, the flight ring, the
    scoped journals, the metrics registry), diffs it against the
    ``leak_baseline`` snapshot taken at the fault window's start, and
    counts its verdict on ``soak_leak_checks_total`` before the combined
    assert fires — a failed soak still exports which gate leaked."""
    from . import device_telemetry, telemetry_scope as ts

    base = runner.ctx.get("leak_baseline")
    assert base is not None, "no leak_baseline event armed the gates"
    evidence: Dict[str, Any] = {}
    failures: List[str] = []

    def gate(name: str, ok: bool, detail) -> None:
        SOAK_LEAK_CHECKS.inc(gate=name, outcome="passed" if ok else "failed")
        evidence[name] = {"passed": bool(ok), "detail": detail}
        if not ok:
            failures.append(name)

    js = blackbox.summary()["journal"]
    gate("journal_bounded", js["stored"] <= js["capacity"], dict(js))
    gate("journal_monotone",
         js["emitted_total"] >= js["stored"]
         and js["emitted_total"] > base["journal_emitted"],
         {"emitted_total": js["emitted_total"],
          "at_baseline": base["journal_emitted"]})
    ring = device_telemetry.FLIGHT_RECORDER
    flight = {"stored": len(ring), "capacity": ring.capacity,
              "recorded_total": ring.recorded_total}
    gate("flight_ring_bounded", flight["stored"] <= flight["capacity"],
         flight)
    gate("flight_ring_monotone",
         flight["recorded_total"] >= flight["stored"]
         and flight["recorded_total"] >= base["flight_recorded"], flight)
    scoped, scoped_ok = {}, True
    for s in ts.all_scopes():
        j = s.journal
        ok = len(j) <= j.capacity and j.emitted_total >= len(j)
        scoped_ok = scoped_ok and ok
        scoped[s.node_id] = {"stored": len(j), "capacity": j.capacity,
                             "emitted_total": j.emitted_total}
    gate("scoped_journals_bounded", scoped_ok and bool(scoped), scoped)
    regressed: List[str] = []
    with metrics._REGISTRY_LOCK:
        current = {name: m.snapshot()
                   for name, m in metrics._REGISTRY.items()
                   if isinstance(m, metrics.Counter)}
    for name, baseline in sorted(base["counters"].items()):
        now = current.get(name)
        if now is None:
            regressed.append(f"{name}: vanished from the registry")
            continue
        for key, value in baseline.items():
            if now.get(key, 0.0) < value:
                regressed.append(
                    f"{name}{dict(key)}: {now.get(key, 0.0)} < {value}")
    gate("counters_monotone", not regressed,
         regressed or {"counters_checked": len(base["counters"])})
    assert not failures, (
        f"leak gates failed: {failures} — "
        + "; ".join(f"{n}={evidence[n]['detail']}" for n in failures))
    return {"leak_gates": evidence}


def _check_long_horizon(runner: ScenarioRunner) -> dict:
    """Leak gates plus the horizon itself: the fleet really stepped 128+
    epochs of virtual time, heads kept proposing across the whole span,
    and the fused device boundary seeded duty caches throughout."""
    from . import device_telemetry

    out = _check_leak_gates(runner)
    spec = runner.sim.live_nodes[0].harness.spec
    last_slot = runner.timeline[-1]["slot"]
    epochs = last_slot // spec.slots_per_epoch
    assert epochs >= 128, f"soak only reached epoch {epochs}"
    head_slot = runner.sim.live_nodes[0].chain.head_slot()
    assert head_slot >= last_slot - spec.slots_per_epoch, (
        f"head stalled at slot {head_slot} of {last_slot}")
    primes = device_telemetry.boundary_prime_counts()
    seeded = sum(v for k, v in primes.items() if k.startswith("seeded:"))
    assert seeded >= epochs, (
        f"fused boundary seeded {seeded} duty caches over {epochs} epochs")
    out["horizon"] = {"epochs": epochs, "head_slot": head_slot,
                      "boundary_seeded": seeded}
    return out


def _check_fleet_soak(runner: ScenarioRunner) -> dict:
    """Leak gates plus the fleet-scale evidence: all 16 nodes converged
    (standard gates), the registry really was thousands of validators,
    and epoch processing really rode the device backend."""
    from . import device_telemetry

    out = _check_leak_gates(runner)
    sim = runner.sim
    assert len(sim.nodes) >= 16, f"only {len(sim.nodes)} nodes"
    n_validators = len(sim.live_nodes[0].chain.head_state.validators)
    assert n_validators >= 2048, f"only {n_validators} validators"
    primes = device_telemetry.boundary_prime_counts()
    seeded = sum(v for k, v in primes.items() if k.startswith("seeded:"))
    assert seeded >= 1, f"no fused boundary seeded a duty cache ({primes})"
    out["fleet_scale"] = {"nodes": len(sim.nodes),
                          "validators": n_validators,
                          "boundary_seeded": seeded}
    return out


def _check_slashing_flood(runner: ScenarioRunner) -> dict:
    """Pipeline gate for all three offenders + flood-specific evidence: no
    block exceeded max_attester_slashings, conviction took >1 block, and
    every pool pruned itself empty once the offenders were slashed."""
    gate = _check_slashing_pipeline(runner)
    assert len(gate["slashing_pipeline"]) == 3, (
        f"expected 3 convictions, got {len(gate['slashing_pipeline'])}")
    from .adversary import iter_canonical_blocks

    node = runner._node(0)
    chain, spec = node.chain, node.harness.spec
    cap = spec.preset.max_attester_slashings
    blocks_with, total = 0, 0
    for block in iter_canonical_blocks(chain):
        n = len(block.message.body.attester_slashings)
        assert n <= cap, f"block packed {n} slashings (cap {cap})"
        if n:
            blocks_with += 1
            total += n
    assert blocks_with >= 2, (
        "3 slashings against a cap of 2 must spread over >1 block")
    for n_ in runner.sim.live_nodes:
        left = n_.chain.op_pool.num_attester_slashings()
        assert left == 0, (
            f"{n_.peer_id}: {left} dead slashings still pooled after "
            "conviction (prune failed)")
    gate.update({"blocks_with_slashings": blocks_with,
                 "included_slashings_total": total,
                 "per_block_cap": cap})
    return gate


#: name -> factory(seed); the full matrix in documentation order
SCENARIOS: Dict[str, Callable[[int], Scenario]] = {
    "smoke_partition": smoke_partition,
    "api_load": api_load,
    "partition_deep_reorg": partition_deep_reorg,
    "nonfinality_spell": nonfinality_spell,
    "checkpoint_join_lossy": checkpoint_join_lossy,
    "device_breaker_mid_sync": device_breaker_mid_sync,
    "mesh_degradation": mesh_degradation,
    "pipeline_mid_sync": pipeline_mid_sync,
    "state_hash_pipeline": state_hash_pipeline,
    "autotune_pinned": autotune_pinned,
    "fused_epoch_boundary": fused_epoch_boundary,
    "spam_slow_peer": spam_slow_peer,
    "byz_double_vote_smoke": byz_double_vote_smoke,
    "byz_minority_equivocation": byz_minority_equivocation,
    "byz_surround_nonfinality": byz_surround_nonfinality,
    "byz_invalid_block_spam": byz_invalid_block_spam,
    "byz_slashing_flood": byz_slashing_flood,
    "byz_invalid_aggregate": byz_invalid_aggregate,
    "byz_malformed_sync_contribution": byz_malformed_sync_contribution,
    "long_horizon_soak": long_horizon_soak,
    "production_fleet_soak": production_fleet_soak,
}


def run_scenario(name_or_scenario, seed: int = 0,
                 out_dir: Optional[str] = None) -> dict:
    scenario = (SCENARIOS[name_or_scenario](seed)
                if isinstance(name_or_scenario, str) else name_or_scenario)
    return ScenarioRunner(scenario, out_dir=out_dir).run()


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    from .crypto.bls.backends import set_backend

    parser = argparse.ArgumentParser(
        description="deterministic multi-node scenario soak")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--scenario", choices=sorted(SCENARIOS),
                        help="run one scenario (default: the full matrix)")
    parser.add_argument("--out", default=None, help="artifact directory")
    parser.add_argument("--runs", type=int, default=1,
                        help="repeat each scenario N times and require "
                             "identical final head roots (determinism gate)")
    args = parser.parse_args(argv)

    set_backend("fake")
    names = [args.scenario] if args.scenario else list(SCENARIOS)
    failures = []
    for name in names:
        heads = []
        for run_index in range(max(1, args.runs)):
            print(f"=== {name} (seed {args.seed}, run {run_index + 1}) ===")
            try:
                artifact = run_scenario(name, seed=args.seed, out_dir=args.out)
            except Exception as e:  # noqa: BLE001 — report, keep the matrix going
                print(f"FAIL {name}: {e}")
                failures.append(name)
                break
            result = artifact["result"]
            heads.append(result["head_root"])
            print(f"ok {name}: head {result['head_root'][:16]} "
                  f"finalized {result['final_finalized_epoch']} "
                  f"({artifact['duration_s']}s) -> "
                  f"{artifact.get('artifact_path', '-')}")
        if len(set(heads)) > 1:
            print(f"FAIL {name}: nondeterministic heads {heads}")
            failures.append(name)
    if failures:
        print(f"scenario soak: FAILED {sorted(set(failures))}")
        return 1
    print(f"scenario soak: OK ({len(names)} scenarios)")
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
