"""Chain presets and runtime spec constants.

Two-level split mirroring the reference (``consensus/types``):

- ``Preset`` — the compile-time ``EthSpec`` typenum sizes (eth_spec.rs:53…):
  container capacities and epoch geometry.  Mainnet / Minimal / Gnosis.
- ``ChainSpec`` — runtime-tunable constants (chain_spec.rs:86…): fork schedule,
  balances, rewards, domains, time parameters.  Loadable/overridable from the
  standard config-YAML key set.

Values are the canonical consensus-spec presets (phase0 → deneb), the same data
the reference embeds from the specs repo.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field, fields as dc_fields
from typing import Dict, Optional

FAR_FUTURE_EPOCH = 2**64 - 1
GENESIS_EPOCH = 0
GENESIS_SLOT = 0

# BLS domain types (domain constants are identical across presets).
DOMAIN_BEACON_PROPOSER = bytes.fromhex("00000000")
DOMAIN_BEACON_ATTESTER = bytes.fromhex("01000000")
DOMAIN_RANDAO = bytes.fromhex("02000000")
DOMAIN_DEPOSIT = bytes.fromhex("03000000")
DOMAIN_VOLUNTARY_EXIT = bytes.fromhex("04000000")
DOMAIN_SELECTION_PROOF = bytes.fromhex("05000000")
DOMAIN_AGGREGATE_AND_PROOF = bytes.fromhex("06000000")
DOMAIN_SYNC_COMMITTEE = bytes.fromhex("07000000")
DOMAIN_SYNC_COMMITTEE_SELECTION_PROOF = bytes.fromhex("08000000")
DOMAIN_CONTRIBUTION_AND_PROOF = bytes.fromhex("09000000")
DOMAIN_BLS_TO_EXECUTION_CHANGE = bytes.fromhex("0A000000")
DOMAIN_APPLICATION_BUILDER = bytes.fromhex("00000001")
DOMAIN_APPLICATION_MASK = bytes.fromhex("00000001")

# Altair participation flag indices / weights.
TIMELY_SOURCE_FLAG_INDEX = 0
TIMELY_TARGET_FLAG_INDEX = 1
TIMELY_HEAD_FLAG_INDEX = 2
TIMELY_SOURCE_WEIGHT = 14
TIMELY_TARGET_WEIGHT = 26
TIMELY_HEAD_WEIGHT = 14
SYNC_REWARD_WEIGHT = 2
PROPOSER_WEIGHT = 8
WEIGHT_DENOMINATOR = 64
PARTICIPATION_FLAG_WEIGHTS = [
    TIMELY_SOURCE_WEIGHT,
    TIMELY_TARGET_WEIGHT,
    TIMELY_HEAD_WEIGHT,
]


@dataclass(frozen=True)
class Preset:
    """Compile-time sizes (the reference's EthSpec trait, eth_spec.rs:53)."""

    name: str
    # Misc / geometry
    slots_per_epoch: int
    max_committees_per_slot: int
    target_committee_size: int
    max_validators_per_committee: int
    shuffle_round_count: int
    hysteresis_quotient: int = 4
    hysteresis_downward_multiplier: int = 1
    hysteresis_upward_multiplier: int = 5
    # State list lengths
    epochs_per_eth1_voting_period: int = 64
    slots_per_historical_root: int = 8192
    epochs_per_historical_vector: int = 65536
    epochs_per_slashings_vector: int = 8192
    historical_roots_limit: int = 2**24
    validator_registry_limit: int = 2**40
    # Max operations per block
    max_proposer_slashings: int = 16
    max_attester_slashings: int = 2
    max_attestations: int = 128
    max_deposits: int = 16
    max_voluntary_exits: int = 16
    # Altair
    sync_committee_size: int = 512
    epochs_per_sync_committee_period: int = 256
    min_sync_committee_participants: int = 1
    # Bellatrix (execution payload)
    max_bytes_per_transaction: int = 2**30
    max_transactions_per_payload: int = 2**20
    bytes_per_logs_bloom: int = 256
    max_extra_data_bytes: int = 32
    # Capella
    max_withdrawals_per_payload: int = 16
    max_validators_per_withdrawals_sweep: int = 16384
    max_bls_to_execution_changes: int = 16
    # Deneb
    max_blob_commitments_per_block: int = 4096
    field_elements_per_blob: int = 4096
    # Electra (EIP-7251/7549/7002/6110)
    max_attestations_electra: int = 8
    max_attester_slashings_electra: int = 1
    max_deposit_requests_per_payload: int = 8192
    max_withdrawal_requests_per_payload: int = 16
    max_consolidation_requests_per_payload: int = 2
    pending_deposits_limit: int = 2**27
    pending_partial_withdrawals_limit: int = 2**27
    pending_consolidations_limit: int = 2**18
    max_pending_partials_per_withdrawals_sweep: int = 8
    max_pending_deposits_per_epoch: int = 16


MAINNET_PRESET = Preset(
    name="mainnet",
    slots_per_epoch=32,
    max_committees_per_slot=64,
    target_committee_size=128,
    max_validators_per_committee=2048,
    shuffle_round_count=90,
)

MINIMAL_PRESET = Preset(
    name="minimal",
    slots_per_epoch=8,
    max_committees_per_slot=4,
    target_committee_size=4,
    max_validators_per_committee=2048,
    shuffle_round_count=10,
    epochs_per_eth1_voting_period=4,
    slots_per_historical_root=64,
    epochs_per_historical_vector=64,
    epochs_per_slashings_vector=64,
    sync_committee_size=32,
    epochs_per_sync_committee_period=8,
    max_withdrawals_per_payload=4,
    max_validators_per_withdrawals_sweep=16,
    max_blob_commitments_per_block=16,
    max_deposit_requests_per_payload=4,
    max_withdrawal_requests_per_payload=2,
    max_consolidation_requests_per_payload=1,
    max_pending_partials_per_withdrawals_sweep=1,
    pending_partial_withdrawals_limit=64,
    pending_consolidations_limit=64,
)

# Gnosis preset (presets/gnosis/*.yaml): mainnet sizes except the faster
# epoch geometry and smaller withdrawals sweep.
GNOSIS_PRESET = dataclasses.replace(
    MAINNET_PRESET,
    name="gnosis",
    slots_per_epoch=16,
    epochs_per_sync_committee_period=512,
    max_withdrawals_per_payload=8,
    max_validators_per_withdrawals_sweep=8192,
)


@dataclass
class ChainSpec:
    """Runtime constants (the reference's ChainSpec, chain_spec.rs:86…)."""

    preset: Preset = MAINNET_PRESET
    config_name: str = "mainnet"

    # Time
    seconds_per_slot: int = 12
    genesis_delay: int = 604800
    min_genesis_time: int = 1606824000
    eth1_follow_distance: int = 2048
    seconds_per_eth1_block: int = 14
    min_genesis_active_validator_count: int = 16384
    min_attestation_inclusion_delay: int = 1
    min_seed_lookahead: int = 1
    max_seed_lookahead: int = 4
    min_epochs_to_inactivity_penalty: int = 4
    min_validator_withdrawability_delay: int = 256
    shard_committee_period: int = 256
    # Fork schedule (version bytes + activation epochs)
    genesis_fork_version: bytes = bytes.fromhex("00000000")
    altair_fork_version: bytes = bytes.fromhex("01000000")
    altair_fork_epoch: Optional[int] = 74240
    bellatrix_fork_version: bytes = bytes.fromhex("02000000")
    bellatrix_fork_epoch: Optional[int] = 144896
    capella_fork_version: bytes = bytes.fromhex("03000000")
    capella_fork_epoch: Optional[int] = 194048
    deneb_fork_version: bytes = bytes.fromhex("04000000")
    deneb_fork_epoch: Optional[int] = 269568
    electra_fork_version: bytes = bytes.fromhex("05000000")
    electra_fork_epoch: Optional[int] = None
    # Balances / deposits (Gwei)
    min_deposit_amount: int = 10**9
    max_effective_balance: int = 32 * 10**9
    effective_balance_increment: int = 10**9
    ejection_balance: int = 16 * 10**9
    # Validator cycle
    min_per_epoch_churn_limit: int = 4
    churn_limit_quotient: int = 65536
    max_per_epoch_activation_churn_limit: int = 8
    # Rewards & penalties
    base_reward_factor: int = 64
    whistleblower_reward_quotient: int = 512
    proposer_reward_quotient: int = 8
    inactivity_penalty_quotient: int = 2**26
    min_slashing_penalty_quotient: int = 128
    proportional_slashing_multiplier: int = 1
    # Altair reward/penalty revisions
    inactivity_penalty_quotient_altair: int = 3 * 2**24
    min_slashing_penalty_quotient_altair: int = 64
    proportional_slashing_multiplier_altair: int = 2
    inactivity_score_bias: int = 4
    inactivity_score_recovery_rate: int = 16
    # Bellatrix revisions
    inactivity_penalty_quotient_bellatrix: int = 2**24
    min_slashing_penalty_quotient_bellatrix: int = 32
    proportional_slashing_multiplier_bellatrix: int = 3
    terminal_total_difficulty: int = 58750000000000000000000
    terminal_block_hash: bytes = b"\x00" * 32
    terminal_block_hash_activation_epoch: int = FAR_FUTURE_EPOCH
    # Fork choice
    intervals_per_slot: int = 3
    proposer_score_boost: int = 40
    reorg_head_weight_threshold: int = 20
    reorg_parent_weight_threshold: int = 160
    reorg_max_epochs_since_finalization: int = 2
    # Deposit contract
    deposit_chain_id: int = 1
    deposit_network_id: int = 1
    deposit_contract_address: bytes = bytes.fromhex("00000000219ab540356cbb839cbe05303d7705fa")
    # Networking-adjacent constants used by validator duties
    target_aggregators_per_committee: int = 16
    attestation_subnet_count: int = 64
    sync_committee_subnet_count: int = 4
    target_aggregators_per_sync_subcommittee: int = 16
    # Deneb
    max_blobs_per_block: int = 6
    min_epochs_for_blob_sidecars_requests: int = 4096
    # Electra
    max_effective_balance_electra: int = 2048 * 10**9
    min_activation_balance: int = 32 * 10**9
    min_per_epoch_churn_limit_electra: int = 128 * 10**9
    max_per_epoch_activation_exit_churn_limit: int = 256 * 10**9
    min_slashing_penalty_quotient_electra: int = 4096
    whistleblower_reward_quotient_electra: int = 4096
    max_blobs_per_block_electra: int = 9
    full_exit_request_amount: int = 0
    compounding_withdrawal_prefix: bytes = b"\x02"
    unset_deposit_requests_start_index: int = FAR_FUTURE_EPOCH

    # ------------------------------------------------------------- helpers

    @property
    def slots_per_epoch(self) -> int:
        return self.preset.slots_per_epoch

    def fork_name_at_epoch(self, epoch: int) -> str:
        if self.electra_fork_epoch is not None and epoch >= self.electra_fork_epoch:
            return "electra"
        if self.deneb_fork_epoch is not None and epoch >= self.deneb_fork_epoch:
            return "deneb"
        if self.capella_fork_epoch is not None and epoch >= self.capella_fork_epoch:
            return "capella"
        if self.bellatrix_fork_epoch is not None and epoch >= self.bellatrix_fork_epoch:
            return "bellatrix"
        if self.altair_fork_epoch is not None and epoch >= self.altair_fork_epoch:
            return "altair"
        return "phase0"

    def fork_name_at_slot(self, slot: int) -> str:
        return self.fork_name_at_epoch(slot // self.slots_per_epoch)

    def attestation_includable(self, att_slot: int, state_slot: int) -> bool:
        """Is an attestation from ``att_slot`` includable in a block at
        ``state_slot``?  Pre-Deneb: within one epoch of slots.  Post-Deneb
        (EIP-7045): any current- or previous-epoch attestation.  Single source
        of truth for both the naive pool and the op pool."""
        if att_slot + self.min_attestation_inclusion_delay > state_slot:
            return False
        if self.fork_name_at_slot(state_slot) in (
            "phase0", "altair", "bellatrix", "capella",
        ):
            return att_slot + self.slots_per_epoch >= state_slot
        return (
            att_slot // self.slots_per_epoch + 1 >= state_slot // self.slots_per_epoch
        )

    def fork_version_for(self, fork_name: str) -> bytes:
        return {
            "phase0": self.genesis_fork_version,
            "altair": self.altair_fork_version,
            "bellatrix": self.bellatrix_fork_version,
            "capella": self.capella_fork_version,
            "deneb": self.deneb_fork_version,
            "electra": self.electra_fork_version,
        }[fork_name]

    def fork_epoch_for(self, fork_name: str) -> Optional[int]:
        return {
            "phase0": 0,
            "altair": self.altair_fork_epoch,
            "bellatrix": self.bellatrix_fork_epoch,
            "capella": self.capella_fork_epoch,
            "deneb": self.deneb_fork_epoch,
            "electra": self.electra_fork_epoch,
        }[fork_name]

    # Spec helper: integer_squareroot
    @staticmethod
    def integer_squareroot(n: int) -> int:
        import math

        return math.isqrt(n)


def mainnet_spec() -> ChainSpec:
    return ChainSpec()


def minimal_spec(**overrides) -> ChainSpec:
    """Minimal-preset spec as the reference test harness uses it: all forks
    enabled from genesis unless overridden (BeaconChainHarness defaults)."""
    base = dict(
        preset=MINIMAL_PRESET,
        config_name="minimal",
        seconds_per_slot=6,
        min_genesis_active_validator_count=64,
        churn_limit_quotient=32,
        shard_committee_period=64,
        min_validator_withdrawability_delay=256,
        # minimal-preset penalty parameters (presets/minimal/phase0.yaml —
        # they differ from mainnet and were silently inheriting it)
        inactivity_penalty_quotient=2**25,
        min_slashing_penalty_quotient=64,
        proportional_slashing_multiplier=2,
        altair_fork_epoch=0,
        bellatrix_fork_epoch=0,
        capella_fork_epoch=0,
        deneb_fork_epoch=0,
        electra_fork_epoch=None,
        altair_fork_version=bytes.fromhex("01000001"),
        bellatrix_fork_version=bytes.fromhex("02000001"),
        capella_fork_version=bytes.fromhex("03000001"),
        deneb_fork_version=bytes.fromhex("04000001"),
        electra_fork_version=bytes.fromhex("05000001"),
        genesis_fork_version=bytes.fromhex("00000001"),
    )
    base.update(overrides)
    return ChainSpec(**base)


def gnosis_spec() -> ChainSpec:
    return ChainSpec(
        preset=GNOSIS_PRESET,
        config_name="gnosis",
        seconds_per_slot=5,
        churn_limit_quotient=4096,
        genesis_fork_version=bytes.fromhex("00000064"),
        altair_fork_version=bytes.fromhex("01000064"),
        altair_fork_epoch=512,
        bellatrix_fork_version=bytes.fromhex("02000064"),
        bellatrix_fork_epoch=385536,
        capella_fork_version=bytes.fromhex("03000064"),
        capella_fork_epoch=648704,
        deneb_fork_version=bytes.fromhex("04000064"),
        deneb_fork_epoch=889856,
        base_reward_factor=25,
    )


SPECS: Dict[str, callable] = {
    "mainnet": mainnet_spec,
    "minimal": minimal_spec,
    "gnosis": gnosis_spec,
}

FORK_ORDER = ["phase0", "altair", "bellatrix", "capella", "deneb", "electra"]


def previous_fork(fork_name: str) -> str:
    i = FORK_ORDER.index(fork_name)
    return FORK_ORDER[max(0, i - 1)]
