"""Consensus containers across forks (phase0 → deneb), preset-parameterized.

The reference expresses fork-variant containers with the ``superstruct`` macro
over compile-time ``EthSpec`` sizes (``consensus/types/src/beacon_state.rs:34``,
``beacon_block_body.rs``).  Here, ``build_types(preset)`` constructs the full
set of SSZ container classes for a preset (Mainnet/Minimal/Gnosis) and returns
a registry; per-fork variants are distinct classes related by explicit
``fork_name`` attributes and upgrade functions (``state_transition/upgrades``).

Field order follows the consensus specs exactly (SSZ stability is
consensus-critical); cross-checked against spec test vectors in tests.
"""

from __future__ import annotations

from functools import lru_cache
from types import SimpleNamespace

from .spec import Preset
from .ssz import (
    Bitlist,
    Bitvector,
    ByteList,
    ByteVector,
    Container,
    List,
    Vector,
    boolean,
    bytes4,
    bytes20,
    bytes32,
    bytes48,
    bytes96,
    uint64,
    uint8,
    uint256,
    _ContainerMeta,
)


@lru_cache(maxsize=None)
def build_types(preset: Preset) -> SimpleNamespace:
    P = preset
    JUSTIFICATION_BITS_LENGTH = 4
    DEPOSIT_CONTRACT_TREE_DEPTH = 32

    ns = SimpleNamespace(preset=P)

    # ---------------------------------------------------------- basic misc

    class Fork(Container):
        fields = {"previous_version": bytes4, "current_version": bytes4, "epoch": uint64}

    class ForkData(Container):
        fields = {"current_version": bytes4, "genesis_validators_root": bytes32}

    class Checkpoint(Container):
        fields = {"epoch": uint64, "root": bytes32}

    class Validator(Container):
        fields = {
            "pubkey": bytes48,
            "withdrawal_credentials": bytes32,
            "effective_balance": uint64,
            "slashed": boolean,
            "activation_eligibility_epoch": uint64,
            "activation_epoch": uint64,
            "exit_epoch": uint64,
            "withdrawable_epoch": uint64,
        }

    class AttestationData(Container):
        fields = {
            "slot": uint64,
            "index": uint64,
            "beacon_block_root": bytes32,
            "source": Checkpoint.ssz_type,
            "target": Checkpoint.ssz_type,
        }

    class IndexedAttestation(Container):
        fields = {
            "attesting_indices": List(uint64, P.max_validators_per_committee),
            "data": AttestationData.ssz_type,
            "signature": bytes96,
        }

    class PendingAttestation(Container):
        fields = {
            "aggregation_bits": Bitlist(P.max_validators_per_committee),
            "data": AttestationData.ssz_type,
            "inclusion_delay": uint64,
            "proposer_index": uint64,
        }

    class Eth1Data(Container):
        fields = {"deposit_root": bytes32, "deposit_count": uint64, "block_hash": bytes32}

    class HistoricalBatch(Container):
        fields = {
            "block_roots": Vector(bytes32, P.slots_per_historical_root),
            "state_roots": Vector(bytes32, P.slots_per_historical_root),
        }

    class DepositMessage(Container):
        fields = {"pubkey": bytes48, "withdrawal_credentials": bytes32, "amount": uint64}

    class DepositData(Container):
        fields = {
            "pubkey": bytes48,
            "withdrawal_credentials": bytes32,
            "amount": uint64,
            "signature": bytes96,
        }

    class BeaconBlockHeader(Container):
        fields = {
            "slot": uint64,
            "proposer_index": uint64,
            "parent_root": bytes32,
            "state_root": bytes32,
            "body_root": bytes32,
        }

    class SignedBeaconBlockHeader(Container):
        fields = {"message": BeaconBlockHeader.ssz_type, "signature": bytes96}

    class SigningData(Container):
        fields = {"object_root": bytes32, "domain": bytes32}

    # ----------------------------------------------------------- operations

    class ProposerSlashing(Container):
        fields = {
            "signed_header_1": SignedBeaconBlockHeader.ssz_type,
            "signed_header_2": SignedBeaconBlockHeader.ssz_type,
        }

    class AttesterSlashing(Container):
        fields = {
            "attestation_1": IndexedAttestation.ssz_type,
            "attestation_2": IndexedAttestation.ssz_type,
        }

    class Attestation(Container):
        fields = {
            "aggregation_bits": Bitlist(P.max_validators_per_committee),
            "data": AttestationData.ssz_type,
            "signature": bytes96,
        }

    class Deposit(Container):
        fields = {
            "proof": Vector(bytes32, DEPOSIT_CONTRACT_TREE_DEPTH + 1),
            "data": DepositData.ssz_type,
        }

    class VoluntaryExit(Container):
        fields = {"epoch": uint64, "validator_index": uint64}

    class SignedVoluntaryExit(Container):
        fields = {"message": VoluntaryExit.ssz_type, "signature": bytes96}

    class SyncAggregate(Container):
        fields = {
            "sync_committee_bits": Bitvector(P.sync_committee_size),
            "sync_committee_signature": bytes96,
        }

    class SyncCommittee(Container):
        fields = {
            "pubkeys": Vector(bytes48, P.sync_committee_size),
            "aggregate_pubkey": bytes48,
        }

    class Withdrawal(Container):
        fields = {
            "index": uint64,
            "validator_index": uint64,
            "address": bytes20,
            "amount": uint64,
        }

    class BLSToExecutionChange(Container):
        fields = {
            "validator_index": uint64,
            "from_bls_pubkey": bytes48,
            "to_execution_address": bytes20,
        }

    class SignedBLSToExecutionChange(Container):
        fields = {"message": BLSToExecutionChange.ssz_type, "signature": bytes96}

    class HistoricalSummary(Container):
        fields = {"block_summary_root": bytes32, "state_summary_root": bytes32}

    # ---------------------------------------------------- execution payloads

    _payload_base = {
        "parent_hash": bytes32,
        "fee_recipient": bytes20,
        "state_root": bytes32,
        "receipts_root": bytes32,
        "logs_bloom": ByteVector(P.bytes_per_logs_bloom),
        "prev_randao": bytes32,
        "block_number": uint64,
        "gas_limit": uint64,
        "gas_used": uint64,
        "timestamp": uint64,
        "extra_data": ByteList(P.max_extra_data_bytes),
        "base_fee_per_gas": uint256,
        "block_hash": bytes32,
    }
    _txs = {"transactions": List(ByteList(P.max_bytes_per_transaction), P.max_transactions_per_payload)}
    _wds = {"withdrawals": List(Withdrawal.ssz_type, P.max_withdrawals_per_payload)}
    _blobgas = {"blob_gas_used": uint64, "excess_blob_gas": uint64}

    class ExecutionPayloadBellatrix(Container):
        fields = {**_payload_base, **_txs}

    class ExecutionPayloadCapella(Container):
        fields = {**_payload_base, **_txs, **_wds}

    class ExecutionPayloadDeneb(Container):
        fields = {**_payload_base, **_txs, **_wds, **_blobgas}

    _hdr_base = dict(_payload_base)
    _hdr_base["transactions_root"] = bytes32

    class ExecutionPayloadHeaderBellatrix(Container):
        fields = dict(_hdr_base)

    class ExecutionPayloadHeaderCapella(Container):
        fields = {**_hdr_base, "withdrawals_root": bytes32}

    class ExecutionPayloadHeaderDeneb(Container):
        fields = {**_hdr_base, "withdrawals_root": bytes32, **_blobgas}

    # -------------------------------------------------------- block bodies

    _body_base = {
        "randao_reveal": bytes96,
        "eth1_data": Eth1Data.ssz_type,
        "graffiti": bytes32,
        "proposer_slashings": List(ProposerSlashing.ssz_type, P.max_proposer_slashings),
        "attester_slashings": List(AttesterSlashing.ssz_type, P.max_attester_slashings),
        "attestations": List(Attestation.ssz_type, P.max_attestations),
        "deposits": List(Deposit.ssz_type, P.max_deposits),
        "voluntary_exits": List(SignedVoluntaryExit.ssz_type, P.max_voluntary_exits),
    }
    _sync_agg = {"sync_aggregate": SyncAggregate.ssz_type}
    _blschanges = {
        "bls_to_execution_changes": List(
            SignedBLSToExecutionChange.ssz_type, P.max_bls_to_execution_changes
        )
    }
    _blobkzg = {
        "blob_kzg_commitments": List(bytes48, P.max_blob_commitments_per_block)
    }

    class BeaconBlockBodyPhase0(Container):
        fork_name = "phase0"
        fields = dict(_body_base)

    class BeaconBlockBodyAltair(Container):
        fork_name = "altair"
        fields = {**_body_base, **_sync_agg}

    class BeaconBlockBodyBellatrix(Container):
        fork_name = "bellatrix"
        fields = {**_body_base, **_sync_agg, "execution_payload": ExecutionPayloadBellatrix.ssz_type}

    class BeaconBlockBodyCapella(Container):
        fork_name = "capella"
        fields = {
            **_body_base,
            **_sync_agg,
            "execution_payload": ExecutionPayloadCapella.ssz_type,
            **_blschanges,
        }

    class BeaconBlockBodyDeneb(Container):
        fork_name = "deneb"
        fields = {
            **_body_base,
            **_sync_agg,
            "execution_payload": ExecutionPayloadDeneb.ssz_type,
            **_blschanges,
            **_blobkzg,
        }

    # ------------------------------------------------------------- electra
    # EIP-7549: attestations span all committees of a slot, selected by
    # committee_bits; EIP-6110/7002/7251: execution-triggered requests ride
    # in an ExecutionRequests block-body field.

    _electra_agg_limit = P.max_validators_per_committee * P.max_committees_per_slot

    class AttestationElectra(Container):
        fields = {
            "aggregation_bits": Bitlist(_electra_agg_limit),
            "data": AttestationData.ssz_type,
            "signature": bytes96,
            "committee_bits": Bitvector(P.max_committees_per_slot),
        }

    class IndexedAttestationElectra(Container):
        fields = {
            "attesting_indices": List(uint64, _electra_agg_limit),
            "data": AttestationData.ssz_type,
            "signature": bytes96,
        }

    class AttesterSlashingElectra(Container):
        fields = {
            "attestation_1": IndexedAttestationElectra.ssz_type,
            "attestation_2": IndexedAttestationElectra.ssz_type,
        }

    class DepositRequest(Container):
        fields = {
            "pubkey": bytes48,
            "withdrawal_credentials": bytes32,
            "amount": uint64,
            "signature": bytes96,
            "index": uint64,
        }

    class WithdrawalRequest(Container):
        fields = {
            "source_address": bytes20,
            "validator_pubkey": bytes48,
            "amount": uint64,
        }

    class ConsolidationRequest(Container):
        fields = {
            "source_address": bytes20,
            "source_pubkey": bytes48,
            "target_pubkey": bytes48,
        }

    class ExecutionRequests(Container):
        fields = {
            "deposits": List(DepositRequest.ssz_type, P.max_deposit_requests_per_payload),
            "withdrawals": List(
                WithdrawalRequest.ssz_type, P.max_withdrawal_requests_per_payload
            ),
            "consolidations": List(
                ConsolidationRequest.ssz_type, P.max_consolidation_requests_per_payload
            ),
        }

    class PendingDeposit(Container):
        fields = {
            "pubkey": bytes48,
            "withdrawal_credentials": bytes32,
            "amount": uint64,
            "signature": bytes96,
            "slot": uint64,
        }

    class PendingPartialWithdrawal(Container):
        fields = {
            "validator_index": uint64,
            "amount": uint64,
            "withdrawable_epoch": uint64,
        }

    class PendingConsolidation(Container):
        fields = {"source_index": uint64, "target_index": uint64}

    _body_base_electra = dict(_body_base)
    _body_base_electra["attester_slashings"] = List(
        AttesterSlashingElectra.ssz_type, P.max_attester_slashings_electra
    )
    _body_base_electra["attestations"] = List(
        AttestationElectra.ssz_type, P.max_attestations_electra
    )

    class BeaconBlockBodyElectra(Container):
        fork_name = "electra"
        fields = {
            **_body_base_electra,
            **_sync_agg,
            # the electra execution payload is structurally deneb's
            "execution_payload": ExecutionPayloadDeneb.ssz_type,
            **_blschanges,
            **_blobkzg,
            "execution_requests": ExecutionRequests.ssz_type,
        }

    _bodies = {
        "phase0": BeaconBlockBodyPhase0,
        "altair": BeaconBlockBodyAltair,
        "bellatrix": BeaconBlockBodyBellatrix,
        "capella": BeaconBlockBodyCapella,
        "deneb": BeaconBlockBodyDeneb,
        "electra": BeaconBlockBodyElectra,
    }

    ns.attestation_by_fork = {}  # filled below

    # --------------------------------------------------- deneb blob sidecars

    Blob = ByteVector(32 * P.field_elements_per_blob)
    # proof depth: list subtree + length mixin + body field tree
    _commit_depth = max(0, (P.max_blob_commitments_per_block - 1).bit_length())
    _body_depth = max(
        0, (len(BeaconBlockBodyDeneb.fields) - 1).bit_length()
    )
    KZG_COMMITMENT_INCLUSION_PROOF_DEPTH = _commit_depth + 1 + _body_depth

    class BlobSidecar(Container):
        """Deneb blob sidecar (reference ``consensus/types/src/blob_sidecar.rs``):
        the gossip unit carrying one blob + its commitment's merkle inclusion
        proof against the signed header's body root."""

        fields = {
            "index": uint64,
            "blob": Blob,
            "kzg_commitment": bytes48,
            "kzg_proof": bytes48,
            "signed_block_header": SignedBeaconBlockHeader.ssz_type,
            "kzg_commitment_inclusion_proof": Vector(
                bytes32, KZG_COMMITMENT_INCLUSION_PROOF_DEPTH
            ),
        }

    class BlobIdentifier(Container):
        fields = {"block_root": bytes32, "index": uint64}

    ns.KZG_COMMITMENT_INCLUSION_PROOF_DEPTH = KZG_COMMITMENT_INCLUSION_PROOF_DEPTH
    ns.Blob = Blob

    _blocks = {}
    _signed_blocks = {}
    for _fork, _body in _bodies.items():
        _blk = type(
            f"BeaconBlock{_fork.capitalize()}",
            (Container,),
            {
                "fork_name": _fork,
                "fields": {
                    "slot": uint64,
                    "proposer_index": uint64,
                    "parent_root": bytes32,
                    "state_root": bytes32,
                    "body": _body.ssz_type,
                },
            },
        )
        _sblk = type(
            f"SignedBeaconBlock{_fork.capitalize()}",
            (Container,),
            {
                "fork_name": _fork,
                "fields": {"message": _blk.ssz_type, "signature": bytes96},
            },
        )
        _blocks[_fork] = _blk
        _signed_blocks[_fork] = _sblk

    # ------------------------------------------------- blinded blocks (MEV)
    # Reference ``consensus/types``: BlindedPayload variants — the body
    # carries the execution payload HEADER; the builder reveals the payload
    # only after the proposer signs (builder_client / blinded production).

    _payload_headers = {
        "bellatrix": ExecutionPayloadHeaderBellatrix,
        "capella": ExecutionPayloadHeaderCapella,
        "deneb": ExecutionPayloadHeaderDeneb,
        "electra": ExecutionPayloadHeaderDeneb,  # structurally deneb's
    }
    _blinded_bodies = {}
    _blinded_blocks = {}
    _signed_blinded_blocks = {}
    for _fork, _body in _bodies.items():
        if "execution_payload" not in _body.fields:
            continue
        _bf = {}
        for _fname, _ftype in _body.fields.items():
            if _fname == "execution_payload":
                _bf["execution_payload_header"] = _payload_headers[_fork].ssz_type
            else:
                _bf[_fname] = _ftype
        _bbody = type(
            f"BlindedBeaconBlockBody{_fork.capitalize()}",
            (Container,),
            {"fork_name": _fork, "fields": _bf},
        )
        _bblk = type(
            f"BlindedBeaconBlock{_fork.capitalize()}",
            (Container,),
            {
                "fork_name": _fork,
                "fields": {
                    "slot": uint64,
                    "proposer_index": uint64,
                    "parent_root": bytes32,
                    "state_root": bytes32,
                    "body": _bbody.ssz_type,
                },
            },
        )
        _sbblk = type(
            f"SignedBlindedBeaconBlock{_fork.capitalize()}",
            (Container,),
            {
                "fork_name": _fork,
                "fields": {"message": _bblk.ssz_type, "signature": bytes96},
            },
        )
        _blinded_bodies[_fork] = _bbody
        _blinded_blocks[_fork] = _bblk
        _signed_blinded_blocks[_fork] = _sbblk

    # ------------------------------------------------ builder API (relay)
    # Reference ``beacon_node/builder_client`` + eth2 builder-specs types.

    class ValidatorRegistrationV1(Container):
        fields = {
            "fee_recipient": bytes20,
            "gas_limit": uint64,
            "timestamp": uint64,
            "pubkey": bytes48,
        }

    class SignedValidatorRegistrationV1(Container):
        fields = {
            "message": ValidatorRegistrationV1.ssz_type,
            "signature": bytes96,
        }

    _builder_bids = {}
    _signed_builder_bids = {}
    for _fork, _hdr in _payload_headers.items():
        _bid_fields = {"header": _hdr.ssz_type}
        if _fork in ("deneb", "electra"):
            _bid_fields["blob_kzg_commitments"] = List(
                bytes48, P.max_blob_commitments_per_block
            )
        if _fork == "electra":
            # electra builder-specs: the bid carries the EL-triggered
            # requests the blinded body must embed (the reference's
            # BuilderBidElectra, builder_bid.rs:14-35, extended per the
            # final builder-specs electra fork).
            _bid_fields["execution_requests"] = ExecutionRequests.ssz_type
        _bid_fields["value"] = uint256
        _bid_fields["pubkey"] = bytes48
        _bid = type(
            f"BuilderBid{_fork.capitalize()}",
            (Container,),
            {"fork_name": _fork, "fields": _bid_fields},
        )
        _sbid = type(
            f"SignedBuilderBid{_fork.capitalize()}",
            (Container,),
            {"fork_name": _fork, "fields": {"message": _bid.ssz_type, "signature": bytes96}},
        )
        _builder_bids[_fork] = _bid
        _signed_builder_bids[_fork] = _sbid

    # -------------------------------------------------------------- states

    _state_pre = {
        "genesis_time": uint64,
        "genesis_validators_root": bytes32,
        "slot": uint64,
        "fork": Fork.ssz_type,
        "latest_block_header": BeaconBlockHeader.ssz_type,
        "block_roots": Vector(bytes32, P.slots_per_historical_root),
        "state_roots": Vector(bytes32, P.slots_per_historical_root),
        "historical_roots": List(bytes32, P.historical_roots_limit),
        "eth1_data": Eth1Data.ssz_type,
        "eth1_data_votes": List(
            Eth1Data.ssz_type, P.epochs_per_eth1_voting_period * P.slots_per_epoch
        ),
        "eth1_deposit_index": uint64,
        "validators": List(Validator.ssz_type, P.validator_registry_limit),
        "balances": List(uint64, P.validator_registry_limit),
        "randao_mixes": Vector(bytes32, P.epochs_per_historical_vector),
        "slashings": Vector(uint64, P.epochs_per_slashings_vector),
    }
    _state_justification = {
        "justification_bits": Bitvector(JUSTIFICATION_BITS_LENGTH),
        "previous_justified_checkpoint": Checkpoint.ssz_type,
        "current_justified_checkpoint": Checkpoint.ssz_type,
        "finalized_checkpoint": Checkpoint.ssz_type,
    }
    _participation = {
        "previous_epoch_participation": List(uint8, P.validator_registry_limit),
        "current_epoch_participation": List(uint8, P.validator_registry_limit),
    }
    _altair_tail = {
        "inactivity_scores": List(uint64, P.validator_registry_limit),
        "current_sync_committee": SyncCommittee.ssz_type,
        "next_sync_committee": SyncCommittee.ssz_type,
    }
    _capella_tail = {
        "next_withdrawal_index": uint64,
        "next_withdrawal_validator_index": uint64,
        "historical_summaries": List(HistoricalSummary.ssz_type, P.historical_roots_limit),
    }

    class BeaconStatePhase0(Container):
        fork_name = "phase0"
        fields = {
            **_state_pre,
            "previous_epoch_attestations": List(
                PendingAttestation.ssz_type, P.max_attestations * P.slots_per_epoch
            ),
            "current_epoch_attestations": List(
                PendingAttestation.ssz_type, P.max_attestations * P.slots_per_epoch
            ),
            **_state_justification,
        }

    class BeaconStateAltair(Container):
        fork_name = "altair"
        fields = {**_state_pre, **_participation, **_state_justification, **_altair_tail}

    class BeaconStateBellatrix(Container):
        fork_name = "bellatrix"
        fields = {
            **_state_pre,
            **_participation,
            **_state_justification,
            **_altair_tail,
            "latest_execution_payload_header": ExecutionPayloadHeaderBellatrix.ssz_type,
        }

    class BeaconStateCapella(Container):
        fork_name = "capella"
        fields = {
            **_state_pre,
            **_participation,
            **_state_justification,
            **_altair_tail,
            "latest_execution_payload_header": ExecutionPayloadHeaderCapella.ssz_type,
            **_capella_tail,
        }

    class BeaconStateDeneb(Container):
        fork_name = "deneb"
        fields = {
            **_state_pre,
            **_participation,
            **_state_justification,
            **_altair_tail,
            "latest_execution_payload_header": ExecutionPayloadHeaderDeneb.ssz_type,
            **_capella_tail,
        }

    class BeaconStateElectra(Container):
        fork_name = "electra"
        fields = {
            **_state_pre,
            **_participation,
            **_state_justification,
            **_altair_tail,
            "latest_execution_payload_header": ExecutionPayloadHeaderDeneb.ssz_type,
            **_capella_tail,
            "deposit_requests_start_index": uint64,
            "deposit_balance_to_consume": uint64,
            "exit_balance_to_consume": uint64,
            "earliest_exit_epoch": uint64,
            "consolidation_balance_to_consume": uint64,
            "earliest_consolidation_epoch": uint64,
            "pending_deposits": List(PendingDeposit.ssz_type, P.pending_deposits_limit),
            "pending_partial_withdrawals": List(
                PendingPartialWithdrawal.ssz_type, P.pending_partial_withdrawals_limit
            ),
            "pending_consolidations": List(
                PendingConsolidation.ssz_type, P.pending_consolidations_limit
            ),
        }

    _states = {
        "phase0": BeaconStatePhase0,
        "altair": BeaconStateAltair,
        "bellatrix": BeaconStateBellatrix,
        "capella": BeaconStateCapella,
        "deneb": BeaconStateDeneb,
        "electra": BeaconStateElectra,
    }

    # ------------------------------------------------- aggregation / duties

    class AggregateAndProof(Container):
        fields = {
            "aggregator_index": uint64,
            "aggregate": Attestation.ssz_type,
            "selection_proof": bytes96,
        }

    class SignedAggregateAndProof(Container):
        fields = {"message": AggregateAndProof.ssz_type, "signature": bytes96}

    class SyncAggregatorSelectionData(Container):
        fields = {"slot": uint64, "subcommittee_index": uint64}

    class SyncCommitteeMessage(Container):
        fields = {
            "slot": uint64,
            "beacon_block_root": bytes32,
            "validator_index": uint64,
            "signature": bytes96,
        }

    _sync_subcommittee_size = max(1, P.sync_committee_size // 4)

    class SyncCommitteeContribution(Container):
        fields = {
            "slot": uint64,
            "beacon_block_root": bytes32,
            "subcommittee_index": uint64,
            "aggregation_bits": Bitvector(_sync_subcommittee_size),
            "signature": bytes96,
        }

    class ContributionAndProof(Container):
        fields = {
            "aggregator_index": uint64,
            "contribution": SyncCommitteeContribution.ssz_type,
            "selection_proof": bytes96,
        }

    class SignedContributionAndProof(Container):
        fields = {"message": ContributionAndProof.ssz_type, "signature": bytes96}

    # ------------------------------------------------- light client protocol
    # Reference: consensus/types/src/light_client_{header,bootstrap,...}.rs.
    # Headers are per-era (light_client_header.rs:40-59): altair/bellatrix
    # carry only the beacon header; capella adds the execution payload
    # header + the 4-deep ``execution_branch`` proving it under the block's
    # body root (EXECUTION_PAYLOAD_GINDEX = 25); deneb/electra carry their
    # era's payload header.  Electra additionally deepens the state-side
    # branches (64-leaf state layout: depths 6/7).

    _exec_branch = Vector(bytes32, 4)  # floorlog2(EXECUTION_PAYLOAD_GINDEX)

    class LightClientHeader(Container):
        fields = {"beacon": BeaconBlockHeader.ssz_type}

    class LightClientHeaderCapella(Container):
        fields = {
            "beacon": BeaconBlockHeader.ssz_type,
            "execution": ExecutionPayloadHeaderCapella.ssz_type,
            "execution_branch": _exec_branch,
        }

    class LightClientHeaderDeneb(Container):
        fields = {
            "beacon": BeaconBlockHeader.ssz_type,
            "execution": ExecutionPayloadHeaderDeneb.ssz_type,
            "execution_branch": _exec_branch,
        }

    _sc_branch = Vector(bytes32, 5)  # depth of a 32-leaf state container
    _fin_branch = Vector(bytes32, 6)  # finalized root: one level deeper

    class LightClientBootstrap(Container):
        fields = {
            "header": LightClientHeader.ssz_type,
            "current_sync_committee": SyncCommittee.ssz_type,
            "current_sync_committee_branch": _sc_branch,
        }

    class LightClientUpdate(Container):
        fields = {
            "attested_header": LightClientHeader.ssz_type,
            "next_sync_committee": SyncCommittee.ssz_type,
            "next_sync_committee_branch": _sc_branch,
            "finalized_header": LightClientHeader.ssz_type,
            "finality_branch": _fin_branch,
            "sync_aggregate": SyncAggregate.ssz_type,
            "signature_slot": uint64,
        }

    class LightClientFinalityUpdate(Container):
        fields = {
            "attested_header": LightClientHeader.ssz_type,
            "finalized_header": LightClientHeader.ssz_type,
            "finality_branch": _fin_branch,
            "sync_aggregate": SyncAggregate.ssz_type,
            "signature_slot": uint64,
        }

    class LightClientOptimisticUpdate(Container):
        fields = {
            "attested_header": LightClientHeader.ssz_type,
            "sync_aggregate": SyncAggregate.ssz_type,
            "signature_slot": uint64,
        }

    # Electra: the state grows past 32 fields (64 leaves), so the sync
    # committee / finality gindices gain one level (spec electra
    # light-client changes: branch depths 6 and 7).
    _sc_branch_electra = Vector(bytes32, 6)
    _fin_branch_electra = Vector(bytes32, 7)

    class LightClientBootstrapElectra(Container):
        fields = {
            "header": LightClientHeaderDeneb.ssz_type,
            "current_sync_committee": SyncCommittee.ssz_type,
            "current_sync_committee_branch": _sc_branch_electra,
        }

    class LightClientUpdateElectra(Container):
        fields = {
            "attested_header": LightClientHeaderDeneb.ssz_type,
            "next_sync_committee": SyncCommittee.ssz_type,
            "next_sync_committee_branch": _sc_branch_electra,
            "finalized_header": LightClientHeaderDeneb.ssz_type,
            "finality_branch": _fin_branch_electra,
            "sync_aggregate": SyncAggregate.ssz_type,
            "signature_slot": uint64,
        }

    class LightClientFinalityUpdateElectra(Container):
        fields = {
            "attested_header": LightClientHeaderDeneb.ssz_type,
            "finalized_header": LightClientHeaderDeneb.ssz_type,
            "finality_branch": _fin_branch_electra,
            "sync_aggregate": SyncAggregate.ssz_type,
            "signature_slot": uint64,
        }

    # ------------------------------------------------------------- exports

    for k, v in dict(locals()).items():
        if isinstance(v, type) and issubclass(v, Container) and v is not Container:
            setattr(ns, v.__name__, v)

    ns.Fork = Fork
    ns.block_body = _bodies
    ns.block = _blocks
    ns.signed_block = _signed_blocks

    # Per-era LC container sets.  The era key tracks BOTH axes that change
    # across forks: the header format (altair beacon-only; capella/deneb
    # execution header + execution_branch) and the state-branch depths
    # (electra: 6/7).  capella/deneb variants are generated here from the
    # altair shapes with the era's header substituted
    # (light_client_bootstrap.rs / light_client_update.rs per-fork structs).
    def _lc_variants(era_name, header_cls):
        out = {}
        for kind, base in (("bootstrap", LightClientBootstrap),
                           ("update", LightClientUpdate),
                           ("finality_update", LightClientFinalityUpdate),
                           ("optimistic_update", LightClientOptimisticUpdate)):
            fields = {}
            for fname, ftype in base.fields.items():
                if fname in ("header", "attested_header", "finalized_header"):
                    fields[fname] = header_cls.ssz_type
                else:
                    fields[fname] = ftype
            cls_name = base.__name__ + era_name.capitalize()
            cls = _ContainerMeta(cls_name, (Container,), {"fields": fields})
            setattr(ns, cls_name, cls)
            out[kind] = cls
        out["header"] = header_cls
        return out

    class LightClientOptimisticUpdateElectra(Container):
        fields = {
            "attested_header": LightClientHeaderDeneb.ssz_type,
            "sync_aggregate": SyncAggregate.ssz_type,
            "signature_slot": uint64,
        }

    ns.LightClientOptimisticUpdateElectra = LightClientOptimisticUpdateElectra
    ns.light_client = {
        "altair": {
            "header": LightClientHeader,
            "bootstrap": LightClientBootstrap,
            "update": LightClientUpdate,
            "finality_update": LightClientFinalityUpdate,
            "optimistic_update": LightClientOptimisticUpdate,
        },
        "capella": _lc_variants("capella", LightClientHeaderCapella),
        "deneb": _lc_variants("deneb", LightClientHeaderDeneb),
        "electra": {
            "header": LightClientHeaderDeneb,
            "bootstrap": LightClientBootstrapElectra,
            "update": LightClientUpdateElectra,
            "finality_update": LightClientFinalityUpdateElectra,
            "optimistic_update": LightClientOptimisticUpdateElectra,
        },
    }
    ns.blinded_block_body = _blinded_bodies
    ns.blinded_block = _blinded_blocks
    ns.signed_blinded_block = _signed_blinded_blocks
    ns.payload_header = {f: h for f, h in _payload_headers.items()}
    ns.execution_payload = {
        "bellatrix": ExecutionPayloadBellatrix,
        "capella": ExecutionPayloadCapella,
        "deneb": ExecutionPayloadDeneb,
        "electra": ExecutionPayloadDeneb,  # structurally deneb's
    }
    ns.builder_bid = _builder_bids
    ns.signed_builder_bid = _signed_builder_bids
    ns.state = _states
    for _f in _bodies:
        ns.attestation_by_fork[_f] = (
            AttestationElectra if _f == "electra" else Attestation
        )
    ns.indexed_attestation_by_fork = {
        _f: (IndexedAttestationElectra if _f == "electra" else IndexedAttestation)
        for _f in _bodies
    }
    return ns
