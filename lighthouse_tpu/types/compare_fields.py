"""Field-by-field container diff for tests and tooling.

Equivalent of the reference's ``common/compare_fields`` (+ derive): when
two states (or any SSZ containers) disagree, a root mismatch tells you
nothing — this walks the field tree and names exactly WHICH leaves differ,
the form the reference's store/transition tests print on failure.
"""

from __future__ import annotations

from typing import Any, List


def _is_container(v: Any) -> bool:
    return hasattr(v, "fields") and hasattr(v, "hash_tree_root")


def _fmt(v: Any) -> str:
    if isinstance(v, (bytes, bytearray)):
        h = bytes(v).hex()
        return "0x" + (h if len(h) <= 18 else h[:16] + "…")
    s = repr(v)
    return s if len(s) <= 48 else s[:45] + "…"


def compare_fields(a: Any, b: Any, path: str = "", *,
                   max_diffs: int = 32) -> List[str]:
    """Dotted paths of every differing leaf between two containers (or
    values), e.g. ``balances[3]: 32000000000 != 31999999999``.  Bounded by
    ``max_diffs`` so a wholesale mismatch stays readable."""
    diffs: List[str] = []
    _walk(a, b, path, diffs, max_diffs)
    return diffs


def _walk(a: Any, b: Any, path: str, diffs: List[str], max_diffs: int) -> None:
    if len(diffs) >= max_diffs:
        return
    if type(a) is not type(b):
        diffs.append(f"{path or '<root>'}: type {type(a).__name__} != "
                     f"{type(b).__name__}")
        return
    if _is_container(a):
        for name in a.fields:
            _walk(getattr(a, name), getattr(b, name),
                  f"{path}.{name}" if path else name, diffs, max_diffs)
        return
    if isinstance(a, (list, tuple)) or (
            hasattr(a, "__len__") and hasattr(a, "__getitem__")
            and not isinstance(a, (bytes, bytearray, str))):
        if len(a) != len(b):
            diffs.append(f"{path}: length {len(a)} != {len(b)}")
            # keep walking the shared prefix — the first divergent entry
            # is usually the real story
        for i in range(min(len(a), len(b))):
            _walk(a[i], b[i], f"{path}[{i}]", diffs, max_diffs)
            if len(diffs) >= max_diffs:
                return
        return
    if isinstance(a, (bytes, bytearray)):
        if bytes(a) != bytes(b):
            diffs.append(f"{path}: {_fmt(a)} != {_fmt(b)}")
        return
    try:
        equal = bool(a == b)
    except TypeError:
        equal = a is b  # non-comparable same-type leaves: identity only
    if not equal:
        diffs.append(f"{path}: {_fmt(a)} != {_fmt(b)}")


def assert_states_equal(a: Any, b: Any) -> None:
    """Raise with the NAMED differing fields (reference compare_fields'
    test usage) instead of a bare root mismatch."""
    if bytes(a.hash_tree_root()) == bytes(b.hash_tree_root()):
        return
    diffs = compare_fields(a, b)
    raise AssertionError(
        "states differ at %d field(s):\n  %s" % (len(diffs), "\n  ".join(diffs))
        if diffs else
        "state roots differ but no field diff found (caching bug?)"
    )
