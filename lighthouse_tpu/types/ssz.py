"""SSZ: simple-serialize encoding/decoding + Merkleized hash-tree-root.

Re-implements the capability of the reference's ``ethereum_ssz``/``tree_hash``
stack (used by every container in ``consensus/types``): fixed/variable-size
encoding with 4-byte offsets, chunk-based SHA-256 Merkleization with
zero-subtree memoization, ``mix_in_length`` for lists/bitlists.

Types are *descriptor objects* (instances of ``SszType``); container classes
declare an ordered ``fields`` mapping and get (de)serialization, equality and
hash-tree-root for free.  The pair-hash primitive is a seam
(``set_hash_pairs_impl``) so the Merkle layer can be swapped for a vectorized /
device implementation without touching any container code.

Spec: consensus-specs ssz/simple-serialize.md (the same document the reference
implements; behavior checked against hand-derived known-answer roots in
tests/test_ssz.py and the vendored conformance vectors in tests/vectors/).
"""

from __future__ import annotations

import hashlib
from typing import Any, Dict, Iterable, Optional, Sequence, Tuple

BYTES_PER_CHUNK = 32
OFFSET_SIZE = 4
ZERO_CHUNK = b"\x00" * 32

# Precomputed roots of all-zero subtrees: ZERO_HASHES[d] = root of depth-d zero tree.
ZERO_HASHES = [ZERO_CHUNK]
for _ in range(64):
    h = hashlib.sha256(ZERO_HASHES[-1] + ZERO_HASHES[-1]).digest()
    ZERO_HASHES.append(h)


def _hash_pairs_hashlib(data: bytes) -> bytes:
    """Hash consecutive 64-byte blocks -> concatenated 32-byte digests."""
    out = bytearray()
    for i in range(0, len(data), 64):
        out += hashlib.sha256(data[i : i + 64]).digest()
    return bytes(out)


_hash_pairs = _hash_pairs_hashlib

# Incremental BeaconState tree hashing (types/tree_cache.py); disable with
# LIGHTHOUSE_TPU_TREE_CACHE=0 (the timing driver's before/after switch).
import os as _os

_TREE_CACHE_ENABLED = _os.environ.get("LIGHTHOUSE_TPU_TREE_CACHE", "1") != "0"


def set_hash_pairs_impl(fn) -> None:
    """Swap the Merkle pair-hash kernel (e.g. for a vectorized implementation)."""
    global _hash_pairs
    _hash_pairs = fn


def _try_install_native_hash_pairs() -> bool:
    """Install the batched C++ SHA-256 (native/hash_pairs.cc) as the Merkle
    pair-hash kernel.  Python-loop hashlib does ~0.6M hashes/s; the native
    loop removes the interpreter from the per-hash path (the reference's
    ethereum_hashing asm/SIMD role).  Returns True on success."""
    try:
        import ctypes

        from ..native import load_hash_pairs

        lib = load_hash_pairs()

        def _hash_pairs_native(data: bytes) -> bytes:
            n = len(data) // 64
            if n == 0:
                return b""
            out = ctypes.create_string_buffer(32 * n)
            lib.hash_pairs(data, n, out)
            return out.raw

        set_hash_pairs_impl(_hash_pairs_native)
        return True
    except Exception:
        return False


if _os.environ.get("LIGHTHOUSE_TPU_NATIVE_SHA", "1") != "0":
    _try_install_native_hash_pairs()


def hash_two(a: bytes, b: bytes) -> bytes:
    return hashlib.sha256(a + b).digest()


def merkleize(chunks: Sequence[bytes], limit: Optional[int] = None) -> bytes:
    """Merkleize 32-byte chunks, padding with zero subtrees to `limit` leaves."""
    count = len(chunks)
    if limit is None:
        limit = count
    elif count > limit:
        raise ValueError(f"merkleize: {count} chunks exceeds limit {limit}")
    if limit == 0:
        return ZERO_CHUNK
    depth = max(0, (limit - 1).bit_length())
    if count == 0:
        return ZERO_HASHES[depth]
    layer = list(chunks)
    for d in range(depth):
        if len(layer) % 2 == 1:
            layer.append(ZERO_HASHES[d])
        buf = b"".join(layer)
        hashed = _hash_pairs(buf)
        layer = [hashed[i : i + 32] for i in range(0, len(hashed), 32)]
    return layer[0]


def mix_in_length(root: bytes, length: int) -> bytes:
    return hash_two(root, length.to_bytes(32, "little"))


def merkle_branch(chunks: Sequence[bytes], limit: Optional[int], index: int) -> list:
    """Sibling branch (bottom-up) proving ``chunks[index]`` under the
    merkleize(chunks, limit) root — the proof-generation dual of
    ``is_valid_merkle_branch`` (reference ``consensus/merkle_proof``)."""
    count = len(chunks)
    if limit is None:
        limit = count
    depth = max(0, (limit - 1).bit_length())
    if index >= limit:
        raise ValueError(f"index {index} out of range for limit {limit}")
    branch = []
    layer = list(chunks)
    for d in range(depth):
        if len(layer) % 2 == 1:
            layer.append(ZERO_HASHES[d])
        sibling = index ^ 1
        branch.append(layer[sibling] if sibling < len(layer) else ZERO_HASHES[d])
        buf = b"".join(layer)
        hashed = _hash_pairs(buf)
        layer = [hashed[i : i + 32] for i in range(0, len(hashed), 32)]
        index //= 2
    return branch


def pack_bytes(data: bytes) -> list:
    """Pack bytes into zero-padded 32-byte chunks."""
    if not data:
        return []
    pad = (-len(data)) % BYTES_PER_CHUNK
    data = data + b"\x00" * pad
    return [data[i : i + 32] for i in range(0, len(data), 32)]


# ---------------------------------------------------------------- descriptors


class SszType:
    is_fixed_size: bool = True
    fixed_size: int = 0

    def serialize(self, value) -> bytes:
        raise NotImplementedError

    def deserialize(self, data: bytes):
        raise NotImplementedError

    def hash_tree_root(self, value) -> bytes:
        raise NotImplementedError

    def default(self):
        raise NotImplementedError


class UintType(SszType):
    def __init__(self, byte_len: int):
        self.fixed_size = byte_len

    def serialize(self, value) -> bytes:
        return int(value).to_bytes(self.fixed_size, "little")

    def deserialize(self, data: bytes) -> int:
        if len(data) != self.fixed_size:
            raise ValueError(f"uint{self.fixed_size*8}: bad length {len(data)}")
        return int.from_bytes(data, "little")

    def hash_tree_root(self, value) -> bytes:
        return int(value).to_bytes(self.fixed_size, "little").ljust(32, b"\x00")

    def default(self):
        return 0


class BooleanType(SszType):
    fixed_size = 1

    def serialize(self, value) -> bytes:
        return b"\x01" if value else b"\x00"

    def deserialize(self, data: bytes) -> bool:
        if data == b"\x00":
            return False
        if data == b"\x01":
            return True
        raise ValueError("invalid boolean")

    def hash_tree_root(self, value) -> bytes:
        return (b"\x01" if value else b"\x00").ljust(32, b"\x00")

    def default(self):
        return False


uint8 = UintType(1)
uint16 = UintType(2)
uint32 = UintType(4)
uint64 = UintType(8)
uint128 = UintType(16)
uint256 = UintType(32)
boolean = BooleanType()

_BASIC_SIZES = {1, 2, 4, 8, 16, 32}


class ByteVector(SszType):
    def __init__(self, length: int):
        self.length = length
        self.fixed_size = length

    def serialize(self, value) -> bytes:
        value = bytes(value)
        if len(value) != self.length:
            raise ValueError(f"ByteVector[{self.length}]: got {len(value)}")
        return value

    def deserialize(self, data: bytes) -> bytes:
        return self.serialize(data)

    def hash_tree_root(self, value) -> bytes:
        return merkleize(pack_bytes(self.serialize(value)))

    def default(self):
        return b"\x00" * self.length


bytes4 = ByteVector(4)
bytes20 = ByteVector(20)
bytes32 = ByteVector(32)
bytes48 = ByteVector(48)
bytes96 = ByteVector(96)


class ByteList(SszType):
    is_fixed_size = False

    def __init__(self, limit: int):
        self.limit = limit

    def serialize(self, value) -> bytes:
        value = bytes(value)
        if len(value) > self.limit:
            raise ValueError("ByteList over limit")
        return value

    def deserialize(self, data: bytes) -> bytes:
        if len(data) > self.limit:
            raise ValueError("ByteList over limit")
        return bytes(data)

    def hash_tree_root(self, value) -> bytes:
        value = bytes(value)
        limit_chunks = (self.limit + 31) // 32
        return mix_in_length(merkleize(pack_bytes(value), limit_chunks), len(value))

    def default(self):
        return b""


class Vector(SszType):
    def __init__(self, elem: SszType, length: int):
        assert length > 0
        self.elem = elem
        self.length = length
        self.is_fixed_size = elem.is_fixed_size
        if self.is_fixed_size:
            self.fixed_size = elem.fixed_size * length

    def serialize(self, value) -> bytes:
        value = list(value)
        if len(value) != self.length:
            raise ValueError(f"Vector[{self.length}]: got {len(value)}")
        return _serialize_homogeneous(self.elem, value)

    def deserialize(self, data: bytes):
        return _deserialize_homogeneous(self.elem, data, exact_count=self.length)

    def hash_tree_root(self, value) -> bytes:
        value = list(value)
        if isinstance(self.elem, (UintType, BooleanType)):
            return merkleize(pack_bytes(b"".join(self.elem.serialize(v) for v in value)))
        return merkleize([self.elem.hash_tree_root(v) for v in value])

    def default(self):
        return [self.elem.default() for _ in range(self.length)]


class List(SszType):
    is_fixed_size = False

    def __init__(self, elem: SszType, limit: int):
        self.elem = elem
        self.limit = limit

    def serialize(self, value) -> bytes:
        value = list(value)
        if len(value) > self.limit:
            raise ValueError("List over limit")
        return _serialize_homogeneous(self.elem, value)

    def deserialize(self, data: bytes):
        out = _deserialize_homogeneous(self.elem, data, exact_count=None)
        if len(out) > self.limit:
            raise ValueError("List over limit")
        return out

    def hash_tree_root(self, value) -> bytes:
        value = list(value)
        if isinstance(self.elem, (UintType, BooleanType)):
            limit_chunks = (self.limit * self.elem.fixed_size + 31) // 32
            body = merkleize(
                pack_bytes(b"".join(self.elem.serialize(v) for v in value)), limit_chunks
            )
        else:
            body = merkleize([self.elem.hash_tree_root(v) for v in value], self.limit)
        return mix_in_length(body, len(value))

    def default(self):
        return []


class Bitvector(SszType):
    def __init__(self, length: int):
        assert length > 0
        self.length = length
        self.fixed_size = (length + 7) // 8

    def serialize(self, value) -> bytes:
        bits = list(value)
        if len(bits) != self.length:
            raise ValueError(f"Bitvector[{self.length}]: got {len(bits)}")
        out = bytearray(self.fixed_size)
        for i, b in enumerate(bits):
            if b:
                out[i // 8] |= 1 << (i % 8)
        return bytes(out)

    def deserialize(self, data: bytes):
        if len(data) != self.fixed_size:
            raise ValueError("Bitvector: bad length")
        if self.length % 8:
            if data[-1] >> (self.length % 8):
                raise ValueError("Bitvector: high bits set")
        return [bool((data[i // 8] >> (i % 8)) & 1) for i in range(self.length)]

    def hash_tree_root(self, value) -> bytes:
        return merkleize(pack_bytes(self.serialize(value)))

    def default(self):
        return [False] * self.length


class Bitlist(SszType):
    is_fixed_size = False

    def __init__(self, limit: int):
        self.limit = limit

    def serialize(self, value) -> bytes:
        bits = list(value)
        if len(bits) > self.limit:
            raise ValueError("Bitlist over limit")
        out = bytearray((len(bits) // 8) + 1)
        for i, b in enumerate(bits):
            if b:
                out[i // 8] |= 1 << (i % 8)
        out[len(bits) // 8] |= 1 << (len(bits) % 8)  # delimiter bit
        return bytes(out)

    def deserialize(self, data: bytes):
        if not data or data[-1] == 0:
            raise ValueError("Bitlist: missing delimiter")
        top = data[-1].bit_length() - 1
        length = (len(data) - 1) * 8 + top
        if length > self.limit:
            raise ValueError("Bitlist over limit")
        return [bool((data[i // 8] >> (i % 8)) & 1) for i in range(length)]

    def hash_tree_root(self, value) -> bytes:
        bits = list(value)
        out = bytearray((len(bits) + 7) // 8)
        for i, b in enumerate(bits):
            if b:
                out[i // 8] |= 1 << (i % 8)
        limit_chunks = (self.limit + 255) // 256
        return mix_in_length(merkleize(pack_bytes(bytes(out)), limit_chunks), len(bits))

    def default(self):
        return []


def _serialize_homogeneous(elem: SszType, values: list) -> bytes:
    if elem.is_fixed_size:
        return b"".join(elem.serialize(v) for v in values)
    parts = [elem.serialize(v) for v in values]
    offset = OFFSET_SIZE * len(parts)
    out = bytearray()
    for p in parts:
        out += offset.to_bytes(4, "little")
        offset += len(p)
    for p in parts:
        out += p
    return bytes(out)


def _deserialize_homogeneous(elem: SszType, data: bytes, exact_count):
    if elem.is_fixed_size:
        size = elem.fixed_size
        if len(data) % size:
            raise ValueError("trailing bytes in fixed-size sequence")
        count = len(data) // size
        if exact_count is not None and count != exact_count:
            raise ValueError("wrong element count")
        return [elem.deserialize(data[i * size : (i + 1) * size]) for i in range(count)]
    if not data:
        if exact_count:
            raise ValueError("wrong element count")
        return []
    first = int.from_bytes(data[:4], "little")
    if first % 4 or first > len(data):
        raise ValueError("bad first offset")
    count = first // 4
    if exact_count is not None and count != exact_count:
        raise ValueError("wrong element count")
    offsets = [int.from_bytes(data[i * 4 : i * 4 + 4], "little") for i in range(count)]
    offsets.append(len(data))
    out = []
    for i in range(count):
        if offsets[i + 1] < offsets[i]:
            raise ValueError("offsets not monotonic")
        out.append(elem.deserialize(data[offsets[i] : offsets[i + 1]]))
    return out


# ----------------------------------------------------------------- containers


class _ContainerType(SszType):
    """Descriptor for a Container class (built by the metaclass)."""

    def __init__(self, cls):
        self.cls = cls
        self.field_types: Dict[str, SszType] = cls.fields
        self.is_fixed_size = all(t.is_fixed_size for t in self.field_types.values())
        if self.is_fixed_size:
            self.fixed_size = sum(t.fixed_size for t in self.field_types.values())
        # BeaconState-shaped containers get an incremental tree-hash cache
        # (the reference's cached_tree_hash/milhouse role).
        self.cacheable = "validators" in self.field_types and "balances" in self.field_types

    def serialize(self, value) -> bytes:
        fixed_parts = []
        var_parts = []
        for name, t in self.field_types.items():
            v = getattr(value, name)
            if t.is_fixed_size:
                fixed_parts.append(t.serialize(v))
            else:
                fixed_parts.append(None)
                var_parts.append(t.serialize(v))
        fixed_len = sum(len(p) if p is not None else 4 for p in fixed_parts)
        out = bytearray()
        offset = fixed_len
        vi = 0
        for p in fixed_parts:
            if p is None:
                out += offset.to_bytes(4, "little")
                offset += len(var_parts[vi])
                vi += 1
            else:
                out += p
        for p in var_parts:
            out += p
        return bytes(out)

    def deserialize(self, data: bytes):
        kwargs = {}
        pos = 0
        offsets: list = []
        var_fields = []
        for name, t in self.field_types.items():
            if t.is_fixed_size:
                kwargs[name] = t.deserialize(data[pos : pos + t.fixed_size])
                pos += t.fixed_size
            else:
                offsets.append(int.from_bytes(data[pos : pos + 4], "little"))
                var_fields.append(name)
                pos += 4
        offsets.append(len(data))
        if not var_fields and pos != len(data):
            raise ValueError("container: trailing bytes")
        if var_fields and offsets[0] != pos:
            raise ValueError("container: bad first offset")
        for i, name in enumerate(var_fields):
            if offsets[i + 1] < offsets[i] or offsets[i + 1] > len(data):
                raise ValueError("container: bad offsets")
            kwargs[name] = self.field_types[name].deserialize(
                data[offsets[i] : offsets[i + 1]]
            )
        return self.cls(**kwargs)

    def hash_tree_root(self, value) -> bytes:
        if self.cacheable and _TREE_CACHE_ENABLED:
            try:
                from .tree_cache import StateTreeHashCache
            except ImportError:
                pass  # degrade to the plain recursive path
            else:
                cache = getattr(value, "_thc", None)
                if cache is None:
                    cache = StateTreeHashCache(self)
                    value._thc = cache
                return cache.root(value)
        return merkleize(
            [t.hash_tree_root(getattr(value, name)) for name, t in self.field_types.items()]
        )

    def default(self):
        return self.cls()


class _ContainerMeta(type):
    def __new__(mcls, name, bases, ns):
        cls = super().__new__(mcls, name, bases, ns)
        if ns.get("fields"):
            cls.ssz_type = _ContainerType(cls)
        return cls


class Container(metaclass=_ContainerMeta):
    """Base for SSZ containers: subclass with an ordered ``fields`` dict."""

    fields: Dict[str, SszType] = {}

    def __init__(self, **kwargs):
        for fname, ftype in self.fields.items():
            if fname in kwargs:
                setattr(self, fname, kwargs.pop(fname))
            else:
                setattr(self, fname, ftype.default())
        if kwargs:
            raise TypeError(f"{type(self).__name__}: unknown fields {sorted(kwargs)}")

    @classmethod
    def from_ssz_bytes(cls, data: bytes):
        return cls.ssz_type.deserialize(data)

    def as_ssz_bytes(self) -> bytes:
        return self.ssz_type.serialize(self)

    def hash_tree_root(self) -> bytes:
        return self.ssz_type.hash_tree_root(self)

    def copy(self):
        import copy as _copy

        return _copy.deepcopy(self)

    def __eq__(self, other):
        if type(self) is not type(other):
            return NotImplemented
        return all(getattr(self, f) == getattr(other, f) for f in self.fields)

    def __repr__(self):
        inner = ", ".join(f"{f}={getattr(self, f)!r}" for f in list(self.fields)[:4])
        more = "…" if len(self.fields) > 4 else ""
        return f"{type(self).__name__}({inner}{more})"


def hash_tree_root(type_or_value, value=None) -> bytes:
    """hash_tree_root(container) or hash_tree_root(ssz_type, value)."""
    if value is None:
        return type_or_value.hash_tree_root()
    return type_or_value.hash_tree_root(value)
