"""Data-model layer: SSZ, chain specs, and consensus containers.

Occupies the slot of the reference's ``consensus/types`` crate (20.5k LoC —
``EthSpec`` presets, ``ChainSpec`` runtime constants, SSZ containers across all
forks).  Design departure for TPU: ``BeaconState`` keeps per-validator data as
dense columnar numpy arrays (balances, participation, validator fields) rather
than a persistent tree — epoch processing then maps onto fused XLA array ops
(the reference's ``single_pass.rs`` fused epoch loop, but SPMD).
"""

from .ssz import (  # noqa: F401
    Bitlist,
    Bitvector,
    ByteList,
    ByteVector,
    Container,
    List,
    Vector,
    boolean,
    bytes4,
    bytes32,
    bytes48,
    bytes96,
    hash_tree_root,
    uint8,
    uint16,
    uint32,
    uint64,
    uint128,
    uint256,
)
