"""Incremental Merkleization for ``BeaconState``.

Equivalent capability to the reference's ``consensus/cached_tree_hash``
(`src/lib.rs:1-45` — arena-backed ``TreeHashCache`` with per-list leaf
caches) + milhouse's tree-backed state hashing
(`consensus/types/src/beacon_state.rs:34`), re-designed for this codebase's
plain-array containers:

- Every big list/vector field keeps its **leaf chunks** and all **interior
  Merkle layers** as flat byte arrays.  On re-hash, fresh leaves are packed
  from the current values (cheap, no hashing), diffed against the cached
  leaves with one vectorized compare, and only the ancestor paths of changed
  leaves are re-hashed — O(k·log n) SHA-256 calls for k changed leaves
  instead of O(n).
- The leaves themselves are always recomputed from the live values, so the
  cache cannot go stale through in-place mutation — correctness never
  depends on dirty *tracking*, only dirty *detection* (the diff).
- Composite element lists (validators) cache one root per element,
  fingerprinted by the element's field tuple; only changed elements are
  re-hashed (8 SHA-256 calls each).

The pair-hash primitive is whatever ``types.ssz`` has installed — the native
batched SHA-256 (`native/hash_pairs.cc`) when available.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from . import ssz as _ssz
from .ssz import (
    ZERO_HASHES,
    Bitvector,
    ByteList,
    ByteVector,
    Container,
    List as SszList,
    UintType,
    Vector,
    mix_in_length,
)


def _hash_blocks(buf: bytes) -> bytes:
    """Hash consecutive 64-byte blocks with the installed pair-hash impl."""
    return _ssz._hash_pairs(buf)


def _ceil_pow2(n: int) -> int:
    return 1 if n <= 1 else 1 << (n - 1).bit_length()


def _make_tree(limit_chunks: int):
    """The leaf-tree engine for one big field: the device-backed
    ``ops/tree_hash.DeviceLeafTree`` when device tree hashing is enabled
    (full rebuilds walk the fused subtree program five levels per dispatch;
    dirty-path pair batches ride the pipeline-aware hash seam), else the
    host :class:`_LeafTree`.  Both engines share the attribute layout
    (leaves/layers/limit/depth/_root), so clone/deepcopy handles either —
    and both are bit-identical to the hashlib golden model.

    Import discipline: ``ops/tree_hash`` pulls jax, and this module must
    stay hermetic for host-only tests — so the device engine is consulted
    only when its module is already loaded (a runtime ``configure`` toggle)
    or the env var opts in; otherwise no jax import ever happens here."""
    import os
    import sys

    _tree_hash = sys.modules.get("lighthouse_tpu.ops.tree_hash")
    if _tree_hash is None:
        if os.environ.get("LIGHTHOUSE_TPU_DEVICE_TREE_HASH", "") != "1":
            return _LeafTree(limit_chunks)
        from ..ops import tree_hash as _tree_hash
    if _tree_hash.enabled():
        return _tree_hash.DeviceLeafTree(limit_chunks)
    return _LeafTree(limit_chunks)


class _LeafTree:
    """Incremental Merkle tree over 32-byte leaf chunks with a chunk limit.

    Layers are stored as numpy uint8 arrays of shape (n_i, 32) covering the
    *occupied* part of each level; everything to the right is the all-zero
    subtree, folded in via ``ZERO_HASHES`` (so a 2^40-limit validator
    registry costs only its occupied prefix).
    """

    def __init__(self, limit_chunks: int):
        self.limit = limit_chunks
        self.depth = max(0, (limit_chunks - 1).bit_length())
        self.leaves: Optional[np.ndarray] = None  # (n, 32) uint8
        self.layers: List[np.ndarray] = []  # interior levels, bottom-up
        self._root: bytes = ZERO_HASHES[self.depth]

    # ------------------------------------------------------------- updates

    def update(self, new_leaves: np.ndarray,
               dirty_hint: Optional[np.ndarray] = None) -> bytes:
        """Bring the tree to ``new_leaves`` (shape (n, 32) uint8), re-hashing
        only changed paths; returns the root.

        ``dirty_hint``: indices the caller asserts are the only possibly-
        changed leaves (hinted rows are still diffed; un-hinted rows are
        trusted unchanged, skipping the O(n) leaf scan).  Only exact
        sources may hint — the validator cache's fingerprint diff is one;
        a wrong hint would serve a stale root."""
        n = len(new_leaves)
        if n > self.limit:
            raise ValueError(f"{n} chunks exceeds limit {self.limit}")
        if self.leaves is None or len(self.leaves) != n:
            return self._rebuild(new_leaves)
        if dirty_hint is not None:
            hint = np.unique(np.asarray(dirty_hint, dtype=np.int64))
            if hint.size == 0:
                return self._root
            changed = np.any(self.leaves[hint] != new_leaves[hint], axis=1)
            dirty = hint[changed]
            if dirty.size == 0:
                return self._root
            self.leaves[dirty] = new_leaves[dirty]
        else:
            diff = np.any(self.leaves != new_leaves, axis=1)
            if not diff.any():
                return self._root
            dirty = np.nonzero(diff)[0]
            self.leaves = new_leaves.copy()
        level = self.leaves
        for d, layer in enumerate(self.layers):
            parents = np.unique(dirty >> 1)
            lo = parents << 1
            hi = lo + 1
            left = level[lo]
            # Right sibling may be past the occupied edge -> zero subtree.
            in_range = hi < len(level)
            right = np.empty_like(left)
            right[in_range] = level[hi[in_range]]
            if not in_range.all():
                right[~in_range] = np.frombuffer(ZERO_HASHES[d], dtype=np.uint8)
            pairs = np.concatenate([left, right], axis=1)  # (k, 64)
            hashed = _hash_blocks(pairs.tobytes())
            layer[parents] = np.frombuffer(hashed, dtype=np.uint8).reshape(-1, 32)
            dirty = parents
            level = layer
        self._root = self._fold_zero_cap(level)
        return self._root

    def _rebuild(self, new_leaves: np.ndarray) -> bytes:
        """Full vectorized rebuild (first call, or occupied size changed)."""
        self.leaves = new_leaves.copy()
        self.layers = []
        level = self.leaves
        occupied_depth = max(0, (_ceil_pow2(max(len(level), 1)) - 1).bit_length())
        for d in range(min(occupied_depth, self.depth)):
            if len(level) % 2:
                zrow = np.frombuffer(ZERO_HASHES[d], dtype=np.uint8).reshape(1, 32)
                level = np.concatenate([level, zrow], axis=0)
            pairs = level.reshape(-1, 64)
            hashed = _hash_blocks(pairs.tobytes())
            layer = np.frombuffer(hashed, dtype=np.uint8).reshape(-1, 32).copy()
            self.layers.append(layer)
            level = layer
        self._root = self._fold_zero_cap(level)
        return self._root

    def _fold_zero_cap(self, top: np.ndarray) -> bytes:
        """Fold the top occupied level up to the limit depth with zero trees."""
        d = len(self.layers)
        if len(top) == 0:
            return ZERO_HASHES[self.depth]
        root = top[0].tobytes()
        for level in range(d, self.depth):
            root = _ssz.hash_two(root, ZERO_HASHES[level])
        return root


def _pack_basic(serialized: bytes) -> np.ndarray:
    """Zero-pad a byte string to 32-byte chunks as an (n, 32) uint8 array."""
    n = len(serialized)
    chunks = (n + 31) // 32
    if chunks == 0:
        return np.empty((0, 32), dtype=np.uint8)
    buf = np.zeros(chunks * 32, dtype=np.uint8)
    buf[:n] = np.frombuffer(serialized, dtype=np.uint8)
    return buf.reshape(-1, 32)


class _BasicListCache:
    """Cache for List/Vector of uints (balances, slashings, …) and byte
    lists (participation): leaves are packed serialization — no per-element
    hashing at all, just the incremental tree."""

    def __init__(self, elem_size: int, limit_elems: int, mix_length: bool):
        limit_chunks = max(1, (limit_elems * elem_size + 31) // 32)
        self.elem_size = elem_size
        self.tree = _make_tree(limit_chunks)
        self.mix_length = mix_length

    def root(self, values) -> bytes:
        if isinstance(values, (bytes, bytearray)):
            data = bytes(values)
            length = len(data)
        else:
            length = len(values)
            if self.elem_size == 8:
                data = np.asarray(values, dtype=np.uint64).tobytes()
            elif self.elem_size == 1:
                data = np.asarray(values, dtype=np.uint8).tobytes()
            else:
                data = b"".join(
                    int(v).to_bytes(self.elem_size, "little") for v in values
                )
        body = self.tree.update(_pack_basic(data))
        return mix_in_length(body, length) if self.mix_length else body


class _RootListCache:
    """Cache for Vector/List of bytes32 roots (block_roots, state_roots,
    randao_mixes, historical roots): each element IS a leaf chunk."""

    def __init__(self, limit_elems: int, mix_length: bool):
        self.tree = _make_tree(max(1, limit_elems))
        self.mix_length = mix_length

    def root(self, values) -> bytes:
        if values:
            arr = np.frombuffer(b"".join(bytes(v) for v in values), dtype=np.uint8)
            leaves = arr.reshape(-1, 32)
        else:
            leaves = np.empty((0, 32), dtype=np.uint8)
        body = self.tree.update(leaves)
        return mix_in_length(body, len(values)) if self.mix_length else body


class _ValidatorListCache:
    """Cache for the validator registry: per-element root memo keyed by the
    element's field-value fingerprint, plus an incremental tree over the
    element roots.  A re-hash after one mutation costs one element re-hash
    (8 SHA-256) + O(log n) interior nodes."""

    def __init__(self, elem_type, limit_elems: int):
        self.elem_type = elem_type  # _ContainerType of Validator
        self.tree = _make_tree(max(1, limit_elems))
        self.fingerprints: List[Optional[tuple]] = []
        self.roots: Optional[np.ndarray] = None  # (n, 32) uint8

    @staticmethod
    def _fingerprint(v) -> tuple:
        # Validator fields are ints/bools/bytes — all hashable values.
        return (
            v.pubkey, v.withdrawal_credentials, v.effective_balance, v.slashed,
            v.activation_eligibility_epoch, v.activation_epoch, v.exit_epoch,
            v.withdrawable_epoch,
        )

    def root(self, values) -> bytes:
        n = len(values)
        if self.roots is None or len(self.roots) != n:
            self.fingerprints = [None] * n
            self.roots = np.zeros((n, 32), dtype=np.uint8)
        dirty = []
        for i, v in enumerate(values):
            fp = self._fingerprint(v)
            if fp != self.fingerprints[i]:
                self.fingerprints[i] = fp
                dirty.append(i)
        if dirty:
            # Re-hash changed validators in one batched pipeline:
            # pubkey root (1 hash) -> 8 leaf chunks -> 4+2+1 hashes.
            k = len(dirty)
            pk = np.zeros((k, 64), dtype=np.uint8)
            for j, i in enumerate(dirty):
                pk[j, :48] = np.frombuffer(bytes(values[i].pubkey), dtype=np.uint8)
            pk_roots = np.frombuffer(_hash_blocks(pk.tobytes()), dtype=np.uint8).reshape(-1, 32)
            leaves = np.zeros((k, 8, 32), dtype=np.uint8)
            for j, i in enumerate(dirty):
                v = values[i]
                leaves[j, 0] = pk_roots[j]
                leaves[j, 1] = np.frombuffer(bytes(v.withdrawal_credentials), dtype=np.uint8)
                leaves[j, 2, :8] = np.frombuffer(
                    int(v.effective_balance).to_bytes(8, "little"), dtype=np.uint8)
                leaves[j, 3, 0] = 1 if v.slashed else 0
                for fi, val in (
                    (4, v.activation_eligibility_epoch), (5, v.activation_epoch),
                    (6, v.exit_epoch), (7, v.withdrawable_epoch),
                ):
                    leaves[j, fi, :8] = np.frombuffer(
                        int(val).to_bytes(8, "little"), dtype=np.uint8)
            level = leaves.reshape(k, 8 * 32)
            for width in (8, 4, 2):
                hashed = _hash_blocks(level.tobytes())
                level = np.frombuffer(hashed, dtype=np.uint8).reshape(k, width // 2 * 32)
            self.roots[dirty] = level.reshape(k, 32)
        # the fingerprint diff IS an exact dirty set (an empty one proves
        # no element root changed): hint the tree so a 1%-dirty mainnet
        # registry skips the O(n) root-leaf scan
        body = self.tree.update(
            self.roots, dirty_hint=np.asarray(dirty, dtype=np.int64))
        return mix_in_length(body, n)




class _ElementMemoListCache:
    """Cache for append-mostly lists of container elements (eth1_data_votes,
    historical_summaries, phase0 pending attestations): per-index root memo
    keyed by the element's SSZ serialization — unlike an identity key, an
    in-place mutation of a cached element can never serve a stale root (a
    wrong BeaconState root is a consensus split), and unlike a deep Python
    tuple, the unchanged-element check is one flat bytes compare (SSZ
    encoding is injective for a fixed type) — plus the incremental tree over
    element roots."""

    def __init__(self, elem_type, limit_elems: int):
        self.elem_type = elem_type
        self.tree = _make_tree(max(1, limit_elems))
        self.fps: List[Optional[bytes]] = []
        self.roots: Optional[np.ndarray] = None  # (n, 32) uint8

    def root(self, values) -> bytes:
        n = len(values)
        if self.roots is None or len(self.roots) != n:
            old_fps, old_roots = self.fps, self.roots
            roots = np.zeros((n, 32), dtype=np.uint8)
            keep = min(n, len(old_fps)) if old_roots is not None else 0
            if keep:
                roots[:keep] = old_roots[:keep]
            self.fps = [None] * n
            self.roots = roots
            for i, v in enumerate(values):
                fp = self.elem_type.serialize(v)
                if i < keep and fp == old_fps[i]:
                    self.fps[i] = fp
                    continue
                self.fps[i] = fp
                self.roots[i] = np.frombuffer(
                    self.elem_type.hash_tree_root(v), dtype=np.uint8)
        else:
            for i, v in enumerate(values):
                fp = self.elem_type.serialize(v)
                if fp != self.fps[i]:
                    self.fps[i] = fp
                    self.roots[i] = np.frombuffer(
                        self.elem_type.hash_tree_root(v), dtype=np.uint8)
        body = self.tree.update(self.roots)
        return mix_in_length(body, n)


class _IdentityMemoCache:
    """Root memo for container fields that are REPLACED, never mutated in
    place (sync committees: a fresh object is assigned each period,
    ``per_epoch.py:293-294``).  Holds a strong ref so the identity stays
    valid; a state.copy() produces a new object and safely recomputes."""

    def __init__(self, t):
        self.t = t
        self.obj = None
        self._root: Optional[bytes] = None

    def root(self, value) -> bytes:
        if value is not self.obj or self._root is None:
            self.obj = value
            self._root = self.t.hash_tree_root(value)
        return self._root


class StateTreeHashCache:
    """Per-state container-level cache: big fields get incremental list
    caches; everything else is recomputed directly (cheap scalars / small
    containers).  Attached lazily to state instances as ``_thc``."""

    # Field names -> cache strategy, resolved per concrete state class.
    def __init__(self, container_type):
        import threading

        self.type = container_type
        self.caches: Dict[str, object] = {}
        # hash_tree_root is no longer a pure function: the HTTP server hashes
        # shared head states from multiple threads, so cache updates must be
        # serialized (the reference wraps its caches in timeout RwLocks).
        self._lock = threading.Lock()
        for name, t in container_type.field_types.items():
            cache = self._cache_for(name, t)
            if cache is not None:
                self.caches[name] = cache

    @staticmethod
    def _cache_for(name: str, t):
        if name in ("current_sync_committee", "next_sync_committee"):
            return _IdentityMemoCache(t)
        if isinstance(t, SszList):
            if isinstance(t.elem, UintType):
                return _BasicListCache(t.elem.fixed_size, t.limit, mix_length=True)
            if isinstance(t.elem, ByteVector) and t.elem.length == 32:
                return _RootListCache(t.limit, mix_length=True)
            if name == "validators":
                return _ValidatorListCache(t.elem, t.limit)
            if name in ("eth1_data_votes", "historical_summaries",
                        "previous_epoch_attestations", "current_epoch_attestations"):
                return _ElementMemoListCache(t.elem, t.limit)
            return None
        if isinstance(t, Vector) and t.length >= 64:
            if isinstance(t.elem, UintType):
                return _BasicListCache(t.elem.fixed_size, t.length, mix_length=False)
            if isinstance(t.elem, ByteVector) and t.elem.length == 32:
                return _RootListCache(t.length, mix_length=False)
            return None
        if isinstance(t, ByteList):
            return _BasicListCache(1, t.limit, mix_length=True)
        return None

    def field_roots(self, state) -> List[bytes]:
        """Per-field roots (the state container's Merkle leaves) — shared
        with the light-client branch builder so proofs reuse the
        incremental caches instead of re-merkleizing the state."""
        with self._lock:
            leaves = []
            for name, t in self.type.field_types.items():
                cache = self.caches.get(name)
                if cache is not None:
                    leaves.append(cache.root(getattr(state, name)))
                else:
                    leaves.append(t.hash_tree_root(getattr(state, name)))
            return leaves

    def root(self, state) -> bytes:
        return _ssz.merkleize(self.field_roots(state))

    def __deepcopy__(self, memo):
        # state.copy() deep-copies the whole object graph; cloning the cache
        # arrays keeps the copy incremental from the parent's position.
        # Cloning runs under the source lock: a concurrent hash_tree_root
        # mid-update must not be snapshotted half-written (new leaves with
        # the old root would make the clone silently serve stale roots).
        import copy as _copy
        import threading

        clone = StateTreeHashCache.__new__(StateTreeHashCache)
        clone.type = self.type
        clone._lock = threading.Lock()
        clone.caches = {}
        with self._lock:
            for name, cache in self.caches.items():
                c = _copy.copy(cache)
                if hasattr(cache, "tree"):
                    c.tree = _copy.copy(cache.tree)
                    c.tree.leaves = (
                        None if cache.tree.leaves is None else cache.tree.leaves.copy()
                    )
                    c.tree.layers = [l.copy() for l in cache.tree.layers]
                if isinstance(cache, _ValidatorListCache):
                    c.fingerprints = list(cache.fingerprints)
                    c.roots = None if cache.roots is None else cache.roots.copy()
                elif isinstance(cache, _ElementMemoListCache):
                    c.fps = list(cache.fps)
                    c.roots = None if cache.roots is None else cache.roots.copy()
                clone.caches[name] = c
        return clone
