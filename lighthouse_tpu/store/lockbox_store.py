"""On-disk ``KeyValueStore`` backed by the native lockbox engine
(reference: ``beacon_node/store/src/leveldb_store.rs`` — the persistent
backend slot; lockbox is our embedded C++ engine, ``native/lockbox.cc``)."""

from __future__ import annotations

import ctypes
import struct
from typing import Iterator, List, Optional, Tuple

from ..native import load_lockbox
from .kv import KeyValueStore, StoreError


class LockboxStore(KeyValueStore):
    def __init__(self, path: str):
        self._lib = load_lockbox()
        self._h = self._lib.lockbox_open(path.encode())
        if not self._h:
            raise StoreError(f"cannot open lockbox at {path}")
        self.path = path

    @staticmethod
    def _k(column: bytes, key: bytes) -> bytes:
        return column + b"\x1f" + key

    def get(self, column: bytes, key: bytes) -> Optional[bytes]:
        k = self._k(column, key)
        buf = ctypes.create_string_buffer(4096)
        n = self._lib.lockbox_get(self._h, k, len(k), buf, len(buf))
        if n == -1:
            return None
        if n < -1:
            raise StoreError("lockbox read error")
        if n <= len(buf):
            return buf.raw[:n]
        big = ctypes.create_string_buffer(n)
        n2 = self._lib.lockbox_get(self._h, k, len(k), big, n)
        if n2 != n:
            raise StoreError("lockbox read race")
        return big.raw[:n]

    def put(self, column: bytes, key: bytes, value: bytes) -> None:
        k = self._k(column, key)
        if self._lib.lockbox_put(self._h, k, len(k), value, len(value)) != 0:
            raise StoreError("lockbox write error")

    def delete(self, column: bytes, key: bytes) -> None:
        k = self._k(column, key)
        if self._lib.lockbox_delete(self._h, k, len(k)) != 0:
            raise StoreError("lockbox delete error")

    def do_atomically(self, ops: List[Tuple[str, bytes, bytes, Optional[bytes]]]) -> None:
        # Crash atomicity holds per record; a torn multi-op batch is bounded
        # by the log-scan truncation on reopen.  Matches the durability class
        # of the reference's non-WAL LevelDB usage.
        for op, column, key, value in ops:
            if op == "put":
                self.put(column, key, value)
            elif op == "del":
                self.delete(column, key)
            else:
                raise StoreError(f"unknown op {op!r}")
        self.flush()

    def iter_column(self, column: bytes) -> Iterator[Tuple[bytes, bytes]]:
        prefix = column + b"\x1f"
        need = self._lib.lockbox_keys(self._h, prefix, len(prefix), None, 0)
        buf = ctypes.create_string_buffer(int(need) or 1)
        self._lib.lockbox_keys(self._h, prefix, len(prefix), buf, len(buf))
        keys = []
        off = 0
        raw = buf.raw[: int(need)]
        while off < len(raw):
            (klen,) = struct.unpack_from("<I", raw, off)
            keys.append(raw[off + 4 : off + 4 + klen])
            off += 4 + klen
        for full_key in keys:
            key = full_key[len(prefix):]
            value = self.get(column, key)
            if value is not None:
                yield (key, value)

    def flush(self) -> None:
        self._lib.lockbox_flush(self._h)

    def compact(self) -> None:
        if self._lib.lockbox_compact(self._h) != 0:
            raise StoreError("lockbox compaction failed")

    def close(self) -> None:
        if self._h:
            self._lib.lockbox_close(self._h)
            self._h = None
