"""Hot/cold split database.

Equivalent of the reference's ``HotColdDB``
(`beacon_node/store/src/hot_cold_store.rs`): the **hot** store holds
unfinalized full states plus ``HotStateSummary`` records; the **cold**
"freezer" holds finalized history compactly — full "restore point" states
every ``slots_per_restore_point`` slots plus chunked per-slot block/state-root
vectors (`store/src/chunked_vector.rs`), with intermediate states rebuilt by
replaying blocks (`store/src/reconstruct.rs` via ``BlockReplayer``).

Blocks always live in the block column (the reference keeps blocks hot-side
too).  Background finalization migration (`beacon_chain/src/migrate.rs`) maps
to ``migrate()``: called with the new finalized checkpoint, it moves
pre-finalized states into the freezer and prunes abandoned forks.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass
from typing import Callable, Dict, Iterator, List, Optional, Tuple

from ..logs import get_logger
from .kv import DBColumn, KeyValueStore, MemoryStore, StoreError

log = get_logger("store")

CHUNK_SIZE = 128  # roots per freezer chunk (reference chunked_vector default)
SCHEMA_VERSION = 1


def encode_stored_block(signed_block, *, blinded: bool) -> bytes:
    """The BEACON_BLOCK column's on-disk framing — ONE owner shared by the
    store and `db prune-payloads`: ``[blinded:]<fork>\\x00<ssz>``."""
    fork = type(signed_block).fork_name
    prefix = b"blinded:" if blinded else b""
    return prefix + fork.encode() + b"\x00" + signed_block.as_ssz_bytes()


def decode_stored_block(types, raw: bytes):
    """Inverse of ``encode_stored_block``; returns (signed_block_or_blinded,
    is_blinded, fork_name)."""
    fork, data = raw.split(b"\x00", 1)
    if fork.startswith(b"blinded:"):
        name = fork[len(b"blinded:"):].decode()
        return types.signed_blinded_block[name].from_ssz_bytes(data), True, name
    name = fork.decode()
    return types.signed_block[name].from_ssz_bytes(data), False, name


def prune_blob_column(kv: "KeyValueStore", types, horizon_slot: int) -> int:
    """Delete every stored sidecar set whose block slot is below the
    horizon; returns the number of blocks pruned.  Shared by the node's
    periodic pruning (HotColdDB.prune_blobs) and `db prune-blobs` — one
    owner of the on-disk framing (u32-be length || sidecar ssz, repeated)."""
    pruned = 0
    for key, raw in kv.iter_column(DBColumn.BLOB_SIDECAR):
        n = int.from_bytes(raw[:4], "big")
        sc = types.BlobSidecar.from_ssz_bytes(raw[4:4 + n])
        if int(sc.signed_block_header.message.slot) < horizon_slot:
            kv.delete(DBColumn.BLOB_SIDECAR, key)
            pruned += 1
    return pruned


def _slot_key(slot: int) -> bytes:
    return struct.pack(">Q", slot)


@dataclass
class HotStateSummary:
    """Hot-side per-state record (reference ``HotStateSummary``)."""

    slot: int
    latest_block_root: bytes
    epoch_boundary_state_root: bytes

    def to_bytes(self) -> bytes:
        return struct.pack(">Q", self.slot) + self.latest_block_root + self.epoch_boundary_state_root

    @classmethod
    def from_bytes(cls, data: bytes) -> "HotStateSummary":
        (slot,) = struct.unpack(">Q", data[:8])
        return cls(slot, data[8:40], data[40:72])


@dataclass
class AnchorInfo:
    """Checkpoint-sync anchor metadata (reference ``metadata.rs``)."""

    anchor_slot: int
    oldest_block_slot: int
    oldest_block_parent: bytes

    def to_bytes(self) -> bytes:
        return struct.pack(">QQ", self.anchor_slot, self.oldest_block_slot) + self.oldest_block_parent

    @classmethod
    def from_bytes(cls, data: bytes) -> "AnchorInfo":
        a, o = struct.unpack(">QQ", data[:16])
        return cls(a, o, data[16:48])


class HotColdDB:
    def __init__(
        self,
        *,
        hot: Optional[KeyValueStore] = None,
        cold: Optional[KeyValueStore] = None,
        types=None,
        spec=None,
        slots_per_restore_point: Optional[int] = None,
    ):
        self.hot = hot if hot is not None else MemoryStore()
        self.cold = cold if cold is not None else MemoryStore()
        self.types = types
        self.spec = spec
        if slots_per_restore_point is None:
            slots_per_restore_point = (
                spec.slots_per_epoch * 2 if spec is not None else 64
            )
        self.slots_per_restore_point = slots_per_restore_point
        self._write_schema_version()

    # ------------------------------------------------------------ metadata

    def _write_schema_version(self) -> None:
        existing = self.hot.get(DBColumn.BEACON_META, b"schema")
        if existing is None:
            self.hot.put(DBColumn.BEACON_META, b"schema", struct.pack(">Q", SCHEMA_VERSION))
        else:
            (version,) = struct.unpack(">Q", existing)
            if version != SCHEMA_VERSION:
                raise StoreError(
                    f"schema version {version} on disk, code expects {SCHEMA_VERSION} "
                    "(run the database manager's migrate command)"
                )

    def schema_version(self) -> int:
        (version,) = struct.unpack(">Q", self.hot.get(DBColumn.BEACON_META, b"schema"))
        return version

    def put_anchor_info(self, info: AnchorInfo) -> None:
        self.hot.put(DBColumn.BEACON_META, b"anchor", info.to_bytes())

    def get_anchor_info(self) -> Optional[AnchorInfo]:
        raw = self.hot.get(DBColumn.BEACON_META, b"anchor")
        return AnchorInfo.from_bytes(raw) if raw else None

    def put_split(self, slot: int, state_root: bytes) -> None:
        """The hot/cold boundary (reference ``Split``)."""
        self.hot.put(DBColumn.BEACON_META, b"split", struct.pack(">Q", slot) + state_root)

    def get_split_slot(self) -> int:
        raw = self.hot.get(DBColumn.BEACON_META, b"split")
        if raw is None:
            return 0
        (slot,) = struct.unpack(">Q", raw[:8])
        return slot

    # -------------------------------------------------------------- blocks

    def put_block(self, block_root: bytes, signed_block) -> None:
        self.hot.put(DBColumn.BEACON_BLOCK, block_root,
                     encode_stored_block(signed_block, blinded=False))

    def put_blinded_block(self, block_root: bytes, signed_blinded) -> None:
        """Persist a block WITHOUT its execution payload (how the reference
        stores every post-merge block; the beacon_block_streamer analog
        reconstructs the payload from the EL on read)."""
        self.hot.put(DBColumn.BEACON_BLOCK, block_root,
                     encode_stored_block(signed_blinded, blinded=True))

    def get_block(self, block_root: bytes):
        """The stored block — a signed full block, or a signed BLINDED block
        when it was persisted payload-free (callers that must serve full
        blocks go through ``BeaconChain.get_block``, which reconstructs)."""
        raw = self.hot.get(DBColumn.BEACON_BLOCK, block_root)
        if raw is None:
            return None
        block, _blinded, _fork = decode_stored_block(self.types, raw)
        return block

    def delete_block(self, block_root: bytes) -> None:
        self.hot.delete(DBColumn.BEACON_BLOCK, block_root)

    # --------------------------------------------------------------- blobs

    def put_blobs(self, block_root: bytes, sidecars) -> None:
        """Persist a block's full sidecar set (index-ascending)."""
        payload = b"".join(
            len(raw).to_bytes(4, "big") + raw
            for raw in (sc.as_ssz_bytes() for sc in sidecars)
        )
        self.hot.put(DBColumn.BLOB_SIDECAR, block_root, payload)

    def get_blobs(self, block_root: bytes) -> list:
        raw = self.hot.get(DBColumn.BLOB_SIDECAR, block_root)
        if raw is None:
            return []
        out = []
        pos = 0
        while pos < len(raw):
            n = int.from_bytes(raw[pos:pos + 4], "big")
            pos += 4
            out.append(self.types.BlobSidecar.from_ssz_bytes(raw[pos:pos + n]))
            pos += n
        return out

    def delete_blobs(self, block_root: bytes) -> None:
        self.hot.delete(DBColumn.BLOB_SIDECAR, block_root)

    def prune_blobs(self, horizon_slot: int) -> int:
        """Drop stored sidecars older than the retention horizon; returns
        the number of blocks pruned (spec MIN_EPOCHS_FOR_BLOB_SIDECARS...)."""
        return prune_blob_column(self.hot, self.types, horizon_slot)

    # ---------------------------------------------------------- hot states

    def put_state(self, state_root: bytes, state, latest_block_root: bytes) -> None:
        """Store a full hot state + its summary."""
        epoch_boundary_slot = (
            int(state.slot) // self.spec.slots_per_epoch * self.spec.slots_per_epoch
        )
        if int(state.slot) == epoch_boundary_slot:
            boundary_root = state_root
        else:
            boundary_root = bytes(
                state.state_roots[epoch_boundary_slot % self.spec.preset.slots_per_historical_root]
            )
        summary = HotStateSummary(int(state.slot), latest_block_root, boundary_root)
        fork = type(state).fork_name
        self.hot.do_atomically(
            [
                ("put", DBColumn.BEACON_STATE, state_root, fork.encode() + b"\x00" + state.as_ssz_bytes()),
                ("put", DBColumn.BEACON_STATE_SUMMARY, state_root, summary.to_bytes()),
            ]
        )

    def get_hot_state(self, state_root: bytes):
        raw = self.hot.get(DBColumn.BEACON_STATE, state_root)
        if raw is None:
            return None
        fork, data = raw.split(b"\x00", 1)
        return self.types.state[fork.decode()].from_ssz_bytes(data)

    def get_state_summary(self, state_root: bytes) -> Optional[HotStateSummary]:
        raw = self.hot.get(DBColumn.BEACON_STATE_SUMMARY, state_root)
        return HotStateSummary.from_bytes(raw) if raw else None

    def delete_state(self, state_root: bytes) -> None:
        self.hot.do_atomically(
            [
                ("del", DBColumn.BEACON_STATE, state_root, None),
                ("del", DBColumn.BEACON_STATE_SUMMARY, state_root, None),
            ]
        )

    # ------------------------------------------------------ freezer chunks

    def _put_chunked_root(self, column: bytes, slot: int, root: bytes) -> None:
        chunk_idx = slot // CHUNK_SIZE
        key = _slot_key(chunk_idx)
        chunk = bytearray(self.cold.get(column, key) or b"\x00" * (32 * CHUNK_SIZE))
        off = (slot % CHUNK_SIZE) * 32
        chunk[off : off + 32] = root
        self.cold.put(column, key, bytes(chunk))

    def _put_chunked_roots(self, column: bytes, roots: Dict[int, bytes]) -> None:
        """Batched chunk update: one read+write per touched 128-slot chunk
        instead of one per slot (append-only backends amplify rewrites)."""
        by_chunk: Dict[int, Dict[int, bytes]] = {}
        for slot, root in roots.items():
            by_chunk.setdefault(slot // CHUNK_SIZE, {})[slot] = root
        for chunk_idx, items in by_chunk.items():
            key = _slot_key(chunk_idx)
            chunk = bytearray(self.cold.get(column, key) or b"\x00" * (32 * CHUNK_SIZE))
            for slot, root in items.items():
                off = (slot % CHUNK_SIZE) * 32
                chunk[off : off + 32] = root
            self.cold.put(column, key, bytes(chunk))

    def _get_chunked_root(self, column: bytes, slot: int) -> Optional[bytes]:
        chunk = self.cold.get(column, _slot_key(slot // CHUNK_SIZE))
        if chunk is None:
            return None
        off = (slot % CHUNK_SIZE) * 32
        root = chunk[off : off + 32]
        return root if root != b"\x00" * 32 else None

    def cold_block_root_at_slot(self, slot: int) -> Optional[bytes]:
        return self._get_chunked_root(DBColumn.BEACON_BLOCK_ROOTS, slot)

    def cold_state_root_at_slot(self, slot: int) -> Optional[bytes]:
        return self._get_chunked_root(DBColumn.BEACON_STATE_ROOTS, slot)

    # ----------------------------------------------------- freezer states

    def _put_restore_point(self, slot: int, state) -> None:
        fork = type(state).fork_name
        self.cold.put(
            DBColumn.BEACON_RESTORE_POINT,
            _slot_key(slot),
            fork.encode() + b"\x00" + state.as_ssz_bytes(),
        )

    def _get_restore_point(self, slot: int):
        raw = self.cold.get(DBColumn.BEACON_RESTORE_POINT, _slot_key(slot))
        if raw is None:
            return None
        fork, data = raw.split(b"\x00", 1)
        return self.types.state[fork.decode()].from_ssz_bytes(data)

    def load_cold_state_by_slot(self, slot: int):
        """Nearest restore point at/below ``slot`` + block replay up to
        ``slot`` (reference ``load_cold_state`` → ``reconstruct.rs``)."""
        rp_slot = slot // self.slots_per_restore_point * self.slots_per_restore_point
        state = self._get_restore_point(rp_slot)
        if state is None:
            return None
        if int(state.slot) == slot:
            return state
        return self._replay_to(state, slot)

    def _replay_to(self, state, target_slot: int):
        """Replay canonical blocks onto ``state`` (reference
        ``block_replayer.rs``; signature verification skipped — these blocks
        were verified at import)."""
        from ..consensus.per_block import BlockSignatureStrategy
        from ..consensus.per_slot import process_slots
        from ..consensus.state_transition import state_transition

        state = state.copy()
        prev_root = None
        for slot in range(int(state.slot) + 1, target_slot + 1):
            block_root = self.cold_block_root_at_slot(slot)
            if block_root is None or block_root == prev_root:
                continue  # skipped slot (root repeats in the chunked vector)
            prev_root = block_root
            block = self.get_block(block_root)
            if block is None or int(block.message.slot) != slot:
                continue
            state = state_transition(
                state,
                block,
                self.types,
                self.spec,
                strategy=BlockSignatureStrategy.NO_VERIFICATION,
                validate_result=False,
            )
        if int(state.slot) < target_slot:
            state = process_slots(state, target_slot, self.types, self.spec)
        return state

    # ----------------------------------------------------------- migration

    def migrate(
        self,
        *,
        finalized_slot: int,
        finalized_state,
        canonical_root_at_slot: Callable[[int], Optional[bytes]],
        state_for_root: Callable[[bytes], Optional[object]],
        abandoned_state_roots: Iterator[bytes] = (),
    ) -> int:
        """Move finalized history below ``finalized_slot`` into the freezer
        (reference ``migrate.rs`` + ``hot_cold_store.rs::migrate_database``).

        Per-slot block/state roots come from ``finalized_state``'s own
        ``block_roots``/``state_roots`` history vectors — the authoritative
        per-slot values, correct across skip slots (a skip slot's state root
        is the slot-advanced root, not the previous block's post-state root),
        and free of any re-hashing.  ``canonical_root_at_slot`` is the
        fallback beyond the vectors' ``slots_per_historical_root`` window.
        ``state_for_root(block_root) -> post-state`` supplies restore-point
        states; at a skip-slot restore point the nearest canonical state is
        advanced with empty slots.  Returns the number of slots frozen."""
        split = self.get_split_slot()
        if finalized_slot <= split:
            return 0
        sphr = self.spec.preset.slots_per_historical_root
        fstate_slot = int(finalized_state.slot)

        def root_from_vector(vector, slot: int) -> Optional[bytes]:
            if slot < fstate_slot <= slot + sphr:
                return bytes(vector[slot % sphr])
            return None

        block_roots: Dict[int, bytes] = {}
        state_roots: Dict[int, bytes] = {}
        for slot in range(split, finalized_slot):
            br = root_from_vector(finalized_state.block_roots, slot)
            if br is None:
                br = canonical_root_at_slot(slot)
            if br is None:
                continue
            block_roots[slot] = br
            sr = root_from_vector(finalized_state.state_roots, slot)
            if sr is not None:
                state_roots[slot] = sr
        self._put_chunked_roots(DBColumn.BEACON_BLOCK_ROOTS, block_roots)
        self._put_chunked_roots(DBColumn.BEACON_STATE_ROOTS, state_roots)

        # Restore points (skip slots get a slot-advanced state).
        rp = self.slots_per_restore_point
        first_rp = (split + rp - 1) // rp * rp
        for slot in range(first_rp, finalized_slot, rp):
            block_root = block_roots.get(slot) or canonical_root_at_slot(slot)
            if block_root is None:
                continue
            state = state_for_root(block_root)
            if state is None:
                continue
            if int(state.slot) != slot:
                from ..consensus.per_slot import process_slots

                state = process_slots(state.copy(), slot, self.types, self.spec)
            self._put_restore_point(slot, state)

        # Full hot states below the split are no longer needed: delete by the
        # block's claimed state root (already verified at import — no hash).
        seen = set()
        for slot, block_root in block_roots.items():
            if block_root in seen:
                continue
            seen.add(block_root)
            block = self.get_block(block_root)
            if block is not None and int(block.message.slot) < finalized_slot:
                self.delete_state(bytes(block.message.state_root))
        for state_root in abandoned_state_roots:
            self.delete_state(state_root)
        final_root = canonical_root_at_slot(finalized_slot)
        self.put_split(finalized_slot, final_root or b"\x00" * 32)
        log.info("freezer migration", split_slot=finalized_slot,
                 frozen_roots=len(block_roots))
        return len(block_roots)
