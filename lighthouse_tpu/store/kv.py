"""Key-value store abstraction + in-memory backend.

Equivalent of the reference's ``KeyValueStore``/``ItemStore`` traits
(`beacon_node/store/src/lib.rs:53`) and ``MemoryStore``
(`beacon_node/store/src/memory_store.rs`).  The hot/cold split database
(``HotColdDB``) builds on these in ``hot_cold.py``; every test runs on
``MemoryStore`` exactly like the reference's harness does.

Keys are column-scoped: ``(column, key)`` → value bytes.  Columns mirror the
reference's ``DBColumn`` enum (block/state/summary/…); using explicit columns
keeps the on-disk layout stable when an embedded native backend is swapped in.
"""

from __future__ import annotations

import threading
from typing import Dict, Iterator, List, Optional, Tuple


class StoreError(Exception):
    pass


class DBColumn:
    """Column families (reference ``DBColumn``, ``store/src/lib.rs``)."""

    BEACON_BLOCK = b"blk"
    BEACON_STATE = b"ste"
    BEACON_STATE_SUMMARY = b"bss"
    BEACON_STATE_TEMPORARY = b"bst"
    BEACON_META = b"bma"
    BEACON_CHAIN = b"bch"
    OP_POOL = b"opo"
    ETH1_CACHE = b"etc"
    FORK_CHOICE = b"frk"
    PUBKEY_CACHE = b"pkc"
    BEACON_RESTORE_POINT = b"brp"
    BEACON_BLOCK_ROOTS = b"bbr"
    BEACON_STATE_ROOTS = b"bsr"
    BEACON_HISTORICAL_ROOTS = b"bhr"
    BEACON_RANDAO_MIXES = b"brm"
    BEACON_HISTORICAL_SUMMARIES = b"bhs"
    BLOB_SIDECAR = b"blb"
    DHT = b"dht"


class KeyValueStore:
    """Abstract synchronous KV store with batched atomic writes."""

    def get(self, column: bytes, key: bytes) -> Optional[bytes]:
        raise NotImplementedError

    def put(self, column: bytes, key: bytes, value: bytes) -> None:
        raise NotImplementedError

    def delete(self, column: bytes, key: bytes) -> None:
        raise NotImplementedError

    def exists(self, column: bytes, key: bytes) -> bool:
        return self.get(column, key) is not None

    def do_atomically(self, ops: List[Tuple[str, bytes, bytes, Optional[bytes]]]) -> None:
        """Apply ``[("put", col, key, value) | ("del", col, key, None)]`` as a
        unit (reference ``do_atomically`` with ``KeyValueStoreOp``)."""
        raise NotImplementedError

    def iter_column(self, column: bytes) -> Iterator[Tuple[bytes, bytes]]:
        """Iterate (key, value) pairs of one column in key order."""
        raise NotImplementedError

    def compact(self) -> None:
        pass

    def close(self) -> None:
        pass


class MemoryStore(KeyValueStore):
    """Dict-backed store (reference ``memory_store.rs``), thread-safe."""

    def __init__(self) -> None:
        self._data: Dict[bytes, Dict[bytes, bytes]] = {}
        self._lock = threading.RLock()

    def get(self, column: bytes, key: bytes) -> Optional[bytes]:
        with self._lock:
            col = self._data.get(column)
            return col.get(key) if col is not None else None

    def put(self, column: bytes, key: bytes, value: bytes) -> None:
        with self._lock:
            self._data.setdefault(column, {})[key] = value

    def delete(self, column: bytes, key: bytes) -> None:
        with self._lock:
            col = self._data.get(column)
            if col is not None:
                col.pop(key, None)

    def do_atomically(self, ops) -> None:
        with self._lock:
            for op, column, key, value in ops:
                if op == "put":
                    self._data.setdefault(column, {})[key] = value
                elif op == "del":
                    col = self._data.get(column)
                    if col is not None:
                        col.pop(key, None)
                else:
                    raise StoreError(f"unknown op {op!r}")

    def iter_column(self, column: bytes) -> Iterator[Tuple[bytes, bytes]]:
        with self._lock:
            items = sorted(self._data.get(column, {}).items())
        return iter(items)
