"""Storage layer (reference: ``beacon_node/store``)."""

from .hot_cold import AnchorInfo, HotColdDB, HotStateSummary
from .kv import DBColumn, KeyValueStore, MemoryStore, StoreError

__all__ = [
    "AnchorInfo",
    "DBColumn",
    "HotColdDB",
    "HotStateSummary",
    "KeyValueStore",
    "LockboxStore",
    "MemoryStore",
    "StoreError",
]


def __getattr__(name):
    # LockboxStore compiles the native engine on first touch; keep the
    # package import light for users who only need MemoryStore.
    if name == "LockboxStore":
        from .lockbox_store import LockboxStore

        return LockboxStore
    raise AttributeError(name)
