"""Storage layer (reference: ``beacon_node/store``)."""

from .kv import DBColumn, KeyValueStore, MemoryStore, StoreError

__all__ = ["DBColumn", "KeyValueStore", "MemoryStore", "StoreError"]
