"""Process + machine health collection from /proc.

Equivalent of the reference's ``common/system_health`` (256 LoC): the
``ProcessHealth``/``SystemHealth`` observations feeding the
``/lighthouse/health`` + ``/lighthouse/ui/health`` endpoints and the
remote-monitoring payloads (``common/monitoring_api/src/types.rs:64-147``
``ProcessMetrics``/``SystemMetrics`` field sets).

Linux-only data sources (/proc, statvfs) with every read individually
guarded — health collection must never take the node down, so a missing
file yields zeros, not an exception.
"""

from __future__ import annotations

import os
import time
from dataclasses import asdict, dataclass

from . import metrics as _metrics

_CLK_TCK = os.sysconf("SC_CLK_TCK") if hasattr(os, "sysconf") else 100
_PAGE = os.sysconf("SC_PAGE_SIZE") if hasattr(os, "sysconf") else 4096


def _read(path: str) -> str:
    try:
        with open(path) as f:
            return f.read()
    except OSError:
        return ""


@dataclass
class ProcessHealth:
    """This process (reference ``ProcessHealth`` -> ``ProcessMetrics``)."""

    pid: int = 0
    pid_num_threads: int = 0
    pid_mem_resident_set_size: int = 0  # bytes
    pid_mem_virtual_memory_size: int = 0  # bytes
    pid_process_seconds_total: int = 0  # utime + stime

    @classmethod
    def observe(cls) -> "ProcessHealth":
        h = cls(pid=os.getpid())
        stat = _read("/proc/self/stat")
        if stat:
            # fields after the parenthesised comm (which may contain spaces)
            try:
                rest = stat.rsplit(")", 1)[1].split()
                # rest[0] is state; utime=rest[11], stime=rest[12],
                # num_threads=rest[17], vsize=rest[20], rss=rest[21] (pages)
                h.pid_process_seconds_total = (
                    int(rest[11]) + int(rest[12])) // _CLK_TCK
                h.pid_num_threads = int(rest[17])
                h.pid_mem_virtual_memory_size = int(rest[20])
                h.pid_mem_resident_set_size = int(rest[21]) * _PAGE
            except (IndexError, ValueError):
                pass
        return h


@dataclass
class SystemHealth:
    """The machine (reference ``SystemHealth`` -> ``SystemMetrics``)."""

    cpu_cores: int = 0
    cpu_threads: int = 0
    cpu_time_total: int = 0  # system seconds
    user_seconds_total: int = 0
    iowait_seconds_total: int = 0
    idle_seconds_total: int = 0

    sys_virt_mem_total: int = 0
    sys_virt_mem_free: int = 0
    sys_virt_mem_cached: int = 0
    sys_virt_mem_buffers: int = 0

    disk_node_bytes_total: int = 0
    disk_node_bytes_free: int = 0
    disk_node_reads_total: int = 0
    disk_node_writes_total: int = 0

    network_node_bytes_total_received: int = 0
    network_node_bytes_total_transmit: int = 0

    misc_node_boot_ts_seconds: int = 0
    misc_os: str = "lin"

    @classmethod
    def observe(cls, disk_path: str = "/") -> "SystemHealth":
        h = cls()
        h.cpu_threads = os.cpu_count() or 0
        h.cpu_cores = h.cpu_threads  # /proc gives no reliable core split here

        stat = _read("/proc/stat")
        for line in stat.splitlines():
            parts = line.split()
            if not parts:
                continue
            if parts[0] == "cpu" and len(parts) >= 6:
                try:
                    jiffies = [int(x) for x in parts[1:]]
                    h.user_seconds_total = jiffies[0] // _CLK_TCK
                    h.idle_seconds_total = jiffies[3] // _CLK_TCK
                    h.iowait_seconds_total = jiffies[4] // _CLK_TCK
                    # reference semantics: cpu_time_total is the TOTAL of
                    # every mode (psutil cpu.total()), not system-mode only
                    # — dashboards derive utilization as (total-idle)/total
                    h.cpu_time_total = sum(jiffies) // _CLK_TCK
                except ValueError:
                    pass
            elif parts[0] == "btime" and len(parts) >= 2:
                try:
                    h.misc_node_boot_ts_seconds = int(parts[1])
                except ValueError:
                    pass

        mem = {}
        for line in _read("/proc/meminfo").splitlines():
            bits = line.split()
            if len(bits) >= 2 and bits[0].endswith(":"):
                try:
                    mem[bits[0][:-1]] = int(bits[1]) * 1024
                except ValueError:
                    pass
        h.sys_virt_mem_total = mem.get("MemTotal", 0)
        h.sys_virt_mem_free = mem.get("MemFree", 0)
        h.sys_virt_mem_cached = mem.get("Cached", 0)
        h.sys_virt_mem_buffers = mem.get("Buffers", 0)

        try:
            st = os.statvfs(disk_path)
            h.disk_node_bytes_total = st.f_frsize * st.f_blocks
            h.disk_node_bytes_free = st.f_frsize * st.f_bavail
        except OSError:
            pass

        # Whole devices only: a partition's IOs are already counted by its
        # parent device (sda1 under sda, nvme0n1p1 under nvme0n1) — summing
        # both double-counts every IO.  A name with a proper-prefix sibling
        # is a partition.
        disk_rows = []
        for line in _read("/proc/diskstats").splitlines():
            bits = line.split()
            if len(bits) >= 10 and not bits[2].startswith(("loop", "ram")):
                disk_rows.append(bits)
        names = {bits[2] for bits in disk_rows}

        def _is_partition(name: str) -> bool:
            """Kernel partition naming: a parent ending in a digit gets
            'p<n>' partitions (nvme0n1 -> nvme0n1p1), otherwise bare digits
            (sda -> sda1).  A plain prefix test would also swallow sibling
            devices like dm-10 under dm-1."""
            for parent in names:
                if parent == name or not name.startswith(parent):
                    continue
                suffix = name[len(parent):]
                if parent[-1].isdigit():
                    if suffix[0] == "p" and suffix[1:].isdigit():
                        return True
                elif suffix.isdigit():
                    return True
            return False

        for bits in disk_rows:
            if _is_partition(bits[2]):
                continue
            try:
                h.disk_node_reads_total += int(bits[3])
                h.disk_node_writes_total += int(bits[7])
            except ValueError:
                pass

        for line in _read("/proc/net/dev").splitlines()[2:]:
            if ":" not in line:
                continue
            name, rest = line.split(":", 1)
            if name.strip() == "lo":
                continue
            bits = rest.split()
            if len(bits) >= 9:
                try:
                    h.network_node_bytes_total_received += int(bits[0])
                    h.network_node_bytes_total_transmit += int(bits[8])
                except ValueError:
                    pass
        return h


def observe_all(disk_path: str = "/") -> dict:
    """Both observations as one flat dict (the /lighthouse/health shape)."""
    out = asdict(ProcessHealth.observe())
    out.update(asdict(SystemHealth.observe(disk_path)))
    out["observed_at_ms"] = int(time.time() * 1000)
    return out


# ------------------------------------------------- standard process metrics
# The three series every stock Grafana "process" dashboard expects, sampled
# on scrape via the registry's collector hook.

PROCESS_CPU_SECONDS = _metrics.counter(
    "process_cpu_seconds_total", "Total user and system CPU time of this process"
)
PROCESS_RESIDENT_MEMORY = _metrics.gauge(
    "process_resident_memory_bytes", "Resident memory size of this process"
)
PROCESS_START_TIME = _metrics.gauge(
    "process_start_time_seconds", "Start time of this process since unix epoch"
)


def _process_start_time() -> float:
    """Epoch start time from /proc (starttime ticks since boot + btime);
    falls back to this module's import time."""
    try:
        stat = _read("/proc/self/stat")
        rest = stat.rsplit(")", 1)[1].split()
        start_ticks = int(rest[19])  # starttime: field 22 of /proc/self/stat
        for line in _read("/proc/stat").splitlines():
            if line.startswith("btime"):
                return int(line.split()[1]) + start_ticks / _CLK_TCK
    except (IndexError, ValueError):
        pass
    return _IMPORT_TIME


_IMPORT_TIME = time.time()
_START_TIME = _process_start_time()


def _process_cpu_seconds() -> float:
    """utime+stime as FLOAT seconds — ProcessHealth's integer field would
    make rate(process_cpu_seconds_total[...]) step in whole-second jumps."""
    try:
        rest = _read("/proc/self/stat").rsplit(")", 1)[1].split()
        return (int(rest[11]) + int(rest[12])) / _CLK_TCK
    except (IndexError, ValueError):
        return 0.0


def _collect_process_metrics() -> None:
    h = ProcessHealth.observe()
    PROCESS_CPU_SECONDS.set_total(_process_cpu_seconds())
    PROCESS_RESIDENT_MEMORY.set(float(h.pid_mem_resident_set_size))
    PROCESS_START_TIME.set(_START_TIME)


_metrics.register_collector(_collect_process_metrics)
