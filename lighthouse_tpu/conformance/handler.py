"""Generic EF spec-test handler: directory walker + typed case runners.

Mirrors the reference's ``testing/ef_tests/src/handler.rs:10-70`` design: a
``Handler`` is (runner name, case fn); cases are the leaf directories of
``tests/<config>/<fork>/<runner>/<handler>/<suite>/<case>``.  The BLS cases
exercise ``verify_signature_sets`` semantics directly
(``testing/ef_tests/src/cases/bls_batch_verify.rs:25-67``) — the bit-identical
gate for the TPU kernel.

Only stdlib + yaml; snappy-compressed ``.ssz_snappy`` payloads are decoded
with our own codec (network/snappy_codec.py).
"""

from __future__ import annotations

import os
from typing import Callable, Dict, Iterator, List, Optional, Tuple

try:
    import yaml
except ImportError:  # pragma: no cover
    yaml = None


class Case:
    """One leaf case directory."""

    def __init__(self, path: str, config: str, fork: str, runner: str, handler: str, suite: str):
        self.path = path
        self.config = config
        self.fork = fork
        self.runner = runner
        self.handler = handler
        self.suite = suite
        self.name = os.path.basename(path)

    def __repr__(self):
        return f"Case({self.config}/{self.fork}/{self.runner}/{self.handler}/{self.suite}/{self.name})"

    # -- file loading ------------------------------------------------------
    def load_yaml(self, name: str):
        p = os.path.join(self.path, name)
        if not os.path.exists(p):
            return None
        if yaml is None:
            raise RuntimeError("PyYAML is required to load spec-test yaml cases")
        with open(p) as f:
            return yaml.safe_load(f)

    def load_json(self, name: str):
        import json

        p = os.path.join(self.path, name)
        if not os.path.exists(p):
            return None
        with open(p) as f:
            return json.load(f)

    def load_ssz(self, name: str) -> Optional[bytes]:
        """Load a .ssz_snappy (preferred) or raw .ssz file."""
        p = os.path.join(self.path, name + ".ssz_snappy")
        if os.path.exists(p):
            from ..network import snappy_codec

            with open(p, "rb") as f:
                return snappy_codec.decompress(f.read())
        p = os.path.join(self.path, name + ".ssz")
        if os.path.exists(p):
            with open(p, "rb") as f:
                return f.read()
        return None


def discover_cases(
    root: str,
    runner: Optional[str] = None,
    config: Optional[str] = None,
    fork: Optional[str] = None,
) -> Iterator[Case]:
    """Walk tests/<config>/<fork>/<runner>/<handler>/<suite>/<case>."""
    tests_root = os.path.join(root, "tests") if os.path.isdir(os.path.join(root, "tests")) else root
    if not os.path.isdir(tests_root):
        return
    for cfg in sorted(os.listdir(tests_root)):
        if config and cfg != config:
            continue
        cfg_dir = os.path.join(tests_root, cfg)
        if not os.path.isdir(cfg_dir):
            continue
        for fk in sorted(os.listdir(cfg_dir)):
            if fork and fk != fork:
                continue
            fk_dir = os.path.join(cfg_dir, fk)
            if not os.path.isdir(fk_dir):
                continue
            for rn in sorted(os.listdir(fk_dir)):
                if runner and rn != runner:
                    continue
                rn_dir = os.path.join(fk_dir, rn)
                if not os.path.isdir(rn_dir):
                    continue
                for hd in sorted(os.listdir(rn_dir)):
                    hd_dir = os.path.join(rn_dir, hd)
                    if not os.path.isdir(hd_dir):
                        continue
                    for suite in sorted(os.listdir(hd_dir)):
                        suite_dir = os.path.join(hd_dir, suite)
                        if not os.path.isdir(suite_dir):
                            continue
                        for case in sorted(os.listdir(suite_dir)):
                            case_dir = os.path.join(suite_dir, case)
                            if os.path.isdir(case_dir):
                                yield Case(case_dir, cfg, fk, rn, hd, suite)


# --------------------------------------------------------------- case runners


def _hex_bytes(s) -> bytes:
    if s is None:
        return b""
    if isinstance(s, bytes):
        return s
    return bytes.fromhex(s[2:] if s.startswith("0x") else s)


def run_bls_case(case: Case) -> Tuple[bool, str]:
    """Run one bls/<handler> case. Returns (passed, detail)."""
    from ..crypto.bls import api

    data = case.load_yaml("data.yaml")
    if data is None:
        return False, "missing data.yaml"
    inp, expected = data.get("input"), data.get("output")
    h = case.handler
    try:
        if h == "sign":
            sk = api.SecretKey(int.from_bytes(_hex_bytes(inp["privkey"]), "big"))
            got = "0x" + sk.sign(_hex_bytes(inp["message"])).to_bytes().hex()
            return got == expected, f"{got} != {expected}"
        if h == "verify":
            pk = api.PublicKey.from_bytes(_hex_bytes(inp["pubkey"]))
            sig = api.Signature.from_bytes(_hex_bytes(inp["signature"]))
            got = sig.verify(pk, _hex_bytes(inp["message"]))
            return got == expected, f"{got} != {expected}"
        if h == "aggregate":
            sigs = [api.Signature.from_bytes(_hex_bytes(s)) for s in inp]
            if not sigs:
                return (expected is None), "empty aggregate"
            agg = api.AggregateSignature.infinity()
            for s in sigs:
                agg.add_assign(s)
            got = "0x" + agg.to_bytes().hex()
            return got == expected, f"{got} != {expected}"
        if h == "aggregate_verify":
            pks = [api.PublicKey.from_bytes(_hex_bytes(p)) for p in inp["pubkeys"]]
            msgs = [_hex_bytes(m) for m in inp["messages"]]
            sig = api.Signature.from_bytes(_hex_bytes(inp["signature"]))
            got = api.aggregate_verify(pks, msgs, sig)
            return got == expected, f"{got} != {expected}"
        if h == "fast_aggregate_verify":
            pks = [api.PublicKey.from_bytes(_hex_bytes(p)) for p in inp["pubkeys"]]
            sig = api.Signature.from_bytes(_hex_bytes(inp["signature"]))
            got = api.fast_aggregate_verify(pks, _hex_bytes(inp["message"]), sig)
            return got == expected, f"{got} != {expected}"
        if h == "batch_verify":
            # The direct gate on verify_signature_sets
            # (testing/ef_tests/src/cases/bls_batch_verify.rs:25-67).
            pks = [api.PublicKey.from_bytes(_hex_bytes(p)) for p in inp["pubkeys"]]
            msgs = [_hex_bytes(m) for m in inp["messages"]]
            sigs = [api.Signature.from_bytes(_hex_bytes(s)) for s in inp["signatures"]]
            sets = [
                api.SignatureSet.single_pubkey(sig, pk, msg)
                for sig, pk, msg in zip(sigs, pks, msgs)
            ]
            got = api.verify_signature_sets(sets)
            return got == expected, f"{got} != {expected}"
    except Exception as e:
        # Invalid-input cases expect output null/false.
        if expected in (None, False):
            return True, f"rejected: {e}"
        return False, f"exception: {e}"
    return False, f"unknown bls handler {h}"


def run_ssz_static_case(case: Case, types_mod) -> Tuple[bool, str]:
    """ssz_static: round-trip serialized.ssz + check roots.yaml."""
    roots = case.load_yaml("roots.yaml")
    raw = case.load_ssz("serialized")
    if roots is None or raw is None:
        return False, "missing files"
    cls = getattr(types_mod, case.handler, None)
    if cls is None:
        return True, f"skip: no container {case.handler}"
    try:
        value = cls.from_ssz_bytes(raw)
    except Exception as e:
        return False, f"deserialize failed: {e}"
    if value.as_ssz_bytes() != raw:
        return False, "re-serialization mismatch"
    got = "0x" + value.hash_tree_root().hex()
    return got == roots["root"], f"root {got} != {roots['root']}"


def run_keystore_case(case: Case) -> Tuple[bool, str]:
    """EIP-2335 keystore decrypt KAT (reference
    ``crypto/eth2_keystore/tests/eip2335_vectors.rs``)."""
    from ..crypto import keystore as ks

    vector = case.load_json("keystore.json")
    meta = case.load_json("meta.json")
    if vector is None or meta is None:
        return False, "missing keystore.json/meta.json"
    try:
        secret = ks.decrypt(vector, meta["password"])
    except Exception as e:
        return False, f"decrypt failed: {e}"
    if secret.hex() != meta["secret"]:
        return False, f"secret {secret.hex()} != {meta['secret']}"
    if vector.get("path", "") != meta.get("path", ""):
        return False, "path mismatch"
    # the embedded pubkey must match the decrypted secret key
    from ..crypto.bls import api

    pk = api.SecretKey(int.from_bytes(secret, "big")).public_key()
    if pk.to_bytes().hex() != vector["pubkey"]:
        return False, "pubkey does not match decrypted secret"
    try:
        ks.decrypt(vector, meta["password"] + "x")
        return False, "wrong password accepted"
    except Exception:
        pass
    return True, "ok"


def run_wallet_case(case: Case) -> Tuple[bool, str]:
    """EIP-2386 wallet seed-decrypt KAT (reference
    ``crypto/eth2_wallet/tests/eip2386_vectors.rs``)."""
    from ..crypto import keystore as ks

    vector = case.load_json("wallet.json")
    meta = case.load_json("meta.json")
    if vector is None or meta is None:
        return False, "missing wallet.json/meta.json"
    try:
        seed = ks.wallet_seed(vector, meta["password"])
    except Exception as e:
        return False, f"seed decrypt failed: {e}"
    if seed.hex() != meta["seed"]:
        return False, f"seed {seed.hex()} != {meta['seed']}"
    for field in ("name", "nextaccount", "type", "uuid"):
        if vector.get(field) != meta[field]:
            return False, f"{field} mismatch"
    return True, "ok"


def run_deposit_data_case(case: Case) -> Tuple[bool, str]:
    """staking-deposit-cli cross-implementation KAT: re-derive the validator
    keys from the documented mnemonic (EIP-2334 paths), rebuild withdrawal
    credentials, deposit roots and the BLS deposit signature, and demand
    bit-identical output (reference ``validator_manager/test_vectors``)."""
    from ..consensus import helpers as h
    from ..crypto import key_derivation as kd
    from ..crypto.bls import api
    from ..types.containers import build_types
    from ..types.spec import DOMAIN_DEPOSIT, mainnet_spec

    deposits = case.load_json("deposit_data.json")
    meta = case.load_json("meta.json")
    if deposits is None or meta is None:
        return False, "missing deposit_data.json/meta.json"
    if len(deposits) != meta["count"]:
        return False, f"expected {meta['count']} deposits, file has {len(deposits)}"

    types = build_types(mainnet_spec().preset)
    seed = kd.mnemonic_to_seed(meta["mnemonic"])
    for j, entry in enumerate(deposits):
        idx = meta["first_index"] + j
        sk = api.SecretKey(kd.derive_path(seed, f"m/12381/3600/{idx}/0/0"))
        if sk.public_key().to_bytes().hex() != entry["pubkey"]:
            return False, f"deposit {j}: derived pubkey mismatch"
        if meta["eth1_withdrawal"]:
            creds = bytes.fromhex(entry["withdrawal_credentials"])
            if creds[:1] != b"\x01" or creds[1:12] != b"\x00" * 11:
                return False, f"deposit {j}: malformed eth1 credentials"
        else:
            import hashlib

            wd_pk = api.SecretKey(
                kd.derive_path(seed, f"m/12381/3600/{idx}/0")
            ).public_key()
            creds = b"\x00" + hashlib.sha256(wd_pk.to_bytes()).digest()[1:]
            if creds.hex() != entry["withdrawal_credentials"]:
                return False, f"deposit {j}: BLS credentials mismatch"
        msg = types.DepositMessage(
            pubkey=bytes.fromhex(entry["pubkey"]),
            withdrawal_credentials=creds,
            amount=int(entry["amount"]),
        )
        if msg.hash_tree_root().hex() != entry["deposit_message_root"]:
            return False, f"deposit {j}: message root mismatch"
        domain = h.compute_domain(
            DOMAIN_DEPOSIT, bytes.fromhex(entry["fork_version"]), b"\x00" * 32
        )
        sig = sk.sign(h.compute_signing_root(msg.hash_tree_root(), domain))
        if sig.to_bytes().hex() != entry["signature"]:
            return False, f"deposit {j}: signature not bit-identical"
        data = types.DepositData(
            pubkey=bytes.fromhex(entry["pubkey"]),
            withdrawal_credentials=creds,
            amount=int(entry["amount"]),
            signature=sig.to_bytes(),
        )
        if data.hash_tree_root().hex() != entry["deposit_data_root"]:
            return False, f"deposit {j}: data root mismatch"
    return True, f"{len(deposits)} deposits bit-identical"


def run_int_to_bytes_case(case: Case) -> Tuple[bool, str]:
    """Spec ``int_to_bytes[n]`` vectors (reference
    ``consensus/int_to_bytes/src/specs/test_vector_int_to_bytes.yml``) —
    little-endian, per int_to_bytes.rs ``to_le_bytes``."""
    data = case.load_yaml("data.yaml")
    if data is None:
        return False, "missing data.yaml"
    n = 0
    for tc in data["test_cases"]:
        got = int(tc["int"]).to_bytes(int(tc["byte_length"]), "little")
        want = _hex_bytes(tc["bytes"])
        if got != want:
            return False, f"int_to_bytes({tc['int']}, {tc['byte_length']}): " \
                          f"{got.hex()} != {want.hex()}"
        n += 1
    return True, f"{n} cases ok"


def run_proto_array_case(case: Case) -> Tuple[bool, str]:
    """Scripted proto-array fork-choice scenario (ported from the reference's
    ``fork_choice_test_definition`` suite by
    ``scripts/port_proto_array_vectors.py``)."""
    from .proto_array_runner import run_scenario

    scenario = case.load_json("scenario.json")
    if scenario is None:
        return False, "missing scenario.json"
    try:
        n = run_scenario(scenario)
    except Exception as e:
        return False, f"{type(e).__name__}: {e}"
    return True, f"{n} ops ok"


def run_case(case: Case, types_mod=None) -> Tuple[bool, str]:
    if case.runner == "bls":
        return run_bls_case(case)
    if case.runner == "ssz_static" and types_mod is not None:
        return run_ssz_static_case(case, types_mod)
    if case.runner == "keystore":
        return run_keystore_case(case)
    if case.runner == "wallet":
        return run_wallet_case(case)
    if case.runner == "deposit_data":
        return run_deposit_data_case(case)
    if case.runner == "int_to_bytes":
        return run_int_to_bytes_case(case)
    if case.runner == "fork_choice":
        return run_proto_array_case(case)
    return True, f"skip: runner {case.runner} not wired"


def run_all(root: str, runner: Optional[str] = None, types_mod=None) -> Dict[str, List[str]]:
    """Run every discovered case; returns {'passed': [...], 'failed': [...]}."""
    out: Dict[str, List[str]] = {"passed": [], "failed": []}
    for case in discover_cases(root, runner=runner):
        ok, detail = run_case(case, types_mod=types_mod)
        (out["passed"] if ok else out["failed"]).append(f"{case!r}: {detail}")
    return out
