"""Spec-conformance harness (the analog of the reference's ``testing/ef_tests``).

Two layers:

- :mod:`handler` — a generic directory-walking handler for the official
  ``consensus-spec-tests`` tarballs, mirroring the reference's
  ``testing/ef_tests/src/handler.rs:10-70``: cases live at
  ``tests/<config>/<fork>/<runner>/<handler>/<suite>/<case>`` and each runner
  maps to a typed case function.  Drop a tarball under ``tests/ef_vectors/``
  (or point ``EF_TESTS_DIR`` at one) and the full suite runs.

- vendored known-answer vectors in ``tests/vectors/`` — external constants
  that ship in-repo (EIP-2333 spec cases, interop keygen pairs,
  staking-deposit-cli 2.7.0 signatures/roots) so the bit-exactness gate runs
  with zero network access.
"""

from .handler import discover_cases, run_case  # noqa: F401
