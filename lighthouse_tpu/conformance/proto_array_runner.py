"""Scripted proto-array scenario interpreter.

Executes the reference's ``ForkChoiceTestDefinition`` operation scripts
(``consensus/proto_array/src/fork_choice_test_definition.rs:75-287``) against
our ``ProtoArray``.  The thin vote/balance wrapper here mirrors the
reference's ``ProtoArrayForkChoice`` (proto_array_fork_choice.rs): latest-
message tracking, delta computation from old/new justified balances, proposer
boost as a committee fraction, and the find-head walk — all with mainnet
constants (32 slots/epoch, proposer_score_boost = 50), exactly as the
scripted suite runs them.
"""

from __future__ import annotations

import numpy as np

from ..fork_choice.proto_array import (
    NONE,
    ExecutionStatus,
    ProtoArray,
    ProtoArrayError,
    VoteTracker,
)

SLOTS_PER_EPOCH = 32  # MainnetEthSpec
PROPOSER_SCORE_BOOST = 50
ZERO = b"\x00" * 32


def _root(hex_str: str) -> bytes:
    return bytes.fromhex(hex_str[2:] if hex_str.startswith("0x") else hex_str)


def _cp(d: dict) -> tuple:
    return (int(d["epoch"]), _root(d["root"]))


class ScriptedForkChoice:
    """The reference ``ProtoArrayForkChoice`` shape, driven purely by ops."""

    def __init__(self, finalized_block_slot: int, justified_checkpoint: tuple,
                 finalized_checkpoint: tuple):
        self.array = ProtoArray(
            slots_per_epoch=SLOTS_PER_EPOCH,
            justified_checkpoint=justified_checkpoint,
            finalized_checkpoint=finalized_checkpoint,
            prune_threshold=256,
        )
        self.votes = VoteTracker()
        self.balances = np.zeros(0, dtype=np.int64)
        # The anchor: the finalized-checkpoint root at the finalized slot,
        # imported optimistically with the zero execution hash and unrealized
        # checkpoints equal to the realized ones
        # (proto_array_fork_choice.rs:384-399 ``ProtoArrayForkChoice::new``).
        self.array.on_block(
            slot=finalized_block_slot,
            root=finalized_checkpoint[1],
            parent_root=None,
            state_root=ZERO,
            target_root=finalized_checkpoint[1],
            justified_checkpoint=justified_checkpoint,
            finalized_checkpoint=finalized_checkpoint,
            unrealized_justified_checkpoint=justified_checkpoint,
            unrealized_finalized_checkpoint=finalized_checkpoint,
            execution_status=ExecutionStatus.OPTIMISTIC,
            execution_block_hash=ZERO,
            current_slot=finalized_block_slot,
        )

    def process_block(self, op: dict) -> None:
        root = _root(op["root"])
        self.array.on_block(
            slot=int(op["slot"]),
            root=root,
            parent_root=_root(op["parent_root"]),
            state_root=ZERO,
            target_root=ZERO,
            justified_checkpoint=_cp(op["justified_checkpoint"]),
            finalized_checkpoint=_cp(op["finalized_checkpoint"]),
            unrealized_justified_checkpoint=None,
            unrealized_finalized_checkpoint=None,
            # All test blocks import optimistically with hash = root
            # (fork_choice_test_definition.rs:206-208).
            execution_status=ExecutionStatus.OPTIMISTIC,
            execution_block_hash=root,
            current_slot=int(op["slot"]),
        )

    def process_attestation(self, op: dict) -> None:
        v = int(op["validator_index"])
        epoch = int(op["target_epoch"])
        self.votes.ensure(v + 1)
        # Reference process_attestation: only a newer target epoch (or a
        # fresh tracker) replaces the pending vote.
        if epoch > self.votes.next_epoch[v] or self.votes.next_root_id[v] == NONE:
            self.votes.next_root_id[v] = self.array.root_id(_root(op["block_root"]))
            self.votes.next_epoch[v] = epoch

    def find_head(self, op: dict, boost_root_hex: str = None) -> bytes:
        new_balances = np.asarray(op["justified_state_balances"], dtype=np.int64)
        self.votes.ensure(max(len(new_balances), len(self.balances)))
        deltas = self.array.compute_deltas(self.votes, self.balances, new_balances)
        boost = (None, 0)
        if boost_root_hex is not None:
            boost_root = _root(boost_root_hex)
            if boost_root != ZERO:
                committee_weight = int(new_balances.sum()) // SLOTS_PER_EPOCH
                score = committee_weight * PROPOSER_SCORE_BOOST // 100
                boost = (boost_root, score)
        jcp = _cp(op["justified_checkpoint"])
        fcp = _cp(op["finalized_checkpoint"])
        self.array.apply_score_changes(
            deltas,
            justified_checkpoint=jcp,
            finalized_checkpoint=fcp,
            current_slot=0,  # the scripted suite always passes Slot::new(0)
            new_proposer_boost=boost,
        )
        self.balances = new_balances
        return self.array.find_head(jcp[1], current_slot=0)


def run_scenario(scenario: dict) -> int:
    """Run every operation; raises AssertionError/ProtoArrayError on any
    mismatch.  Returns the number of operations executed."""
    fc = ScriptedForkChoice(
        int(scenario.get("finalized_block_slot", 0)),
        _cp(scenario["justified_checkpoint"]),
        _cp(scenario["finalized_checkpoint"]),
    )
    for i, op in enumerate(scenario["operations"]):
        kind = op["op"]
        where = f"op {i} ({kind})"
        if kind == "FindHead" or kind == "ProposerBoostFindHead":
            head = fc.find_head(op, op.get("proposer_boost_root"))
            expected = _root(op["expected_head"])
            assert head == expected, (
                f"{where}: head {head.hex()[:16]} != expected {expected.hex()[:16]}"
            )
        elif kind == "InvalidFindHead":
            try:
                fc.find_head(op)
            except ProtoArrayError:
                pass
            else:
                raise AssertionError(f"{where}: find_head unexpectedly succeeded")
        elif kind == "ProcessBlock":
            fc.process_block(op)
        elif kind == "ProcessAttestation":
            fc.process_attestation(op)
        elif kind == "Prune":
            fc.array.prune_threshold = int(op["prune_threshold"])
            fc.array.prune(_root(op["finalized_root"]))
            got = len(fc.array.nodes)
            assert got == int(op["expected_len"]), (
                f"{where}: {got} nodes != expected {op['expected_len']}"
            )
        elif kind == "InvalidatePayload":
            lva = op.get("latest_valid_ancestor_root")
            fc.array.on_invalid_execution_payload(
                _root(op["head_block_root"]),
                _root(lva) if lva is not None else None,
                always_invalidate_head=True,
            )
        elif kind == "AssertWeight":
            node = fc.array.get_block(_root(op["block_root"]))
            assert node is not None, f"{where}: unknown block"
            assert node.weight == int(op["weight"]), (
                f"{where}: weight {node.weight} != expected {op['weight']}"
            )
        else:
            raise AssertionError(f"{where}: unknown operation")
    return len(scenario["operations"])
