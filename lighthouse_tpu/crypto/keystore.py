"""EIP-2335 keystores and EIP-2386 wallets.

Equivalent of the reference's ``crypto/eth2_keystore`` + ``crypto/eth2_wallet``
crates: scrypt/pbkdf2 KDF (stdlib hashlib), AES-128-CTR cipher (OpenSSL
libcrypto via ctypes — no external Python deps), sha256 checksum, the v4
keystore JSON layout, and the hierarchical-deterministic wallet that derives
EIP-2334 validator paths from a mnemonic seed.
"""

from __future__ import annotations

import ctypes
import ctypes.util
import hashlib
import json
import os
import secrets
import unicodedata
import uuid
from typing import Optional, Tuple

from . import key_derivation as kd

KEYSTORE_VERSION = 4
WALLET_VERSION = 1


class KeystoreError(Exception):
    pass


# ------------------------------------------------------------- AES-128-CTR


class _OpenSslCtr:
    _lib = None

    @classmethod
    def lib(cls):
        if cls._lib is None:
            name = ctypes.util.find_library("crypto")
            if name is None:
                raise KeystoreError("libcrypto not found for AES-128-CTR")
            lib = ctypes.CDLL(name)
            lib.EVP_CIPHER_CTX_new.restype = ctypes.c_void_p
            lib.EVP_aes_128_ctr.restype = ctypes.c_void_p
            lib.EVP_EncryptInit_ex.argtypes = [ctypes.c_void_p] * 5
            lib.EVP_EncryptUpdate.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.POINTER(ctypes.c_int),
                ctypes.c_char_p, ctypes.c_int,
            ]
            lib.EVP_CIPHER_CTX_free.argtypes = [ctypes.c_void_p]
            cls._lib = lib
        return cls._lib


def aes128_ctr(key: bytes, iv: bytes, data: bytes) -> bytes:
    """AES-128-CTR keystream XOR (encrypt == decrypt)."""
    if len(key) != 16 or len(iv) != 16:
        raise KeystoreError("aes-128-ctr needs 16-byte key and iv")
    lib = _OpenSslCtr.lib()
    ctx = lib.EVP_CIPHER_CTX_new()
    try:
        if lib.EVP_EncryptInit_ex(ctx, lib.EVP_aes_128_ctr(), None, key, iv) != 1:
            raise KeystoreError("EVP init failed")
        out = ctypes.create_string_buffer(len(data) + 16)
        outlen = ctypes.c_int(0)
        if lib.EVP_EncryptUpdate(ctx, out, ctypes.byref(outlen), data, len(data)) != 1:
            raise KeystoreError("EVP update failed")
        return out.raw[: outlen.value]
    finally:
        lib.EVP_CIPHER_CTX_free(ctx)


# ----------------------------------------------------------------- KDF


def _kdf_derive(password: bytes, kdf: dict) -> bytes:
    params = kdf["params"]
    salt = bytes.fromhex(params["salt"])
    if kdf["function"] == "scrypt":
        return hashlib.scrypt(
            password, salt=salt, n=params["n"], r=params["r"], p=params["p"],
            dklen=params["dklen"], maxmem=2**31 - 1,  # fits n=2^18, r=8 (256 MiB)
        )
    if kdf["function"] == "pbkdf2":
        return hashlib.pbkdf2_hmac(
            params["prf"].replace("hmac-", ""), password, salt, params["c"],
            dklen=params["dklen"],
        )
    raise KeystoreError(f"unsupported kdf {kdf['function']}")


def _normalize_password(password: str) -> bytes:
    """EIP-2335: NFKD normalize, strip C0/C1/DEL control codes."""
    norm = unicodedata.normalize("NFKD", password)
    return "".join(
        c for c in norm if not (ord(c) < 0x20 or 0x7F <= ord(c) < 0xA0)
    ).encode()


# ------------------------------------------------------------- keystore


def encrypt(secret: bytes, password: str, *, path: str = "",
            pubkey: Optional[bytes] = None, kdf: str = "scrypt",
            description: str = "",
            _test_fast_kdf: bool = False) -> dict:
    """Build an EIP-2335 v4 keystore JSON object for ``secret``.

    ``_test_fast_kdf`` lowers work factors (tests only — interop with other
    tooling requires the defaults)."""
    salt = secrets.token_bytes(32)
    iv = secrets.token_bytes(16)
    if kdf == "scrypt":
        n = 2**4 if _test_fast_kdf else 2**18
        kdf_module = {
            "function": "scrypt",
            "params": {"dklen": 32, "n": n, "r": 8, "p": 1, "salt": salt.hex()},
            "message": "",
        }
    elif kdf == "pbkdf2":
        c = 2**4 if _test_fast_kdf else 2**18
        kdf_module = {
            "function": "pbkdf2",
            "params": {"dklen": 32, "c": c, "prf": "hmac-sha256", "salt": salt.hex()},
            "message": "",
        }
    else:
        raise KeystoreError(f"unsupported kdf {kdf}")
    dk = _kdf_derive(_normalize_password(password), kdf_module)
    cipher_message = aes128_ctr(dk[:16], iv, secret)
    checksum = hashlib.sha256(dk[16:32] + cipher_message).hexdigest()
    return {
        "crypto": {
            "kdf": kdf_module,
            "checksum": {"function": "sha256", "params": {}, "message": checksum},
            "cipher": {
                "function": "aes-128-ctr",
                "params": {"iv": iv.hex()},
                "message": cipher_message.hex(),
            },
        },
        "description": description,
        "pubkey": pubkey.hex() if pubkey is not None else "",
        "path": path,
        "uuid": str(uuid.uuid4()),
        "version": KEYSTORE_VERSION,
    }


def decrypt(keystore: dict, password: str) -> bytes:
    """Recover the secret; raises KeystoreError on a wrong password."""
    if int(keystore.get("version", 0)) != KEYSTORE_VERSION:
        raise KeystoreError(f"unsupported keystore version {keystore.get('version')}")
    crypto = keystore["crypto"]
    dk = _kdf_derive(_normalize_password(password), crypto["kdf"])
    cipher_message = bytes.fromhex(crypto["cipher"]["message"])
    checksum = hashlib.sha256(dk[16:32] + cipher_message).hexdigest()
    if checksum != crypto["checksum"]["message"]:
        raise KeystoreError("wrong password (checksum mismatch)")
    if crypto["cipher"]["function"] != "aes-128-ctr":
        raise KeystoreError(f"unsupported cipher {crypto['cipher']['function']}")
    iv = bytes.fromhex(crypto["cipher"]["params"]["iv"])
    return aes128_ctr(dk[:16], iv, cipher_message)


# --------------------------------------------------------------- wallet


def create_wallet(name: str, password: str, *, seed: Optional[bytes] = None,
                  _test_fast_kdf: bool = False) -> Tuple[dict, bytes]:
    """EIP-2386 hierarchical-deterministic wallet: the encrypted master seed
    plus derivation bookkeeping.  Returns (wallet_json, seed)."""
    if seed is None:
        seed = secrets.token_bytes(32)
    crypto = encrypt(seed, password, _test_fast_kdf=_test_fast_kdf)["crypto"]
    wallet = {
        "crypto": crypto,
        "name": name,
        "nextaccount": 0,
        "type": "hierarchical deterministic",
        "uuid": str(uuid.uuid4()),
        "version": WALLET_VERSION,
    }
    return wallet, seed


def wallet_seed(wallet: dict, password: str) -> bytes:
    if wallet.get("type") != "hierarchical deterministic":
        raise KeystoreError(f"unsupported wallet type {wallet.get('type')}")
    return decrypt({"crypto": wallet["crypto"], "version": KEYSTORE_VERSION}, password)


def derive_validator_keystores(wallet: dict, wallet_password: str,
                               keystore_password: str, count: int,
                               _test_fast_kdf: bool = False):
    """Derive the next ``count`` validators at the EIP-2334 signing paths
    m/12381/3600/i/0/0; advances ``wallet['nextaccount']``.  Returns
    ``[(voting_keystore_json, secret_key_int)]``."""
    from .bls import api as bls

    seed = wallet_seed(wallet, wallet_password)
    out = []
    start = int(wallet["nextaccount"])
    for i in range(start, start + count):
        path = f"m/12381/3600/{i}/0/0"
        sk_int = kd.derive_path(seed, path)
        sk = bls.SecretKey(sk_int)
        ks = encrypt(
            sk_int.to_bytes(32, "big"), keystore_password,
            path=path, pubkey=sk.public_key().to_bytes(),
            _test_fast_kdf=_test_fast_kdf,
        )
        out.append((ks, sk_int))
    wallet["nextaccount"] = start + count
    return out


def load_keystore_signing_key(keystore: dict, password: str):
    from .bls import api as bls

    secret = decrypt(keystore, password)
    return bls.SecretKey(int.from_bytes(secret, "big"))


def save_json(obj: dict, path: str) -> None:
    # key material: owner-only permissions (the reference writes 0600 too)
    fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
    with os.fdopen(fd, "w") as f:
        json.dump(obj, f, indent=2)


def load_json(path: str) -> dict:
    with open(path) as f:
        return json.load(f)
