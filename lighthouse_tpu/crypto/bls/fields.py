"""BLS12-381 extension-field tower over Python integers (the golden model).

Tower: Fp2 = Fp[u]/(u^2+1); Fp6 = Fp2[v]/(v^3 - xi), xi = 1+u; Fp12 = Fp6[w]/(w^2 - v).

This is the bit-exact host reference against which the JAX/TPU kernels in
``lighthouse_tpu/ops`` are validated (the role the ``blst`` C library plays for the
reference client's ``crypto/bls/src/impls/blst.rs``).  Clarity over speed; used by
tests, key management, and as the CPU fallback backend.
"""

from __future__ import annotations

from .params import P


class Fq:
    """Element of the base field GF(p)."""

    __slots__ = ("n",)

    def __init__(self, n: int):
        self.n = n % P

    @staticmethod
    def zero() -> "Fq":
        return Fq(0)

    @staticmethod
    def one() -> "Fq":
        return Fq(1)

    def __add__(self, o: "Fq") -> "Fq":
        return Fq(self.n + o.n)

    def __sub__(self, o: "Fq") -> "Fq":
        return Fq(self.n - o.n)

    def __mul__(self, o: "Fq") -> "Fq":
        return Fq(self.n * o.n)

    def __neg__(self) -> "Fq":
        return Fq(-self.n)

    def square(self) -> "Fq":
        return Fq(self.n * self.n)

    def inv(self) -> "Fq":
        if self.n == 0:
            raise ZeroDivisionError("inverse of 0 in Fq")
        return Fq(pow(self.n, P - 2, P))

    def pow(self, e: int) -> "Fq":
        return Fq(pow(self.n, e, P))

    def is_zero(self) -> bool:
        return self.n == 0

    def is_square(self) -> bool:
        return self.n == 0 or pow(self.n, (P - 1) // 2, P) == 1

    def sqrt(self):
        """Square root (p ≡ 3 mod 4) or None if not a QR."""
        if self.n == 0:
            return Fq(0)
        c = pow(self.n, (P + 1) // 4, P)
        if c * c % P != self.n:
            return None
        return Fq(c)

    def sgn0(self) -> int:
        return self.n & 1

    def __eq__(self, o) -> bool:
        return isinstance(o, Fq) and self.n == o.n

    def __hash__(self):
        return hash(("Fq", self.n))

    def __repr__(self):
        return f"Fq(0x{self.n:x})"


class Fq2:
    """c0 + c1*u with u^2 = -1."""

    __slots__ = ("c0", "c1")

    def __init__(self, c0: int, c1: int):
        self.c0 = c0 % P
        self.c1 = c1 % P

    @staticmethod
    def zero() -> "Fq2":
        return Fq2(0, 0)

    @staticmethod
    def one() -> "Fq2":
        return Fq2(1, 0)

    def __add__(self, o: "Fq2") -> "Fq2":
        return Fq2(self.c0 + o.c0, self.c1 + o.c1)

    def __sub__(self, o: "Fq2") -> "Fq2":
        return Fq2(self.c0 - o.c0, self.c1 - o.c1)

    def __neg__(self) -> "Fq2":
        return Fq2(-self.c0, -self.c1)

    def __mul__(self, o: "Fq2") -> "Fq2":
        t0 = self.c0 * o.c0
        t1 = self.c1 * o.c1
        c1 = (self.c0 + self.c1) * (o.c0 + o.c1) - t0 - t1
        return Fq2(t0 - t1, c1)

    def mul_scalar(self, k: int) -> "Fq2":
        return Fq2(self.c0 * k, self.c1 * k)

    def square(self) -> "Fq2":
        a, b = self.c0, self.c1
        return Fq2((a + b) * (a - b), 2 * a * b)

    def conj(self) -> "Fq2":
        return Fq2(self.c0, -self.c1)

    def mul_by_xi(self) -> "Fq2":
        """Multiply by xi = 1 + u (the Fp6 non-residue)."""
        return Fq2(self.c0 - self.c1, self.c0 + self.c1)

    def inv(self) -> "Fq2":
        if self.is_zero():
            raise ZeroDivisionError("inverse of 0 in Fq2")
        d = pow(self.c0 * self.c0 + self.c1 * self.c1, P - 2, P)
        return Fq2(self.c0 * d, -self.c1 * d)

    def pow(self, e: int) -> "Fq2":
        if e < 0:
            return self.inv().pow(-e)
        r = Fq2.one()
        b = self
        while e:
            if e & 1:
                r = r * b
            b = b.square()
            e >>= 1
        return r

    def is_zero(self) -> bool:
        return self.c0 == 0 and self.c1 == 0

    def is_square(self) -> bool:
        # norm = c0^2 + c1^2 must be a square in Fp.
        n = (self.c0 * self.c0 + self.c1 * self.c1) % P
        return n == 0 or pow(n, (P - 1) // 2, P) == 1

    def sqrt(self):
        """Square root via the complex method (p ≡ 3 mod 4), or None."""
        if self.is_zero():
            return Fq2(0, 0)
        a0, a1 = self.c0, self.c1
        if a1 == 0:
            s = Fq(a0).sqrt()
            if s is not None:
                return Fq2(s.n, 0)
            # sqrt(a0) = i * sqrt(-a0)
            s = Fq(-a0).sqrt()
            if s is None:
                return None
            return Fq2(0, s.n)
        n = (a0 * a0 + a1 * a1) % P
        s = pow(n, (P + 1) // 4, P)
        if s * s % P != n:
            return None
        inv2 = pow(2, P - 2, P)
        d = (a0 + s) * inv2 % P
        x = Fq(d).sqrt()
        if x is None:
            d = (a0 - s) * inv2 % P
            x = Fq(d).sqrt()
            if x is None:
                return None
        if x.n == 0:
            return None
        y = a1 * inv2 % P * pow(x.n, P - 2, P) % P
        cand = Fq2(x.n, y)
        if cand.square() == self:
            return cand
        return None

    def sgn0(self) -> int:
        """RFC 9380 sgn0 for m=2."""
        sign_0 = self.c0 & 1
        zero_0 = self.c0 == 0
        sign_1 = self.c1 & 1
        return sign_0 | (int(zero_0) & sign_1)

    def __eq__(self, o) -> bool:
        return isinstance(o, Fq2) and self.c0 == o.c0 and self.c1 == o.c1

    def __hash__(self):
        return hash(("Fq2", self.c0, self.c1))

    def __repr__(self):
        return f"Fq2(0x{self.c0:x}, 0x{self.c1:x})"


class Fq6:
    """c0 + c1*v + c2*v^2 with v^3 = xi = 1+u."""

    __slots__ = ("c0", "c1", "c2")

    def __init__(self, c0: Fq2, c1: Fq2, c2: Fq2):
        self.c0 = c0
        self.c1 = c1
        self.c2 = c2

    @staticmethod
    def zero() -> "Fq6":
        return Fq6(Fq2.zero(), Fq2.zero(), Fq2.zero())

    @staticmethod
    def one() -> "Fq6":
        return Fq6(Fq2.one(), Fq2.zero(), Fq2.zero())

    def __add__(self, o: "Fq6") -> "Fq6":
        return Fq6(self.c0 + o.c0, self.c1 + o.c1, self.c2 + o.c2)

    def __sub__(self, o: "Fq6") -> "Fq6":
        return Fq6(self.c0 - o.c0, self.c1 - o.c1, self.c2 - o.c2)

    def __neg__(self) -> "Fq6":
        return Fq6(-self.c0, -self.c1, -self.c2)

    def __mul__(self, o: "Fq6") -> "Fq6":
        a0, a1, a2 = self.c0, self.c1, self.c2
        b0, b1, b2 = o.c0, o.c1, o.c2
        t0 = a0 * b0
        t1 = a1 * b1
        t2 = a2 * b2
        c0 = t0 + ((a1 + a2) * (b1 + b2) - t1 - t2).mul_by_xi()
        c1 = (a0 + a1) * (b0 + b1) - t0 - t1 + t2.mul_by_xi()
        c2 = (a0 + a2) * (b0 + b2) - t0 - t2 + t1
        return Fq6(c0, c1, c2)

    def square(self) -> "Fq6":
        return self * self

    def mul_by_v(self) -> "Fq6":
        """Multiply by v (the Fp12 non-residue)."""
        return Fq6(self.c2.mul_by_xi(), self.c0, self.c1)

    def inv(self) -> "Fq6":
        a0, a1, a2 = self.c0, self.c1, self.c2
        c0 = a0.square() - (a1 * a2).mul_by_xi()
        c1 = a2.square().mul_by_xi() - a0 * a1
        c2 = a1.square() - a0 * a2
        t = (a0 * c0 + (a2 * c1 + a1 * c2).mul_by_xi()).inv()
        return Fq6(c0 * t, c1 * t, c2 * t)

    def is_zero(self) -> bool:
        return self.c0.is_zero() and self.c1.is_zero() and self.c2.is_zero()

    def __eq__(self, o) -> bool:
        return isinstance(o, Fq6) and self.c0 == o.c0 and self.c1 == o.c1 and self.c2 == o.c2

    def __hash__(self):
        return hash(("Fq6", self.c0, self.c1, self.c2))

    def __repr__(self):
        return f"Fq6({self.c0}, {self.c1}, {self.c2})"


# Frobenius coefficients gamma_i = xi^{i*(p-1)/6}, i = 1..5.
_XI = Fq2(1, 1)
GAMMA = [ _XI.pow(i * (P - 1) // 6) for i in range(6) ]  # GAMMA[0] unused (== 1)


class Fq12:
    """c0 + c1*w with w^2 = v."""

    __slots__ = ("c0", "c1")

    def __init__(self, c0: Fq6, c1: Fq6):
        self.c0 = c0
        self.c1 = c1

    @staticmethod
    def zero() -> "Fq12":
        return Fq12(Fq6.zero(), Fq6.zero())

    @staticmethod
    def one() -> "Fq12":
        return Fq12(Fq6.one(), Fq6.zero())

    @staticmethod
    def from_fq2(x: Fq2) -> "Fq12":
        return Fq12(Fq6(x, Fq2.zero(), Fq2.zero()), Fq6.zero())

    @staticmethod
    def w() -> "Fq12":
        return Fq12(Fq6.zero(), Fq6.one())

    def __add__(self, o: "Fq12") -> "Fq12":
        return Fq12(self.c0 + o.c0, self.c1 + o.c1)

    def __sub__(self, o: "Fq12") -> "Fq12":
        return Fq12(self.c0 - o.c0, self.c1 - o.c1)

    def __neg__(self) -> "Fq12":
        return Fq12(-self.c0, -self.c1)

    def __mul__(self, o: "Fq12") -> "Fq12":
        t0 = self.c0 * o.c0
        t1 = self.c1 * o.c1
        c0 = t0 + t1.mul_by_v()
        c1 = (self.c0 + self.c1) * (o.c0 + o.c1) - t0 - t1
        return Fq12(c0, c1)

    def square(self) -> "Fq12":
        return self * self

    def conj(self) -> "Fq12":
        """Conjugation = Frobenius^6 (inverse on the cyclotomic subgroup)."""
        return Fq12(self.c0, -self.c1)

    def inv(self) -> "Fq12":
        t = (self.c0.square() - self.c1.square().mul_by_v()).inv()
        return Fq12(self.c0 * t, -(self.c1 * t))

    def frobenius(self) -> "Fq12":
        """x -> x^p."""
        a0, a1, a2 = self.c0.c0, self.c0.c1, self.c0.c2
        b0, b1, b2 = self.c1.c0, self.c1.c1, self.c1.c2
        return Fq12(
            Fq6(a0.conj(), a1.conj() * GAMMA[2], a2.conj() * GAMMA[4]),
            Fq6(b0.conj() * GAMMA[1], b1.conj() * GAMMA[3], b2.conj() * GAMMA[5]),
        )

    def frobenius_n(self, n: int) -> "Fq12":
        r = self
        for _ in range(n % 12):
            r = r.frobenius()
        return r

    def pow(self, e: int) -> "Fq12":
        if e < 0:
            return self.inv().pow(-e)
        r = Fq12.one()
        b = self
        while e:
            if e & 1:
                r = r * b
            b = b.square()
            e >>= 1
        return r

    def is_one(self) -> bool:
        return self.c0 == Fq6.one() and self.c1.is_zero()

    def is_zero(self) -> bool:
        return self.c0.is_zero() and self.c1.is_zero()

    def __eq__(self, o) -> bool:
        return isinstance(o, Fq12) and self.c0 == o.c0 and self.c1 == o.c1

    def __hash__(self):
        return hash(("Fq12", self.c0, self.c1))

    def __repr__(self):
        return f"Fq12({self.c0}, {self.c1})"
