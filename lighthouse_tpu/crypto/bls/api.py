"""Public BLS API: keys, signatures, signature sets, batch verification.

Mirrors the reference's backend-swappable generic layer (``crypto/bls/src/lib.rs:84-139``
``define_mod!`` over ``impls::{blst, fake_crypto}``): every signature in the framework
funnels through ``SignatureSet`` + ``verify_signature_sets`` so the execution backend
(host | fake | jax) can be swapped at one seam.

Batch semantics are byte-for-byte those of ``crypto/bls/src/impls/blst.rs:35-117``:
empty batch -> False; per set: nonzero 64-bit random weight, signature subgroup
check, no-pubkeys -> False, pubkey aggregation; then one multi-pairing.
"""

from __future__ import annotations

import hashlib
import secrets
from typing import Iterable, List, Optional, Sequence

from . import curve, serde
from .curve import Point
from .hash_to_curve import hash_to_g2
from .params import DST, R, RAND_BITS

PUBLIC_KEY_BYTES_LEN = 48
SIGNATURE_BYTES_LEN = 96
SECRET_KEY_BYTES_LEN = 32

INFINITY_SIGNATURE = bytes([0xC0]) + b"\x00" * 95
INFINITY_PUBLIC_KEY = bytes([0xC0]) + b"\x00" * 47


class BlsError(ValueError):
    pass


def _hkdf_extract(salt: bytes, ikm: bytes) -> bytes:
    import hmac

    return hmac.new(salt, ikm, hashlib.sha256).digest()


def _hkdf_expand(prk: bytes, info: bytes, length: int) -> bytes:
    import hmac

    okm = b""
    t = b""
    i = 0
    while len(okm) < length:
        i += 1
        t = hmac.new(prk, t + info + bytes([i]), hashlib.sha256).digest()
        okm += t
    return okm[:length]


class SecretKey:
    """Scalar secret key (nonzero, < r). Reference: generic_secret_key.rs."""

    __slots__ = ("_k",)

    def __init__(self, k: int):
        if not 0 < k < R:
            raise BlsError("secret key out of range")
        self._k = k

    @classmethod
    def from_bytes(cls, data: bytes) -> "SecretKey":
        if len(data) != SECRET_KEY_BYTES_LEN:
            raise BlsError("secret key must be 32 bytes")
        return cls(int.from_bytes(data, "big"))

    @classmethod
    def random(cls) -> "SecretKey":
        while True:
            k = secrets.randbits(255) % R
            if k:
                return cls(k)

    @classmethod
    def key_gen(cls, ikm: bytes, key_info: bytes = b"") -> "SecretKey":
        """IETF BLS KeyGen (HKDF mod r), used by EIP-2333 derivation."""
        salt = b"BLS-SIG-KEYGEN-SALT-"
        while True:
            salt = hashlib.sha256(salt).digest()
            prk = _hkdf_extract(salt, ikm + b"\x00")
            okm = _hkdf_expand(prk, key_info + (48).to_bytes(2, "big"), 48)
            k = int.from_bytes(okm, "big") % R
            if k:
                return cls(k)

    def to_bytes(self) -> bytes:
        return self._k.to_bytes(32, "big")

    @property
    def scalar(self) -> int:
        return self._k

    def public_key(self) -> "PublicKey":
        return PublicKey(point=curve.mul(curve.G1, self._k))

    def sign(self, message: bytes, dst: bytes = DST) -> "Signature":
        h = hash_to_g2(bytes(message), dst)
        return Signature(point=curve.mul(h, self._k))


class PublicKey:
    """A *validated* public key: decoded, non-infinity, in G1.

    Matches the reference invariant that `GenericPublicKey` is always
    subgroup-checked and infinity-checked on deserialization
    (impls/blst.rs `deserialize` + generic_public_key.rs infinity check).
    """

    __slots__ = ("point", "_bytes")

    def __init__(self, point: Point, _skip_checks: bool = False):
        if not _skip_checks:
            if point is None:
                raise BlsError("public key is the point at infinity")
            if not curve.in_g1(point):
                raise BlsError("public key not in G1")
        self.point = point
        self._bytes: Optional[bytes] = None

    @classmethod
    def from_bytes(cls, data: bytes) -> "PublicKey":
        if len(data) != PUBLIC_KEY_BYTES_LEN:
            raise BlsError(f"public key must be 48 bytes, got {len(data)}")
        try:
            pt = serde.g1_decompress(data)
        except serde.DecodeError as e:
            raise BlsError(str(e)) from e
        return cls(pt)

    def to_bytes(self) -> bytes:
        if self._bytes is None:
            self._bytes = serde.g1_compress(self.point)
        return self._bytes

    def __eq__(self, o):
        return isinstance(o, PublicKey) and self.point == o.point

    def __hash__(self):
        return hash(self.to_bytes())

    def __repr__(self):
        return f"PublicKey(0x{self.to_bytes().hex()})"


class AggregatePublicKey:
    """Sum of public keys in G1 (blst AggregatePublicKey equivalent)."""

    __slots__ = ("point",)

    def __init__(self, point: Point = None):
        self.point = point

    @classmethod
    def aggregate(cls, pubkeys: Sequence[PublicKey]) -> "AggregatePublicKey":
        acc: Point = None
        for pk in pubkeys:
            acc = curve.add(acc, pk.point)
        return cls(acc)

    def to_public_key(self) -> PublicKey:
        return PublicKey(point=self.point)


class Signature:
    """A signature point in G2 (possibly infinity; subgroup check at verify time,
    as in the reference where deserialize only curve-checks)."""

    __slots__ = ("point", "is_infinity", "_bytes")

    def __init__(self, point: Point = None, _bytes: Optional[bytes] = None):
        self.point = point
        self.is_infinity = point is None
        self._bytes = _bytes

    @classmethod
    def empty(cls) -> "Signature":
        return cls(None, INFINITY_SIGNATURE)

    @classmethod
    def from_bytes(cls, data: bytes) -> "Signature":
        if len(data) != SIGNATURE_BYTES_LEN:
            raise BlsError(f"signature must be 96 bytes, got {len(data)}")
        try:
            pt = serde.g2_decompress(data)
        except serde.DecodeError as e:
            raise BlsError(str(e)) from e
        return cls(pt, bytes(data))

    def to_bytes(self) -> bytes:
        if self._bytes is None:
            self._bytes = serde.g2_compress(self.point)
        return self._bytes

    def subgroup_check(self) -> bool:
        return curve.in_g2(self.point)

    def verify(self, pubkey: PublicKey, message: bytes, dst: bytes = DST) -> bool:
        return fast_aggregate_verify([pubkey], message, self, dst)

    def __eq__(self, o):
        return isinstance(o, Signature) and self.point == o.point

    def __hash__(self):
        return hash(self.to_bytes())

    def __repr__(self):
        return f"Signature(0x{self.to_bytes().hex()})"


class AggregateSignature:
    """Accumulating aggregate signature (generic_aggregate_signature.rs)."""

    __slots__ = ("point",)

    def __init__(self, point: Point = None):
        self.point = point

    @classmethod
    def infinity(cls) -> "AggregateSignature":
        return cls(None)

    @classmethod
    def from_signature(cls, sig: Signature) -> "AggregateSignature":
        return cls(sig.point)

    @classmethod
    def from_bytes(cls, data: bytes) -> "AggregateSignature":
        return cls(Signature.from_bytes(data).point)

    def add_assign(self, sig: Signature) -> None:
        self.point = curve.add(self.point, sig.point)

    def add_assign_aggregate(self, other: "AggregateSignature") -> None:
        self.point = curve.add(self.point, other.point)

    def to_signature(self) -> Signature:
        return Signature(self.point)

    def to_bytes(self) -> bytes:
        return serde.g2_compress(self.point)

    @classmethod
    def aggregate(cls, sigs: Sequence[Signature]) -> "AggregateSignature":
        out = cls()
        for s in sigs:
            out.add_assign(s)
        return out


class SignatureSet:
    """(signature, message, signing_keys): one unit of the batch-verification IR
    (generic_signature_set.rs:61-121)."""

    __slots__ = ("signature", "message", "signing_keys")

    def __init__(self, signature, message: bytes, signing_keys: List[PublicKey]):
        if isinstance(signature, Signature):
            signature = AggregateSignature.from_signature(signature)
        self.signature: AggregateSignature = signature
        self.message = bytes(message)
        self.signing_keys = list(signing_keys)

    @classmethod
    def single_pubkey(cls, signature, signing_key: PublicKey, message: bytes) -> "SignatureSet":
        return cls(signature, message, [signing_key])

    @classmethod
    def multiple_pubkeys(cls, signature, signing_keys: List[PublicKey], message: bytes) -> "SignatureSet":
        return cls(signature, message, signing_keys)

    def verify(self) -> bool:
        return fast_aggregate_verify(
            self.signing_keys, self.message, self.signature.to_signature()
        )


# ---------------------------------------------------------------- verification

def _core_verify_pairs(pairs) -> bool:
    from .pairing import multi_pairing_is_one

    return multi_pairing_is_one(pairs)


def verify(pubkey: PublicKey, message: bytes, signature: Signature, dst: bytes = DST) -> bool:
    return fast_aggregate_verify([pubkey], message, signature, dst)


def fast_aggregate_verify(
    pubkeys: Sequence[PublicKey], message: bytes, signature: Signature, dst: bytes = DST
) -> bool:
    """All pubkeys signed the same message."""
    if not pubkeys:
        return False
    if signature.is_infinity or not signature.subgroup_check():
        return False
    agg = AggregatePublicKey.aggregate(pubkeys)
    h = hash_to_g2(message, dst)
    return _core_verify_pairs([
        (curve.neg(curve.G1), signature.point),
        (agg.point, h),
    ])


def aggregate_verify(
    pubkeys: Sequence[PublicKey], messages: Sequence[bytes], signature: Signature, dst: bytes = DST
) -> bool:
    """Each pubkey signed its own message (requires distinct messages per IETF,
    not enforced here — matches blst's aggregate_verify with grouped msgs)."""
    if not pubkeys or len(pubkeys) != len(messages):
        return False
    if signature.is_infinity or not signature.subgroup_check():
        return False
    pairs = [(curve.neg(curve.G1), signature.point)]
    for pk, msg in zip(pubkeys, messages):
        pairs.append((pk.point, hash_to_g2(bytes(msg), dst)))
    return _core_verify_pairs(pairs)


def eth_fast_aggregate_verify(
    pubkeys: Sequence[PublicKey], message: bytes, signature: Signature, dst: bytes = DST
) -> bool:
    """Eth2 consensus-spec deviation: empty pubkeys + infinity signature is valid
    (used for empty sync aggregates)."""
    if not pubkeys and signature.to_bytes() == INFINITY_SIGNATURE:
        return True
    return fast_aggregate_verify(pubkeys, message, signature, dst)


def verify_signature_sets(signature_sets: Iterable[SignatureSet], seed: Optional[bytes] = None) -> bool:
    """Batch verification via the active backend (impls/blst.rs:35-117 semantics).

    `seed` pins the random weights for reproducibility in tests; production use
    leaves it None (host CSPRNG — randomness must stay host-side, blst.rs:52-57).

    When the async device pipeline is enabled (``device_pipeline.enable()``,
    done by the client builder for jax-backend nodes), seedless calls submit
    their sets as ONE group to the persistent device worker and block on a
    future — the pipeline coalesces groups across work types into maximal
    device batches instead of dispatching this caller's sets alone.  Seeded
    calls (reproducibility contracts) and oversized batches keep the direct
    backend path.
    """
    from ... import device_pipeline, metrics, tracing
    from .backends import backend_name, get_backend

    sets = list(signature_sets)
    backend = get_backend()
    metrics.DEVICE_BATCH_INVOCATIONS.inc()
    metrics.SIGNATURE_SETS_VERIFIED.inc(len(sets))
    metrics.ATTESTATION_BATCH_SIZE.observe(len(sets))
    with tracing.span(
        "device_batch", hist=metrics.ATTESTATION_BATCH_SECONDS,
        n_sets=len(sets), backend=backend_name(),
    ):
        if device_pipeline.routes(sets, seed):
            try:
                return device_pipeline.verify(sets)
            except device_pipeline.PipelineShutdown:
                pass  # racing Client.stop: the direct path still answers
        return backend.verify_signature_sets(sets, seed=seed)
