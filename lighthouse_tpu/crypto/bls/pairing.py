"""Optimal-ate pairing on BLS12-381 (host golden model).

Semantics match the multi-pairing used by the reference's batch verifier
(``crypto/bls/src/impls/blst.rs:112-114`` — blst's
``verify_multiple_aggregate_signatures``): accumulate Miller-loop values for many
(G1, G2) pairs, one shared final exponentiation, compare against 1.

The Miller loop runs on the untwisted curve E(Fp12) with affine line functions —
slow but transparently correct; the TPU kernel (``lighthouse_tpu/ops``) implements
the optimised projective/sparse version and is validated against this module.
"""

from __future__ import annotations

from typing import Iterable, Optional, Sequence, Tuple

from . import curve
from .curve import Point, add, double, embed_g1, neg, untwist
from .fields import Fq12
from .params import P, R, X, X_ABS

_X_BITS = bin(X_ABS)[3:]  # bits of |x| below the leading 1


def _line(t: Point, q: Point, p: Point) -> Fq12:
    """Evaluate the line through t and q at p (all on E(Fp12), affine)."""
    xt, yt = t
    xq, yq = q
    xp, yp = p
    if xt != xq:
        m = (yq - yt) * (xq - xt).inv()
        return yp - yt - m * (xp - xt)
    if yt == yq:
        # tangent
        m = (xt * xt + xt * xt + xt * xt) * (yt + yt).inv()
        return yp - yt - m * (xp - xt)
    # vertical
    return xp - xt


def miller_loop(p: Point, q: Point) -> Fq12:
    """f_{|x|,Q}(P) with the end-of-loop conjugation for the negative BLS x.

    p is a G1 point embedded in Fp12, q a G2 point untwisted into Fp12.
    Returns 1 for either input at infinity.
    """
    if p is None or q is None:
        return Fq12.one()
    f = Fq12.one()
    t = q
    for bit in _X_BITS:
        f = f.square() * _line(t, t, p)
        t = double(t)
        if bit == "1":
            f = f * _line(t, q, p)
            t = add(t, q)
    # x < 0: invert; cheap inversion via conjugation is only valid post easy part,
    # so use the honest inverse here (reference model).
    return f.inv()


def _pow_x(g: Fq12) -> Fq12:
    """g^x for the (negative) BLS parameter x, for g in the cyclotomic subgroup."""
    r = Fq12.one()
    b = g
    e = X_ABS
    while e:
        if e & 1:
            r = r * b
        b = b.square()
        e >>= 1
    return r.conj()  # x negative; conj == inverse on the cyclotomic subgroup


def final_exponentiation(f: Fq12) -> Fq12:
    """f^((p^12-1)/r · 3).

    Easy part (p^6-1)(p^2+1), then the hard part scaled by 3 via the
    Hayashida–Hayasaka–Teruya decomposition
        3·(p^4-p^2+1)/r = (x-1)^2·(x+p)·(x^2+p^2-1) + 3
    (identity asserted in tests).  The extra cube is harmless for every use here:
    the framework only ever compares pairing products against 1, and gcd(3, r) = 1.
    """
    f = f.conj() * f.inv()          # ^(p^6 - 1); result is unitary
    f = f.frobenius_n(2) * f        # ^(p^2 + 1); now in the cyclotomic subgroup
    t0 = _pow_x(f) * f.conj()               # f^(x-1)
    t1 = _pow_x(t0) * t0.conj()             # ^(x-1) again
    t2 = _pow_x(t1) * t1.frobenius()        # ^(x+p)
    t3 = _pow_x(_pow_x(t2)) * t2.frobenius_n(2) * t2.conj()  # ^(x^2+p^2-1)
    return t3 * f * f * f                   # · f^3


def pairing(p: Point, q: Point) -> Fq12:
    """e(P, Q)^3 for P in G1(Fp), Q in G2(Fp2).  (Constant cube — see above.)"""
    return final_exponentiation(miller_loop(embed_g1(p), untwist(q)))


def multi_pairing_is_one(pairs: Sequence[Tuple[Point, Point]]) -> bool:
    """prod_i e(P_i, Q_i) == 1, with a single shared final exponentiation.

    This is the host-reference analog of blst's batched
    ``verify_multiple_aggregate_signatures`` multi-pairing check.
    """
    f = Fq12.one()
    for p, q in pairs:
        f = f * miller_loop(embed_g1(p), untwist(q))
    return final_exponentiation(f).is_one()
