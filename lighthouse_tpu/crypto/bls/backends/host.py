"""Host (pure-Python) batch verification backend — the golden model.

Exact port of the *semantics* of ``crypto/bls/src/impls/blst.rs:35-117``:
empty batch fails; each set contributes a nonzero 64-bit random weight; the
signature is subgroup-checked; sets with no signing keys fail; public keys are
aggregated per set; one multi-pairing decides the batch:

    e(-g1, sum_i r_i sig_i) * prod_i e([r_i] aggpk_i, H(m_i)) == 1
"""

from __future__ import annotations

import secrets
from typing import List, Optional

from .. import curve
from ..hash_to_curve import hash_to_g2
from ..pairing import multi_pairing_is_one
from ..params import DST


def _rand_scalars(n: int, seed: Optional[bytes]) -> List[int]:
    if seed is not None:
        import hashlib

        out = []
        ctr = 0
        while len(out) < n:
            r = int.from_bytes(
                hashlib.sha256(seed + ctr.to_bytes(4, "big")).digest()[:8], "big"
            )
            ctr += 1
            if r:
                out.append(r)
        return out
    out = []
    while len(out) < n:
        r = secrets.randbits(64)
        if r:
            out.append(r)
    return out


def verify_signature_sets(sets, seed: Optional[bytes] = None) -> bool:
    if not sets:
        return False
    rands = _rand_scalars(len(sets), seed)

    sig_acc = None  # sum_i [r_i] sig_i
    pairs = []
    for set_, r in zip(sets, rands):
        sig_pt = set_.signature.point
        if sig_pt is None:
            return False  # "empty" signature fails the batch
        if not curve.in_g2(sig_pt):
            return False
        if not set_.signing_keys:
            return False
        agg_pk = None
        for pk in set_.signing_keys:
            agg_pk = curve.add(agg_pk, pk.point)
        sig_acc = curve.add(sig_acc, curve.mul(sig_pt, r))
        pairs.append((curve.mul(agg_pk, r), hash_to_g2(set_.message, DST)))

    pairs.append((curve.neg(curve.G1), sig_acc))
    return multi_pairing_is_one(pairs)
