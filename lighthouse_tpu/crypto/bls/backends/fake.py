"""Always-valid backend, mirroring ``crypto/bls/src/impls/fake_crypto.rs``.

Decouples chain-logic tests from crypto cost: structural failures (empty batch,
missing keys, infinity signature) still fail, so scheduling/fallback logic keeps
its shape, but no pairing runs.  The reference uses the same trick to run its
entire test ladder without BLS cost (SURVEY.md §4, bls_setting gate).
"""

from __future__ import annotations

from typing import Optional


def verify_signature_sets(sets, seed: Optional[bytes] = None) -> bool:
    if not sets:
        return False
    for set_ in sets:
        if not set_.signing_keys:
            return False
    return True
