"""JAX/TPU batch-verification backend.

The slot of ``crypto/bls/src/impls/blst.rs`` in the reference: all signature
sets in the node funnel through here, and the multi-pairing runs as a fused,
shape-bucketed device program (``lighthouse_tpu/ops/verify.py``).
"""

from __future__ import annotations

from typing import Optional


def verify_signature_sets(sets, seed: Optional[bytes] = None) -> bool:
    from ....ops.verify import verify_signature_sets_device

    return verify_signature_sets_device(sets, seed=seed)
