"""JAX/TPU batch-verification backend.

The slot of ``crypto/bls/src/impls/blst.rs`` in the reference: all signature
sets in the node funnel through here, and the multi-pairing runs as a fused,
shape-bucketed device program (``lighthouse_tpu/ops/verify.py``).
"""

from __future__ import annotations

from typing import Optional

_platform: Optional[str] = None


def _device_platform() -> str:
    """The executing device platform (cached — jax.devices() is cheap after
    backend init, but the span field should cost a dict lookup, not a
    client call, on every batch)."""
    global _platform
    if _platform is None:
        try:
            import jax

            _platform = jax.devices()[0].platform
        except Exception:
            _platform = "unknown"
    return _platform


def verify_signature_sets(sets, seed: Optional[bytes] = None) -> bool:
    from .... import tracing
    from ....ops.verify import verify_signature_sets_device

    sets = list(sets)
    # The device-side parent span: the four stage spans recorded inside
    # verify_signature_sets_device (setup/dispatch/wait/verdict) nest here,
    # and the callee stamps its flight-recorder seq (and host-fallback flag,
    # when taken) onto this span — so a trace tree and the
    # /lighthouse/device/batches ring cross-reference in both directions.
    with tracing.span(
        "device_verify", backend="jax", platform=_device_platform(),
        n_sets=len(sets),
    ):
        return verify_signature_sets_device(sets, seed=seed)
