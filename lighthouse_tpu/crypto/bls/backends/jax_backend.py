"""JAX/TPU batch-verification backend.

The slot of ``crypto/bls/src/impls/blst.rs`` in the reference: all signature
sets in the node funnel through here, and the multi-pairing runs as a fused,
shape-bucketed device program (``lighthouse_tpu/ops/verify.py``).
"""

from __future__ import annotations

from typing import Optional


def verify_signature_sets(sets, seed: Optional[bytes] = None) -> bool:
    from .... import tracing
    from ....ops.verify import verify_signature_sets_device

    sets = list(sets)
    # The device-side parent span: the four stage spans recorded inside
    # verify_signature_sets_device (setup/dispatch/wait/verdict) nest here,
    # so a trace shows host-vs-device time for THIS batch at a glance.
    with tracing.span("device_verify", backend="jax", n_sets=len(sets)):
        return verify_signature_sets_device(sets, seed=seed)
