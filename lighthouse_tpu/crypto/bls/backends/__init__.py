"""BLS execution backends (the reference's `define_mod!` seam, crypto/bls/src/lib.rs:84-139).

- ``host``: pure-Python multi-pairing (the golden model; always available)
- ``fake``: always-valid (mirrors impls/fake_crypto.rs — lets every logic test run
  without crypto cost or TPU access)
- ``jax``: batched TPU multi-pairing kernel (lighthouse_tpu/ops)

Selected via ``set_backend()`` or env ``LIGHTHOUSE_TPU_BLS_BACKEND``.
"""

from __future__ import annotations

import os
from typing import Optional

_ACTIVE = None
_NAME = None


def get_backend():
    global _ACTIVE, _NAME
    if _ACTIVE is None:
        set_backend(os.environ.get("LIGHTHOUSE_TPU_BLS_BACKEND", "host"))
    return _ACTIVE


def backend_name() -> Optional[str]:
    get_backend()
    return _NAME


def set_backend(name: str):
    global _ACTIVE, _NAME
    if name == "host":
        from . import host as mod
    elif name == "fake":
        from . import fake as mod
    elif name == "jax":
        from . import jax_backend as mod
    else:
        raise ValueError(f"unknown BLS backend: {name!r}")
    _ACTIVE = mod
    _NAME = name
    return mod
