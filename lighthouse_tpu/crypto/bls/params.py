"""BLS12-381 curve parameters.

Role-equivalent to the constants baked into the ``blst`` backend used by the
reference (``crypto/bls/src/impls/blst.rs``).  Everything here is a plain
Python integer; all derived quantities are asserted in ``tests/test_bls_fields.py``
rather than trusted.
"""

# Base field modulus.
P = 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAAAB

# Subgroup order (scalar field modulus).
R = 0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001

# BLS parameter x (negative for BLS12-381).  t = x + 1 is the trace of Frobenius.
X = -0xD201000000010000
X_ABS = -X

# G1 cofactor h1 = (x - 1)^2 / 3 (asserted in tests: h1 * r == p + 1 - (x + 1)).
H1 = (X - 1) ** 2 // 3

# G2 (twist) cofactor h2 = (x^8 - 4x^7 + 5x^6 - 4x^4 + 6x^3 - 4x^2 - 4x + 13) / 9.
H2 = (X**8 - 4 * X**7 + 5 * X**6 - 4 * X**4 + 6 * X**3 - 4 * X**2 - 4 * X + 13) // 9

# Curve equations: E1/Fp: y^2 = x^3 + 4;  E2/Fp2: y^2 = x^3 + 4(1 + i).
B1 = 4
B2 = (4, 4)  # 4 + 4i as an Fp2 pair (c0, c1)

# Standard generators (zcash serialization spec); asserted on-curve/in-subgroup in tests.
G1_X = 0x17F1D3A73197D7942695638C4FA9AC0FC3688C4F9774B905A14E3A3F171BAC586C55E83FF97A1AEFFB3AF00ADB22C6BB
G1_Y = 0x08B3F481E3AAA0F1A09E30ED741D8AE4FCF5E095D5D00AF600DB18CB2C04B3EDD03CC744A2888AE40CAA232946C5E7E1

G2_X_C0 = 0x024AA2B2F08F0A91260805272DC51051C6E47AD4FA403B02B4510B647AE3D1770BAC0326A805BBEFD48056C8C121BDB8
G2_X_C1 = 0x13E02B6052719F607DACD3A088274F65596BD0D09920B61AB5DA61BBDC7F5049334CF11213945D57E5AC7D055D042B7E
G2_Y_C0 = 0x0CE5D527727D6E118CC9CDC6DA2E351AADFD9BAA8CBDD3A76D429A695160D12C923AC9CC3BACA289E193548608B82801
G2_Y_C1 = 0x0606C4A02EA734CC32ACD2B02BC28B99CB3E287E85A763AF267492AB572E99AB3F370D275CEC1DA1AAA9075FF05F79BE

# Domain separation tag for eth2 signatures (crypto/bls/src/impls/blst.rs:13).
DST = b"BLS_SIG_BLS12381G2_XMD:SHA-256_SSWU_RO_POP_"

# Batch-verification random-weight size in bits (crypto/bls/src/impls/blst.rs:14).
RAND_BITS = 64

# RFC 9380 8.8.2 SSWU parameters for the 3-isogenous curve E' over Fp2:
# E': y^2 = x^3 + A' x + B' with A' = 240*i, B' = 1012*(1+i), Z = -(2+i).
SSWU_A = (0, 240)
SSWU_B = (1012, 1012)
SSWU_Z = (P - 2, P - 1)

assert (X - 1) ** 2 % 3 == 0
assert (X**8 - 4 * X**7 + 5 * X**6 - 4 * X**4 + 6 * X**3 - 4 * X**2 - 4 * X + 13) % 9 == 0
assert (P - 1) % 6 == 0, "tower construction requires p ≡ 1 (mod 6)"
