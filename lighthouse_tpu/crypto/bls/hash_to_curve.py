"""RFC 9380 hash-to-curve for BLS12-381 G2 (suite BLS12381G2_XMD:SHA-256_SSWU_RO_).

The reference client delegates this to blst via its DST constant
(``crypto/bls/src/impls/blst.rs:13``).  Here: expand_message_xmd(SHA-256) →
hash_to_field(Fp2, m=2, count=2, L=64) → simplified SWU on the 3-isogenous curve
E' → derived Velu isogeny (``_sswu_g2_iso.py``, see scripts/derive_g2_isogeny.py
for the derivation and the RFC-fingerprint cross-checks) → Budroni–Pintore
cofactor clearing.
"""

from __future__ import annotations

import hashlib
import struct

from . import _sswu_g2_iso as ISO
from .curve import Point, add, clear_cofactor_g2
from .fields import Fq2
from .params import P, SSWU_A, SSWU_B, SSWU_Z

_A = Fq2(*SSWU_A)
_B = Fq2(*SSWU_B)
_Z = Fq2(*SSWU_Z)

_XNUM = [Fq2(c0, c1) for c0, c1 in ISO.XNUM]
_XDEN = [Fq2(c0, c1) for c0, c1 in ISO.XDEN]
_YNUM = [Fq2(c0, c1) for c0, c1 in ISO.YNUM]
_YDEN = [Fq2(c0, c1) for c0, c1 in ISO.YDEN]

L = 64  # ceil((ceil(log2(p)) + k) / 8) = ceil((381 + 128) / 8)


def expand_message_xmd(msg: bytes, dst: bytes, len_in_bytes: int) -> bytes:
    """RFC 9380 §5.3.1 with SHA-256."""
    b_in_bytes = 32
    r_in_bytes = 64
    ell = (len_in_bytes + b_in_bytes - 1) // b_in_bytes
    if ell > 255 or len_in_bytes > 65535 or len(dst) > 255:
        raise ValueError("expand_message_xmd parameter out of range")
    dst_prime = dst + bytes([len(dst)])
    z_pad = b"\x00" * r_in_bytes
    l_i_b_str = struct.pack(">H", len_in_bytes)
    b0 = hashlib.sha256(z_pad + msg + l_i_b_str + b"\x00" + dst_prime).digest()
    b1 = hashlib.sha256(b0 + b"\x01" + dst_prime).digest()
    bs = [b1]
    for i in range(2, ell + 1):
        prev = bs[-1]
        xored = bytes(a ^ b for a, b in zip(b0, prev))
        bs.append(hashlib.sha256(xored + bytes([i]) + dst_prime).digest())
    return b"".join(bs)[:len_in_bytes]


def hash_to_field_fq2(msg: bytes, count: int, dst: bytes) -> list:
    """RFC 9380 §5.2: count elements of Fp2."""
    m = 2
    uniform = expand_message_xmd(msg, dst, count * m * L)
    out = []
    for i in range(count):
        coeffs = []
        for j in range(m):
            off = L * (j + i * m)
            coeffs.append(int.from_bytes(uniform[off : off + L], "big") % P)
        out.append(Fq2(coeffs[0], coeffs[1]))
    return out


def map_to_curve_simple_swu(u: Fq2):
    """Simplified SWU map onto E': y^2 = x^3 + A'x + B' (RFC 9380 §6.6.2)."""
    u2 = u.square()
    zu2 = _Z * u2
    tv = zu2.square() + zu2  # Z^2 u^4 + Z u^2
    neg_b_over_a = -(_B * _A.inv())
    if tv.is_zero():
        x1 = _B * (_Z * _A).inv()
    else:
        x1 = neg_b_over_a * (Fq2.one() + tv.inv())
    gx1 = x1.square() * x1 + _A * x1 + _B
    y1 = gx1.sqrt()
    if y1 is not None:
        x, y = x1, y1
    else:
        x2 = zu2 * x1
        gx2 = x2.square() * x2 + _A * x2 + _B
        y2 = gx2.sqrt()
        assert y2 is not None, "SSWU: neither gx1 nor gx2 is square (impossible)"
        x, y = x2, y2
    if u.sgn0() != y.sgn0():
        y = -y
    return (x, y)


def _horner(poly, x: Fq2) -> Fq2:
    acc = Fq2.zero()
    for c in reversed(poly):
        acc = acc * x + c
    return acc


def iso_map(pt) -> Point:
    """Evaluate the 3-isogeny E' -> E2."""
    x, y = pt
    xden = _horner(_XDEN, x)
    if xden.is_zero():
        return None  # kernel point maps to infinity
    x2 = _horner(_XNUM, x) * xden.inv()
    y2 = y * _horner(_YNUM, x) * _horner(_YDEN, x).inv()
    return (x2, y2)


def hash_to_g2(msg: bytes, dst: bytes) -> Point:
    """hash_to_curve (random-oracle variant): the signing/verification H(m)."""
    u0, u1 = hash_to_field_fq2(msg, 2, dst)
    q0 = iso_map(map_to_curve_simple_swu(u0))
    q1 = iso_map(map_to_curve_simple_swu(u1))
    return clear_cofactor_g2(add(q0, q1))
