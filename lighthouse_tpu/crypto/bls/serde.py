"""ZCash-convention point (de)serialization for BLS12-381.

Byte-compatible with the encodings the reference handles via blst
(``crypto/bls/src/generic_public_key_bytes.rs`` / ``generic_signature.rs``):
48-byte compressed G1, 96-byte compressed G2, with the three flag bits in the
most-significant byte (compression 0x80, infinity 0x40, y-sign 0x20).
"""

from __future__ import annotations

from typing import Optional

from .curve import B1_FQ, B2_FQ2, Point, is_on_curve
from .fields import Fq, Fq2
from .params import P

_C_FLAG = 0x80
_I_FLAG = 0x40
_S_FLAG = 0x20
_HALF_P = (P - 1) // 2

G1_COMPRESSED_LEN = 48
G2_COMPRESSED_LEN = 96


class DecodeError(ValueError):
    pass


def _y_is_big_fq(y: Fq) -> bool:
    return y.n > _HALF_P


def _y_is_big_fq2(y: Fq2) -> bool:
    if y.c1 != 0:
        return y.c1 > _HALF_P
    return y.c0 > _HALF_P


def g1_compress(pt: Point) -> bytes:
    if pt is None:
        return bytes([_C_FLAG | _I_FLAG]) + b"\x00" * 47
    x, y = pt
    flags = _C_FLAG | (_S_FLAG if _y_is_big_fq(y) else 0)
    raw = x.n.to_bytes(48, "big")
    return bytes([raw[0] | flags]) + raw[1:]


def g1_decompress(data: bytes) -> Point:
    if len(data) != G1_COMPRESSED_LEN:
        raise DecodeError(f"G1 compressed must be 48 bytes, got {len(data)}")
    flags = data[0]
    if not flags & _C_FLAG:
        raise DecodeError("compression flag not set")
    if flags & _I_FLAG:
        if flags & _S_FLAG or any(data[1:]) or data[0] & 0x1F:
            raise DecodeError("malformed infinity encoding")
        return None
    x_int = int.from_bytes(bytes([data[0] & 0x1F]) + data[1:], "big")
    if x_int >= P:
        raise DecodeError("x >= p")
    x = Fq(x_int)
    y2 = x * x * x + B1_FQ
    y = y2.sqrt()
    if y is None:
        raise DecodeError("x not on curve")
    if _y_is_big_fq(y) != bool(flags & _S_FLAG):
        y = -y
    return (x, y)


def g2_compress(pt: Point) -> bytes:
    if pt is None:
        return bytes([_C_FLAG | _I_FLAG]) + b"\x00" * 95
    x, y = pt
    flags = _C_FLAG | (_S_FLAG if _y_is_big_fq2(y) else 0)
    raw_c1 = x.c1.to_bytes(48, "big")
    raw_c0 = x.c0.to_bytes(48, "big")
    return bytes([raw_c1[0] | flags]) + raw_c1[1:] + raw_c0


def g2_decompress(data: bytes) -> Point:
    if len(data) != G2_COMPRESSED_LEN:
        raise DecodeError(f"G2 compressed must be 96 bytes, got {len(data)}")
    flags = data[0]
    if not flags & _C_FLAG:
        raise DecodeError("compression flag not set")
    if flags & _I_FLAG:
        if flags & _S_FLAG or any(data[1:]) or data[0] & 0x1F:
            raise DecodeError("malformed infinity encoding")
        return None
    x_c1 = int.from_bytes(bytes([data[0] & 0x1F]) + data[1:48], "big")
    x_c0 = int.from_bytes(data[48:], "big")
    if x_c1 >= P or x_c0 >= P:
        raise DecodeError("x component >= p")
    x = Fq2(x_c0, x_c1)
    y2 = x * x * x + B2_FQ2
    y = y2.sqrt()
    if y is None:
        raise DecodeError("x not on curve")
    if _y_is_big_fq2(y) != bool(flags & _S_FLAG):
        y = -y
    return (x, y)
