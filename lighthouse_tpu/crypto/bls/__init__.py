"""BLS12-381 signatures for the beacon chain (reference: ``crypto/bls``).

Public surface mirrors the reference's generic layer; the execution backend
(host golden model | fake | JAX/TPU batched pairing) is swappable at one seam,
exactly like the reference's ``define_mod!`` backend trait
(``crypto/bls/src/lib.rs:84-139``).
"""

from .api import (
    INFINITY_PUBLIC_KEY,
    INFINITY_SIGNATURE,
    PUBLIC_KEY_BYTES_LEN,
    SECRET_KEY_BYTES_LEN,
    SIGNATURE_BYTES_LEN,
    AggregatePublicKey,
    AggregateSignature,
    BlsError,
    PublicKey,
    SecretKey,
    Signature,
    SignatureSet,
    aggregate_verify,
    eth_fast_aggregate_verify,
    fast_aggregate_verify,
    verify,
    verify_signature_sets,
)
from .backends import backend_name, set_backend
from .params import DST, RAND_BITS

__all__ = [
    "AggregatePublicKey",
    "AggregateSignature",
    "BlsError",
    "DST",
    "INFINITY_PUBLIC_KEY",
    "INFINITY_SIGNATURE",
    "PUBLIC_KEY_BYTES_LEN",
    "PublicKey",
    "RAND_BITS",
    "SECRET_KEY_BYTES_LEN",
    "SIGNATURE_BYTES_LEN",
    "SecretKey",
    "Signature",
    "SignatureSet",
    "aggregate_verify",
    "backend_name",
    "eth_fast_aggregate_verify",
    "fast_aggregate_verify",
    "set_backend",
    "verify",
    "verify_signature_sets",
]
