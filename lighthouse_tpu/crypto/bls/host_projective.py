"""Host-integer mirror of the *device* pairing algorithm.

The golden model (``pairing.py``) uses affine arithmetic with field inversions —
transparently correct, but inversion-per-step is unusable on TPU.  The device
kernels (``lighthouse_tpu/ops``) instead run an inversion-free projective Miller
loop on the twist with denominator elimination.  This module is that exact
algorithm over Python integers, so the JAX/limb implementation can be validated
bit-for-bit against it, while *this* module is validated against the golden model
(tests/test_host_projective.py).

Role-equivalent to the optimised pairing inside ``blst`` that backs the
reference's ``crypto/bls/src/impls/blst.rs:112-114`` batch verification.

Derivation notes (why denominator elimination is sound here)
------------------------------------------------------------
Untwisting the M-twist point (x', y') on E'/Fq2: y^2 = x^3 + 4(1+u) gives
(x' * v^-1, y' * (v/xi) * w) on E/Fq12 (w^2 = v, v^3 = xi).  For a line through
untwisted twist points evaluated at P = (xp, yp) in G1(Fp), both the doubling
and addition slopes have the shape M * (v^2/xi) * w with M in Fq2, so the line
value is

    l = yp - w * [ y~ * v/xi  +  M * xp * v^2/xi  -  M * x~ * v/xi ]

Scaling l by any element of the subfield F_{p^6} (the c1 = 0 subalgebra, which
contains Fq2, v and v^2) multiplies the Miller value by a factor that the final
exponentiation's (p^6 - 1) stage maps to 1.  We scale away all denominators
(2y~, x~q - x~, Z powers, xi), leaving polynomial line coefficients:

    doubling at T=(X,Y,Z):   l'' = 2*Y*Z^2*xi*yp
                                   - w*( (2*Y^2*Z - 3*X^3)*v + 3*X^2*Z*xp*v^2 )
    addition (T, Q=(xq,yq)): l'' = xi*F*yp
                                   - w*( (yq*F - E*xq)*v + E*xp*v^2 )
        with E = yq*Z - Y, F = xq*Z - X   (both Fq2)

The Miller accumulator is f_{|x|,Q}(P) *without* the final inversion for the
negative BLS parameter; ``final_exponentiation(f)`` then differs from the golden
model's value exactly by inversion, which preserves the only predicate the
framework uses: ``== 1``.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

from .fields import Fq2, Fq6, Fq12
from .pairing import final_exponentiation
from .params import X_ABS

# Bits of |x| below the leading one, MSB first — the fixed Miller schedule.
X_BITS = [int(b) for b in bin(X_ABS)[3:]]

XI = Fq2(1, 1)


# ---------------------------------------------------------------- G2 projective
# Homogeneous projective coordinates (X : Y : Z) on the twist, affine = (X/Z, Y/Z).
# Formulas verified against the affine golden model in tests.

Proj2 = Tuple[Fq2, Fq2, Fq2]


def proj_from_affine(pt) -> Proj2:
    x, y = pt
    return (x, y, Fq2.one())


def proj_to_affine(p: Proj2):
    x, y, z = p
    if z.is_zero():
        return None
    zi = z.inv()
    return (x * zi, y * zi)


def proj_dbl(t: Proj2) -> Tuple[Proj2, Tuple[Fq2, Fq2, Fq2]]:
    """Double T and return the (eliminated-denominator) line coefficients.

    Line l'' = L00 * yp + w*( L1v + L1vv * xp ) with
        L00 = 2*Y*Z^2*xi      (an Fq2; multiplied by the Fp scalar yp)
        L1v = -(2*Y^2*Z - 3*X^3)
        L1vv = -3*X^2*Z       (multiplied by the Fp scalar xp)
    """
    x, y, z = t
    xx = x.square()                     # X^2
    w3 = xx + xx + xx                   # 3X^2
    s = y * z                           # S = Y*Z
    b = x * y * s                       # B = X*Y*S
    h = w3.square() - (b + b + b + b + b + b + b + b)   # W^2 - 8B
    x3 = (h * s).mul_scalar(2)
    y2s2 = (y * s).square()
    y3 = w3 * (b + b + b + b - h) - y2s2.mul_scalar(8)
    z3 = s.square() * s
    z3 = z3.mul_scalar(8)

    l00 = (y * z.square()).mul_scalar(2).mul_by_xi()    # 2YZ^2 * xi
    l1v = -(y.square() * z.mul_scalar(2) - xx * x.mul_scalar(3))
    l1vv = -(xx * z).mul_scalar(3)
    return (x3, y3, z3), (l00, l1v, l1vv)


def proj_add_mixed(t: Proj2, q) -> Tuple[Proj2, Tuple[Fq2, Fq2, Fq2]]:
    """T + Q for affine twist point Q, plus the line through them.

    Line l'' = L00 * yp + w*( L1v + L1vv * xp ) with
        L00 = xi * F
        L1v = -(yq*F - E*xq)
        L1vv = -E            (times xp)
        E = yq*Z - Y, F = xq*Z - X
    """
    x, y, z = t
    xq, yq = q
    e = yq * z - y
    f = xq * z - x
    ff = f.square()
    fff = f * ff
    t1 = e.square() * z - ff * (x + xq * z)
    x3 = f * t1
    y3 = e * (ff * x - t1) - fff * y
    z3 = z * fff

    l00 = f.mul_by_xi()
    l1v = -(yq * f - e * xq)
    l1vv = -e
    return (x3, y3, z3), (l00, l1v, l1vv)


def line_to_fq12(line: Tuple[Fq2, Fq2, Fq2], xp: int, yp: int) -> Fq12:
    """Assemble the sparse line value  L00*yp + w*(L1v*v + L1vv*xp*v^2)."""
    l00, l1v, l1vv = line
    c0 = Fq6(l00.mul_scalar(yp), Fq2.zero(), Fq2.zero())
    c1 = Fq6(Fq2.zero(), l1v, l1vv.mul_scalar(xp))
    return Fq12(c0, c1)


def miller_loop_projective(p, q) -> Fq12:
    """f_{|x|,Q}(P) via the inversion-free schedule the device kernel runs.

    p: G1 affine (Fq pair as ints via .n), q: G2 affine twist point (Fq2 pair).
    Infinity on either side contributes the neutral value 1.
    """
    if p is None or q is None:
        return Fq12.one()
    xp, yp = p[0].n, p[1].n
    f = Fq12.one()
    t: Proj2 = proj_from_affine(q)
    for bit in X_BITS:
        t, line = proj_dbl(t)
        f = f.square() * line_to_fq12(line, xp, yp)
        if bit:
            t, line = proj_add_mixed(t, q)
            f = f * line_to_fq12(line, xp, yp)
    return f


def multi_pairing_is_one_projective(pairs: Sequence[Tuple]) -> bool:
    """Device-algorithm analog of ``pairing.multi_pairing_is_one``."""
    f = Fq12.one()
    for p, q in pairs:
        f = f * miller_loop_projective(p, q)
    return final_exponentiation(f).is_one()
