"""BLS12-381 elliptic-curve group operations (host golden model).

Generic short-Weierstrass (a = 0) affine arithmetic parameterised over the field
element type, so the same code serves E1(Fp), the twist E2(Fp2) and the untwisted
E(Fp12) used by the Miller loop.  Mirrors the capability surface of the reference's
``crypto/bls`` point types (``crypto/bls/src/generic_public_key.rs`` et al.).
"""

from __future__ import annotations

from typing import Optional, Tuple

from .fields import Fq, Fq2, Fq6, Fq12, GAMMA
from .params import B1, B2, G1_X, G1_Y, G2_X_C0, G2_X_C1, G2_Y_C0, G2_Y_C1, H1, P, R, X

# A point is None (infinity) or a tuple (x, y) of field elements.
Point = Optional[Tuple[object, object]]


def is_on_curve(pt: Point, b) -> bool:
    if pt is None:
        return True
    x, y = pt
    return y * y == x * x * x + b


def add(p1: Point, p2: Point) -> Point:
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2:
        if y1 == y2:
            return double(p1)
        return None
    m = (y2 - y1) * (x2 - x1).inv()
    x3 = m * m - x1 - x2
    return (x3, m * (x1 - x3) - y1)


def double(p1: Point) -> Point:
    if p1 is None:
        return None
    x1, y1 = p1
    if y1.is_zero():
        return None
    m = (x1 * x1 + x1 * x1 + x1 * x1) * (y1 + y1).inv()
    x3 = m * m - x1 - x1
    return (x3, m * (x1 - x3) - y1)


def neg(p1: Point) -> Point:
    if p1 is None:
        return None
    x1, y1 = p1
    return (x1, -y1)


def mul(p1: Point, k: int) -> Point:
    """Scalar multiplication [k]P (double-and-add; host reference only)."""
    if k < 0:
        return mul(neg(p1), -k)
    acc: Point = None
    addend = p1
    while k:
        if k & 1:
            acc = add(acc, addend)
        addend = double(addend)
        k >>= 1
    return acc


G1 = (Fq(G1_X), Fq(G1_Y))
G2 = (Fq2(G2_X_C0, G2_X_C1), Fq2(G2_Y_C0, G2_Y_C1))

B1_FQ = Fq(B1)
B2_FQ2 = Fq2(*B2)
B12_FQ12 = Fq12.from_fq2(Fq2(4, 0))  # untwisted curve: y^2 = x^3 + 4 over Fp12


def untwist(pt: Point) -> Point:
    """Map E2(Fp2) -> E(Fp12): (x, y) -> (x / w^2, y / w^3)  (M-twist)."""
    if pt is None:
        return None
    x, y = pt
    w = Fq12.w()
    w2_inv = (w * w).inv()
    w3_inv = (w * w * w).inv()
    return (Fq12.from_fq2(x) * w2_inv, Fq12.from_fq2(y) * w3_inv)


def embed_g1(pt: Point) -> Point:
    """Embed E1(Fp) into E(Fp12)."""
    if pt is None:
        return None
    x, y = pt
    return (Fq12.from_fq2(Fq2(x.n, 0)), Fq12.from_fq2(Fq2(y.n, 0)))


# psi: untwist -> Frobenius -> twist endomorphism on E2(Fp2).
# psi(x, y) = (cx * conj(x), cy * conj(y)) with cx = xi^{-(p-1)/3}, cy = xi^{-(p-1)/2}.
_XI = Fq2(1, 1)
PSI_CX = _XI.pow((P - 1) // 3).inv()
PSI_CY = _XI.pow((P - 1) // 2).inv()


def psi(pt: Point) -> Point:
    if pt is None:
        return None
    x, y = pt
    return (x.conj() * PSI_CX, y.conj() * PSI_CY)


def psi2(pt: Point) -> Point:
    return psi(psi(pt))


def clear_cofactor_g2(pt: Point) -> Point:
    """Budroni–Pintore fast cofactor clearing, as specified for BLS12-381 G2
    (RFC 9380 / hash-to-curve draft; what blst implements):

        h_eff * P = [x^2 - x - 1]P + [x - 1]psi(P) + psi^2([2]P)
    """
    t1 = mul(pt, X * X - X - 1)
    t2 = mul(psi(pt), X - 1)
    t3 = psi2(double(pt))
    return add(add(t1, t2), t3)


def mul_by_x(pt: Point) -> Point:
    """[x]P with the (negative) BLS parameter."""
    return mul(pt, X)


def in_g1(pt: Point) -> bool:
    """Full G1 membership: on curve and in the r-order subgroup."""
    if pt is None:
        return True
    if not is_on_curve(pt, B1_FQ):
        return False
    return mul(pt, R) is None


def in_g2(pt: Point) -> bool:
    """Full G2 membership: on the twist and in the r-order subgroup.

    Uses the psi-eigenvalue check psi(P) == [x]P (valid for BLS12-381; the host
    tests cross-validate against the naive [r]P == O check).
    """
    if pt is None:
        return True
    if not is_on_curve(pt, B2_FQ2):
        return False
    return psi(pt) == mul_by_x(pt)
