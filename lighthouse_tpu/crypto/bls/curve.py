"""BLS12-381 elliptic-curve group operations (host golden model).

Generic short-Weierstrass (a = 0) affine arithmetic parameterised over the field
element type, so the same code serves E1(Fp), the twist E2(Fp2) and the untwisted
E(Fp12) used by the Miller loop.  Mirrors the capability surface of the reference's
``crypto/bls`` point types (``crypto/bls/src/generic_public_key.rs`` et al.).
"""

from __future__ import annotations

from typing import Optional, Tuple

from .fields import Fq, Fq2, Fq6, Fq12, GAMMA
from .params import B1, B2, G1_X, G1_Y, G2_X_C0, G2_X_C1, G2_Y_C0, G2_Y_C1, H1, P, R, X

# A point is None (infinity) or a tuple (x, y) of field elements.
Point = Optional[Tuple[object, object]]


def is_on_curve(pt: Point, b) -> bool:
    if pt is None:
        return True
    x, y = pt
    return y * y == x * x * x + b


def add(p1: Point, p2: Point) -> Point:
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2:
        if y1 == y2:
            return double(p1)
        return None
    m = (y2 - y1) * (x2 - x1).inv()
    x3 = m * m - x1 - x2
    return (x3, m * (x1 - x3) - y1)


def double(p1: Point) -> Point:
    if p1 is None:
        return None
    x1, y1 = p1
    if y1.is_zero():
        return None
    m = (x1 * x1 + x1 * x1 + x1 * x1) * (y1 + y1).inv()
    x3 = m * m - x1 - x1
    return (x3, m * (x1 - x3) - y1)


def neg(p1: Point) -> Point:
    if p1 is None:
        return None
    x1, y1 = p1
    return (x1, -y1)


# Jacobian projective coordinates (x = X/Z^2, y = Y/Z^3) for the scalar-
# multiplication hot path: affine double-and-add pays one ~381-bit modular
# inversion PER STEP (~0.3 ms each on Python ints), so deriving a
# production-scale registry's worth of interop keypairs took minutes of
# setup.  Jacobian arithmetic is inversion-free until the single final
# conversion — an order-of-magnitude speedup with identical results.
# Formulas: EFD dbl-2009-l / add-2007-bl, valid for a = 0 over every field
# in the tower (the same genericity contract as the affine ops above).
# Infinity stays None; a Jacobian point is a tuple (X, Y, Z).


def _jac_double(p):
    if p is None:
        return None
    X1, Y1, Z1 = p
    if Y1.is_zero():
        return None
    A = X1 * X1
    B = Y1 * Y1
    C = B * B
    t = X1 + B
    D = t * t - A - C
    D = D + D
    E = A + A + A
    F = E * E
    X3 = F - (D + D)
    C8 = C + C
    C8 = C8 + C8
    C8 = C8 + C8
    Y3 = E * (D - X3) - C8
    Z3 = (Y1 + Y1) * Z1
    return (X3, Y3, Z3)


def _jac_add(p, q):
    if p is None:
        return q
    if q is None:
        return p
    X1, Y1, Z1 = p
    X2, Y2, Z2 = q
    Z1Z1 = Z1 * Z1
    Z2Z2 = Z2 * Z2
    U1 = X1 * Z2Z2
    U2 = X2 * Z1Z1
    S1 = Y1 * Z2 * Z2Z2
    S2 = Y2 * Z1 * Z1Z1
    if U1 == U2:
        if S1 == S2:
            return _jac_double(p)
        return None
    H = U2 - U1
    t = H + H
    I = t * t
    J = H * I
    r = S2 - S1
    r = r + r
    V = U1 * I
    X3 = r * r - J - (V + V)
    S1J2 = S1 * J
    Y3 = r * (V - X3) - (S1J2 + S1J2)
    t2 = Z1 + Z2
    Z3 = (t2 * t2 - Z1Z1 - Z2Z2) * H
    return (X3, Y3, Z3)


def mul(p1: Point, k: int) -> Point:
    """Scalar multiplication [k]P (host reference only) — Jacobian
    double-and-add internally, converted back to the affine form the rest
    of the module speaks."""
    if k < 0:
        return mul(neg(p1), -k)
    if p1 is None or k == 0:
        return None
    acc = None
    addend = (p1[0], p1[1], p1[0].one())
    while k:
        if k & 1:
            acc = _jac_add(acc, addend)
        addend = _jac_double(addend)
        k >>= 1
    if acc is None:
        return None
    X, Y, Z = acc
    zinv = Z.inv()
    zinv2 = zinv * zinv
    return (X * zinv2, Y * zinv2 * zinv)


G1 = (Fq(G1_X), Fq(G1_Y))
G2 = (Fq2(G2_X_C0, G2_X_C1), Fq2(G2_Y_C0, G2_Y_C1))

B1_FQ = Fq(B1)
B2_FQ2 = Fq2(*B2)
B12_FQ12 = Fq12.from_fq2(Fq2(4, 0))  # untwisted curve: y^2 = x^3 + 4 over Fp12


def untwist(pt: Point) -> Point:
    """Map E2(Fp2) -> E(Fp12): (x, y) -> (x / w^2, y / w^3)  (M-twist)."""
    if pt is None:
        return None
    x, y = pt
    w = Fq12.w()
    w2_inv = (w * w).inv()
    w3_inv = (w * w * w).inv()
    return (Fq12.from_fq2(x) * w2_inv, Fq12.from_fq2(y) * w3_inv)


def embed_g1(pt: Point) -> Point:
    """Embed E1(Fp) into E(Fp12)."""
    if pt is None:
        return None
    x, y = pt
    return (Fq12.from_fq2(Fq2(x.n, 0)), Fq12.from_fq2(Fq2(y.n, 0)))


# psi: untwist -> Frobenius -> twist endomorphism on E2(Fp2).
# psi(x, y) = (cx * conj(x), cy * conj(y)) with cx = xi^{-(p-1)/3}, cy = xi^{-(p-1)/2}.
_XI = Fq2(1, 1)
PSI_CX = _XI.pow((P - 1) // 3).inv()
PSI_CY = _XI.pow((P - 1) // 2).inv()


def psi(pt: Point) -> Point:
    if pt is None:
        return None
    x, y = pt
    return (x.conj() * PSI_CX, y.conj() * PSI_CY)


def psi2(pt: Point) -> Point:
    return psi(psi(pt))


def clear_cofactor_g2(pt: Point) -> Point:
    """Budroni–Pintore fast cofactor clearing, as specified for BLS12-381 G2
    (RFC 9380 / hash-to-curve draft; what blst implements):

        h_eff * P = [x^2 - x - 1]P + [x - 1]psi(P) + psi^2([2]P)
    """
    t1 = mul(pt, X * X - X - 1)
    t2 = mul(psi(pt), X - 1)
    t3 = psi2(double(pt))
    return add(add(t1, t2), t3)


def mul_by_x(pt: Point) -> Point:
    """[x]P with the (negative) BLS parameter."""
    return mul(pt, X)


def in_g1(pt: Point) -> bool:
    """Full G1 membership: on curve and in the r-order subgroup."""
    if pt is None:
        return True
    if not is_on_curve(pt, B1_FQ):
        return False
    return mul(pt, R) is None


def in_g2(pt: Point) -> bool:
    """Full G2 membership: on the twist and in the r-order subgroup.

    Uses the psi-eigenvalue check psi(P) == [x]P (valid for BLS12-381; the host
    tests cross-validate against the naive [r]P == O check).
    """
    if pt is None:
        return True
    if not is_on_curve(pt, B2_FQ2):
        return False
    return psi(pt) == mul_by_x(pt)
