"""KZG polynomial commitments for Deneb blob sidecars (EIP-4844).

Role-equivalent of the reference's ``crypto/kzg`` crate (`crypto/kzg/src/
lib.rs:32-144`: ``Kzg`` holding a trusted setup with
``blob_to_kzg_commitment``, ``compute_blob_kzg_proof``,
``verify_blob_kzg_proof{,_batch}``, point-eval verify), which wraps the C
``c-kzg-4844`` library.  Re-designed rather than ported: polynomial math runs
over dense int arrays with Pippenger MSM on host (``g1.py``), and the final
pairing product reuses the same BLS12-381 pairing engine as signature
verification — on TPU both KZG batches and signature batches feed one batched
multi-pairing program.

Follows the consensus-specs Deneb ``polynomial-commitments.md`` functions
(compute_challenge / evaluate_polynomial_in_evaluation_form /
verify_kzg_proof_batch) with their exact Fiat-Shamir byte layouts, so
commitments/proofs are interoperable with c-kzg given the same trusted setup.

The engine is parameterized by the trusted setup:
 - ``TrustedSetup.from_json`` reads the c-kzg JSON format (the official
   ceremony file a node operator supplies);
 - ``TrustedSetup.insecure_dev_setup`` derives a setup from a known secret —
   the testing analog of the reference's bundled setup, valid for
   self-consistent prove/verify but NOT for mainnet data.
"""

from __future__ import annotations

import hashlib
import json
from dataclasses import dataclass
from functools import lru_cache
from typing import List, Optional, Sequence, Tuple

from ..bls import curve, serde
from ..bls.fields import Fq, Fq2
from ..bls.pairing import multi_pairing_is_one
from ..bls.params import R
from . import g1

BLS_MODULUS = R
BYTES_PER_FIELD_ELEMENT = 32
FIELD_ELEMENTS_PER_BLOB = 4096  # mainnet & minimal presets alike
PRIMITIVE_ROOT_OF_UNITY = 7
# Domain tags, spec polynomial-commitments.md
FIAT_SHAMIR_PROTOCOL_DOMAIN = b"FSBLOBVERIFY_V1_"
RANDOM_CHALLENGE_KZG_BATCH_DOMAIN = b"RCKZGBATCH___V1_"
KZG_ENDIANNESS = "big"

G1_GEN = (curve.G1[0].n, curve.G1[1].n)


class KzgError(ValueError):
    pass


def _inv(x: int) -> int:
    return pow(x, BLS_MODULUS - 2, BLS_MODULUS)


def _batch_inv(xs: Sequence[int]) -> List[int]:
    """Montgomery batch inversion: one modexp for the whole list."""
    n = len(xs)
    prefix = [1] * (n + 1)
    for i, x in enumerate(xs):
        if x == 0:
            raise KzgError("division by zero in batch inversion")
        prefix[i + 1] = prefix[i] * x % BLS_MODULUS
    inv_all = _inv(prefix[n])
    out = [0] * n
    for i in range(n - 1, -1, -1):
        out[i] = prefix[i] * inv_all % BLS_MODULUS
        inv_all = inv_all * xs[i] % BLS_MODULUS
    return out


@lru_cache(maxsize=8)
def compute_roots_of_unity(width: int) -> Tuple[int, ...]:
    if width <= 0 or width & (width - 1) != 0 or (BLS_MODULUS - 1) % width != 0:
        raise KzgError(f"domain width {width} is not a valid power of two")
    root = pow(PRIMITIVE_ROOT_OF_UNITY, (BLS_MODULUS - 1) // width, BLS_MODULUS)
    out = [1] * width
    for i in range(1, width):
        out[i] = out[i - 1] * root % BLS_MODULUS
    return tuple(out)


@lru_cache(maxsize=8)
def _brp_indices(width: int) -> Tuple[int, ...]:
    bits = width.bit_length() - 1
    return tuple(int(format(i, f"0{bits}b")[::-1], 2) for i in range(width))


@lru_cache(maxsize=8)
def roots_of_unity_brp(width: int) -> Tuple[int, ...]:
    roots = compute_roots_of_unity(width)
    return tuple(roots[i] for i in _brp_indices(width))


def bit_reversal_permutation(seq: Sequence, width: Optional[int] = None) -> list:
    width = len(seq) if width is None else width
    return [seq[i] for i in _brp_indices(width)]


# ---------------------------------------------------------------------------
# Field / blob (de)serialization
# ---------------------------------------------------------------------------


def bytes_to_bls_field(b: bytes) -> int:
    if len(b) != BYTES_PER_FIELD_ELEMENT:
        raise KzgError(f"field element must be {BYTES_PER_FIELD_ELEMENT} bytes")
    x = int.from_bytes(b, KZG_ENDIANNESS)
    if x >= BLS_MODULUS:
        raise KzgError("field element not canonical")
    return x


def bls_field_to_bytes(x: int) -> bytes:
    return int.to_bytes(x, BYTES_PER_FIELD_ELEMENT, KZG_ENDIANNESS)


def hash_to_bls_field(data: bytes) -> int:
    return int.from_bytes(hashlib.sha256(data).digest(), KZG_ENDIANNESS) % BLS_MODULUS


def blob_to_polynomial(blob: bytes, width: int = FIELD_ELEMENTS_PER_BLOB) -> List[int]:
    if len(blob) != width * BYTES_PER_FIELD_ELEMENT:
        raise KzgError(f"blob must be {width * BYTES_PER_FIELD_ELEMENT} bytes")
    return [
        bytes_to_bls_field(blob[i * 32 : (i + 1) * 32]) for i in range(width)
    ]


def _bytes_to_g1(b: bytes) -> g1.Affine:
    """48-byte compressed G1 → int affine, with curve + subgroup checks
    (c-kzg ``validate_kzg_g1``)."""
    try:
        pt = serde.g1_decompress(b)
    except serde.DecodeError as e:
        raise KzgError(f"bad G1 encoding: {e}") from e
    if pt is None:
        return None
    if not curve.in_g1(pt):
        raise KzgError("point not in G1 subgroup")
    return (pt[0].n, pt[1].n)


def _g1_to_bytes(pt: g1.Affine) -> bytes:
    if pt is None:
        return serde.g1_compress(None)
    return serde.g1_compress((Fq(pt[0]), Fq(pt[1])))


def _g1_to_curve_point(pt: g1.Affine):
    if pt is None:
        return None
    return (Fq(pt[0]), Fq(pt[1]))


# ---------------------------------------------------------------------------
# Trusted setup
# ---------------------------------------------------------------------------


@dataclass
class TrustedSetup:
    """Lagrange-form G1 points + monomial G2 points (``[1]G2, [tau]G2, ...``).

    Reference: ``crypto/kzg/src/trusted_setup.rs`` (JSON loader feeding
    ``c_kzg::KzgSettings``)."""

    g1_lagrange: List[g1.Affine]
    g2_monomial: List[curve.Point]  # Fq2-based points
    width: int

    @classmethod
    def from_json(cls, text: str, validate: bool = True) -> "TrustedSetup":
        obj = json.loads(text)
        # Both historical key spellings are in circulation.
        g1_key = "g1_lagrange" if "g1_lagrange" in obj else "setup_G1_lagrange"
        g2_key = "g2_monomial" if "g2_monomial" in obj else "setup_G2"
        g1_pts = []
        for s in obj[g1_key]:
            raw = bytes.fromhex(s[2:] if s.startswith("0x") else s)
            g1_pts.append(_bytes_to_g1(raw) if validate else _unchecked_g1(raw))
        g2_pts = []
        for s in obj[g2_key]:
            raw = bytes.fromhex(s[2:] if s.startswith("0x") else s)
            try:
                pt = serde.g2_decompress(raw)
            except serde.DecodeError as e:
                raise KzgError(f"bad G2 encoding in trusted setup: {e}") from e
            if validate and not curve.in_g2(pt):
                raise KzgError("G2 setup point not in subgroup")
            g2_pts.append(pt)
        # The ceremony file stores Lagrange points in NATURAL root order;
        # evaluation-form math here uses the bit-reversed ordering, so the
        # loader applies the permutation exactly like c-kzg's
        # load_trusted_setup (caught by the vendored-official-setup KAT:
        # proofs verified under the dev setup but not the real file).
        g1_pts = bit_reversal_permutation(g1_pts)
        return cls(g1_lagrange=g1_pts, g2_monomial=g2_pts, width=len(g1_pts))

    @classmethod
    def insecure_dev_setup(
        cls, width: int = FIELD_ELEMENTS_PER_BLOB, secret: int = 1337
    ) -> "TrustedSetup":
        """Derive a setup from a known ``tau`` — test/bench only.

        Lagrange point i is ``[L_i(tau)]G1`` with
        ``L_i(x) = w_i (x^n - 1) / (n (x - w_i))`` over the bit-reversed root
        ordering, computed in the scalar field (no per-point MSM needed when
        tau is known)."""
        tau = secret % BLS_MODULUS
        roots = roots_of_unity_brp(width)
        zn = (pow(tau, width, BLS_MODULUS) - 1) % BLS_MODULUS
        denoms = _batch_inv([width * (tau - w) % BLS_MODULUS for w in roots])
        g1_pts = [
            g1.scalar_mul(G1_GEN, w * zn % BLS_MODULUS * d % BLS_MODULUS)
            for w, d in zip(roots, denoms)
        ]
        g2_pts = [curve.G2, curve.mul(curve.G2, tau)]
        return cls(g1_lagrange=g1_pts, g2_monomial=g2_pts, width=width)


def _unchecked_g1(raw: bytes) -> g1.Affine:
    try:
        pt = serde.g1_decompress(raw)
    except serde.DecodeError as e:
        raise KzgError(f"bad G1 encoding: {e}") from e
    return None if pt is None else (pt[0].n, pt[1].n)


# ---------------------------------------------------------------------------
# The engine
# ---------------------------------------------------------------------------


class Kzg:
    """The reference's ``Kzg`` wrapper (``crypto/kzg/src/lib.rs:32``)."""

    def __init__(self, setup: TrustedSetup, device: bool = False):
        """``device=True`` routes batch verification's MSMs + 2-pairing
        through the fused TPU program (``ops/kzg_device.py``) — the
        reference's c-kzg hot path re-sited onto the accelerator.  The host
        path stays the golden model and the fallback."""
        self.setup = setup
        self.width = setup.width
        self.device = device
        self.roots_brp = roots_of_unity_brp(self.width)
        self._root_index = {w: i for i, w in enumerate(self.roots_brp)}

    # -------------------------------------------------------------- commit

    def blob_to_kzg_commitment(self, blob: bytes) -> bytes:
        poly = blob_to_polynomial(blob, self.width)
        return _g1_to_bytes(g1.msm(self.setup.g1_lagrange, poly))

    # ------------------------------------------------------------ evaluate

    def evaluate_polynomial_in_evaluation_form(self, poly: Sequence[int], z: int) -> int:
        """Barycentric evaluation at an arbitrary point (spec
        ``evaluate_polynomial_in_evaluation_form``)."""
        width = self.width
        idx = self._root_index.get(z)
        if idx is not None:
            return poly[idx]
        invs = _batch_inv([(z - w) % BLS_MODULUS for w in self.roots_brp])
        acc = 0
        for p, w, inv_zw in zip(poly, self.roots_brp, invs):
            acc += p * w % BLS_MODULUS * inv_zw
        acc %= BLS_MODULUS
        zn_minus_1 = (pow(z, width, BLS_MODULUS) - 1) % BLS_MODULUS
        return acc * zn_minus_1 % BLS_MODULUS * _inv(width) % BLS_MODULUS

    # --------------------------------------------------------------- prove

    def _compute_kzg_proof_impl(self, poly: Sequence[int], z: int) -> Tuple[bytes, int]:
        y = self.evaluate_polynomial_in_evaluation_form(poly, z)
        shifted = [(p - y) % BLS_MODULUS for p in poly]
        quotient = [0] * self.width
        m = self._root_index.get(z)
        if m is None:
            invs = _batch_inv([(w - z) % BLS_MODULUS for w in self.roots_brp])
            for i in range(self.width):
                quotient[i] = shifted[i] * invs[i] % BLS_MODULUS
        else:
            # z is the m-th root: quotient at m via the in-domain formula
            # q_m = sum_{i != m} f_i w_i / (z (z - w_i)); elsewhere
            # q_i = f_i / (w_i - z) = -f_i * (z - w_i)^-1.
            zinv = _inv(z)
            invs = _batch_inv(
                [
                    (z - w) % BLS_MODULUS if i != m else 1
                    for i, w in enumerate(self.roots_brp)
                ]
            )
            qm = 0
            for i, w in enumerate(self.roots_brp):
                if i == m:
                    continue
                quotient[i] = -shifted[i] * invs[i] % BLS_MODULUS
                qm += shifted[i] * w % BLS_MODULUS * invs[i] % BLS_MODULUS * zinv
            quotient[m] = qm % BLS_MODULUS
        proof = _g1_to_bytes(g1.msm(self.setup.g1_lagrange, quotient))
        return proof, y

    def compute_kzg_proof(self, blob: bytes, z_bytes: bytes) -> Tuple[bytes, bytes]:
        poly = blob_to_polynomial(blob, self.width)
        proof, y = self._compute_kzg_proof_impl(poly, bytes_to_bls_field(z_bytes))
        return proof, bls_field_to_bytes(y)

    def compute_blob_kzg_proof(self, blob: bytes, commitment: bytes) -> bytes:
        _bytes_to_g1(commitment)  # validate
        poly = blob_to_polynomial(blob, self.width)
        challenge = self.compute_challenge(blob, commitment)
        proof, _ = self._compute_kzg_proof_impl(poly, challenge)
        return proof

    # ------------------------------------------------------------- verify

    def verify_kzg_proof(
        self, commitment: bytes, z_bytes: bytes, y_bytes: bytes, proof: bytes
    ) -> bool:
        """Point-evaluation verify (the EIP-4844 precompile semantics;
        reference ``crypto/kzg/src/lib.rs:128-144``)."""
        return self._verify_kzg_proof_impl(
            _bytes_to_g1(commitment),
            bytes_to_bls_field(z_bytes),
            bytes_to_bls_field(y_bytes),
            _bytes_to_g1(proof),
        )

    def _verify_kzg_proof_impl(
        self, commitment: g1.Affine, z: int, y: int, proof: g1.Affine
    ) -> bool:
        # e(C - [y]G1, -G2) * e(proof, [tau]G2 - [z]G2) == 1
        g2_tau = self.setup.g2_monomial[1]
        x_minus_z = curve.add(g2_tau, curve.neg(curve.mul(curve.G2, z)))
        p_minus_y = g1.add(commitment, g1.neg(g1.scalar_mul(G1_GEN, y)))
        return multi_pairing_is_one(
            [
                (_g1_to_curve_point(p_minus_y), curve.neg(curve.G2)),
                (_g1_to_curve_point(proof), x_minus_z),
            ]
        )

    def compute_challenge(self, blob: bytes, commitment: bytes) -> int:
        degree_poly = int.to_bytes(self.width, 16, KZG_ENDIANNESS)
        data = FIAT_SHAMIR_PROTOCOL_DOMAIN + degree_poly + blob + commitment
        return hash_to_bls_field(data)

    def verify_blob_kzg_proof(self, blob: bytes, commitment: bytes, proof: bytes) -> bool:
        c_pt = _bytes_to_g1(commitment)
        p_pt = _bytes_to_g1(proof)
        poly = blob_to_polynomial(blob, self.width)
        challenge = self.compute_challenge(blob, commitment)
        y = self.evaluate_polynomial_in_evaluation_form(poly, challenge)
        return self._verify_kzg_proof_impl(c_pt, challenge, y, p_pt)

    def verify_blob_kzg_proof_batch(
        self, blobs: Sequence[bytes], commitments: Sequence[bytes], proofs: Sequence[bytes]
    ) -> bool:
        """Batch verify: one random linear combination, one 2-pairing check
        (reference hot path ``crypto/kzg/src/lib.rs:81-107`` →
        ``c_kzg::KzgProof::verify_blob_kzg_proof_batch``)."""
        if not (len(blobs) == len(commitments) == len(proofs)):
            raise KzgError("length mismatch")
        if len(blobs) == 0:
            return True
        if len(blobs) == 1:
            return self.verify_blob_kzg_proof(blobs[0], commitments[0], proofs[0])
        c_pts = [_bytes_to_g1(c) for c in commitments]
        p_pts = [_bytes_to_g1(p) for p in proofs]
        zs, ys = [], []
        for blob, commitment in zip(blobs, commitments):
            poly = blob_to_polynomial(blob, self.width)
            challenge = self.compute_challenge(blob, commitment)
            zs.append(challenge)
            ys.append(self.evaluate_polynomial_in_evaluation_form(poly, challenge))
        return self._verify_kzg_proof_batch(c_pts, commitments, zs, ys, p_pts, proofs)

    def _compute_r_powers(
        self,
        commitments_bytes: Sequence[bytes],
        zs: Sequence[int],
        ys: Sequence[int],
        proofs_bytes: Sequence[bytes],
    ) -> List[int]:
        n = len(commitments_bytes)
        data = RANDOM_CHALLENGE_KZG_BATCH_DOMAIN
        data += int.to_bytes(self.width, 8, KZG_ENDIANNESS)
        data += int.to_bytes(n, 8, KZG_ENDIANNESS)
        for c, z, y, p in zip(commitments_bytes, zs, ys, proofs_bytes):
            data += c + bls_field_to_bytes(z) + bls_field_to_bytes(y) + p
        r = hash_to_bls_field(data)
        powers = [1] * n
        for i in range(1, n):
            powers[i] = powers[i - 1] * r % BLS_MODULUS
        return powers

    def _verify_kzg_proof_batch(
        self, c_pts, commitments_bytes, zs, ys, p_pts, proofs_bytes
    ) -> bool:
        r_powers = self._compute_r_powers(commitments_bytes, zs, ys, proofs_bytes)
        if self.device:
            from ...ops.kzg_device import verify_kzg_proof_batch_device

            # Supervised: the host MSM path below is the golden-model
            # fallback a hung/failing device (or an OPEN kzg_batch breaker)
            # resolves through — blob DA degrades to slow-but-correct.
            return verify_kzg_proof_batch_device(
                [_g1_to_curve_point(c) for c in c_pts],
                [_g1_to_curve_point(p) for p in p_pts],
                r_powers, zs, ys, self.setup.g2_monomial[1],
                host_fn=lambda: self._verify_kzg_proof_batch_host(
                    c_pts, zs, ys, p_pts, r_powers
                ),
            )
        return self._verify_kzg_proof_batch_host(c_pts, zs, ys, p_pts, r_powers)

    def _verify_kzg_proof_batch_host(
        self, c_pts, zs, ys, p_pts, r_powers
    ) -> bool:
        proof_lincomb = g1.msm(p_pts, r_powers)
        proof_z_lincomb = g1.msm(
            p_pts, [r * z % BLS_MODULUS for r, z in zip(r_powers, zs)]
        )
        # sum r_i (C_i - [y_i]G1) = MSM(C, r) - [sum r_i y_i]G1: fold the
        # y-terms into one scalar so there's a single G1_GEN multiplication.
        c_lincomb = g1.msm(c_pts, r_powers)
        ry = sum(r * y % BLS_MODULUS for r, y in zip(r_powers, ys)) % BLS_MODULUS
        c_minus_y_lincomb = g1.add(c_lincomb, g1.neg(g1.scalar_mul(G1_GEN, ry)))
        rhs = g1.add(c_minus_y_lincomb, proof_z_lincomb)
        g2_tau = self.setup.g2_monomial[1]
        return multi_pairing_is_one(
            [
                (_g1_to_curve_point(proof_lincomb), g2_tau),
                (_g1_to_curve_point(g1.neg(rhs)), curve.G2),
            ]
        )
