"""Fast host-side G1 arithmetic over plain integers (Jacobian coordinates)
plus a Pippenger multi-scalar multiplication.

The KZG hot operations (`blob_to_kzg_commitment`, proof computation, batch
lin-combs) are G1 MSMs over the 4096-point Lagrange setup.  The generic
``crypto/bls/curve.py`` path works on wrapped field elements and is an order
of magnitude slower; this module is the host baseline the device MSM is
measured against (role of blst's Pippenger in the reference,
``crypto/bls/src/impls/blst.rs``).

Points are affine ``(x, y)`` int tuples or ``None`` for infinity at the API
boundary; Jacobian ``(X, Y, Z)`` internally with ``Z == 0`` for infinity.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

from ..bls.params import P, R

Affine = Optional[Tuple[int, int]]
Jac = Tuple[int, int, int]

INF: Jac = (1, 1, 0)


def to_jac(pt: Affine) -> Jac:
    if pt is None:
        return INF
    return (pt[0], pt[1], 1)


def to_affine(p: Jac) -> Affine:
    X, Y, Z = p
    if Z == 0:
        return None
    zinv = pow(Z, P - 2, P)
    z2 = zinv * zinv % P
    return (X * z2 % P, Y * z2 * zinv % P)


def jac_dbl(p: Jac) -> Jac:
    X1, Y1, Z1 = p
    if Z1 == 0 or Y1 == 0:
        return INF
    A = X1 * X1 % P
    B = Y1 * Y1 % P
    C = B * B % P
    D = 2 * ((X1 + B) * (X1 + B) - A - C) % P
    E = 3 * A % P
    F = E * E % P
    X3 = (F - 2 * D) % P
    Y3 = (E * (D - X3) - 8 * C) % P
    Z3 = 2 * Y1 * Z1 % P
    return (X3, Y3, Z3)


def jac_add(p: Jac, q: Jac) -> Jac:
    X1, Y1, Z1 = p
    X2, Y2, Z2 = q
    if Z1 == 0:
        return q
    if Z2 == 0:
        return p
    Z1Z1 = Z1 * Z1 % P
    Z2Z2 = Z2 * Z2 % P
    U1 = X1 * Z2Z2 % P
    U2 = X2 * Z1Z1 % P
    S1 = Y1 * Z2 * Z2Z2 % P
    S2 = Y2 * Z1 * Z1Z1 % P
    if U1 == U2:
        if S1 != S2:
            return INF
        return jac_dbl(p)
    H = (U2 - U1) % P
    I = 4 * H * H % P
    J = H * I % P
    r = 2 * (S2 - S1) % P
    V = U1 * I % P
    X3 = (r * r - J - 2 * V) % P
    Y3 = (r * (V - X3) - 2 * S1 * J) % P
    Z3 = 2 * H * Z1 * Z2 % P
    return (X3, Y3, Z3)


def jac_add_affine(p: Jac, q: Affine) -> Jac:
    """Mixed addition (q affine, Z2 == 1)."""
    if q is None:
        return p
    X1, Y1, Z1 = p
    if Z1 == 0:
        return (q[0], q[1], 1)
    X2, Y2 = q
    Z1Z1 = Z1 * Z1 % P
    U2 = X2 * Z1Z1 % P
    S2 = Y2 * Z1 * Z1Z1 % P
    if X1 == U2:
        if Y1 != S2:
            return INF
        return jac_dbl(p)
    H = (U2 - X1) % P
    HH = H * H % P
    I = 4 * HH % P
    J = H * I % P
    r = 2 * (S2 - Y1) % P
    V = X1 * I % P
    X3 = (r * r - J - 2 * V) % P
    Y3 = (r * (V - X3) - 2 * Y1 * J) % P
    Z3 = (Z1 + H) * (Z1 + H) % P
    Z3 = (Z3 - Z1Z1 - HH) % P
    return (X3, Y3, Z3)


def jac_neg(p: Jac) -> Jac:
    X, Y, Z = p
    return (X, (P - Y) % P, Z)


def scalar_mul(pt: Affine, k: int) -> Affine:
    k %= R
    if pt is None or k == 0:
        return None
    acc = INF
    base = to_jac(pt)
    while k:
        if k & 1:
            acc = jac_add(acc, base)
        base = jac_dbl(base)
        k >>= 1
    return to_affine(acc)


def msm(points: Sequence[Affine], scalars: Sequence[int], window: int = 8) -> Affine:
    """Pippenger bucket MSM: ``sum_i scalars[i] * points[i]``."""
    n = len(points)
    if n != len(scalars):
        raise ValueError(f"msm: {n} points vs {len(scalars)} scalars")
    ks = [s % R for s in scalars]
    if n == 0:
        return None
    if n == 1:
        return scalar_mul(points[0], ks[0])
    nbits = R.bit_length()
    nwin = (nbits + window - 1) // window
    acc = INF
    mask = (1 << window) - 1
    for w in range(nwin - 1, -1, -1):
        if acc[2] != 0:
            for _ in range(window):
                acc = jac_dbl(acc)
        buckets: List[Jac] = [INF] * (mask + 1)
        shift = w * window
        for pt, k in zip(points, ks):
            if pt is None:
                continue
            d = (k >> shift) & mask
            if d:
                buckets[d] = jac_add_affine(buckets[d], pt)
        # running-sum trick: sum_d d * bucket[d]
        run = INF
        win_sum = INF
        for d in range(mask, 0, -1):
            run = jac_add(run, buckets[d])
            win_sum = jac_add(win_sum, run)
        acc = jac_add(acc, win_sum)
    return to_affine(acc)


def add(p: Affine, q: Affine) -> Affine:
    return to_affine(jac_add(to_jac(p), to_jac(q)))


def neg(p: Affine) -> Affine:
    if p is None:
        return None
    return (p[0], (P - p[1]) % P)


def is_on_curve(p: Affine) -> bool:
    if p is None:
        return True
    x, y = p
    return (y * y - (x * x * x + 4)) % P == 0
