"""KZG commitments for blob sidecars (reference: ``crypto/kzg``)."""

from .kzg import (
    BLS_MODULUS,
    BYTES_PER_FIELD_ELEMENT,
    FIELD_ELEMENTS_PER_BLOB,
    Kzg,
    KzgError,
    TrustedSetup,
    bit_reversal_permutation,
    blob_to_polynomial,
    bytes_to_bls_field,
    bls_field_to_bytes,
    compute_roots_of_unity,
    hash_to_bls_field,
    roots_of_unity_brp,
)

__all__ = [
    "BLS_MODULUS",
    "BYTES_PER_FIELD_ELEMENT",
    "FIELD_ELEMENTS_PER_BLOB",
    "Kzg",
    "KzgError",
    "TrustedSetup",
    "bit_reversal_permutation",
    "blob_to_polynomial",
    "bytes_to_bls_field",
    "bls_field_to_bytes",
    "compute_roots_of_unity",
    "hash_to_bls_field",
    "roots_of_unity_brp",
]
