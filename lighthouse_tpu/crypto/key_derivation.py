"""EIP-2333 BLS key derivation (HKDF tree) + EIP-2334 paths.

Re-implements the capability of the reference's ``crypto/eth2_key_derivation``
(``src/derived_key.rs``: ``DerivedKey::from_seed`` / ``child``) from the
public EIP-2333 specification: a Lamport-keyed HKDF derivation tree over the
BLS12-381 scalar field.  Host-side code — key derivation is cold-path setup,
not device work.

Checked against the official EIP-2333 test vectors (tests/vectors/eip2333.json,
the same vectors the reference pins in tests/eip2333_vectors.rs).
"""

from __future__ import annotations

import hashlib
import hmac
from typing import List

from .bls.params import R  # BLS12-381 scalar field order

_SALT0 = b"BLS-SIG-KEYGEN-SALT-"
_LAMPORT_CHUNKS = 255


def _hkdf_extract(salt: bytes, ikm: bytes) -> bytes:
    return hmac.new(salt, ikm, hashlib.sha256).digest()


def _hkdf_expand(prk: bytes, info: bytes, length: int) -> bytes:
    out = b""
    t = b""
    counter = 1
    while len(out) < length:
        t = hmac.new(prk, t + info + bytes([counter]), hashlib.sha256).digest()
        out += t
        counter += 1
    return out[:length]


def hkdf_mod_r(ikm: bytes, key_info: bytes = b"") -> int:
    """RFC-draft KeyGen: map IKM to a nonzero scalar mod r."""
    salt = _SALT0
    sk = 0
    while sk == 0:
        salt = hashlib.sha256(salt).digest()
        prk = _hkdf_extract(salt, ikm + b"\x00")
        okm = _hkdf_expand(prk, key_info + (48).to_bytes(2, "big"), 48)
        sk = int.from_bytes(okm, "big") % R
    return sk


def _ikm_to_lamport_sk(ikm: bytes, salt: bytes) -> List[bytes]:
    prk = _hkdf_extract(salt, ikm)
    okm = _hkdf_expand(prk, b"", 32 * _LAMPORT_CHUNKS)
    return [okm[i * 32 : (i + 1) * 32] for i in range(_LAMPORT_CHUNKS)]


def _parent_sk_to_lamport_pk(parent_sk: int, index: int) -> bytes:
    salt = index.to_bytes(4, "big")
    ikm = parent_sk.to_bytes(32, "big")
    lamport_0 = _ikm_to_lamport_sk(ikm, salt)
    not_ikm = bytes(b ^ 0xFF for b in ikm)
    lamport_1 = _ikm_to_lamport_sk(not_ikm, salt)
    pk = b"".join(hashlib.sha256(x).digest() for x in lamport_0 + lamport_1)
    return hashlib.sha256(pk).digest()


def derive_master_sk(seed: bytes) -> int:
    if len(seed) < 32:
        raise ValueError("seed must be >= 32 bytes (EIP-2333)")
    return hkdf_mod_r(seed)


def derive_child_sk(parent_sk: int, index: int) -> int:
    if not 0 <= index < 2**32:
        raise ValueError("child index out of range")
    return hkdf_mod_r(_parent_sk_to_lamport_pk(parent_sk, index))


def derive_path(seed: bytes, path: str) -> int:
    """EIP-2334 path derivation, e.g. ``m/12381/3600/0/0/0`` (validator
    signing key i = m/12381/3600/i/0/0, withdrawal key = m/12381/3600/i/0)."""
    parts = path.strip().split("/")
    if not parts or parts[0] != "m":
        raise ValueError(f"bad derivation path {path!r}")
    sk = derive_master_sk(seed)
    for p in parts[1:]:
        if not p.isdigit():
            raise ValueError(f"bad path component {p!r}")
        sk = derive_child_sk(sk, int(p))
    return sk


def mnemonic_to_seed(mnemonic: str, passphrase: str = "") -> bytes:
    """BIP-39 seed derivation (PBKDF2-HMAC-SHA512, 2048 rounds)."""
    import unicodedata

    norm = unicodedata.normalize("NFKD", mnemonic)
    salt = unicodedata.normalize("NFKD", "mnemonic" + passphrase)
    return hashlib.pbkdf2_hmac("sha512", norm.encode(), salt.encode(), 2048, 64)
