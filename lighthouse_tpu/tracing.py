"""Span tracing for the block-import → device-batch pipeline.

The metrics registry (``lighthouse_tpu/metrics``) answers "how slow is this
stage on average"; this module answers "where did THIS block's 400 ms go".
Every instrumentation point opens a ``span(name, hist=...)`` that feeds the
stage's existing histogram on close AND records a node in a per-trace tree —
one seam, two sinks, so aggregates and traces can never disagree about what
was measured.

Model (a deliberately small subset of OpenTelemetry):

- A :class:`Span` has a name, perf-counter start/end, a field dict, and
  children.  The active span propagates through a ``contextvars.ContextVar``,
  so nesting is automatic within a thread.
- A span opened with no active parent starts a new :class:`Trace`.  When that
  root closes, the completed trace lands in the bounded :data:`TRACES` ring,
  keyed by root-span name (one sub-ring per root, so chatty roots cannot
  evict rare ones) and filterable by the root's ``slot`` field.
- Cross-thread hops (the scheduler's enqueue→worker seam) carry the parent
  span explicitly: the sender stamps it on the ``WorkEvent``, the worker
  re-attaches with :func:`attach`/:func:`detach`.  ``time.perf_counter`` is
  CLOCK_MONOTONIC — comparable across threads — so enqueue→drain queue-wait
  spans are exact.
- Trees are bounded (:data:`MAX_SPANS_PER_TRACE`); past the cap spans are
  still timed (their histograms must not go dark) but dropped from the tree,
  counted in ``Trace.dropped``.

A parent may close before a late child does (a delayed re-processed event
whose originating request already returned).  The child still attaches — the
tree is serialized at read time — it just renders past its parent's end.

HTTP surface (``http_api/server.py``): ``/lighthouse/traces`` lists recent
trace summaries; ``/lighthouse/traces/{trace_id}`` returns the full tree,
``?format=chrome`` as Chrome trace-event JSON loadable in Perfetto.
"""

from __future__ import annotations

import contextvars
import itertools
import os
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Any, Dict, List, Optional

MAX_SPANS_PER_TRACE = 512
TRACES_PER_ROOT = 128

_current: "contextvars.ContextVar[Optional[Span]]" = contextvars.ContextVar(
    "lighthouse_tpu_current_span", default=None
)
_seq = itertools.count(1)


def _new_trace_id() -> str:
    return f"{next(_seq):08x}{os.urandom(4).hex()}"


class Span:
    __slots__ = (
        "name", "fields", "trace", "parent", "children",
        "start_pc", "end_pc", "start_wall", "tid",
    )

    def __init__(self, name: str, trace: "Trace", parent: Optional["Span"],
                 fields: Dict[str, Any], start_pc: Optional[float] = None):
        self.name = name
        self.trace = trace
        self.parent = parent
        self.fields = fields
        self.children: List[Span] = []
        self.start_pc = time.perf_counter() if start_pc is None else start_pc
        self.end_pc: Optional[float] = None
        self.start_wall = time.time()
        self.tid = threading.get_ident()

    @property
    def duration(self) -> float:
        end = self.end_pc if self.end_pc is not None else time.perf_counter()
        return max(0.0, end - self.start_pc)

    def close(self, end_pc: Optional[float] = None) -> None:
        if self.end_pc is None:
            self.end_pc = time.perf_counter() if end_pc is None else end_pc


class Trace:
    """One bounded span tree; completed when its root span closes."""

    __slots__ = ("trace_id", "root", "n_spans", "dropped", "_lock")

    def __init__(self, root_name: str, fields: Dict[str, Any]):
        self.trace_id = _new_trace_id()
        self._lock = threading.Lock()
        self.n_spans = 1
        self.dropped = 0
        self.root = Span(root_name, self, None, fields)

    def new_child(self, parent: Span, name: str, fields: Dict[str, Any],
                  start_pc: Optional[float] = None) -> Span:
        """A child span under ``parent``.  Past the per-trace cap the span is
        created detached (timed, histogram-fed) but not added to the tree."""
        sp = Span(name, self, parent, fields, start_pc=start_pc)
        with self._lock:
            if self.n_spans >= MAX_SPANS_PER_TRACE:
                self.dropped += 1
                return sp
            self.n_spans += 1
        parent.children.append(sp)
        return sp


class TraceRing:
    """Completed traces, keyed by root-span name with per-root bounds."""

    def __init__(self, per_root: int = TRACES_PER_ROOT):
        self.per_root = per_root
        self._by_root: Dict[str, deque] = {}
        self._by_id: Dict[str, Trace] = {}
        self._lock = threading.Lock()

    def push(self, trace: Trace) -> None:
        with self._lock:
            dq = self._by_root.setdefault(trace.root.name, deque())
            if len(dq) >= self.per_root:
                evicted = dq.popleft()
                self._by_id.pop(evicted.trace_id, None)
            dq.append(trace)
            self._by_id[trace.trace_id] = trace

    def get(self, trace_id: str) -> Optional[Trace]:
        with self._lock:
            return self._by_id.get(trace_id)

    def recent(self, limit: int = 64, root: Optional[str] = None,
               slot: Optional[int] = None) -> List[Trace]:
        """Newest-first completed traces, optionally filtered by root name
        and/or the root span's ``slot`` field."""
        with self._lock:
            if root is not None:
                traces = list(self._by_root.get(root, ()))
            else:
                traces = [t for dq in self._by_root.values() for t in dq]
        traces.sort(key=lambda t: t.root.start_wall, reverse=True)
        if slot is not None:
            traces = [t for t in traces if t.root.fields.get("slot") == slot]
        return traces[:limit]

    def clear(self) -> None:
        with self._lock:
            self._by_root.clear()
            self._by_id.clear()


TRACES = TraceRing()


# ------------------------------------------------------------------ context


def current_span() -> Optional[Span]:
    return _current.get()


def attach(parent: Optional[Span]):
    """Adopt ``parent`` as the active span on THIS thread (the worker side
    of a cross-thread hop).  Returns a token for :func:`detach`."""
    return _current.set(parent)


def detach(token) -> None:
    _current.reset(token)


def annotate(**fields) -> None:
    """Merge fields into the active span (no-op outside any span)."""
    sp = _current.get()
    if sp is not None:
        sp.fields.update(fields)


def annotate_trace(**fields) -> None:
    """Merge fields into the active TRACE's root span — how an inner stage
    keys the whole trace (a block import stamps its slot on the enclosing
    work/http root so ``TRACES.recent(slot=...)`` finds it)."""
    sp = _current.get()
    if sp is not None:
        sp.trace.root.fields.update(fields)


@contextmanager
def span(name: str, hist=None, hist_labels: Optional[dict] = None, **fields):
    """Record a span; on close, observe its duration into ``hist`` too.

    With no active parent this starts a new trace, completed (and pushed to
    :data:`TRACES`) when the span exits.
    """
    parent = _current.get()
    if parent is None:
        trace = Trace(name, fields)
        sp = trace.root
    else:
        trace = parent.trace
        sp = trace.new_child(parent, name, fields)
    token = _current.set(sp)
    try:
        yield sp
    finally:
        _current.reset(token)
        sp.close()
        if hist is not None:
            hist.observe(sp.duration, **(hist_labels or {}))
        if sp.parent is None:
            TRACES.push(trace)


@contextmanager
def resume_remote(ctx: Optional[dict], name: str, **fields):
    """Resume an envelope-propagated trace context from ANOTHER node as a
    new local root trace (the receiving half of cross-node propagation).

    The remote linkage rides in fields — ``remote_trace_id`` /
    ``remote_node`` / ``remote_lamport`` — rather than by reusing the
    origin's trace id: :data:`TRACES` is process-global across simulated
    nodes, so id reuse would splice two nodes' spans into one tree.  The
    fleet artifact joins proposal and import trees on
    ``remote_trace_id == <proposal trace_id>``.  Always roots a fresh
    trace: any span active on this worker thread belongs to LOCAL work,
    not to the remote cause."""
    ctx = ctx or {}
    token = _current.set(None)
    try:
        with span(name,
                  remote_trace_id=ctx.get("trace_id"),
                  remote_node=ctx.get("node"),
                  remote_lamport=ctx.get("lamport"),
                  **fields) as sp:
            yield sp
    finally:
        _current.reset(token)


def span_event(name: str, **fields) -> Optional[Span]:
    """A zero-duration marker child on the active span — for point events
    that explain a trace without timing anything (a response-cache
    invalidation inside ``head_recompute``, a shed decision inside an HTTP
    span).  No-op (returns None) outside any trace."""
    now = time.perf_counter()
    return record_span(name, start_pc=now, end_pc=now, **fields)


def record_span(name: str, start_pc: float, end_pc: Optional[float] = None,
                hist=None, hist_labels: Optional[dict] = None,
                **fields) -> Optional[Span]:
    """Add an already-measured interval as a closed child of the active span
    (the queue-wait case: the start happened on the sending thread).  Feeds
    ``hist`` regardless of whether a trace is active."""
    end = time.perf_counter() if end_pc is None else end_pc
    parent = _current.get()
    sp = None
    if parent is not None:
        sp = parent.trace.new_child(parent, name, fields, start_pc=start_pc)
        sp.close(end)
    if hist is not None:
        hist.observe(max(0.0, end - start_pc), **(hist_labels or {}))
    return sp


# -------------------------------------------------------------- serializers


def span_to_dict(sp: Span, root_start_pc: float) -> dict:
    return {
        "name": sp.name,
        "start_offset_s": round(max(0.0, sp.start_pc - root_start_pc), 6),
        "duration_s": round(sp.duration, 6),
        "fields": dict(sp.fields),
        "children": [span_to_dict(c, root_start_pc) for c in sp.children],
    }


def trace_to_dict(trace: Trace) -> dict:
    root = trace.root
    return {
        "trace_id": trace.trace_id,
        "started_at_ms": int(root.start_wall * 1000),
        "duration_s": round(root.duration, 6),
        "n_spans": trace.n_spans,
        "dropped_spans": trace.dropped,
        "root": span_to_dict(root, root.start_pc),
    }


def trace_summary(trace: Trace) -> dict:
    root = trace.root
    out = {
        "trace_id": trace.trace_id,
        "root": root.name,
        "started_at_ms": int(root.start_wall * 1000),
        "duration_s": round(root.duration, 6),
        "n_spans": trace.n_spans,
    }
    if "slot" in root.fields:
        out["slot"] = root.fields["slot"]
    return out


def trace_to_chrome(trace: Trace) -> dict:
    """Chrome trace-event JSON (``ph: "X"`` complete events, microsecond
    timestamps relative to the trace root) — loadable in Perfetto /
    chrome://tracing."""
    root = trace.root
    events: List[dict] = []

    def walk(sp: Span) -> None:
        events.append({
            "name": sp.name,
            "cat": "lighthouse_tpu",
            "ph": "X",
            "ts": round((sp.start_pc - root.start_pc) * 1e6, 1),
            "dur": round(sp.duration * 1e6, 1),
            "pid": os.getpid(),
            "tid": sp.tid,
            "args": {k: str(v) for k, v in sp.fields.items()},
        })
        for c in sp.children:
            walk(c)

    walk(root)
    return {"displayTimeUnit": "ms", "traceEvents": events}
