"""Supervised device execution: watchdog, split-batch retry, circuit breaker.

Every hot-path signature/hash/epoch batch funnels through three jitted
device entry points (``ops/verify.py`` bls_verify, ``ops/sha256_device.py``,
``ops/epoch_device.py``).  Before this module, a device OOM, a failed cold
compile, or a hung dispatch propagated as an unhandled exception — or an
indefinite stall — straight into block import and the scheduler.  The
reference survives exactly this failure class at its execution-layer
boundary (``execution_layer/src/engines.rs`` upcheck/cooldown supervision);
this is the same discipline applied to the device boundary:

- **dispatch watchdog** — each device call runs on a per-op worker thread
  (which is where ``block_until_ready`` blocks); the caller waits with a
  per-op deadline.  A hung device strands the *worker*, never the caller:
  on expiry the worker is abandoned (a fresh one is spawned for the next
  batch) and the batch resolves through the host path.
- **split-batch retry** — one retry on transient device errors, with the
  batch split in half (a poisoned set or an OOM at a big bucket shape often
  passes at half size).  Both halves still run under the watchdog.
- **circuit breaker** — per-op CLOSED → OPEN after N consecutive failures
  → HALF_OPEN probe batches after a cooldown → CLOSED.  While OPEN, batches
  route straight to the existing host backends
  (``crypto/bls/backends/host.py``, the numpy epoch/sha paths) without
  touching the device: the chain degrades to slow-but-correct instead of
  crashing.
- **per-device breakers** (``device_mesh.py``) — when the data-parallel
  mesh is active, a dispatch failure is first charged to a *device*; a
  tripped device is removed, the mesh re-shards over the survivors and the
  batch retries there (``device_mesh_reshards_total``/``device_mesh_size``)
  before the op-level ladder above ever engages.  One sick chip costs one
  mesh lane, not the whole op.

Every state transition is exported via ``metrics/``
(``device_breaker_state{op}``, ``device_breaker_transitions_total``),
surfaced on ``GET /lighthouse/device`` (via ``device_telemetry.summary``),
and published as a ``device_breaker`` SSE event on every registered
:class:`chain.events.EventBus` — so an operator watching
``/eth/v1/events?topics=device_breaker`` sees the device degrade and
recover in real time.

The ``w_at_infinity`` host re-verify that used to live inline in
``ops/verify.py`` also routes through :meth:`DeviceSupervisor.run` (the
device path raises :class:`HostFallback`), so there is exactly ONE
host-fallback mechanism and one counter:
``device_batch_host_fallback_total{reason=w_at_infinity|breaker_open|
dispatch_timeout|device_error}``.
"""

from __future__ import annotations

import os
import queue
import threading
import time
import weakref
from typing import Any, Callable, Dict, List, Optional, Tuple

from . import blackbox, metrics, tracing
from .logs import get_logger
from .scheduler.work import RequeueWork
from .timeout_lock import TimeoutLock

log = get_logger("device_supervisor")

STATE_CLOSED = "closed"
STATE_OPEN = "open"
STATE_HALF_OPEN = "half_open"

#: Gauge encoding of the state machine (device_breaker_state{op}).
STATE_CODES = {STATE_CLOSED: 0, STATE_OPEN: 1, STATE_HALF_OPEN: 2}

#: Per-op dispatch deadlines (seconds).  Generous: a first-seen bucket
#: shape pays trace+compile *inside* the dispatch, and the big pairing
#: shapes take tens of seconds to compile.  The watchdog exists to catch a
#: *hung* device, not a slow compile.
DEFAULT_DEADLINES = {
    "bls_verify": 300.0,
    "sha256_pairs": 120.0,
    "tree_hash": 120.0,
    "epoch_deltas": 300.0,
    "epoch_deltas_leak": 300.0,
    # the fused boundary composes deltas + shuffle + proposer into one
    # program — its first-bucket compile is the longest of the epoch ops
    "epoch_boundary": 600.0,
    "epoch_boundary_leak": 600.0,
    "shuffle": 300.0,
    "proposer_select": 300.0,
    "kzg_batch": 300.0,
    # the autotune fq A/B microbench (autotune.measure_fq_backend): small
    # batch, but the first run pays both backends' probe compiles — the
    # deadline guards node startup against a hung device, not a compiler
    "autotune_probe": 120.0,
}
DEFAULT_DEADLINE_S = 300.0

#: Ops whose device kernels compute batch-GLOBAL reductions (the epoch pass
#: sums participation over the whole registry; the kzg program tree-sums
#: its random-linear-combination over the blob axis): the halves of a split
#: are not independent sub-problems, so split-batch retry is forbidden for
#: them no matter what a caller passes — with 4096-scale standard buckets a
#: mis-wired split would silently change the op's semantics, not just its
#: shape.  Failures for these ops go straight to the host fallback.  Must
#: stay in sync with the ``reduces_over_batch`` entries in
#: ``ops/batch_axes.py`` (the sharding contract reads the same property).
NO_SPLIT_OPS = frozenset({
    "epoch_deltas", "epoch_deltas_leak", "kzg_batch",
    # the fused boundary embeds the same registry-wide sums; the shuffle
    # and proposer walks are whole-permutation computations — no half of
    # a swap-or-not network is a smaller swap-or-not network
    "epoch_boundary", "epoch_boundary_leak", "shuffle", "proposer_select",
})


class DispatchTimeout(RequeueWork):
    """A device dispatch exceeded its watchdog deadline.

    Subclasses :class:`scheduler.work.RequeueWork`: if a caller without a
    host fallback lets it escape into a scheduler worker, the work is
    re-enqueued once instead of dropped (the device may have recovered — or
    the breaker opened, routing the retry to the host).
    """

    def __init__(self, op: str, deadline_s: float):
        super().__init__(f"device dispatch for {op!r} exceeded {deadline_s}s deadline")
        self.op = op
        self.deadline_s = deadline_s


class HostFallback(Exception):
    """Raised by a device path that executed fine but disclaims its verdict
    (the W-at-infinity check): the supervisor re-verifies on the host
    WITHOUT counting a breaker failure."""

    def __init__(self, reason: str):
        super().__init__(reason)
        self.reason = reason


class BreakerConfig:
    """Tuning knobs (see ROBUSTNESS.md), overridable via env."""

    def __init__(
        self,
        failure_threshold: int = 3,
        open_cooldown_s: float = 30.0,
        probe_successes: int = 2,
    ):
        self.failure_threshold = max(1, int(failure_threshold))
        self.open_cooldown_s = float(open_cooldown_s)
        self.probe_successes = max(1, int(probe_successes))

    @classmethod
    def from_env(cls) -> "BreakerConfig":
        return cls(
            failure_threshold=int(
                os.environ.get("LIGHTHOUSE_TPU_BREAKER_FAILURES", "3")
            ),
            open_cooldown_s=float(
                os.environ.get("LIGHTHOUSE_TPU_BREAKER_COOLDOWN_S", "30")
            ),
            probe_successes=int(
                os.environ.get("LIGHTHOUSE_TPU_BREAKER_PROBES", "2")
            ),
        )


# Injectable cooldown clock (ISSUE 20): a breaker cooldown is control-path
# time — whether a scenario's breaker recovers before the run ends must be
# a property of the run's virtual timeline, not of host load.  The scenario
# runner installs its VirtualClock.now here and restores the default in
# _cleanup; production keeps wall time.
_cooldown_clock: Callable[[], float] = time.monotonic


def set_cooldown_clock(fn: Optional[Callable[[], float]] = None) -> None:
    global _cooldown_clock
    # process-boundary: ok(clock seam: harness-only install, restored in _cleanup)
    _cooldown_clock = fn if fn is not None else time.monotonic


class CircuitBreaker:
    """CLOSED → OPEN → HALF_OPEN → CLOSED, per device op.

    Lock discipline: the :class:`TimeoutLock` guards only the counters and
    state word; transition side effects (metrics, SSE, logs) run after
    release via the collected ``transitions`` list.
    """

    def __init__(self, op: str, config: BreakerConfig):
        self.op = op
        self.config = config
        self._lock = TimeoutLock(f"breaker[{op}]", label="CircuitBreaker._lock")
        self._state = STATE_CLOSED
        self._consecutive_failures = 0
        self._opened_at = 0.0  # monotonic
        self._probe_successes = 0
        self.trips_total = 0       # CLOSED/HALF_OPEN -> OPEN transitions
        self.probes_total = 0      # batches admitted while HALF_OPEN
        self.last_failure: Optional[str] = None
        metrics.DEVICE_BREAKER_STATE.set(STATE_CODES[STATE_CLOSED], op=op)

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def _transition(self, to: str, reason: str,
                    transitions: List[Tuple[str, str, str]]) -> None:
        """Record a state change (lock held); effects are emitted later."""
        transitions.append((self._state, to, reason))
        self._state = to
        if to == STATE_OPEN:
            self.trips_total += 1
            self._opened_at = _cooldown_clock()
            self._probe_successes = 0
        elif to == STATE_CLOSED:
            self._consecutive_failures = 0
            self._probe_successes = 0

    def route(self) -> Tuple[str, List[Tuple[str, str, str]]]:
        """``("device"|"host", transitions)`` for the next batch.  OPEN past
        its cooldown flips to HALF_OPEN and admits a probe."""
        transitions: List[Tuple[str, str, str]] = []
        with self._lock:
            if self._state == STATE_OPEN:
                if _cooldown_clock() - self._opened_at >= self.config.open_cooldown_s:
                    self._transition(STATE_HALF_OPEN, "cooldown_elapsed", transitions)
                else:
                    return "host", transitions
            if self._state == STATE_HALF_OPEN:
                self.probes_total += 1
            return "device", transitions

    def record_success(self) -> List[Tuple[str, str, str]]:
        transitions: List[Tuple[str, str, str]] = []
        with self._lock:
            self._consecutive_failures = 0
            if self._state == STATE_HALF_OPEN:
                self._probe_successes += 1
                if self._probe_successes >= self.config.probe_successes:
                    self._transition(STATE_CLOSED, "probes_passed", transitions)
        return transitions

    def record_failure(self, reason: str) -> List[Tuple[str, str, str]]:
        transitions: List[Tuple[str, str, str]] = []
        with self._lock:
            self.last_failure = reason
            self._consecutive_failures += 1
            if self._state == STATE_HALF_OPEN:
                self._transition(STATE_OPEN, f"probe_failed:{reason}", transitions)
            elif (
                self._state == STATE_CLOSED
                and self._consecutive_failures >= self.config.failure_threshold
            ):
                self._transition(STATE_OPEN, reason, transitions)
        return transitions

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "op": self.op,
                "state": self._state,
                "consecutive_failures": self._consecutive_failures,
                "trips_total": self.trips_total,
                "probes_total": self.probes_total,
                "last_failure": self.last_failure,
                "failure_threshold": self.config.failure_threshold,
                "open_cooldown_s": self.config.open_cooldown_s,
                "probe_successes_required": self.config.probe_successes,
            }


# ---------------------------------------------------------- watchdog worker


class _Job:
    __slots__ = ("fn", "parent_span", "done", "value", "error")

    def __init__(self, fn: Callable[[], Any], parent_span):
        self.fn = fn
        self.parent_span = parent_span
        self.done = threading.Event()
        self.value: Any = None
        self.error: Optional[BaseException] = None


class _OpWorker:
    """One long-lived dispatch thread per op.

    Steady state costs one queue handoff per batch (no thread spawn).  When
    a dispatch hangs past its deadline the supervisor *abandons* this
    worker — the stranded thread parks on ``block_until_ready`` until (if
    ever) the device returns, then exits; the next batch gets a fresh
    worker.  The caller is never the thread that blocks on the device.
    """

    def __init__(self, op: str):
        self.op = op
        self.abandoned = False
        self._q: "queue.SimpleQueue[Optional[_Job]]" = queue.SimpleQueue()
        self._thread = threading.Thread(
            target=self._loop, name=f"device-dispatch-{op}", daemon=True
        )
        self._thread.start()

    def _loop(self) -> None:
        while True:
            job = self._q.get()
            if job is None:
                return
            # Adopt the caller's span so dispatch/wait spans created inside
            # the device fn land in the caller's trace (the same cross-thread
            # seam the scheduler workers use).
            token = tracing.attach(job.parent_span)
            try:
                job.value = job.fn()
            except BaseException as e:  # noqa: BLE001 — marshalled to caller
                job.error = e
            finally:
                tracing.detach(token)
                job.done.set()
            if self.abandoned:
                return

    def submit(self, fn: Callable[[], Any]) -> _Job:
        job = _Job(fn, tracing.current_span())
        self._q.put(job)
        return job

    def stop(self) -> None:
        self.abandoned = True
        self._q.put(None)


# -------------------------------------------------------------- supervisor


class DeviceSupervisor:
    def __init__(self, config: Optional[BreakerConfig] = None,
                 deadlines: Optional[Dict[str, float]] = None):
        self._lock = TimeoutLock("device_supervisor",
                                 label="DeviceSupervisor._lock")
        self._config = config or BreakerConfig.from_env()
        self._breakers: Dict[str, CircuitBreaker] = {}
        self._workers: Dict[str, _OpWorker] = {}
        self._deadlines = dict(DEFAULT_DEADLINES)
        if deadlines:
            self._deadlines.update(deadlines)
        env_deadline = os.environ.get("LIGHTHOUSE_TPU_DISPATCH_DEADLINE_S")
        if env_deadline:
            self._default_deadline = float(env_deadline)
            for op in list(self._deadlines):
                self._deadlines[op] = float(env_deadline)
        else:
            self._default_deadline = DEFAULT_DEADLINE_S

    # ------------------------------------------------------------- config

    def configure(self, *, config: Optional[BreakerConfig] = None,
                  deadlines: Optional[Dict[str, float]] = None) -> None:
        """Re-tune (tests, admin tooling).  Existing breakers are rebuilt so
        new thresholds apply immediately."""
        cleared: List[str] = []
        with self._lock:
            if config is not None:
                self._config = config
                cleared = list(self._breakers)
                self._breakers.clear()
            if deadlines is not None:
                self._deadlines.update(deadlines)
        # A rebuilt breaker starts CLOSED; reset the gauge now rather than
        # leaving a stale OPEN reading until the op next dispatches.
        for op in cleared:
            metrics.DEVICE_BREAKER_STATE.set(STATE_CODES[STATE_CLOSED], op=op)

    def deadline_for(self, op: str) -> float:
        with self._lock:
            return self._deadlines.get(op, self._default_deadline)

    def breaker(self, op: str) -> CircuitBreaker:
        with self._lock:
            br = self._breakers.get(op)
            if br is None:
                br = self._breakers[op] = CircuitBreaker(op, self._config)
            return br

    # ------------------------------------------------------------ plumbing

    def _worker(self, op: str) -> _OpWorker:
        with self._lock:
            w = self._workers.get(op)
            if w is None or w.abandoned:
                w = self._workers[op] = _OpWorker(op)
            return w

    def _dispatch(self, op: str, fn: Callable[[], Any],
                  deadline_s: float) -> Any:
        """Run ``fn`` under the watchdog; raise :class:`DispatchTimeout` on
        expiry (abandoning the worker), else return/raise ``fn``'s result."""
        if deadline_s <= 0:  # watchdog disabled: run inline
            return fn()
        worker = self._worker(op)
        job = worker.submit(fn)
        if not job.done.wait(deadline_s):
            worker.abandoned = True
            with self._lock:
                if self._workers.get(op) is worker:
                    del self._workers[op]
            metrics.DEVICE_DISPATCH_TIMEOUTS.inc(op=op)
            log.error("device dispatch watchdog fired",
                      op=op, deadline_s=deadline_s)
            blackbox.emit("watchdog", "timeout", op=op, deadline_s=deadline_s)
            blackbox.capture(f"dispatch_timeout:{op}")
            raise DispatchTimeout(op, deadline_s)
        if job.error is not None:
            raise job.error
        return job.value

    def _dispatch_meshed(self, op: str, fn: Callable[[], Any],
                         deadline_s: float, info: dict) -> Any:
        """The mesh-aware dispatch: while the device mesh is active, a
        failure (device error OR watchdog timeout) is charged to a
        *device* (``device_mesh.note_failure`` — parsed from the error
        when the runtime names a chip, else the deterministic suspect).
        A charge that trips that device's breaker re-shards the mesh over
        the survivors and the batch RETRIES on the shrunk topology —
        ``device_fn`` re-places its arrays against the new generation —
        instead of tripping the whole op to host.  A failure that does not
        reshard (threshold not reached, or the mesh is off/exhausted)
        propagates into the existing split-retry / op-breaker ladder, so
        host fallback remains the terminal degradation state."""
        from . import device_mesh

        while True:
            meshed = device_mesh.enabled()
            try:
                result = self._dispatch(op, fn, deadline_s)
                if meshed:
                    # keep the per-device thresholds CONSECUTIVE: a clean
                    # dispatch clears every still-closed breaker's counter
                    device_mesh.note_success()
                return result
            except HostFallback:
                raise  # a disclaimer, not a device failure
            except DispatchTimeout as err:
                if meshed and device_mesh.note_failure(
                        "dispatch_timeout", err=err):
                    info["mesh_reshards"] = info.get("mesh_reshards", 0) + 1
                    log.warning("mesh resharded after dispatch timeout; "
                                "retrying batch", op=op,
                                survivors=device_mesh.size())
                    continue
                raise
            except Exception as err:  # noqa: BLE001 — charged + re-raised
                if meshed and device_mesh.note_failure(
                        "device_error", err=err):
                    info["mesh_reshards"] = info.get("mesh_reshards", 0) + 1
                    log.warning("mesh resharded after device error; "
                                "retrying batch", op=op,
                                error=f"{type(err).__name__}: {err}",
                                survivors=device_mesh.size())
                    continue
                raise

    def _emit(self, op: str, transitions: List[Tuple[str, str, str]]) -> None:
        """Metrics + SSE + log for breaker transitions (no locks held)."""
        for old, new, reason in transitions:
            metrics.DEVICE_BREAKER_STATE.set(STATE_CODES[new], op=op)
            metrics.DEVICE_BREAKER_TRANSITIONS.inc(op=op, to=new)
            log.warning("device breaker transition",
                        op=op, frm=old, to=new, reason=reason)
            blackbox.emit("breaker", "transition",
                          op=op, frm=old, to=new, reason=reason)
            payload = {
                "op": op,
                "from": old,
                "to": new,
                "reason": reason,
                "timestamp_ms": int(time.time() * 1000),
            }
            for bus in list(_EVENT_BUSES):
                try:
                    bus.device_breaker(**payload)
                except Exception:
                    pass  # a dead bus must never break the hot path
            if new == STATE_OPEN:
                # The trigger the black box exists for: freeze the journal
                # window (pre-trip context included) before the ring
                # evicts it.
                blackbox.capture(f"breaker_open:{op}",
                                 extra={"transition": payload})

    def _host(self, op: str, host_fn: Callable[[], Any], reason: str,
              info: dict) -> Any:
        """THE host-fallback path — every reason funnels through here, so
        ``device_batch_host_fallback_total{reason}`` is the one counter that
        tells the whole degradation story."""
        info["route"] = "host"
        info["fallback_reason"] = reason
        metrics.DEVICE_HOST_FALLBACK.inc(reason=reason)
        tracing.annotate(host_fallback=True, fallback_reason=reason)
        log.warning("device batch routed to host backend", op=op, reason=reason)
        blackbox.emit("supervisor", "host_fallback", op=op, reason=reason)
        t0 = time.perf_counter()
        try:
            return host_fn()
        finally:
            info["host_seconds"] = round(time.perf_counter() - t0, 6)

    # ----------------------------------------------------------- execution

    def run(
        self,
        op: str,
        device_fn: Callable[[], Any],
        host_fn: Optional[Callable[[], Any]] = None,
        *,
        split_fn: Optional[Callable[[], List[Callable[[], Any]]]] = None,
        combine_fn: Optional[Callable[[List[Any]], Any]] = None,
        deadline_s: Optional[float] = None,
        info: Optional[dict] = None,
    ) -> Any:
        """Execute one device batch under supervision.

        ``device_fn`` runs the dispatch + wait + verdict (on the watchdog
        worker); ``host_fn`` is the slow-but-correct fallback.  ``split_fn``
        returns per-half thunks for the one split-batch retry (each half
        still watchdogged); ``combine_fn`` merges the halves' results.
        ``info`` (if given) is filled with route/breaker/fallback details
        for the caller's flight-recorder entry.

        With ``host_fn=None`` failures propagate to the caller —
        :class:`DispatchTimeout` subclasses ``RequeueWork``, so inside a
        scheduler worker the work re-enqueues instead of dropping.
        """
        if info is None:
            info = {}
        if split_fn is not None and op in NO_SPLIT_OPS:
            log.warning("split_fn ignored for batch-global op", op=op)
            split_fn = None
        br = self.breaker(op)
        route, transitions = br.route()
        self._emit(op, transitions)
        info["breaker_state"] = br.state
        if route == "host":
            if host_fn is None:
                raise RequeueWork(f"{op}: breaker open and no host fallback")
            return self._host(op, host_fn, "breaker_open", info)
        deadline = self.deadline_for(op) if deadline_s is None else deadline_s

        try:
            result = self._dispatch_meshed(op, device_fn, deadline, info)
        except HostFallback as hf:
            # The device executed and disclaimed — not a device failure.
            self._emit(op, br.record_success())
            if host_fn is None:
                raise RuntimeError(
                    f"{op}: device disclaimed ({hf.reason}) and no host fallback"
                ) from hf
            return self._host(op, host_fn, hf.reason, info)
        except DispatchTimeout:
            self._emit(op, br.record_failure("dispatch_timeout"))
            info["breaker_state"] = br.state
            if host_fn is None:
                raise
            return self._host(op, host_fn, "dispatch_timeout", info)
        except Exception as err:
            # Transient device error: one split-batch retry, then host.
            if split_fn is not None:
                try:
                    halves = split_fn()
                    results = [
                        self._dispatch(op, thunk, deadline) for thunk in halves
                    ]
                    metrics.DEVICE_SPLIT_RETRIES.inc(op=op, outcome="success")
                    info["split_retry"] = "success"
                    info["route"] = "device"
                    tracing.annotate(split_retry=True)
                    self._emit(op, br.record_success())
                    return combine_fn(results) if combine_fn else results
                except HostFallback as hf:
                    # A half executed and disclaimed its verdict — the
                    # device is fine; re-verify on the host under the
                    # disclaimer's own reason, no breaker failure.
                    info["split_retry"] = "host_fallback"
                    self._emit(op, br.record_success())
                    if host_fn is None:
                        raise RuntimeError(
                            f"{op}: device disclaimed ({hf.reason}) "
                            "and no host fallback"
                        ) from hf
                    return self._host(op, host_fn, hf.reason, info)
                except DispatchTimeout:
                    # A half hung past the watchdog: label it what it is —
                    # a timeout, not a generic device error (the timeout
                    # counter already incremented for this op).
                    metrics.DEVICE_SPLIT_RETRIES.inc(op=op, outcome="failure")
                    info["split_retry"] = "failure"
                    self._emit(op, br.record_failure("dispatch_timeout"))
                    info["breaker_state"] = br.state
                    if host_fn is None:
                        raise
                    return self._host(op, host_fn, "dispatch_timeout", info)
                except Exception:
                    metrics.DEVICE_SPLIT_RETRIES.inc(op=op, outcome="failure")
                    info["split_retry"] = "failure"
            self._emit(op, br.record_failure("device_error"))
            info["breaker_state"] = br.state
            info["device_error"] = f"{type(err).__name__}: {err}"
            log.error("device batch failed", op=op,
                      error=f"{type(err).__name__}: {err}")
            if host_fn is None:
                raise
            return self._host(op, host_fn, "device_error", info)
        else:
            self._emit(op, br.record_success())
            info["route"] = "device"
            return result

    # ------------------------------------------------------------- surface

    def summary(self) -> dict:
        """The supervisor section of ``GET /lighthouse/device``."""
        with self._lock:
            breakers = list(self._breakers.values())
            deadlines = dict(self._deadlines)
        return {
            "breakers": [br.snapshot() for br in breakers],
            "deadlines_s": deadlines,
        }

    def reset_for_tests(self) -> None:
        with self._lock:
            cleared = list(self._breakers)
            self._breakers.clear()
            workers = list(self._workers.values())
            self._workers.clear()
            self._config = BreakerConfig.from_env()
            self._deadlines = dict(DEFAULT_DEADLINES)
        for w in workers:
            w.stop()
        for op in cleared:
            metrics.DEVICE_BREAKER_STATE.set(STATE_CODES[STATE_CLOSED], op=op)


SUPERVISOR = DeviceSupervisor()


def run(op: str, device_fn, host_fn=None, **kwargs) -> Any:
    return SUPERVISOR.run(op, device_fn, host_fn, **kwargs)


def summary() -> dict:
    return SUPERVISOR.summary()


def breaker_state(op: str) -> str:
    """Current breaker state for ``op`` (creates the breaker CLOSED on first
    ask) — the cheap probe the device pipeline's tests/scenarios use to
    assert breaker-open batches still resolve futures."""
    return SUPERVISOR.breaker(op).state


def reset_for_tests() -> None:
    set_cooldown_clock(None)
    SUPERVISOR.reset_for_tests()


# --------------------------------------------------------------- SSE wiring

# Breaker transitions publish to every live EventBus (weakly held: test
# harnesses build many chains per process; dead buses drop out on GC).
_EVENT_BUSES: "weakref.WeakSet" = weakref.WeakSet()


def register_event_bus(bus) -> None:
    """Called by ``BeaconChain.__init__`` so breaker transitions reach the
    node's ``/eth/v1/events`` stream as ``device_breaker`` events."""
    _EVENT_BUSES.add(bus)
