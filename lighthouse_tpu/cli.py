"""The ``lighthouse-tpu`` command-line interface.

Equivalent of the reference's ``lighthouse`` binary (``lighthouse/src/main.rs:79-402``
clap tree): ``beacon_node`` (bn), ``validator_client`` (vc), and
``account_manager`` (am) subcommands over the same library stack the tests
drive.  ``python -m lighthouse_tpu <subcommand> --help`` for usage.
"""

from __future__ import annotations

import argparse
import getpass
import json
import logging
import os
import signal
import sys
import threading
import time
from typing import List, Optional


def _spec_for(network: str):
    from .types.spec import SPECS

    if network not in SPECS:
        raise SystemExit(f"unknown network {network!r} (have: {', '.join(SPECS)})")
    return SPECS[network]()


def _write_secret_file(path: str, text: str) -> None:
    """Owner-only (0600) secret write — keys and tokens must never be
    world-readable."""
    fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
    with os.fdopen(fd, "w") as f:
        f.write(text)


def _read_password(path, prompt: str) -> str:
    if path:
        with open(path) as f:
            return f.read().strip()
    return getpass.getpass(prompt)


# ------------------------------------------------------------ beacon node


def run_beacon_node(args) -> int:
    from .client import ClientBuilder

    from .logs import setup_logging

    setup_logging(
        logging.DEBUG if args.debug else logging.INFO,
        json_format=getattr(args, "log_json", False),
    )
    if getattr(args, "testnet_dir", None):
        from .network_config import Eth2NetworkConfig

        spec = Eth2NetworkConfig.from_testnet_dir(args.testnet_dir).spec
    else:
        spec = _spec_for(args.network)
    if getattr(args, "aot_warmup", False):
        # The builder's compile-cache hook reads the env flag; the CLI flag
        # is just its spelled-out form.
        os.environ["LIGHTHOUSE_TPU_AOT_WARMUP"] = "1"
    builder = ClientBuilder().with_spec(spec).with_bls_backend(args.bls_backend)
    if getattr(args, "checkpoint_sync_url", None):
        builder.with_checkpoint_sync(args.checkpoint_sync_url)
    elif args.interop_validators:
        builder.with_interop_genesis(
            args.interop_validators, genesis_time=args.interop_genesis_time
        )
    elif args.genesis_state:
        from .types.containers import build_types

        types = build_types(spec.preset)
        fork = spec.fork_name_at_epoch(0)
        with open(args.genesis_state, "rb") as f:
            builder.with_genesis_state(types.state[fork].from_ssz_bytes(f.read()))
    else:
        raise SystemExit("provide --checkpoint-sync-url URL, "
                         "--interop-validators N or --genesis-state FILE")
    if args.datadir:
        builder.with_datadir(args.datadir)
    if args.execution_endpoint:
        if not args.execution_jwt:
            raise SystemExit("--execution-endpoint requires --execution-jwt FILE")
        from .execution_layer.auth import strip_prefix

        with open(args.execution_jwt) as f:
            builder.with_execution_layer(args.execution_endpoint, strip_prefix(f.read()))
    builder.with_http_api(args.http_port)
    if args.slasher:
        builder.with_slasher()
    if getattr(args, "monitoring_endpoint", None):
        builder.with_monitoring(args.monitoring_endpoint)
    if args.listen_port is not None or args.peers or args.boot_nodes:
        builder.with_network(
            listen_port=args.listen_port or 0,
            peers=[p for p in (args.peers or "").split(",") if p],
            boot_nodes=[b for b in (args.boot_nodes or "").split(",") if b],
        )

    client = builder.build().start()
    print(f"beacon node up: http API on :{args.http_port}, "
          f"network={args.network}, backend={args.bls_backend}")
    _wait_for_shutdown()
    client.stop()
    return 0


# -------------------------------------------------------- validator client


def run_validator_client(args) -> int:
    from .crypto import keystore as ks
    from .http_api import BeaconNodeHttpClient
    from .types.containers import build_types
    from .validator_client import SlashingProtectionDB, ValidatorClient

    from .logs import setup_logging

    setup_logging(logging.INFO)
    spec = _spec_for(args.network)
    types = build_types(spec.preset)

    password = _read_password(args.password_file, "keystore password: ")
    keys = []
    for name in sorted(os.listdir(args.keystore_dir)):
        if not name.endswith(".json"):
            continue
        keystore = ks.load_json(os.path.join(args.keystore_dir, name))
        if "crypto" not in keystore or "pubkey" not in keystore:
            continue
        keys.append(ks.load_keystore_signing_key(keystore, password))
    if not keys:
        raise SystemExit(f"no keystores found under {args.keystore_dir}")
    print(f"loaded {len(keys)} validator keys")

    clients = [BeaconNodeHttpClient(u) for u in args.beacon_nodes.split(",")]
    genesis = clients[0].genesis()
    slashing_db = SlashingProtectionDB()
    if args.slashing_protection_db:
        from .store.lockbox_store import LockboxStore

        slashing_db = SlashingProtectionDB(
            store=LockboxStore(args.slashing_protection_db)
        )
    vc = ValidatorClient(
        keys=keys,
        beacon_nodes=clients,
        spec=spec,
        types=types,
        genesis_validators_root=bytes.fromhex(genesis["genesis_validators_root"][2:]),
        slashing_db=slashing_db,
    )
    keymanager = None
    if getattr(args, "keymanager_port", None) is not None:
        from .validator_client.keymanager import KeymanagerServer

        keymanager = KeymanagerServer(
            store=vc.store,
            genesis_validators_root=vc.store.genesis_validators_root,
            port=args.keymanager_port,
            preparation=vc.preparation, blocks=vc.blocks,
        ).start()
        token_path = os.path.join(args.keystore_dir, "api-token.txt")
        # owner-only: the token grants key deletion/import (reference writes
        # api-token.txt 0600)
        _write_secret_file(token_path, keymanager.token)
        print(f"keymanager API on {keymanager.url} (token in {token_path})")
    print("validator client running (ctrl-c to stop)")
    try:
        vc.run_forever(genesis_time=int(genesis["genesis_time"]))
    except KeyboardInterrupt:
        pass
    finally:
        if keymanager is not None:
            keymanager.stop()
    return 0


# -------------------------------------------------------- account manager


def run_account(args) -> int:
    from .crypto import keystore as ks

    os.makedirs(args.base_dir, exist_ok=True)
    if args.account_cmd == "wallet-create":
        password = _read_password(args.password_file, "wallet password: ")
        wallet, _seed = ks.create_wallet(args.name, password)
        path = os.path.join(args.base_dir, f"wallet-{args.name}.json")
        ks.save_json(wallet, path)
        print(f"wallet written to {path}")
        return 0
    if args.account_cmd == "validator-create":
        wallet = ks.load_json(args.wallet)
        wpass = _read_password(args.password_file, "wallet password: ")
        kpass = _read_password(args.keystore_password_file, "keystore password: ")
        out_dir = os.path.join(args.base_dir, "validators")
        os.makedirs(out_dir, exist_ok=True)
        derived = ks.derive_validator_keystores(wallet, wpass, kpass, args.count)
        for keystore, _sk in derived:
            path = os.path.join(out_dir, f"keystore-{keystore['pubkey'][:16]}.json")
            ks.save_json(keystore, path)
            print(f"validator {keystore['pubkey'][:16]}… -> {path}")
        ks.save_json(wallet, args.wallet)  # persists nextaccount
        return 0
    if args.account_cmd == "validator-list":
        vdir = os.path.join(args.base_dir, "validators")
        if not os.path.isdir(vdir):
            print("no validators")
            return 0
        for name in sorted(os.listdir(vdir)):
            if name.endswith(".json"):
                obj = ks.load_json(os.path.join(vdir, name))
                print(f"0x{obj.get('pubkey', '')}  path={obj.get('path', '')}")
        return 0
    if args.account_cmd == "slashing-protection-export":
        from .store.lockbox_store import LockboxStore
        from .validator_client import SlashingProtectionDB

        db = SlashingProtectionDB(store=LockboxStore(args.db))
        text = db.export_json(bytes.fromhex(args.genesis_validators_root[2:]))
        with open(args.out, "w") as f:
            f.write(text)
        print(f"interchange written to {args.out}")
        return 0
    if args.account_cmd == "slashing-protection-import":
        from .store.lockbox_store import LockboxStore
        from .validator_client import SlashingProtectionDB

        db = SlashingProtectionDB(store=LockboxStore(args.db))
        n = db.import_json(
            open(args.interchange).read(),
            bytes.fromhex(args.genesis_validators_root[2:]),
        )
        print(f"imported protection for {n} validators")
        return 0
    raise SystemExit(f"unknown account command {args.account_cmd}")


# ---------------------------------------------------------------- parser


def run_database_manager(args) -> int:
    """``lighthouse db`` equivalent (reference ``database_manager/``):
    inspect / version / compact an on-disk node database."""
    from .store.kv import DBColumn
    from .store.lockbox_store import LockboxStore

    path = os.path.join(args.datadir, "chain.db")
    if not os.path.exists(path):
        print(f"no database at {path}", file=sys.stderr)
        return 1
    store = LockboxStore(path)
    try:
        if args.db_cmd == "version":
            import struct

            raw = store.get(DBColumn.BEACON_META, b"schema")
            version = struct.unpack(">Q", raw)[0] if raw else None
            print(json.dumps({"path": path, "schema_version": version}))
        elif args.db_cmd == "inspect":
            counts = {}
            names = {
                getattr(DBColumn, n): n for n in dir(DBColumn) if not n.startswith("_")
            }
            for column in names:
                n_keys = sum(1 for _ in store.iter_column(column))
                if n_keys:
                    counts[names[column]] = n_keys
            print(json.dumps({"path": path, "keys_per_column": counts}))
        elif args.db_cmd == "compact":
            store.compact()
            print(json.dumps({"path": path, "compacted": True}))
        elif args.db_cmd == "prune-payloads":
            # Reference `lighthouse db prune-payloads`: rewrite stored
            # post-merge blocks WITHOUT their execution payloads — the
            # block streamer reconstructs them from the EL on read.
            from .chain.block_streamer import blind_signed_block
            from .store.hot_cold import decode_stored_block, encode_stored_block
            from .types.containers import build_types

            spec = _spec_for(args.network)
            types = build_types(spec.preset)
            pruned = skipped = 0
            # iter_column snapshots its key list up front, so rewriting
            # entries mid-iteration is safe without materializing every
            # block's bytes at once
            for key, raw in store.iter_column(DBColumn.BEACON_BLOCK):
                signed, is_blinded, _fork = decode_stored_block(types, raw)
                if is_blinded or not hasattr(
                        signed.message.body, "execution_payload"):
                    skipped += 1  # payload-free already, or pre-merge
                    continue
                blinded = blind_signed_block(signed, types)
                store.put(DBColumn.BEACON_BLOCK, key,
                          encode_stored_block(blinded, blinded=True))
                pruned += 1
            print(json.dumps({"path": path, "payloads_pruned": pruned,
                              "skipped": skipped}))
        elif args.db_cmd == "prune-blobs":
            # Reference `lighthouse db prune-blobs`: drop sidecars below the
            # retention horizon (--before-slot; the node's own periodic
            # pruning uses the spec MIN_EPOCHS_FOR_BLOB_SIDECARS horizon).
            from .store.hot_cold import prune_blob_column
            from .types.containers import build_types

            spec = _spec_for(args.network)
            types = build_types(spec.preset)
            pruned = prune_blob_column(store, types, args.before_slot)
            print(json.dumps({"path": path, "blob_sets_pruned": pruned}))
    finally:
        store.close()
    return 0


def run_lcli(args) -> int:
    """Dev swiss-army knife (reference ``lcli/``): state-transition timing
    loops, root computation, SSZ inspection."""
    from .types.containers import build_types

    if args.lcli_cmd == "transition-bench":
        import subprocess

        cmd = [sys.executable,
               os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                            "scripts", "transition_bench.py"),
               "--validators", str(args.validators)]
        if args.slots:
            cmd += ["--slots", str(args.slots)]
        for _ in range(args.runs):
            subprocess.run(cmd, check=True)
        return 0

    if args.lcli_cmd == "generate-bootnode-enr":
        # Reference `lcli generate-bootnode-enr`: mint a bootnode identity —
        # a fresh secp256k1 key + the signed ENR advertising ip/udp/tcp —
        # into an output dir (refusing to clobber an existing one).
        from .network.discv5 import KeyPair
        from .network.discv5.enr import ENR, EnrError

        if os.path.exists(args.output_dir):
            raise SystemExit(f"{args.output_dir} already exists, will not override")
        keypair = KeyPair()
        try:
            # build (validating the ip) BEFORE creating the directory: a
            # failure must not leave a half-made dir the clobber guard
            # then refuses on the corrected rerun
            enr = ENR.build(keypair, seq=1, ip=args.ip,
                            udp=args.udp_port, tcp=args.tcp_port)
        except (ValueError, EnrError) as e:
            raise SystemExit(f"cannot build ENR: {e}")
        os.makedirs(args.output_dir)
        with open(os.path.join(args.output_dir, "enr.dat"), "w") as f:
            f.write(enr.to_text())
        # fixed-width 32-byte key: hex() drops leading zeros and can emit
        # odd-length strings bytes.fromhex chokes on
        _write_secret_file(os.path.join(args.output_dir, "key"),
                           f"0x{keypair.priv:064x}")
        print(json.dumps({"enr": enr.to_text(),
                          "node_id": "0x" + keypair.node_id.hex(),
                          "output_dir": args.output_dir}))
        return 0

    if args.lcli_cmd == "mock-el":
        # Reference `lcli mock-el`: a standalone fake execution engine a
        # beacon node can point its --execution-endpoint at for testing.
        import secrets as _secrets

        from .execution_layer.mock_server import MockEngineServer

        if args.jwt_output and args.jwt_secret:
            raise SystemExit("--jwt-output and --jwt-secret are exclusive: "
                             "generate a fresh secret OR reuse an existing one")
        if args.jwt_output:
            secret = _secrets.token_bytes(32)
            # owner-only: the secret authenticates engine-API calls
            _write_secret_file(args.jwt_output, "0x" + secret.hex())
        else:
            raw = _read_password(args.jwt_secret, "jwt secret (hex): ")
            try:
                secret = bytes.fromhex(raw.removeprefix("0x"))
            except ValueError as e:
                raise SystemExit(f"invalid jwt secret hex: {e}")
            if len(secret) != 32:
                raise SystemExit(
                    f"jwt secret must be 32 bytes, got {len(secret)}")
        server = MockEngineServer(secret, port=args.port).start()
        print(json.dumps({"endpoint": server.url,
                          "jwt_secret_file": args.jwt_output or "(provided)"}))
        sys.stdout.flush()
        stop = threading.Event()
        for s in (signal.SIGINT, signal.SIGTERM):
            signal.signal(s, lambda *_: stop.set())
        stop.wait()
        server.stop()
        return 0

    if args.lcli_cmd == "skip-slots":
        spec = _spec_for(args.network)
        types = build_types(spec.preset)
        from .consensus.per_slot import process_slots

        with open(args.pre_state, "rb") as f:
            data = f.read()
        state = types.state[args.fork].from_ssz_bytes(data)
        t0 = time.perf_counter()
        state = process_slots(state, int(state.slot) + args.slots, types, spec)
        dt = time.perf_counter() - t0
        print(json.dumps({"slots": args.slots, "seconds": round(dt, 3),
                          "state_root": "0x" + state.hash_tree_root().hex()}))
        if args.output:
            with open(args.output, "wb") as f:
                f.write(state.as_ssz_bytes())
        return 0

    if args.lcli_cmd in ("state-root", "block-root"):
        spec = _spec_for(args.network)
        types = build_types(spec.preset)
        with open(args.file, "rb") as f:
            data = f.read()
        registry = types.state if args.lcli_cmd == "state-root" else types.signed_block
        obj = registry[args.fork].from_ssz_bytes(data)
        root = (obj.hash_tree_root() if args.lcli_cmd == "state-root"
                else obj.message.hash_tree_root())
        print(json.dumps({"root": "0x" + root.hex()}))
        return 0

    if args.lcli_cmd == "parse-ssz":
        spec = _spec_for(args.network)
        types = build_types(spec.preset)
        from .http_api.serde import to_json

        cls = getattr(types, args.type_name, None)
        if cls is None:
            cls = types.signed_block.get(args.type_name) or types.state.get(args.type_name)
        if cls is None:
            print(f"unknown type {args.type_name!r}", file=sys.stderr)
            return 1
        with open(args.file, "rb") as f:
            obj = cls.from_ssz_bytes(f.read())
        print(json.dumps(to_json(obj), indent=2))
        return 0
    return 1


def _parse_pubkey(s: str) -> bytes:
    raw = s[2:] if s.startswith("0x") else s
    try:
        pk = bytes.fromhex(raw)
    except ValueError:
        raise SystemExit(f"invalid pubkey {s!r}")
    if len(pk) != 48:
        raise SystemExit(f"pubkey must be 48 bytes: {s!r}")
    return pk


def run_validator_manager(args) -> int:
    """``lighthouse validator_manager`` equivalent: manage a RUNNING VC's
    keys over its keymanager API (reference ``validator_manager/``)."""
    from .validator_client.keymanager import KeymanagerClient

    token = args.token
    if args.token_file:
        with open(args.token_file) as f:
            token = f.read().strip()
    if not token:
        raise SystemExit("provide --token or --token-file")
    client = KeymanagerClient(args.vc_url, token)

    if args.vm_cmd == "list":
        for row in client.list_keystores():
            print(row["validating_pubkey"])
        for row in client.list_remotekeys():
            print(f"{row['pubkey']} (remote: {row['url']})")
        return 0
    if args.vm_cmd == "import":
        from .crypto import keystore as ks

        password = _read_password(args.password_file, "keystore password: ")
        keystores = []
        for name in sorted(os.listdir(args.keystores_dir)):
            if name.endswith(".json"):
                keystores.append(ks.load_json(os.path.join(args.keystores_dir, name)))
        if not keystores:
            raise SystemExit(f"no keystores under {args.keystores_dir}")
        protection = None
        if args.slashing_protection:
            with open(args.slashing_protection) as f:
                protection = f.read()
        statuses = client.import_keystores(
            keystores, [password] * len(keystores), protection
        )
        for ks_obj, st in zip(keystores, statuses):
            print(f"0x{ks_obj.get('pubkey', '')[:16]}…: {st['status']}")
        return 0 if all(s["status"] == "imported" for s in statuses) else 1
    if args.vm_cmd == "delete":
        resp = client.delete_keystores([_parse_pubkey(p) for p in args.pubkeys])
        for p, st in zip(args.pubkeys, resp["data"]):
            print(f"{p}: {st['status']}")
        if args.slashing_protection_out:
            with open(args.slashing_protection_out, "w") as f:
                f.write(resp["slashing_protection"])
            print(f"slashing protection exported to {args.slashing_protection_out}")
        return 0
    if args.vm_cmd == "import-remote":
        statuses = client.import_remotekeys(
            [{"pubkey": "0x" + _parse_pubkey(p).hex(), "url": args.signer_url}
             for p in args.pubkeys]
        )
        for p, st in zip(args.pubkeys, statuses):
            print(f"{p}: {st['status']}")
        return 0
    return 1


def run_watch(args) -> int:
    """Chain analytics service (reference ``watch/``): poll a BN, serve
    aggregates."""
    from .http_api import BeaconNodeHttpClient
    from .watch import WatchDB, WatchServer, WatchUpdater

    spec = _spec_for(args.network)
    db = WatchDB(args.db)
    updater = WatchUpdater(
        client=BeaconNodeHttpClient(args.beacon_node), db=db, spec=spec
    )
    server = WatchServer(db, port=args.port).start()
    print(f"watch serving on {server.url}, polling {args.beacon_node}")
    try:
        while True:
            try:
                n = updater.update()
                if n:
                    print(f"ingested {n} slots (highest {db.highest_slot()})")
            except Exception as e:
                print(f"update failed: {e}")
            time.sleep(args.interval)
    except KeyboardInterrupt:
        server.stop()
        db.close()
    return 0


def run_boot_node(args) -> int:
    """Standalone discovery bootstrapper (reference ``boot_node/``)."""
    from .network.boot_node import run_forever

    run_forever(args.listen_address, args.port)
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="lighthouse-tpu",
        description="TPU-native Ethereum consensus client",
    )
    sub = p.add_subparsers(dest="cmd", required=True)

    bn = sub.add_parser("beacon_node", aliases=["bn"], help="run a beacon node")
    bn.add_argument("--network", default="mainnet")
    bn.add_argument("--testnet-dir", default=None,
                    help="directory holding a config.yaml network definition")
    bn.add_argument("--monitoring-endpoint", default=None,
                    help="push node stats to this client-stats URL every 60s")
    bn.add_argument("--listen-port", type=int, default=None,
                    help="join the p2p network, listening on this TCP port")
    bn.add_argument("--peers", default=None,
                    help="comma-separated host:port static peers to dial")
    bn.add_argument("--boot-nodes", default=None,
                    help="comma-separated host:port boot nodes for discovery")
    bn.add_argument("--checkpoint-sync-url", default=None,
                    help="boot from this trusted node's finalized checkpoint")
    bn.add_argument("--datadir", default=None)
    bn.add_argument("--http-port", type=int, default=5052)
    bn.add_argument("--execution-endpoint", default=None)
    bn.add_argument("--execution-jwt", default=None)
    bn.add_argument("--interop-validators", type=int, default=None)
    bn.add_argument("--interop-genesis-time", type=int, default=None)
    bn.add_argument("--genesis-state", default=None)
    bn.add_argument("--slasher", action="store_true")
    bn.add_argument("--bls-backend", default="jax", choices=["jax", "host", "fake"])
    bn.add_argument("--aot-warmup", action="store_true",
                    help="ahead-of-time compile the standard device buckets "
                         "at startup (background thread; persistent compile "
                         "cache makes repeat starts near-instant)")
    bn.add_argument("--debug", action="store_true")
    bn.add_argument("--log-json", action="store_true", dest="log_json",
                    help="emit structured JSON log lines (one object per line)")
    bn.set_defaults(func=run_beacon_node)

    vc = sub.add_parser("validator_client", aliases=["vc"], help="run a validator client")
    vc.add_argument("--network", default="mainnet")
    vc.add_argument("--beacon-nodes", default="http://127.0.0.1:5052")
    vc.add_argument("--keystore-dir", required=True)
    vc.add_argument("--password-file", default=None)
    vc.add_argument("--slashing-protection-db", default=None)
    vc.add_argument("--keymanager-port", type=int, default=None,
                    help="serve the keymanager API on this port")
    vc.set_defaults(func=run_validator_client)

    am = sub.add_parser("account_manager", aliases=["am", "account"],
                        help="wallets, validators, slashing protection")
    am.add_argument("--base-dir", default=os.path.expanduser("~/.lighthouse-tpu"))
    amsub = am.add_subparsers(dest="account_cmd", required=True)
    w = amsub.add_parser("wallet-create")
    w.add_argument("--name", required=True)
    w.add_argument("--password-file", default=None)
    v = amsub.add_parser("validator-create")
    v.add_argument("--wallet", required=True)
    v.add_argument("--count", type=int, default=1)
    v.add_argument("--password-file", default=None)
    v.add_argument("--keystore-password-file", default=None)
    amsub.add_parser("validator-list")
    ex = amsub.add_parser("slashing-protection-export")
    ex.add_argument("--db", required=True)
    ex.add_argument("--out", required=True)
    ex.add_argument("--genesis-validators-root", required=True)
    im = amsub.add_parser("slashing-protection-import")
    im.add_argument("--db", required=True)
    im.add_argument("--interchange", required=True)
    im.add_argument("--genesis-validators-root", required=True)
    am.set_defaults(func=run_account)

    db = sub.add_parser("database_manager", aliases=["db"],
                        help="inspect/compact a node database")
    dbsub = db.add_subparsers(dest="db_cmd", required=True)
    for name in ("version", "inspect", "compact"):
        d = dbsub.add_parser(name)
        d.add_argument("--datadir", required=True)
    # --network is REQUIRED on the destructive commands: decoding a
    # mainnet db with the minimal preset rewrites valid blocks as garbage
    pp = dbsub.add_parser("prune-payloads",
                          help="strip execution payloads from stored blocks")
    pp.add_argument("--datadir", required=True)
    pp.add_argument("--network", required=True)
    pb = dbsub.add_parser("prune-blobs",
                          help="drop blob sidecars below a slot horizon")
    pb.add_argument("--datadir", required=True)
    pb.add_argument("--network", required=True)
    pb.add_argument("--before-slot", type=int, required=True)
    db.set_defaults(func=run_database_manager)

    lcli = sub.add_parser("lcli", help="dev tools (transition timing, roots, ssz)")
    lsub = lcli.add_subparsers(dest="lcli_cmd", required=True)
    tb = lsub.add_parser("transition-bench")
    tb.add_argument("--validators", type=int, default=16384)
    tb.add_argument("--slots", type=int, default=None)
    tb.add_argument("--runs", type=int, default=1)
    sk = lsub.add_parser("skip-slots")
    sk.add_argument("--network", default="minimal")
    sk.add_argument("--fork", default="capella")
    sk.add_argument("--pre-state", required=True)
    sk.add_argument("--slots", type=int, required=True)
    sk.add_argument("--output", default=None)
    for name in ("state-root", "block-root"):
        r = lsub.add_parser(name)
        r.add_argument("--network", default="minimal")
        r.add_argument("--fork", default="capella")
        r.add_argument("file")
    ge = lsub.add_parser("generate-bootnode-enr",
                         help="mint a bootnode key + signed ENR")
    ge.add_argument("--ip", required=True)
    ge.add_argument("--udp-port", type=int, required=True)
    ge.add_argument("--tcp-port", type=int, required=True)
    ge.add_argument("--output-dir", required=True)
    me = lsub.add_parser("mock-el", help="run a standalone fake execution engine")
    me.add_argument("--port", type=int, default=0)
    me.add_argument("--jwt-output", default="",
                    help="write a fresh jwt secret here (hex)")
    me.add_argument("--jwt-secret", default="",
                    help="file holding an existing jwt secret (hex)")
    ps = lsub.add_parser("parse-ssz")
    ps.add_argument("--network", default="minimal")
    ps.add_argument("type_name")
    ps.add_argument("file")
    lcli.set_defaults(func=run_lcli)

    vm = sub.add_parser("validator_manager", aliases=["vm"],
                        help="manage a running VC's keys over the keymanager API")
    vm.add_argument("--vc-url", default="http://127.0.0.1:5062")
    vm.add_argument("--token", default=None)
    vm.add_argument("--token-file", default=None)
    vmsub = vm.add_subparsers(dest="vm_cmd", required=True)
    vmsub.add_parser("list")
    vi = vmsub.add_parser("import")
    vi.add_argument("--keystores-dir", required=True)
    vi.add_argument("--password-file", default=None)
    vi.add_argument("--slashing-protection", default=None)
    vd = vmsub.add_parser("delete")
    vd.add_argument("pubkeys", nargs="+")
    vd.add_argument("--slashing-protection-out", default=None)
    vr = vmsub.add_parser("import-remote")
    vr.add_argument("pubkeys", nargs="+")
    vr.add_argument("--signer-url", required=True)
    vm.set_defaults(func=run_validator_manager)

    watch = sub.add_parser("watch", help="chain analytics: poll a BN, serve aggregates")
    watch.add_argument("--network", default="mainnet")
    watch.add_argument("--beacon-node", default="http://127.0.0.1:5052")
    watch.add_argument("--db", default="watch.sqlite")
    watch.add_argument("--port", type=int, default=5059)
    watch.add_argument("--interval", type=float, default=12.0)
    watch.set_defaults(func=run_watch)

    boot = sub.add_parser("boot_node", help="run a peer-introduction boot node")
    boot.add_argument("--listen-address", default="0.0.0.0")
    boot.add_argument("--port", type=int, default=9100)
    boot.set_defaults(func=run_boot_node)
    return p


def _wait_for_shutdown() -> None:
    stop = {"flag": False}

    def handler(signum, frame):
        stop["flag"] = True

    signal.signal(signal.SIGINT, handler)
    signal.signal(signal.SIGTERM, handler)
    while not stop["flag"]:
        time.sleep(0.5)


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    return args.func(args)


if __name__ == "__main__":
    sys.exit(main())
