"""``python -m lighthouse_tpu`` — the CLI entry (reference: the
``lighthouse`` binary)."""

import sys

from .cli import main

sys.exit(main())
