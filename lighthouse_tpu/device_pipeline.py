"""Async device pipeline: a persistent device-worker queue that decouples the
scheduler from the device (ROADMAP item 3).

Before this module, every caller of ``bls.verify_signature_sets`` — block
import, a drained gossip attestation batch, a sync-committee contribution —
blocked its own thread for the full dispatch+wait of its own batch, and the
scheduler could only coalesce events from a single queue class.  Real traffic
therefore dispatched many small, latency-dominated device batches while the
4096-set standard bucket (PR 6) sat empty.

This module inverts that: callers **submit** a group of ``SignatureSet``\\ s
and immediately receive a :class:`VerifyFuture`; one long-lived pipeline per
op owns the device and

- **coalesces** pending groups *across work types* (block import + gossip
  attestations + aggregates + sync committee + API batches) into one maximal
  pairing batch, targeting the standard device bucket, with a small linger
  window so a lone attestation never waits forever;
- **double-buffers** host-side batch building against in-flight device
  execution: a builder thread marshals batch N+1 (``ops/verify.py``
  ``build_device_batch`` — validation, hash-to-curve, limb packing) while the
  executor thread is still waiting on batch N, handing off through a depth-1
  queue.  While the device is busy the pending queue keeps filling, so device
  latency itself widens the next batch (the natural-backpressure fill
  mechanism);
- **dispatches through the device supervisor** (``device_supervisor.py``):
  watchdog, split-retry and circuit-breaker semantics are exactly those of
  the direct path — a breaker-OPEN op routes the coalesced batch to the host
  golden model and the futures still resolve;
- **attributes verdicts per group**: a passing batch resolves every group
  True; a failing (or host-disclaimed) batch re-checks each group once on the
  host golden model so only the actually-bad group fails — one host re-check
  per group, never per set.

The enrolment seam is ``crypto/bls/api.verify_signature_sets`` — the one
funnel every signature in the system already flows through — so enabling the
pipeline (``ClientBuilder.build`` does, for the jax backend) streams ALL
device-bound verification through one seam without touching any caller.
Callers that pin ``seed=`` (reproducibility tests) or exceed the standard
bucket bypass the pipeline and keep their exact semantics.

Observability: ``device_pipeline_{pending_sets,depth,batch_fill_ratio,
linger_seconds,wait_seconds,batches_total,groups_total}`` metrics, a
``pipeline_batch`` trace root per coalesced dispatch (submit→coalesce→
dispatch→resolve via ``pipeline_submit``/``pipeline_wait`` child spans in the
caller's trace), flight-recorder records carrying ``n_groups``/``work_mix``,
and a ``summary()`` section on ``GET /lighthouse/device``.
"""

from __future__ import annotations

import contextvars
import os
import queue
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Any, Dict, List, Optional

from . import metrics, tracing
from .logs import get_logger
from .scheduler.work import STANDARD_DEVICE_BATCH

log = get_logger("device_pipeline")

#: Groups larger than this bypass the pipeline entirely (the direct path
#: chunks them through the standard bucket itself).  The scheduler's
#: standard device batch, clamped to the device's single-dispatch ceiling
#: (ops/verify.MAX_SETS_PER_DISPATCH == 4096 — kept as a literal here so
#: importing the pipeline never pulls jax, same convention as work.py):
#: a raised LIGHTHOUSE_TPU_STANDARD_BATCH must not let the pipeline build
#: batches the device entry point refuses.
MAX_GROUP_SETS = min(STANDARD_DEVICE_BATCH, 4096)

#: Default linger: how long the builder waits for more groups once the FIRST
#: pending group is older than this and the target bucket is not yet full.
#: Small on purpose — while a batch is in flight the pending queue fills for
#: free; the linger only bounds the latency of a lone set on an idle device.
DEFAULT_LINGER_S = float(os.environ.get("LIGHTHOUSE_TPU_PIPELINE_LINGER_S", "0.02"))

#: Default coalescing target (sets per dispatched batch).
DEFAULT_TARGET_SETS = int(
    os.environ.get("LIGHTHOUSE_TPU_PIPELINE_TARGET_SETS", str(STANDARD_DEVICE_BATCH))
)

#: Bounded ring of recent per-batch summaries for summary()/tests.
RECENT_BATCHES = 64

_WORK_KIND: "contextvars.ContextVar[Optional[str]]" = contextvars.ContextVar(
    "lighthouse_tpu_pipeline_work_kind", default=None
)


@contextmanager
def work_context(kind: str):
    """Tag pipeline submissions made inside this context with ``kind`` (the
    ``work_mix`` attribution on coalesced batches)."""
    token = _WORK_KIND.set(kind)
    try:
        yield
    finally:
        _WORK_KIND.reset(token)


def current_work_kind() -> str:
    return _WORK_KIND.get() or "other"


class PipelineShutdown(RuntimeError):
    """The pipeline was shut down without draining this group."""


class VerifyFuture:
    """Resolution handle for one submitted group."""

    __slots__ = ("_done", "_result", "_error", "submitted_pc", "work", "n_sets")

    def __init__(self, work: str, n_sets: int):
        self._done = threading.Event()
        self._result: Optional[bool] = None
        self._error: Optional[BaseException] = None
        self.submitted_pc = time.perf_counter()
        self.work = work
        self.n_sets = n_sets

    def done(self) -> bool:
        return self._done.is_set()

    def set_result(self, value: bool) -> None:
        self._result = bool(value)
        self._done.set()

    def set_error(self, err: BaseException) -> None:
        self._error = err
        self._done.set()

    def result(self, timeout: Optional[float] = None) -> bool:
        """Block until the group's verdict is known; raises the pipeline's
        error if its batch failed outside verification semantics."""
        if not self._done.wait(timeout):
            raise TimeoutError("pipeline verdict not available in time")
        if self._error is not None:
            raise self._error
        return bool(self._result)


class _Group:
    __slots__ = ("sets", "future")

    def __init__(self, sets: list, future: VerifyFuture):
        self.sets = sets
        self.future = future


class _BuiltBatch:
    """One coalesced batch on its way to the executor."""

    __slots__ = ("groups", "flat_sets", "built", "unbuilt",
                 "linger_s", "build_s", "work_mix")

    def __init__(self, groups: List[_Group], flat_sets: list, built,
                 unbuilt: bool, linger_s: float, build_s: float,
                 work_mix: Dict[str, int]):
        self.groups = groups
        self.flat_sets = flat_sets
        self.built = built          # ops.verify.BuiltBatch | None (host modes)
        #: device mode only: the build stage produced no device batch (a
        #: marshalling error OR host-side validation deciding False) — the
        #: batch verdict is not trustworthy as a signature verdict, so EVERY
        #: group (even a lone one) resolves via its own host re-check.
        self.unbuilt = unbuilt
        self.linger_s = linger_s
        self.build_s = build_s
        self.work_mix = work_mix


class DevicePipeline:
    """One persistent device-worker pipeline for one op (``bls_verify``).

    ``verify_flat_fn``: test seam — replaces the whole batch-execution leg
    (called with the flat set list, returns the combined verdict).
    ``recheck_fn``: test seam — replaces the per-group host re-check.
    """

    def __init__(self, op: str = "bls_verify", *,
                 target_sets: Optional[int] = None,
                 linger_s: Optional[float] = None,
                 verify_flat_fn=None, recheck_fn=None):
        self.op = op
        # clamped to the single-dispatch ceiling: one coalesced batch must
        # stay buildable by ops/verify.build_device_batch
        self.target_sets = max(1, min(int(target_sets or DEFAULT_TARGET_SETS),
                                      MAX_GROUP_SETS))
        self.linger_s = DEFAULT_LINGER_S if linger_s is None else float(linger_s)
        self._verify_flat_fn = verify_flat_fn
        self._recheck_fn = recheck_fn
        self._cond = threading.Condition()
        self._pending: deque = deque()          # _Group FIFO
        self._pending_sets = 0
        self._in_flight_groups = 0              # taken but not yet resolved
        self._shutdown = False
        self._idle = threading.Event()
        self._idle.set()
        # depth-1 handoff: the double buffer.  The builder blocks here while
        # the executor still owns the previous batch, which is exactly when
        # the pending queue should keep filling.
        self._built_q: "queue.Queue[Optional[_BuiltBatch]]" = queue.Queue(maxsize=1)
        self._recent: deque = deque(maxlen=RECENT_BATCHES)
        self.batches_total = 0
        self.groups_total = 0
        self.sets_total = 0
        self._builder = threading.Thread(
            target=self._build_loop, name=f"device-pipeline-build-{op}", daemon=True
        )
        self._executor = threading.Thread(
            target=self._execute_loop, name=f"device-pipeline-exec-{op}", daemon=True
        )
        self._builder.start()
        self._executor.start()

    # ------------------------------------------------------------- ingress

    def submit(self, sets, work: Optional[str] = None,
               ) -> VerifyFuture:
        """Queue one group; returns its future.  Raises
        :class:`PipelineShutdown` after :meth:`shutdown`."""
        sets = list(sets)
        work = work or current_work_kind()
        fut = VerifyFuture(work, len(sets))
        if not sets:
            fut.set_result(False)  # empty batch fails (host-backend parity)
            return fut
        with self._cond:
            if self._shutdown:
                raise PipelineShutdown(f"{self.op}: pipeline is shut down")
            self._pending.append(_Group(sets, fut))
            self._pending_sets += len(sets)
            self.groups_total += 1
            self.sets_total += len(sets)
            self._idle.clear()
            metrics.DEVICE_PIPELINE_PENDING_SETS.set(self._pending_sets, op=self.op)
            metrics.DEVICE_PIPELINE_DEPTH.set(
                len(self._pending) + self._in_flight_groups, op=self.op)
            self._cond.notify_all()
        metrics.DEVICE_PIPELINE_GROUPS.inc(op=self.op, work=work)
        # submit marker in the caller's trace: the submit→resolve interval is
        # recorded by verify() as the pipeline_wait span.
        tracing.annotate(pipeline_submitted=True, pipeline_work=work)
        return fut

    def verify(self, sets, work: Optional[str] = None) -> bool:
        """Submit + block on the verdict (the drop-in form the bls api seam
        uses).  The caller's thread waits on a cheap event — never inside
        ``block_until_ready``."""
        fut = self.submit(sets, work=work)
        try:
            ok = fut.result()
        finally:
            tracing.record_span(
                "pipeline_wait", start_pc=fut.submitted_pc,
                hist=metrics.DEVICE_PIPELINE_WAIT_SECONDS,
                hist_labels={"op": self.op},
                n_sets=fut.n_sets, work=fut.work,
            )
        return ok

    # ------------------------------------------------------------- builder

    def _effective_target(self) -> int:
        """The coalescing target scaled to the CURRENT mesh: a mesh shrunk
        by per-device breaker trips fills proportionally fewer lanes, so
        waiting for the full-strength target would only add linger latency
        (identity when the mesh is off or at full strength)."""
        from . import device_mesh

        return device_mesh.scale_target(self.target_sets)

    def _take_batch(self) -> Optional[List[_Group]]:
        """Block until a batch is worth dispatching (target fill reached, the
        oldest group's linger expired, or shutdown-drain); pop and return it.
        Returns None only when shut down AND drained."""
        with self._cond:
            while True:
                target = self._effective_target()
                if self._pending:
                    if self._shutdown or self._pending_sets >= target:
                        break
                    oldest = self._pending[0].future.submitted_pc
                    remaining = self.linger_s - (time.perf_counter() - oldest)
                    if remaining <= 0:
                        break
                    self._cond.wait(timeout=min(remaining, 0.05))
                elif self._shutdown:
                    return None
                else:
                    self._cond.wait(timeout=0.1)
            groups: List[_Group] = []
            n_sets = 0
            while self._pending:
                g = self._pending[0]
                if groups and n_sets + len(g.sets) > target:
                    break
                self._pending.popleft()
                groups.append(g)
                n_sets += len(g.sets)
            self._pending_sets -= n_sets
            self._in_flight_groups += len(groups)
            metrics.DEVICE_PIPELINE_PENDING_SETS.set(self._pending_sets, op=self.op)
            return groups

    def _build_loop(self) -> None:
        while True:
            try:
                groups = self._take_batch()
            except Exception:
                log.error("pipeline builder take failed", exc_info=True)
                continue
            if groups is None:
                self._built_q.put(None)  # drained: wake + stop the executor
                return
            oldest = min(g.future.submitted_pc for g in groups)
            linger = max(0.0, time.perf_counter() - oldest)
            flat = [s for g in groups for s in g.sets]
            work_mix: Dict[str, int] = {}
            for g in groups:
                work_mix[g.future.work] = work_mix.get(g.future.work, 0) + len(g.sets)
            built = None
            unbuilt = False
            t0 = time.perf_counter()
            if self._device_mode():
                try:
                    with tracing.span("pipeline_build", n_sets=len(flat),
                                      n_groups=len(groups)):
                        from .ops import verify as verify_mod

                        built = verify_mod.build_device_batch(flat)
                except Exception:
                    # Marshalling itself failed (device OOM mid-upload, a
                    # malformed point, ...): the executor resolves EVERY
                    # group on the host model — a build error must never be
                    # reported as a bad signature.
                    log.warning("pipeline batch build failed; groups resolve "
                                "on the host model", exc_info=True)
                unbuilt = built is None
            self._built_q.put(_BuiltBatch(
                groups, flat, built, unbuilt, linger,
                time.perf_counter() - t0, work_mix,
            ))

    # ------------------------------------------------------------ executor

    def _device_mode(self) -> bool:
        """True when the batch should run the staged device path (jax
        backend); host/fake backends run their own verify over the flat
        batch instead — same coalescing, no device."""
        if self._verify_flat_fn is not None:
            return False
        from .crypto.bls.backends import backend_name

        return backend_name() == "jax"

    def _verify_flat(self, batch: _BuiltBatch) -> bool:
        if self._verify_flat_fn is not None:
            return bool(self._verify_flat_fn(batch.flat_sets))
        from .crypto.bls.backends import backend_name, get_backend

        if backend_name() == "jax":
            from .ops import verify as verify_mod

            # unbuilt batches never reach here (_execute_one re-checks
            # every group on the host instead)
            return verify_mod.execute_built_batch(
                batch.built, n_groups=len(batch.groups), work_mix=batch.work_mix
            )
        return bool(get_backend().verify_signature_sets(batch.flat_sets))

    def _recheck_group(self, sets: list) -> bool:
        """ONE host re-check per group — the per-group verdict attribution
        on a failed coalesced batch."""
        if self._recheck_fn is not None:
            return bool(self._recheck_fn(sets))
        from .crypto.bls.backends import backend_name

        if backend_name() == "fake":
            from .crypto.bls.backends import fake

            return bool(fake.verify_signature_sets(sets))
        from .crypto.bls.backends import host

        return bool(host.verify_signature_sets(sets))

    def _execute_loop(self) -> None:
        while True:
            batch = self._built_q.get()
            if batch is None:
                with self._cond:
                    if not self._pending and self._in_flight_groups == 0:
                        self._idle.set()
                return
            try:
                self._execute_one(batch)
            except Exception as err:  # noqa: BLE001 — marshalled to futures
                log.error("pipeline batch execution failed",
                          op=self.op, error=f"{type(err).__name__}: {err}")
                for g in batch.groups:
                    g.future.set_error(err)
            finally:
                with self._cond:
                    self._in_flight_groups -= len(batch.groups)
                    metrics.DEVICE_PIPELINE_DEPTH.set(
                        len(self._pending) + self._in_flight_groups, op=self.op)
                    if (not self._pending and self._in_flight_groups == 0
                            and self._built_q.empty()):
                        self._idle.set()
                    self._cond.notify_all()

    def _execute_one(self, batch: _BuiltBatch) -> None:
        n_sets = len(batch.flat_sets)
        fill = min(1.0, n_sets / self.target_sets)
        metrics.DEVICE_PIPELINE_BATCHES.inc(op=self.op)
        metrics.DEVICE_PIPELINE_BATCH_FILL_RATIO.observe(fill, op=self.op)
        metrics.DEVICE_PIPELINE_LINGER_SECONDS.observe(batch.linger_s, op=self.op)
        with tracing.span(
            "pipeline_batch", op=self.op, n_sets=n_sets,
            n_groups=len(batch.groups), fill_ratio=round(fill, 4),
            linger_s=round(batch.linger_s, 6), work_mix=dict(batch.work_mix),
        ):
            rechecked = 0
            if batch.unbuilt:
                # No device batch exists (build failed or host-side
                # validation said False): EVERY group — lone ones included —
                # gets its own host re-check, so a transient build error
                # can never surface as "bad signature".
                tracing.annotate(group_recheck=True, unbuilt=True)
                verdict = True
                for g in batch.groups:
                    rechecked += 1
                    ok = self._recheck_group(g.sets)
                    verdict = verdict and ok
                    g.future.set_result(ok)
            else:
                verdict = self._verify_flat(batch)
                if verdict:
                    for g in batch.groups:
                        g.future.set_result(True)
                elif len(batch.groups) == 1:
                    # a single-group batch IS its own attribution
                    batch.groups[0].future.set_result(False)
                else:
                    tracing.annotate(group_recheck=True)
                    for g in batch.groups:
                        rechecked += 1
                        g.future.set_result(self._recheck_group(g.sets))
        self.batches_total += 1
        self._recent.append({
            "t_ms": int(time.time() * 1000),
            "n_sets": n_sets,
            "n_groups": len(batch.groups),
            "fill_ratio": round(fill, 4),
            "linger_s": round(batch.linger_s, 6),
            "build_s": round(batch.build_s, 6),
            "work_mix": dict(batch.work_mix),
            "verdict": bool(verdict),
            "group_rechecks": rechecked,
        })

    # ------------------------------------------------------------- control

    def wait_idle(self, timeout: float = 10.0) -> bool:
        """Block until no group is pending or in flight."""
        return self._idle.wait(timeout)

    def shutdown(self, timeout: float = 30.0) -> None:
        """Drain: pending groups still execute (possibly as smaller final
        batches) and every future resolves; then both threads exit."""
        with self._cond:
            if self._shutdown:
                return
            self._shutdown = True
            self._cond.notify_all()
        self._builder.join(timeout=timeout)
        self._executor.join(timeout=timeout)
        # anything still unresolved (thread died / join timed out) must not
        # hang callers forever
        with self._cond:
            leftovers = list(self._pending)
            self._pending.clear()
            self._pending_sets = 0
        for g in leftovers:
            if not g.future.done():
                g.future.set_error(PipelineShutdown(
                    f"{self.op}: pipeline shut down before this group ran"))

    def snapshot(self) -> dict:
        with self._cond:
            pending_groups = len(self._pending)
            pending_sets = self._pending_sets
            in_flight = self._in_flight_groups
        return {
            "op": self.op,
            "target_sets": self.target_sets,
            # identical to target_sets unless the device mesh is degraded
            # (device_mesh.scale_target shrinks the fill target with it)
            "effective_target_sets": self._effective_target(),
            "linger_s": self.linger_s,
            "pending_groups": pending_groups,
            "pending_sets": pending_sets,
            "in_flight_groups": in_flight,
            "batches_total": self.batches_total,
            "groups_total": self.groups_total,
            "sets_total": self.sets_total,
            "recent_batches": list(self._recent),
        }


# ----------------------------------------------------------- module wiring

_LOCK = threading.Lock()
_PIPELINE: Optional[DevicePipeline] = None
_ENABLED = os.environ.get("LIGHTHOUSE_TPU_DEVICE_PIPELINE", "") == "1"


def get_pipeline() -> DevicePipeline:
    """The process-wide bls_verify pipeline (lazily started)."""
    global _PIPELINE
    with _LOCK:
        if _PIPELINE is None:
            _PIPELINE = DevicePipeline("bls_verify")
        return _PIPELINE


def enabled() -> bool:
    return _ENABLED


def enable() -> None:
    """Route ``bls.verify_signature_sets`` through the pipeline (the
    ``ClientBuilder`` calls this for jax-backend nodes; tests/scenarios call
    it explicitly).  ``LIGHTHOUSE_TPU_DEVICE_PIPELINE=0`` wins over callers."""
    global _ENABLED
    if os.environ.get("LIGHTHOUSE_TPU_DEVICE_PIPELINE", "") == "0":
        return
    _ENABLED = True


def disable() -> None:
    global _ENABLED
    _ENABLED = False


def routes(sets: list, seed) -> bool:
    """Should this verify_signature_sets call ride the pipeline?  Explicit
    seeds (reproducibility contracts) and oversized batches keep the direct
    path; so does everything when the pipeline is off."""
    return (
        _ENABLED
        and seed is None
        and 0 < len(sets) <= MAX_GROUP_SETS
    )


def verify(sets: list) -> bool:
    """The api-seam entry: resolve the live pipeline WITHOUT resurrecting
    one that a racing ``shutdown()`` just tore down — a caller already past
    ``routes()`` must fall back to the direct path (the api seam catches
    :class:`PipelineShutdown`), not leak a fresh thread pair post-stop."""
    global _PIPELINE
    with _LOCK:
        pipe = _PIPELINE
        if pipe is None:
            if not _ENABLED:
                raise PipelineShutdown("pipeline disabled mid-call")
            pipe = _PIPELINE = DevicePipeline("bls_verify")
    return pipe.verify(sets)


def summary() -> Optional[dict]:
    """The pipeline section of ``GET /lighthouse/device`` (None until the
    pipeline has been started)."""
    with _LOCK:
        pipe = _PIPELINE
    if pipe is None:
        return None
    return pipe.snapshot()


def shutdown(timeout: float = 30.0) -> None:
    """Disable routing and drain the process pipeline (Client.stop).  New
    verify calls fall back to the direct backend path immediately; in-flight
    futures still resolve."""
    global _PIPELINE
    disable()
    with _LOCK:
        pipe, _PIPELINE = _PIPELINE, None
    if pipe is not None:
        pipe.shutdown(timeout=timeout)


def reset_for_tests() -> None:
    shutdown(timeout=5.0)
