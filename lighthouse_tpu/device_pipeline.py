"""Async device pipeline: a persistent device-worker queue that decouples the
scheduler from the device (ROADMAP item 3).

Before this module, every caller of ``bls.verify_signature_sets`` — block
import, a drained gossip attestation batch, a sync-committee contribution —
blocked its own thread for the full dispatch+wait of its own batch, and the
scheduler could only coalesce events from a single queue class.  Real traffic
therefore dispatched many small, latency-dominated device batches while the
4096-set standard bucket (PR 6) sat empty.

This module inverts that: callers **submit** a group of ``SignatureSet``\\ s
and immediately receive a :class:`VerifyFuture`; one long-lived pipeline per
op owns the device and

- **coalesces** pending groups *across work types* (block import + gossip
  attestations + aggregates + sync committee + API batches) into one maximal
  pairing batch, targeting the standard device bucket, with a small linger
  window so a lone attestation never waits forever;
- **double-buffers** host-side batch building against in-flight device
  execution: a builder thread marshals batch N+1 (``ops/verify.py``
  ``build_device_batch`` — validation, hash-to-curve, limb packing) while the
  executor thread is still waiting on batch N, handing off through a depth-1
  queue.  While the device is busy the pending queue keeps filling, so device
  latency itself widens the next batch (the natural-backpressure fill
  mechanism);
- **dispatches through the device supervisor** (``device_supervisor.py``):
  watchdog, split-retry and circuit-breaker semantics are exactly those of
  the direct path — a breaker-OPEN op routes the coalesced batch to the host
  golden model and the futures still resolve;
- **attributes verdicts per group**: a passing batch resolves every group
  True; a failing (or host-disclaimed) batch re-checks each group once on the
  host golden model so only the actually-bad group fails — one host re-check
  per group, never per set.

The enrolment seam is ``crypto/bls/api.verify_signature_sets`` — the one
funnel every signature in the system already flows through — so enabling the
pipeline (``ClientBuilder.build`` does, for the jax backend) streams ALL
device-bound verification through one seam without touching any caller.
Callers that pin ``seed=`` (reproducibility tests) or exceed the standard
bucket bypass the pipeline and keep their exact semantics.

Beyond bls_verify (the module's originally declared remaining scope, now
landed): **sha256_pairs** and the **epoch ops** dispatch through here too,
so block import, epoch boundaries and tree-hash traffic contend for the
device through ONE arbiter (:class:`DeviceArbiter` — every pipelined
dispatch acquires the shared slot, so "who is holding the device" is one
scrape away):

- :class:`HashPipeline` coalesces pair-hash groups (``ops/tree_hash.py``
  dirty-path batches, Merkle layer builds) into one ``sha256_pairs``
  dispatch and slices the digests back per group — 64-byte blocks are
  independent, so attribution is exact by construction; a batch that fails
  outside the supervisor's own fallback re-hashes per group on the host
  kernel, so a transient error can never corrupt a group's digest.
- :class:`JobPipeline` runs registry-wide jobs (``epoch_deltas[_leak]`` —
  batch-global sums, nothing to coalesce) FIFO under the same arbiter; the
  supervisor inside the job keeps breaker-open host routing exact.

**Adaptive linger** (the self-tuning slice of ROADMAP item 2): unless
pinned (env ``LIGHTHOUSE_TPU_PIPELINE_LINGER_S`` or an explicit
``linger_s``), the effective linger follows the flight recorder's observed
in-flight batch duration (``device_telemetry.recent_inflight_seconds``) —
while a batch is in flight the pending queue fills for free, so lingering
~half the in-flight time buys fill at zero throughput cost.  The snapshot
exposes ``effective_linger_s`` next to the configured base.

Observability: ``device_pipeline_{pending_sets,depth,batch_fill_ratio,
linger_seconds,wait_seconds,batches_total,groups_total}`` metrics, a
``pipeline_batch`` trace root per coalesced dispatch (submit→coalesce→
dispatch→resolve via ``pipeline_submit``/``pipeline_wait`` child spans in the
caller's trace), flight-recorder records carrying ``n_groups``/``work_mix``,
and a ``summary()`` section on ``GET /lighthouse/device``.
"""

from __future__ import annotations

import contextvars
import os
import queue
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Any, Callable, Dict, List, Optional

from . import blackbox, locksmith, metrics, tracing
from .logs import get_logger
from .scheduler.work import STANDARD_DEVICE_BATCH

log = get_logger("device_pipeline")

#: Groups larger than this bypass the pipeline entirely (the direct path
#: chunks them through the standard bucket itself).  The scheduler's
#: standard device batch, clamped to the device's single-dispatch ceiling
#: (ops/verify.MAX_SETS_PER_DISPATCH == 4096 — kept as a literal here so
#: importing the pipeline never pulls jax, same convention as work.py):
#: a raised LIGHTHOUSE_TPU_STANDARD_BATCH must not let the pipeline build
#: batches the device entry point refuses.
MAX_GROUP_SETS = min(STANDARD_DEVICE_BATCH, 4096)

#: Default linger: how long the builder waits for more groups once the FIRST
#: pending group is older than this and the target bucket is not yet full.
#: Small on purpose — while a batch is in flight the pending queue fills for
#: free; the linger only bounds the latency of a lone set on an idle device.
DEFAULT_LINGER_S = float(os.environ.get("LIGHTHOUSE_TPU_PIPELINE_LINGER_S", "0.02"))

#: Default coalescing target (sets per dispatched batch).
DEFAULT_TARGET_SETS = int(
    os.environ.get("LIGHTHOUSE_TPU_PIPELINE_TARGET_SETS", str(STANDARD_DEVICE_BATCH))
)

#: Bounded ring of recent per-batch summaries for summary()/tests.
RECENT_BATCHES = 64

#: Hash groups larger than this many 64-byte blocks bypass the hash
#: pipeline (the direct supervised op buckets them itself).  The top
#: ``ops/sha256_device.N_BUCKETS`` bucket, kept as a literal so importing
#: the pipeline never pulls jax (same convention as MAX_GROUP_SETS).
MAX_HASH_GROUP_BLOCKS = 262144

#: Default coalescing target for the hash pipeline (blocks per dispatched
#: sha256_pairs batch).
DEFAULT_HASH_TARGET_BLOCKS = int(
    os.environ.get("LIGHTHOUSE_TPU_PIPELINE_HASH_TARGET_BLOCKS", "16384")
)

#: Adaptive linger clamps: the effective linger never exceeds the MAX (a
#: pathological in-flight observation must not park gossip for seconds) and
#: tracks ``FRACTION`` of the observed in-flight batch duration.
ADAPTIVE_LINGER_MAX_S = 0.25
ADAPTIVE_LINGER_FRACTION = 0.5

#: An explicit env linger pins every pipeline (the operator override the
#: adaptive default must never fight).
_LINGER_ENV_PINNED = "LIGHTHOUSE_TPU_PIPELINE_LINGER_S" in os.environ

# Injectable linger clock (ISSUE 20): how long a pending group has lingered
# is a control-path decision — during a scenario it runs on the virtual
# clock so batch cut points sit at virtual instants, not wall instants.
# Telemetry spans (pipeline_wait, batch linger observations) deliberately
# stay on ``submitted_pc``/``time.perf_counter``: an operator reading them
# wants real latency.
_linger_clock: Callable[[], float] = time.perf_counter


def set_linger_clock(fn: Optional[Callable[[], float]] = None) -> None:
    global _linger_clock
    # process-boundary: ok(clock seam: harness-only install, restored in _cleanup)
    _linger_clock = fn if fn is not None else time.perf_counter


def effective_linger(op: str, base_s: float, pinned: bool) -> float:
    """The linger actually applied to the next coalescing decision:
    ``base_s`` when pinned or unobserved, else ~half the flight recorder's
    median in-flight batch duration for ``op`` (clamped; never below the
    configured base — a fast device should not erase the floor)."""
    if pinned:
        return base_s
    from . import device_telemetry

    observed = device_telemetry.recent_inflight_seconds(op)
    if observed is None:
        return base_s
    return max(base_s, min(ADAPTIVE_LINGER_MAX_S,
                           observed * ADAPTIVE_LINGER_FRACTION))


# ------------------------------------------------------------- the arbiter

DEVICE_ARBITER_WAIT_SECONDS = metrics.histogram(
    "device_arbiter_wait_seconds",
    "wait to acquire the shared device-dispatch arbiter slot, by op",
)
DEVICE_ARBITER_GRANTS = metrics.counter(
    "device_arbiter_grants_total",
    "device-dispatch slots granted by the shared pipeline arbiter, by op",
)
DEVICE_ARBITER_API_TIMEOUTS = metrics.counter(
    "device_arbiter_api_timeouts_total",
    "API-side arbiter acquisitions that timed out and proceeded ungated, "
    "by op",
)


class DeviceArbiter:
    """THE device-access gate for pipelined dispatch: every pipeline
    (bls_verify batches, sha256_pairs hash batches, epoch jobs) acquires
    one shared slot around its device leg, so concurrent work types
    *contend here* — visibly (`device_arbiter_wait_seconds{op}`) — instead
    of interleaving dispatches blindly.  Direct (non-pipelined) callers are
    deliberately not gated: their semantics predate the pipeline and the
    supervisor already serializes per-op dispatch through its worker."""

    def __init__(self) -> None:
        self._lock = locksmith.lock("DeviceArbiter._lock")
        self._stats = locksmith.lock("DeviceArbiter._stats")
        self._grants: Dict[str, int] = {}
        self._wait_s: Dict[str, float] = {}
        self._holder: Optional[str] = None

    @contextmanager
    def slot(self, op: str):
        t0 = time.perf_counter()
        with self._lock:
            wait = time.perf_counter() - t0
            with self._stats:
                self._grants[op] = self._grants.get(op, 0) + 1
                self._wait_s[op] = self._wait_s.get(op, 0.0) + wait
                self._holder = op
            DEVICE_ARBITER_WAIT_SECONDS.observe(wait, op=op)
            DEVICE_ARBITER_GRANTS.inc(op=op)
            try:
                yield
            finally:
                with self._stats:
                    self._holder = None

    @contextmanager
    def api_slot(self, op: str, timeout: float = 2.0, hold: bool = True):
        """Arbiter contention for a NON-pipelined caller — the HTTP API's
        cache-miss state queries (ROADMAP item 4 REMAINING: API work must
        stop bypassing the arbiter).  Differs from :meth:`slot` in two
        deliberate ways:

        - ``timeout``-bounded acquire: an API thread that cannot get the
          slot proceeds UNGATED (counted on
          ``device_arbiter_api_timeouts_total``) instead of deadlocking —
          a read query is never worth wedging the serving thread pool.
        - ``hold=False`` runs the body AFTER releasing the slot (a
          turnstile): the caller waits its turn behind in-flight device
          dispatch, but does not exclude the pipelines while its own body
          runs.  Required whenever the body may submit pipeline jobs
          (``run_job`` legs acquire the slot from the pipeline worker —
          holding here while waiting on their futures is a deadlock)."""
        t0 = time.perf_counter()
        acquired = self._lock.acquire(timeout=timeout)
        wait = time.perf_counter() - t0
        if not acquired:
            DEVICE_ARBITER_API_TIMEOUTS.inc(op=op)
            yield
            return
        with self._stats:
            self._grants[op] = self._grants.get(op, 0) + 1
            self._wait_s[op] = self._wait_s.get(op, 0.0) + wait
            self._holder = op
        DEVICE_ARBITER_WAIT_SECONDS.observe(wait, op=op)
        DEVICE_ARBITER_GRANTS.inc(op=op)
        if not hold:
            with self._stats:
                self._holder = None
            self._lock.release()
            yield
            return
        try:
            yield
        finally:
            with self._stats:
                self._holder = None
            self._lock.release()

    def snapshot(self) -> dict:
        with self._stats:
            return {
                "holding": self._holder,
                "grants": dict(self._grants),
                "wait_s": {k: round(v, 6) for k, v in self._wait_s.items()},
            }

    def reset_for_tests(self) -> None:
        with self._stats:
            self._grants.clear()
            self._wait_s.clear()
            self._holder = None


ARBITER = DeviceArbiter()

_WORK_KIND: "contextvars.ContextVar[Optional[str]]" = contextvars.ContextVar(
    "lighthouse_tpu_pipeline_work_kind", default=None
)


@contextmanager
def work_context(kind: str):
    """Tag pipeline submissions made inside this context with ``kind`` (the
    ``work_mix`` attribution on coalesced batches)."""
    token = _WORK_KIND.set(kind)
    try:
        yield
    finally:
        _WORK_KIND.reset(token)


def current_work_kind() -> str:
    return _WORK_KIND.get() or "other"


class PipelineShutdown(RuntimeError):
    """The pipeline was shut down without draining this group."""


class _FutureBase:
    """Resolution handle for one submitted unit of pipeline work: the one
    Event/result/error pattern every pipeline shares (verify groups, hash
    groups, epoch jobs differ only in payload fields and result type)."""

    __slots__ = ("_done", "_result", "_error", "submitted_pc",
                 "submitted_lc", "work")

    #: result(timeout) message on expiry; subclasses name their unit.
    _timeout_msg = "pipeline result not available in time"

    def __init__(self, work: str):
        self._done = threading.Event()
        self._result = None
        self._error: Optional[BaseException] = None
        # submitted_pc: real perf_counter, telemetry spans only.
        # submitted_lc: the linger clock's reading, the coalescing
        # decision's time base (virtual during scenarios).
        self.submitted_pc = time.perf_counter()
        self.submitted_lc = _linger_clock()
        self.work = work

    def done(self) -> bool:
        return self._done.is_set()

    def set_result(self, value) -> None:
        self._result = value
        self._done.set()

    def set_error(self, err: BaseException) -> None:
        self._error = err
        self._done.set()

    def result(self, timeout: Optional[float] = None):
        """Block until resolution; raises the pipeline's error if the work
        failed outside the op's own semantics."""
        if not self._done.wait(timeout):
            raise TimeoutError(self._timeout_msg)
        if self._error is not None:
            raise self._error
        return self._result


class VerifyFuture(_FutureBase):
    """Resolution handle for one submitted group (bool verdict out)."""

    __slots__ = ("n_sets",)
    _timeout_msg = "pipeline verdict not available in time"

    def __init__(self, work: str, n_sets: int):
        super().__init__(work)
        self.n_sets = n_sets

    def set_result(self, value) -> None:
        super().set_result(bool(value))

    def result(self, timeout: Optional[float] = None) -> bool:
        return bool(super().result(timeout))


class _Group:
    __slots__ = ("sets", "future")

    def __init__(self, sets: list, future: VerifyFuture):
        self.sets = sets
        self.future = future


class _BuiltBatch:
    """One coalesced batch on its way to the executor."""

    __slots__ = ("groups", "flat_sets", "built", "unbuilt",
                 "linger_s", "build_s", "work_mix")

    def __init__(self, groups: List[_Group], flat_sets: list, built,
                 unbuilt: bool, linger_s: float, build_s: float,
                 work_mix: Dict[str, int]):
        self.groups = groups
        self.flat_sets = flat_sets
        self.built = built          # ops.verify.BuiltBatch | None (host modes)
        #: device mode only: the build stage produced no device batch (a
        #: marshalling error OR host-side validation deciding False) — the
        #: batch verdict is not trustworthy as a signature verdict, so EVERY
        #: group (even a lone one) resolves via its own host re-check.
        self.unbuilt = unbuilt
        self.linger_s = linger_s
        self.build_s = build_s
        self.work_mix = work_mix


class DevicePipeline:
    """One persistent device-worker pipeline for one op (``bls_verify``).

    ``verify_flat_fn``: test seam — replaces the whole batch-execution leg
    (called with the flat set list, returns the combined verdict).
    ``recheck_fn``: test seam — replaces the per-group host re-check.
    """

    def __init__(self, op: str = "bls_verify", *,
                 target_sets: Optional[int] = None,
                 linger_s: Optional[float] = None,
                 verify_flat_fn=None, recheck_fn=None):
        self.op = op
        # clamped to the single-dispatch ceiling: one coalesced batch must
        # stay buildable by ops/verify.build_device_batch
        self.target_sets = max(1, min(int(target_sets or DEFAULT_TARGET_SETS),
                                      MAX_GROUP_SETS))
        # an explicit linger (ctor arg, later assignment, or the env var)
        # PINS the value; otherwise the effective linger adapts to the
        # observed in-flight batch duration (see effective_linger)
        self._linger_pinned = linger_s is not None or _LINGER_ENV_PINNED
        self._linger_s = (DEFAULT_LINGER_S if linger_s is None
                          else float(linger_s))
        self._verify_flat_fn = verify_flat_fn
        self._recheck_fn = recheck_fn
        self._cond = locksmith.condition("DevicePipeline._cond")
        self._pending: deque = deque()          # _Group FIFO
        self._pending_sets = 0
        self._in_flight_groups = 0              # taken but not yet resolved
        self._shutdown = False
        self._idle = threading.Event()
        self._idle.set()
        # depth-1 handoff: the double buffer.  The builder blocks here while
        # the executor still owns the previous batch, which is exactly when
        # the pending queue should keep filling.
        self._built_q: "queue.Queue[Optional[_BuiltBatch]]" = queue.Queue(maxsize=1)
        self._recent: deque = deque(maxlen=RECENT_BATCHES)
        self.batches_total = 0
        self.groups_total = 0
        self.sets_total = 0
        self._builder = threading.Thread(
            target=self._build_loop, name=f"device-pipeline-build-{op}", daemon=True
        )
        self._executor = threading.Thread(
            target=self._execute_loop, name=f"device-pipeline-exec-{op}", daemon=True
        )
        self._builder.start()
        self._executor.start()

    # ------------------------------------------------------------- ingress

    def submit(self, sets, work: Optional[str] = None,
               ) -> VerifyFuture:
        """Queue one group; returns its future.  Raises
        :class:`PipelineShutdown` after :meth:`shutdown`."""
        sets = list(sets)
        work = work or current_work_kind()
        fut = VerifyFuture(work, len(sets))
        if not sets:
            fut.set_result(False)  # empty batch fails (host-backend parity)
            return fut
        with self._cond:
            if self._shutdown:
                raise PipelineShutdown(f"{self.op}: pipeline is shut down")
            self._pending.append(_Group(sets, fut))
            self._pending_sets += len(sets)
            self.groups_total += 1
            self.sets_total += len(sets)
            self._idle.clear()
            metrics.DEVICE_PIPELINE_PENDING_SETS.set(self._pending_sets, op=self.op)
            metrics.DEVICE_PIPELINE_DEPTH.set(
                len(self._pending) + self._in_flight_groups, op=self.op)
            self._cond.notify_all()
        metrics.DEVICE_PIPELINE_GROUPS.inc(op=self.op, work=work)
        # submit marker in the caller's trace: the submit→resolve interval is
        # recorded by verify() as the pipeline_wait span.
        tracing.annotate(pipeline_submitted=True, pipeline_work=work)
        return fut

    def verify(self, sets, work: Optional[str] = None) -> bool:
        """Submit + block on the verdict (the drop-in form the bls api seam
        uses).  The caller's thread waits on a cheap event — never inside
        ``block_until_ready``."""
        fut = self.submit(sets, work=work)
        try:
            ok = fut.result()
        finally:
            tracing.record_span(
                "pipeline_wait", start_pc=fut.submitted_pc,
                hist=metrics.DEVICE_PIPELINE_WAIT_SECONDS,
                hist_labels={"op": self.op},
                n_sets=fut.n_sets, work=fut.work,
            )
        return ok

    # ------------------------------------------------------------- builder

    @property
    def linger_s(self) -> float:
        return self._linger_s

    @linger_s.setter
    def linger_s(self, value: float) -> None:
        # assigning a linger anywhere (tests, scenarios, bench) pins it —
        # the adaptive default must never fight an explicit choice
        self._linger_s = float(value)
        self._linger_pinned = True

    def _effective_linger(self) -> float:
        return effective_linger(self.op, self._linger_s, self._linger_pinned)

    def _effective_target(self) -> int:
        """The coalescing target scaled to the CURRENT mesh: a mesh shrunk
        by per-device breaker trips fills proportionally fewer lanes, so
        waiting for the full-strength target would only add linger latency
        (identity when the mesh is off or at full strength)."""
        from . import device_mesh

        return device_mesh.scale_target(self.target_sets)

    def _take_batch(self) -> Optional[List[_Group]]:
        """Block until a batch is worth dispatching (target fill reached, the
        oldest group's linger expired, or shutdown-drain); pop and return it.
        Returns None only when shut down AND drained."""
        with self._cond:
            # sampled once per take, at the moment the first group is seen:
            # the adaptive signal only moves when a batch completes, so
            # recomputing it (a flight-recorder scan) on every 50ms
            # wait-loop wake under the lock is wasted work — but sampling
            # at take ENTRY would bake a pre-pin value into a worker that
            # was already parked on an empty queue when a test/scenario
            # assigned linger_s
            linger = None
            frozen = 0
            while True:
                target = self._effective_target()
                if self._pending:
                    if self._shutdown or self._pending_sets >= target:
                        break
                    if linger is None:
                        linger = self._effective_linger()
                    now_lc = _linger_clock()
                    oldest = self._pending[0].future.submitted_lc
                    remaining = linger - (now_lc - oldest)
                    # a reading BEHIND the stamp means the group straddled
                    # a clock install/restore: dispatch rather than trust
                    # cross-clock arithmetic
                    if remaining <= 0 or now_lc < oldest:
                        break
                    # Stall-breaker: a linger clock frozen across
                    # consecutive waits means the thread that advances it
                    # (a virtual clock's runner) is blocked on one of OUR
                    # futures — dispatch now instead of deadlocking.  A
                    # wall clock always advances, so production coalescing
                    # is untouched.
                    if frozen >= 2:
                        break
                    self._cond.wait(timeout=min(remaining, 0.05))
                    frozen = frozen + 1 if _linger_clock() == now_lc else 0
                elif self._shutdown:
                    return None
                else:
                    self._cond.wait(timeout=0.1)
            groups: List[_Group] = []
            n_sets = 0
            while self._pending:
                g = self._pending[0]
                if groups and n_sets + len(g.sets) > target:
                    break
                self._pending.popleft()
                groups.append(g)
                n_sets += len(g.sets)
            self._pending_sets -= n_sets
            self._in_flight_groups += len(groups)
            metrics.DEVICE_PIPELINE_PENDING_SETS.set(self._pending_sets, op=self.op)
            return groups

    def _build_loop(self) -> None:
        while True:
            try:
                groups = self._take_batch()
            except Exception:
                log.error("pipeline builder take failed", exc_info=True)
                continue
            if groups is None:
                self._built_q.put(None)  # drained: wake + stop the executor
                return
            oldest = min(g.future.submitted_pc for g in groups)
            linger = max(0.0, time.perf_counter() - oldest)
            flat = [s for g in groups for s in g.sets]
            work_mix: Dict[str, int] = {}
            for g in groups:
                work_mix[g.future.work] = work_mix.get(g.future.work, 0) + len(g.sets)
            built = None
            unbuilt = False
            t0 = time.perf_counter()
            if self._device_mode():
                try:
                    with tracing.span("pipeline_build", n_sets=len(flat),
                                      n_groups=len(groups)):
                        from .ops import verify as verify_mod

                        built = verify_mod.build_device_batch(flat)
                except Exception:
                    # Marshalling itself failed (device OOM mid-upload, a
                    # malformed point, ...): the executor resolves EVERY
                    # group on the host model — a build error must never be
                    # reported as a bad signature.
                    log.warning("pipeline batch build failed; groups resolve "
                                "on the host model", exc_info=True)
                unbuilt = built is None
            self._built_q.put(_BuiltBatch(
                groups, flat, built, unbuilt, linger,
                time.perf_counter() - t0, work_mix,
            ))

    # ------------------------------------------------------------ executor

    def _device_mode(self) -> bool:
        """True when the batch should run the staged device path (jax
        backend); host/fake backends run their own verify over the flat
        batch instead — same coalescing, no device."""
        if self._verify_flat_fn is not None:
            return False
        from .crypto.bls.backends import backend_name

        return backend_name() == "jax"

    def _verify_flat(self, batch: _BuiltBatch) -> bool:
        if self._verify_flat_fn is not None:
            return bool(self._verify_flat_fn(batch.flat_sets))
        from .crypto.bls.backends import backend_name, get_backend

        if backend_name() == "jax":
            from .ops import verify as verify_mod

            # unbuilt batches never reach here (_execute_one re-checks
            # every group on the host instead)
            return verify_mod.execute_built_batch(
                batch.built, n_groups=len(batch.groups), work_mix=batch.work_mix
            )
        return bool(get_backend().verify_signature_sets(batch.flat_sets))

    def _recheck_group(self, sets: list) -> bool:
        """ONE host re-check per group — the per-group verdict attribution
        on a failed coalesced batch."""
        if self._recheck_fn is not None:
            return bool(self._recheck_fn(sets))
        from .crypto.bls.backends import backend_name

        if backend_name() == "fake":
            from .crypto.bls.backends import fake

            return bool(fake.verify_signature_sets(sets))
        from .crypto.bls.backends import host

        return bool(host.verify_signature_sets(sets))

    def _execute_loop(self) -> None:
        while True:
            batch = self._built_q.get()
            if batch is None:
                with self._cond:
                    if not self._pending and self._in_flight_groups == 0:
                        self._idle.set()
                return
            try:
                self._execute_one(batch)
            except Exception as err:  # noqa: BLE001 — marshalled to futures
                log.error("pipeline batch execution failed",
                          op=self.op, error=f"{type(err).__name__}: {err}")
                for g in batch.groups:
                    g.future.set_error(err)
            finally:
                with self._cond:
                    self._in_flight_groups -= len(batch.groups)
                    metrics.DEVICE_PIPELINE_DEPTH.set(
                        len(self._pending) + self._in_flight_groups, op=self.op)
                    if (not self._pending and self._in_flight_groups == 0
                            and self._built_q.empty()):
                        self._idle.set()
                    self._cond.notify_all()

    def _execute_one(self, batch: _BuiltBatch) -> None:
        n_sets = len(batch.flat_sets)
        fill = min(1.0, n_sets / self.target_sets)
        metrics.DEVICE_PIPELINE_BATCHES.inc(op=self.op)
        metrics.DEVICE_PIPELINE_BATCH_FILL_RATIO.observe(fill, op=self.op)
        metrics.DEVICE_PIPELINE_LINGER_SECONDS.observe(batch.linger_s, op=self.op)
        with tracing.span(
            "pipeline_batch", op=self.op, n_sets=n_sets,
            n_groups=len(batch.groups), fill_ratio=round(fill, 4),
            linger_s=round(batch.linger_s, 6), work_mix=dict(batch.work_mix),
        ):
            rechecked = 0
            if batch.unbuilt:
                # No device batch exists (build failed or host-side
                # validation said False): EVERY group — lone ones included —
                # gets its own host re-check, so a transient build error
                # can never surface as "bad signature".
                tracing.annotate(group_recheck=True, unbuilt=True)
                verdict = True
                for g in batch.groups:
                    rechecked += 1
                    ok = self._recheck_group(g.sets)
                    verdict = verdict and ok
                    g.future.set_result(ok)
            else:
                # the one shared device slot: bls batches contend with hash
                # and epoch pipeline traffic here, not at the driver
                with ARBITER.slot(self.op):
                    verdict = self._verify_flat(batch)
                if verdict:
                    for g in batch.groups:
                        g.future.set_result(True)
                elif len(batch.groups) == 1:
                    # a single-group batch IS its own attribution
                    batch.groups[0].future.set_result(False)
                else:
                    tracing.annotate(group_recheck=True)
                    for g in batch.groups:
                        rechecked += 1
                        g.future.set_result(self._recheck_group(g.sets))
        self.batches_total += 1
        self._recent.append({
            "t_ms": int(time.time() * 1000),
            "n_sets": n_sets,
            "n_groups": len(batch.groups),
            "fill_ratio": round(fill, 4),
            "linger_s": round(batch.linger_s, 6),
            "build_s": round(batch.build_s, 6),
            "work_mix": dict(batch.work_mix),
            "verdict": bool(verdict),
            "group_rechecks": rechecked,
        })
        blackbox.emit("pipeline", "batch", op=self.op, n_sets=n_sets,
                      n_groups=len(batch.groups), verdict=bool(verdict),
                      group_rechecks=rechecked or None,
                      unbuilt=bool(batch.unbuilt) or None)

    # ------------------------------------------------------------- control

    def wait_idle(self, timeout: float = 10.0) -> bool:
        """Block until no group is pending or in flight."""
        return self._idle.wait(timeout)

    def shutdown(self, timeout: float = 30.0) -> None:
        """Drain: pending groups still execute (possibly as smaller final
        batches) and every future resolves; then both threads exit."""
        with self._cond:
            if self._shutdown:
                return
            self._shutdown = True
            self._cond.notify_all()
        self._builder.join(timeout=timeout)
        self._executor.join(timeout=timeout)
        # anything still unresolved (thread died / join timed out) must not
        # hang callers forever
        with self._cond:
            leftovers = list(self._pending)
            self._pending.clear()
            self._pending_sets = 0
        for g in leftovers:
            if not g.future.done():
                g.future.set_error(PipelineShutdown(
                    f"{self.op}: pipeline shut down before this group ran"))

    def snapshot(self) -> dict:
        with self._cond:
            pending_groups = len(self._pending)
            pending_sets = self._pending_sets
            in_flight = self._in_flight_groups
        return {
            "op": self.op,
            "target_sets": self.target_sets,
            # identical to target_sets unless the device mesh is degraded
            # (device_mesh.scale_target shrinks the fill target with it)
            "effective_target_sets": self._effective_target(),
            "linger_s": self._linger_s,
            # the linger actually applied to the next take: adaptive
            # (flight-recorder in-flight median) unless pinned
            "effective_linger_s": round(self._effective_linger(), 6),
            "linger_adaptive": not self._linger_pinned,
            "pending_groups": pending_groups,
            "pending_sets": pending_sets,
            "in_flight_groups": in_flight,
            "batches_total": self.batches_total,
            "groups_total": self.groups_total,
            "sets_total": self.sets_total,
            "recent_batches": list(self._recent),
        }


# ------------------------------------------------------------ hash pipeline


class HashFuture(_FutureBase):
    """Resolution handle for one submitted pair-hash group (bytes out)."""

    __slots__ = ("n_blocks",)
    _timeout_msg = "pipeline hash result not available in time"

    def __init__(self, work: str, n_blocks: int):
        super().__init__(work)
        self.n_blocks = n_blocks


class _HashGroup:
    __slots__ = ("data", "future")

    def __init__(self, data: bytes, future: HashFuture):
        self.data = data
        self.future = future


class HashPipeline:
    """One persistent pipeline for ``sha256_pairs`` pair-hash traffic.

    Groups are byte buffers of independent 64-byte blocks (Merkle pair
    batches from ``ops/tree_hash.py``, bulk layer builds), so coalescing is
    concatenation and per-group result attribution is an exact slice of the
    output digests — no re-check pass exists because none is needed.  The
    single worker dispatches the joined batch through the SUPERVISED direct
    op (``sha256_device.hash_pairs_device`` — watchdog, split-retry,
    breaker → host kernel with identical bytes) under the shared
    :data:`ARBITER` slot.  A failure that escapes the supervisor anyway
    (bug territory) re-hashes each group on the host kernel so one poisoned
    group cannot corrupt another's digest.

    ``hash_flat_fn``: test seam — replaces the supervised device leg.
    """

    def __init__(self, *, target_blocks: Optional[int] = None,
                 linger_s: Optional[float] = None, hash_flat_fn=None):
        self.op = "sha256_pairs"
        self.target_blocks = max(1, min(
            int(target_blocks or DEFAULT_HASH_TARGET_BLOCKS),
            MAX_HASH_GROUP_BLOCKS))
        self._linger_pinned = linger_s is not None or _LINGER_ENV_PINNED
        self._linger_s = (DEFAULT_LINGER_S if linger_s is None
                          else float(linger_s))
        self._hash_flat_fn = hash_flat_fn
        self._cond = locksmith.condition("HashPipeline._cond")
        self._pending: deque = deque()          # _HashGroup FIFO
        self._pending_blocks = 0
        self._in_flight_groups = 0
        self._shutdown = False
        self._idle = threading.Event()
        self._idle.set()
        self._recent: deque = deque(maxlen=RECENT_BATCHES)
        self.batches_total = 0
        self.groups_total = 0
        self.blocks_total = 0
        self._worker = threading.Thread(
            target=self._run_loop, name="device-pipeline-hash", daemon=True
        )
        self._worker.start()

    # ------------------------------------------------------------- ingress

    @property
    def linger_s(self) -> float:
        return self._linger_s

    @linger_s.setter
    def linger_s(self, value: float) -> None:
        self._linger_s = float(value)
        self._linger_pinned = True

    def _effective_linger(self) -> float:
        return effective_linger(self.op, self._linger_s, self._linger_pinned)

    def submit(self, data: bytes, work: Optional[str] = None) -> HashFuture:
        """Queue one pair-hash group (``len(data)`` a multiple of 64);
        returns its future.  Raises :class:`PipelineShutdown` after
        :meth:`shutdown`."""
        n_blocks = len(data) // 64
        if len(data) % 64:
            raise ValueError("hash group must be a multiple of 64 bytes")
        work = work or current_work_kind()
        fut = HashFuture(work, n_blocks)
        if n_blocks == 0:
            fut.set_result(b"")
            return fut
        with self._cond:
            if self._shutdown:
                raise PipelineShutdown("sha256_pairs: pipeline is shut down")
            self._pending.append(_HashGroup(data, fut))
            self._pending_blocks += n_blocks
            self.groups_total += 1
            self.blocks_total += n_blocks
            self._idle.clear()
            metrics.DEVICE_PIPELINE_PENDING_SETS.set(
                self._pending_blocks, op=self.op)
            metrics.DEVICE_PIPELINE_DEPTH.set(
                len(self._pending) + self._in_flight_groups, op=self.op)
            self._cond.notify_all()
        metrics.DEVICE_PIPELINE_GROUPS.inc(op=self.op, work=work)
        return fut

    # -------------------------------------------------------------- worker

    def _take_batch(self) -> Optional[List[_HashGroup]]:
        with self._cond:
            # sampled once per take, at first-group observation — same
            # rationale as DevicePipeline._take_batch
            linger = None
            frozen = 0
            while True:
                if self._pending:
                    if (self._shutdown
                            or self._pending_blocks >= self.target_blocks):
                        break
                    if linger is None:
                        linger = self._effective_linger()
                    now_lc = _linger_clock()
                    oldest = self._pending[0].future.submitted_lc
                    remaining = linger - (now_lc - oldest)
                    # clock-straddle + stall-breaker — see
                    # DevicePipeline._take_batch
                    if remaining <= 0 or now_lc < oldest:
                        break
                    if frozen >= 2:
                        break
                    self._cond.wait(timeout=min(remaining, 0.05))
                    frozen = frozen + 1 if _linger_clock() == now_lc else 0
                elif self._shutdown:
                    return None
                else:
                    self._cond.wait(timeout=0.1)
            groups: List[_HashGroup] = []
            n_blocks = 0
            while self._pending:
                g = self._pending[0]
                if groups and n_blocks + g.future.n_blocks > self.target_blocks:
                    break
                self._pending.popleft()
                groups.append(g)
                n_blocks += g.future.n_blocks
            self._pending_blocks -= n_blocks
            self._in_flight_groups += len(groups)
            metrics.DEVICE_PIPELINE_PENDING_SETS.set(
                self._pending_blocks, op=self.op)
            return groups

    def _hash_flat(self, data: bytes) -> bytes:
        if self._hash_flat_fn is not None:
            return self._hash_flat_fn(data)
        from .ops.sha256_device import hash_pairs_device

        return hash_pairs_device(data)

    def _run_loop(self) -> None:
        while True:
            try:
                groups = self._take_batch()
            except Exception:
                log.error("hash pipeline take failed", exc_info=True)
                continue
            if groups is None:
                with self._cond:
                    if not self._pending and self._in_flight_groups == 0:
                        self._idle.set()
                return
            try:
                self._execute_one(groups)
            finally:
                with self._cond:
                    self._in_flight_groups -= len(groups)
                    metrics.DEVICE_PIPELINE_DEPTH.set(
                        len(self._pending) + self._in_flight_groups,
                        op=self.op)
                    if not self._pending and self._in_flight_groups == 0:
                        self._idle.set()
                    self._cond.notify_all()

    def _execute_one(self, groups: List[_HashGroup]) -> None:
        oldest = min(g.future.submitted_pc for g in groups)
        linger = max(0.0, time.perf_counter() - oldest)
        n_blocks = sum(g.future.n_blocks for g in groups)
        fill = min(1.0, n_blocks / self.target_blocks)
        work_mix: Dict[str, int] = {}
        for g in groups:
            work_mix[g.future.work] = (
                work_mix.get(g.future.work, 0) + g.future.n_blocks)
        metrics.DEVICE_PIPELINE_BATCHES.inc(op=self.op)
        metrics.DEVICE_PIPELINE_BATCH_FILL_RATIO.observe(fill, op=self.op)
        metrics.DEVICE_PIPELINE_LINGER_SECONDS.observe(linger, op=self.op)
        rehashed = 0
        with tracing.span(
            "pipeline_batch", op=self.op, n_blocks=n_blocks,
            n_groups=len(groups), fill_ratio=round(fill, 4),
            linger_s=round(linger, 6), work_mix=dict(work_mix),
        ):
            try:
                joined = b"".join(g.data for g in groups)
                with ARBITER.slot(self.op):
                    out = self._hash_flat(joined)
                offset = 0
                for g in groups:
                    size = g.future.n_blocks * 32
                    g.future.set_result(out[offset: offset + size])
                    offset += size
            except Exception as err:  # noqa: BLE001 — per-group host rescue
                # The supervised op resolves device faults itself; anything
                # landing here is unexpected — isolate it per group so one
                # poisoned buffer cannot corrupt the others' digests.
                log.error("hash pipeline batch failed; groups re-hash on "
                          "the host kernel",
                          error=f"{type(err).__name__}: {err}")
                tracing.annotate(group_rehash=True)
                from .ops.sha256_device import _host_hash_pairs

                for g in groups:
                    rehashed += 1
                    try:
                        g.future.set_result(_host_hash_pairs(g.data))
                    except Exception as host_err:  # noqa: BLE001
                        g.future.set_error(host_err)
        self.batches_total += 1
        self._recent.append({
            "t_ms": int(time.time() * 1000),
            "n_blocks": n_blocks,
            "n_groups": len(groups),
            "fill_ratio": round(fill, 4),
            "linger_s": round(linger, 6),
            "work_mix": dict(work_mix),
            "group_rehashes": rehashed,
        })
        blackbox.emit("pipeline", "batch", op=self.op, n_blocks=n_blocks,
                      n_groups=len(groups),
                      group_rehashes=rehashed or None)

    # ------------------------------------------------------------- control

    def wait_idle(self, timeout: float = 10.0) -> bool:
        return self._idle.wait(timeout)

    def shutdown(self, timeout: float = 30.0) -> None:
        with self._cond:
            if self._shutdown:
                return
            self._shutdown = True
            self._cond.notify_all()
        self._worker.join(timeout=timeout)
        with self._cond:
            leftovers = list(self._pending)
            self._pending.clear()
            self._pending_blocks = 0
        for g in leftovers:
            if not g.future.done():
                g.future.set_error(PipelineShutdown(
                    "sha256_pairs: pipeline shut down before this group ran"))

    def snapshot(self) -> dict:
        with self._cond:
            pending_groups = len(self._pending)
            pending_blocks = self._pending_blocks
            in_flight = self._in_flight_groups
        return {
            "op": self.op,
            "target_blocks": self.target_blocks,
            "linger_s": self._linger_s,
            "effective_linger_s": round(self._effective_linger(), 6),
            "linger_adaptive": not self._linger_pinned,
            "pending_groups": pending_groups,
            "pending_blocks": pending_blocks,
            "in_flight_groups": in_flight,
            "batches_total": self.batches_total,
            "groups_total": self.groups_total,
            "blocks_total": self.blocks_total,
            "recent_batches": list(self._recent),
        }


# ------------------------------------------------------------- job pipeline


class JobFuture(_FutureBase):
    """Resolution handle for one pipelined device job (arbitrary result)."""

    __slots__ = ()
    _timeout_msg = "pipeline job result not available in time"


class JobPipeline:
    """FIFO pipeline for batch-global device jobs (the epoch ops).

    An epoch transition is one registry-wide dispatch — its sums span the
    whole batch (``device_supervisor.NO_SPLIT_OPS``), so there is nothing
    to coalesce; what enrolment buys is the ARBITER: an epoch boundary
    queues for the same device slot block import and tree-hash traffic use,
    instead of dispatching into their middle.  The submitted thunk is the
    caller's full supervised call (watchdog/breaker/host fallback run
    inside it), so breaker-open host routing and result attribution are
    exactly the direct path's."""

    def __init__(self, op: str):
        self.op = op
        self._q: "queue.SimpleQueue[Optional[tuple]]" = queue.SimpleQueue()
        self._shutdown = False
        self._pending = 0
        self._lock = locksmith.lock("JobPipeline._lock")
        self.jobs_total = 0
        self._worker = threading.Thread(
            target=self._run_loop, name=f"device-pipeline-job-{op}",
            daemon=True)
        self._worker.start()

    def submit(self, fn, work: Optional[str] = None) -> JobFuture:
        work = work or current_work_kind()
        fut = JobFuture(work)
        with self._lock:
            if self._shutdown:
                raise PipelineShutdown(f"{self.op}: pipeline is shut down")
            self._pending += 1
            self.jobs_total += 1
            # enqueue under the lock (SimpleQueue.put never blocks): a job
            # can then never land BEHIND shutdown's poison pill, which sets
            # _shutdown under this same lock before putting None
            self._q.put((fn, fut))
        metrics.DEVICE_PIPELINE_GROUPS.inc(op=self.op, work=work)
        metrics.DEVICE_PIPELINE_DEPTH.set(self._pending, op=self.op)
        return fut

    def _run_loop(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            fn, fut = item
            try:
                with ARBITER.slot(self.op):
                    fut.set_result(fn())
            except BaseException as err:  # noqa: BLE001 — marshalled
                fut.set_error(err)
            finally:
                with self._lock:
                    self._pending -= 1
                metrics.DEVICE_PIPELINE_DEPTH.set(self._pending, op=self.op)
                metrics.DEVICE_PIPELINE_BATCHES.inc(op=self.op)

    def shutdown(self, timeout: float = 30.0) -> None:
        with self._lock:
            if self._shutdown:
                return
            self._shutdown = True
            self._q.put(None)
        self._worker.join(timeout=timeout)
        # The lock-ordered put above guarantees every accepted job precedes
        # the poison pill, so a clean worker exit leaves nothing behind;
        # this sweep only matters if the join TIMED OUT on a hung worker —
        # resolve whatever it abandoned so no caller blocks forever.
        while True:
            try:
                item = self._q.get_nowait()
            except queue.Empty:
                break
            if item is None:
                # leave the pill in place: a worker stuck past the join
                # timeout may yet unstick, and swallowing its exit signal
                # would park that thread on _q.get() forever
                self._q.put(None)
                break
            _, fut = item
            with self._lock:
                self._pending -= 1
            if not fut.done():
                fut.set_error(PipelineShutdown(
                    f"{self.op}: pipeline shut down before this job ran"))

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "op": self.op,
                "pending_jobs": self._pending,
                "jobs_total": self.jobs_total,
            }


# ----------------------------------------------------------- module wiring

_LOCK = locksmith.lock("device_pipeline._LOCK")
_PIPELINE: Optional[DevicePipeline] = None
_HASH_PIPELINE: Optional[HashPipeline] = None
_JOB_PIPELINES: Dict[str, JobPipeline] = {}
_ENABLED = os.environ.get("LIGHTHOUSE_TPU_DEVICE_PIPELINE", "") == "1"


def get_pipeline() -> DevicePipeline:
    """The process-wide bls_verify pipeline (lazily started)."""
    global _PIPELINE
    with _LOCK:
        if _PIPELINE is None:
            _PIPELINE = DevicePipeline("bls_verify")
        return _PIPELINE


def enabled() -> bool:
    return _ENABLED


def enable() -> None:
    """Route ``bls.verify_signature_sets`` through the pipeline (the
    ``ClientBuilder`` calls this for jax-backend nodes; tests/scenarios call
    it explicitly).  ``LIGHTHOUSE_TPU_DEVICE_PIPELINE=0`` wins over callers."""
    global _ENABLED
    if os.environ.get("LIGHTHOUSE_TPU_DEVICE_PIPELINE", "") == "0":
        return
    _ENABLED = True


def disable() -> None:
    global _ENABLED
    _ENABLED = False


def routes(sets: list, seed) -> bool:
    """Should this verify_signature_sets call ride the pipeline?  Explicit
    seeds (reproducibility contracts) and oversized batches keep the direct
    path; so does everything when the pipeline is off."""
    return (
        _ENABLED
        and seed is None
        and 0 < len(sets) <= MAX_GROUP_SETS
    )


def verify(sets: list) -> bool:
    """The api-seam entry: resolve the live pipeline WITHOUT resurrecting
    one that a racing ``shutdown()`` just tore down — a caller already past
    ``routes()`` must fall back to the direct path (the api seam catches
    :class:`PipelineShutdown`), not leak a fresh thread pair post-stop."""
    global _PIPELINE
    with _LOCK:
        pipe = _PIPELINE
        if pipe is None:
            if not _ENABLED:
                raise PipelineShutdown("pipeline disabled mid-call")
            pipe = _PIPELINE = DevicePipeline("bls_verify")
    return pipe.verify(sets)


def get_hash_pipeline() -> HashPipeline:
    """The process-wide sha256_pairs hash pipeline (lazily started)."""
    global _HASH_PIPELINE
    with _LOCK:
        if _HASH_PIPELINE is None:
            _HASH_PIPELINE = HashPipeline()
        return _HASH_PIPELINE


def routes_hash(n_blocks: int) -> bool:
    """Should a pair-hash batch of ``n_blocks`` 64-byte blocks ride the
    hash pipeline?  Oversized batches keep the direct supervised path; so
    does everything when the pipeline is off."""
    return _ENABLED and 0 < n_blocks <= MAX_HASH_GROUP_BLOCKS


def hash_pairs(data: bytes, work: Optional[str] = None) -> bytes:
    """Pair-hash ``data`` through the hash pipeline (the ``ops/tree_hash``
    seam calls this after :func:`routes_hash`): same no-resurrection
    discipline as :func:`verify` — a caller racing ``shutdown()`` gets
    :class:`PipelineShutdown` and falls back to the direct path."""
    global _HASH_PIPELINE
    with _LOCK:
        pipe = _HASH_PIPELINE
        if pipe is None:
            if not _ENABLED:
                raise PipelineShutdown("pipeline disabled mid-call")
            pipe = _HASH_PIPELINE = HashPipeline()
    fut = pipe.submit(data, work=work)
    try:
        return fut.result()
    finally:
        tracing.record_span(
            "pipeline_wait", start_pc=fut.submitted_pc,
            hist=metrics.DEVICE_PIPELINE_WAIT_SECONDS,
            hist_labels={"op": "sha256_pairs"},
            n_blocks=fut.n_blocks, work=fut.work,
        )


def routes_job() -> bool:
    """Should a batch-global device job (epoch ops) ride its job
    pipeline — i.e. queue for the shared arbiter slot?"""
    return _ENABLED


@contextmanager
def api_arbiter_slot(op: str = "http_state_query"):
    """Arbiter contention for an API-side device-bearing computation (the
    HTTP layer's cache-miss state/duties/rewards work).  When the pipelines
    are routing, this is a turnstile — the caller queues for the slot like
    any pipelined work, then releases before running so its own nested
    ``run_job`` legs (epoch deltas, hash batches) can re-contend from the
    pipeline workers without deadlocking.  When the pipelines are off, the
    slot is held across the body: the API thread's direct device dispatches
    are then serialized against any other direct callers."""
    with ARBITER.api_slot(op, hold=not _ENABLED):
        yield


def run_job(op: str, fn, work: Optional[str] = None):
    """Run ``fn`` (a full supervised device call) on ``op``'s job pipeline
    and return its result.  Raises :class:`PipelineShutdown` when racing a
    shutdown — callers fall back to running ``fn`` directly."""
    global _JOB_PIPELINES
    with _LOCK:
        pipe = _JOB_PIPELINES.get(op)
        if pipe is None:
            if not _ENABLED:
                raise PipelineShutdown("pipeline disabled mid-call")
            pipe = _JOB_PIPELINES[op] = JobPipeline(op)
    fut = pipe.submit(fn, work=work)
    try:
        return fut.result()
    finally:
        tracing.record_span(
            "pipeline_wait", start_pc=fut.submitted_pc,
            hist=metrics.DEVICE_PIPELINE_WAIT_SECONDS,
            hist_labels={"op": op}, work=fut.work,
        )


def summary() -> Optional[dict]:
    """The pipeline section of ``GET /lighthouse/device`` (None until any
    pipeline has been started).  The bls pipeline's snapshot keys stay
    top-level (the section's original shape); the hash/job pipelines and
    the shared arbiter ride as sub-sections."""
    with _LOCK:
        pipe = _PIPELINE
        hash_pipe = _HASH_PIPELINE
        jobs = dict(_JOB_PIPELINES)
    if pipe is None and hash_pipe is None and not jobs:
        return None
    out = pipe.snapshot() if pipe is not None else {"op": "bls_verify"}
    out["hash"] = hash_pipe.snapshot() if hash_pipe is not None else None
    out["jobs"] = {op: p.snapshot() for op, p in sorted(jobs.items())} or None
    out["arbiter"] = ARBITER.snapshot()
    return out


def shutdown(timeout: float = 30.0) -> None:
    """Disable routing and drain every process pipeline (Client.stop).  New
    verify/hash/job calls fall back to the direct paths immediately;
    in-flight futures still resolve."""
    global _PIPELINE, _HASH_PIPELINE, _JOB_PIPELINES
    disable()
    with _LOCK:
        pipe, _PIPELINE = _PIPELINE, None
        hash_pipe, _HASH_PIPELINE = _HASH_PIPELINE, None
        jobs, _JOB_PIPELINES = _JOB_PIPELINES, {}
    if pipe is not None:
        pipe.shutdown(timeout=timeout)
    if hash_pipe is not None:
        hash_pipe.shutdown(timeout=timeout)
    for job_pipe in jobs.values():
        job_pipe.shutdown(timeout=timeout)


def reset_for_tests() -> None:
    set_linger_clock(None)
    shutdown(timeout=5.0)
    ARBITER.reset_for_tests()
