"""Checkpoint-keyed HTTP response cache.

The serving layer's answer to "millions of users asking the same
questions": between head changes, every duty/state/rewards query against a
given ``(head, justified, finalized)`` checkpoint tuple has exactly one
answer — so the server computes it once and replays the serialized bytes.
The reference client reaches the same place with per-fork cached responses
inside ``beacon_chain`` (e.g. the validator-duties and deposit caches);
here the cache sits at the HTTP seam so *every* declared hot route gets it
mechanically.

Correctness model
-----------------
- The key embeds the **checkpoint fingerprint** — ``(head_root,
  justified_checkpoint, finalized_checkpoint)`` — plus the route template,
  path params, canonicalized query, canonicalized POST body, and the
  negotiated content type.  A request computes its key from the chain's
  *current* fingerprint, so a reorg or new head can never serve a stale
  entry: the stale entry's key simply stops being computed.
- Event-driven invalidation keeps the map bounded and exact: on a
  ``head``/``finalized_checkpoint``/``chain_reorg`` event every entry whose
  fingerprint differs from the chain's current fingerprint is dropped
  (counted per topic on ``http_response_cache_invalidations_total``).
  Routes whose answers depend on the *set of known blocks* rather than the
  canonical chain (``/eth/v1/beacon/headers`` by parent root, debug heads)
  additionally declare the ``block`` topic: a block event drops their
  entries even when the fingerprint is unchanged.
- A handler that ran while the head moved under it is not stored: ``put``
  re-reads the fingerprint and discards the entry on mismatch (otherwise a
  reorg A→B→A could resurrect a B-computed answer under an A key).

Entries hold the **serialized** response (JSON bytes or SSZ bytes), so a
cache hit is a dict lookup plus a socket write, and cached vs uncached
responses are bit-identical by construction — the property the ``api_load``
scenario's determinism gate pins down.
"""

from __future__ import annotations

import json
import os
import threading
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

from .. import locksmith, metrics, tracing

#: Topics a cached route may declare.  ``head`` and ``finalized_checkpoint``
#: prune dead-fingerprint entries; ``block``/``chain_reorg`` additionally
#: drop same-fingerprint entries of routes that declared them.
VALID_INVALIDATION_TOPICS = (
    "head",
    "finalized_checkpoint",
    "block",
    "chain_reorg",
)

#: The standard declaration for canonical-chain-derived routes (duties,
#: state queries, rewards): pinned by the checkpoint fingerprint, pruned on
#: head/finality movement.
CKPT = ("head", "finalized_checkpoint")
#: For routes that also read non-canonical blocks (headers search, debug
#: heads): any imported block may change the answer without moving the head.
CKPT_BLOCKS = ("head", "finalized_checkpoint", "block")

_TRIGGER_TOPICS = frozenset(VALID_INVALIDATION_TOPICS)


class CacheEntry:
    __slots__ = ("kind", "body", "version", "headers", "fingerprint", "topics")

    def __init__(self, kind: str, body: bytes, version: Optional[str],
                 headers: Tuple[Tuple[str, str], ...],
                 fingerprint: Tuple, topics: Tuple[str, ...]):
        self.kind = kind  # "json" | "ssz"
        self.body = body
        self.version = version
        self.headers = headers
        self.fingerprint = fingerprint
        self.topics = topics


def default_capacity() -> int:
    raw = os.environ.get("LIGHTHOUSE_TPU_API_CACHE_CAPACITY", "4096")
    try:
        return max(16, int(raw))
    except ValueError:
        return 4096


class ResponseCache:
    """LRU over serialized responses, keyed by checkpoint fingerprint +
    request identity, invalidated by chain events."""

    def __init__(self, chain, capacity: Optional[int] = None):
        self.chain = chain
        self.capacity = capacity if capacity is not None else default_capacity()
        self._entries: "OrderedDict[Tuple, CacheEntry]" = OrderedDict()
        self._lock = locksmith.lock("ResponseCache._lock")
        self._attached_bus = None
        self.hits = 0
        self.misses = 0
        self.invalidated = 0
        #: bumped on every invalidation-relevant chain event — the
        #: store-guard against mid-handler reorgs (see :meth:`put`)
        self.generation = 0

    # ------------------------------------------------------------- wiring

    def attach(self, event_bus) -> None:
        """Subscribe invalidation to the chain's event bus (idempotent)."""
        if self._attached_bus is not None:
            return
        event_bus.add_listener(self.on_event)
        self._attached_bus = event_bus

    def detach(self) -> None:
        if self._attached_bus is not None:
            self._attached_bus.remove_listener(self.on_event)
            self._attached_bus = None

    # --------------------------------------------------------------- keys

    def fingerprint(self) -> Tuple:
        """The chain's current ``(head, justified, finalized)`` identity.
        Justified rides along because ``state_id=justified`` answers can
        move when a side-branch block advances justification without
        changing the head."""
        chain = self.chain
        j_epoch, j_root = chain.justified_checkpoint()
        f_epoch, f_root = chain.finalized_checkpoint()
        return (chain.head_root, j_epoch, j_root, f_epoch, f_root)

    @staticmethod
    def _canonical_body(body: Any) -> Optional[str]:
        if body is None:
            return None
        try:
            return json.dumps(body, sort_keys=True, separators=(",", ":"))
        except (TypeError, ValueError):
            return None  # unhashable/binary body: treat as uncacheable

    def make_key(self, method: str, route: str, params: Dict[str, str],
                 query: Dict[str, List[str]], body: Any,
                 wants_ssz: bool) -> Optional[Tuple]:
        """The full cache key, or ``None`` when the request is uncacheable
        (non-JSON body)."""
        if isinstance(body, (bytes, bytearray)):
            return None
        body_key = self._canonical_body(body)
        if body is not None and body_key is None:
            return None
        return (
            self.fingerprint(),
            method,
            route,
            tuple(sorted(params.items())),
            tuple(sorted((k, tuple(v)) for k, v in query.items())),
            body_key,
            wants_ssz,
        )

    # ------------------------------------------------------------ get/put

    def get(self, key: Tuple, route: str) -> Optional[CacheEntry]:
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                self._entries.move_to_end(key)
                self.hits += 1
            else:
                self.misses += 1
        if entry is None:
            metrics.HTTP_CACHE_MISSES.inc(route=route)
            return None
        metrics.HTTP_CACHE_HITS.inc(route=route)
        return entry

    def put(self, key: Tuple, route: str, entry: CacheEntry,
            generation: Optional[int] = None) -> bool:
        """Store; refused when the chain moved while the handler ran.

        Two guards: the fingerprint must still equal the key's, AND — when
        the caller passes the ``generation`` it read at handler start — no
        invalidation event may have fired since.  The fingerprint check
        alone cannot catch an A→B→A reorg that completes within the
        handler's run (the response was computed against B but both
        fingerprint reads see A); the round trip necessarily publishes
        head events, each of which bumps :attr:`generation`."""
        if self.fingerprint() != key[0]:
            return False
        if generation is not None and generation != self.generation:
            return False
        with self._lock:
            self._entries[key] = entry
            self._entries.move_to_end(key)
            while len(self._entries) > self.capacity:
                self._entries.popitem(last=False)
            metrics.HTTP_CACHE_ENTRIES.set(len(self._entries))
        return True

    # ------------------------------------------------------- invalidation

    def on_event(self, topic: str, data: dict) -> None:
        """Chain-event invalidation: drop every entry whose fingerprint is
        no longer the chain's, plus same-fingerprint entries of routes that
        declared this topic as content-bearing (``block``/``chain_reorg``)."""
        if topic not in _TRIGGER_TOPICS:
            return
        current = self.fingerprint()
        dropped = 0
        with self._lock:
            self.generation += 1
            stale = [
                k for k, e in self._entries.items()
                if e.fingerprint != current
                or (topic in e.topics and topic not in CKPT)
            ]
            for k in stale:
                del self._entries[k]
            dropped = len(stale)
            self.invalidated += dropped
            metrics.HTTP_CACHE_ENTRIES.set(len(self._entries))
        if dropped:
            metrics.HTTP_CACHE_INVALIDATIONS.inc(dropped, topic=topic)
            # Visible inside the publishing trace (head_recompute /
            # block_import): which event emptied the cache, and how much.
            tracing.span_event("api_cache_invalidate",
                               topic=topic, dropped=dropped)

    def clear(self) -> int:
        with self._lock:
            n = len(self._entries)
            self._entries.clear()
            metrics.HTTP_CACHE_ENTRIES.set(0)
        return n

    # ----------------------------------------------------------- visible

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def keys_snapshot(self) -> List[Tuple]:
        with self._lock:
            return list(self._entries.keys())

    def snapshot(self) -> dict:
        with self._lock:
            n = len(self._entries)
        total = self.hits + self.misses
        return {
            "entries": n,
            "capacity": self.capacity,
            "hits": self.hits,
            "misses": self.misses,
            "hit_rate": round(self.hits / total, 4) if total else None,
            "invalidated": self.invalidated,
        }
