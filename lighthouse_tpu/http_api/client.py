"""Typed beacon-node HTTP client.

Equivalent of the reference's ``common/eth2`` crate (``BeaconNodeHttpClient``
— the client the validator client, lcli, and tests drive every beacon node
through).  stdlib ``urllib`` over TCP; JSON wire format.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional

from .serde import container_from_json, to_json


class ApiClientError(Exception):
    def __init__(self, status: int, message: str):
        super().__init__(f"HTTP {status}: {message}")
        self.status = status
        self.message = message


class BeaconNodeHttpClient:
    def __init__(self, base_url: str, timeout: float = 10.0):
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout

    # ------------------------------------------------------------- plumbing

    def _request(self, method: str, path: str, body: Any = None,
                 headers: Optional[Dict[str, str]] = None) -> Any:
        url = self.base_url + path
        data = None
        hdrs = {"Accept": "application/json"}
        if body is not None:
            data = json.dumps(body).encode()
            hdrs["Content-Type"] = "application/json"
        if headers:
            hdrs.update(headers)
        req = urllib.request.Request(url, data=data, method=method, headers=hdrs)
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                raw = resp.read()
                if not raw:
                    return None
                return json.loads(raw)
        except urllib.error.HTTPError as e:
            raw = e.read()
            try:
                payload = json.loads(raw)
                msg = payload.get("message", raw.decode(errors="replace"))
            except (json.JSONDecodeError, AttributeError):
                msg = raw.decode(errors="replace")
            raise ApiClientError(e.code, msg) from None

    def get(self, path: str) -> Any:
        return self._request("GET", path)

    def get_ssz(self, path: str):
        """GET with ``Accept: application/octet-stream``; returns
        ``(raw_bytes, consensus_version)`` — the checkpoint-sync fetch shape.
        Errors surface as ``ApiClientError`` like every other method."""
        req = urllib.request.Request(
            self.base_url + path,
            headers={"Accept": "application/octet-stream"},
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                ctype = (resp.headers.get("Content-Type") or "").lower()
                if "application/octet-stream" not in ctype:
                    # 406 Not Acceptable: an HTTP-200 with the wrong type is
                    # still a failed negotiation from the caller's view
                    raise ApiClientError(
                        406,
                        f"server answered {ctype!r}, not SSZ — it does not "
                        "support octet-stream on this route",
                    )
                return resp.read(), resp.headers.get("Eth-Consensus-Version")
        except urllib.error.HTTPError as e:
            try:
                msg = e.read().decode(errors="replace")
            except Exception:
                msg = str(e)
            raise ApiClientError(e.code, msg) from None

    def post(self, path: str, body: Any = None,
             headers: Optional[Dict[str, str]] = None) -> Any:
        return self._request("POST", path, body, headers)

    # ----------------------------------------------------------------- node

    def node_version(self) -> str:
        return self.get("/eth/v1/node/version")["data"]["version"]

    def node_syncing(self) -> dict:
        return self.get("/eth/v1/node/syncing")["data"]

    def node_health_ok(self) -> bool:
        try:
            self.get("/eth/v1/node/health")
            return True
        except ApiClientError:
            return False

    # --------------------------------------------------------------- beacon

    def genesis(self) -> dict:
        return self.get("/eth/v1/beacon/genesis")["data"]

    def state_fork(self, state_id: str = "head") -> dict:
        return self.get(f"/eth/v1/beacon/states/{state_id}/fork")["data"]

    def state_root(self, state_id: str = "head") -> bytes:
        data = self.get(f"/eth/v1/beacon/states/{state_id}/root")["data"]
        return bytes.fromhex(data["root"][2:])

    def finality_checkpoints(self, state_id: str = "head") -> dict:
        return self.get(f"/eth/v1/beacon/states/{state_id}/finality_checkpoints")["data"]

    def validators(self, state_id: str = "head",
                   ids: Optional[List[str]] = None) -> List[dict]:
        path = f"/eth/v1/beacon/states/{state_id}/validators"
        if ids:
            path += "?id=" + ",".join(str(i) for i in ids)
        return self.get(path)["data"]

    def block_header(self, block_id: str = "head") -> dict:
        return self.get(f"/eth/v1/beacon/headers/{block_id}")["data"]

    def block(self, block_id: str = "head") -> dict:
        return self.get(f"/eth/v2/beacon/blocks/{block_id}")

    def block_root(self, block_id: str = "head") -> bytes:
        data = self.get(f"/eth/v1/beacon/blocks/{block_id}/root")["data"]
        return bytes.fromhex(data["root"][2:])

    def publish_block(self, signed_block) -> None:
        fork = type(signed_block.message).fork_name
        self.post(
            "/eth/v2/beacon/blocks",
            to_json(signed_block),
            headers={"Eth-Consensus-Version": fork},
        )

    def submit_attestations(self, attestations) -> None:
        self.post(
            "/eth/v1/beacon/pool/attestations",
            [to_json(a) for a in attestations],
        )

    def submit_voluntary_exit(self, signed_exit) -> None:
        self.post("/eth/v1/beacon/pool/voluntary_exits", to_json(signed_exit))

    def produce_blinded_block(self, slot: int, randao_reveal: bytes,
                              graffiti: Optional[bytes] = None) -> dict:
        path = (f"/eth/v1/validator/blinded_blocks/{slot}"
                f"?randao_reveal=0x{bytes(randao_reveal).hex()}")
        if graffiti:
            path += f"&graffiti=0x{bytes(graffiti).hex()}"
        return self.get(path)

    def publish_blinded_block(self, signed_blinded_block) -> None:
        fork = type(signed_blinded_block.message).fork_name
        self.post(
            "/eth/v2/beacon/blinded_blocks",
            to_json(signed_blinded_block),
            headers={"Eth-Consensus-Version": fork},
        )

    def register_validator(self, signed_registrations) -> None:
        self.post(
            "/eth/v1/validator/register_validator",
            [to_json(r) for r in signed_registrations],
        )

    def submit_sync_committee_messages(self, messages) -> None:
        self.post(
            "/eth/v1/beacon/pool/sync_committees",
            [to_json(m) for m in messages],
        )

    def sync_duties(self, epoch: int, indices: List[int]) -> dict:
        return self.post(
            f"/eth/v1/validator/duties/sync/{epoch}",
            [str(i) for i in indices],
        )

    def sync_committee_contribution(self, slot: int, subcommittee_index: int,
                                    beacon_block_root: bytes, types=None):
        data = self.get(
            f"/eth/v1/validator/sync_committee_contribution"
            f"?slot={slot}&subcommittee_index={subcommittee_index}"
            f"&beacon_block_root=0x{bytes(beacon_block_root).hex()}"
        )["data"]
        if types is not None:
            return container_from_json(types.SyncCommitteeContribution, data)
        return data

    def publish_contribution_and_proofs(self, signed_contributions) -> None:
        self.post(
            "/eth/v1/validator/contribution_and_proofs",
            [to_json(c) for c in signed_contributions],
        )

    @staticmethod
    def _lc_era(branch, header_json=None) -> str:
        """Era from wire shape: 6/7-element state branches are electra
        (64-leaf state); otherwise the header tells capella vs deneb vs
        the beacon-only altair format (blob-gas fields are deneb-only)."""
        if len(branch) >= 6:
            return "electra"
        execution = (header_json or {}).get("execution")
        if execution is None:
            return "altair"
        return "deneb" if "blob_gas_used" in execution else "capella"

    def light_client_bootstrap(self, block_root: bytes, types=None):
        data = self.get(
            f"/eth/v1/beacon/light_client/bootstrap/0x{bytes(block_root).hex()}"
        )["data"]
        if types is not None:
            era = self._lc_era(data["current_sync_committee_branch"],
                               data.get("header"))
            return container_from_json(types.light_client[era]["bootstrap"], data)
        return data

    def light_client_updates(self, start_period: int, count: int, types=None):
        entries = self.get(
            f"/eth/v1/beacon/light_client/updates"
            f"?start_period={start_period}&count={count}"
        )
        if types is not None:
            return [
                container_from_json(
                    types.light_client[
                        self._lc_era(e["data"]["next_sync_committee_branch"],
                                     e["data"].get("attested_header"))
                    ]["update"],
                    e["data"],
                )
                for e in entries
            ]
        return entries

    def light_client_finality_update(self, types=None):
        data = self.get("/eth/v1/beacon/light_client/finality_update")["data"]
        if types is not None:
            branch = data["finality_branch"]
            era = ("electra" if len(branch) >= 7 else
                   self._lc_era([], data.get("attested_header")))
            return container_from_json(
                types.light_client[era]["finality_update"], data
            )
        return data

    def light_client_optimistic_update(self, types=None):
        data = self.get("/eth/v1/beacon/light_client/optimistic_update")["data"]
        if types is not None:
            # No branch on the wire: the header shape is the only signal
            # (electra optimistic updates share deneb's header).
            era = self._lc_era([], data.get("attested_header"))
            return container_from_json(
                types.light_client[era]["optimistic_update"], data)
        return data

    def prepare_beacon_proposer(self, preparations: List[dict]) -> None:
        self.post("/eth/v1/validator/prepare_beacon_proposer", preparations)

    def liveness(self, epoch: int, indices: List[int]) -> List[dict]:
        return self.post(
            f"/eth/v1/validator/liveness/{epoch}",
            [str(i) for i in indices],
        )["data"]

    # ------------------------------------------------------------ validator

    def proposer_duties(self, epoch: int) -> dict:
        return self.get(f"/eth/v1/validator/duties/proposer/{epoch}")

    def attester_duties(self, epoch: int, indices: List[int]) -> dict:
        return self.post(
            f"/eth/v1/validator/duties/attester/{epoch}",
            [str(i) for i in indices],
        )

    def sync_duties(self, epoch: int, indices: List[int]) -> dict:
        return self.post(
            f"/eth/v1/validator/duties/sync/{epoch}",
            [str(i) for i in indices],
        )

    def produce_block(self, slot: int, randao_reveal: bytes,
                      graffiti: Optional[bytes] = None) -> dict:
        path = f"/eth/v3/validator/blocks/{slot}?randao_reveal=0x{randao_reveal.hex()}"
        if graffiti:
            path += f"&graffiti=0x{graffiti.hex()}"
        return self.get(path)

    def attestation_data(self, slot: int, committee_index: int, types=None):
        data = self.get(
            f"/eth/v1/validator/attestation_data?slot={slot}"
            f"&committee_index={committee_index}"
        )["data"]
        if types is not None:
            return container_from_json(types.AttestationData, data)
        return data

    def aggregate_attestation(self, slot: int, data_root: bytes, types=None,
                              committee_index=None):
        """``committee_index`` (v2/electra): post-electra all committees share
        one data root, so the pool needs it to return OUR committee's
        aggregate — without it an aggregator can be handed another
        committee's aggregate and fail the BN's committee-membership check."""
        url = (
            f"/eth/v2/validator/aggregate_attestation"
            f"?attestation_data_root=0x{data_root.hex()}&slot={slot}"
        )
        if committee_index is not None:
            url += f"&committee_index={int(committee_index)}"
        data = self.get(url)["data"]
        if types is not None:
            return container_from_json(types.Attestation, data)
        return data

    def publish_aggregate_and_proofs(self, signed_aggregates) -> None:
        self.post(
            "/eth/v1/validator/aggregate_and_proofs",
            [to_json(a) for a in signed_aggregates],
        )

    # --------------------------------------------------------------- config

    def config_spec(self) -> dict:
        return self.get("/eth/v1/config/spec")["data"]
