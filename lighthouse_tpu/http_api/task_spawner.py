"""Dispatch beacon-API handlers through the priority scheduler.

Equivalent of the reference's ``beacon_node/http_api/src/task_spawner.rs``:
every route runs as ``Priority::P0`` (validator-critical), duties, or ``P1``
work on the ``BeaconProcessor``, so API load contends with gossip under the
same drain order instead of starving block import.

On top of the processor's queues sits the admission layer
(``scheduler/admission.py``): each request is classified
(``critical`` > ``duties`` > ``bulk``), counted against a bounded per-class
inflight budget at ingress (immediate 503 past the bound), and shed at
dequeue when it waited past its class deadline — an answer delivered after
the client's own timeout is pure waste, and computing it anyway is how an
overload becomes a collapse.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Optional

from ..scheduler import BeaconProcessor
from ..scheduler.admission import (
    CLASS_BULK,
    CLASS_CRITICAL,
    CLASS_DUTIES,
    AdmissionController,
    ShedError,
)
from ..scheduler.work import W, WorkEvent

P0 = W.API_REQUEST_P0
PD = W.API_REQUEST_DUTIES
P1 = W.API_REQUEST_P1

#: processor priority -> default admission class (routes may override)
DEFAULT_CLASS = {
    P0: CLASS_CRITICAL,
    PD: CLASS_DUTIES,
    P1: CLASS_BULK,
}


class TaskSpawner:
    def __init__(
        self,
        processor: Optional[BeaconProcessor],
        timeout: float = 30.0,
        admission: Optional[AdmissionController] = None,
    ):
        self.processor = processor
        self.timeout = timeout
        self.admission = admission if admission is not None else AdmissionController()

    def blocking_json_task(
        self, priority: str, func: Callable[[], Any], klass: Optional[str] = None
    ) -> Any:
        """Run ``func`` on the processor at ``priority`` and block for the
        result (the warp handler's await).  Falls back to inline execution
        when there is no processor (bare-chain servers in tests) — admission
        bounds still apply there (inline threads are a finite resource too).

        Raises :class:`ShedError` when admission sheds the request — at
        ingress (class inflight bound) or at dequeue (class deadline)."""
        klass = klass or DEFAULT_CLASS.get(priority, CLASS_BULK)
        ticket = self.admission.try_admit(klass)  # raises ShedError when full
        if self.processor is None:
            try:
                return func()
            finally:
                ticket.release()
        done = threading.Event()
        box: dict = {}

        def run(_item=None):
            try:
                ticket.check_deadline()  # raises ShedError when stale
                box["result"] = func()
            except BaseException as e:  # propagate to the HTTP thread
                box["error"] = e
            finally:
                ticket.release()
                done.set()

        accepted = self.processor.send(WorkEvent(work_type=priority, process=run))
        if not accepted:
            ticket.release()
            raise OverloadedError("beacon processor queue full")
        if not done.wait(self.timeout):
            raise TimeoutError("beacon processor did not run the API task in time")
        if "error" in box:
            raise box["error"]
        return box.get("result")


class OverloadedError(Exception):
    pass
