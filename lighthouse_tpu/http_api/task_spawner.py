"""Dispatch beacon-API handlers through the priority scheduler.

Equivalent of the reference's ``beacon_node/http_api/src/task_spawner.rs``:
every route runs as ``Priority::P0`` (validator-critical) or ``Priority::P1``
work on the ``BeaconProcessor``, so API load contends with gossip under the
same drain order instead of starving block import.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Optional

from ..scheduler import BeaconProcessor
from ..scheduler.work import W, WorkEvent

P0 = W.API_REQUEST_P0
P1 = W.API_REQUEST_P1


class TaskSpawner:
    def __init__(self, processor: Optional[BeaconProcessor], timeout: float = 30.0):
        self.processor = processor
        self.timeout = timeout

    def blocking_json_task(self, priority: str, func: Callable[[], Any]) -> Any:
        """Run ``func`` on the processor at ``priority`` and block for the
        result (the warp handler's await).  Falls back to inline execution
        when there is no processor (bare-chain servers in tests)."""
        if self.processor is None:
            return func()
        done = threading.Event()
        box: dict = {}

        def run(_item=None):
            try:
                box["result"] = func()
            except BaseException as e:  # propagate to the HTTP thread
                box["error"] = e
            finally:
                done.set()

        accepted = self.processor.send(WorkEvent(work_type=priority, process=run))
        if not accepted:
            raise OverloadedError("beacon processor queue full")
        if not done.wait(self.timeout):
            raise TimeoutError("beacon processor did not run the API task in time")
        if "error" in box:
            raise box["error"]
        return box.get("result")


class OverloadedError(Exception):
    pass
