"""The beacon-node HTTP API server.

Equivalent of the reference's ``beacon_node/http_api`` crate
(``src/lib.rs`` — the warp route table, 205 routes; handlers dispatched
through the priority scheduler via ``task_spawner.rs``).  This implements the
contract surface the validator client and sync tooling need: node status,
beacon state/block queries, pool submissions, validator duties + block
production, SSE events, config, debug, and Prometheus ``/metrics``.

Transport: stdlib ``ThreadingHTTPServer`` (one thread per connection — the
Python analog of warp's task-per-request; real work still funnels through the
``BeaconProcessor`` so API load obeys the same drain order as gossip).
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional, Tuple
from urllib.parse import parse_qs, urlparse

from .. import metrics, tracing
from ..chain import events as ev
from ..consensus import helpers as h
from ..device_pipeline import api_arbiter_slot
from ..scheduler.admission import CLASS_DUTIES, AdmissionController, ShedError
from ..types.spec import FAR_FUTURE_EPOCH
from .response_cache import CKPT, CKPT_BLOCKS, CacheEntry, ResponseCache
from .serde import container_from_json, to_json
from .task_spawner import P0, P1, PD, OverloadedError, TaskSpawner

VERSION_STRING = "lighthouse-tpu/0.2.0"


class ApiError(Exception):
    def __init__(self, code: int, message: str):
        super().__init__(message)
        self.code = code
        self.message = message


def _not_found(what: str) -> ApiError:
    return ApiError(404, f"NOT_FOUND: {what}")


def _bad(msg: str) -> ApiError:
    return ApiError(400, f"BAD_REQUEST: {msg}")


# --------------------------------------------------------------- id parsing


def parse_root_or_slot(s: str) -> Tuple[Optional[bytes], Optional[int]]:
    if s.startswith("0x"):
        try:
            root = bytes.fromhex(s[2:])
        except ValueError:
            raise _bad(f"invalid root {s!r}")
        if len(root) != 32:
            raise _bad(f"root must be 32 bytes: {s!r}")
        return root, None
    try:
        return None, int(s)
    except ValueError:
        raise _bad(f"invalid block/state id {s!r}")


class SszResponse:
    """A handler's SSZ (application/octet-stream) answer — the server writes
    the raw bytes with Eth-Consensus-Version plus any extra headers (the
    beacon-API spec carries finality metadata as headers on SSZ answers)."""

    __slots__ = ("data", "version", "headers")

    def __init__(self, data: bytes, version: Optional[str] = None,
                 headers: Optional[Dict[str, str]] = None):
        self.data = data
        self.version = version
        self.headers = headers or {}


class Context:
    """Everything a route handler needs."""

    def __init__(self, server: "HttpApiServer", params: Dict[str, str],
                 query: Dict[str, List[str]], body: Any, headers):
        self.server = server
        self.chain = server.chain
        self.params = params
        self.query = query
        self.body = body
        self.headers = headers

    def q1(self, name: str, default: Optional[str] = None) -> Optional[str]:
        vals = self.query.get(name)
        return vals[0] if vals else default

    @property
    def wants_ssz(self) -> bool:
        """True when the client PREFERS application/octet-stream (q-values
        honored: an explicit lower/zero q on octet-stream keeps JSON)."""
        accept = self.headers.get("Accept", "") or ""
        q_octet = q_json = None
        for part in accept.split(","):
            fields = part.strip().split(";")
            mtype = fields[0].strip().lower()
            q = 1.0
            for f in fields[1:]:
                f = f.strip()
                if f.startswith("q="):
                    try:
                        q = float(f[2:])
                    except ValueError:
                        q = 0.0
            if mtype == "application/octet-stream":
                q_octet = q
            elif mtype in ("application/json", "*/*"):
                q_json = max(q_json or 0.0, q)
        if q_octet is None or q_octet <= 0:
            return False
        return q_json is None or q_octet >= q_json

    # ------------------------------------------------------- id resolution

    def resolve_block_root(self, block_id: str) -> bytes:
        chain = self.chain
        if block_id == "head":
            return chain.head_root
        if block_id == "genesis":
            return chain.genesis_block_root
        if block_id == "finalized":
            return chain.finalized_checkpoint()[1]
        if block_id == "justified":
            return chain.justified_checkpoint()[1]
        root, slot = parse_root_or_slot(block_id)
        if root is not None:
            # Existence check against the RAW store (db.get_block may return
            # a blinded block) — resolving a root must not trigger a payload
            # reconstruction round trip.
            if (
                root != chain.genesis_block_root
                and root not in chain._blocks
                and chain.db.get_block(root) is None
                and chain.early_attester_cache.get_block(root) is None
            ):
                raise _not_found(f"block {block_id}")
            return root
        found = chain.block_root_at_slot(slot)
        if found is None:
            raise _not_found(f"block at slot {slot}")
        return found

    def resolve_block(self, block_id: str):
        root = self.resolve_block_root(block_id)
        block = self.chain.get_block(root) or self.chain.db.get_block(root)
        if block is None:
            if root == self.chain.genesis_block_root:
                raise _not_found("genesis block body is not stored")
            raise _not_found(f"block {block_id}")
        return root, block

    def resolve_state(self, state_id: str):
        """Returns (state, block_root). ``state_id``: head|genesis|finalized|
        justified|<slot>|<0xstate_root>."""
        chain = self.chain
        if state_id == "head":
            return chain.head_state, chain.head_root
        if state_id == "genesis":
            return chain.genesis_state, chain.genesis_block_root
        if state_id in ("finalized", "justified"):
            _, root = (
                chain.finalized_checkpoint()
                if state_id == "finalized"
                else chain.justified_checkpoint()
            )
            state = chain.get_state(root)
            if state is None:
                raise _not_found(f"{state_id} state pruned")
            return state, root
        root, slot = parse_root_or_slot(state_id)
        if root is not None:
            for broot, st in chain._states.items():
                if st.hash_tree_root() == root:
                    return st, broot
            st = chain.db.get_hot_state(root)
            if st is None:
                raise _not_found(f"state {state_id}")
            return st, b"\x00" * 32
        head_state = chain.head_state
        if slot >= int(head_state.slot):
            state, root = chain.state_at_slot(slot)
            return state, root
        # Historical slot: resolve the canonical block at/before it and
        # advance through any empty slots.
        broot = chain.block_root_at_slot(slot)
        if broot is None:
            raise _not_found(f"state at slot {slot}")
        st = chain.get_state(broot)
        if st is None:
            raise _not_found(f"state at slot {slot} pruned from the hot cache")
        if int(st.slot) < slot:
            from ..consensus.per_slot import process_slots

            # process_slots returns a NEW object when a fork upgrade occurs
            # mid-advance — always take the return value.
            st = process_slots(st.copy(), slot, chain.types, chain.spec)
        return st, broot


# ------------------------------------------------------------------ routes

ROUTES: List[Tuple[str, str, str, Callable[[Context], Any]]] = []

#: (method, pattern) -> invalidation topics for every response-cached route.
#: The contract the static check (scripts/check_metrics.py) enforces: a
#: route may only be cached by *declaring* which chain events invalidate it
#: — there is no way to add a silently-stale route.
CACHED_ROUTES: Dict[Tuple[str, str], Tuple[str, ...]] = {}


def route(method: str, pattern: str, priority: str = P1,
          cache: Optional[Tuple[str, ...]] = None,
          klass: Optional[str] = None):
    """Register a handler.  ``cache`` (a tuple of chain-event topics, e.g.
    ``response_cache.CKPT``) opts the route into the checkpoint-keyed
    response cache AND routes its cache-miss execution through the device
    arbiter slot; ``klass`` overrides the admission class derived from
    ``priority`` (see task_spawner.DEFAULT_CLASS)."""
    segs = pattern.strip("/").split("/")

    def deco(fn):
        ROUTES.append((method, pattern, priority, fn))
        fn._segs = segs
        if cache is not None:
            fn._cache_topics = tuple(cache)
            CACHED_ROUTES[(method, pattern)] = tuple(cache)
        if klass is not None:
            fn._klass = klass
        return fn

    return deco


def match_route(method: str, path: str):
    """-> (priority, fn, params, pattern) — the pattern (route template) is
    the bounded-cardinality label the HTTP metrics series use."""
    path_segs = path.strip("/").split("/")
    for m, pattern, priority, fn in ROUTES:
        if m != method:
            continue
        segs = pattern.strip("/").split("/")
        if len(segs) != len(path_segs):
            continue
        params = {}
        ok = True
        for want, got in zip(segs, path_segs):
            if want.startswith("{") and want.endswith("}"):
                params[want[1:-1]] = got
            elif want != got:
                ok = False
                break
        if ok:
            return priority, fn, params, pattern
    return None


# ------------------------------------------------------------- node routes


@route("GET", "/eth/v1/node/version")
def node_version(ctx):
    return {"data": {"version": VERSION_STRING}}


@route("GET", "/eth/v1/node/identity")
def node_identity(ctx):
    peer_id = getattr(ctx.server, "peer_id", "") or ""
    return {"data": {
        "peer_id": peer_id,
        "enr": "",
        "p2p_addresses": [],
        "discovery_addresses": [],
        "metadata": {"seq_number": "0", "attnets": "0x" + "00" * 8, "syncnets": "0x00"},
    }}


@route("GET", "/eth/v1/node/syncing")
def node_syncing(ctx):
    chain = ctx.chain
    head_slot = chain._blocks_slot(chain.head_root)
    current = chain.current_slot()
    distance = max(0, current - head_slot)
    return {"data": {
        "head_slot": str(head_slot),
        "sync_distance": str(distance),
        "is_syncing": distance > 1,
        "is_optimistic": False,
        "el_offline": False,
    }}


@route("GET", "/eth/v1/node/health")
def node_health(ctx):
    chain = ctx.chain
    distance = chain.current_slot() - chain._blocks_slot(chain.head_root)
    raise ApiError(200 if distance <= 1 else 206, "")


@route("GET", "/eth/v1/node/peers")
def node_peers(ctx):
    peers = []
    pm = getattr(ctx.server, "peer_manager", None)
    if pm is not None:
        for pid, info in pm.peers().items():
            peers.append({
                "peer_id": str(pid),
                "enr": "",
                "last_seen_p2p_address": "",
                "state": "connected" if info.connected else "disconnected",
                "direction": "outbound",
            })
    return {"data": peers, "meta": {"count": len(peers)}}


@route("GET", "/eth/v1/node/peer_count")
def node_peer_count(ctx):
    pm = getattr(ctx.server, "peer_manager", None)
    n = len([p for p in pm.peers().values() if p.connected]) if pm else 0
    return {"data": {
        "connected": str(n), "connecting": "0", "disconnected": "0", "disconnecting": "0",
    }}


# ----------------------------------------------------------- beacon routes


@route("GET", "/eth/v1/beacon/genesis")
def beacon_genesis(ctx):
    chain = ctx.chain
    return {"data": {
        "genesis_time": str(chain.genesis_time),
        "genesis_validators_root": "0x" + chain.genesis_validators_root.hex(),
        "genesis_fork_version": "0x" + chain.spec.genesis_fork_version.hex(),
    }}


def _finality_meta(ctx, block_root):
    f_epoch, f_root = ctx.chain.finalized_checkpoint()
    try:
        slot = ctx.chain._blocks_slot(block_root)
        finalized = slot <= f_epoch * ctx.chain.spec.slots_per_epoch
    except KeyError:
        finalized = False
    return {"execution_optimistic": False, "finalized": finalized}


@route("GET", "/eth/v1/beacon/states/{state_id}/root", cache=CKPT)
def state_root(ctx):
    state, broot = ctx.resolve_state(ctx.params["state_id"])
    out = {"data": {"root": "0x" + state.hash_tree_root().hex()}}
    out.update(_finality_meta(ctx, broot))
    return out


@route("GET", "/eth/v1/beacon/states/{state_id}/fork", cache=CKPT)
def state_fork(ctx):
    state, broot = ctx.resolve_state(ctx.params["state_id"])
    out = {"data": to_json(state.fork)}
    out.update(_finality_meta(ctx, broot))
    return out


@route("GET", "/eth/v1/beacon/states/{state_id}/finality_checkpoints", cache=CKPT)
def state_finality(ctx):
    state, broot = ctx.resolve_state(ctx.params["state_id"])
    out = {"data": {
        "previous_justified": to_json(state.previous_justified_checkpoint),
        "current_justified": to_json(state.current_justified_checkpoint),
        "finalized": to_json(state.finalized_checkpoint),
    }}
    out.update(_finality_meta(ctx, broot))
    return out


def validator_status(v, balance: int, epoch: int) -> str:
    """The standard beacon-API validator status taxonomy
    (reference ``consensus/types/src/validator.rs`` + api spec)."""
    if epoch < int(v.activation_eligibility_epoch):
        return "pending_initialized"
    if epoch < int(v.activation_epoch):
        return "pending_queued"
    if epoch < int(v.exit_epoch):
        if int(v.exit_epoch) == FAR_FUTURE_EPOCH:
            return "active_ongoing"
        return "active_slashed" if v.slashed else "active_exiting"
    if epoch < int(v.withdrawable_epoch):
        return "exited_slashed" if v.slashed else "exited_unslashed"
    return "withdrawal_possible" if balance > 0 else "withdrawal_done"


def _validator_entry(state, i: int, epoch: int) -> dict:
    v = state.validators[i]
    bal = int(state.balances[i])
    return {
        "index": str(i),
        "balance": str(bal),
        "status": validator_status(v, bal, epoch),
        "validator": to_json(v),
    }


def _parse_validator_id(state, vid: str) -> Optional[int]:
    if vid.startswith("0x"):
        pk = bytes.fromhex(vid[2:])
        for i, v in enumerate(state.validators):
            if bytes(v.pubkey) == pk:
                return i
        return None
    idx = int(vid)
    return idx if 0 <= idx < len(state.validators) else None


@route("GET", "/eth/v1/beacon/states/{state_id}/validators", cache=CKPT)
def state_validators(ctx):
    state, broot = ctx.resolve_state(ctx.params["state_id"])
    epoch = h.get_current_epoch(state, ctx.chain.spec)
    ids = ctx.query.get("id")
    statuses = set(ctx.query.get("status", []))
    if ids:
        wanted = []
        for vid in ids:
            for part in vid.split(","):
                i = _parse_validator_id(state, part)
                if i is not None:
                    wanted.append(i)
    else:
        wanted = range(len(state.validators))
    data = [_validator_entry(state, i, epoch) for i in wanted]
    if statuses:
        data = [d for d in data if d["status"] in statuses]
    out = {"data": data}
    out.update(_finality_meta(ctx, broot))
    return out


@route("POST", "/eth/v1/beacon/states/{state_id}/validators", cache=CKPT)
def state_validators_post(ctx):
    body = ctx.body or {}
    ctx.query = dict(ctx.query)
    if body.get("ids"):
        ctx.query["id"] = [str(x) for x in body["ids"]]
    if body.get("statuses"):
        ctx.query["status"] = list(body["statuses"])
    return state_validators(ctx)


@route("GET", "/eth/v1/beacon/states/{state_id}/validators/{validator_id}", cache=CKPT)
def state_validator(ctx):
    state, broot = ctx.resolve_state(ctx.params["state_id"])
    epoch = h.get_current_epoch(state, ctx.chain.spec)
    i = _parse_validator_id(state, ctx.params["validator_id"])
    if i is None:
        raise _not_found(f"validator {ctx.params['validator_id']}")
    out = {"data": _validator_entry(state, i, epoch)}
    out.update(_finality_meta(ctx, broot))
    return out


@route("GET", "/eth/v1/beacon/states/{state_id}/validator_balances", cache=CKPT)
def state_balances(ctx):
    state, broot = ctx.resolve_state(ctx.params["state_id"])
    ids = ctx.query.get("id")
    if ids:
        wanted = []
        for vid in ids:
            for part in vid.split(","):
                i = _parse_validator_id(state, part)
                if i is not None:
                    wanted.append(i)
    else:
        wanted = range(len(state.balances))
    out = {"data": [
        {"index": str(i), "balance": str(int(state.balances[i]))} for i in wanted
    ]}
    out.update(_finality_meta(ctx, broot))
    return out


@route("GET", "/eth/v1/beacon/states/{state_id}/committees", cache=CKPT)
def state_committees(ctx):
    state, broot = ctx.resolve_state(ctx.params["state_id"])
    spec = ctx.chain.spec
    epoch = (
        int(ctx.q1("epoch"))
        if ctx.q1("epoch") is not None
        else h.get_current_epoch(state, spec)
    )
    want_index = ctx.q1("index")
    want_slot = ctx.q1("slot")
    data = []
    for slot in range(
        epoch * spec.slots_per_epoch, (epoch + 1) * spec.slots_per_epoch
    ):
        if want_slot is not None and slot != int(want_slot):
            continue
        count = h.get_committee_count_per_slot(state, epoch, spec)
        for index in range(count):
            if want_index is not None and index != int(want_index):
                continue
            committee = h.get_beacon_committee(state, slot, index, spec)
            data.append({
                "index": str(index),
                "slot": str(slot),
                "validators": [str(int(v)) for v in committee],
            })
    out = {"data": data}
    out.update(_finality_meta(ctx, broot))
    return out


@route("GET", "/eth/v1/beacon/states/{state_id}/sync_committees", cache=CKPT)
def state_sync_committees(ctx):
    state, broot = ctx.resolve_state(ctx.params["state_id"])
    if not hasattr(state, "current_sync_committee"):
        raise _bad("state has no sync committees (phase0)")
    pk_to_index = {bytes(v.pubkey): i for i, v in enumerate(state.validators)}
    indices = [
        pk_to_index[bytes(pk)] for pk in state.current_sync_committee.pubkeys
    ]
    sub_size = max(1, len(indices) // 4)
    out = {"data": {
        "validators": [str(i) for i in indices],
        "validator_aggregates": [
            [str(i) for i in indices[k : k + sub_size]]
            for k in range(0, len(indices), sub_size)
        ],
    }}
    out.update(_finality_meta(ctx, broot))
    return out


@route("GET", "/eth/v1/beacon/states/{state_id}/randao", cache=CKPT)
def state_randao(ctx):
    state, broot = ctx.resolve_state(ctx.params["state_id"])
    spec = ctx.chain.spec
    epoch = (
        int(ctx.q1("epoch"))
        if ctx.q1("epoch") is not None
        else h.get_current_epoch(state, spec)
    )
    mix = h.get_randao_mix(state, epoch, spec)
    out = {"data": {"randao": "0x" + bytes(mix).hex()}}
    out.update(_finality_meta(ctx, broot))
    return out


def _header_json(ctx, root: bytes, signed_block) -> dict:
    msg = signed_block.message
    header = {
        "slot": str(int(msg.slot)),
        "proposer_index": str(int(msg.proposer_index)),
        "parent_root": "0x" + bytes(msg.parent_root).hex(),
        "state_root": "0x" + bytes(msg.state_root).hex(),
        "body_root": "0x" + msg.body.hash_tree_root().hex(),
    }
    return {
        "root": "0x" + root.hex(),
        "canonical": ctx.chain.block_root_at_slot(int(msg.slot)) == root,
        "header": {
            "message": header,
            "signature": "0x" + bytes(signed_block.signature).hex(),
        },
    }


@route("GET", "/eth/v1/beacon/headers", cache=CKPT_BLOCKS)
def beacon_headers(ctx):
    slot = ctx.q1("slot")
    parent_root = ctx.q1("parent_root")
    chain = ctx.chain
    results = []
    if slot is not None:
        root = chain.block_root_at_slot(int(slot))
        if root is not None and chain.get_block(root) is not None:
            results.append((root, chain.get_block(root)))
    elif parent_root is not None:
        want = bytes.fromhex(parent_root[2:])
        for root, blk in chain._blocks.items():
            if bytes(blk.message.parent_root) == want:
                results.append((root, blk))
    else:
        root = chain.head_root
        blk = chain.get_block(root)
        if blk is not None:
            results.append((root, blk))
    return {
        "data": [_header_json(ctx, r, b) for r, b in results],
        "execution_optimistic": False,
        "finalized": False,
    }


@route("GET", "/eth/v1/beacon/headers/{block_id}", cache=CKPT)
def beacon_header(ctx):
    root, block = ctx.resolve_block(ctx.params["block_id"])
    out = {"data": _header_json(ctx, root, block)}
    out.update(_finality_meta(ctx, root))
    return out


@route("GET", "/eth/v2/beacon/blocks/{block_id}", cache=CKPT)
def beacon_block(ctx):
    root, block = ctx.resolve_block(ctx.params["block_id"])
    fork = type(block.message).fork_name
    if ctx.wants_ssz:
        meta = _finality_meta(ctx, root)
        return SszResponse(block.as_ssz_bytes(), fork, headers={
            "Eth-Execution-Optimistic": str(meta.get("execution_optimistic", False)).lower(),
            "Eth-Finalized": str(meta.get("finalized", False)).lower(),
        })
    out = {
        "version": fork,
        "data": to_json(block),
    }
    out.update(_finality_meta(ctx, root))
    return out


@route("GET", "/eth/v1/beacon/blocks/{block_id}", cache=CKPT)
def beacon_block_v1(ctx):
    """v1 block fetch: bare {data} envelope (reference get_beacon_block is
    version-generic via any_version; V1 responses carry no version key)."""
    root, block = ctx.resolve_block(ctx.params["block_id"])
    fork = type(block.message).fork_name
    if ctx.wants_ssz:
        return SszResponse(block.as_ssz_bytes(), fork)
    return {"data": to_json(block)}


@route("GET", "/eth/v1/beacon/blocks/{block_id}/root", cache=CKPT)
def beacon_block_root(ctx):
    root = ctx.resolve_block_root(ctx.params["block_id"])
    out = {"data": {"root": "0x" + root.hex()}}
    out.update(_finality_meta(ctx, root))
    return out


@route("GET", "/eth/v1/beacon/blocks/{block_id}/attestations", cache=CKPT)
def beacon_block_attestations(ctx):
    root, block = ctx.resolve_block(ctx.params["block_id"])
    out = {"data": [to_json(a) for a in block.message.body.attestations]}
    out.update(_finality_meta(ctx, root))
    return out


@route("GET", "/eth/v1/beacon/blob_sidecars/{block_id}")
def beacon_blob_sidecars(ctx):
    root, _ = ctx.resolve_block(ctx.params["block_id"])
    sidecars = ctx.chain.get_blobs(root) if hasattr(ctx.chain, "get_blobs") else []
    indices = ctx.query.get("indices")
    if indices:
        want = {int(i) for x in indices for i in x.split(",")}
        sidecars = [s for s in sidecars if int(s.index) in want]
    return {"data": [to_json(s) for s in sidecars]}


def _decode_ssz_signed_block(ctx, body: bytes, registry) -> Any:
    """SSZ block upload: version from the consensus-version header, else
    derived from the slot at its fixed offset (message offset word ++
    96-byte signature ++ slot u64 = bytes 100..108) — the same decision the
    JSON path makes; never guess-and-swallow across forks."""
    types, spec = ctx.chain.types, ctx.chain.spec
    version = ctx.headers.get("Eth-Consensus-Version")  # case-insensitive get
    if version is None:
        if len(body) < 108:
            raise _bad("SSZ block too short")
        slot = int.from_bytes(body[100:108], "little")
        version = spec.fork_name_at_slot(slot)
    cls = registry.get(str(version).lower())
    if cls is None:
        raise _bad(f"unknown consensus version {version!r}")
    try:
        return cls.from_ssz_bytes(bytes(body))
    except (ValueError, IndexError) as e:
        raise _bad(f"malformed SSZ block: {e}")


def _signed_block_from_json(ctx, body) -> Any:
    types, spec = ctx.chain.types, ctx.chain.spec
    if isinstance(body, (bytes, bytearray)):
        return _decode_ssz_signed_block(ctx, bytes(body), types.signed_block)
    version = None
    for k in ("Eth-Consensus-Version", "eth-consensus-version"):
        if ctx.headers.get(k):
            version = ctx.headers.get(k).lower()
            break
    if version is None:
        slot = int(body["message"]["slot"])
        version = spec.fork_name_at_slot(slot)
    cls = types.signed_block.get(version)
    if cls is None:
        raise _bad(f"unknown consensus version {version!r}")
    try:
        return container_from_json(cls, body)
    except (KeyError, TypeError, ValueError) as e:
        raise _bad(f"malformed {version} SignedBeaconBlock body: {e}")


def _import_and_publish_block(ctx, signed_block):
    from ..chain.beacon_chain import BlockError

    chain = ctx.chain
    try:
        chain.process_block(signed_block)
    except BlockError as e:
        if "unknown parent" in str(e):
            raise ApiError(202, f"block queued: {e}")
        raise _bad(f"invalid block: {e}")
    publish = getattr(ctx.server, "publish_block_fn", None)
    if publish is not None:
        publish(signed_block)
    return None


@route("POST", "/eth/v1/beacon/blocks", P0)
def publish_block_v1(ctx):
    return _import_and_publish_block(ctx, _signed_block_from_json(ctx, ctx.body))


publish_block_v1._accepts_ssz = True


@route("POST", "/eth/v2/beacon/blocks", P0)
def publish_block_v2(ctx):
    return _import_and_publish_block(ctx, _signed_block_from_json(ctx, ctx.body))


publish_block_v2._accepts_ssz = True


# -------------------------------------------------------------- pool routes


def _submit_attestations(ctx, att_cls) -> None:
    from ..chain.beacon_chain import AttestationError

    chain = ctx.chain
    failures = []
    for i, att_json in enumerate(ctx.body or []):
        try:
            att = container_from_json(att_cls, att_json)
            chain.process_attestation(att)
            publish = getattr(ctx.server, "publish_attestation_fn", None)
            if publish is not None:
                publish(att)
        except (AttestationError, KeyError, ValueError) as e:
            failures.append({"index": i, "message": str(e)})
    if failures:
        raise ApiError(400, json.dumps({
            "code": 400,
            "message": "error processing attestations",
            "failures": failures,
        }))


@route("POST", "/eth/v1/beacon/pool/attestations", P0)
def pool_attestations_post(ctx):
    return _submit_attestations(ctx, ctx.chain.types.Attestation)


@route("POST", "/eth/v2/beacon/pool/attestations", P0)
def pool_attestations_post_v2(ctx):
    """v2 submission (electra, EIP-7549): the Eth-Consensus-Version header
    selects the per-fork attestation container (committee_bits form for
    electra)."""
    version = (ctx.headers.get("Eth-Consensus-Version") or "").lower()
    att_cls = ctx.chain.types.attestation_by_fork.get(
        version, ctx.chain.types.Attestation
    )
    return _submit_attestations(ctx, att_cls)


@route("POST", "/eth/v1/beacon/pool/sync_committees", P0)
def pool_sync_committees_post(ctx):
    """Submit ``SyncCommitteeMessage``s (the VC's slot+1/3 sync duty)."""
    from ..chain.beacon_chain import AttestationError

    chain = ctx.chain
    failures = []
    messages = []
    slots = []  # original body index per decoded message
    for i, msg_json in enumerate(ctx.body or []):
        try:
            messages.append(
                container_from_json(chain.types.SyncCommitteeMessage, msg_json)
            )
            slots.append(i)
        except (KeyError, ValueError) as e:
            failures.append({"index": i, "message": str(e)})
    # ONE batched verification for the whole submission (a per-message
    # pairing would put a full committee's POST past client timeouts).
    for i, err in zip(slots, chain.process_sync_committee_messages(messages)):
        if err is not None:
            failures.append({"index": i, "message": err})
    if failures:
        raise ApiError(400, json.dumps({
            "code": 400,
            "message": "error processing sync committee messages",
            "failures": failures,
        }))
    return None


@route("GET", "/eth/v1/validator/sync_committee_contribution", P0)
def sync_committee_contribution(ctx):
    slot = ctx.q1("slot")
    sub = ctx.q1("subcommittee_index")
    root_hex = ctx.q1("beacon_block_root")
    if slot is None or sub is None or root_hex is None:
        raise _bad("slot, subcommittee_index and beacon_block_root are required")
    c = ctx.chain.sync_contribution_pool.get_contribution(
        int(slot), bytes.fromhex(root_hex[2:]), int(sub)
    )
    if c is None:
        raise _not_found("no contribution for that subcommittee")
    return {"data": to_json(c)}


@route("POST", "/eth/v1/validator/contribution_and_proofs", P0)
def contribution_and_proofs(ctx):
    from ..chain.beacon_chain import AttestationError

    chain = ctx.chain
    failures = []
    signed_list = []
    idxs = []
    for i, c_json in enumerate(ctx.body or []):
        try:
            signed_list.append(container_from_json(
                chain.types.SignedContributionAndProof, c_json
            ))
            idxs.append(i)
        except (KeyError, ValueError) as e:
            failures.append({"index": i, "message": str(e)})
    # ONE batched verification (3 sets per contribution) per submission.
    for i, err in zip(idxs, chain.process_signed_contributions(signed_list)):
        if err is not None:
            failures.append({"index": i, "message": err})
    if failures:
        raise ApiError(400, json.dumps({
            "code": 400,
            "message": "error processing contributions",
            "failures": failures,
        }))
    return None


@route("POST", "/eth/v1/validator/liveness/{epoch}", P0)
def validator_liveness(ctx):
    """Per-validator liveness for ``epoch`` — the doppelganger service's
    data source.  ORs every observed cache that can prove activity (gossip
    attesters, block-included attesters, aggregators, block proposers),
    matching the reference's four-cache ``validator_seen_at_epoch``
    (beacon_chain.rs:6615): a duplicate instance whose attestations reach
    this node only inside aggregates or blocks must still read live."""
    epoch = int(ctx.params["epoch"])
    chain = ctx.chain
    out = []
    for raw in (ctx.body or []):
        idx = int(raw)
        out.append({
            "index": str(idx),
            "is_live": bool(chain.observed.validator_seen_at_epoch(
                epoch, idx, chain.spec.slots_per_epoch)),
        })
    return {"data": out}


def _pool_attestations(ctx):
    atts = list(ctx.chain.attestation_pool._pool.values())
    slot = ctx.q1("slot")
    index = ctx.q1("committee_index")
    if slot is not None:
        atts = [a for a in atts if int(a.data.slot) == int(slot)]
    if index is not None:
        atts = [a for a in atts if int(a.data.index) == int(index)]
    return atts


@route("GET", "/eth/v1/beacon/pool/attestations")
def pool_attestations_get(ctx):
    return {"data": [to_json(a) for a in _pool_attestations(ctx)]}


@route("GET", "/eth/v2/beacon/pool/attestations")
def pool_attestations_get_v2(ctx):
    """v2 wraps the pool dump in a version envelope (electra-era API)."""
    chain = ctx.chain
    version = chain.spec.fork_name_at_slot(chain.current_slot())
    return {"version": version,
            "data": [to_json(a) for a in _pool_attestations(ctx)]}


def _publish_op(ctx, kind: str, op) -> None:
    """Gossip a freshly-pooled operation out (reference publish flow); a
    node without networking simply has no hook installed."""
    publish = getattr(ctx.server, "publish_operation_fn", None)
    if publish is not None:
        publish(kind, op)


@route("POST", "/eth/v1/beacon/pool/voluntary_exits", P0)
def pool_exits_post(ctx):
    from ..chain.beacon_chain import ChainError

    chain = ctx.chain
    exit_ = container_from_json(chain.types.SignedVoluntaryExit, ctx.body)
    # Validation + dedup + pooling + SSE share ONE owner with the gossip
    # path (the reference's verify_operation path).
    try:
        fresh = chain.on_gossip_voluntary_exit(exit_)
    except ChainError as e:
        raise _bad(str(e))
    if fresh:
        _publish_op(ctx, "voluntary_exit", exit_)
    return None


@route("GET", "/eth/v1/beacon/pool/voluntary_exits")
def pool_exits_get(ctx):
    return {"data": [to_json(e) for e in ctx.chain.op_pool._voluntary_exits.values()]}


@route("POST", "/eth/v1/beacon/pool/proposer_slashings", P0)
def pool_proposer_slashings_post(ctx):
    from ..chain.beacon_chain import ChainError

    chain = ctx.chain
    slashing = container_from_json(chain.types.ProposerSlashing, ctx.body)
    try:
        fresh = chain.on_gossip_proposer_slashing(slashing)
    except ChainError as e:
        raise _bad(str(e))
    if fresh:
        _publish_op(ctx, "proposer_slashing", slashing)
    return None


@route("GET", "/eth/v1/beacon/pool/proposer_slashings")
def pool_proposer_slashings_get(ctx):
    return {"data": [to_json(s) for s in ctx.chain.op_pool._proposer_slashings.values()]}


@route("POST", "/eth/v1/beacon/pool/attester_slashings", P0)
def pool_attester_slashings_post(ctx):
    from ..chain.beacon_chain import ChainError

    chain = ctx.chain
    slashing = container_from_json(chain.types.AttesterSlashing, ctx.body)
    try:
        fresh = chain.on_gossip_attester_slashing(slashing)
    except ChainError as e:
        raise _bad(str(e))
    if fresh:
        _publish_op(ctx, "attester_slashing", slashing)
    return None


@route("GET", "/eth/v1/beacon/pool/attester_slashings")
def pool_attester_slashings_get(ctx):
    return {"data": [to_json(s) for s in ctx.chain.op_pool.attester_slashings()]}


@route("POST", "/eth/v2/beacon/pool/attester_slashings", P0)
def pool_attester_slashings_post_v2(ctx):
    """v2 submission: Eth-Consensus-Version selects the per-fork container
    (electra slashings carry IndexedAttestationElectra)."""
    chain = ctx.chain
    version = (ctx.headers.get("Eth-Consensus-Version") or "").lower()
    from ..chain.beacon_chain import ChainError

    cls = (chain.types.AttesterSlashingElectra if version == "electra"
           else chain.types.AttesterSlashing)
    slashing = container_from_json(cls, ctx.body)
    try:
        fresh = chain.on_gossip_attester_slashing(slashing)
    except ChainError as e:
        raise _bad(str(e))
    if fresh:
        _publish_op(ctx, "attester_slashing", slashing)
    return None


@route("GET", "/eth/v2/beacon/pool/attester_slashings")
def pool_attester_slashings_get_v2(ctx):
    chain = ctx.chain
    version = chain.spec.fork_name_at_slot(chain.current_slot())
    return {"version": version,
            "data": [to_json(s) for s in chain.op_pool.attester_slashings()]}


@route("POST", "/eth/v1/beacon/pool/bls_to_execution_changes", P0)
def pool_bls_changes_post(ctx):
    from ..chain.beacon_chain import ChainError

    chain = ctx.chain
    # Beacon-API batch contract: process EVERY item, report per-index
    # failures — one bad change must not drop the valid ones after it.
    failures = []
    scratch = chain.head_state.copy() if ctx.body else None  # one copy per batch
    for i, change_json in enumerate(ctx.body or []):
        try:
            change = container_from_json(
                chain.types.SignedBLSToExecutionChange, change_json)
            fresh = chain.on_gossip_bls_change(change, scratch=scratch)
        except (ChainError, KeyError, ValueError, TypeError) as e:
            failures.append({"index": i, "message": str(e)})
            continue
        if fresh:
            _publish_op(ctx, "bls_to_execution_change", change)
    if failures:
        raise ApiError(400, json.dumps({
            "code": 400,
            "message": "error processing bls_to_execution_changes",
            "failures": failures,
        }))
    return None


# --------------------------------------------------------- validator routes


def _advance_to_epoch(ctx, epoch: int):
    """Head state advanced (empty slots) to the start of ``epoch``."""
    chain = ctx.chain
    spec = chain.spec
    state = chain.head_state
    target = epoch * spec.slots_per_epoch
    if int(state.slot) < target:
        state, _ = chain.state_at_slot(target)
    return state


def _dependent_root(ctx, epoch: int) -> bytes:
    """Block root the duties depend on (last block before epoch start)."""
    chain = ctx.chain
    slot = epoch * chain.spec.slots_per_epoch
    if slot == 0:
        return chain.genesis_block_root
    root = chain.block_root_at_slot(slot - 1)
    return root if root is not None else chain.genesis_block_root


@route("GET", "/eth/v1/validator/duties/proposer/{epoch}", PD, cache=CKPT)
def duties_proposer(ctx):
    chain = ctx.chain
    spec = chain.spec
    epoch = int(ctx.params["epoch"])
    state = _advance_to_epoch(ctx, epoch)
    duties = []
    state = state.copy()
    from ..consensus.per_slot import process_slots

    for slot in range(epoch * spec.slots_per_epoch, (epoch + 1) * spec.slots_per_epoch):
        if int(state.slot) < slot:
            process_slots(state, slot, chain.types, spec)
        proposer = h.get_beacon_proposer_index(state, spec, slot=slot)
        duties.append({
            "pubkey": "0x" + bytes(state.validators[proposer].pubkey).hex(),
            "validator_index": str(proposer),
            "slot": str(slot),
        })
    return {
        "dependent_root": "0x" + _dependent_root(ctx, epoch).hex(),
        "execution_optimistic": False,
        "data": duties,
    }


@route("POST", "/eth/v1/validator/duties/attester/{epoch}", PD, cache=CKPT)
def duties_attester(ctx):
    chain = ctx.chain
    spec = chain.spec
    epoch = int(ctx.params["epoch"])
    indices = [int(i) for i in (ctx.body or [])]
    state = _advance_to_epoch(ctx, epoch)
    committees_per_slot = h.get_committee_count_per_slot(state, epoch, spec)
    wanted = set(indices)
    duties = []
    for slot in range(epoch * spec.slots_per_epoch, (epoch + 1) * spec.slots_per_epoch):
        for index in range(committees_per_slot):
            committee = h.get_beacon_committee(state, slot, index, spec)
            for pos, vidx in enumerate(committee):
                if int(vidx) in wanted:
                    duties.append({
                        "pubkey": "0x" + bytes(state.validators[int(vidx)].pubkey).hex(),
                        "validator_index": str(int(vidx)),
                        "committee_index": str(index),
                        "committee_length": str(len(committee)),
                        "committees_at_slot": str(committees_per_slot),
                        "validator_committee_index": str(pos),
                        "slot": str(slot),
                    })
    return {
        "dependent_root": "0x" + _dependent_root(ctx, max(epoch - 1, 0)).hex(),
        "execution_optimistic": False,
        "data": duties,
    }


@route("POST", "/eth/v1/validator/duties/sync/{epoch}", PD, cache=CKPT)
def duties_sync(ctx):
    chain = ctx.chain
    epoch = int(ctx.params["epoch"])
    indices = {int(i) for i in (ctx.body or [])}
    state = _advance_to_epoch(ctx, epoch)
    if not hasattr(state, "current_sync_committee"):
        return {"data": [], "execution_optimistic": False}
    pk_to_index = {bytes(v.pubkey): i for i, v in enumerate(state.validators)}
    duties: Dict[int, List[int]] = {}
    for pos, pk in enumerate(state.current_sync_committee.pubkeys):
        vidx = pk_to_index.get(bytes(pk))
        if vidx is not None and vidx in indices:
            duties.setdefault(vidx, []).append(pos)
    return {
        "execution_optimistic": False,
        "data": [
            {
                "pubkey": "0x" + bytes(state.validators[vidx].pubkey).hex(),
                "validator_index": str(vidx),
                "validator_sync_committee_indices": [str(p) for p in positions],
            }
            for vidx, positions in duties.items()
        ],
    }


@route("GET", "/eth/v3/validator/blocks/{slot}", P0)
def produce_block_v3(ctx):
    """v3 production: builder path when a relay is configured and bids
    (reference ``produce_block.rs`` local-vs-builder choice — builder first,
    local fallback on any failure)."""
    from ..chain.beacon_chain import ChainError

    chain = ctx.chain
    slot = int(ctx.params["slot"])
    reveal = ctx.q1("randao_reveal")
    if reveal is None:
        raise _bad("randao_reveal is required")
    graffiti = ctx.q1("graffiti")
    kwargs = {}
    if graffiti:
        kwargs["graffiti"] = bytes.fromhex(graffiti[2:]).ljust(32, b"\x00")
    blinded = False
    block = None
    if chain.builder is not None and ctx.q1("builder_boost_factor") != "0":
        try:
            block, _ = chain.produce_blinded_block(
                slot, bytes.fromhex(reveal[2:]), **kwargs
            )
            blinded = True
        except ChainError:
            block = None  # fall back to local production
    if block is None:
        block, _ = chain.produce_block(slot, bytes.fromhex(reveal[2:]), **kwargs)
    return {
        "version": type(block).fork_name,
        "execution_payload_blinded": blinded,
        "execution_payload_value": "0",
        "consensus_block_value": "0",
        "data": to_json(block),
    }


@route("GET", "/eth/v1/validator/blinded_blocks/{slot}", P0)
def produce_blinded_block_route(ctx):
    chain = ctx.chain
    slot = int(ctx.params["slot"])
    reveal = ctx.q1("randao_reveal")
    if reveal is None:
        raise _bad("randao_reveal is required")
    graffiti = ctx.q1("graffiti")
    kwargs = {}
    if graffiti:
        kwargs["graffiti"] = bytes.fromhex(graffiti[2:]).ljust(32, b"\x00")
    from ..chain.beacon_chain import ChainError

    try:
        block, _ = chain.produce_blinded_block(
            slot, bytes.fromhex(reveal[2:]), **kwargs
        )
    except ChainError as e:
        raise _bad(f"blinded production failed: {e}")
    return {"version": type(block).fork_name, "data": to_json(block)}


@route("POST", "/eth/v1/beacon/blinded_blocks", P0)
@route("POST", "/eth/v2/beacon/blinded_blocks", P0)
def publish_blinded_block(ctx):
    from ..chain.beacon_chain import BlockError, ChainError

    chain = ctx.chain
    if isinstance(ctx.body, (bytes, bytearray)):
        signed = _decode_ssz_signed_block(
            ctx, bytes(ctx.body), chain.types.signed_blinded_block
        )
    else:
        version = ctx.headers.get("Eth-Consensus-Version")
        if version is None:
            version = chain.spec.fork_name_at_slot(int(ctx.body["message"]["slot"]))
        cls = chain.types.signed_blinded_block.get(str(version).lower())
        if cls is None:
            raise _bad(f"unknown consensus version {version!r}")
        try:
            signed = container_from_json(cls, ctx.body)
        except (KeyError, TypeError, ValueError) as e:
            raise _bad(f"malformed SignedBlindedBeaconBlock: {e}")
    try:
        _root, signed_full = chain.unblind_and_import(signed)
    except (BlockError, ChainError) as e:
        raise _bad(f"invalid blinded block: {e}")
    publish = getattr(ctx.server, "publish_block_fn", None)
    if publish is not None:
        publish(signed_full)
    return None


publish_blinded_block._accepts_ssz = True


@route("POST", "/eth/v1/validator/register_validator", P0)
def register_validator(ctx):
    """Forward fee-recipient registrations to the configured relay
    (reference ``register_validators`` passthrough); a no-op without one."""
    chain = ctx.chain
    if chain.builder is None:
        return None
    regs = [
        container_from_json(chain.types.SignedValidatorRegistrationV1, r)
        for r in (ctx.body or [])
    ]
    from ..execution_layer.builder_client import BuilderError

    try:
        chain.builder.register_validators(regs)
    except BuilderError as e:
        raise ApiError(502, json.dumps({"code": 502, "message": str(e)}))
    return None


@route("GET", "/eth/v1/validator/attestation_data", P0)
def attestation_data(ctx):
    slot = ctx.q1("slot")
    committee_index = ctx.q1("committee_index")
    if slot is None or committee_index is None:
        raise _bad("slot and committee_index are required")
    data = ctx.chain.produce_attestation_data(int(slot), int(committee_index))
    return {"data": to_json(data)}


@route("GET", "/eth/v1/validator/aggregate_attestation", P0)
@route("GET", "/eth/v2/validator/aggregate_attestation", P0)
def aggregate_attestation(ctx):
    root_hex = ctx.q1("attestation_data_root")
    slot = ctx.q1("slot")
    committee_index = ctx.q1("committee_index")  # v2 (electra) parameter
    if root_hex is None or slot is None:
        raise _bad("attestation_data_root and slot are required")
    att = ctx.chain.attestation_pool.get_aggregate(
        int(slot), bytes.fromhex(root_hex[2:]),
        committee_index=None if committee_index is None else int(committee_index),
    )
    if att is None:
        raise _not_found("no aggregate for that data root")
    return {"data": to_json(att)}


@route("POST", "/eth/v1/validator/aggregate_and_proofs", P0)
@route("POST", "/eth/v2/validator/aggregate_and_proofs", P0)
def aggregate_and_proofs(ctx):
    from ..chain.beacon_chain import AttestationError

    chain = ctx.chain
    failures = []
    for i, agg_json in enumerate(ctx.body or []):
        try:
            signed = container_from_json(chain.types.SignedAggregateAndProof, agg_json)
            chain.process_aggregate(signed)
        except (AttestationError, KeyError, ValueError) as e:
            failures.append({"index": i, "message": str(e)})
    if failures:
        raise ApiError(400, json.dumps({
            "code": 400,
            "message": "error processing aggregates",
            "failures": failures,
        }))
    return None


@route("POST", "/eth/v1/validator/beacon_committee_subscriptions", P0)
def committee_subscriptions(ctx):
    """Feed aggregator duty subscriptions to the subnet service (reference
    subnet_service/attestation_subnets.rs); a no-op when the node runs
    without networking (or with --subscribe-all-subnets)."""
    subnets = getattr(ctx.server, "subnet_service", None)
    if subnets is not None:
        subnets.on_committee_subscriptions(ctx.body or [])
    return None


@route("POST", "/eth/v1/validator/sync_committee_subscriptions", P0)
def sync_subscriptions(ctx):
    subnets = getattr(ctx.server, "subnet_service", None)
    if subnets is not None:
        subnets.on_sync_committee_subscriptions(ctx.body or [])
    return None


@route("POST", "/eth/v1/validator/prepare_beacon_proposer", P0)
def prepare_proposer(ctx):
    """Record per-validator fee recipients (reference proposer_prep_service:
    the VC's PreparationService posts these each epoch; payload production
    consumes them)."""
    chain = ctx.chain
    for entry in (ctx.body or []):
        try:
            idx = int(entry["validator_index"])
            recipient = bytes.fromhex(entry["fee_recipient"][2:])
        except (KeyError, TypeError, ValueError) as e:
            raise _bad(f"malformed preparation entry: {e}")
        if len(recipient) != 20:
            raise _bad("fee_recipient must be 20 bytes")
        chain.proposer_preparations[idx] = recipient
    return None


# ------------------------------------------------------------ config routes


def _validator_indices(state, raw_ids):
    """Beacon-API validator ids: indices or 0x-pubkeys -> index list (400 on
    junk), None when the body is empty (= all validators)."""
    if not raw_ids:
        return None
    out = []
    pk_to_idx = None
    for item in raw_ids:
        s = str(item)
        if s.startswith("0x"):
            if pk_to_idx is None:
                pk_to_idx = {bytes(v.pubkey): i for i, v in enumerate(state.validators)}
            try:
                idx = pk_to_idx.get(bytes.fromhex(s[2:]))
            except ValueError:
                raise _bad(f"invalid pubkey {s!r}")
            if idx is None:
                raise _bad(f"unknown validator {s!r}")
            out.append(idx)
        else:
            try:
                idx = int(s)
            except ValueError:
                raise _bad(f"invalid validator id {s!r}")
            if not (0 <= idx < len(state.validators)):
                raise _bad(f"unknown validator index {idx}")
            out.append(idx)
    return out


@route("POST", "/eth/v1/beacon/rewards/attestations/{epoch}", P1, cache=CKPT)
def rewards_attestations(ctx):
    """Attestation rewards for ``epoch`` (reference attestation_rewards.rs):
    computed on a state in epoch+1, whose previous-epoch participation IS
    epoch's."""
    from ..chain import rewards as rewards_mod

    chain = ctx.chain
    epoch = int(ctx.params["epoch"])
    spe = chain.spec.slots_per_epoch
    # Rewards for E need previous-epoch participation of E INCLUDING late
    # inclusions, i.e. the state at the END of epoch E+1 (reference
    # attestation_rewards.rs); resolve_state serves historical slots too.
    target_slot = min((epoch + 2) * spe - 1,
                      max(int(chain.head_state.slot), (epoch + 1) * spe))
    state, _ = ctx.resolve_state(str(target_slot))
    ids = _validator_indices(state, ctx.body)
    try:
        data = rewards_mod.attestation_rewards(state, chain.spec, ids)
    except ValueError as e:
        raise _bad(str(e))
    return {"execution_optimistic": False, "finalized": False, "data": data}


@route("GET", "/eth/v1/beacon/rewards/blocks/{block_id}", P1, cache=CKPT)
def rewards_blocks(ctx):
    from ..chain import rewards as rewards_mod

    root = ctx.resolve_block_root(ctx.params["block_id"])
    data = rewards_mod.block_rewards(ctx.chain, root)
    if data is None:
        raise _not_found("block or its states unavailable")
    return {"execution_optimistic": False, "finalized": False, "data": data}


@route("POST", "/eth/v1/beacon/rewards/sync_committee/{block_id}", P1, cache=CKPT)
def rewards_sync_committee(ctx):
    from ..chain import rewards as rewards_mod
    from ..consensus.per_slot import process_slots

    chain = ctx.chain
    root = ctx.resolve_block_root(ctx.params["block_id"])
    block = chain.get_block(root)
    if block is None:
        raise _not_found("unknown block")
    pre = chain.get_state(bytes(block.message.parent_root))
    if pre is None:
        raise _not_found("parent state unavailable")
    pre = pre.copy()
    if int(pre.slot) < int(block.message.slot):
        pre = process_slots(pre, int(block.message.slot), chain.types, chain.spec)
    ids = _validator_indices(pre, ctx.body)
    data = rewards_mod.sync_committee_rewards(pre, block, chain.spec, ids)
    return {"execution_optimistic": False, "finalized": False, "data": data}


@route("POST", "/lighthouse/ui/validator_monitor", P1)
def validator_monitor_register(ctx):
    """Register validator indices with the monitor (reference:
    --validator-monitor flags + the lighthouse UI endpoints)."""
    chain = ctx.chain
    epoch = chain.current_slot() // chain.spec.slots_per_epoch
    chain.validator_monitor.register(
        (int(i) for i in (ctx.body or [])), current_epoch=epoch
    )
    return None


@route("POST", "/lighthouse/ui/validator_metrics", P1)
def validator_metrics(ctx):
    """Cumulative hit/miss metrics for monitored validators (reference
    http_api/src/ui.rs:176 post_validator_monitor_metrics)."""
    body = ctx.body or {}
    indices = [int(i) for i in body.get("indices", [])]
    return {"data": ctx.chain.validator_monitor.validator_metrics(indices)}


@route("GET", "/lighthouse/ui/validator_monitor/{epoch}", P1)
def validator_monitor_summary(ctx):
    return {"data": ctx.chain.validator_monitor.summary(int(ctx.params["epoch"]))}


@route("GET", "/eth/v1/beacon/light_client/bootstrap/{block_root}")
def lc_bootstrap(ctx):
    root = bytes.fromhex(ctx.params["block_root"][2:])
    bootstrap = ctx.chain.produce_light_client_bootstrap(root)
    if bootstrap is None:
        raise _not_found("no light-client bootstrap for that root")
    return {"version": "altair", "data": to_json(bootstrap)}


@route("GET", "/eth/v1/beacon/light_client/updates")
def lc_updates(ctx):
    start = ctx.q1("start_period")
    count = ctx.q1("count")
    if start is None or count is None:
        raise _bad("start_period and count are required")
    updates = ctx.chain.lc_cache.get_updates(int(start), int(count))
    return [{"version": "altair", "data": to_json(u)} for u in updates]


@route("GET", "/eth/v1/beacon/light_client/finality_update")
def lc_finality_update(ctx):
    u = ctx.chain.lc_cache.latest_finality_update
    if u is None:
        raise _not_found("no finality update available")
    return {"version": "altair", "data": to_json(u)}


@route("GET", "/eth/v1/beacon/light_client/optimistic_update")
def lc_optimistic_update(ctx):
    u = ctx.chain.lc_cache.latest_optimistic_update
    if u is None:
        raise _not_found("no optimistic update available")
    return {"version": "altair", "data": to_json(u)}


@route("GET", "/eth/v1/config/spec")
def config_spec(ctx):
    spec = ctx.chain.spec
    preset = spec.preset
    out = {}
    for obj in (spec, preset):
        for k, v in vars(obj).items():
            if isinstance(v, bool) or k in ("preset", "config_name", "name"):
                continue
            if isinstance(v, int):
                out[k.upper()] = str(v)
            elif isinstance(v, bytes):
                out[k.upper()] = "0x" + v.hex()
    out["PRESET_BASE"] = preset.name
    out["CONFIG_NAME"] = spec.config_name
    out["SECONDS_PER_SLOT"] = str(spec.seconds_per_slot)
    return {"data": out}


@route("GET", "/eth/v1/config/fork_schedule")
def config_fork_schedule(ctx):
    spec = ctx.chain.spec
    sched = []
    prev = spec.genesis_fork_version
    forks = [
        ("phase0", spec.genesis_fork_version, 0),
        ("altair", spec.altair_fork_version, spec.altair_fork_epoch),
        ("bellatrix", spec.bellatrix_fork_version, spec.bellatrix_fork_epoch),
        ("capella", spec.capella_fork_version, spec.capella_fork_epoch),
        ("deneb", spec.deneb_fork_version, spec.deneb_fork_epoch),
        ("electra", spec.electra_fork_version, getattr(spec, "electra_fork_epoch", None)),
    ]
    for _, version, epoch in forks:
        if epoch is None:
            continue
        sched.append({
            "previous_version": "0x" + prev.hex(),
            "current_version": "0x" + version.hex(),
            "epoch": str(epoch),
        })
        prev = version
    return {"data": sched}


@route("GET", "/eth/v1/config/deposit_contract")
def config_deposit_contract(ctx):
    spec = ctx.chain.spec
    return {"data": {
        "chain_id": str(getattr(spec, "deposit_chain_id", 1)),
        "address": "0x" + "00" * 20,
    }}


# ------------------------------------------------------------- debug routes


@route("GET", "/eth/v2/debug/beacon/states/{state_id}")
def debug_state(ctx):
    state, _ = ctx.resolve_state(ctx.params["state_id"])
    fork = type(state).fork_name
    if ctx.wants_ssz:
        return SszResponse(state.as_ssz_bytes(), fork)
    return {
        "version": fork,
        "execution_optimistic": False,
        "finalized": False,
        "data": to_json(state),
    }


def _head_entries(ctx, with_optimistic: bool):
    chain = ctx.chain
    proto = chain.fork_choice.proto
    heads = []
    with chain.fork_choice.locked():  # prune() rebuilds the node array
        for root in proto.head_roots() if hasattr(proto, "head_roots") else [chain.head_root]:
            entry = {"root": "0x" + root.hex(), "slot": str(chain._blocks_slot(root))}
            if with_optimistic:
                entry["execution_optimistic"] = False
            heads.append(entry)
    return heads


@route("GET", "/eth/v1/debug/beacon/heads", cache=CKPT_BLOCKS)
def debug_heads(ctx):
    return {"data": _head_entries(ctx, with_optimistic=False)}


@route("GET", "/eth/v2/debug/beacon/heads", cache=CKPT_BLOCKS)
def debug_heads_v2(ctx):
    """v2 adds per-head execution_optimistic (reference get_debug_beacon_heads
    accepts any endpoint version via its any_version filter)."""
    return {"data": _head_entries(ctx, with_optimistic=True)}


@route("GET", "/eth/v1/debug/beacon/states/{state_id}")
def debug_state_v1(ctx):
    """v1 debug state: bare {data}, no version envelope (reference
    get_debug_beacon_states is version-generic; V1 responses are
    unversioned)."""
    state, _ = ctx.resolve_state(ctx.params["state_id"])
    fork = type(state).fork_name
    if ctx.wants_ssz:
        return SszResponse(state.as_ssz_bytes(), fork)
    return {"data": to_json(state)}


@route("GET", "/eth/v1/debug/fork_choice")
def debug_fork_choice(ctx):
    chain = ctx.chain
    proto = chain.fork_choice.proto
    nodes = []
    with chain.fork_choice.locked():  # prune() rebuilds the node array
        for node in proto.nodes_snapshot() if hasattr(proto, "nodes_snapshot") else []:
            nodes.append(node)
    j_epoch, j_root = chain.justified_checkpoint()
    f_epoch, f_root = chain.finalized_checkpoint()
    return {
        "justified_checkpoint": {"epoch": str(j_epoch), "root": "0x" + j_root.hex()},
        "finalized_checkpoint": {"epoch": str(f_epoch), "root": "0x" + f_root.hex()},
        "fork_choice_nodes": nodes,
    }


# ------------------------------------------ standard-API completion (r4)
# Reference beacon_node/http_api/src/lib.rs routes absent until round 4.


@route("GET", "/eth/v1/beacon/blinded_blocks/{block_id}", cache=CKPT)
def beacon_blinded_block(ctx):
    """The stored block served in blinded form (payload summarized to its
    header) — identical hash_tree_root by construction.  Reads the store's
    blinded representation directly when present: no EL round trip, and a
    payload the EL has since pruned cannot fail this endpoint."""
    root = ctx.resolve_block_root(ctx.params["block_id"])
    signed = ctx.chain.get_blinded_block(root)
    if signed is None:
        raise _not_found(f"block {ctx.params['block_id']}")
    fork = type(signed.message).fork_name
    return {"version": fork, "execution_optimistic": False,
            "finalized": False, "data": to_json(signed)}


@route("GET", "/eth/v1/beacon/deposit_snapshot")
def beacon_deposit_snapshot(ctx):
    """EIP-4881 deposit-tree snapshot from the eth1 follower (empty when no
    eth1 service is wired)."""
    svc = ctx.chain.eth1_service
    if svc is None or len(svc.deposit_cache) == 0:
        raise ApiError(404, "no deposit snapshot available")
    cache = svc.deposit_cache
    count = len(cache)
    return {"data": {
        "finalized": [],
        "deposit_root": "0x" + cache.deposit_root(count).hex(),
        "deposit_count": str(count),
        "execution_block_hash": "0x" + (
            svc.block_cache[-1]["hash"] if svc.block_cache else "00" * 32
        ).replace("0x", ""),
        "execution_block_height": str(
            svc.block_cache[-1]["number"] if svc.block_cache else 0),
    }}


@route("GET", "/eth/v1/beacon/pool/bls_to_execution_changes")
def pool_bls_changes_get(ctx):
    changes = list(ctx.chain.op_pool._bls_changes.values())
    return {"data": [to_json(c) for c in changes]}


@route("GET", "/eth/v1/builder/states/{state_id}/expected_withdrawals", cache=CKPT)
def expected_withdrawals(ctx):
    """The withdrawals the next payload built on this state must contain."""
    state, _ = ctx.resolve_state(ctx.params["state_id"])
    if not hasattr(state, "next_withdrawal_index"):
        raise _bad("state is pre-capella: withdrawals do not exist yet")
    if type(state).fork_name == "electra":
        expected, _ = h.get_expected_withdrawals_electra(
            state, ctx.chain.types, ctx.chain.spec)
    else:
        expected = h.get_expected_withdrawals(state, ctx.chain.types, ctx.chain.spec)
    return {"execution_optimistic": False, "finalized": False,
            "data": [to_json(w) for w in expected]}


@route("GET", "/eth/v2/validator/blocks/{slot}", P0)
def produce_block_v2(ctx):
    """v2 production: always a FULL block (the pre-v3 contract)."""
    chain = ctx.chain
    slot = int(ctx.params["slot"])
    reveal = ctx.q1("randao_reveal")
    if reveal is None:
        raise _bad("randao_reveal is required")
    graffiti = ctx.q1("graffiti")
    kwargs = {}
    if graffiti:
        kwargs["graffiti"] = bytes.fromhex(graffiti[2:]).ljust(32, b"\x00")
    block, _ = chain.produce_block(slot, bytes.fromhex(reveal[2:]), **kwargs)
    return {"version": type(block).fork_name, "data": to_json(block)}


@route("POST", "/eth/v1/beacon/states/{state_id}/validator_balances", cache=CKPT)
def state_validator_balances_post(ctx):
    """POST variant: ids in the body (the GET query-string variant caps out
    on URL length for big id sets)."""
    ctx.query = dict(ctx.query)
    body = ctx.body or {}
    ids = body.get("ids") if isinstance(body, dict) else body
    if ids:
        ctx.query["id"] = [str(x) for x in ids]
    return state_balances(ctx)


@route("GET", "/eth/v1/node/peers/{peer_id}")
def node_peer_by_id(ctx):
    pm = getattr(ctx.server, "peer_manager", None)
    if pm is not None:
        for pid, info in pm.peers().items():
            if str(pid) == ctx.params["peer_id"]:
                return {"data": {
                    "peer_id": str(pid),
                    "enr": "",
                    "last_seen_p2p_address": "",
                    "state": "connected" if info.connected else "disconnected",
                    "direction": "outbound",
                }}
    raise ApiError(404, "peer not found")


# ---------------------------------------------- lighthouse extension routes
# Reference http_api lighthouse/* surface (operator/UI endpoints).


@route("GET", "/lighthouse/health")
def lighthouse_health(ctx):
    """Process + machine health (reference common/system_health observation
    surfaced by the /lighthouse/health endpoint)."""
    import os as _os

    from ..system_health import observe_all

    data = observe_all()
    la = _os.getloadavg() if hasattr(_os, "getloadavg") else (0.0, 0.0, 0.0)
    data["sys_loadavg_1"], data["sys_loadavg_5"], data["sys_loadavg_15"] = la
    return {"data": data}


@route("GET", "/lighthouse/ui/health")
def lighthouse_ui_health(ctx):
    data = lighthouse_health(ctx)["data"]
    data["network_name"] = getattr(ctx.server, "network_name", "custom")
    return {"data": data}


@route("GET", "/lighthouse/ui/validator_count")
def lighthouse_validator_count(ctx):
    state = ctx.chain.head_state
    epoch = h.get_current_epoch(state, ctx.chain.spec)
    counts = {"active_ongoing": 0, "active_exiting": 0, "active_slashed": 0,
              "pending_initialized": 0, "pending_queued": 0,
              "withdrawal_possible": 0, "withdrawal_done": 0,
              "exited_unslashed": 0, "exited_slashed": 0}
    from ..types.spec import FAR_FUTURE_EPOCH as far
    for v in state.validators:
        if v.activation_epoch <= epoch < v.exit_epoch:
            if v.slashed:
                counts["active_slashed"] += 1
            elif v.exit_epoch != far:
                counts["active_exiting"] += 1
            else:
                counts["active_ongoing"] += 1
        elif epoch < v.activation_epoch:
            counts["pending_queued" if v.activation_eligibility_epoch != far
                   else "pending_initialized"] += 1
        elif epoch >= v.withdrawable_epoch:
            counts["withdrawal_possible"] += 1
        else:
            counts["exited_slashed" if v.slashed else "exited_unslashed"] += 1
    return {"data": counts}


@route("GET", "/lighthouse/syncing")
def lighthouse_syncing(ctx):
    data = node_syncing(ctx)["data"]
    return {"data": "Synced" if not data["is_syncing"] else {
        "SyncingFinalized": {"start_slot": "0",
                             "target_slot": data["head_slot"]}}}


@route("GET", "/lighthouse/peers")
def lighthouse_peers(ctx):
    return node_peers(ctx)


@route("GET", "/lighthouse/peers/connected")
def lighthouse_peers_connected(ctx):
    full = node_peers(ctx)
    data = [p for p in full["data"] if p["state"] == "connected"]
    return {"data": data, "meta": {"count": len(data)}}


@route("GET", "/lighthouse/proto_array")
def lighthouse_proto_array(ctx):
    proto = ctx.chain.fork_choice.proto
    nodes = []
    with ctx.chain.fork_choice.locked():  # prune() rebuilds the node array
        for i, n in enumerate(proto.nodes):
            nodes.append({
                "slot": str(n.slot),
                "root": "0x" + n.root.hex(),
                "parent": n.parent,
                "weight": str(n.weight),
                "best_child": n.best_child,
                "best_descendant": n.best_descendant,
                "execution_status": n.execution_status,
            })
    return {"data": {
        "justified_checkpoint": {
            "epoch": str(proto.justified_checkpoint[0]),
            "root": "0x" + proto.justified_checkpoint[1].hex(),
        },
        "finalized_checkpoint": {
            "epoch": str(proto.finalized_checkpoint[0]),
            "root": "0x" + proto.finalized_checkpoint[1].hex(),
        },
        "nodes": nodes,
    }}


@route("GET", "/lighthouse/database/info")
def lighthouse_database_info(ctx):
    db = ctx.chain.db
    return {"data": {
        "schema_version": db.schema_version()
        if hasattr(db, "schema_version") else 0,
        "config": {
            "slots_per_restore_point": getattr(db, "slots_per_restore_point", 0),
        },
        "split": {"slot": str(getattr(ctx.chain, "_migrated_slot", 0))},
        "anchor": {"anchor_slot": str(ctx.chain.anchor_slot)},
    }}


@route("POST", "/lighthouse/database/reconstruct")
def lighthouse_database_reconstruct(ctx):
    """Kick historic-state reconstruction (checkpoint-synced nodes): replay
    from the anchor forward.  Synchronous here — the in-process store
    reconstructs via the backfill path."""
    n = 0
    if hasattr(ctx.chain, "reconstruct_historic_states"):
        n = ctx.chain.reconstruct_historic_states()
    return {"data": f"started reconstruction ({n} states)"}


@route("GET", "/lighthouse/eth1/syncing")
def lighthouse_eth1_syncing(ctx):
    svc = ctx.chain.eth1_service
    if svc is None:
        raise ApiError(404, "eth1 service not enabled")
    head = svc.block_cache[-1] if svc.block_cache else None
    return {"data": {
        "head_block_number": head["number"] if head else 0,
        "head_block_timestamp": head.get("timestamp", 0) if head else 0,
        "latest_cached_block_number": head["number"] if head else 0,
        "latest_cached_block_timestamp": head.get("timestamp", 0) if head else 0,
        "voting_target_timestamp": 0,
        "eth1_node_sync_status_percentage": 100.0,
        "lighthouse_is_cached_and_ready": head is not None,
    }}


@route("GET", "/lighthouse/eth1/block_cache")
def lighthouse_eth1_blocks(ctx):
    svc = ctx.chain.eth1_service
    if svc is None:
        raise ApiError(404, "eth1 service not enabled")
    return {"data": svc.block_cache}


@route("GET", "/lighthouse/eth1/deposit_cache")
def lighthouse_eth1_deposits(ctx):
    svc = ctx.chain.eth1_service
    if svc is None:
        raise ApiError(404, "eth1 service not enabled")
    return {"data": [to_json(d) for d in svc.deposit_cache._deposit_data]}


@route("GET", "/lighthouse/nat")
def lighthouse_nat(ctx):
    return {"data": True}  # own-fabric transport: no NAT discovery problem


@route("GET", "/lighthouse/staking")
def lighthouse_staking(ctx):
    # reference: 200 iff the node was started with staking flags (eth1 /
    # payload production able); our chain always has an execution engine
    return {"data": ctx.chain.execution_engine is not None}


@route("GET", "/lighthouse/merge_readiness")
def lighthouse_merge_readiness(ctx):
    state = ctx.chain.head_state
    merged = hasattr(state, "latest_execution_payload_header") and any(
        bytes(state.latest_execution_payload_header.block_hash)
    )
    return {"data": {"type": "ready", "config": {"post_merge": merged}}}


def _inclusion_state(ctx, epoch: int):
    """The state whose ``current_epoch_participation`` register belongs to
    the requested epoch (reference validator_inclusion loads the state at
    the requested epoch, so 'current'/'previous' fields each come from
    their own register)."""
    chain = ctx.chain
    state = chain.head_state
    current_epoch = h.get_current_epoch(state, chain.spec)
    if epoch not in (current_epoch, max(0, current_epoch - 1)):
        raise _bad(f"epoch {epoch} is not the current or previous epoch")
    if epoch != current_epoch:
        # Rewind to the requested epoch's end: replay from the ANCESTOR block
        # at/before that slot (state_at_slot cannot rewind the head state).
        end_slot = (epoch + 1) * chain.spec.slots_per_epoch - 1
        ancestor = h.get_block_root_at_slot(state, end_slot, chain.spec)
        state, _ = chain.state_at_slot(end_slot, bytes(ancestor))
    return state


def _inclusion_data(ctx, epoch: int):
    """Per-epoch participation totals from the flag registry (the
    reference's validator_inclusion computed from participation caches) —
    current-epoch fields from ``current_epoch_participation``,
    previous-epoch fields from ``previous_epoch_participation``."""
    from ..types.spec import TIMELY_HEAD_FLAG_INDEX, TIMELY_TARGET_FLAG_INDEX

    state = _inclusion_state(ctx, epoch)
    prev_epoch = max(0, epoch - 1)
    cur_part = state.current_epoch_participation
    prev_part = state.previous_epoch_participation
    cur_active = 0
    cur_target = prev_target = prev_head = 0
    for i, v in enumerate(state.validators):
        eb = int(v.effective_balance)
        if v.activation_epoch <= epoch < v.exit_epoch:
            cur_active += eb
            flags = int(cur_part[i]) if i < len(cur_part) else 0
            if flags & (1 << TIMELY_TARGET_FLAG_INDEX) and not v.slashed:
                cur_target += eb
        if v.activation_epoch <= prev_epoch < v.exit_epoch:
            flags = int(prev_part[i]) if i < len(prev_part) else 0
            if not v.slashed:
                if flags & (1 << TIMELY_TARGET_FLAG_INDEX):
                    prev_target += eb
                if flags & (1 << TIMELY_HEAD_FLAG_INDEX):
                    prev_head += eb
    # Exactly the reference GlobalValidatorInclusionData fields
    # (common/eth2/src/lighthouse.rs:54-66) — no extra keys.
    return {
        "current_epoch_active_gwei": str(cur_active),
        "current_epoch_target_attesting_gwei": str(cur_target),
        "previous_epoch_target_attesting_gwei": str(prev_target),
        "previous_epoch_head_attesting_gwei": str(prev_head),
    }


@route("GET", "/lighthouse/validator_inclusion/{epoch}/global")
def lighthouse_inclusion_global(ctx):
    return {"data": _inclusion_data(ctx, int(ctx.params["epoch"]))}


@route("GET", "/lighthouse/validator_inclusion/{epoch}/{validator_id}")
def lighthouse_inclusion_validator(ctx):
    from ..types.spec import (
        TIMELY_HEAD_FLAG_INDEX,
        TIMELY_SOURCE_FLAG_INDEX,
        TIMELY_TARGET_FLAG_INDEX,
    )

    chain = ctx.chain
    epoch = int(ctx.params["epoch"])
    state = _inclusion_state(ctx, epoch)
    prev_epoch = max(0, epoch - 1)
    vid = ctx.params["validator_id"]
    idx = int(vid) if not vid.startswith("0x") else next(
        (i for i, v in enumerate(state.validators)
         if bytes(v.pubkey).hex() == vid[2:]), -1)
    if not (0 <= idx < len(state.validators)):
        raise ApiError(404, "validator not found")
    v = state.validators[idx]
    cur_part = state.current_epoch_participation
    prev_part = state.previous_epoch_participation
    cur_flags = int(cur_part[idx]) if idx < len(cur_part) else 0
    prev_flags = int(prev_part[idx]) if idx < len(prev_part) else 0
    # Attester booleans follow the reference ParticipationCache's
    # is_unslashed_participating_index: flag AND active-in-epoch AND
    # not slashed (a slashed validator's stale flags must not read true).
    unslashed_cur = (v.activation_epoch <= epoch < v.exit_epoch) and not v.slashed
    unslashed_prev = (
        v.activation_epoch <= prev_epoch < v.exit_epoch
    ) and not v.slashed
    return {"data": {
        "is_slashed": bool(v.slashed),
        "is_withdrawable_in_current_epoch": epoch >= int(v.withdrawable_epoch),
        "is_active_unslashed_in_current_epoch": unslashed_cur,
        "is_active_unslashed_in_previous_epoch": unslashed_prev,
        "current_epoch_effective_balance_gwei": str(int(v.effective_balance)),
        "is_current_epoch_target_attester":
            unslashed_cur and bool(cur_flags & (1 << TIMELY_TARGET_FLAG_INDEX)),
        "is_previous_epoch_target_attester":
            unslashed_prev and bool(prev_flags & (1 << TIMELY_TARGET_FLAG_INDEX)),
        "is_previous_epoch_head_attester":
            unslashed_prev and bool(prev_flags & (1 << TIMELY_HEAD_FLAG_INDEX)),
        "is_previous_epoch_source_attester":
            unslashed_prev and bool(prev_flags & (1 << TIMELY_SOURCE_FLAG_INDEX)),
    }}


@route("POST", "/lighthouse/liveness")
def lighthouse_liveness(ctx):
    """Like the standard liveness route but takes {indices, epoch} in one
    body (the VC's preferred bulk shape)."""
    body = ctx.body or {}
    epoch = int(body.get("epoch", 0))
    chain = ctx.chain
    out = []
    for raw in body.get("indices", []):
        idx = int(raw)
        out.append({
            "index": str(idx),
            "epoch": str(epoch),
            "is_live": bool(chain.observed.validator_seen_at_epoch(
                epoch, idx, chain.spec.slots_per_epoch)),
        })
    return {"data": out}


def _block_rewards_range(ctx, start_slot: int, end_slot: int):
    from ..chain.rewards import block_rewards as _block_rewards

    chain = ctx.chain
    out = []
    root = chain.head_root
    # walk the canonical chain backwards through the requested window
    while root is not None and root != chain.genesis_block_root:
        slot = chain._blocks_slot(root)
        if slot < start_slot:
            break
        if slot <= end_slot:
            r = _block_rewards(chain, root)
            if r is not None:
                # analysis-layer enrichment (watch keys rows by slot); the
                # standard /eth/v1/beacon/rewards/blocks response keeps the
                # bare spec shape.
                r = dict(r, slot=str(slot), block_root="0x" + root.hex())
                out.append(r)
        blk = chain.get_block(root)
        if blk is None:
            break
        root = bytes(blk.message.parent_root)
    out.reverse()
    return out


@route("GET", "/lighthouse/analysis/block_rewards")
def lighthouse_block_rewards(ctx):
    start = int(ctx.q1("start_slot", "1"))
    end = int(ctx.q1("end_slot", str(ctx.chain.current_slot())))
    return {"data": _block_rewards_range(ctx, start, end)}


@route("POST", "/lighthouse/analysis/block_rewards")
def lighthouse_block_rewards_post(ctx):
    body = ctx.body or {}
    return {"data": _block_rewards_range(
        ctx, int(body.get("start_slot", 1)),
        int(body.get("end_slot", ctx.chain.current_slot())))}


@route("GET", "/lighthouse/analysis/attestation_performance/{index}")
def lighthouse_attestation_performance(ctx):
    """Per-validator inclusion record over an epoch range, from the
    validator monitor + participation flags."""
    from ..types.spec import TIMELY_TARGET_FLAG_INDEX

    chain = ctx.chain
    state = chain.head_state
    idx = int(ctx.params["index"])
    if idx >= len(state.validators):
        raise ApiError(404, "validator not found")
    current_epoch = h.get_current_epoch(state, chain.spec)
    start = int(ctx.q1("start_epoch", str(max(0, current_epoch - 1))))
    end = int(ctx.q1("end_epoch", str(current_epoch)))
    out = []
    for epoch in range(start, end + 1):
        if epoch == current_epoch:
            part = state.current_epoch_participation
        elif epoch == current_epoch - 1:
            part = state.previous_epoch_participation
        else:
            continue  # only the live window is cheaply answerable
        flags = int(part[idx]) if idx < len(part) else 0
        out.append({
            "epoch": str(epoch),
            "active": bool(
                state.validators[idx].activation_epoch <= epoch
                < state.validators[idx].exit_epoch),
            "attested": bool(flags & (1 << TIMELY_TARGET_FLAG_INDEX)),
        })
    return {"data": [{"index": str(idx), "epochs": out}]}


@route("GET", "/lighthouse/analysis/block_packing_efficiency")
def lighthouse_block_packing(ctx):
    """Attestation-packing efficiency over a slot window: included unique
    attester bits vs available (reference block_packing_efficiency.rs)."""
    chain = ctx.chain
    start = int(ctx.q1("start_epoch", "0"))
    end = int(ctx.q1("end_epoch", str(
        chain.current_slot() // chain.spec.slots_per_epoch)))
    spe = chain.spec.slots_per_epoch
    out = []
    root = chain.head_root
    while root is not None and root != chain.genesis_block_root:
        slot = chain._blocks_slot(root)
        if slot < start * spe:
            break
        blk = chain.get_block(root)
        if blk is None:
            break
        if slot < (end + 1) * spe:
            atts = list(blk.message.body.attestations)
            included = sum(
                sum(1 for b in a.aggregation_bits if b) for a in atts
            )
            out.append({
                "slot": str(slot),
                "block_hash": "0x" + root.hex(),
                "available_attestations": included,  # naive-pool upper bound
                "included_attestations": included,
                "prior_skip_slots": 0,
            })
        root = bytes(blk.message.parent_root)
    out.reverse()
    return {"data": out}


@route("POST", "/lighthouse/ui/validator_info")
def lighthouse_ui_validator_info(ctx):
    body = ctx.body or {}
    state = ctx.chain.head_state
    info = {}
    for raw in body.get("indices", []):
        idx = int(raw)
        if 0 <= idx < len(state.validators):
            v = state.validators[idx]
            info[str(idx)] = {
                "info": {
                    "activation_epoch": str(int(v.activation_epoch)),
                    "balance": str(int(state.balances[idx])),
                    "effective_balance": str(int(v.effective_balance)),
                    "slashed": bool(v.slashed),
                    "withdrawal_credentials":
                        "0x" + bytes(v.withdrawal_credentials).hex(),
                },
            }
    return {"data": {"validators": info}}


# ------------------------------------------------------------ traces routes
# The span-tracing surface (tracing.py): per-event span trees for the
# block-import → device-batch pipeline, the per-trace complement of the
# aggregate /metrics histograms.


@route("GET", "/lighthouse/traces", P1)
def lighthouse_traces(ctx):
    """Recent completed-trace summaries, newest first.  Query params:
    ``root`` (root-span name, e.g. ``block_import`` or ``work:gossip_block``),
    ``slot`` (root's slot field), ``limit``."""
    slot = ctx.q1("slot")
    try:
        limit = int(ctx.q1("limit", "64"))
    except ValueError:
        raise _bad("limit must be an integer")
    traces = tracing.TRACES.recent(
        limit=max(1, min(limit, 512)),
        root=ctx.q1("root"),
        slot=None if slot is None else int(slot),
    )
    return {"data": [tracing.trace_summary(t) for t in traces]}


@route("GET", "/lighthouse/traces/{trace_id}", P1)
def lighthouse_trace_by_id(ctx):
    """One full span tree; ``?format=chrome`` emits Chrome trace-event JSON
    loadable in Perfetto / chrome://tracing."""
    trace = tracing.TRACES.get(ctx.params["trace_id"])
    if trace is None:
        raise _not_found(f"trace {ctx.params['trace_id']}")
    if ctx.q1("format") == "chrome":
        return tracing.trace_to_chrome(trace)
    return {"data": tracing.trace_to_dict(trace)}


# ------------------------------------------------------------ device routes
# The device telemetry surface (device_telemetry.py): compile-cache
# inventory, padding-waste occupancy, the batch flight recorder, device
# memory, and the on-demand profiler — the "why was device_batch_wait
# slow" complement of the traces API.


@route("GET", "/lighthouse/device", P1)
def lighthouse_device(ctx):
    """Device telemetry summary: compiled-program inventory (op, bucket
    shape, compile seconds, invocation counts), occupancy percentiles over
    the flight-recorder window, host-fallback tallies, and per-device
    ``memory_stats()``."""
    from .. import device_telemetry

    return {"data": device_telemetry.summary()}


@route("GET", "/lighthouse/device/batches", P1)
def lighthouse_device_batches(ctx):
    """Recent device-batch flight-recorder records, newest first.  Query
    params: ``op`` (e.g. ``bls_verify``), ``trace_id`` (cross-reference
    from ``/lighthouse/traces/{id}``), ``node`` (records stamped by one
    node's telemetry scope), ``limit``."""
    from .. import device_telemetry

    try:
        limit = int(ctx.q1("limit", "64"))
    except ValueError:
        raise _bad("limit must be an integer")
    return {"data": device_telemetry.FLIGHT_RECORDER.recent(
        limit=max(1, min(limit, device_telemetry.FLIGHT_RECORDER.capacity)),
        op=ctx.q1("op"),
        trace_id=ctx.q1("trace_id"),
        node=ctx.q1("node"),
    )}


@route("POST", "/lighthouse/device/profile", P1)
def lighthouse_device_profile(ctx):
    """Capture ``?seconds=N`` (default 3, capped at 10 — the API task
    spawner allows 30 s per handler) of ``jax.profiler.trace`` and return
    the dump directory for Perfetto.  501 on CPU, 409 when a capture is
    already running."""
    from .. import device_telemetry

    try:
        seconds = float(ctx.q1("seconds", "3"))
    except ValueError:
        raise _bad("seconds must be a number")
    if seconds <= 0:
        raise _bad("seconds must be positive")
    try:
        return {"data": device_telemetry.capture_profile(seconds)}
    except device_telemetry.ProfilerUnavailable as e:
        raise ApiError(501, f"NOT_IMPLEMENTED: {e}")
    except device_telemetry.ProfilerBusy as e:
        raise ApiError(409, f"CONFLICT: {e}")


# ------------------------------------------------------------ faults routes
# The fault-injection admin surface (fault_injection.py): install, list,
# and clear deterministic fault plans against the named injection points —
# the chaos-testing companion of the device supervisor.


@route("GET", "/lighthouse/faults", P1)
def lighthouse_faults(ctx):
    """Active fault plans with hit/fired counts, plus the known points."""
    from .. import fault_injection

    return {"data": fault_injection.summary()}


@route("POST", "/lighthouse/faults", P1)
def lighthouse_faults_install(ctx):
    """Install fault plans.  Body: ``{"spec": "<plan;plan;...>"}`` (the
    env-var syntax, e.g. ``device.dispatch[op=bls_verify]=error``) or a
    single structured plan ``{"point": ..., "mode": ..., "op": ...,
    "first_n": ..., "probability": ..., "seed": ..., "sleep_s": ...}``."""
    from .. import fault_injection

    body = ctx.body or {}
    if not isinstance(body, dict):
        raise _bad("body must be a JSON object")
    try:
        if "spec" in body:
            plans = [
                fault_injection.REGISTRY.install(p)
                for p in fault_injection.parse_spec(body["spec"])
            ]
        elif "point" in body:
            kwargs = {
                k: body[k]
                for k in ("op", "first_n", "probability", "seed",
                          "sleep_s", "message")
                if body.get(k) is not None
            }
            plans = [fault_injection.install(
                body["point"], body.get("mode", "error"), **kwargs)]
        else:
            raise _bad("body needs a 'spec' string or a 'point' plan")
    except (TypeError, ValueError) as e:
        # TypeError: non-numeric probability/first_n/seed in a structured
        # plan — a client input error, not a server bug.
        raise _bad(str(e))
    return {"data": [p.to_dict() for p in plans]}


@route("DELETE", "/lighthouse/faults", P1)
def lighthouse_faults_clear(ctx):
    """Clear fault plans: all of them, ``?point=<point>``, or ``?id=<id>``."""
    from .. import fault_injection

    plan_id = ctx.q1("id")
    try:
        plan_id = None if plan_id is None else int(plan_id)
    except ValueError:
        raise _bad(f"id must be an integer, got {plan_id!r}")
    cleared = fault_injection.clear(point=ctx.q1("point"), plan_id=plan_id)
    return {"data": {"cleared": cleared}}


@route("GET", "/lighthouse/events/subscribers", P1)
def lighthouse_events_subscribers(ctx):
    """Per-subscriber SSE state: topics, queue depth, delivered and dropped
    event counts (the per-topic aggregates live on /metrics as
    ``http_sse_events_{sent,dropped}_total``)."""
    return {"data": ctx.chain.events.summary()}


@route("GET", "/lighthouse/autotune", P1)
def lighthouse_autotune(ctx):
    """The self-tuning control plane in one read (autotune.py): mode,
    static vs live bucket vocabularies, the decision log (every adoption /
    drop / refusal with its guardrail reason), warmup states, the measured
    fq-backend selection, and the admission layer's effective (latency-
    tracked) bounds next to its static configuration.  The first stop when
    "the controller made a bad decision" — see OBSERVABILITY.md."""
    from .. import autotune

    data = autotune.snapshot()
    data["admission"] = ctx.server.spawner.admission.snapshot()
    return {"data": data}


@route("GET", "/lighthouse/serving", P1)
def lighthouse_serving(ctx):
    """The serving-performance surface in one read: response-cache
    occupancy/hit-rate, per-class admission state, and the device
    arbiter's grant table (is API work contending like pipeline work?)."""
    from .. import device_pipeline

    cache = ctx.server.response_cache
    return {"data": {
        "cache": cache.snapshot() if cache is not None else None,
        "admission": ctx.server.spawner.admission.snapshot(),
        "arbiter": device_pipeline.ARBITER.snapshot(),
        "cached_routes": {
            f"{m} {p}": list(t) for (m, p), t in sorted(CACHED_ROUTES.items())
        },
    }}


# ---------------------------------------------------------- blackbox routes
# The incident black box (blackbox.py): the causally-ordered journal that
# every seam feeds, and the frozen postmortem bundles it writes on breaker
# trips / watchdog timeouts / scenario gate failures.


@route("GET", "/lighthouse/postmortems", P1)
def lighthouse_postmortems(ctx):
    """The black-box summary: journal occupancy, capture index (reason,
    slot, journal/flight/trace counts per bundle), and the bundle files on
    disk, newest first.  ``?bundle=<filename>`` returns one full bundle."""
    from .. import blackbox

    name = ctx.q1("bundle")
    if name is not None:
        bundle = blackbox.load_bundle(name)
        if bundle is None:
            raise _not_found(f"bundle {name}")
        return {"data": bundle}
    return {"data": blackbox.summary()}


@route("GET", "/lighthouse/postmortems/journal", P1)
def lighthouse_postmortems_journal(ctx):
    """The live incident journal, oldest first.  Query params: ``source``
    (e.g. ``breaker``, ``device_batch``), ``limit``."""
    from .. import blackbox

    try:
        limit = int(ctx.q1("limit", "256"))
    except ValueError:
        raise _bad("limit must be an integer")
    return {"data": blackbox.JOURNAL.window(
        limit=max(1, min(limit, blackbox.JOURNAL.capacity)),
        source=ctx.q1("source"),
    )}


@route("GET", "/lighthouse/fleet", P1)
def lighthouse_fleet(ctx):
    """Fleet observability (telemetry_scope.py): per-node scope snapshots
    (Lamport clock, journal/tail occupancy, per-scope tallies) and the
    merged causally-ordered timeline over every registered node's journal
    — ordered on (virtual slot, Lamport clock, node id, per-node seq), so
    "which node broke the fleet" reads top-to-bottom.  Query params:
    ``limit`` (tail of the merged timeline)."""
    from .. import blackbox

    limit = ctx.q1("limit")
    if limit is not None:
        try:
            limit = max(1, int(limit))
        except ValueError:
            raise _bad("limit must be an integer")
    return {"data": blackbox.fleet_summary(limit=limit)}


@route("POST", "/lighthouse/postmortem", P1)
def lighthouse_postmortem_capture(ctx):
    """Freeze a postmortem bundle right now (the operator's "something is
    off, snapshot everything" button).  Body: ``{"reason": "..."}``
    (optional; defaults to ``manual``)."""
    from .. import blackbox

    body = ctx.body or {}
    if not isinstance(body, dict):
        raise _bad("body must be a JSON object")
    reason = body.get("reason") or "manual"
    if not isinstance(reason, str):
        raise _bad("reason must be a string")
    return {"data": blackbox.capture(f"manual:{reason}"
                                     if reason != "manual" else "manual")}


# ------------------------------------------------------------------ server


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server_version = VERSION_STRING
    # Responses go out as (at least) two segments — buffered headers, then
    # body.  With Nagle on, the body write sits behind the peer's delayed
    # ACK: a measured ~40 ms floor per response on loopback, which would
    # bury the cache's sub-millisecond hits.
    disable_nagle_algorithm = True

    def log_message(self, fmt, *args):  # quiet
        pass

    @property
    def api(self) -> "HttpApiServer":
        return self.server.api_server  # type: ignore[attr-defined]

    def _write_json(self, code: int, payload,
                    headers: Optional[Dict[str, str]] = None) -> None:
        body = b"" if payload is None else json.dumps(payload).encode()
        self._write_json_bytes(code, body, headers)

    def _write_json_bytes(self, code: int, body: bytes,
                          headers: Optional[Dict[str, str]] = None) -> None:
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        for hk, hv in (headers or {}).items():
            self.send_header(hk, hv)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        if body:
            self.wfile.write(body)

    def _write_ssz(self, data: bytes, version: Optional[str],
                   headers: Dict[str, str]) -> None:
        self.send_response(200)
        self.send_header("Content-Type", "application/octet-stream")
        if version:
            self.send_header("Eth-Consensus-Version", version)
        for hk, hv in headers.items():
            self.send_header(hk, hv)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _write_cached(self, entry: "CacheEntry") -> None:
        if entry.kind == "ssz":
            self._write_ssz(entry.body, entry.version, dict(entry.headers))
        else:
            self._write_json_bytes(200, entry.body)

    def _handle(self, method: str) -> None:
        parsed = urlparse(self.path)
        path = parsed.path
        # Resolve the route TEMPLATE first: the metrics label must be
        # bounded-cardinality (templates + the three streaming endpoints +
        # "unmatched"), never the raw client-controlled path.
        if path in ("/metrics", "/eth/v1/events", "/lighthouse/logs"):
            route, m = path, None
        else:
            m = match_route(method, path)
            route = m[3] if m is not None else "unmatched"
        metrics.HTTP_REQUESTS.inc(method=method, route=route)
        labels = {"method": method, "route": route}
        # One seam feeds both the request histogram and the trace ring.
        # Streaming endpoints, 404s, and the traces API itself (observing
        # the observer) are timed but not traced.  The root name carries the
        # route template so each route gets its OWN bounded sub-ring — a
        # health-check poller must not evict the rare block-publish trace.
        if m is not None and not route.startswith("/lighthouse/traces"):
            timer = tracing.span(
                f"http:{method} {route}", hist=metrics.HTTP_REQUEST_SECONDS,
                hist_labels=labels, **labels,
            )
        else:
            timer = metrics.HTTP_REQUEST_SECONDS.time(**labels)
        with timer:
            try:
                if path == "/metrics" and method == "GET":
                    body = metrics.render_prometheus().encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "text/plain; version=0.0.4")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if path == "/eth/v1/events" and method == "GET":
                    self._serve_events(parse_qs(parsed.query))
                    return
                if path == "/lighthouse/logs" and method == "GET":
                    self._serve_logs()
                    return
                # Drain the body before any response — an unread body on a
                # keep-alive connection corrupts the next request.
                body = None
                length = int(self.headers.get("Content-Length") or 0)
                raw = self.rfile.read(length) if length else b""
                if m is None:
                    self._write_json(404, {"code": 404, "message": f"NOT_FOUND: {path}"})
                    return
                priority, fn, params, _ = m
                if raw:
                    ctype = (self.headers.get("Content-Type") or "").lower()
                    if "application/octet-stream" in ctype:
                        if not getattr(fn, "_accepts_ssz", False):
                            self._write_json(415, {
                                "code": 415,
                                "message": "this route does not accept application/octet-stream",
                            })
                            return
                        body = raw  # SSZ upload: the handler decodes
                    else:
                        try:
                            body = json.loads(raw)
                        except json.JSONDecodeError:
                            self._write_json(400, {"code": 400, "message": "invalid JSON"})
                            return
                ctx = Context(self.api, params, parse_qs(parsed.query), body, self.headers)
                # Checkpoint-keyed response cache (response_cache.py): a hit
                # replays stored bytes from the HTTP thread — no admission,
                # no scheduler queue, no handler.
                cache = self.api.response_cache
                topics = getattr(fn, "_cache_topics", None)
                ckey = None
                if cache is not None and topics:
                    ckey = cache.make_key(
                        method, route, params, ctx.query, body, ctx.wants_ssz)
                if ckey is not None:
                    hit = cache.get(ckey, route)
                    if hit is not None:
                        tracing.annotate(cache="hit")
                        self._write_cached(hit)
                        return
                    tracing.annotate(cache="miss")
                gen_box = {}
                if ckey is not None:
                    # Cache-miss state work must contend at the shared
                    # device arbiter like pipeline work does (ROADMAP item
                    # 4 REMAINING) — one bounded-cardinality op label for
                    # the whole API surface.  The cache generation is read
                    # on the worker thread just before the handler runs:
                    # put() refuses the entry if any invalidation event
                    # fired during execution (mid-handler reorg guard).
                    def call(fn=fn, ctx=ctx, cache=cache, gen_box=gen_box):
                        gen_box["gen"] = cache.generation
                        with api_arbiter_slot("http_api"):
                            return fn(ctx)
                else:
                    def call(fn=fn, ctx=ctx):
                        return fn(ctx)
                try:
                    result = self.api.spawner.blocking_json_task(
                        priority, call, klass=getattr(fn, "_klass", None))
                    # Store BEFORE writing: the moment the response bytes
                    # reach the client it may fire the next request, which
                    # must hit.
                    if isinstance(result, SszResponse):
                        if ckey is not None:
                            cache.put(ckey, route, CacheEntry(
                                "ssz", result.data, result.version,
                                tuple(result.headers.items()), ckey[0], topics),
                                generation=gen_box.get("gen"))
                        self._write_ssz(result.data, result.version, result.headers)
                    else:
                        body_bytes = (b"" if result is None
                                      else json.dumps(result).encode())
                        if ckey is not None and result is not None:
                            cache.put(ckey, route, CacheEntry(
                                "json", body_bytes, None, (), ckey[0], topics),
                                generation=gen_box.get("gen"))
                        self._write_json_bytes(200, body_bytes)
                except ValueError as e:
                    # Malformed user-supplied ints/hex parse straight to
                    # ValueError — a contract 400.  Other exception types stay
                    # 500s so server bugs aren't masked as client errors.
                    self._write_json(400, {"code": 400, "message": f"BAD_REQUEST: {e}"})
                except ApiError as e:
                    if e.code in (200, 206):  # health-style status responses
                        self._write_json(e.code, None)
                    else:
                        try:
                            payload = json.loads(e.message)
                        except (json.JSONDecodeError, TypeError):
                            payload = {"code": e.code, "message": e.message}
                        self._write_json(e.code, payload)
                except ShedError as e:
                    # Admission shed: immediate 503 + Retry-After so a
                    # well-behaved client backs off instead of hammering.
                    tracing.annotate(shed=e.reason)
                    self._write_json(
                        503, {"code": 503, "message": str(e)},
                        headers={"Retry-After": str(e.retry_after_s)})
                except OverloadedError as e:
                    self._write_json(503, {"code": 503, "message": str(e)},
                                     headers={"Retry-After": "1"})
                except TimeoutError as e:
                    self._write_json(504, {"code": 504, "message": str(e)})
            except BrokenPipeError:
                pass
            except Exception as e:  # internal error — never kill the thread
                try:
                    self._write_json(500, {"code": 500, "message": f"{type(e).__name__}: {e}"})
                except Exception:
                    pass

    def _serve_events(self, query) -> None:
        topics = []
        for t in query.get("topics", []):
            topics.extend(t.split(","))
        if not topics:
            self._write_json(400, {"code": 400, "message": "topics required"})
            return
        try:
            sub = self.api.chain.events.subscribe(topics)
        except ValueError as e:
            self._write_json(400, {"code": 400, "message": str(e)})
            return
        self.send_response(200)
        self.send_header("Content-Type", "text/event-stream")
        self.send_header("Cache-Control", "no-cache")
        self.send_header("Connection", "close")
        self.end_headers()
        try:
            while not self.api._shutdown.is_set():
                item = sub.poll(timeout=0.25)
                if item is None:
                    continue
                topic, data = item
                chunk = f"event: {topic}\ndata: {json.dumps(data)}\n\n".encode()
                self.wfile.write(chunk)
                self.wfile.flush()
                # Delivery accounting: the write succeeded (a broken pipe
                # raises before this line), so the event reached the client.
                sub.sent += 1
                metrics.SSE_EVENTS_SENT.inc(topic=topic)
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass
        finally:
            self.api.chain.events.unsubscribe(sub)

    def _serve_logs(self) -> None:
        """SSE tail of the structured log ring (the reference's
        ``lighthouse/logs`` Siren feed, common/logging SSE tap)."""
        from ..logs import RING

        self.send_response(200)
        self.send_header("Content-Type", "text/event-stream")
        self.send_header("Cache-Control", "no-cache")
        self.send_header("Connection", "close")
        self.end_headers()
        last_seq = 0
        try:
            # replay the recent tail first, then follow
            for entry in RING.tail(64):
                last_seq = entry["seq"]
                self.wfile.write(
                    f"event: logs\ndata: {json.dumps(entry)}\n\n".encode())
            self.wfile.flush()
            while not self.api._shutdown.is_set():
                fresh = RING.wait_for(last_seq, timeout=0.25)
                for entry in fresh:
                    last_seq = entry["seq"]
                    self.wfile.write(
                        f"event: logs\ndata: {json.dumps(entry)}\n\n".encode())
                if fresh:
                    self.wfile.flush()
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass

    def do_GET(self):
        self._handle("GET")

    def do_POST(self):
        self._handle("POST")

    def do_DELETE(self):
        self._handle("DELETE")


class _ApiHTTPServer(ThreadingHTTPServer):
    """Thread-per-connection server with a listen backlog sized for load
    bursts: the stdlib default of 5 refuses connections the moment a
    thousand clients arrive in one RTT, which turns a load spike into
    connect errors before admission control ever sees the requests."""

    request_queue_size = 1024
    daemon_threads = True


class HttpApiServer:
    """Serve the beacon API for a chain over TCP.

    ``processor`` routes handlers through the scheduler (P0/P1); ``None``
    runs them inline.  ``publish_block_fn``/``publish_attestation_fn`` are
    called after successful local import to gossip the object out (wired by
    ``LocalNode``)."""

    def __init__(
        self,
        chain,
        *,
        processor=None,
        host: str = "127.0.0.1",
        port: int = 0,
        peer_id: str = "",
        peer_manager=None,
        publish_block_fn=None,
        publish_attestation_fn=None,
        response_cache: bool = True,
        admission: Optional[AdmissionController] = None,
    ):
        self.chain = chain
        self.spawner = TaskSpawner(processor, admission=admission)
        self.peer_id = peer_id
        self.peer_manager = peer_manager
        self.publish_block_fn = publish_block_fn
        self.publish_attestation_fn = publish_attestation_fn
        # Checkpoint-keyed response cache, invalidated by the chain's own
        # head/finalization events.  ``response_cache=False`` (or the env
        # kill switch) serves every request uncached — the baseline the
        # load harness and the api_load scenario compare against.
        import os as _os

        enabled = (response_cache
                   and _os.environ.get("LIGHTHOUSE_TPU_API_CACHE", "1") != "0")
        self.response_cache: Optional[ResponseCache] = (
            ResponseCache(chain) if enabled else None
        )
        if self.response_cache is not None:
            self.response_cache.attach(chain.events)
        self._httpd = _ApiHTTPServer((host, port), _Handler)
        self._httpd.api_server = self  # type: ignore[attr-defined]
        self._thread: Optional[threading.Thread] = None
        self._shutdown = threading.Event()
        # Postmortem bundles get the serving admission state alongside the
        # built-in breaker/mesh/pipeline snapshots (last server wins when
        # tests run several; stop() withdraws ours).
        from .. import blackbox

        blackbox.register_snapshot("admission", self.spawner.admission.snapshot)

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    def start(self) -> "HttpApiServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="http-api", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._shutdown.set()
        from .. import blackbox

        blackbox.unregister_snapshot("admission")
        if self.response_cache is not None:
            self.response_cache.detach()
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
