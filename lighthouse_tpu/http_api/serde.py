"""Beacon-API JSON (de)serialization for SSZ containers.

The beacon API encodes uint64 as decimal strings, byte vectors as 0x-hex,
bitlists/bitvectors as 0x-hex SSZ bytes, and containers as objects — this
module derives all of that generically from the container's SSZ type
descriptors (reference: the serde derives across ``consensus/types``).

Encoding is type-driven: bit fields reuse the descriptor's own SSZ
``serialize``/``deserialize`` so Bitvector fields carry no bitlist delimiter
bit and an empty Bitlist round-trips as ``0x01``.
"""

from __future__ import annotations

from typing import Any, Optional

from ..types import ssz as ssz_mod

_HEX_TYPES = (ssz_mod.Bitlist, ssz_mod.Bitvector, ssz_mod.ByteVector, ssz_mod.ByteList)


def to_json(value: Any, ftype: Optional[ssz_mod.SszType] = None) -> Any:
    if isinstance(value, ssz_mod.Container):
        return {
            name: to_json(getattr(value, name), ft) for name, ft in value.fields.items()
        }
    if isinstance(ftype, _HEX_TYPES):
        return "0x" + ftype.serialize(value).hex()
    if isinstance(value, bool):
        return value
    if isinstance(value, int):
        return str(value)
    if isinstance(value, (bytes, bytearray)):
        return "0x" + bytes(value).hex()
    if isinstance(value, (list, tuple)):
        elem = getattr(ftype, "elem", None)
        if elem is None and value and all(isinstance(b, bool) for b in value):
            # Untyped bool list: assume bitlist (SSZ hex with delimiter).
            return "0x" + ssz_mod.Bitlist(len(value)).serialize(list(value)).hex()
        return [to_json(v, elem) for v in value]
    return value


def container_from_json(cls, obj: dict):
    """Inverse of ``to_json`` for containers (sufficient for the API
    surface's POST bodies; SSZ octet-stream is the preferred wire format)."""
    kwargs = {}
    for name, ftype in cls.fields.items():
        kwargs[name] = _field_from_json(ftype, obj[name])
    return cls(**kwargs)


def _field_from_json(ftype, v):
    if isinstance(ftype, ssz_mod.BooleanType):
        return v if isinstance(v, bool) else v in ("true", "1", 1)
    if isinstance(ftype, ssz_mod.UintType):
        return int(v)
    if isinstance(ftype, (ssz_mod.Bitlist, ssz_mod.Bitvector)):
        return ftype.deserialize(bytes.fromhex(v[2:]))
    if isinstance(v, str) and v.startswith("0x"):
        return bytes.fromhex(v[2:])
    if isinstance(ftype, ssz_mod._ContainerType):
        return container_from_json(ftype.cls, v)
    if isinstance(v, dict):
        # nested container via a wrapper type exposing the class
        cls = getattr(ftype, "container_class", None) or getattr(ftype, "cls", None)
        if cls is not None:
            return container_from_json(cls, v)
    if isinstance(v, list):
        return [_field_from_json(getattr(ftype, "elem", None), x) for x in v]
    return v
