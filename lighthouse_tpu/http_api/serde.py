"""Beacon-API JSON (de)serialization for SSZ containers.

The beacon API encodes uint64 as decimal strings, byte vectors as 0x-hex,
bitlists/bitvectors as 0x-hex SSZ bytes, and containers as objects — this
module derives all of that generically from the container's SSZ type
(reference: the serde derives across ``consensus/types``)."""

from __future__ import annotations

from typing import Any

from ..types import ssz as ssz_mod


def to_json(value: Any) -> Any:
    if isinstance(value, ssz_mod.Container):
        return {name: to_json(getattr(value, name)) for name in value.fields}
    if isinstance(value, bool):
        return value
    if isinstance(value, int):
        return str(value)
    if isinstance(value, (bytes, bytearray)):
        return "0x" + bytes(value).hex()
    if isinstance(value, (list, tuple)):
        if value and all(isinstance(b, bool) for b in value):
            # bitlist/bitvector → SSZ hex is the API convention; a plain bool
            # list is ambiguous here, so emit the list of bools' SSZ-ish hex
            return _bits_to_hex(list(value))
        return [to_json(v) for v in value]
    return value


def _bits_to_hex(bits) -> str:
    # bitlist encoding with delimiter bit (beacon API uses SSZ encoding)
    out = bytearray((len(bits) + 8) // 8)
    for i, b in enumerate(bits):
        if b:
            out[i // 8] |= 1 << (i % 8)
    out[len(bits) // 8] |= 1 << (len(bits) % 8)
    return "0x" + bytes(out).hex()


def container_from_json(cls, obj: dict):
    """Inverse of ``to_json`` for containers (sufficient for the API
    surface's POST bodies; SSZ octet-stream is the preferred wire format)."""
    kwargs = {}
    for name, ftype in cls.fields.items():
        kwargs[name] = _field_from_json(ftype, obj[name])
    return cls(**kwargs)


def _field_from_json(ftype, v):
    if isinstance(ftype, ssz_mod.UintType):
        return int(v)
    if isinstance(v, str) and v.startswith("0x"):
        raw = bytes.fromhex(v[2:])
        if isinstance(ftype, ssz_mod.Bitlist):
            return _hex_to_bits(raw)
        return raw
    if isinstance(v, dict):
        # nested container: the field type wraps the class
        cls = getattr(ftype, "container_class", None)
        if cls is not None:
            return container_from_json(cls, v)
    if isinstance(v, list):
        return [_field_from_json(getattr(ftype, "elem", None), x) for x in v]
    return v


def _hex_to_bits(raw: bytes):
    # strip the bitlist delimiter
    bits = []
    for i in range(len(raw) * 8):
        bits.append(bool(raw[i // 8] >> (i % 8) & 1))
    while bits and not bits[-1]:
        bits.pop()
    if bits:
        bits.pop()  # delimiter
    return bits
