"""Beacon-node HTTP API: server (reference ``beacon_node/http_api``), typed
client (``common/eth2``), and the beacon-API JSON serde layer."""

from .client import ApiClientError, BeaconNodeHttpClient
from .serde import container_from_json, to_json
from .server import ApiError, HttpApiServer

__all__ = [
    "ApiClientError",
    "ApiError",
    "BeaconNodeHttpClient",
    "HttpApiServer",
    "container_from_json",
    "to_json",
]
