"""watch: off-node chain analytics.

Equivalent of the reference's ``watch/`` crate (6.5k LoC — a PostgreSQL
updater + HTTP server tracking block packing, proposer activity, and
suboptimal attestations).  The host database here is stdlib sqlite3 (the
embedded analog of the reference's diesel/Postgres layer); the shape is the
same: an updater polls a beacon node over the standard HTTP API, a read-only
HTTP server exposes the aggregates.
"""

from __future__ import annotations

import json
import sqlite3
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional

_SCHEMA = """
CREATE TABLE IF NOT EXISTS blocks (
    slot INTEGER PRIMARY KEY,
    root TEXT NOT NULL,
    proposer INTEGER NOT NULL,
    attestation_count INTEGER NOT NULL,
    sync_participation REAL,
    graffiti TEXT
);
CREATE TABLE IF NOT EXISTS skipped_slots (
    slot INTEGER PRIMARY KEY
);
CREATE TABLE IF NOT EXISTS attestation_performance (
    epoch INTEGER NOT NULL,
    validator INTEGER NOT NULL,
    source INTEGER NOT NULL,
    target INTEGER NOT NULL,
    head INTEGER NOT NULL,
    PRIMARY KEY (epoch, validator)
);
CREATE TABLE IF NOT EXISTS block_packing (
    slot INTEGER PRIMARY KEY,
    available INTEGER NOT NULL,
    included INTEGER NOT NULL,
    prior_skip_slots INTEGER NOT NULL
);
CREATE TABLE IF NOT EXISTS block_rewards (
    slot INTEGER PRIMARY KEY,
    total INTEGER NOT NULL,
    attestation_reward INTEGER NOT NULL,
    sync_committee_reward INTEGER NOT NULL
);
CREATE TABLE IF NOT EXISTS blockprint (
    slot INTEGER PRIMARY KEY,
    best_guess TEXT NOT NULL
);
"""


class WatchDB:
    def __init__(self, path: str = ":memory:"):
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._lock = threading.Lock()
        with self._lock:
            self._conn.executescript(_SCHEMA)
            self._conn.commit()

    def record_block(self, *, slot: int, root: bytes, proposer: int,
                     attestation_count: int, sync_participation: Optional[float],
                     graffiti: str = "") -> None:
        with self._lock:
            self._conn.execute(
                "INSERT OR REPLACE INTO blocks VALUES (?,?,?,?,?,?)",
                (slot, "0x" + bytes(root).hex(), proposer, attestation_count,
                 sync_participation, graffiti),
            )
            self._conn.commit()

    def record_skipped(self, slot: int) -> None:
        with self._lock:
            self._conn.execute(
                "INSERT OR IGNORE INTO skipped_slots VALUES (?)", (slot,)
            )
            self._conn.commit()

    def record_block_packing(self, slot: int, available: int, included: int,
                             prior_skip_slots: int) -> None:
        with self._lock:
            self._conn.execute(
                "INSERT OR REPLACE INTO block_packing VALUES (?,?,?,?)",
                (slot, available, included, prior_skip_slots),
            )
            self._conn.commit()

    def record_block_rewards(self, slot: int, total: int,
                             attestation_reward: int,
                             sync_committee_reward: int) -> None:
        with self._lock:
            self._conn.execute(
                "INSERT OR REPLACE INTO block_rewards VALUES (?,?,?,?)",
                (slot, total, attestation_reward, sync_committee_reward),
            )
            self._conn.commit()

    def record_blockprint(self, slot: int, best_guess: str) -> None:
        with self._lock:
            self._conn.execute(
                "INSERT OR REPLACE INTO blockprint VALUES (?,?)",
                (slot, best_guess),
            )
            self._conn.commit()

    def record_attestation_performance(self, epoch: int, rows: List[dict]) -> None:
        with self._lock:
            for r in rows:
                self._conn.execute(
                    "INSERT OR REPLACE INTO attestation_performance VALUES (?,?,?,?,?)",
                    (epoch, int(r["validator_index"]),
                     1 if int(r["source"]) > 0 else 0,
                     1 if int(r["target"]) > 0 else 0,
                     1 if int(r["head"]) > 0 else 0),
                )
            self._conn.commit()

    # ------------------------------------------------------------- queries

    def highest_slot(self) -> Optional[int]:
        with self._lock:
            row = self._conn.execute(
                "SELECT MAX(s) FROM (SELECT MAX(slot) AS s FROM blocks "
                "UNION SELECT MAX(slot) FROM skipped_slots)"
            ).fetchone()
        return row[0]

    def block_at(self, slot: int) -> Optional[dict]:
        with self._lock:
            row = self._conn.execute(
                "SELECT slot, root, proposer, attestation_count, "
                "sync_participation, graffiti FROM blocks WHERE slot=?", (slot,)
            ).fetchone()
        if row is None:
            return None
        return {"slot": row[0], "root": row[1], "proposer": row[2],
                "attestation_count": row[3], "sync_participation": row[4],
                "graffiti": row[5]}

    def proposer_blocks(self, proposer: int) -> List[int]:
        with self._lock:
            return [r[0] for r in self._conn.execute(
                "SELECT slot FROM blocks WHERE proposer=? ORDER BY slot",
                (proposer,),
            )]

    def suboptimal_attestations(self, epoch: int) -> List[dict]:
        """Validators that missed any flag in ``epoch`` (the reference's
        suboptimal-attestation tracking)."""
        with self._lock:
            rows = self._conn.execute(
                "SELECT validator, source, target, head FROM "
                "attestation_performance WHERE epoch=? AND "
                "(source=0 OR target=0 OR head=0) ORDER BY validator",
                (epoch,),
            ).fetchall()
        return [{"validator": v, "source": bool(s), "target": bool(t),
                 "head": bool(h)} for v, s, t, h in rows]

    def block_packing(self, slot: int) -> Optional[dict]:
        with self._lock:
            row = self._conn.execute(
                "SELECT slot, available, included, prior_skip_slots FROM "
                "block_packing WHERE slot=?", (slot,),
            ).fetchone()
        if row is None:
            return None
        avail = row[1]
        return {"slot": row[0], "available": avail, "included": row[2],
                "prior_skip_slots": row[3],
                "efficiency": (row[2] / avail) if avail else 0.0}

    def block_rewards(self, slot: int) -> Optional[dict]:
        with self._lock:
            row = self._conn.execute(
                "SELECT slot, total, attestation_reward, "
                "sync_committee_reward FROM block_rewards WHERE slot=?",
                (slot,),
            ).fetchone()
        if row is None:
            return None
        return {"slot": row[0], "total": row[1],
                "attestation_reward": row[2],
                "sync_committee_reward": row[3]}

    def blockprint_at(self, slot: int) -> Optional[str]:
        with self._lock:
            row = self._conn.execute(
                "SELECT best_guess FROM blockprint WHERE slot=?", (slot,),
            ).fetchone()
        return row[0] if row else None

    def blockprint_summary(self) -> Dict[str, int]:
        """Client-diversity counts over all fingerprinted blocks
        (reference blockprint's aggregate view)."""
        with self._lock:
            rows = self._conn.execute(
                "SELECT best_guess, COUNT(*) FROM blockprint GROUP BY "
                "best_guess ORDER BY COUNT(*) DESC",
            ).fetchall()
        return {guess: n for guess, n in rows}

    def participation_rate(self, epoch: int) -> Optional[dict]:
        with self._lock:
            row = self._conn.execute(
                "SELECT COUNT(*), SUM(source), SUM(target), SUM(head) FROM "
                "attestation_performance WHERE epoch=?", (epoch,),
            ).fetchone()
        if not row or not row[0]:
            return None
        n = row[0]
        return {"epoch": epoch, "validators": n,
                "source_rate": row[1] / n, "target_rate": row[2] / n,
                "head_rate": row[3] / n}

    def close(self) -> None:
        with self._lock:
            self._conn.close()


def blockprint_guess(graffiti: str) -> str:
    """Heuristic client fingerprint from the block's visible shape.

    The reference's blockprint subsystem defers to an external ML service;
    offline, the strongest public signal is the graffiti convention each
    client ships by default."""
    g = graffiti.lower()
    for needle, name in (("lighthouse", "Lighthouse"), ("teku", "Teku"),
                         ("nimbus", "Nimbus"), ("prysm", "Prysm"),
                         ("lodestar", "Lodestar"), ("grandine", "Grandine")):
        if needle in g:
            return name
    return "Uncertain"


class WatchUpdater:
    """Poll a beacon node into the DB (reference watch's updater loop)."""

    def __init__(self, *, client, db: WatchDB, spec):
        self.client = client
        self.db = db
        self.spec = spec
        self._last_rewards_epoch: Optional[int] = None
        self._packing_frontier_epoch: int = 0

    def update(self) -> int:
        """One round: ingest new slots up to the node's head; pull
        attestation performance for newly completed epochs.  Returns the
        number of slots ingested."""
        head = self.client.block_header("head")
        head_slot = int(head["header"]["message"]["slot"])
        start = (self.db.highest_slot() or 0) + 1
        try:
            ingested, last_done = self._ingest_blocks(start, head_slot)
        finally:
            # Analytics must cover every slot that actually landed, even
            # when the block loop aborted mid-round (a transient error must
            # not leave a permanent packing/rewards gap).
            if last_done >= start:
                self._ingest_packing_and_rewards(start, last_done)
        self._maybe_pull_rewards_performance(head_slot)
        return ingested

    def _ingest_blocks(self, start: int, head_slot: int):
        from ..http_api.client import ApiClientError

        head = self.client.block_header("head")
        ingested = 0
        last_done = start - 1
        for slot in range(start, head_slot + 1):
            try:
                resp = self.client.block(str(slot))
            except ApiClientError as e:
                if e.code == 404:
                    self.db.record_skipped(slot)  # genuinely empty slot
                    last_done = slot
                    continue
                return ingested, last_done  # node-side error: retry next round
            except OSError:
                return ingested, last_done  # transient transport failure:
                                            # never record a live slot skipped
            msg = resp["data"]["message"]
            if int(msg["slot"]) != slot:
                self.db.record_skipped(slot)
                last_done = slot
                continue
            body = msg["body"]
            sync_part = None
            if "sync_aggregate" in body:
                bits = body["sync_aggregate"]["sync_committee_bits"]
                raw = bytes.fromhex(bits[2:])
                total = self.spec.preset.sync_committee_size
                ones = sum(bin(b).count("1") for b in raw)
                sync_part = min(1.0, ones / total)
            att_count = len(body.get("attestations", []))
            graffiti = body.get("graffiti", "")
            self.db.record_block(
                slot=slot,
                root=bytes.fromhex(head["root"][2:]) if slot == head_slot
                else self._root_for(slot),
                proposer=int(msg["proposer_index"]),
                attestation_count=att_count,
                sync_participation=sync_part,
                graffiti=graffiti,
            )
            self.db.record_blockprint(slot, blockprint_guess(graffiti))
            ingested += 1
            last_done = slot
        return ingested, last_done

    def _maybe_pull_rewards_performance(self, head_slot: int) -> None:
        spe = self.spec.slots_per_epoch
        completed_epoch = head_slot // spe - 2
        if completed_epoch >= 0 and completed_epoch != self._last_rewards_epoch:
            try:
                resp = self.client.post(
                    f"/eth/v1/beacon/rewards/attestations/{completed_epoch}", None
                )
                self.db.record_attestation_performance(
                    completed_epoch, resp["data"]["total_rewards"]
                )
                self._last_rewards_epoch = completed_epoch
            except Exception:
                pass  # rewards unavailable (pruned state): analytics are best-effort

    def _ingest_packing_and_rewards(self, start: int, end: int) -> None:
        """Best-effort packing + rewards pulls for the newly ingested span
        (reference watch's block_packing and block_rewards updaters)."""
        spe = self.spec.slots_per_epoch
        try:
            # Epoch-granular endpoint: only re-fetch from the frontier (the
            # last epoch may have been partial when previously pulled).
            start_epoch = min(start // spe, self._packing_frontier_epoch)
            resp = self.client.get(
                "/lighthouse/analysis/block_packing_efficiency"
                f"?start_epoch={start_epoch}&end_epoch={end // spe}"
            )
            for row in resp["data"]:
                self.db.record_block_packing(
                    int(row["slot"]), int(row["available_attestations"]),
                    int(row["included_attestations"]),
                    int(row["prior_skip_slots"]),
                )
            self._packing_frontier_epoch = end // spe
        except Exception:
            pass
        try:
            resp = self.client.get(
                f"/lighthouse/analysis/block_rewards?start_slot={max(1, start)}"
                f"&end_slot={end}"
            )
            for row in resp["data"]:
                self.db.record_block_rewards(
                    int(row["slot"]), int(row["total"]),
                    int(row["attestations"]), int(row["sync_aggregate"]),
                )
        except Exception:
            pass

    def _root_for(self, slot: int) -> bytes:
        return self.client.block_root(str(slot))


class WatchServer:
    """Read-only analytics API over the DB (reference watch's HTTP server)."""

    def __init__(self, db: WatchDB, port: int = 0):
        self.db = db
        self._port = port
        self._server: Optional[ThreadingHTTPServer] = None

    def start(self) -> "WatchServer":
        db = self.db

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _reply(self, code: int, obj):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                parts = self.path.strip("/").split("/")
                try:
                    if parts[:2] == ["v1", "slots"] and len(parts) == 3:
                        row = db.block_at(int(parts[2]))
                        if row is None:
                            self._reply(404, {"message": "no block at that slot"})
                        else:
                            self._reply(200, {"data": row})
                        return
                    if parts[:2] == ["v1", "proposers"] and len(parts) == 3:
                        self._reply(200, {"data": db.proposer_blocks(int(parts[2]))})
                        return
                    if parts[:2] == ["v1", "participation"] and len(parts) == 3:
                        row = db.participation_rate(int(parts[2]))
                        if row is None:
                            self._reply(404, {"message": "epoch not ingested"})
                        else:
                            self._reply(200, {"data": row})
                        return
                    if parts[:2] == ["v1", "packing"] and len(parts) == 3:
                        row = db.block_packing(int(parts[2]))
                        if row is None:
                            self._reply(404, {"message": "no packing data"})
                        else:
                            self._reply(200, {"data": row})
                        return
                    if parts[:2] == ["v1", "rewards"] and len(parts) == 3:
                        row = db.block_rewards(int(parts[2]))
                        if row is None:
                            self._reply(404, {"message": "no rewards data"})
                        else:
                            self._reply(200, {"data": row})
                        return
                    if parts[:2] == ["v1", "blockprint"] and len(parts) == 3:
                        if parts[2] == "summary":
                            self._reply(200, {"data": db.blockprint_summary()})
                            return
                        guess = db.blockprint_at(int(parts[2]))
                        if guess is None:
                            self._reply(404, {"message": "no blockprint"})
                        else:
                            self._reply(200, {"data": {"best_guess": guess}})
                        return
                    if (parts[:2] == ["v1", "suboptimal_attestations"]
                            and len(parts) == 3):
                        self._reply(
                            200, {"data": db.suboptimal_attestations(int(parts[2]))}
                        )
                        return
                except ValueError:
                    self._reply(400, {"message": "bad parameter"})
                    return
                self._reply(404, {"message": "unknown route"})

        self._server = ThreadingHTTPServer(("127.0.0.1", self._port), Handler)
        threading.Thread(target=self._server.serve_forever, daemon=True).start()
        return self

    @property
    def url(self) -> str:
        host, port = self._server.server_address[:2]
        return f"http://{host}:{port}"

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
