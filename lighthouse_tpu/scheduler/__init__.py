"""Priority work scheduler (reference: ``beacon_node/beacon_processor``)."""

from .admission import (
    CLASS_BULK,
    CLASS_CRITICAL,
    CLASS_DUTIES,
    AdmissionController,
    ClassPolicy,
    DropPolicy,
    ShedError,
    SyncDropPolicy,
)
from .processor import BeaconProcessor, ProcessorMetrics, ReprocessQueue
from .work import BATCH_RULES, DRAIN_ORDER, W, WorkEvent

__all__ = [
    "AdmissionController",
    "BATCH_RULES",
    "BeaconProcessor",
    "CLASS_BULK",
    "CLASS_CRITICAL",
    "CLASS_DUTIES",
    "ClassPolicy",
    "DRAIN_ORDER",
    "DropPolicy",
    "ProcessorMetrics",
    "ReprocessQueue",
    "ShedError",
    "SyncDropPolicy",
    "W",
    "WorkEvent",
]
