"""Priority work scheduler (reference: ``beacon_node/beacon_processor``)."""

from .processor import BeaconProcessor, ProcessorMetrics, ReprocessQueue
from .work import BATCH_RULES, DRAIN_ORDER, W, WorkEvent

__all__ = [
    "BATCH_RULES",
    "BeaconProcessor",
    "DRAIN_ORDER",
    "ProcessorMetrics",
    "ReprocessQueue",
    "W",
    "WorkEvent",
]
