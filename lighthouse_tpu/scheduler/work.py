"""Work taxonomy for the priority scheduler.

Mirrors the reference's ``Work`` enum — 36 priority classes drained in a
hard-coded order (``beacon_node/beacon_processor/src/lib.rs:549-615`` and the
drain order at ``:932-1110``).  The order encodes consensus-criticality:
chain-extending data (blocks, blobs) first, then priority-0 API requests,
aggregates, unaggregated attestations, sync work, and finally backfill and
low-priority API traffic.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Callable, Optional


class W:
    """Work type ids (reference ``WorkType``)."""

    # chain extension (highest priority)
    GOSSIP_BLOCK = "gossip_block"
    GOSSIP_BLOB_SIDECAR = "gossip_blob_sidecar"
    DELAYED_IMPORT_BLOCK = "delayed_import_block"
    RPC_BLOCK = "rpc_block"
    RPC_BLOBS = "rpc_blobs"
    CHAIN_SEGMENT = "chain_segment"
    # priority API
    API_REQUEST_P0 = "api_request_p0"
    # duties API: proposer/attester/sync duty queries — below
    # consensus-critical submissions, above bulk reads (the serving
    # admission layer's middle class; see scheduler/admission.py)
    API_REQUEST_DUTIES = "api_request_duties"
    # aggregates & proofs
    GOSSIP_AGGREGATE = "gossip_aggregate"
    GOSSIP_AGGREGATE_BATCH = "gossip_aggregate_batch"
    # unaggregated attestations
    GOSSIP_ATTESTATION = "gossip_attestation"
    GOSSIP_ATTESTATION_BATCH = "gossip_attestation_batch"
    UNKNOWN_BLOCK_ATTESTATION = "unknown_block_attestation"
    UNKNOWN_BLOCK_AGGREGATE = "unknown_block_aggregate"
    # sync committee
    GOSSIP_SYNC_SIGNATURE = "gossip_sync_signature"
    GOSSIP_SYNC_CONTRIBUTION = "gossip_sync_contribution"
    # other gossip ops
    GOSSIP_VOLUNTARY_EXIT = "gossip_voluntary_exit"
    GOSSIP_PROPOSER_SLASHING = "gossip_proposer_slashing"
    GOSSIP_ATTESTER_SLASHING = "gossip_attester_slashing"
    GOSSIP_BLS_TO_EXECUTION_CHANGE = "gossip_bls_to_execution_change"
    GOSSIP_LIGHT_CLIENT_FINALITY_UPDATE = "gossip_lc_finality"
    GOSSIP_LIGHT_CLIENT_OPTIMISTIC_UPDATE = "gossip_lc_optimistic"
    # RPC serving
    STATUS = "status"
    BLOCKS_BY_RANGE_REQUEST = "blocks_by_range"
    BLOCKS_BY_ROOTS_REQUEST = "blocks_by_roots"
    BLOBS_BY_RANGE_REQUEST = "blobs_by_range"
    BLOBS_BY_ROOTS_REQUEST = "blobs_by_roots"
    LIGHT_CLIENT_BOOTSTRAP_REQUEST = "lc_bootstrap"
    # low priority
    BACKFILL_SYNC = "backfill_sync"
    API_REQUEST_P1 = "api_request_p1"


# Drain order (reference ``beacon_processor/src/lib.rs:932-1110``): the
# manager always serves the first non-empty queue in this list.
DRAIN_ORDER = (
    W.GOSSIP_BLOCK,
    W.GOSSIP_BLOB_SIDECAR,
    W.DELAYED_IMPORT_BLOCK,
    W.RPC_BLOCK,
    W.RPC_BLOBS,
    W.CHAIN_SEGMENT,
    W.API_REQUEST_P0,
    W.GOSSIP_AGGREGATE,
    W.GOSSIP_ATTESTATION,
    W.UNKNOWN_BLOCK_AGGREGATE,
    W.UNKNOWN_BLOCK_ATTESTATION,
    W.API_REQUEST_DUTIES,
    W.GOSSIP_SYNC_CONTRIBUTION,
    W.GOSSIP_SYNC_SIGNATURE,
    W.GOSSIP_ATTESTER_SLASHING,
    W.GOSSIP_PROPOSER_SLASHING,
    W.GOSSIP_VOLUNTARY_EXIT,
    W.GOSSIP_BLS_TO_EXECUTION_CHANGE,
    W.STATUS,
    W.BLOCKS_BY_RANGE_REQUEST,
    W.BLOCKS_BY_ROOTS_REQUEST,
    W.BLOBS_BY_RANGE_REQUEST,
    W.BLOBS_BY_ROOTS_REQUEST,
    W.LIGHT_CLIENT_BOOTSTRAP_REQUEST,
    W.GOSSIP_LIGHT_CLIENT_FINALITY_UPDATE,
    W.GOSSIP_LIGHT_CLIENT_OPTIMISTIC_UPDATE,
    W.BACKFILL_SYNC,
    W.API_REQUEST_P1,
)

# Default per-queue bounds (reference scales these to the validator count,
# ``lib.rs:96``; these are the minimal-preset-scale defaults).
DEFAULT_QUEUE_LENGTHS = {
    W.GOSSIP_BLOCK: 1024,
    W.GOSSIP_BLOB_SIDECAR: 1024,
    W.GOSSIP_AGGREGATE: 4096,
    W.GOSSIP_ATTESTATION: 16384,
    W.UNKNOWN_BLOCK_ATTESTATION: 8192,
    W.UNKNOWN_BLOCK_AGGREGATE: 4096,
    W.BACKFILL_SYNC: 1024,
    W.API_REQUEST_P0: 1024,
    W.API_REQUEST_DUTIES: 1024,
    W.API_REQUEST_P1: 1024,
}
DEFAULT_QUEUE_LENGTH = 4096

#: How many times a worker-raised ``RequeueWork`` re-enqueues an event
#: before it is dropped for good.
MAX_WORK_RETRIES = 1


class RequeueWork(RuntimeError):
    """Raised by a work handler to ask the processor to re-enqueue the
    event(s) instead of counting them dropped.

    The canonical raiser is the device supervisor's ``DispatchTimeout``
    (``device_supervisor.py``): a dispatch that exceeded its watchdog
    deadline with no host fallback available is worth exactly one retry —
    by then the device has recovered, or the circuit breaker has opened and
    the retry routes to the host backend.  Each event retries at most
    :data:`MAX_WORK_RETRIES` times (``WorkEvent.retries``).
    """

# Batchable work: (batch_work_type, max batch size).  The reference caps
# coalescing at 64 attestations (``lib.rs:200-201``) because blst verifies
# on CPU threads; here the cap is the production standard device bucket
# (ops/verify.py ``N_BUCKETS[-1]``; kept as a literal so importing the work
# taxonomy never pulls jax).  Overridable for hosts where giant buckets are
# wrong (e.g. CPU-only deployments).
#
# Since the async device pipeline (device_pipeline.py), these caps are
# throughput HINTS — how much one worker drains per wakeup — not the batch
# formation mechanism: the pipeline coalesces what every worker submits
# ACROSS work types into the actual device batch, so a one-event drain
# still ends up in a maximal bucket.
def _standard_batch_from_env() -> int:
    raw = os.environ.get("LIGHTHOUSE_TPU_STANDARD_BATCH", "4096")
    try:
        n = int(raw)
    except ValueError:
        raise ValueError(
            f"LIGHTHOUSE_TPU_STANDARD_BATCH={raw!r}: expected a positive integer"
        ) from None
    if n < 1:
        raise ValueError(
            f"LIGHTHOUSE_TPU_STANDARD_BATCH={n}: must be >= 1"
        )
    return n


STANDARD_DEVICE_BATCH = _standard_batch_from_env()
BATCH_RULES = {
    W.GOSSIP_ATTESTATION: (W.GOSSIP_ATTESTATION_BATCH, STANDARD_DEVICE_BATCH),
    W.GOSSIP_AGGREGATE: (W.GOSSIP_AGGREGATE_BATCH, STANDARD_DEVICE_BATCH),
}


@dataclass
class WorkEvent:
    """One unit of work: ``process(*items)`` runs on a worker thread.

    ``drop_during_sync`` mirrors the reference's flag of the same name —
    gossip work that is stale while syncing can be discarded."""

    work_type: str
    process: Callable[..., Any]
    item: Any = None
    drop_during_sync: bool = False
    # Batch handler: called with a list of items when coalesced.
    process_batch: Optional[Callable[..., Any]] = None
    # Trace carriage across the enqueue→worker thread hop: the sender's
    # active span (stamped by BeaconProcessor.send unless pre-set) and the
    # enqueue instant, from which the worker records the queue-wait span.
    trace_parent: Any = None
    enqueued_at: float = 0.0
    # Times this event has been re-enqueued after a RequeueWork (bounded by
    # MAX_WORK_RETRIES).
    retries: int = 0
