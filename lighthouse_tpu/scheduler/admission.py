"""Prioritized admission control and load shedding for API work.

The serving half of the reference's ``beacon_processor`` drop/requeue
semantics: the processor's bounded per-class queues protect the node from
*gossip* floods, but nothing protected it from *HTTP read* floods — every
duty/state/rewards query used to queue unconditionally and time out 30 s
later, long after the client gave up.  This module puts a policy object in
front of :class:`~lighthouse_tpu.scheduler.processor.BeaconProcessor`:

- inbound HTTP work is classified (``critical`` > ``duties`` > ``bulk``),
- each class holds a bounded number of admitted-but-unfinished requests —
  past the bound the request is shed *immediately* (503 + Retry-After),
  which costs microseconds instead of a queue slot,
- admitted work that waited past its class deadline before a worker picked
  it up is shed at dequeue (the reference's stale-work drop: a duties
  answer delivered after the client's own timeout is pure waste),
- every decision is visible: ``http_requests_shed_total{class,reason}``
  and the ``http_admission_wait_seconds{class}`` queue-wait histogram.

It also generalizes the processor's ad-hoc ``is_syncing`` callable into
:class:`DropPolicy` — the one object that decides which enqueued work is
discarded instead of queued (``drop_during_sync`` was the first policy;
admission deadlines are the second).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, Optional

from .. import metrics

# ------------------------------------------------------------ HTTP classes

#: Consensus-critical API work: block/attestation/aggregate submission and
#: production — shedding these risks missed duties network-wide, so their
#: bound is the loosest and their deadline the longest.
CLASS_CRITICAL = "critical"
#: Validator duties queries (proposer/attester/sync) — latency-sensitive
#: but recomputable; a VC retries on its own schedule.
CLASS_DUTIES = "duties"
#: Bulk read traffic: state dumps, rewards, analysis — the first thing to
#: shed under overload.
CLASS_BULK = "bulk"

HTTP_REQUESTS_SHED = metrics.counter(
    "http_requests_shed_total",
    "Beacon API requests shed by admission control, by class and reason "
    "(admission_full|deadline)",
)
HTTP_ADMISSION_WAIT_SECONDS = metrics.histogram(
    "http_admission_wait_seconds",
    "admission-to-execution wait for admitted API work, by class",
)
HTTP_ADMISSION_INFLIGHT = metrics.gauge(
    "http_admission_inflight",
    "admitted-but-unfinished API requests, by class",
)


class ShedError(Exception):
    """The request was shed; the server answers 503 with Retry-After."""

    def __init__(self, klass: str, reason: str, retry_after_s: int):
        super().__init__(
            f"overloaded: {klass} request shed ({reason}); "
            f"retry after {retry_after_s}s"
        )
        self.klass = klass
        self.reason = reason
        self.retry_after_s = retry_after_s


@dataclass(frozen=True)
class ClassPolicy:
    """Admission bounds for one work class.

    ``max_inflight`` caps admitted-but-unfinished requests (the cheap
    early shed); ``deadline_s`` bounds how stale an admitted request may
    be when a worker finally picks it up (the dequeue shed);
    ``retry_after_s`` is what a shed response tells the client."""

    name: str
    max_inflight: int
    deadline_s: float
    retry_after_s: int


#: Defaults sized for the minimal-preset CI host; production deployments
#: scale ``max_inflight`` with worker count the way the reference scales
#: its queue lengths with the validator count.
DEFAULT_POLICIES = (
    ClassPolicy(CLASS_CRITICAL, max_inflight=512, deadline_s=8.0, retry_after_s=1),
    ClassPolicy(CLASS_DUTIES, max_inflight=256, deadline_s=4.0, retry_after_s=2),
    ClassPolicy(CLASS_BULK, max_inflight=128, deadline_s=2.0, retry_after_s=5),
)


class Ticket:
    """One admitted request: stamped at admission, released when finished
    (shed or served).  ``check_deadline`` is called by the worker just
    before running the handler — the dequeue-side shed."""

    __slots__ = ("controller", "policy", "admitted_pc")

    def __init__(self, controller: "AdmissionController", policy: ClassPolicy):
        self.controller = controller
        self.policy = policy
        self.admitted_pc = time.perf_counter()

    def check_deadline(self) -> float:
        """Record the queue wait; raise :class:`ShedError` when this request
        waited past its class deadline.  Returns the wait in seconds."""
        wait = time.perf_counter() - self.admitted_pc
        HTTP_ADMISSION_WAIT_SECONDS.observe(wait, **{"class": self.policy.name})
        if wait > self.policy.deadline_s:
            HTTP_REQUESTS_SHED.inc(**{"class": self.policy.name,
                                      "reason": "deadline"})
            self.controller._count_shed()
            raise ShedError(self.policy.name, "deadline",
                            self.policy.retry_after_s)
        return wait

    def release(self) -> None:
        self.controller._release(self.policy.name)


class AdmissionController:
    """Bounded per-class admission in front of the processor."""

    def __init__(self, policies=DEFAULT_POLICIES):
        self._policies: Dict[str, ClassPolicy] = {p.name: p for p in policies}
        self._inflight: Dict[str, int] = {p.name: 0 for p in policies}
        self._lock = threading.Lock()
        self.shed = 0  # process-lifetime total, for snapshots/tests

    def policy(self, klass: str) -> ClassPolicy:
        return self._policies[klass]

    def try_admit(self, klass: str) -> Ticket:
        """Admit or shed.  Unknown classes are admitted unbounded (a route
        added without a policy must not 503 by accident — it just isn't
        protected yet)."""
        policy = self._policies.get(klass)
        if policy is None:
            policy = ClassPolicy(klass, max_inflight=1 << 30,
                                 deadline_s=60.0, retry_after_s=1)
            with self._lock:
                self._policies.setdefault(klass, policy)
                self._inflight.setdefault(klass, 0)
        with self._lock:
            if self._inflight[policy.name] >= policy.max_inflight:
                self.shed += 1
                HTTP_REQUESTS_SHED.inc(**{"class": policy.name,
                                          "reason": "admission_full"})
                raise ShedError(policy.name, "admission_full",
                                policy.retry_after_s)
            self._inflight[policy.name] += 1
            HTTP_ADMISSION_INFLIGHT.set(self._inflight[policy.name],
                                        **{"class": policy.name})
        return Ticket(self, policy)

    def _count_shed(self) -> None:
        with self._lock:
            self.shed += 1

    def _release(self, klass: str) -> None:
        with self._lock:
            self._inflight[klass] = max(0, self._inflight[klass] - 1)
            HTTP_ADMISSION_INFLIGHT.set(self._inflight[klass],
                                        **{"class": klass})

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "inflight": dict(self._inflight),
                "bounds": {k: p.max_inflight for k, p in self._policies.items()},
                "deadlines_s": {k: p.deadline_s for k, p in self._policies.items()},
                "shed_total": self.shed,
            }


# ------------------------------------------------------------ drop policy


class DropPolicy:
    """Decides whether an enqueued :class:`WorkEvent` should be discarded
    instead of queued.  Returns a drop *reason* (metric label) or ``None``
    to admit — the generalization of the processor's original hard-coded
    ``drop_during_sync and is_syncing()`` test."""

    def should_drop(self, event) -> Optional[str]:  # pragma: no cover
        return None


class SyncDropPolicy(DropPolicy):
    """The original policy: while ``is_syncing()`` holds, events flagged
    ``drop_during_sync`` are discarded (stale gossip is useless to a
    syncing chain and crowds out the sync work itself)."""

    def __init__(self, is_syncing: Optional[Callable[[], bool]]):
        self.is_syncing = is_syncing

    def should_drop(self, event) -> Optional[str]:
        if (
            event.drop_during_sync
            and self.is_syncing is not None
            and self.is_syncing()
        ):
            return "syncing"
        return None
