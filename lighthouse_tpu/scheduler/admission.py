"""Prioritized admission control and load shedding for API work.

The serving half of the reference's ``beacon_processor`` drop/requeue
semantics: the processor's bounded per-class queues protect the node from
*gossip* floods, but nothing protected it from *HTTP read* floods — every
duty/state/rewards query used to queue unconditionally and time out 30 s
later, long after the client gave up.  This module puts a policy object in
front of :class:`~lighthouse_tpu.scheduler.processor.BeaconProcessor`:

- inbound HTTP work is classified (``critical`` > ``duties`` > ``bulk``),
- each class holds a bounded number of admitted-but-unfinished requests —
  past the bound the request is shed *immediately* (503 + Retry-After),
  which costs microseconds instead of a queue slot,
- admitted work that waited past its class deadline before a worker picked
  it up is shed at dequeue (the reference's stale-work drop: a duties
  answer delivered after the client's own timeout is pure waste),
- every decision is visible: ``http_requests_shed_total{class,reason}``
  and the ``http_admission_wait_seconds{class}`` queue-wait histogram.

It also generalizes the processor's ad-hoc ``is_syncing`` callable into
:class:`DropPolicy` — the one object that decides which enqueued work is
discarded instead of queued (``drop_during_sync`` was the first policy;
admission deadlines are the second).

**Latency-driven bounds (ISSUE 15).**  The configured
:class:`ClassPolicy` values are *static guesses*; the observed handler
latency is a *measurement* — every :class:`Ticket` release feeds a
per-class service-time EWMA.  When the autotune layer runs live
(``LIGHTHOUSE_TPU_AUTOTUNE=live``, or ``adaptive=True`` on the
controller), the effective dequeue deadline tracks
``DEADLINE_LATENCY_FACTOR`` × EWMA and the effective inflight bound tracks
how many requests one worker can clear inside that deadline — both clamped
to a band whose ceiling IS the configured static value (the statics remain
the contract; the controller only tightens inside it).  Fast handlers →
static bounds shed late and waste queue slots on stale answers;
slow handlers → static bounds admit work that cannot possibly be served in
time.  Both are visible on the ``http_admission_effective_*`` gauges.

**Measured Retry-After.**  A shed response's Retry-After used to be a
per-class constant.  It now reflects the class's *observed drain rate*
(completions over a sliding window): the hint is the time for roughly half
the currently-inflight requests to drain, clamped to
[1, :data:`RETRY_AFTER_MAX_S`] — falling back to the configured constant
below :data:`DRAIN_MIN_SAMPLES` completions.  This path is always on
(it shapes a response hint, not an admission decision).
"""

from __future__ import annotations

import math
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Callable, Deque, Dict, Optional, Tuple

from .. import blackbox, locksmith, metrics

# ------------------------------------------------------------ HTTP classes

#: Consensus-critical API work: block/attestation/aggregate submission and
#: production — shedding these risks missed duties network-wide, so their
#: bound is the loosest and their deadline the longest.
CLASS_CRITICAL = "critical"
#: Validator duties queries (proposer/attester/sync) — latency-sensitive
#: but recomputable; a VC retries on its own schedule.
CLASS_DUTIES = "duties"
#: Bulk read traffic: state dumps, rewards, analysis — the first thing to
#: shed under overload.
CLASS_BULK = "bulk"

HTTP_REQUESTS_SHED = metrics.counter(
    "http_requests_shed_total",
    "Beacon API requests shed by admission control, by class and reason "
    "(admission_full|deadline)",
)
HTTP_ADMISSION_WAIT_SECONDS = metrics.histogram(
    "http_admission_wait_seconds",
    "admission-to-execution wait for admitted API work, by class",
)
HTTP_ADMISSION_INFLIGHT = metrics.gauge(
    "http_admission_inflight",
    "admitted-but-unfinished API requests, by class",
)
HTTP_ADMISSION_LATENCY_EWMA = metrics.gauge(
    "http_admission_latency_ewma_seconds",
    "observed handler service-time EWMA feeding the latency-driven "
    "admission bounds, by class",
)
HTTP_ADMISSION_EFFECTIVE_DEADLINE = metrics.gauge(
    "http_admission_effective_deadline_seconds",
    "dequeue deadline currently in force (static, or latency-tracked in "
    "autotune live mode), by class",
)
HTTP_ADMISSION_EFFECTIVE_INFLIGHT = metrics.gauge(
    "http_admission_effective_max_inflight",
    "inflight bound currently in force (static, or latency-tracked in "
    "autotune live mode), by class",
)

#: Service-time EWMA smoothing (~20 samples to converge on a step).
EWMA_ALPHA = 0.2
#: The effective deadline targets this multiple of the observed service
#: time: an admitted request that already waited 4 service times is deep
#: into diminishing-value territory.
DEADLINE_LATENCY_FACTOR = 4.0
#: Band floors (the configured static value is the ceiling for both): the
#: controller may tighten a deadline to a quarter of its static value and
#: an inflight bound to an eighth — never below, so a latency spike can
#: only narrow service, not collapse it.
DEADLINE_FLOOR_FRACTION = 0.25
INFLIGHT_FLOOR_FRACTION = 0.125

#: Retry-After derivation: sliding completion window + sample floor.
DRAIN_WINDOW = 64
DRAIN_MIN_SAMPLES = 8
RETRY_AFTER_MAX_S = 30


class ShedError(Exception):
    """The request was shed; the server answers 503 with Retry-After."""

    def __init__(self, klass: str, reason: str, retry_after_s: int):
        super().__init__(
            f"overloaded: {klass} request shed ({reason}); "
            f"retry after {retry_after_s}s"
        )
        self.klass = klass
        self.reason = reason
        self.retry_after_s = retry_after_s


@dataclass(frozen=True)
class ClassPolicy:
    """Admission bounds for one work class.

    ``max_inflight`` caps admitted-but-unfinished requests (the cheap
    early shed); ``deadline_s`` bounds how stale an admitted request may
    be when a worker finally picks it up (the dequeue shed);
    ``retry_after_s`` is what a shed response tells the client when the
    drain rate is unobserved.  All three are the STATIC configuration —
    the latency-driven layer narrows inside them, never past them."""

    name: str
    max_inflight: int
    deadline_s: float
    retry_after_s: int


#: Defaults sized for the minimal-preset CI host; production deployments
#: scale ``max_inflight`` with worker count the way the reference scales
#: its queue lengths with the validator count.
DEFAULT_POLICIES = (
    ClassPolicy(CLASS_CRITICAL, max_inflight=512, deadline_s=8.0, retry_after_s=1),
    ClassPolicy(CLASS_DUTIES, max_inflight=256, deadline_s=4.0, retry_after_s=2),
    ClassPolicy(CLASS_BULK, max_inflight=128, deadline_s=2.0, retry_after_s=5),
)


class Ticket:
    """One admitted request: stamped at admission, released when finished
    (shed or served).  ``check_deadline`` is called by the worker just
    before running the handler — the dequeue-side shed."""

    __slots__ = ("controller", "policy", "admitted_pc", "started_pc", "shed")

    def __init__(self, controller: "AdmissionController", policy: ClassPolicy):
        self.controller = controller
        self.policy = policy
        self.admitted_pc = time.perf_counter()
        self.started_pc: Optional[float] = None
        self.shed = False

    def check_deadline(self) -> float:
        """Record the queue wait; raise :class:`ShedError` when this request
        waited past its class's EFFECTIVE deadline (static, or
        latency-tracked in live mode).  Returns the wait in seconds."""
        now = time.perf_counter()
        wait = now - self.admitted_pc
        HTTP_ADMISSION_WAIT_SECONDS.observe(wait, **{"class": self.policy.name})
        _, deadline_s = self.controller.effective_bounds(self.policy.name)
        if wait > deadline_s:
            self.shed = True
            HTTP_REQUESTS_SHED.inc(**{"class": self.policy.name,
                                      "reason": "deadline"})
            self.controller._count_shed()
            blackbox.emit("admission", "shed", klass=self.policy.name,
                          reason="deadline", wait_s=round(wait, 4))
            raise ShedError(self.policy.name, "deadline",
                            self.controller.retry_after(self.policy.name))
        self.started_pc = now
        return wait

    def release(self) -> None:
        # Only a request whose handler actually RAN (check_deadline set
        # started_pc) feeds the latency EWMA: a shed one never ran, and a
        # queue-full rejection released straight after try_admit would
        # record its ~microsecond enqueue failure as a 'service time' —
        # dragging the EWMA to zero exactly when the system is overloaded.
        duration: Optional[float] = None
        if not self.shed and self.started_pc is not None:
            duration = time.perf_counter() - self.started_pc
        self.controller._release(self.policy.name, duration)


class AdmissionController:
    """Bounded per-class admission in front of the processor.

    ``adaptive=None`` (production) follows the autotune mode — the bounds
    track latency only under ``LIGHTHOUSE_TPU_AUTOTUNE=live``; ``True`` /
    ``False`` pins the behavior (tests, the bench harness)."""

    def __init__(self, policies=DEFAULT_POLICIES,
                 adaptive: Optional[bool] = None):
        self._policies: Dict[str, ClassPolicy] = {p.name: p for p in policies}
        self._inflight: Dict[str, int] = {p.name: 0 for p in policies}
        self._lock = locksmith.lock("AdmissionController._lock")
        self._adaptive = adaptive
        self._ewma: Dict[str, float] = {}
        self._done: Dict[str, Deque[float]] = {
            p.name: deque(maxlen=DRAIN_WINDOW) for p in policies
        }
        self.shed = 0  # process-lifetime total, for snapshots/tests

    def policy(self, klass: str) -> ClassPolicy:
        return self._policies[klass]

    # ------------------------------------------------- latency-driven bounds

    def _adaptive_on(self) -> bool:
        if self._adaptive is not None:
            return self._adaptive
        from .. import autotune

        return autotune.live()

    def effective_bounds(self, klass: str) -> Tuple[int, float]:
        """(max_inflight, deadline_s) currently in force for ``klass``:
        the static policy values, or — adaptive mode with an observed
        EWMA — the latency-tracked values inside the static band.

        The deadline targets :data:`DEADLINE_LATENCY_FACTOR` × EWMA
        (floor ``static × DEADLINE_FLOOR_FRACTION``, ceiling static); the
        inflight bound is how many requests one worker clears inside that
        deadline, ``deadline / EWMA`` (floor ``static ×
        INFLIGHT_FLOOR_FRACTION``, ceiling static) — Little's law with the
        observed service rate.  Fast handlers pin both at the static
        ceiling's spirit: a tight deadline sheds stale work early while
        the large drain keeps the inflight bound at its ceiling."""
        policy = self._policies.get(klass)
        if policy is None:
            return (1 << 30, 60.0)
        with self._lock:
            ewma = self._ewma.get(klass)
        if ewma is None or ewma <= 0 or not self._adaptive_on():
            return (policy.max_inflight, policy.deadline_s)
        return self._bounds_from_ewma(policy, ewma)

    @staticmethod
    def _bounds_from_ewma(policy: ClassPolicy,
                          ewma: float) -> Tuple[int, float]:
        deadline = min(policy.deadline_s,
                       max(policy.deadline_s * DEADLINE_FLOOR_FRACTION,
                           DEADLINE_LATENCY_FACTOR * ewma))
        floor = max(1, int(policy.max_inflight * INFLIGHT_FLOOR_FRACTION))
        max_inflight = min(policy.max_inflight,
                           max(floor, int(deadline / ewma)))
        return (max_inflight, deadline)

    def retry_after(self, klass: str) -> int:
        """The Retry-After hint for a shed ``klass`` request: time for
        roughly half the inflight requests to drain at the observed
        completion rate, clamped to [1, :data:`RETRY_AFTER_MAX_S`].  Below
        :data:`DRAIN_MIN_SAMPLES` completions (cold start, idle class) the
        configured constant stands — a hint must never be derived from
        noise."""
        policy = self._policies.get(klass)
        fallback = policy.retry_after_s if policy is not None else 1
        with self._lock:
            done = self._done.get(klass)
            if done is None or len(done) < DRAIN_MIN_SAMPLES:
                return fallback
            span = done[-1] - done[0]
            if span <= 0:
                return fallback
            rate = (len(done) - 1) / span  # completions per second
            backlog = max(1, self._inflight.get(klass, 0))
        return max(1, min(RETRY_AFTER_MAX_S,
                          int(math.ceil((backlog / 2.0) / rate))))

    # ------------------------------------------------------------ admission

    def try_admit(self, klass: str) -> Ticket:
        """Admit or shed.  Unknown classes are admitted unbounded (a route
        added without a policy must not 503 by accident — it just isn't
        protected yet)."""
        policy = self._policies.get(klass)
        if policy is None:
            policy = ClassPolicy(klass, max_inflight=1 << 30,
                                 deadline_s=60.0, retry_after_s=1)
            with self._lock:
                self._policies.setdefault(klass, policy)
                self._inflight.setdefault(klass, 0)
                self._done.setdefault(klass, deque(maxlen=DRAIN_WINDOW))
        bound, _ = self.effective_bounds(policy.name)
        with self._lock:
            if self._inflight[policy.name] < bound:
                self._inflight[policy.name] += 1
                HTTP_ADMISSION_INFLIGHT.set(self._inflight[policy.name],
                                            **{"class": policy.name})
                return Ticket(self, policy)
            self.shed += 1
            HTTP_REQUESTS_SHED.inc(**{"class": policy.name,
                                      "reason": "admission_full"})
        # Retry-After derivation re-acquires the lock — raise outside it
        # (and the journal emit stays off the lock for the same reason).
        blackbox.emit("admission", "shed", klass=policy.name,
                      reason="admission_full", bound=bound)
        raise ShedError(policy.name, "admission_full",
                        self.retry_after(policy.name))

    def _count_shed(self) -> None:
        with self._lock:
            self.shed += 1

    def _release(self, klass: str, duration: Optional[float] = None) -> None:
        with self._lock:
            self._inflight[klass] = max(0, self._inflight[klass] - 1)
            HTTP_ADMISSION_INFLIGHT.set(self._inflight[klass],
                                        **{"class": klass})
            if duration is not None:
                prev = self._ewma.get(klass)
                ewma = duration if prev is None else (
                    EWMA_ALPHA * duration + (1.0 - EWMA_ALPHA) * prev)
                self._ewma[klass] = ewma
                done = self._done.setdefault(klass,
                                             deque(maxlen=DRAIN_WINDOW))
                done.append(time.perf_counter())
        if duration is not None:
            # bounds derived from the ewma just computed — no second trip
            # through the lock on the per-completion hot path
            HTTP_ADMISSION_LATENCY_EWMA.set(ewma, **{"class": klass})
            policy = self._policies.get(klass)
            if policy is not None and self._adaptive_on():
                bound, deadline = self._bounds_from_ewma(policy, ewma)
            elif policy is not None:
                bound, deadline = policy.max_inflight, policy.deadline_s
            else:
                return
            HTTP_ADMISSION_EFFECTIVE_INFLIGHT.set(bound, **{"class": klass})
            HTTP_ADMISSION_EFFECTIVE_DEADLINE.set(deadline,
                                                  **{"class": klass})

    def snapshot(self) -> dict:
        with self._lock:
            # copy under the lock: try_admit registers unknown classes into
            # _policies concurrently, and effective_bounds/retry_after each
            # re-acquire the lock themselves (so they run on the copy)
            policies = dict(self._policies)
        effective = {k: self.effective_bounds(k) for k in policies}
        retry = {k: self.retry_after(k) for k in policies}
        adaptive = self._adaptive_on()  # resolves autotune mode: outside the lock
        with self._lock:
            return {
                "inflight": dict(self._inflight),
                "bounds": {k: p.max_inflight for k, p in policies.items()},
                "deadlines_s": {k: p.deadline_s for k, p in policies.items()},
                # the RESOLVED state (ctor pin, else the live autotune
                # mode) — OBSERVABILITY.md's triage reads this to decide
                # whether tightened bounds can be autotune's doing
                "adaptive": adaptive,
                "latency_ewma_s": {k: round(v, 6)
                                   for k, v in self._ewma.items()},
                "effective": {
                    k: {"max_inflight": b, "deadline_s": round(d, 4)}
                    for k, (b, d) in effective.items()
                },
                "retry_after_s": retry,
                "shed_total": self.shed,
            }


# ------------------------------------------------------------ drop policy


class DropPolicy:
    """Decides whether an enqueued :class:`WorkEvent` should be discarded
    instead of queued.  Returns a drop *reason* (metric label) or ``None``
    to admit — the generalization of the processor's original hard-coded
    ``drop_during_sync and is_syncing()`` test."""

    def should_drop(self, event) -> Optional[str]:  # pragma: no cover
        return None


class SyncDropPolicy(DropPolicy):
    """The original policy: while ``is_syncing()`` holds, events flagged
    ``drop_during_sync`` are discarded (stale gossip is useless to a
    syncing chain and crowds out the sync work itself)."""

    def __init__(self, is_syncing: Optional[Callable[[], bool]]):
        self.is_syncing = is_syncing

    def should_drop(self, event) -> Optional[str]:
        if (
            event.drop_during_sync
            and self.is_syncing is not None
            and self.is_syncing()
        ):
            return "syncing"
        return None
