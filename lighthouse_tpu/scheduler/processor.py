"""The priority work scheduler.

Equivalent of the reference's ``BeaconProcessor``
(`beacon_node/beacon_processor/src/lib.rs:753` ``spawn_manager``): a manager
thread drains bounded per-class queues in strict priority order into a pool of
``<= max_workers`` worker threads, coalescing attestation-class work into
batches sized to the device program's bucket shapes.

Design notes vs the reference:
- The reference's workers are tokio blocking threads; here they are plain
  threads.  CPU-bound Python work holds the GIL, but the workloads this
  scheduler feeds — the batched JAX verification program, native SSZ/hash
  code, IO — all release it, which is exactly the deployment shape
  (host Python orchestrates, device/native code computes).
- Batch coalescing IS the TPU batch formation: one drained
  ``GossipAttestationBatch`` becomes one padded device invocation
  (``ops/verify.py`` buckets), so queue pressure directly widens device
  batches — the mechanism the reference uses to amortize multi-pairings
  (``attestation_verification/batch.rs``).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from .work import (
    BATCH_RULES,
    DEFAULT_QUEUE_LENGTH,
    DEFAULT_QUEUE_LENGTHS,
    DRAIN_ORDER,
    MAX_WORK_RETRIES,
    RequeueWork,
    W,
    WorkEvent,
)


from .. import locksmith
from .. import metrics as _gm
from .. import tracing
from ..logs import get_logger

log = get_logger("scheduler.processor")

# Per-work-class series on /metrics (reference: the beacon_processor's
# per-queue event counters, task_executor's per-task metrics).
WORK_EVENTS_RECEIVED = _gm.counter(
    "beacon_processor_work_events_received_total",
    "work events enqueued, by work class",
)
WORK_EVENTS_PROCESSED = _gm.counter(
    "beacon_processor_work_events_processed_total",
    "work events completed, by work class",
)
WORK_EVENTS_DROPPED = _gm.counter(
    "beacon_processor_work_events_dropped_total",
    "work events dropped (full queue or worker panic), by work class",
)
DROPPED_DURING_SYNC = _gm.counter(
    "beacon_processor_dropped_during_sync_total",
    "gossip work discarded because the node is syncing, by work class",
)
WORK_EVENTS_REQUEUED = _gm.counter(
    "beacon_processor_work_requeued_total",
    "work events re-enqueued after a RequeueWork (device dispatch "
    "deadline exceeded and retryable), by work class",
)
QUEUE_DEPTH = _gm.BEACON_PROCESSOR_QUEUE_DEPTH


@dataclass
class ProcessorMetrics:
    received: Dict[str, int] = field(default_factory=dict)
    processed: Dict[str, int] = field(default_factory=dict)
    dropped: Dict[str, int] = field(default_factory=dict)
    dropped_during_sync: Dict[str, int] = field(default_factory=dict)
    batches: Dict[str, int] = field(default_factory=dict)
    batch_items: Dict[str, int] = field(default_factory=dict)

    def bump(self, table: Dict[str, int], key: str, n: int = 1) -> None:
        table[key] = table.get(key, 0) + n
        # mirror the event tables onto the Prometheus registry
        if table is self.received:
            WORK_EVENTS_RECEIVED.inc(n, work=key)
        elif table is self.processed:
            WORK_EVENTS_PROCESSED.inc(n, work=key)
        elif table is self.dropped:
            WORK_EVENTS_DROPPED.inc(n, work=key)
        elif table is self.dropped_during_sync:
            DROPPED_DURING_SYNC.inc(n, work=key)


class BeaconProcessor:
    def __init__(
        self,
        max_workers: int = 4,
        queue_lengths: Optional[dict] = None,
        is_syncing: Optional[Callable[[], bool]] = None,
        drop_policy: Optional["DropPolicy"] = None,
    ):
        """``is_syncing``: zero-arg callable consulted on enqueue; while it
        returns True, events flagged ``drop_during_sync`` are discarded
        (reference ``beacon_processor`` drops stale gossip during sync
        instead of queueing work the chain can't use yet).

        ``drop_policy``: the generalized form (scheduler/admission.py
        :class:`DropPolicy`) — decides per-event whether to discard instead
        of queue.  When omitted, ``is_syncing`` is wrapped in the original
        :class:`SyncDropPolicy`; passing both composes (either may drop)."""
        from .admission import SyncDropPolicy

        self.max_workers = max(1, max_workers)
        self.is_syncing = is_syncing
        self._drop_policies = [SyncDropPolicy(is_syncing)]
        if drop_policy is not None:
            self._drop_policies.append(drop_policy)
        self._drain_set = frozenset(DRAIN_ORDER)
        self._queues: Dict[str, deque] = {}
        self._limits = dict(DEFAULT_QUEUE_LENGTHS)
        if queue_lengths:
            self._limits.update(queue_lengths)
        self._lock = locksmith.condition("BeaconProcessor._lock")
        self._active_workers = 0
        self._last_depth_sample = 0.0
        self._shutdown = False
        self._idle = threading.Event()
        self._idle.set()
        self.metrics = ProcessorMetrics()
        self._manager = threading.Thread(target=self._manage, name="beacon-processor", daemon=True)
        self._manager.start()

    # ------------------------------------------------------------ ingress

    def send(self, event: WorkEvent) -> bool:
        """Enqueue; returns False when the class queue is full and the event
        was dropped (reference: queue-full drop + metric)."""
        if event.work_type not in self._drain_set:
            raise ValueError(f"unknown work type {event.work_type!r} (not in DRAIN_ORDER)")
        # Policy-driven discard (scheduler/admission.py): stale-while-syncing
        # gossip is the canonical case — attestations and aggregates against
        # a head we don't have yet would only fail later and crowd out the
        # sync work itself.  Only the "syncing" reason counts on the
        # dropped-during-sync series; custom policies' drops land on the
        # generic dropped counter so the sync metric never lies.
        for policy in self._drop_policies:
            reason = policy.should_drop(event)
            if reason is not None:
                table = (self.metrics.dropped_during_sync
                         if reason == "syncing" else self.metrics.dropped)
                self.metrics.bump(table, event.work_type)
                return False
        # Carry the sender's trace context across the thread hop; stamp the
        # enqueue instant for the worker-side queue-wait span.
        if event.trace_parent is None:
            event.trace_parent = tracing.current_span()
        event.enqueued_at = time.perf_counter()
        with self._lock:
            if self._shutdown:
                return False
            q = self._queues.setdefault(event.work_type, deque())
            limit = self._limits.get(event.work_type, DEFAULT_QUEUE_LENGTH)
            self.metrics.bump(self.metrics.received, event.work_type)
            if len(q) >= limit:
                self.metrics.bump(self.metrics.dropped, event.work_type)
                return False
            q.append(event)
            self._idle.clear()
            self._lock.notify_all()
            return True

    # ------------------------------------------------------------ manager

    def _next_work(self) -> Optional[List[WorkEvent]]:
        """First non-empty queue in drain order; batchable classes coalesce
        up to their batch size (must hold the lock).

        A batchable class with exactly ONE queued event still takes the
        batch path: the batch handlers are the seam that feeds the async
        device pipeline (device_pipeline.py), and a single attestation must
        enter it like any other group — the old ``len(q) > 1`` guard routed
        lone events through the per-item handler, so they never coalesced
        with anything.  With the pipeline doing the real cross-work-type
        batching, the per-class caps here are throughput hints (how much one
        worker drains per wakeup), not the batch-formation mechanism."""
        for wt in DRAIN_ORDER:
            q = self._queues.get(wt)
            if not q:
                continue
            rule = BATCH_RULES.get(wt)
            if rule is not None:
                _, max_batch = rule
                batch = []
                while q and len(batch) < max_batch:
                    batch.append(q.popleft())
                return batch
            return [q.popleft()]
        return None

    def _manage(self) -> None:
        while True:
            with self._lock:
                while not self._shutdown and (
                    self._active_workers >= self.max_workers or self._next_ready() is None
                ):
                    if self._active_workers == 0 and self._all_empty():
                        self._idle.set()
                    self._sample_queue_depths()
                    self._lock.wait(timeout=0.05)
                if self._shutdown:
                    return
                self._sample_queue_depths()
                batch = self._next_work()
                if batch is None:
                    continue
                self._active_workers += 1
            threading.Thread(target=self._run_worker, args=(batch,), daemon=True).start()

    def _sample_queue_depths(self) -> None:
        """Mirror per-class queue lengths onto
        ``beacon_processor_queue_depth{work}`` (throttled; must hold the
        lock).  Read next to ``device_pipeline_pending_sets``: queue
        pressure here vs batch fill there attributes a small-batches
        regression in one scrape."""
        now = time.monotonic()
        if now - self._last_depth_sample < 0.25:
            return
        self._last_depth_sample = now
        for wt, q in self._queues.items():
            QUEUE_DEPTH.set(len(q), work=wt)

    def _next_ready(self) -> Optional[str]:
        for wt in DRAIN_ORDER:
            if self._queues.get(wt):
                return wt
        return None

    def _all_empty(self) -> bool:
        return all(not q for q in self._queues.values())

    def _requeue(self, events: List[WorkEvent], wt: str) -> None:
        """Deadline-exceeded (or otherwise retryable) work: re-enqueue each
        event once instead of dropping it — by the retry, the device has
        recovered or its breaker has opened and routed the work to the host
        backend (device_supervisor.DispatchTimeout subclasses RequeueWork
        exactly for this seam)."""
        for ev in events:
            if ev.retries < MAX_WORK_RETRIES:
                ev.retries += 1
                WORK_EVENTS_REQUEUED.inc(work=wt)
                # A failed send already accounts for its own drop
                # (queue-full / during-sync) — don't double-count here.
                self.send(ev)
            else:
                self.metrics.bump(self.metrics.dropped, wt)

    def _run_worker(self, batch: List[WorkEvent]) -> None:
        wt = batch[0].work_type
        token = tracing.attach(batch[0].trace_parent)
        try:
            with tracing.span(f"work:{wt}", n_items=len(batch)):
                # enqueue→drain wait, measured from the OLDEST event in the
                # drained batch (its wait bounds everyone else's).
                tracing.record_span(
                    "queue_wait",
                    start_pc=min(ev.enqueued_at for ev in batch),
                    hist=_gm.QUEUE_WAIT_SECONDS,
                    hist_labels={"work": wt},
                    work=wt,
                )
                # Batch handler whenever one exists — including a batch of
                # ONE (the handler is the device-pipeline seam; see
                # _next_work).  Events without a batch handler run per-item.
                # A drained batch may MIX shapes: the same queue holds fresh
                # gossip (process_batch + item) and re-queued events from
                # the reprocess queue; feeding a shapeless event's
                # item=None through the batch handler would throw and take
                # every sibling down with it — so the batch call covers
                # only the events that opted into it, the rest run
                # per-item.
                if wt in BATCH_RULES:
                    grouped = [ev for ev in batch
                               if ev.process_batch is not None]
                    loose = [ev for ev in batch if ev.process_batch is None]
                else:
                    grouped, loose = [], batch
                if grouped:
                    batch_wt = BATCH_RULES[wt][0]
                    self.metrics.bump(self.metrics.batches, batch_wt)
                    self.metrics.bump(self.metrics.batch_items, batch_wt,
                                      len(grouped))
                    try:
                        grouped[0].process_batch([ev.item for ev in grouped])
                    except RequeueWork:
                        self._requeue(grouped, wt)
                    else:
                        self.metrics.bump(self.metrics.processed, wt,
                                          len(grouped))
                if loose:
                    idx = 0
                    try:
                        for idx, ev in enumerate(loose):
                            ev.process(ev.item)
                            self.metrics.bump(self.metrics.processed, wt)
                    except RequeueWork:
                        # Only the raiser and the unprocessed tail retry;
                        # events before it already ran to completion.
                        self._requeue(loose[idx:], wt)
        except Exception:
            # A worker panic must not kill the node (reference logs + metric)
            # — but it must not vanish either: the batch it took down is
            # real work (the silent-drop variant of this cost a soak run
            # its attestations), so leave a trace for triage.
            log.warning("worker panic", work=wt, n_items=len(batch),
                        exc_info=True)
            self.metrics.bump(self.metrics.dropped, wt, len(batch))
        finally:
            tracing.detach(token)
            with self._lock:
                self._active_workers -= 1
                if self._active_workers == 0 and self._all_empty():
                    self._idle.set()
                self._lock.notify_all()

    # ------------------------------------------------------------ control

    def wait_idle(self, timeout: float = 10.0) -> bool:
        """Block until all queues are drained and workers are done."""
        return self._idle.wait(timeout)

    def shutdown(self) -> None:
        with self._lock:
            self._shutdown = True
            self._lock.notify_all()
        self._manager.join(timeout=2.0)


class ReprocessQueue:
    """Delay queue for work that can't run yet: early blocks (before their
    slot), attestations referencing unknown blocks, backfill batches
    (reference: ``work_reprocessing_queue.rs``, doc ``:1-12``)."""

    MAX_DELAYED = 16384
    #: how long work may await a block that never imports (a lookup that
    #: aborts — dead peer, depth cap, bad response — must not strand its
    #: parked attestations forever, or the cap above eventually disables
    #: parking for the rest of the process)
    AWAIT_TTL_S = 12.0

    def __init__(self, processor: BeaconProcessor):
        self.processor = processor
        self._lock = locksmith.condition("ReprocessQueue._lock")
        self._by_time: List = []  # heap of (due, seq, event)
        # root -> [(expires_at, event)]
        self._awaiting_root: Dict[bytes, List[tuple]] = {}
        self._seq = 0
        self._n_awaiting = 0
        self._shutdown = False
        self._thread = threading.Thread(target=self._run, name="reprocess-queue", daemon=True)
        self._thread.start()

    def schedule_at(self, due: float, event: WorkEvent) -> None:
        """Run ``event`` at ``time.monotonic()``-clock instant ``due``
        (early-block delay): ``schedule_at(time.monotonic() + d, ev)``."""
        import heapq

        with self._lock:
            self._seq += 1
            heapq.heappush(self._by_time, (due, self._seq, event))
            self._lock.notify_all()

    def await_block(self, block_root: bytes, event: WorkEvent) -> bool:
        """Queue ``event`` until ``block_imported(block_root)`` — or until
        ``AWAIT_TTL_S`` passes without it (then it is dropped)."""
        with self._lock:
            if self._n_awaiting >= self.MAX_DELAYED:
                return False
            self._awaiting_root.setdefault(block_root, []).append(
                (time.monotonic() + self.AWAIT_TTL_S, event))
            self._n_awaiting += 1
            return True

    def block_imported(self, block_root: bytes) -> int:
        """Release work waiting on a now-imported block; returns #released."""
        with self._lock:
            entries = self._awaiting_root.pop(block_root, [])
            self._n_awaiting -= len(entries)
        for _expires, ev in entries:
            self.processor.send(ev)
        return len(entries)

    def _expire_awaiting(self, now: float) -> None:
        """Drop parked work whose block never imported (caller holds the
        lock) — the sibling of the reference's queued-attestation expiry."""
        for root in list(self._awaiting_root):
            kept = [e for e in self._awaiting_root[root] if e[0] > now]
            dropped = len(self._awaiting_root[root]) - len(kept)
            if dropped:
                self._n_awaiting -= dropped
                if kept:
                    self._awaiting_root[root] = kept
                else:
                    del self._awaiting_root[root]

    def _run(self) -> None:
        import heapq

        while True:
            with self._lock:
                if self._shutdown:
                    return
                now = time.monotonic()
                self._expire_awaiting(now)
                due_events = []
                while self._by_time and self._by_time[0][0] <= now:
                    _, _, ev = heapq.heappop(self._by_time)
                    due_events.append(ev)
                timeout = (
                    max(0.0, self._by_time[0][0] - now) if self._by_time else 0.1
                )
            for ev in due_events:
                self.processor.send(ev)
            with self._lock:
                if not self._shutdown:
                    self._lock.wait(timeout=min(timeout, 0.1))

    def shutdown(self) -> None:
        with self._lock:
            self._shutdown = True
            self._lock.notify_all()
        self._thread.join(timeout=2.0)
