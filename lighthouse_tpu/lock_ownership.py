"""Lock-ownership registry: which lock guards which shared mutable state.

ROADMAP items 2 (multi-process mesh / decoupled device service) and 4
(virtual-time scenario engine) both multiply the number of thread roots
touching the runtime's shared state.  Today the mapping from "this lock"
to "these attributes" is folklore living in docstrings — the exact failure
mode the ``ops/batch_axes.py`` registry was built to kill for sharding.
This registry is the concurrency counterpart: one entry per lock in the
concurrent subsystems, naming the attributes (instance attributes for
class locks, module globals for module-level locks) that must only be
written while that lock is held.

Consumed two ways:

- the **race static pass** (``scripts/analysis/race_pass.py``) reads this
  file via ``ast.literal_eval`` (check_static stays import-free of
  ``lighthouse_tpu``) and flags (a) writes to a registered attribute
  reachable from two or more thread roots without the owning lock held,
  and (b) registry rot — a lock in a scanned module missing here, or an
  entry naming a lock/attribute that no longer exists;
- the **runtime lock sanitizer** (``lighthouse_tpu/locksmith.py``)
  imports it when ``LIGHTHOUSE_TPU_LOCK_SANITIZE=1`` to install write
  guards: a write to a registered attribute while the owning sanitized
  lock is NOT held by the writing thread becomes a test failure.

Keys are repo-relative paths; per file, ``classes`` maps
``ClassName -> {lock_attr: [guarded instance attrs]}`` and ``module``
maps ``LOCK_GLOBAL -> [guarded module globals]``.  This module must stay
a plain dict literal with no imports: the static pass parses it, never
imports it.

Registration discipline: register the attributes a lock's docstring/
comments claim it guards AND that every write site actually honors.
Attributes that are deliberately written lock-free (benign races,
single-writer fast-path flags like ``fault_injection.ACTIVE``) stay out
of the registry — the race pass's job is enforcing the contract, not
inventing one.
"""

#: lock -> guarded-state contract per concurrent module (see module
#: docstring; race_pass.py enforces completeness of this mapping).
LOCK_OWNERSHIP = {
    "lighthouse_tpu/device_supervisor.py": {
        "classes": {
            "CircuitBreaker": {
                "_lock": [
                    "_state",
                    "_consecutive_failures",
                    "_opened_at",
                    "_probe_successes",
                    "trips_total",
                    "probes_total",
                    "last_failure",
                ],
            },
            "DeviceSupervisor": {
                "_lock": ["_breakers", "_workers", "_deadlines", "_config"],
            },
        },
        "module": {},
    },
    "lighthouse_tpu/device_pipeline.py": {
        "classes": {
            "DeviceArbiter": {
                # _lock is the dispatch slot itself (a gate, not a guard):
                # registered with no guarded attributes so the race pass
                # knows it is accounted for, not forgotten.
                "_lock": [],
                "_stats": ["_grants", "_wait_s", "_holder"],
            },
            # batches_total is NOT registered: it is single-writer state,
            # incremented only by the one exec/worker thread and read
            # lock-free by summary() (benign monitoring read) — the
            # runtime sanitizer proved the over-claim when it was listed.
            "DevicePipeline": {
                "_cond": [
                    "_pending",
                    "_pending_sets",
                    "_in_flight_groups",
                    "_shutdown",
                    "groups_total",
                    "sets_total",
                ],
            },
            "HashPipeline": {
                "_cond": [
                    "_pending",
                    "_pending_blocks",
                    "_in_flight_groups",
                    "_shutdown",
                    "groups_total",
                    "blocks_total",
                ],
            },
            "JobPipeline": {
                "_lock": ["_pending", "_shutdown", "jobs_total"],
            },
        },
        "module": {
            "_LOCK": ["_PIPELINE", "_HASH_PIPELINE", "_JOB_PIPELINES"],
        },
    },
    "lighthouse_tpu/device_mesh.py": {
        "classes": {
            "MeshState": {
                "_lock": [
                    "_configured",
                    "_devices",
                    "_mesh",
                    "_full_size",
                    "_generation",
                    "_reshards_total",
                    "_breakers",
                    "_threshold",
                ],
            },
            "ShardedEntry": {
                "_cache_lock": ["_jitted"],
            },
        },
        "module": {},
    },
    "lighthouse_tpu/blackbox.py": {
        "classes": {
            "Journal": {
                "_lock": ["_buf", "_seq"],
            },
        },
        "module": {
            "_SNAPSHOTTERS_LOCK": ["_SNAPSHOTTERS"],
            "_CAPTURE_LOCK": ["_CAPTURE_SEQ", "_INDEX"],
        },
    },
    # Node-scoped telemetry (ISSUE 19): the scope's Lamport clock and
    # worker-deferred event buffer are written from processor worker
    # threads and drained on the runner; the registry lock guards the
    # node-id -> scope map.  The flight/log tail deques are deliberately
    # unregistered: single-writer monitoring mirrors, atomic appends.
    "lighthouse_tpu/telemetry_scope.py": {
        "classes": {
            "TelemetryScope": {
                "_lock": ["_lamport", "_pending"],
            },
        },
        "module": {
            "_SCOPES_LOCK": ["_SCOPES"],
        },
    },
    "lighthouse_tpu/autotune.py": {
        "classes": {
            "Controller": {
                "_lock": [
                    "evaluations",
                    "_decisions",
                    "_decision_seq",
                    "_pin",
                    "_pin_applied",
                    "_pin_loaded_env",
                    "_warmups",
                ],
            },
        },
        "module": {
            "_MODE_LOCK": ["_MODE"],
            "_OVERLAY_LOCK": ["_OVERLAY", "_MERGED"],
            "_BUDGET_LOCK": ["_BUDGET_CACHE"],
            "_THREAD_LOCK": ["_THREAD", "_THREAD_STOP"],
        },
    },
    "lighthouse_tpu/fault_injection.py": {
        "classes": {
            "FaultRegistry": {
                "_lock": ["_plans", "_next_id"],
            },
        },
        "module": {},
    },
    "lighthouse_tpu/scheduler/processor.py": {
        "classes": {
            "BeaconProcessor": {
                "_lock": ["_queues", "_active_workers", "_shutdown"],
            },
            "ReprocessQueue": {
                "_lock": [
                    "_by_time",
                    "_awaiting_root",
                    "_seq",
                    "_n_awaiting",
                    "_shutdown",
                ],
            },
        },
        "module": {},
    },
    "lighthouse_tpu/scheduler/admission.py": {
        "classes": {
            "AdmissionController": {
                "_lock": ["_inflight", "_ewma", "_done", "shed"],
            },
        },
        "module": {},
    },
    "lighthouse_tpu/http_api/response_cache.py": {
        "classes": {
            "ResponseCache": {
                "_lock": [
                    "_entries",
                    "hits",
                    "misses",
                    "invalidated",
                    "generation",
                ],
            },
        },
        "module": {},
    },
    # Scenario soak: the runner itself owns no locks (it drives the Hub's
    # fabric and the nodes' own locked subsystems) — an empty entry keeps
    # the file under registry-rot audit so a lock added here later must be
    # registered.
    "lighthouse_tpu/scenarios.py": {
        "classes": {},
        "module": {},
    },
    "lighthouse_tpu/network/transport.py": {
        "classes": {
            "Hub": {
                "_lock": [
                    "_endpoints",
                    "_links",
                    "_partitions",
                    "_link_plans",
                    "_default_plan",
                    "_link_seq",
                    "_delayed",
                    "_delayed_seq",
                    "_tick",
                    "_counters",
                    "_schedule",
                ],
            },
        },
        "module": {},
    },
}

#: Lock-order edges the runtime sanitizer accepts even though the static
#: graph does not contain them, as ``(first_acquired, then_acquired)``
#: label pairs with a reason.  Cross-object edges are outside the static
#: pass's per-class scope (ANALYSIS.md); list here ONLY pairs that are
#: provably acyclic in the wider graph.
SANCTIONED_ORDER_PAIRS = {
    # The arbiter's stats lock nests strictly inside the slot lock and is
    # never held across any other acquisition.
    ("DeviceArbiter._lock", "DeviceArbiter._stats"):
        "leaf stats lock, nests one way inside the slot",
}
