"""Node-scoped telemetry: per-node views of the observability plane.

Every telemetry surface in this tree — the metrics registry, the flight
recorder, the log ring, the blackbox journal, the trace store — began as a
process-global singleton.  That is the right zero-cost default for a
single-node run, but a multi-node scenario fleet (and, next, the
multi-process device service of ROADMAP item 2) smears N nodes into one
timeline where node A's breaker trip and node B's reorg are
indistinguishable.  ``process_boundary_pass`` baselined those singletons
as "the split's work map"; this module is the seam that burns the
telemetry-owned subset down.

A :class:`TelemetryScope` is one node's (or, later, one process's) view of
the plane:

- its own :class:`blackbox.Journal` ring (records mirrored from the
  process-global journal, stamped with ``node`` + Lamport ``lamport``);
- its own flight-recorder tail and log tail (copies of the entries the
  global rings saw while the scope was active);
- a :class:`metrics.LocalTally` — a per-scope metrics view next to the
  process-global registry;
- a per-node **Lamport clock**: ``tick()`` on every scoped journal append,
  ``tick(at_least=remote)`` when a record is causally linked to another
  node's event (a gossip import resuming a remote trace), ``clock()`` for
  a read-only stamp on outbound envelopes.  ``blackbox.merge_journals``
  orders the fleet timeline on (virtual slot, lamport, node, seq), so the
  clock is what makes cross-node causality hold in the merge.

Propagation follows ``tracing``'s model: a contextvar carries the active
scope on the thread that entered it (``activate()``), and long-lived
subsystems that outlive a context — a node's transport endpoint, its
gossip router — hold a direct scope reference instead (contextvars do not
reach into already-running threads).  When no scope is active every
telemetry call degrades to exactly the old process-global behavior:
single-node runs pay nothing.

Worker-thread events (a gossip block import on a processor worker) must
NOT append into the scoped journal directly — thread interleaving would
make per-node ``seq`` assignment racy across runs.  They go through
``defer()`` into a pending buffer and are drained on the runner thread at
settle boundaries (``Simulator.drain_fleet_events``), sorted on stable
keys, so two runs at one seed produce byte-identical merged timelines.

Import discipline: host-side plumbing only (no jax), like ``blackbox.py``
— which imports this module at its top, so the reverse edge here is lazy.
"""

from __future__ import annotations

import contextlib
from collections import deque
from contextvars import ContextVar
from typing import Dict, Iterator, List, Optional, Tuple

from . import locksmith, metrics

#: Per-scope flight/log tail lengths — mirrors, not the system of record
#: (the global rings keep their own capacity).
FLIGHT_TAIL = 256
LOG_TAIL = 200

FLEET_JOURNAL_EVENTS = metrics.counter(
    "fleet_journal_events_total",
    "journal records routed into a node scope, by node",
)
FLEET_TRACE_LINKS = metrics.counter(
    "fleet_trace_links_total",
    "cross-node causal links recorded (envelope trace resumes, journal "
    "links), by kind",
)


class TelemetryScope:
    """One node's view of the telemetry plane (see module docstring)."""

    def __init__(self, node_id: str):
        from . import blackbox  # lazy: blackbox imports this module at top

        self.node_id = str(node_id)
        self.journal = blackbox.Journal()
        #: per-scope mirrors; deque appends are atomic, single-purpose
        #: monitoring tails — deliberately not lock-guarded state.
        self.flight: deque = deque(maxlen=FLIGHT_TAIL)
        self.log_tail: deque = deque(maxlen=LOG_TAIL)
        self.tally = metrics.LocalTally()
        self._lock = locksmith.lock("TelemetryScope._lock")
        self._lamport = 0
        self._pending: List[dict] = []

    # ------------------------------------------------------- lamport clock

    def tick(self, at_least: int = 0) -> int:
        """Advance the Lamport clock past ``at_least`` and return it."""
        with self._lock:
            self._lamport = max(self._lamport, int(at_least)) + 1
            return self._lamport

    def clock(self) -> int:
        """Read the clock WITHOUT ticking — outbound envelope stamps read
        the proposer's current value so the receiver's ``tick(at_least=)``
        orders the import strictly after the proposal."""
        with self._lock:
            return self._lamport

    # ---------------------------------------------------- deferred events

    def defer(self, source: str, event: str, fields: dict,
              link: Optional[Tuple[str, int]] = None) -> None:
        """Queue a journal event from a worker thread for a deterministic
        runner-thread drain (see module docstring)."""
        item = {"source": source, "event": event, "fields": dict(fields)}
        if link is not None:
            item["link"] = (str(link[0]), int(link[1]))
        with self._lock:
            self._pending.append(item)

    def drain_pending(self) -> List[dict]:
        """Pop all deferred events, sorted on stable fields (slot, then
        root/event) so arrival interleaving cannot reorder them."""
        with self._lock:
            pending, self._pending = self._pending, []
        pending.sort(key=lambda it: (
            it["fields"].get("slot", -1),
            str(it["fields"].get("root", "")),
            it["event"],
            str(it.get("link", "")),
        ))
        return pending

    # ------------------------------------------------------------ mirrors

    def note_flight(self, entry: dict) -> None:
        self.flight.append(dict(entry))

    def note_log(self, entry: dict) -> None:
        self.log_tail.append(dict(entry))

    # ----------------------------------------------------------- snapshot

    def snapshot(self) -> dict:
        return {
            "node": self.node_id,
            "lamport": self.clock(),
            "journal_len": len(self.journal),
            "flight_tail": len(self.flight),
            "log_tail": len(self.log_tail),
            "tally": self.tally.snapshot(),
        }


def envelope_trace_ctx(scope: Optional["TelemetryScope"]) -> Optional[dict]:
    """The trace context an outbound envelope carries: active trace id (if
    any), origin node, and a read-only Lamport stamp.  Excluded from
    ``Hub.record_schedule``'s determinism digest by construction — the hub
    logs only link names and delivery decisions."""
    if scope is None:
        return None
    from . import tracing  # lazy: keep this module import-light

    sp = tracing.current_span()
    return {
        "trace_id": sp.trace.trace_id if sp is not None else None,
        "node": scope.node_id,
        "lamport": scope.clock(),
    }


# ----------------------------------------------------------- scope registry

_SCOPES_LOCK = locksmith.lock("telemetry_scope._SCOPES_LOCK")
_SCOPES: Dict[str, TelemetryScope] = {}

#: The active scope on this thread/context (None = process-global plane).
_current: ContextVar[Optional[TelemetryScope]] = ContextVar(
    "telemetry_scope", default=None)


def register(scope: TelemetryScope) -> TelemetryScope:
    with _SCOPES_LOCK:
        _SCOPES[scope.node_id] = scope
    return scope


def unregister(node_id: str) -> None:
    with _SCOPES_LOCK:
        _SCOPES.pop(str(node_id), None)


def get(node_id: str) -> Optional[TelemetryScope]:
    with _SCOPES_LOCK:
        return _SCOPES.get(str(node_id))


def all_scopes() -> List[TelemetryScope]:
    """Registered scopes in stable (node id) order."""
    with _SCOPES_LOCK:
        scopes = list(_SCOPES.values())
    return sorted(scopes, key=lambda s: s.node_id)


def current() -> Optional[TelemetryScope]:
    return _current.get()


@contextlib.contextmanager
def activate(scope: Optional[TelemetryScope]) -> Iterator[None]:
    """Make ``scope`` the active telemetry scope for this context."""
    token = _current.set(scope)
    try:
        yield
    finally:
        _current.reset(token)


def reset_for_tests() -> None:
    with _SCOPES_LOCK:
        _SCOPES.clear()
